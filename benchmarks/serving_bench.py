"""Serving benchmark: static batching vs continuous (slot-based) batching on a
mixed-length synthetic workload.

Workload: `--requests` prompts with uniform lengths in [--prompt-min,
--prompt-max], budgets in [--max-new-min, --max-new-max], Poisson arrivals
(exponential inter-arrival, mean --mean-interarrival seconds). Both paths serve
the SAME workload greedily on the same model and are timed against a virtual
clock that advances by measured compute, so arrival gating is identical and
deterministic modulo host timing noise.

  - **static**: requests are batched `num_slots` at a time in arrival order
    (left-padded to the batch's prompt bucket) through the fused `Generator`
    loop; a batch runs to its LONGEST budget before the next one starts — the
    convoy effect this PR removes.
  - **continuous**: the same requests stream through `serving.ContinuousBatcher`
    (insert-into-free-slot + chunked decode), late arrivals joining mid-flight.

Emits exactly ONE JSON line on stdout (the bench-driver contract): headline is
continuous-batching tokens/sec, with static/continuous tokens/sec, TTFT p50/p99,
and total decode-loop iterations for both paths in `extra`.

CPU smoke sizes by default off-accelerator; `python bench.py --mode serving`
routes here.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np


def log(msg):
    print(f"[serving-bench] {msg}", file=sys.stderr, flush=True)


def _reattempt_tunnel_probe() -> bool:
    """Re-attempt the memoized TPU tunnel probe (bench.py's preflight memo
    protocol, same as train_bench): a fresh memo answers instantly, an expired
    one triggers ONE short probe whose verdict is memoized for the next
    caller. Returns True when an accelerator backend is reachable; the verdict
    is recorded in the bench JSON so an artifact states which backend class
    actually produced its numbers."""
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return False  # explicitly pinned; nothing to probe
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        import bench
    except ImportError:
        return False
    memo = bench._read_tunnel_state()
    ttl = bench._env_int("BENCH_TUNNEL_MEMO_TTL", bench.TUNNEL_MEMO_TTL_S)
    age = None if memo is None else time.time() - float(memo.get("checked_at", 0) or 0)
    if memo is not None and age is not None and 0 <= age < ttl:
        alive = bool(memo.get("alive"))
        log(f"tunnel memo: {'alive' if alive else 'dead'} ({age:.0f}s old, "
            f"source={memo.get('source', '?')})")
        return alive
    timeout = bench._env_int("BENCH_PREFLIGHT_TIMEOUT", 60)
    alive = bench._backend_preflight(timeout)
    bench._write_tunnel_state(alive, source="serving-bench")
    log(f"tunnel probe: {'alive' if alive else 'dead'} (memoized)")
    return alive


def build_workload(args, vocab_size, rng):
    prompts = [
        rng.integers(1, vocab_size, (int(rng.integers(args.prompt_min, args.prompt_max + 1)),)).astype(np.int32)
        for _ in range(args.requests)
    ]
    budgets = [int(rng.integers(args.max_new_min, args.max_new_max + 1)) for _ in range(args.requests)]
    arrivals = np.cumsum(rng.exponential(args.mean_interarrival, size=args.requests))
    return prompts, budgets, arrivals


def run_static(gen, prompts, budgets, arrivals, num_slots, max_length):
    """Arrival-order batches of `num_slots` through the fused Generator; returns
    (tokens_per_sec, ttfts, decode_iterations, makespan). `gen` is reused across
    warmup and timed passes so the timed pass runs warm executables."""
    import jax.numpy as jnp

    from accelerate_tpu.generation import GenerationConfig, _bucket_for

    clock = 0.0
    ttfts, decode_iterations = [], 0
    n = len(prompts)
    for start in range(0, n, num_slots):
        idx = list(range(start, min(start + num_slots, n)))
        batch_prompts = [prompts[i] for i in idx]
        batch_new = max(budgets[i] for i in idx)
        width = min(_bucket_for(max(p.size for p in batch_prompts)), max_length - batch_new)
        ids = np.zeros((len(idx), width), np.int32)
        mask = np.zeros((len(idx), width), np.int32)
        for r, p in enumerate(batch_prompts):
            ids[r, width - p.size:] = p  # LEFT padding (the Generator convention)
            mask[r, width - p.size:] = 1
        ids, mask = jnp.asarray(ids), jnp.asarray(mask)
        # the whole batch must have arrived before its prefill can start
        clock = max(clock, float(arrivals[idx[-1]]))
        # TTFT component: a 1-token run isolates prefill+first-token latency
        # (measured outside the clock; the real serving time is the full run)
        t0 = time.perf_counter()
        np.asarray(gen(ids, GenerationConfig(max_new_tokens=1), attention_mask=mask))
        t_first = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(gen(ids, GenerationConfig(max_new_tokens=batch_new), attention_mask=mask))
        t_full = time.perf_counter() - t0
        for i in idx:
            ttfts.append(clock - float(arrivals[i]) + t_first)
        clock += t_full
        # greedy, no EOS: the fused while_loop runs exactly (batch_new - 1)
        # body iterations (the first token comes from prefill)
        decode_iterations += batch_new - 1
    useful = sum(budgets)
    makespan = clock - float(arrivals[0])
    return useful / max(makespan, 1e-9), ttfts, decode_iterations, makespan


def run_continuous(engine, prompts, budgets, arrivals, collect_tokens=None):
    """The same workload through the slot engine; arrival-gated submission on
    the virtual clock. Returns (tokens_per_sec, ttfts, decode_iterations,
    makespan). Finished requests are `release()`d at the end, so the engine is
    reusable across warmup and timed passes with the same request ids.
    `collect_tokens` (a dict) captures each request's generated tokens before
    release — the quant A/B compares token streams across engines with it."""
    from accelerate_tpu.serving import Request

    clock = 0.0
    n = len(prompts)
    submitted = 0
    first_seen = {}
    base_steps = engine.stats["decode_steps"]
    while submitted < n or engine.pending:
        while submitted < n and float(arrivals[submitted]) <= clock:
            engine.submit(Request(submitted, prompts[submitted], max_new_tokens=budgets[submitted]))
            submitted += 1
        if not engine.pending:
            clock = float(arrivals[submitted])  # idle until the next arrival
            continue
        t0 = time.perf_counter()
        events = engine.step()
        clock += time.perf_counter() - t0
        for rid, _toks in events:
            first_seen.setdefault(rid, clock)
    ttfts = [first_seen[i] - float(arrivals[i]) for i in range(n)]
    useful = sum(budgets)
    makespan = clock - float(arrivals[0])
    for i in range(n):
        if collect_tokens is not None:
            collect_tokens[i] = [int(t) for t in engine.results[i].tokens]
        engine.release(i)
    return (
        useful / max(makespan, 1e-9),
        ttfts,
        engine.stats["decode_steps"] - base_steps,
        makespan,
    )


def pct(values, q):
    return float(np.percentile(np.asarray(values), q))


def run_router_workload(model, args, cfg, max_length, rng, tracer=None):
    """The replicated-fleet A/B (`--replicas N`): the mixed workload served
    through a `router.Router` over N engines — once clean (baseline), once
    with replica 0 killed mid-traffic (the chaos-kill shape, through the
    router's ops seam so the engine's warm executables are reused on rejoin).
    Reports throughput for both passes, the dip during the degraded window,
    and the measured recovery time (kill -> replica live again), under the
    same hard 0-recompile / 0-host-transfer gate as the single-engine passes
    (one process-wide TraceGuard: zero total means zero per engine)."""
    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.router import Router
    from accelerate_tpu.serving import Request

    prompts, budgets, arrivals = build_workload(args, cfg.vocab_size, rng)
    router = Router(
        model, replicas=args.replicas, num_slots=args.num_slots,
        max_length=max_length, chunk_size=args.chunk_size,
        max_queue=args.requests + 16, default_deadline_s=600.0,
        paged=not args.no_paged, page_size=args.page_size, tracer=tracer,
        rejoin_cooldown_s=0.2, probation_steps=1, stall_degrade_s=None,
        attention_impl=args.attention_impl,
        weight_dtype=args.weight_dtype, kv_cache_dtype=args.kv_cache_dtype,
    )

    def run_traffic(kill_fraction=None):
        """Arrival-gated traffic on the virtual clock. With `kill_fraction`,
        replica 0 is failed once that fraction of requests has finished;
        returns per-pass measurements including the kill/recovery marks."""
        clock = 0.0
        n = len(prompts)
        submitted = 0
        first_seen = {}
        token_marks = []  # (virtual clock, tokens streamed in this event)
        killed = False
        kill_clock = recover_clock = None
        kill_wall = recover_wall = None
        while submitted < n or router.pending or (killed and recover_wall is None):
            while submitted < n and float(arrivals[submitted]) <= clock:
                router.submit(Request(submitted, prompts[submitted],
                                      max_new_tokens=budgets[submitted]))
                submitted += 1
            if not router.pending and submitted < n:
                clock = float(arrivals[submitted])
                continue
            t0 = time.perf_counter()
            events = router.step()
            clock += time.perf_counter() - t0
            for rid, toks in events:
                first_seen.setdefault(rid, clock)
                token_marks.append((clock, len(toks)))
            if kill_fraction is not None and not killed and submitted == n:
                finished = sum(router.results[i].finished for i in range(n))
                if finished >= n * kill_fraction:
                    killed = True
                    kill_clock, kill_wall = clock, time.perf_counter()
                    log(f"kill A/B: failing replica 0 after {finished}/{n} requests")
                    router.fail_replica(0, reason="bench kill A/B", dead=False)
            if killed and recover_wall is None and router.replica_states[0] == "live":
                recover_clock, recover_wall = clock, time.perf_counter()
            if killed and recover_wall is None and not router.pending:
                time.sleep(0.02)  # idle: let the rejoin cooldown elapse
        delivered = sum(len(router.results[i].tokens) for i in range(n))
        reasons = {}
        for i in range(n):
            reason = router.results[i].finish_reason
            reasons[reason] = reasons.get(reason, 0) + 1
        ttfts = [first_seen.get(i, clock) - float(arrivals[i]) for i in range(n)]
        makespan = clock - float(arrivals[0])
        out = {
            "tokens_per_sec": round(delivered / max(makespan, 1e-9), 2),
            "tokens_delivered": delivered,
            "ttft_p50_ms": round(pct(ttfts, 50) * 1000, 2),
            "ttft_p99_ms": round(pct(ttfts, 99) * 1000, 2),
            "makespan_s": round(makespan, 3),
            "finish_reasons": reasons,
        }
        if killed:
            out["recovery_s"] = (
                round(recover_wall - kill_wall, 3) if recover_wall is not None else None
            )
            if recover_clock is not None and recover_clock > kill_clock:
                window = [t for t in token_marks if kill_clock <= t[0] <= recover_clock]
                out["degraded_window_tokens_per_sec"] = round(
                    sum(c for _, c in window) / (recover_clock - kill_clock), 2
                )
        for i in range(n):
            router.release(i)
        return out

    log(f"router workload ({args.replicas} replicas): warmup...")
    warmed = router.warm_inserts()
    log(f"router insert buckets warmed: {sorted(set(sum(warmed.values(), [])))}")
    run_traffic()
    run_traffic()
    guard = TraceGuard(
        transfer_guard="disallow", on_violation="record", name="serving-bench-router"
    )
    with guard:
        baseline = run_traffic()
        killed = run_traffic(kill_fraction=1 / 3)
    if guard.total_recompiles or guard.host_transfers:
        log(f"TRACE-GUARD VIOLATIONS in router workload: {guard.report().summary()}")
    # The fleet pin: routing, retry, soft-kill recovery and rejoin must all
    # reuse the warm per-engine executables — 0 recompiles, 0 host transfers
    # across every engine (a process-wide zero is a per-engine zero).
    assert guard.total_recompiles == 0 and guard.host_transfers == 0, (
        "router workload regressed the 0-recompile / 0-host-transfer discipline: "
        f"{guard.report().summary()}"
    )
    stats = router.stats
    result = {
        "replicas": args.replicas,
        "baseline": baseline,
        "kill_ab": killed,
        "throughput_dip_ratio": round(
            killed["tokens_per_sec"] / max(baseline["tokens_per_sec"], 1e-9), 3
        ),
        "recovery_s": killed.get("recovery_s"),
        "retries": stats["retries"],
        "ejected": stats["ejected"],
        "replica_states": stats["replica_states"],
        "recompiles": guard.total_recompiles,
        "host_transfers": guard.host_transfers,
    }
    router.close()
    return result


def run_spec_workload(model, args, cfg, max_length, rng, tracer=None):
    """The speculative A/B: a repetition-heavy workload (each prompt tiles a
    short motif — prompt-lookup's natural habitat, and greedy decode of small
    models collapses into loops anyway) served through two otherwise-identical
    engines, speculation OFF vs ON. The ON pass runs under an armed TraceGuard
    with the same hard 0-recompile / 0-host-transfer gate as the main timed
    passes, and reports accepted_tokens_per_step measured over the TIMED pass
    only — the speedup is a number in the artifact, not a claim."""
    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.serving import ContinuousBatcher

    def motif_prompt():
        motif = rng.integers(1, cfg.vocab_size, (6,)).astype(np.int32)
        length = int(rng.integers(args.prompt_min, max(args.prompt_min + 1, args.prompt_max // 2)))
        return np.tile(motif, -(-length // motif.size))[:length].astype(np.int32)

    prompts = [motif_prompt() for _ in range(args.requests)]
    # Decode-heavy on purpose: full budgets give greedy decode time to settle
    # into its loops, which is where prompt-lookup acceptance compounds.
    budgets = [args.max_new_max for _ in range(args.requests)]
    arrivals = np.cumsum(rng.exponential(args.mean_interarrival, size=args.requests))

    result = {"draft_tokens": args.draft_tokens, "draft_ngram": args.draft_ngram}
    for label, spec_on in (("plain", False), ("speculative", True)):
        engine = ContinuousBatcher(
            model, num_slots=args.num_slots, max_length=max_length,
            chunk_size=args.chunk_size, paged=not args.no_paged,
            page_size=args.page_size, tracer=tracer, speculative=spec_on,
            draft_tokens=args.draft_tokens, draft_ngram=args.draft_ngram,
            max_queue=args.requests,
        )
        log(f"speculative workload ({label}): warmup...")
        # The closed bucket ladder, then twice through the real traffic (pass 1
        # registers prefixes, pass 2 runs the prefix-hit path) like the prefix
        # workload.
        engine.warm_inserts()
        run_continuous(engine, prompts, budgets, arrivals)
        run_continuous(engine, prompts, budgets, arrivals)
        registry = engine.metrics
        steps0 = registry.value("serving_spec_verify_steps_total") or 0
        accepted0 = registry.value("serving_spec_accepted_draft_tokens_total") or 0
        guard = TraceGuard(
            transfer_guard="disallow", on_violation="record",
            name=f"serving-bench-spec-{label}",
        )
        engine.trace_guard = guard
        with guard:
            tps, ttfts, iters, span = run_continuous(engine, prompts, budgets, arrivals)
        if guard.total_recompiles or guard.host_transfers:
            log(f"TRACE-GUARD VIOLATIONS in speculative workload ({label}): {guard.report().summary()}")
        # The speculation-overhead pin: the draft/verify chunk must hold the
        # same steady-state discipline as the plain one.
        assert guard.total_recompiles == 0 and guard.host_transfers == 0, (
            f"speculative workload ({label}) regressed the 0-recompile / "
            f"0-host-transfer discipline: {guard.report().summary()}"
        )
        block = {
            "tokens_per_sec": round(tps, 2),
            "ttft_p50_ms": round(pct(ttfts, 50) * 1000, 2),
            "ttft_p99_ms": round(pct(ttfts, 99) * 1000, 2),
            "makespan_s": round(span, 3),
            "decode_iterations": iters,
            "recompiles": guard.total_recompiles,
            "host_transfers": guard.host_transfers,
        }
        if spec_on:
            steps = (registry.value("serving_spec_verify_steps_total") or 0) - steps0
            accepted = (registry.value("serving_spec_accepted_draft_tokens_total") or 0) - accepted0
            block["verify_steps"] = int(steps)
            block["accepted_draft_tokens"] = int(accepted)
            block["accepted_tokens_per_step"] = (
                round((steps + accepted) / steps, 4) if steps else None
            )
            block["cumulative"] = engine.stats["speculative"]
        result[label] = block
    spec, plain = result["speculative"], result["plain"]
    result["accepted_tokens_per_step"] = spec["accepted_tokens_per_step"]
    result["decode_iterations_ratio_plain_over_spec"] = round(
        plain["decode_iterations"] / max(spec["decode_iterations"], 1), 3
    )
    return result


def estimate_decode_hbm_bytes(
    num_slots, pages_per_slot, page_size, model_cfg, pool_dtype_bytes,
    compute_dtype_bytes=None,
):
    """Estimated HBM bytes the attention CACHE READ moves per decode step,
    derived from pool geometry (worst case: every slot's full page window)
    and PER-PASS dtypes — `pool_dtype_bytes` from the live engine's pool
    leaves (`engine.kv_pool_itemsize`), never the params dtype, and
    `compute_dtype_bytes` for the buffers XLA materializes in the compute
    dtype. Per implementation:

      - ``xla``: `update_slot_cache` reads the pool pages (POOL dtype — the
        only quantized pass), dequantizes into a logical [S, L, hkv, d] K/V
        buffer it writes, then the masked attention reads that buffer back —
        the gather write + re-read move COMPUTE-dtype bytes even on a
        quantized pool, which is exactly why the oracle is the parity path
        and dequant must fuse into the kernel to bank the bandwidth.
      - ``pallas_paged``: the kernel streams each table page into VMEM once —
        1 pass at POOL dtype, no materialized buffer.

    An estimate, not a measurement (XLA may fuse or spill differently): its
    job is to size the bandwidth claim a real-hardware run should verify."""
    if compute_dtype_bytes is None:
        compute_dtype_bytes = pool_dtype_bytes
    L = pages_per_slot * page_size
    hkv = getattr(model_cfg, "num_key_value_heads", model_cfg.num_attention_heads)
    values = num_slots * L * hkv * model_cfg.head_dim * 2  # K + V
    per_layer = {
        "xla": values * (pool_dtype_bytes + 2 * compute_dtype_bytes),
        "pallas_paged": values * pool_dtype_bytes,
    }
    return {
        impl: val * model_cfg.num_hidden_layers for impl, val in per_layer.items()
    }


def run_attention_workload(model, args, cfg, max_length, workload, tracer=None):
    """The kernel-vs-XLA A/B: the SAME mixed workload served through two
    otherwise-identical paged engines, attention_impl "xla" (gather oracle)
    vs "pallas_paged" (fused page-walk kernels). Each engine's timed pass
    runs under an armed TraceGuard with the hard 0-recompile /
    0-host-transfer gate — the kernel path must hold the compiled-once
    discipline, not just match tokens — and the block records the impl each
    decode executable ACTUALLY traced (`ops.attention.LAST_DISPATCH`), the
    decode tokens/sec, the mean per-dispatch / per-decode-step chunk seconds,
    and the pool-geometry HBM estimate, so the MFU/bandwidth claim is a
    recorded artifact for the next real-hardware run."""
    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.ops import attention as attention_ops
    from accelerate_tpu.serving import ContinuousBatcher

    import jax

    prompts, budgets, arrivals = workload
    # Off-TPU, pallas_paged runs the Pallas INTERPRETER (the CPU-test shim):
    # parity and the 0-recompile discipline are real, the timing is not — the
    # block records it so a CPU-smoke ratio can never pass as TPU behavior.
    interpreted = jax.default_backend() != "tpu"
    if interpreted:
        log(
            "attention A/B off-TPU: pallas_paged runs the Pallas interpreter — "
            "parity/discipline are meaningful, tokens/sec ratios are NOT "
            "(interpreted=true is recorded in the block)"
        )
    result = {"backend": jax.default_backend()}
    for impl in ("xla", "pallas_paged"):
        engine = ContinuousBatcher(
            model, num_slots=args.num_slots, max_length=max_length,
            chunk_size=args.chunk_size, paged=True, page_size=args.page_size,
            tracer=tracer, max_queue=args.requests, attention_impl=impl,
            weight_dtype=args.weight_dtype, kv_cache_dtype=args.kv_cache_dtype,
        )
        # Honest dtype accounting: pool passes at the LIVE pool leaf dtype
        # (int8/fp8 pools move 1 byte/value), XLA's materialized gather at
        # the compute dtype — never a single params-derived figure.
        pool_bytes = engine.kv_pool_itemsize
        compute_bytes = np.dtype(
            jax.tree_util.tree_leaves(model.params)[0].dtype
        ).itemsize
        log(f"attention workload ({impl}): warmup...")
        engine.warm_inserts()
        run_continuous(engine, prompts, budgets, arrivals)
        # The chunk executable traced during the pass above; LAST_DISPATCH is
        # a trace-time record, so it still names the impl that program chose.
        dispatch_impl = attention_ops.LAST_DISPATCH
        run_continuous(engine, prompts, budgets, arrivals)
        registry = engine.metrics
        chunk_hist = registry.get("serving_chunk_seconds")
        count0, sum0 = chunk_hist.count, chunk_hist.sum
        guard = TraceGuard(
            transfer_guard="disallow", on_violation="record",
            name=f"serving-bench-attention-{impl}",
        )
        engine.trace_guard = guard
        with guard:
            tps, ttfts, iters, span = run_continuous(engine, prompts, budgets, arrivals)
        if guard.total_recompiles or guard.host_transfers:
            log(f"TRACE-GUARD VIOLATIONS in attention workload ({impl}): {guard.report().summary()}")
        # The kernel-path discipline pin: pallas_paged must hold the same
        # steady state as the oracle — one decode executable, page tables as
        # traced operands, zero host syncs.
        assert guard.total_recompiles == 0 and guard.host_transfers == 0, (
            f"attention workload ({impl}) regressed the 0-recompile / "
            f"0-host-transfer discipline: {guard.report().summary()}"
        )
        chunks = chunk_hist.count - count0
        chunk_s = (chunk_hist.sum - sum0) / max(chunks, 1)
        hbm = estimate_decode_hbm_bytes(
            args.num_slots, engine.pages_per_slot, args.page_size, cfg,
            pool_bytes, compute_bytes,
        )
        result[impl] = {
            "dispatch_impl": dispatch_impl,
            "interpreted": interpreted and impl == "pallas_paged",
            "tokens_per_sec": round(tps, 2),
            "decode_iterations": iters,
            "ttft_p50_ms": round(pct(ttfts, 50) * 1000, 2),
            "ttft_p99_ms": round(pct(ttfts, 99) * 1000, 2),
            "makespan_s": round(span, 3),
            "decode_chunk_mean_s": round(chunk_s, 6),
            "decode_attention_s_per_dispatch": round(chunk_s / args.chunk_size, 6),
            "est_hbm_bytes_per_decode_step": hbm[impl],
            "recompiles": guard.total_recompiles,
            "host_transfers": guard.host_transfers,
        }
    result["tokens_per_sec_ratio_pallas_over_xla"] = round(
        result["pallas_paged"]["tokens_per_sec"] / max(result["xla"]["tokens_per_sec"], 1e-9), 3
    )
    result["est_hbm_bytes_ratio_xla_over_pallas"] = round(
        result["xla"]["est_hbm_bytes_per_decode_step"]
        / max(result["pallas_paged"]["est_hbm_bytes_per_decode_step"], 1), 3
    )
    return result


def run_quant_workload(model, args, cfg, max_length, workload, tracer=None):
    """The quantization A/B: the SAME mixed workload served through
    otherwise-identical paged engines — bf16 baseline, int8 weights + int8 KV
    pool, int8 weights + fp8_e4m3 KV pool — each timed pass under the hard
    0-recompile / 0-host-transfer gate (dtypes are static config, scales are
    traced operands: quantization must not cost the compiled-once
    discipline). Per row the block records decode tokens/sec, per-dispatch
    attention seconds, the ACTUAL pool bytes (`engine.kv_cache_nbytes`,
    scales included) and weight bytes, the pool-geometry HBM estimate off the
    live pool dtype, token agreement against the bf16 row's streams, the max
    logit error of the quantized-weight forward vs dense on a probe batch,
    and interpreter provenance. Asserts the headline acceptance number: int8
    KV cuts estimated cache-read bytes >= 2x vs bf16 at identical geometry."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.ops.quantization import params_nbytes, quantize_params_int8, weight_autocast
    from accelerate_tpu.serving import ContinuousBatcher

    prompts, budgets, arrivals = workload
    interpreted = (
        args.attention_impl == "pallas_paged" and jax.default_backend() != "tpu"
    )

    # Max logit error of the int8-weight forward vs dense, one probe batch —
    # the weight-quantization accuracy budget as a recorded artifact. Probe
    # width is the shortest sampled prompt, so ragged --prompt-min/-max
    # settings below 8 tokens still stack.
    width = min(8, min(p.size for p in prompts[:4]))
    probe = jnp.asarray(np.stack([p[:width] for p in prompts[:4]]).astype(np.int32))
    dense_logits = np.asarray(model.apply_fn(model.params, probe), np.float32)
    qparams = quantize_params_int8(
        model.params if "params" in model.params else {"params": model.params}
    )
    with weight_autocast("int8"):
        int8_logits = np.asarray(jax.jit(model.apply_fn)(qparams, probe), np.float32)
    weight_max_logit_err = float(np.abs(int8_logits - dense_logits).max())

    rows = (
        ("bf16", "bf16", "bf16"),
        ("int8", "int8", "int8"),
        ("fp8_e4m3", "int8", "fp8_e4m3"),
    )
    result = {
        "backend": jax.default_backend(),
        "attention_impl": args.attention_impl,
        "weight_int8_max_logit_error_vs_bf16": round(weight_max_logit_err, 6),
    }
    baseline_tokens = None
    for label, weight_dtype, kv_dtype in rows:
        engine = ContinuousBatcher(
            model, num_slots=args.num_slots, max_length=max_length,
            chunk_size=args.chunk_size, paged=True, page_size=args.page_size,
            tracer=tracer, max_queue=args.requests,
            attention_impl=args.attention_impl,
            weight_dtype=weight_dtype, kv_cache_dtype=kv_dtype,
        )
        log(f"quantization workload ({label}): warmup...")
        engine.warm_inserts()
        run_continuous(engine, prompts, budgets, arrivals)
        run_continuous(engine, prompts, budgets, arrivals)
        registry = engine.metrics
        chunk_hist = registry.get("serving_chunk_seconds")
        count0, sum0 = chunk_hist.count, chunk_hist.sum
        guard = TraceGuard(
            transfer_guard="disallow", on_violation="record",
            name=f"serving-bench-quant-{label}",
        )
        engine.trace_guard = guard
        tokens = {}
        with guard:
            tps, ttfts, iters, span = run_continuous(
                engine, prompts, budgets, arrivals, collect_tokens=tokens
            )
        if guard.total_recompiles or guard.host_transfers:
            log(f"TRACE-GUARD VIOLATIONS in quantization workload ({label}): {guard.report().summary()}")
        # The quantization-discipline pin: static dtypes + traced scale
        # operands must keep the one-executable / zero-sync steady state.
        assert guard.total_recompiles == 0 and guard.host_transfers == 0, (
            f"quantization workload ({label}) regressed the 0-recompile / "
            f"0-host-transfer discipline: {guard.report().summary()}"
        )
        if baseline_tokens is None:
            baseline_tokens = tokens
            agreement = 1.0
        else:
            pairs = [
                (x, y)
                for i in baseline_tokens
                for x, y in zip(baseline_tokens[i], tokens.get(i, []))
            ]
            agreement = (
                sum(x == y for x, y in pairs) / len(pairs) if pairs else None
            )
        chunks = chunk_hist.count - count0
        chunk_s = (chunk_hist.sum - sum0) / max(chunks, 1)
        compute_bytes = np.dtype(
            jax.tree_util.tree_leaves(model.params)[0].dtype
        ).itemsize
        hbm = estimate_decode_hbm_bytes(
            args.num_slots, engine.pages_per_slot, args.page_size, cfg,
            engine.kv_pool_itemsize, compute_bytes,
        )
        result[label] = {
            "weight_dtype": weight_dtype,
            "kv_cache_dtype": kv_dtype,
            "interpreted": interpreted,
            "tokens_per_sec": round(tps, 2),
            "ttft_p50_ms": round(pct(ttfts, 50) * 1000, 2),
            "ttft_p99_ms": round(pct(ttfts, 99) * 1000, 2),
            "makespan_s": round(span, 3),
            "decode_iterations": iters,
            "decode_chunk_mean_s": round(chunk_s, 6),
            "decode_attention_s_per_dispatch": round(chunk_s / args.chunk_size, 6),
            "kv_pool_bytes": engine.kv_cache_nbytes,
            "kv_pool_itemsize": engine.kv_pool_itemsize,
            "weight_bytes": params_nbytes(engine.params),
            # Both impls' estimates ride every row: the serving impl's number
            # is what THIS engine moved; the pallas one is the fused-dequant
            # hot-path claim (the XLA oracle re-materializes the gather in
            # the compute dtype, so its quantized saving is structurally
            # smaller — that is the point of fusing).
            "est_hbm_bytes_per_decode_step": hbm[args.attention_impl],
            "est_hbm_bytes_per_decode_step_pallas": hbm["pallas_paged"],
            "token_agreement_vs_bf16": round(agreement, 4) if agreement is not None else None,
            "recompiles": guard.total_recompiles,
            "host_transfers": guard.host_transfers,
        }
    ratio = result["bf16"]["est_hbm_bytes_per_decode_step_pallas"] / max(
        result["int8"]["est_hbm_bytes_per_decode_step_pallas"], 1
    )
    result["est_cache_hbm_ratio_bf16_over_int8"] = round(ratio, 3)
    # The acceptance headline, evaluated on the fused-kernel path (one pool
    # pass — where the pool dtype IS the traffic): int8 KV at identical pool
    # geometry must at least halve the estimated cache-read bytes per step.
    assert ratio >= 2.0, (
        f"int8 KV cache only cut estimated cache-read HBM bytes by {ratio:.2f}x "
        "(expected >= 2x at identical pool geometry) — dtype accounting is off"
    )
    result["kv_pool_bytes_ratio_bf16_over_int8"] = round(
        result["bf16"]["kv_pool_bytes"] / max(result["int8"]["kv_pool_bytes"], 1), 3
    )
    result["weight_bytes_ratio_bf16_over_int8"] = round(
        result["bf16"]["weight_bytes"] / max(result["int8"]["weight_bytes"], 1), 3
    )
    return result


def _run_guarded_engine_pass(model, args, cfg, max_length, workload, tracer, label, **engine_kwargs):
    """One engine through the shared A/B measurement harness: build it, warm
    the insert ladder, run the workload twice unguarded (compiles + page-pool
    steady state), then once under an armed TraceGuard collecting tokens.
    Returns (row, tokens, engine) — `row` carries the timing/footprint fields
    every A/B block shares, with the 0-recompile / 0-host-transfer gate
    already asserted."""
    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.serving import ContinuousBatcher

    prompts, budgets, arrivals = workload
    engine = ContinuousBatcher(
        model, num_slots=args.num_slots, max_length=max_length,
        chunk_size=args.chunk_size, paged=not args.no_paged,
        page_size=args.page_size, tracer=tracer, max_queue=args.requests,
        attention_impl=args.attention_impl,
        weight_dtype=args.weight_dtype, kv_cache_dtype=args.kv_cache_dtype,
        **engine_kwargs,
    )
    log(f"{label}: warmup...")
    engine.warm_inserts()
    run_continuous(engine, prompts, budgets, arrivals)
    run_continuous(engine, prompts, budgets, arrivals)
    chunk_hist = engine.metrics.get("serving_chunk_seconds")
    count0, sum0 = chunk_hist.count, chunk_hist.sum
    guard = TraceGuard(
        transfer_guard="disallow", on_violation="record", name=f"serving-bench-{label}",
    )
    engine.trace_guard = guard
    tokens = {}
    with guard:
        tps, ttfts, iters, span = run_continuous(
            engine, prompts, budgets, arrivals, collect_tokens=tokens
        )
    if guard.total_recompiles or guard.host_transfers:
        log(f"TRACE-GUARD VIOLATIONS in {label}: {guard.report().summary()}")
    # The sharded-operand discipline pin: collectives inserted by GSPMD
    # must not cost the one-executable / zero-host-sync steady state.
    assert guard.total_recompiles == 0 and guard.host_transfers == 0, (
        f"{label} regressed the 0-recompile / 0-host-transfer discipline: "
        f"{guard.report().summary()}"
    )
    chunks = chunk_hist.count - count0
    chunk_s = (chunk_hist.sum - sum0) / max(chunks, 1)
    row = {
        "tokens_per_sec": round(tps, 2),
        "ttft_p50_ms": round(pct(ttfts, 50) * 1000, 2),
        "ttft_p99_ms": round(pct(ttfts, 99) * 1000, 2),
        "makespan_s": round(span, 3),
        "decode_iterations": iters,
        "decode_chunk_mean_s": round(chunk_s, 6),
        "per_chip_weight_bytes": engine.per_device_weight_nbytes,
        "per_chip_kv_pool_bytes": engine.per_device_kv_cache_nbytes,
        "params_leaves_sharded": sum(
            1 for spec in engine.tp_sharding_report()["params"].values() if "model" in spec
        ),
        "recompiles": guard.total_recompiles,
        "host_transfers": guard.host_transfers,
    }
    return row, tokens, engine


def _token_agreement(baseline_tokens, tokens, what):
    """Exact greedy-token agreement between two passes of the same workload:
    identical per-request token COUNTS (a zip would silently forgive a short
    stream) and identical values. GSPMD partitioning is a layout change, not
    a numerics change, so anything under 1.0 asserts."""
    lengths = {i: len(v) for i, v in baseline_tokens.items()}
    assert lengths == {i: len(v) for i, v in tokens.items()}, (
        f"{what} emitted a different token COUNT per request"
    )
    pairs = [
        (x, y)
        for i in baseline_tokens
        for x, y in zip(baseline_tokens[i], tokens.get(i, []))
    ]
    agreement = sum(x == y for x, y in pairs) / len(pairs) if pairs else None
    assert agreement == 1.0, (
        f"{what} diverged (agreement {agreement}) — sharded decode is not token-exact"
    )
    return agreement


def run_tensor_parallel_workload(model, args, cfg, max_length, workload, tracer=None):
    """The tensor-parallel A/B (`--tp N`): the SAME mixed workload served by a
    single-device engine and by one engine spanning an N-device submesh
    (weights Megatron-sharded by the model family's rules, the KV pool
    sharded by KV head, page tables and sampling scalars replicated traced
    operands). Per row the block records decode tokens/sec, per-dispatch
    attention seconds, and PER-CHIP weight + KV-pool bytes read off the live
    shardings (`engine.per_device_*_nbytes`), each timed pass under the hard
    0-recompile / 0-host-transfer gate. Asserts the two acceptance headlines:
    greedy token IDENTITY tp=N vs tp=1, and combined per-chip weight+pool
    bytes dropping to ~1/N (>= 60% of the ideal reduction — replicated
    norms/biases/scalars keep it off the exact bound)."""
    import jax

    tp_n = int(args.tp)
    result = {
        "backend": jax.default_backend(),
        "attention_impl": args.attention_impl,
        "kv_cache_dtype": args.kv_cache_dtype,
        "weight_dtype": args.weight_dtype,
        "devices_visible": len(jax.devices()),
    }
    baseline_tokens = None
    for tp in (1, tp_n):
        label = f"tp{tp}"
        row, tokens, engine = _run_guarded_engine_pass(
            model, args, cfg, max_length, workload, tracer,
            f"tensor-parallel workload ({label})",
            tp=tp, sharding_rules=getattr(args, "sharding", None),
        )
        if baseline_tokens is None:
            baseline_tokens = tokens
            agreement = 1.0
        else:
            agreement = _token_agreement(
                baseline_tokens, tokens, f"tp={tp} vs tp=1 greedy tokens"
            )
        row["tp"] = tp
        row["decode_attention_s_per_dispatch"] = round(
            row["decode_chunk_mean_s"] / args.chunk_size, 6
        )
        row["token_agreement_vs_tp1"] = round(agreement, 4) if agreement is not None else None
        result[label] = row
    base = result["tp1"]["per_chip_weight_bytes"] + result["tp1"]["per_chip_kv_pool_bytes"]
    tp_key = f"tp{tp_n}"
    spanned = result[tp_key]["per_chip_weight_bytes"] + result[tp_key]["per_chip_kv_pool_bytes"]
    ratio = base / max(spanned, 1)
    result["per_chip_bytes_ratio_tp1_over_tpN"] = round(ratio, 3)
    result["tokens_per_sec_ratio_tpN_over_tp1"] = round(
        result[tp_key]["tokens_per_sec"] / max(result["tp1"]["tokens_per_sec"], 1e-9), 3
    )
    # The footprint headline: per-chip weight+pool bytes must approach 1/N.
    # 60% of ideal leaves room for replicated norms/biases/pad masks at the
    # tiny CPU-smoke sizes; real model shapes sit much closer to N.
    assert ratio >= 1.0 + 0.6 * (tp_n - 1), (
        f"tp={tp_n} only cut per-chip weight+pool bytes {ratio:.2f}x "
        f"(expected >= {1.0 + 0.6 * (tp_n - 1):.2f}x) — something is "
        "silently replicated (see engine.tp_sharding_report())"
    )
    return result


def run_sharding_plan_workload(model, args, cfg, max_length, workload, tracer=None):
    """The sharding-source A/B (`--tp N` engines, hand `rules` vs planner
    `auto`): the SAME mixed workload served by two mesh-spanning engines that
    differ ONLY in where their partition table came from — the model family's
    hand-written rules, or the cost-model planner's emitted table
    (`parallel/planner.py`, `sharding_rules="auto"`). Per row: decode
    tokens/sec, per-chip weight + KV-pool bytes read off the LIVE shardings,
    and for the auto engine the planner's predictions next to reality — the
    predicted-vs-live per-chip byte error and the predicted-vs-measured
    step-time error (the honesty metric behind measure-and-refine). Asserts
    the acceptance headlines: greedy tokens IDENTICAL auto vs rules, both
    engines under the 0-recompile / 0-host-transfer gate, and auto per-chip
    weight+pool bytes at >= 60% of the ideal 1/N reduction off the
    replicated footprint."""
    import jax

    tp_n = int(args.tp)
    result = {
        "backend": jax.default_backend(),
        "tp": tp_n,
        "devices_visible": len(jax.devices()),
    }
    baseline_tokens = None
    for mode in ("rules", "auto"):
        row, tokens, engine = _run_guarded_engine_pass(
            model, args, cfg, max_length, workload, tracer,
            f"sharding-plan workload ({mode})",
            tp=tp_n, sharding_rules=mode,
        )
        if baseline_tokens is None:
            baseline_tokens = tokens
            agreement = 1.0
        else:
            # The planner emits a table the SAME GSPMD derivation consumes:
            # a layout change, never a numerics change.
            agreement = _token_agreement(
                baseline_tokens, tokens, "sharding_rules='auto' vs the hand rules"
            )
        measured_step_s = row["decode_chunk_mean_s"] / args.chunk_size
        row["sharding"] = mode
        row["measured_step_s"] = round(measured_step_s, 6)
        row["token_agreement_vs_rules"] = round(agreement, 4) if agreement is not None else None
        if engine.sharding_plan is not None:
            plan = engine.sharding_plan
            predicted_bytes = plan.cost.per_chip_param_bytes
            live_bytes = engine.per_device_weight_nbytes
            predicted_step = plan.cost.step_time_s
            row["planner"] = {
                "rules_emitted": len(plan.rules),
                "predicted_per_chip_param_bytes": int(predicted_bytes),
                "predicted_per_chip_kv_bytes": int(plan.cost.per_chip_kv_bytes),
                "predicted_collective_bytes_per_dispatch": int(plan.cost.collective_bytes),
                "predicted_step_s": round(predicted_step, 9),
                "predicted_vs_live_bytes_error": round(
                    abs(predicted_bytes - live_bytes) / max(live_bytes, 1), 4
                ),
                "predicted_vs_measured_step_error": round(
                    abs(predicted_step - measured_step_s) / max(measured_step_s, 1e-12), 4
                ),
            }
        # The footprint headline off the LIVE shardings: per-chip weight+pool
        # bytes at >= 60% of the ideal 1/N cut from the replicated footprint
        # (replicated norms/biases/page tables keep it off the exact bound).
        replicated = sum(
            int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            for tree in (engine.params, engine._cache)
            for leaf in jax.tree_util.tree_leaves(tree)
        )
        spanned = row["per_chip_weight_bytes"] + row["per_chip_kv_pool_bytes"]
        ratio = replicated / max(spanned, 1)
        row["per_chip_bytes_ratio_vs_replicated"] = round(ratio, 3)
        assert ratio >= 1.0 + 0.6 * (tp_n - 1), (
            f"sharding={mode} only cut per-chip weight+pool bytes {ratio:.2f}x "
            f"(expected >= {1.0 + 0.6 * (tp_n - 1):.2f}x) — something is "
            "silently replicated (see engine.tp_sharding_report())"
        )
        result[mode] = row
    result["tokens_per_sec_ratio_auto_over_rules"] = round(
        result["auto"]["tokens_per_sec"] / max(result["rules"]["tokens_per_sec"], 1e-9), 3
    )
    return result


def run_prefix_workload(model, args, cfg, max_length, rng, tracer=None):
    """The prefix-heavy serving workload: every request opens with the SAME
    `--prefix-tokens`-long system prompt followed by a random tail. Served
    twice through paged engines — shared-prefix cache ON vs OFF — so the
    prefill-tokens-saved and TTFT deltas are measured against a same-run
    baseline, with a fresh TraceGuard armed over each timed pass (the paged
    cache must hold the 0-recompile / 0-host-transfer discipline too)."""
    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.serving import ContinuousBatcher

    prefix = rng.integers(1, cfg.vocab_size, (args.prefix_tokens,)).astype(np.int32)
    tail_max = max(args.prompt_min, max_length - args.max_new_max - args.prefix_tokens)
    prompts = [
        np.concatenate(
            [prefix, rng.integers(1, cfg.vocab_size, (int(rng.integers(args.prompt_min, tail_max + 1)),)).astype(np.int32)]
        )
        for _ in range(args.requests)
    ]
    budgets = [int(rng.integers(args.max_new_min, args.max_new_max + 1)) for _ in range(args.requests)]
    arrivals = np.cumsum(rng.exponential(args.mean_interarrival, size=args.requests))

    result = {"prefix_tokens": args.prefix_tokens}
    for label, use_prefix in (("uncached", False), ("cached", True)):
        engine = ContinuousBatcher(
            model, num_slots=args.num_slots, max_length=max_length,
            chunk_size=args.chunk_size, paged=True, page_size=args.page_size,
            prefix_cache=use_prefix, tracer=tracer, max_queue=args.requests,
        )
        log(f"prefix workload ({label}): warmup...")
        # The closed bucket ladder first (no admission can mint a fresh
        # bucket), then twice through the real traffic: pass 1 registers the
        # prefix, pass 2 runs the prefix-HIT suffix path before timing.
        engine.warm_inserts()
        run_continuous(engine, prompts, budgets, arrivals)
        run_continuous(engine, prompts, budgets, arrivals)
        guard = TraceGuard(
            transfer_guard="disallow", on_violation="record",
            name=f"serving-bench-prefix-{label}",
        )
        engine.trace_guard = guard
        with guard:
            tps, ttfts, _iters, span = run_continuous(engine, prompts, budgets, arrivals)
        if guard.total_recompiles or guard.host_transfers:
            log(f"TRACE-GUARD VIOLATIONS in prefix workload ({label}): {guard.report().summary()}")
        # The tracing-overhead pin, prefix half: span instrumentation rides
        # these timed passes too and must not cost a recompile or a sync.
        assert guard.total_recompiles == 0 and guard.host_transfers == 0, (
            f"prefix workload ({label}) regressed the 0-recompile / 0-host-transfer "
            f"discipline with tracing enabled: {guard.report().summary()}"
        )
        stats = engine.stats
        result[label] = {
            "tokens_per_sec": round(tps, 2),
            "ttft_p50_ms": round(pct(ttfts, 50) * 1000, 2),
            "ttft_p99_ms": round(pct(ttfts, 99) * 1000, 2),
            "makespan_s": round(span, 3),
            "prefill_tokens_saved": stats["prefix_cache"]["prefill_tokens_saved"],
            "prefix_hits": stats["prefix_cache"]["hits"],
            "prefix_misses": stats["prefix_cache"]["misses"],
            "prefix_evictions": stats["prefix_cache"]["evictions"],
            "pages_total": stats["pages_total"],
            "recompiles": guard.total_recompiles,
            "host_transfers": guard.host_transfers,
        }
    result["ttft_p50_ratio_uncached_over_cached"] = round(
        result["uncached"]["ttft_p50_ms"] / max(result["cached"]["ttft_p50_ms"], 1e-9), 3
    )
    return result


def run_ramp_workload(model, args, cfg, max_length, rng, tracer=None):
    """The open-loop capacity ramp (`--workload ramp`): requests arrive at a
    FIXED offered rate regardless of completions (open loop — the arrival
    process never slows down for a saturated server, unlike the closed-loop
    workloads above), swept over geometrically increasing rates. Each level
    records p99 TTFT against offered load; the **knee point** — the highest
    offered rate whose p99 TTFT stays within `--ramp-knee-factor` of the
    unloaded level — is the fleet's capacity number, emitted in the JSON.

    Runs against the in-process fleet by default and against REAL subprocess
    engine workers with `--out-of-process`: same workload, same knee
    definition, so the two topologies' capacity numbers are comparable. The
    0-recompile / 0-host-transfer discipline is enforced per engine — a
    process-wide TraceGuard in-process, the workers' own guards (reset after
    warmup, read back through stats) out of process."""
    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.router import Router
    from accelerate_tpu.serving import QueueFull, Request

    n = args.ramp_requests
    prompts = [
        rng.integers(1, cfg.vocab_size, (int(rng.integers(args.prompt_min, args.prompt_max + 1)),)).astype(np.int32)
        for _ in range(n)
    ]
    budgets = [int(rng.integers(args.max_new_min, args.max_new_max + 1)) for _ in range(n)]
    replicas = max(args.replicas, 1)
    router = Router(
        model,
        replicas=replicas,
        num_slots=args.num_slots,
        max_length=max_length,
        chunk_size=args.chunk_size,
        # Open loop: overload must surface as TTFT blow-up (the knee), not as
        # rejected arrivals — the queue bound is sized above one full level.
        max_queue=max(4 * n, 64),
        default_deadline_s=600.0,
        paged=not args.no_paged,
        page_size=args.page_size,
        tracer=tracer,
        out_of_process=args.out_of_process,
        worker_kwargs=(
            dict(guard=True, transport=args.transport) if args.out_of_process else None
        ),
        stall_degrade_s=None,
        weight_dtype=args.weight_dtype, kv_cache_dtype=args.kv_cache_dtype,
    )
    next_id = 0

    def run_level(rate):
        """One offered-load level on the shared virtual clock (real step
        durations, virtual arrivals at `rate` req/s). Returns per-request
        TTFTs and the rejected count."""
        nonlocal next_id
        base = next_id
        arrivals = {base + i: i / rate for i in range(n)}
        clock = 0.0
        submitted = 0
        rejected = 0
        first_seen = {}
        while submitted < n or router.pending:
            while submitted < n and arrivals[base + submitted] <= clock:
                rid = base + submitted
                try:
                    router.submit(Request(
                        rid, prompts[submitted], max_new_tokens=budgets[submitted]
                    ))
                except QueueFull:
                    rejected += 1
                submitted += 1
            if not router.pending and submitted < n:
                clock = max(clock, arrivals[base + submitted])
                continue
            t0 = time.perf_counter()
            events = router.step()
            clock += time.perf_counter() - t0
            for rid, _toks in events:
                first_seen.setdefault(rid, clock)
        next_id = base + n
        ttfts = [first_seen[rid] - arrivals[rid] for rid in sorted(first_seen)]
        for rid in list(router.results):
            router.release(rid)
        return ttfts, rejected

    rates = [args.ramp_base_rate * (2.0 ** k) for k in range(args.ramp_levels)]
    log(f"ramp workload ({'out-of-process' if args.out_of_process else 'in-process'}, "
        f"{replicas} replica(s)): warmup...")
    warmed = router.warm_inserts()
    log(f"ramp insert buckets warmed: {sorted(set(sum(warmed.values(), [])))}")
    run_level(rates[0])  # decode executables + prefix floors warm

    guard = None
    if args.out_of_process:
        for replica in router.replica_set.replicas:
            assert replica.engine.reset_guard(), "worker spawned without --guard"
    else:
        guard = TraceGuard(
            transfer_guard="disallow", on_violation="record", name="serving-bench-ramp"
        )
        guard.__enter__()

    levels = []
    for rate in rates:
        ttfts, rejected = run_level(rate)
        completed = len(ttfts)
        levels.append({
            "offered_rps": round(rate, 3),
            "offered_tokens_per_sec": round(rate * float(np.mean(budgets)), 2),
            "completed": completed,
            "rejected": rejected,
            "ttft_p50_ms": round(pct(ttfts, 50) * 1000, 2) if ttfts else None,
            "ttft_p99_ms": round(pct(ttfts, 99) * 1000, 2) if ttfts else None,
        })
        log(f"ramp level {rate:.1f} req/s: p99 TTFT {levels[-1]['ttft_p99_ms']}ms, "
            f"{completed}/{n} completed, {rejected} rejected")

    worker_guards = None
    if guard is not None:
        guard.__exit__(None, None, None)
        assert guard.total_recompiles == 0 and guard.host_transfers == 0, (
            "ramp workload regressed the 0-recompile / 0-host-transfer discipline: "
            f"{guard.report().summary()}"
        )
        recompiles, host_transfers = guard.total_recompiles, guard.host_transfers
    else:
        # Per-worker discipline: every subprocess engine's own guard must have
        # stayed at zero across every timed level.
        worker_guards = {}
        recompiles = host_transfers = 0
        for replica in router.replica_set.replicas:
            stats = replica.engine.stats
            info = (stats.get("worker") or {}).get("guard") or {}
            worker_guards[replica.index] = info
            recompiles += int(info.get("recompiles", 0))
            host_transfers += int(info.get("host_transfers", 0))
        assert recompiles == 0 and host_transfers == 0, (
            "a subprocess worker regressed the 0-recompile / 0-host-transfer "
            f"discipline under the ramp: {worker_guards}"
        )

    # The knee: the highest offered rate whose p99 TTFT is still within
    # ramp_knee_factor of the unloaded (first) level — the capacity number.
    base_p99 = levels[0]["ttft_p99_ms"] or 1e-9
    knee = levels[0]
    for level in levels:
        if level["ttft_p99_ms"] is not None and (
            level["ttft_p99_ms"] <= args.ramp_knee_factor * base_p99
        ) and level["rejected"] == 0:
            knee = level
    saturated = knee is not levels[-1]
    router.close()
    return {
        "out_of_process": args.out_of_process,
        "transport": args.transport if args.out_of_process else None,
        "replicas": replicas,
        "requests_per_level": n,
        "levels": levels,
        "knee": {
            "offered_rps": knee["offered_rps"],
            "offered_tokens_per_sec": knee["offered_tokens_per_sec"],
            "ttft_p99_ms": knee["ttft_p99_ms"],
            "knee_factor": args.ramp_knee_factor,
            # False means every level stayed under the knee: the ramp never
            # reached saturation and capacity is a lower bound.
            "saturated": saturated,
        },
        "recompiles": recompiles,
        "host_transfers": host_transfers,
        "worker_guards": worker_guards,
    }


def run_transport_workload(model, args, cfg, max_length, rng, tracer=None):
    """The pipe-vs-socket transport A/B (loopback): the SAME mixed workload
    served through two out-of-process fleets of real subprocess workers
    (`accelerate_tpu.worker`) — one over the spawned stdio pipe framing, one
    over a loopback TCP socket (the worker self-listens, the controller dials
    and handshakes) — so the JSON records what the socket hop itself costs.
    Both fleets report tokens/sec, TTFT p50/p99, and the frame RTT histogram
    (`transport_rtt_seconds`, observed on every protocol roundtrip through
    the shared registry the router attaches); the delta between the two RTT
    medians is the wire overhead number. The framing is byte-identical on
    both transports, so greedy token parity across them is asserted, and BOTH
    paths hold the per-worker 0-recompile / 0-host-transfer discipline (each
    worker's own TraceGuard, reset after warmup, read back through stats)."""
    from accelerate_tpu.router import Router
    from accelerate_tpu.serving import Request

    prompts, budgets, arrivals = build_workload(args, cfg.vocab_size, rng)
    n = len(prompts)

    def run_fleet(transport):
        router = Router(
            model, replicas=1, num_slots=args.num_slots, max_length=max_length,
            chunk_size=args.chunk_size, max_queue=args.requests + 16,
            default_deadline_s=600.0, paged=not args.no_paged,
            page_size=args.page_size, tracer=tracer, stall_degrade_s=None,
            weight_dtype=args.weight_dtype, kv_cache_dtype=args.kv_cache_dtype,
            out_of_process=True,
            worker_kwargs=dict(guard=True, transport=transport),
        )
        try:
            def run_traffic():
                clock = 0.0
                submitted = 0
                first_seen = {}
                delivered = 0
                while submitted < n or router.pending:
                    while submitted < n and float(arrivals[submitted]) <= clock:
                        router.submit(Request(
                            submitted, prompts[submitted],
                            max_new_tokens=budgets[submitted],
                        ))
                        submitted += 1
                    if not router.pending and submitted < n:
                        clock = float(arrivals[submitted])
                        continue
                    t0 = time.perf_counter()
                    events = router.step()
                    clock += time.perf_counter() - t0
                    for rid, toks in events:
                        first_seen.setdefault(rid, clock)
                        delivered += len(toks)
                tokens = {i: list(router.results[i].tokens) for i in range(n)}
                reasons = {}
                for i in range(n):
                    reason = router.results[i].finish_reason
                    reasons[reason] = reasons.get(reason, 0) + 1
                ttfts = [first_seen.get(i, clock) - float(arrivals[i]) for i in range(n)]
                makespan = clock - float(arrivals[0])
                for i in range(n):
                    router.release(i)
                return tokens, ttfts, delivered, makespan, reasons

            log(f"transport A/B ({transport}): warmup...")
            warmed = router.warm_inserts()
            log(f"transport A/B ({transport}) insert buckets warmed: "
                f"{sorted(set(sum(warmed.values(), [])))}")
            # Two warm passes, like the headline continuous path: the first
            # registers prompt prefixes, the second runs the prefix-HIT suffix
            # path, so the timed pass below can't mint a fresh executable.
            run_traffic()
            run_traffic()
            for replica in router.replica_set.replicas:
                assert replica.engine.reset_guard(), "worker spawned without --guard"
            tokens, ttfts, delivered, makespan, reasons = run_traffic()
            # Per-worker discipline: the transport must be a wire change, not
            # a compute change — the worker's own guard stayed at 0/0 across
            # the timed pass on BOTH transports (the ISSUE gate names the
            # socket path; holding pipe to the same bar keeps the A/B honest).
            worker_guards = {}
            recompiles = host_transfers = 0
            for replica in router.replica_set.replicas:
                stats = replica.engine.stats
                info = (stats.get("worker") or {}).get("guard") or {}
                worker_guards[replica.index] = info
                recompiles += int(info.get("recompiles", 0))
                host_transfers += int(info.get("host_transfers", 0))
            assert recompiles == 0 and host_transfers == 0, (
                f"a subprocess worker regressed the 0-recompile / "
                f"0-host-transfer discipline on the {transport} transport: "
                f"{worker_guards}"
            )
            # Frame RTT: every controller->worker protocol call observes its
            # roundtrip into the fleet registry (cumulative over warmup + the
            # timed pass — the transport's wire cost, not workload timing).
            rtt = router.metrics.get("transport_rtt_seconds", {"replica": "0"})
            rtt_block = None
            if rtt is not None and rtt.count:
                rtt_block = {
                    "count": rtt.count,
                    "mean_us": round(rtt.sum / rtt.count * 1e6, 1),
                    "p50_us": round((rtt.quantile(0.5) or 0.0) * 1e6, 1),
                    "p99_us": round((rtt.quantile(0.99) or 0.0) * 1e6, 1),
                }
            block = {
                "tokens_per_sec": round(delivered / max(makespan, 1e-9), 2),
                "tokens_delivered": delivered,
                "ttft_p50_ms": round(pct(ttfts, 50) * 1000, 2),
                "ttft_p99_ms": round(pct(ttfts, 99) * 1000, 2),
                "makespan_s": round(makespan, 3),
                "finish_reasons": reasons,
                "frame_rtt": rtt_block,
                "recompiles": recompiles,
                "host_transfers": host_transfers,
            }
            return block, tokens
        finally:
            router.close()

    pipe_block, pipe_tokens = run_fleet("pipe")
    socket_block, socket_tokens = run_fleet("socket")
    _token_agreement(pipe_tokens, socket_tokens, "the socket-transport fleet")
    overhead = None
    if pipe_block["frame_rtt"] and socket_block["frame_rtt"]:
        overhead = round(
            socket_block["frame_rtt"]["p50_us"] - pipe_block["frame_rtt"]["p50_us"], 1
        )
    return {
        "pipe": pipe_block,
        "socket": socket_block,
        # Median frame RTT delta, socket minus pipe: the loopback TCP hop's
        # per-call cost over the spawned-pipe baseline (negative = noise; the
        # median, because the histogram is cumulative and warmup's compile
        # roundtrips own the mean and the tail).
        "frame_rtt_overhead_us": overhead,
        "tokens_match": True,  # asserted above; pinned in the artifact
    }


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--workload", default="standard", choices=["standard", "ramp"],
                        help="standard: the static-vs-continuous A/B suite; ramp: the "
                        "open-loop arrival ramp (p99 TTFT vs offered load + knee-point "
                        "capacity), against an in-process or --out-of-process fleet")
    parser.add_argument("--out-of-process", action="store_true",
                        help="ramp workload: serve through REAL subprocess engine workers "
                        "(accelerate_tpu.worker) instead of in-process engines")
    parser.add_argument("--transport", default="pipe", choices=["pipe", "socket"],
                        help="out-of-process worker transport: the spawned stdio pipe, or "
                        "a loopback TCP socket (the worker self-listens, the controller "
                        "dials and handshakes) — applies to the --out-of-process ramp "
                        "fleet; the standard workload runs the pipe-vs-socket A/B either "
                        "way (extra.transport) unless --no-transport-ab")
    parser.add_argument("--no-transport-ab", action="store_true",
                        help="skip the pipe-vs-socket transport A/B (extra.transport)")
    parser.add_argument("--ramp-levels", type=int, default=5,
                        help="offered-load levels in the ramp (each doubles the rate)")
    parser.add_argument("--ramp-base-rate", type=float, default=4.0,
                        help="ramp starting offered load in requests per virtual second")
    parser.add_argument("--ramp-requests", type=int, default=None,
                        help="requests per ramp level (default: --requests)")
    parser.add_argument("--ramp-knee-factor", type=float, default=3.0,
                        help="knee = highest rate with p99 TTFT within this factor of "
                        "the unloaded level")
    parser.add_argument("--model", default=None, help="named model (accelerate_tpu.models); default llama-1b on accelerators, llama-tiny on CPU")
    parser.add_argument("--requests", type=int, default=None)
    parser.add_argument("--num-slots", type=int, default=4)
    parser.add_argument("--chunk-size", type=int, default=8)
    parser.add_argument("--prompt-min", type=int, default=8)
    parser.add_argument("--prompt-max", type=int, default=None, help="default 256 on accelerators, 96 on CPU")
    parser.add_argument("--max-new-min", type=int, default=8)
    parser.add_argument("--max-new-max", type=int, default=None, help="default 128 on accelerators, 32 on CPU")
    parser.add_argument("--max-length", type=int, default=None)
    parser.add_argument("--mean-interarrival", type=float, default=0.02, help="Poisson arrival mean gap (virtual seconds)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--page-size", type=int, default=16, help="KV pool page size in tokens (paged cache)")
    parser.add_argument("--no-paged", action="store_true", help="use the contiguous per-slot KV layout (disables the prefix workload)")
    parser.add_argument("--prefix-tokens", type=int, default=None,
                        help="shared system-prompt length for the prefix-heavy workload; default 64 on accelerators, 24 on CPU; 0 disables")
    parser.add_argument("--no-speculative", action="store_true",
                        help="skip the speculative-decode A/B workload")
    parser.add_argument("--draft-tokens", type=int, default=4,
                        help="draft tokens per verify step in the speculative workload")
    parser.add_argument("--draft-ngram", type=int, default=2,
                        help="n-gram length the speculative drafter matches on")
    parser.add_argument("--attention-impl", default="xla", choices=["xla", "pallas_paged"],
                        help="decode/verify attention implementation for the main engine and "
                        "the --replicas fleet: the XLA gather oracle or the fused Pallas "
                        "page-walk kernels (paged cache only)")
    parser.add_argument("--no-attention-ab", action="store_true",
                        help="skip the kernel-vs-XLA attention A/B workload")
    parser.add_argument("--weight-dtype", default="bf16", choices=["bf16", "int8"],
                        help="weight storage dtype for the main engine, the attention A/B "
                        "and the fleet workloads: int8 = per-output-channel weight-only "
                        "quantization with the fused epilogue matmul (ops/quantization.py)")
    parser.add_argument("--kv-cache-dtype", default="bf16", choices=["bf16", "int8", "fp8_e4m3"],
                        help="KV page-pool storage dtype for the same engines: int8/fp8_e4m3 "
                        "store pages quantized with per-page-per-head scale pools, with "
                        "dequant fused into the Pallas decode kernels (paged cache only)")
    parser.add_argument("--no-quant-ab", action="store_true",
                        help="skip the quantization A/B workload (bf16 vs int8 weights + "
                        "int8/fp8 KV cache on the same workload)")
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel A/B: serve the same workload through a "
                        "single-device engine and ONE engine spanning a --tp-device "
                        "submesh (Megatron-sharded weights, KV pool sharded by KV "
                        "head) — token parity asserted, per-chip bytes recorded in "
                        "extra.tensor_parallel; 1 disables")
    parser.add_argument("--sharding", default="rules", choices=["rules", "auto"],
                        help="partition source for the --tp engines: the model family's "
                        "hand-written table, or the cost-model planner's emitted one "
                        "(parallel/planner.py); the rules-vs-auto A/B in "
                        "extra.sharding_plan runs either way unless --no-sharding-ab")
    parser.add_argument("--no-sharding-ab", action="store_true",
                        help="skip the sharding rules-vs-auto A/B (extra.sharding_plan)")
    parser.add_argument("--replicas", type=int, default=1,
                        help="run the replicated-router workload over N engines with a "
                        "kill-one-replica A/B (throughput dip + recovery time); 1 disables")
    parser.add_argument("--trace-dir", default=None,
                        help="flight-recorder trace dir (span JSONL + Perfetto dump); default: a fresh temp dir — the artifact path is emitted in extra.telemetry.trace")
    args = parser.parse_args(argv)

    import jax

    from accelerate_tpu.models import create_named_model, get_model_family
    from accelerate_tpu.serving import ContinuousBatcher

    on_accel = jax.devices()[0].platform in ("tpu", "gpu")
    model_name = args.model or ("llama-1b" if on_accel else "llama-tiny")
    if args.requests is None:
        args.requests = 32 if on_accel else 12
    if args.prompt_max is None:
        args.prompt_max = 256 if on_accel else 96
    if args.prefix_tokens is None:
        args.prefix_tokens = 64 if on_accel else 24
    if args.max_new_max is None:
        args.max_new_max = 128 if on_accel else 32
    if args.ramp_requests is None:
        args.ramp_requests = args.requests
    if args.prompt_min > args.prompt_max:
        parser.error(f"--prompt-min {args.prompt_min} > --prompt-max {args.prompt_max}")
    if args.max_new_min > args.max_new_max:
        parser.error(f"--max-new-min {args.max_new_min} > --max-new-max {args.max_new_max}")

    _fam, cfg = get_model_family(model_name)
    max_length = args.max_length or min(
        cfg.max_position_embeddings, args.prompt_max + args.max_new_max
    )
    if args.prompt_max + args.max_new_max > max_length:
        args.prompt_max = max_length - args.max_new_max
        log(f"capping prompt_max to {args.prompt_max} for the {max_length}-token cache")
        if args.prompt_max < args.prompt_min:
            parser.error(
                f"--max-length {max_length} leaves room for prompts up to "
                f"{args.prompt_max} after --max-new-max {args.max_new_max}, "
                f"below --prompt-min {args.prompt_min}"
            )

    log(f"model {model_name} | slots {args.num_slots} chunk {args.chunk_size} | "
        f"{args.requests} reqs, prompts {args.prompt_min}-{args.prompt_max}, "
        f"max_new {args.max_new_min}-{args.max_new_max}, cache {max_length}")
    model = create_named_model(
        model_name, seq_len=min(128, max_length), param_dtype="bfloat16" if on_accel else None
    )
    rng = np.random.default_rng(args.seed)
    prompts, budgets, arrivals = build_workload(args, cfg.vocab_size, rng)

    from accelerate_tpu.generation import Generator
    from accelerate_tpu.telemetry import FlightRecorder
    from accelerate_tpu.telemetry.tracing import Tracer

    # Request-scoped tracing rides the whole bench: every request's
    # submit->finish span streams into the trace dir, and the Perfetto dump
    # path lands in the JSON (extra.telemetry.trace) so a bench artifact links
    # straight to its timeline. The armed TraceGuard below is the pin that
    # this instrumentation costs 0 recompiles / 0 host transfers.
    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="serving_bench_trace_")
    tracer = Tracer(recorder=FlightRecorder(log_dir=trace_dir), category="serve")

    if args.workload == "ramp":
        ramp = run_ramp_workload(model, args, cfg, max_length, rng, tracer=tracer)
        prefix = "" if on_accel else "cpu-smoke "
        topo = ", out-of-process" if args.out_of_process else ""
        result = {
            "metric": f"{prefix}serving capacity knee (open-loop ramp, {model_name}, "
            f"{ramp['replicas']} replica(s){topo})",
            "value": ramp["knee"]["offered_tokens_per_sec"],
            "unit": "offered tokens/sec at the p99-TTFT knee",
            "extra": {
                "device_kind": jax.devices()[0].device_kind,
                "ramp_workload": ramp,
                "num_slots": args.num_slots,
                "chunk_size": args.chunk_size,
                "prompt_range": [args.prompt_min, args.prompt_max],
                "max_new_range": [args.max_new_min, args.max_new_max],
                "seed": args.seed,
            },
        }
        print(json.dumps(result))
        return 0

    if args.attention_impl == "pallas_paged" and args.no_paged:
        parser.error("--attention-impl pallas_paged requires the paged cache (drop --no-paged)")
    if args.kv_cache_dtype != "bf16" and args.no_paged:
        parser.error("--kv-cache-dtype requires the paged cache (drop --no-paged)")
    engine = ContinuousBatcher(
        model, num_slots=args.num_slots, max_length=max_length, chunk_size=args.chunk_size,
        paged=not args.no_paged, page_size=args.page_size, tracer=tracer,
        max_queue=args.requests, attention_impl=args.attention_impl,
        weight_dtype=args.weight_dtype, kv_cache_dtype=args.kv_cache_dtype,
    )
    static_gen = Generator(model, max_new_tokens=max(budgets), max_length=max_length)

    # Warmup pass: compile every program both paths use (static per batch shape,
    # continuous per insert bucket + the one chunk program), then measure.
    # `warm_inserts` precompiles the engine's CLOSED insert-bucket ladder — a
    # mechanical guarantee that no admission of the timed pass can mint a fresh
    # bucket, whatever prefix-cache depth it arrives at (the first-hit insert
    # recompile that used to trip the 0-recompile assert at non-default
    # --max-new-max / --page-size combinations). The continuous path still
    # warms TWICE: the first pass registers prompt prefixes, so the second
    # runs the prefix-HIT suffix path end to end before timing.
    log("warmup (compiles)...")
    t0 = time.perf_counter()
    run_static(static_gen, prompts, budgets, arrivals, args.num_slots, max_length)
    log(f"insert buckets warmed: {engine.warm_inserts()}")
    run_continuous(engine, prompts, budgets, arrivals)
    # Impl provenance: the decode chunk traced during the pass above (after
    # every insert bucket), and LAST_DISPATCH is a trace-time record — it
    # still names the attention implementation the MAIN engine's one decode
    # executable actually chose, which the JSON pins next to the flag.
    from accelerate_tpu.ops import attention as attention_ops

    main_dispatch_impl = attention_ops.LAST_DISPATCH
    run_continuous(engine, prompts, budgets, arrivals)
    log(f"warmup done in {time.perf_counter() - t0:.1f}s; timed runs...")

    # Steady state runs ARMED: every executable is warm, so the timed passes
    # must neither recompile nor make a guarded (implicit) host transfer — the
    # counters land in the bench JSON and 0/0 is the regression gate. The
    # engine's fault isolation `observe()`s violations it swallows, so they
    # reach this ledger even when serving keeps running.
    from accelerate_tpu.analysis import TraceGuard

    guard = TraceGuard(transfer_guard="disallow", on_violation="record", name="serving-bench")
    engine.trace_guard = guard
    with guard:
        s_tps, s_ttft, s_iters, s_span = run_static(
            static_gen, prompts, budgets, arrivals, args.num_slots, max_length
        )
        c_tps, c_ttft, c_iters, c_span = run_continuous(engine, prompts, budgets, arrivals)
    if guard.total_recompiles or guard.host_transfers:
        log(f"TRACE-GUARD VIOLATIONS in steady state: {guard.report().summary()}")
    assert engine.trace_counts["decode_chunk"] == 1, engine.trace_counts
    # The tracing-overhead pin: span instrumentation (request lifecycles,
    # insert/chunk spans) rides the timed passes above — it must not have
    # cost a single recompile or guarded host transfer.
    assert guard.total_recompiles == 0 and guard.host_transfers == 0, (
        "timed passes regressed the 0-recompile / 0-host-transfer discipline "
        f"with tracing enabled: {guard.report().summary()}"
    )

    # Prefix-heavy workload: same model, shared system prompt across requests,
    # prefix cache ON vs OFF (paged engines only — the contiguous layout has no
    # pages to share).
    prefix_block = None
    if not args.no_paged and args.prefix_tokens > 0:
        max_prefix = max_length - args.max_new_max - args.prompt_min
        if args.prefix_tokens > max_prefix:
            log(f"capping prefix_tokens to {max_prefix} for the {max_length}-token cache")
            args.prefix_tokens = max_prefix
        if args.prefix_tokens >= args.page_size:
            prefix_block = run_prefix_workload(model, args, cfg, max_length, rng, tracer=tracer)
        else:
            log(
                f"prefix_tokens {args.prefix_tokens} < page_size {args.page_size}: "
                "no full page to share; skipping the prefix workload"
            )

    # Speculative-decode A/B: repetition-heavy workload, speculation off vs on,
    # TraceGuard-armed timed passes (hard 0/0 gate with speculation enabled).
    spec_block = None
    if not args.no_speculative:
        spec_block = run_spec_workload(model, args, cfg, max_length, rng, tracer=tracer)
        if (spec_block["accepted_tokens_per_step"] or 0) <= 1.0:
            log(
                "speculation accepted no drafts on the repetitive workload "
                f"(accepted_tokens_per_step={spec_block['accepted_tokens_per_step']}) "
                "— output is still token-identical, but check drafter knobs"
            )

    # Kernel-vs-XLA attention A/B: the SAME workload as the headline timed
    # passes through two otherwise-identical paged engines, so the JSON
    # records both impls' decode tokens/sec plus the pool-geometry HBM
    # estimate — the bandwidth claim as an artifact.
    attention_ab = None
    if not args.no_paged and not args.no_attention_ab:
        attention_ab = run_attention_workload(
            model, args, cfg, max_length, (prompts, budgets, arrivals), tracer=tracer
        )

    # Quantization A/B: bf16 vs int8-weights+int8-KV vs int8-weights+fp8-KV on
    # the same workload — tokens/sec, per-dispatch attention seconds, actual
    # pool/weight bytes, token agreement and the >= 2x cache-byte drop gate.
    quant_block = None
    if not args.no_paged and not args.no_quant_ab:
        quant_block = run_quant_workload(
            model, args, cfg, max_length, (prompts, budgets, arrivals), tracer=tracer
        )

    # Tensor-parallel A/B (--tp N): tp=1 vs one engine spanning N devices on
    # the same workload — token parity and the ~1/N per-chip footprint drop
    # asserted, per-chip bytes read off the live shardings.
    tp_block = None
    if args.tp > 1:
        tp_block = run_tensor_parallel_workload(
            model, args, cfg, max_length, (prompts, budgets, arrivals), tracer=tracer
        )

    # Sharding-source A/B (--tp N): hand rules vs the planner's auto table on
    # the same mesh — token identity + the >= 60%-of-ideal footprint asserted,
    # the planner's predicted-vs-measured step time reported.
    sharding_block = None
    if args.tp > 1 and not args.no_sharding_ab:
        sharding_block = run_sharding_plan_workload(
            model, args, cfg, max_length, (prompts, budgets, arrivals), tracer=tracer
        )

    # Replicated-router A/B: the same workload behind a health-routed fleet,
    # with one replica chaos-killed mid-traffic (dip + recovery measured).
    router_block = None
    if args.replicas > 1:
        router_block = run_router_workload(model, args, cfg, max_length, rng, tracer=tracer)

    # Pipe-vs-socket transport A/B: the same workload through two
    # out-of-process fleets over loopback — the socket hop's cost (frame RTT,
    # TTFT, tokens/sec) as an artifact, token parity + per-worker 0/0 asserted.
    # The memoized TPU tunnel probe verdict rides along (ROADMAP item 7): the
    # artifact states which backend class produced its numbers.
    transport_block = None
    if not args.no_transport_ab:
        transport_block = run_transport_workload(
            model, args, cfg, max_length, rng, tracer=tracer
        )
        transport_block["tunnel_probe_alive"] = _reattempt_tunnel_probe()

    speedup = c_tps / max(s_tps, 1e-9)
    prefix = "" if on_accel else "cpu-smoke "

    # Telemetry block: the engine's MetricsRegistry view of the SAME run —
    # real-wall-clock TTFT / inter-token / chunk latency histograms (cumulative
    # over warmup + timed passes; the virtual-clock numbers above stay the
    # headline) plus occupancy gauges. docs/observability.md documents the
    # instruments.
    registry = engine.metrics

    def _hist_ms(name):
        hist = registry.get(name)
        if hist is None or hist.count == 0:
            return None
        return {
            "count": hist.count,
            "p50_ms": round((hist.quantile(0.5) or 0.0) * 1000, 3),
            "p99_ms": round((hist.quantile(0.99) or 0.0) * 1000, 3),
        }

    # Per-phase span counts + the Perfetto artifact: how many request
    # lifecycles, admission dispatches and decode chunks the recorder saw
    # (ring-bounded — the JSONL streams in trace_dir carry the full history).
    span_counts = {}
    for record in tracer.recorder.records():
        if record.get("kind") == "span":
            span_counts[record["name"]] = span_counts.get(record["name"], 0) + 1
    trace_artifact = tracer.recorder.dump(reason="bench")

    telemetry_block = {
        "ttft": _hist_ms("serving_ttft_seconds"),
        "inter_token": _hist_ms("serving_inter_token_seconds"),
        "chunk": _hist_ms("serving_chunk_seconds"),
        "queue_peak": registry.value("serving_queue_peak"),
        "slot_utilization": registry.value("serving_slot_utilization"),
        "requests_submitted": registry.value("serving_requests_submitted_total"),
        "pages_total": registry.value("serving_pages_total"),
        "pages_in_use": registry.value("serving_pages_in_use"),
        "prefix_cache_hits": registry.value("serving_prefix_cache_hits_total"),
        "prefix_cache_misses": registry.value("serving_prefix_cache_misses_total"),
        "prefix_cache_evictions": registry.value("serving_prefix_cache_evictions_total"),
        "prefill_tokens_saved": registry.value("prefill_tokens_saved_total"),
        "trace": {
            "artifact": trace_artifact,
            "trace_dir": trace_dir,
            "span_counts": span_counts,
        },
    }
    paging_block = {"enabled": not args.no_paged}
    if not args.no_paged:
        paging_block.update(
            page_size=args.page_size,
            pages_total=engine.stats["pages_total"],
            kv_cache_dtype=engine.stats["kv_cache_dtype"],
            prefix_cache=engine.stats["prefix_cache"],
        )
    result = {
        "metric": f"{prefix}continuous-batching serving tokens/sec "
        f"({model_name}, slots {args.num_slots}, chunk {args.chunk_size}, "
        f"{args.requests} mixed reqs)",
        "value": round(c_tps, 2),
        "unit": "tokens/sec",
        # baseline = the static path measured in THIS run: apples-to-apples on
        # any backend (higher is better).
        "vs_baseline": round(speedup, 3),
        "extra": {
            "device_kind": jax.devices()[0].device_kind,
            "static_tokens_per_sec": round(s_tps, 2),
            "continuous_tokens_per_sec": round(c_tps, 2),
            "speedup": round(speedup, 3),
            "ttft_p50_ms_static": round(pct(s_ttft, 50) * 1000, 2),
            "ttft_p99_ms_static": round(pct(s_ttft, 99) * 1000, 2),
            "ttft_p50_ms_continuous": round(pct(c_ttft, 50) * 1000, 2),
            "ttft_p99_ms_continuous": round(pct(c_ttft, 99) * 1000, 2),
            "decode_iterations_static": s_iters,
            "decode_iterations_continuous": c_iters,
            # Engine health ledger (cumulative over warmup + timed passes):
            # how close the queue ran to its backpressure limit, and where
            # every request ended up (all "length" on this EOS-free workload —
            # any timeout/error/cancelled here is a bench regression).
            "queue_peak": engine.stats["queue_peak"],
            "finish_reasons": dict(engine.stats["finish_reasons"]),
            "telemetry": telemetry_block,
            # Attention-impl provenance + the kernel-vs-XLA A/B: which
            # implementation the main engine's decode executable traced, and
            # both impls' decode tokens/sec / per-dispatch seconds / estimated
            # HBM bytes from the same workload (docs/observability.md).
            "attention": {
                "impl": args.attention_impl,
                "dispatch_impl": main_dispatch_impl,
                # pallas_paged off-TPU = the Pallas INTERPRETER (the kernels'
                # interpret=None auto-select): parity and the 0/0 discipline
                # hold, the timing is not kernel timing.
                "interpreted": (
                    args.attention_impl == "pallas_paged"
                    and jax.default_backend() != "tpu"
                ),
                "ab": attention_ab,
            },
            # Quantization A/B (bf16 vs int8 weights + int8/fp8 KV cache):
            # the bandwidth/capacity multipliers as artifacts — tokens/sec,
            # per-dispatch seconds, actual pool + weight bytes, estimated
            # cache-read HBM drop (>= 2x asserted), token agreement vs bf16,
            # max logit error of the int8-weight forward, interpreter
            # provenance. Main-engine dtypes are pinned next to it.
            "quantization": {
                "weight_dtype": args.weight_dtype,
                "kv_cache_dtype": args.kv_cache_dtype,
                "ab": quant_block,
            },
            # Paged-KV state of the MAIN engine plus the shared-system-prompt
            # A/B (prefix cache on/off); prefill_tokens_saved > 0 with TTFT no
            # worse than the uncached run is the prefix-cache acceptance gate.
            "paging": paging_block,
            "prefix_workload": prefix_block,
            # Speculative A/B (repetition-heavy workload): tokens/sec and
            # accepted_tokens_per_step, spec-off vs spec-on, both timed passes
            # TraceGuard-verified at 0 recompiles / 0 host transfers.
            "speculative_workload": spec_block,
            # Tensor-parallel A/B (--tp N): tp=1 vs one mesh-spanning engine
            # on the same workload — tokens/sec, per-dispatch attention
            # seconds, per-chip weight + KV-pool bytes from live shardings
            # (~1/N asserted), greedy token identity asserted, TraceGuard
            # 0/0 per row (docs/observability.md).
            "tensor_parallel": tp_block,
            # hand rules vs planner auto on the same mesh: per-chip bytes off
            # live shardings for BOTH plans + predicted-vs-measured step error
            "sharding_plan": sharding_block,
            # Replicated-fleet A/B (--replicas N): baseline vs kill-one-replica
            # throughput, degraded-window tokens/sec, measured recovery
            # seconds, retry/replica_lost accounting — still 0 recompiles /
            # 0 host transfers per engine.
            "router_workload": router_block,
            # Pipe-vs-socket transport A/B over loopback subprocess fleets:
            # tokens/sec, TTFT p50/p99 and frame RTT per transport, the
            # socket hop's mean RTT overhead, greedy token parity, per-worker
            # 0/0 guards, and the memoized TPU tunnel probe verdict.
            "transport": transport_block,
            # Steady-state discipline counters (TraceGuard armed over both
            # timed passes): any nonzero value is a no-recompile regression.
            "recompiles": guard.total_recompiles,
            "host_transfers": guard.host_transfers,
            "recompiled_executables": dict(guard.compiles),
            "makespan_s_static": round(s_span, 3),
            "makespan_s_continuous": round(c_span, 3),
            "requests": args.requests,
            "num_slots": args.num_slots,
            "chunk_size": args.chunk_size,
            "prompt_range": [args.prompt_min, args.prompt_max],
            "max_new_range": [args.max_new_min, args.max_new_max],
            "mean_interarrival_s": args.mean_interarrival,
            "seed": args.seed,
        },
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())

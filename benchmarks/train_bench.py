"""Training-parallelism benchmark: 1D-replicated vs 2D-ZeRO A/B, and
(``--pipeline-ab``) 2D-ZeRO vs 3D-MPMD-pipeline A/B.

Two passes over the same tiny causal-LM training workload on the one global
mesh (the forced 8-device CPU mesh on the test tier, a real slice when the
TPU tunnel is up):

  - **1d**: ``ParallelismConfig(data=-1)`` — pure data parallelism; params,
    grads and optimizer state fully replicated per chip (the pre-planner
    training layout).
  - **2d**: ``ParallelismConfig(data=-1, model=2)`` with
    ``sharding_rules="auto"`` — the cost-model planner's 2D plan: params
    tensor-parallel over "model", optimizer moments ZeRO-sharded along "data"
    (`parallel/planner.plan_train_sharding`).
  - **3d** (``--pipeline-ab`` swaps the pair to 2d-vs-3d): ``ParallelismConfig(
    data=-1, model=TP, pipeline=PP)`` — the 3D MPMD plan: the planner splits
    the layer stack into byte-balanced stages, each stage jit-compiles against
    its own submesh, and the 1F1B schedule runs them (`parallel/mpmd.py`).
    The pass additionally reports per-chip param/opt bytes off the LIVE stage
    shardings vs the plan's prediction, the compiled-once program audit, and
    the pipeline-bubble account: `measure_stage_times` times each stage's
    compiled fwd+bwd per microbatch and `pipeline_bubble_terms` turns that
    into a MEASURED bubble fraction next to the planner's predicted one.

Per pass: steady-state step time under a TraceGuard (0 recompiles / 0 host
transfers after warmup, ASSERTED), per-chip param/grad/optimizer bytes off the
LIVE shardings (`tree_device_nbytes`), and for the 2d pass the planner's
predicted-vs-live per-chip bytes error for all three trees. Loss-trajectory
parity vs the 1d pass is asserted (same data, same init, same optimizer — the
layout must not change the math).

Emits exactly ONE JSON line on stdout (the bench-driver contract); headline is
the 2d per-chip optimizer-state bytes, ``vs_baseline`` the 1d/2d opt-bytes
ratio (how many times less optimizer HBM each chip holds under ZeRO).

`python bench.py --mode train --zero-ab` routes here. Before touching the
backend the memoized TPU tunnel probe is re-attempted (cheap, fails fast;
bench.py's preflight memo protocol) so a dead tunnel costs seconds, not the
attempt budget.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def log(msg):
    print(f"[train-bench] {msg}", file=sys.stderr, flush=True)


def _reattempt_tunnel_probe() -> bool:
    """Re-attempt the memoized TPU tunnel probe (bench.py's protocol): a fresh
    memo answers instantly, an expired one triggers ONE short probe whose
    verdict is memoized for the next caller. Returns True when an accelerator
    backend is reachable; False pins this run to the CPU mesh."""
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return False  # explicitly pinned; nothing to probe
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        import bench
    except ImportError:
        return False
    memo = bench._read_tunnel_state()
    ttl = bench._env_int("BENCH_TUNNEL_MEMO_TTL", bench.TUNNEL_MEMO_TTL_S)
    age = None if memo is None else time.time() - float(memo.get("checked_at", 0) or 0)
    if memo is not None and age is not None and 0 <= age < ttl:
        alive = bool(memo.get("alive"))
        log(f"tunnel memo: {'alive' if alive else 'dead'} ({age:.0f}s old, "
            f"source={memo.get('source', '?')}); {'using accelerator' if alive else 'CPU mesh'}")
        return alive
    timeout = bench._env_int("BENCH_PREFLIGHT_TIMEOUT", 60)
    alive = bench._backend_preflight(timeout)
    bench._write_tunnel_state(alive, source="train-bench")
    log(f"tunnel probe: {'alive' if alive else 'dead'} (memoized)")
    return alive


def _build_batches(cfg, global_batch, seq_len, count):
    import numpy as np

    rng = np.random.default_rng(0)
    return [
        {"input_ids": rng.integers(0, cfg.vocab_size, (global_batch, seq_len)).astype(np.int32)}
        for _ in range(count)
    ]


def run_pass(mode, args):
    """One measured pass. Returns (result dict, loss list)."""
    import numpy as np
    import optax

    import jax
    from accelerate_tpu import Accelerator
    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.models import CREATE_BY_FAMILY, get_model_family
    from accelerate_tpu.parallel.sharding import tree_device_nbytes
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import ParallelismConfig, set_seed

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    set_seed(0)

    family, cfg = get_model_family(args.model)
    bundle = CREATE_BY_FAMILY[family](cfg, seq_len=args.seq_len)
    if mode == "3d":
        bundle.sharding_rules = "auto"
        pcfg = ParallelismConfig(data=-1, model=args.tp, pipeline=args.pp)
    elif mode == "2d":
        bundle.sharding_rules = "auto"
        pcfg = ParallelismConfig(data=-1, model=args.tp)
    else:
        pcfg = ParallelismConfig(data=-1)
    accelerator = Accelerator(parallelism_config=pcfg)
    mesh_axes = {k: v for k, v in dict(accelerator.mesh.shape).items() if v > 1}
    model, opt = accelerator.prepare(bundle, optax.adam(1e-3))

    # Pre-place batches on the mesh (what the prepared DataLoader does): the
    # TraceGuard below forbids host transfers in the steady-state window, and
    # the steady-state input path IS device-resident.
    from jax.sharding import NamedSharding
    from accelerate_tpu.parallel.sharding import data_spec

    batch_sharding = NamedSharding(accelerator.mesh, data_spec(accelerator.mesh))
    batches = [
        jax.device_put(b, jax.tree_util.tree_map(lambda _: batch_sharding, b))
        for b in _build_batches(cfg, args.global_batch, args.seq_len, args.warmup + args.steps)
    ]
    step_fn = accelerator.train_step()
    for batch in batches[: args.warmup]:
        jax.block_until_ready(step_fn(batch))

    guard = TraceGuard(name=f"train-{mode}", on_violation="record")
    raw_losses = []
    t0 = time.perf_counter()
    with guard:
        for batch in batches[args.warmup :]:
            raw_losses.append(step_fn(batch))
        jax.block_until_ready(raw_losses[-1])
    wall = time.perf_counter() - t0
    losses = [float(l) for l in raw_losses]

    assert guard.total_recompiles == 0, (
        f"{mode} pass recompiled in steady state: {guard.report().summary()}"
    )
    assert guard.host_transfers == 0, (
        f"{mode} pass transferred to host in steady state: {guard.transfer_violations}"
    )

    if mode == "3d":
        # MPMD pass: bytes off the LIVE per-stage shardings (busiest stage),
        # the compiled-once audit, and the measured-vs-predicted bubble.
        from accelerate_tpu.parallel.planner import pipeline_bubble_terms

        plan = model.plan
        counts = model.compiled_program_counts()
        multi = {name: n for name, n in counts.items() if n != 1}
        assert not multi, f"3d pass compiled a stage program more than once: {multi}"

        live = model.live_per_chip_bytes()
        stage_times = model.measure_stage_times(batches[0])
        measured_wall, measured_bubble = pipeline_bubble_terms(
            stage_times, plan.num_microbatches, 0.0
        )
        result = {
            "mesh": mesh_axes,
            "steps": args.steps,
            "step_time_s_mean": wall / args.steps,
            "per_chip_param_bytes": live["per_chip_param_bytes"],
            "per_chip_opt_bytes": live["per_chip_opt_bytes"],
            "recompiles": guard.total_recompiles,
            "host_transfers": guard.host_transfers,
            "final_loss": losses[-1],
            "pipeline": {
                "num_stages": plan.num_stages,
                "stage_layers": [
                    len(plan.stage_plan.stage_layers(k)) for k in range(plan.num_stages)
                ],
                "num_microbatches": plan.num_microbatches,
                "stage_times_s": stage_times,
                "measured_wall_s": measured_wall,
                "measured_bubble_fraction": measured_bubble,
                "predicted_bubble_fraction": plan.bubble_fraction,
                "predicted_p2p_time_s": plan.p2p_time_s,
            },
        }
        for tree, predicted, live_key in (
            ("params", plan.cost.per_chip_param_bytes, "per_chip_param_bytes"),
            ("opt", plan.cost.per_chip_opt_bytes, "per_chip_opt_bytes"),
        ):
            live_bytes = result[live_key]
            result[f"predicted_{tree}_bytes"] = int(predicted)
            result[f"predicted_{tree}_error_pct"] = (
                abs(predicted - live_bytes) / live_bytes * 100.0 if live_bytes else 0.0
            )
        return result, losses

    dev0 = jax.devices()[0]
    # Grads live exactly where the params do (jax.grad output sharding follows
    # the param placement the step pins), so a placed zeros tree measures them.
    from accelerate_tpu.parallel.sharding import place_params

    grads = place_params(
        jax.tree_util.tree_map(lambda x: jax.numpy.zeros_like(x), model.params),
        model.param_compute_sharding,
    )
    result = {
        "mesh": mesh_axes,
        "steps": args.steps,
        "step_time_s_mean": wall / args.steps,
        "per_chip_param_bytes": int(tree_device_nbytes(model.params, dev0)),
        "per_chip_grad_bytes": int(tree_device_nbytes(grads, dev0)),
        "per_chip_opt_bytes": int(tree_device_nbytes(opt.opt_state, dev0)),
        "recompiles": guard.total_recompiles,
        "host_transfers": guard.host_transfers,
        "final_loss": losses[-1],
    }
    if mode == "2d":
        # Predicted-vs-live: re-run the (deterministic) planner the prepare()
        # seam ran and compare its per-chip account against the live bytes.
        from accelerate_tpu.parallel.planner import Workload, plan_sharding

        plan = plan_sharding(
            jax.eval_shape(lambda p: p, model.params),
            {k: v for k, v in dict(accelerator.mesh.shape).items() if k in ("data", "model")},
            axes=tuple(a for a in ("data", "model") if dict(accelerator.mesh.shape).get(a, 1) > 1),
            workload=Workload(batch=8, seq=512, opt_bytes_per_param=8.0),
        )
        for tree, predicted, live_key in (
            ("params", plan.cost.per_chip_param_bytes, "per_chip_param_bytes"),
            ("grads", plan.cost.per_chip_param_bytes, "per_chip_grad_bytes"),
            ("opt", plan.cost.per_chip_opt_bytes, "per_chip_opt_bytes"),
        ):
            live = result[live_key]
            result[f"predicted_{tree}_bytes"] = int(predicted)
            result[f"predicted_{tree}_error_pct"] = (
                abs(predicted - live) / live * 100.0 if live else 0.0
            )
    return result, losses


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="llama-tiny", help="named in-tree model")
    parser.add_argument("--steps", type=int, default=4, help="measured steps per pass")
    parser.add_argument("--warmup", type=int, default=2, help="warmup (compile) steps per pass")
    parser.add_argument("--seq-len", type=int, default=32)
    parser.add_argument("--global-batch", type=int, default=8,
                        help="global batch (must divide by the data axis of BOTH passes)")
    parser.add_argument("--tp", type=int, default=2, help="model-axis size of the 2d/3d passes")
    parser.add_argument("--pp", type=int, default=2,
                        help="pipeline-axis size of the 3d pass (--pipeline-ab)")
    parser.add_argument("--pipeline-ab", action="store_true",
                        help="A/B the 2D ZeRO plan against the 3D MPMD pipeline plan "
                             "(2d-vs-3d) instead of the default 1d-vs-2d")
    parser.add_argument("--loss-atol", type=float, default=2e-4,
                        help="per-step loss parity tolerance between the two passes")
    parser.add_argument("--mode", default="train", help=argparse.SUPPRESS)  # routing residue
    args = parser.parse_args(argv)

    on_accel = _reattempt_tunnel_probe()
    if not on_accel:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

    import jax

    n_chips = jax.device_count()
    log(f"backend: {n_chips}x {jax.devices()[0].device_kind}")

    baseline, contender = ("2d", "3d") if args.pipeline_ab else ("1d", "2d")
    results = {}
    losses = {}
    for mode in (baseline, contender):
        log(f"{mode} pass: {args.warmup}+{args.steps} steps, global batch {args.global_batch}...")
        results[mode], losses[mode] = run_pass(mode, args)
        log(f"{mode}: {results[mode]['step_time_s_mean'] * 1000:.1f} ms/step, "
            f"opt {results[mode]['per_chip_opt_bytes']} B/chip")

    # Loss-trajectory parity: same data, same init, same optimizer — the
    # parallel decomposition must not change the math.
    drift = max(abs(a - b) for a, b in zip(losses[baseline], losses[contender]))
    assert drift <= args.loss_atol, (
        f"{baseline}-vs-{contender} loss trajectories diverged (max |Δ| {drift:.2e} "
        f"> atol {args.loss_atol:.0e}): {losses[baseline]} vs {losses[contender]}"
    )

    device = jax.devices()[0].platform
    prefix = "" if device in ("tpu", "gpu") else "cpu-smoke "
    extra = {
        "device_kind": device,
        "tunnel_probe_alive": on_accel,
        "loss_parity_max_drift": drift,
        f"loss_trajectory_{baseline}": losses[baseline],
        f"loss_trajectory_{contender}": losses[contender],
        baseline: results[baseline],
        contender: results[contender],
    }
    if args.pipeline_ab:
        # Headline: busiest-stage per-chip PARAM bytes under the 3D pipeline
        # plan — pipelining's memory win over the flat 2D mesh. The bubble
        # account (measured vs predicted) rides in extra["3d"]["pipeline"].
        par_2d = results["2d"]["per_chip_param_bytes"]
        par_3d = results["3d"]["per_chip_param_bytes"]
        row = {
            "metric": f"{prefix}per-chip param bytes, 3D MPMD pipeline plan "
            f"({args.model}, mesh {results['3d']['mesh']}, vs 2D ZeRO baseline)",
            "value": par_3d,
            "unit": "bytes/chip",
            # Ratio > 1: how many times less param HBM each chip holds.
            "vs_baseline": round(par_2d / max(par_3d, 1), 3),
            "extra": extra,
        }
    else:
        opt_1d = results["1d"]["per_chip_opt_bytes"]
        opt_2d = results["2d"]["per_chip_opt_bytes"]
        row = {
            "metric": f"{prefix}per-chip optimizer-state bytes, 2D ZeRO plan "
            f"({args.model}, mesh {results['2d']['mesh']}, vs 1D replicated baseline)",
            "value": opt_2d,
            "unit": "bytes/chip",
            # Ratio > 1: how many times less optimizer HBM each chip holds.
            "vs_baseline": round(opt_1d / max(opt_2d, 1), 3),
            "extra": extra,
        }
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())

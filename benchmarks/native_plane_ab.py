"""A/B the native C++ data plane against the pure-Python paths it replaces.

Host-side (no TPU needed); run `python benchmarks/native_plane_ab.py`.

1. Batch gather — the default training-input journey:
   SimpleDataLoader over an ArrayDataset (native gather pool, C++ threads)
   vs the per-row Python collate the loader uses for non-columnar datasets.
   This is the role torch's C++ DataLoader workers play in the reference.

2. Disk tier read — the big-model streamed executor's journey:
   NativeOffloadStore (single blob; group readahead tickets on >1-core hosts,
   inline pread below the stripe floor) vs the reference's layout: one .npy
   file per tensor, opened + mmapped + materialized per access
   (utils/offload.py:25-192), reading layer-sized groups in the access
   pattern of `DispatchedModel._fetch_block_pytree`.

Prints one JSON line per experiment (cpus records the container's core count:
on a 1-vCPU box the pool's parallel pread cannot win — the layout win is
what's measurable there).
"""

import json
import os
import shutil
import tempfile
import time

import numpy as np

from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
from accelerate_tpu.native import ArrayDataset, NativeOffloadStore, native_available


def _time(fn, repeats=3):
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_gather(n_rows=100_000, seq=512, batch=256):
    rng = np.random.default_rng(0)
    cols = {
        "input_ids": rng.integers(0, 32000, size=(n_rows, seq)).astype(np.int32),
        "labels": rng.integers(0, 32000, size=(n_rows, seq)).astype(np.int32),
    }
    ds = ArrayDataset(cols)
    sampler = BatchSampler(range(n_rows), batch)
    native_loader = SimpleDataLoader(ds, sampler)
    assert native_loader._columnar()
    rowwise_loader = SimpleDataLoader(ds, sampler, collate_fn=None)
    rowwise_loader.collate_fn = lambda rows: {  # the pre-columnar per-row path
        k: np.stack([r[k] for r in rows]) for k in rows[0]
    }
    assert not rowwise_loader._columnar()

    def drain(loader):
        for b in loader:
            b["input_ids"].sum()  # touch to defeat lazy anything

    t_native = _time(lambda: drain(native_loader))
    t_rowwise = _time(lambda: drain(rowwise_loader))
    gb = sum(a.nbytes for a in cols.values()) / 1e9
    print(json.dumps({
        "experiment": "batch_gather",
        "native_lib": native_available(),
        "cpus": os.cpu_count(),
        "rows": n_rows, "seq": seq, "batch": batch, "dataset_gb": round(gb, 3),
        "native_s": round(t_native, 3), "rowwise_python_s": round(t_rowwise, 3),
        "speedup": round(t_rowwise / t_native, 2),
        "native_gbps": round(gb / t_native, 2),
    }))


def bench_disk_read(n_layers=8, tensors_per_layer=8, mb_per_tensor=8):
    shape = (mb_per_tensor * 1024 * 1024 // 4,)
    rng = np.random.default_rng(1)
    d = tempfile.mkdtemp(prefix="native_ab_")
    d_ref = tempfile.mkdtemp(prefix="native_ab_npy_")
    try:
        from accelerate_tpu.utils.offload import offload_weight, save_offload_index, OffloadedWeightsLoader

        store = NativeOffloadStore(d, num_threads=8)
        index = {}
        for l in range(n_layers):
            for t in range(tensors_per_layer):
                name = f"layer_{l}/t{t}"
                arr = rng.normal(size=shape).astype(np.float32)
                store.save({name: arr})
                index = offload_weight(arr, name, d_ref, index)  # reference layout
        save_offload_index(index, d_ref)
        ref_loader = OffloadedWeightsLoader(save_folder=d_ref)

        groups = [[f"layer_{l}/t{t}" for t in range(tensors_per_layer)] for l in range(n_layers)]

        def read_blob():
            # the streamed executor's pattern: one readahead ticket per layer,
            # then materialize it (what _fetch_block_pytree does). store.read
            # returns an already-materialized ndarray.
            for group in groups:
                store.prefetch_many(group)
                for n in group:
                    store.read(n)

        def read_npy():
            # the reference pattern: open + mmap each tensor file, then copy out
            # of the mapping (np.array, not np.asarray — asarray on a memmap is
            # a no-read view; device_put is what faults it in the real path)
            for group in groups:
                for n in group:
                    np.array(ref_loader[n])

        t_native = _time(read_blob, repeats=2)
        t_ref = _time(read_npy, repeats=2)
        gb = n_layers * tensors_per_layer * mb_per_tensor / 1024
        print(json.dumps({
            "experiment": "disk_tier_read",
            "native_lib": native_available(),
            "cpus": os.cpu_count(),
            "blob_gb": round(gb, 3),
            "native_blob_s": round(t_native, 3),
            "per_tensor_npy_s": round(t_ref, 3),
            "speedup": round(t_ref / t_native, 2),
            "native_gbps": round(gb / t_native, 2),
        }))
    finally:
        shutil.rmtree(d, ignore_errors=True)
        shutil.rmtree(d_ref, ignore_errors=True)


if __name__ == "__main__":
    bench_gather()
    bench_disk_read()

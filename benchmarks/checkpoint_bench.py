"""Checkpoint benchmark: synchronous vs asynchronous (snapshot-then-commit)
save_state, measured through the goodput ledger.

Workload: a tiny regression train loop whose model carries `--ballast-mb` of
incompressible parameters, so each checkpoint pays a REAL serialize+fsync cost.
Both passes run the same steps and save every step through the same
`CheckpointManager` pipeline; the only difference is the `async_save` knob:

  - **sync**: the step blocks for the full serialize+fsync+publish — every
    second lands in the goodput ledger's ``checkpoint`` cause
    (``lost_checkpoint_s``).
  - **async**: the step blocks only for the device->host snapshot (plus a
    barrier when the previous commit is still in flight); the commit pipeline
    runs on the background committer and reports through
    ``checkpoint_async_commit_seconds`` — measured separately, NOT lost time.

Emits exactly ONE JSON line on stdout (the bench-driver contract): headline is
per-save BLOCKING seconds under async, `vs_baseline` is the sync/async blocking
ratio (how many times less train time each save steals), and `extra` carries
both passes' ledgers — blocking per save, async commit seconds, goodput.

CPU smoke by default; `python bench.py --mode checkpoint` routes here.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time


def log(msg):
    print(f"[checkpoint-bench] {msg}", file=sys.stderr, flush=True)


def build_workload(base_dir, ballast_mb, async_save, keep_last_n=3):
    import numpy as np
    import optax

    import jax.numpy as jnp
    from accelerate_tpu import Accelerator, SimpleDataLoader
    from accelerate_tpu.data_loader import BatchSampler
    from accelerate_tpu.modeling import Model
    from accelerate_tpu.test_utils.training import RegressionDataset
    from accelerate_tpu.utils import ProjectConfiguration

    accelerator = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(base_dir), automatic_checkpoint_naming=True, total_limit=keep_last_n
        ),
        async_save=async_save,
    )
    # Ballast: incompressible float32 params so the npz serialize pays real
    # compression + fsync cost proportional to --ballast-mb.
    n = max(1, int(ballast_mb * (1 << 20)) // 4)
    rng = np.random.default_rng(0)
    params = {
        "w": np.zeros((1, 1), np.float32),
        "b": np.zeros((1,), np.float32),
        "ballast": rng.standard_normal((n,)).astype(np.float32),
    }

    def apply_fn(p, x):
        return x[:, None] * p["w"] + p["b"]

    def loss_fn(p, batch):
        pred = apply_fn(p, batch["x"][:, 0])
        # 0-weight ballast term keeps its gradient defined (and zero).
        return jnp.mean((pred[:, 0] - batch["y"]) ** 2) + 0.0 * p["ballast"][0]

    model = Model.from_fn(apply_fn, params, loss_fn=loss_fn)
    data = [RegressionDataset(length=16, seed=0)[i] for i in range(16)]
    dl = SimpleDataLoader(data, BatchSampler(range(16), 8))
    model, opt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
    return accelerator, model, opt, pdl


def run_pass(base_dir, steps, ballast_mb, async_save, step_s=0.0, save_every=1):
    """One measured pass: N steps, one save_state per step. Returns the ledger
    the comparison is made of."""
    accelerator, model, opt, pdl = build_workload(base_dir, ballast_mb, async_save)
    stream = iter(lambda: None, 1)  # placeholder; rebuilt below

    def batches():
        while True:
            for b in pdl:
                yield b

    stream = batches()
    # Warm the train step (compiles) before the timed region.
    batch = next(stream)
    accelerator.backward(model.loss_fn, batch)
    opt.step()
    opt.zero_grad()
    accelerator.timeline.reset()

    save_block_s = []
    t0 = time.perf_counter()
    for _step in range(steps):
        batch = next(stream)
        accelerator.backward(model.loss_fn, batch)
        opt.step()
        opt.zero_grad()
        if step_s:
            # Simulated device-compute per step: the window a background commit
            # overlaps with. The regression model's real step is microseconds;
            # without this the A/B degenerates to back-to-back saves where the
            # next save's barrier absorbs the whole commit — the worst case,
            # not the training case.
            time.sleep(step_s)
        if (_step + 1) % save_every:
            continue
        s0 = time.perf_counter()
        accelerator.save_state()
        save_block_s.append(time.perf_counter() - s0)
    wall_to_last_save = time.perf_counter() - t0
    d0 = time.perf_counter()
    accelerator.drain_checkpoints()
    drain_s = time.perf_counter() - d0
    stream.close()
    goodput = accelerator.timeline.goodput()
    commit_hist = accelerator._m_ckpt_commit_seconds
    return {
        "steps": steps,
        "saves": len(save_block_s),
        "save_blocking_s_mean": sum(save_block_s) / len(save_block_s),
        "save_blocking_s_max": max(save_block_s),
        "lost_checkpoint_s": goodput["lost_s"].get("checkpoint", 0.0),
        "lost_checkpoint_s_per_save": goodput["lost_s"].get("checkpoint", 0.0) / len(save_block_s),
        "checkpoint_async_commit_s": commit_hist.sum,
        "async_commits": commit_hist.count,
        "final_drain_s": drain_s,
        "wall_to_last_save_s": wall_to_last_save,
        "goodput": goodput,
    }


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--steps", type=int, default=6, help="train steps")
    parser.add_argument("--save-every", type=int, default=2, help="save_state every N steps")
    parser.add_argument("--step-ms", type=float, default=400.0,
                        help="simulated device compute per step (the commit-overlap window); "
                        "0 measures the degenerate back-to-back-saves worst case")
    parser.add_argument("--ballast-mb", type=float, default=8.0,
                        help="incompressible parameter ballast per checkpoint (MiB)")
    parser.add_argument("--base-dir", default=None,
                        help="checkpoint root (default: a temp dir, cleaned up)")
    args = parser.parse_args(argv)
    if args.steps < max(args.save_every, 1):
        parser.error(
            f"--steps {args.steps} < --save-every {args.save_every}: the run would never save"
        )

    scratch = args.base_dir or tempfile.mkdtemp(prefix="accelerate_tpu_ckpt_bench_")
    try:
        results = {}
        for mode in ("sync", "async"):
            base = os.path.join(scratch, mode)
            log(f"{mode} pass: {args.steps} steps ({args.step_ms:g} ms each) x "
                f"{args.ballast_mb} MiB ballast, save every {args.save_every}...")
            results[mode] = run_pass(base, args.steps, args.ballast_mb, mode == "async",
                                     step_s=args.step_ms / 1000.0, save_every=max(args.save_every, 1))
            log(
                f"{mode}: blocking/save {results[mode]['save_blocking_s_mean'] * 1000:.1f} ms, "
                f"lost_checkpoint_s {results[mode]['lost_checkpoint_s']:.3f}, "
                f"async commit {results[mode]['checkpoint_async_commit_s']:.3f}s"
            )
    finally:
        if args.base_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)

    sync_block = results["sync"]["lost_checkpoint_s_per_save"]
    async_block = results["async"]["lost_checkpoint_s_per_save"]
    import jax

    device = jax.devices()[0].platform
    prefix = "cpu-smoke " if device == "cpu" else ""
    row = {
        "metric": f"{prefix}blocking checkpoint seconds per save, async (vs sync baseline, "
        f"{args.ballast_mb:g} MiB state)",
        "value": round(async_block, 6),
        "unit": "s/save blocking",
        # Ratio > 1: how many times LESS step time each async save steals.
        "vs_baseline": round(sync_block / max(async_block, 1e-9), 3),
        "extra": {
            "device_kind": device,
            "ballast_mb": args.ballast_mb,
            "step_ms": args.step_ms,
            "save_every": args.save_every,
            "sync": {k: (round(v, 6) if isinstance(v, float) else v)
                     for k, v in results["sync"].items() if k != "goodput"},
            "async": {k: (round(v, 6) if isinstance(v, float) else v)
                      for k, v in results["async"].items() if k != "goodput"},
            "goodput_sync": results["sync"]["goodput"],
            "goodput_async": results["async"]["goodput"],
        },
    }
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())

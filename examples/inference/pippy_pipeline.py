"""inference/pippy_pipeline (parity: reference examples/inference/pippy/llama.py —
PiPPy stage-parallel inference): layer-stage pipeline inference via `prepare_pippy`
(inference.py), the native replacement for torch.fx tracing + c10d send/recv. The
model's layers are split over the "stage" mesh axis and microbatches stream through
with ppermute."""

import argparse
import time

import numpy as np

from accelerate_tpu import PartialState
from accelerate_tpu.inference import prepare_pippy
from accelerate_tpu.models.llama import LlamaConfig, LlamaLayeredApply, create_llama_model
from accelerate_tpu.parallel.mesh import build_mesh
from accelerate_tpu.utils import ParallelismConfig

SEQ_LEN = 64


def main(args):
    state = PartialState()
    mesh = build_mesh(ParallelismConfig(stage=args.pp_degree, data=-1))
    cfg = LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_hidden_layers=args.pp_degree,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=SEQ_LEN,
        rope_theta=10000.0,
    )
    model = create_llama_model(cfg, seq_len=SEQ_LEN)
    infer = prepare_pippy(
        model, layered=LlamaLayeredApply(cfg), mesh=mesh, num_microbatches=args.num_microbatches
    )

    rng = np.random.default_rng(0)
    batch = rng.integers(2, cfg.vocab_size, size=(args.batch_size, SEQ_LEN)).astype(np.int32)

    logits = infer(batch)  # compile
    t0 = time.perf_counter()
    logits = np.asarray(infer(batch))
    elapsed = time.perf_counter() - t0
    state.print(
        f"pipeline inference: {args.pp_degree} stages, {args.num_microbatches} microbatches, "
        f"batch {args.batch_size} -> logits {logits.shape} in {elapsed * 1000:.1f}ms"
    )
    assert logits.shape == (args.batch_size, SEQ_LEN, cfg.vocab_size)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--pp_degree", type=int, default=4)
    parser.add_argument("--num_microbatches", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=8)
    main(parser.parse_args())

"""inference/quantized_inference (parity: the reference's bitsandbytes int8/4-bit
serving flow — utils/bnb.py `load_and_quantize_model` + generate): quantize a model's
weights to int8 / int4 / nf4, report the footprint saving, and generate through the
same fused KV-cache decode loop as the dense path. The Generator dequantizes inside
its compiled programs, so HBM holds the packed buffers and XLA fuses scale*q into
each consuming matmul."""

import argparse

import numpy as np

import jax.numpy as jnp

from accelerate_tpu.generation import GenerationConfig, Generator
from accelerate_tpu.models.llama import create_llama_model, llama_tiny
from accelerate_tpu.utils.quantization import (
    QuantizationConfig,
    load_and_quantize_model,
    quantized_nbytes,
)


def main(args):
    cfg = llama_tiny()
    model = create_llama_model(cfg, seq_len=args.prompt_len + args.max_new_tokens)
    import jax

    dense_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(model.params)
    )

    qconfig = (
        QuantizationConfig(load_in_8bit=True, compute_dtype=jnp.float32)
        if args.bits == 8
        else QuantizationConfig(load_in_4bit=True, quant_type=args.quant_type, compute_dtype=jnp.float32)
    )
    qmodel = load_and_quantize_model(model, qconfig)
    q_bytes = quantized_nbytes(qmodel.params)
    print(f"weights: {dense_bytes / 1e6:.1f} MB dense -> {q_bytes / 1e6:.1f} MB quantized ({args.bits}-bit)")

    rng = np.random.default_rng(args.seed)
    prompts = rng.integers(1, cfg.vocab_size, (args.num_prompts, args.prompt_len)).astype(np.int32)
    gen = Generator(
        qmodel, max_new_tokens=args.max_new_tokens, max_length=args.prompt_len + args.max_new_tokens
    )
    out = gen(prompts, GenerationConfig(max_new_tokens=args.max_new_tokens))
    print(f"generated {out.shape[0]} completions of {out.shape[1] - args.prompt_len} tokens at the quantized footprint")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--bits", type=int, default=8, choices=[4, 8])
    parser.add_argument("--quant_type", default="nf4", choices=["int4", "nf4"])
    parser.add_argument("--num_prompts", type=int, default=4)
    parser.add_argument("--prompt_len", type=int, default=16)
    parser.add_argument("--max_new_tokens", type=int, default=8)
    parser.add_argument("--seed", type=int, default=0)
    main(parser.parse_args())

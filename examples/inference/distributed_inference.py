"""inference/distributed_inference (parity: reference
examples/inference/distributed/phi2.py — `split_between_processes` batch inference):
each process generates for its slice of the prompt list, then the results are
re-joined with `gather_object`. Runs the KV-cached Generator on a llama-tiny model
(zero-egress stand-in for a Hub checkpoint; point --checkpoint at a local HF llama
directory to use real weights via hf_loading)."""

import argparse

import numpy as np

from accelerate_tpu import PartialState
from accelerate_tpu.generation import GenerationConfig, Generator
from accelerate_tpu.models.llama import create_llama_model, llama_tiny
from accelerate_tpu.utils.operations import gather_object


def main(args):
    state = PartialState()
    if args.checkpoint:
        import json

        from accelerate_tpu.models.llama import LlamaConfig
        from accelerate_tpu.utils.hf_loading import load_hf_checkpoint_in_model

        with open(f"{args.checkpoint}/config.json") as f:
            hf_cfg = json.load(f)
        cfg = LlamaConfig(
            **{k: hf_cfg[k] for k in (
                "vocab_size", "hidden_size", "intermediate_size", "num_hidden_layers",
                "num_attention_heads", "num_key_value_heads", "max_position_embeddings",
                "rope_theta",
            ) if k in hf_cfg}
        )
        model = create_llama_model(cfg, seq_len=args.prompt_len + args.max_new_tokens)
        load_hf_checkpoint_in_model(model, args.checkpoint, "llama", cfg)
    else:
        cfg = llama_tiny()
        model = create_llama_model(cfg, seq_len=args.prompt_len + args.max_new_tokens)
    rng = np.random.default_rng(0)
    # Stand-in prompts: token arrays (a tokenizer would produce these).
    prompts = [
        rng.integers(2, cfg.vocab_size, size=(args.prompt_len,)).astype(np.int32)
        for _ in range(args.num_prompts)
    ]

    gen = Generator(model, max_new_tokens=args.max_new_tokens, max_length=args.prompt_len + args.max_new_tokens)
    with state.split_between_processes(prompts) as my_prompts:
        completions = []
        for prompt in my_prompts:
            out = gen(prompt[None, :], GenerationConfig(max_new_tokens=args.max_new_tokens))
            completions.append(np.asarray(out)[0, -args.max_new_tokens:].tolist())
    all_completions = gather_object(completions)
    state.print(
        f"{len(prompts)} prompts -> {len(all_completions)} completions across "
        f"{state.num_processes} process(es); first: {all_completions[0][:8]}..."
    )
    assert len(all_completions) == len(prompts)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--checkpoint", default=None, help="local HF llama checkpoint dir")
    parser.add_argument("--num_prompts", type=int, default=8)
    parser.add_argument("--prompt_len", type=int, default=32)
    parser.add_argument("--max_new_tokens", type=int, default=16)
    main(parser.parse_args())

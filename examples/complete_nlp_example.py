"""The 'complete' NLP example (parity: reference examples/complete_nlp_example.py —
every production knob of the canonical nlp_example in one script): CLI-selected
checkpointing granularity (`--checkpointing_steps N|epoch`), mid-epoch resume via
`--resume_from_checkpoint`, experiment tracking behind `--with_tracking`, an LR
schedule stepped with the optimizer, and gathered eval metrics.

    python examples/complete_nlp_example.py --checkpointing_steps epoch
    python examples/complete_nlp_example.py --checkpointing_steps 50 \
        --resume_from_checkpoint latest --with_tracking
"""

import argparse

import numpy as np
import optax

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import ProjectConfiguration, set_seed
from nlp_example import MAX_LEN, get_dataset


class StepCounter:
    """BATCH counter (one increment per dataloader batch, inside accumulate())
    checkpointed alongside model/optimizer state via
    `register_for_checkpointing`, so resume lands on the exact batch regardless
    of checkpoint granularity (`save_iteration` only counts save_state calls).

    It deliberately does NOT count optimizer steps: the resume arithmetic
    (`overall_step // len(train_dl)` epochs + `overall_step % len(train_dl)`
    batches to skip) only works at batch granularity — under gradient
    accumulation an optimizer-step counter would land resume mid-accumulation
    span on the wrong batch."""

    def __init__(self):
        self.overall_step = 0

    def state_dict(self):
        return {"overall_step": self.overall_step}

    def load_state_dict(self, state):
        self.overall_step = int(state["overall_step"])


def training_function(args):
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        log_with="json" if args.with_tracking else None,
        project_dir=args.output_dir,
        project_config=ProjectConfiguration(automatic_checkpoint_naming=True, total_limit=3),
    )
    set_seed(args.seed)

    config = bert_tiny()
    model = create_bert_model(config, seq_len=MAX_LEN)
    vocab = config.vocab_size - 1

    train_data = get_dataset(vocab, n=args.train_size, seed=0)
    eval_data = get_dataset(vocab, n=args.eval_size, seed=1)
    sampler = SeedableRandomSampler(num_samples=len(train_data), seed=args.seed)
    train_dl = SimpleDataLoader(train_data, BatchSampler(sampler, args.batch_size))
    eval_dl = SimpleDataLoader(eval_data, BatchSampler(range(len(eval_data)), args.batch_size))

    schedule = optax.linear_schedule(args.lr, 0.0, transition_steps=args.epochs * len(train_dl))
    optimizer = optax.inject_hyperparams(optax.adamw)(learning_rate=args.lr)
    model, optimizer, train_dl, eval_dl, scheduler = accelerator.prepare(
        model, optimizer, train_dl, eval_dl, schedule
    )
    if args.with_tracking:
        accelerator.init_trackers("complete_nlp_example", config=vars(args))

    # Checkpoint granularity: every N optimizer steps, or once per epoch.
    checkpointing_steps = args.checkpointing_steps
    if checkpointing_steps is not None and checkpointing_steps != "epoch":
        checkpointing_steps = int(checkpointing_steps)

    counter = StepCounter()
    accelerator.register_for_checkpointing(counter)

    start_epoch = 0
    resume_step = 0
    if args.resume_from_checkpoint:
        # 'latest' -> load_state() with no path: the accelerator resolves the
        # newest checkpoint NUMERICALLY (a lexicographic listdir would order
        # checkpoint_10 before checkpoint_9 once rotation passes ten saves).
        path = None if args.resume_from_checkpoint == "latest" else args.resume_from_checkpoint
        accelerator.load_state(path)
        start_epoch = counter.overall_step // len(train_dl)
        resume_step = counter.overall_step % len(train_dl)
        accelerator.print(
            f"resumed from {path or 'latest checkpoint'}: epoch {start_epoch}, step {resume_step}"
        )

    if start_epoch >= args.epochs:
        accelerator.print(
            f"nothing to train: checkpoint is at epoch {start_epoch} of {args.epochs} — "
            "raise --epochs to continue"
        )
        return None

    accuracy = 0.0
    for epoch in range(start_epoch, args.epochs):
        # Pin the shuffle epoch explicitly: exact regardless of where in the
        # epoch the checkpoint landed (the skip wrapper inherits the pin).
        train_dl.set_epoch(epoch)
        dl = train_dl
        if epoch == start_epoch and resume_step:
            dl = accelerator.skip_first_batches(train_dl, resume_step)
        total_loss = 0.0
        n_batches = 0
        for batch in dl:
            with accelerator.accumulate(model):
                loss = accelerator.backward(model.loss, batch)
                accelerator.clip_grad_norm_(max_norm=1.0)
                optimizer.step()
                scheduler.step()
                optimizer.zero_grad()
            # Device-side accumulation: float(loss) here would block on the
            # device every step (tpu-lint TPU111); read once per epoch below.
            total_loss += loss
            n_batches += 1
            counter.overall_step += 1
            if isinstance(checkpointing_steps, int) and counter.overall_step % checkpointing_steps == 0:
                accelerator.save_state()
        if checkpointing_steps == "epoch":
            accelerator.save_state()

        correct, total = 0, 0
        for batch in eval_dl:
            logits = model(batch["input_ids"], None, batch["token_type_ids"])
            preds = accelerator.gather_for_metrics(np.asarray(logits).argmax(-1))
            labels = accelerator.gather_for_metrics(np.asarray(batch["labels"]))
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(labels))
        accuracy = correct / total
        train_loss = float(total_loss) / max(n_batches, 1)
        accelerator.print(f"epoch {epoch}: loss {train_loss:.4f} accuracy {accuracy:.4f}")
        if args.with_tracking:
            accelerator.log(
                {"train_loss": train_loss, "accuracy": accuracy, "step": counter.overall_step},
                step=epoch,
            )

    if args.with_tracking:
        accelerator.end_training()
    return accuracy


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16", choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=512)
    parser.add_argument("--eval_size", type=int, default=128)
    parser.add_argument("--output_dir", default="/tmp/accelerate_tpu_complete_nlp")
    parser.add_argument(
        "--checkpointing_steps",
        default=None,
        help="checkpoint every N optimizer steps, or 'epoch' for once per epoch",
    )
    parser.add_argument("--resume_from_checkpoint", default=None, help="path or 'latest'")
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--performance_lower_bound", type=float, default=None)
    args = parser.parse_args()
    accuracy = training_function(args)
    if args.performance_lower_bound is not None and accuracy is not None:
        assert accuracy >= args.performance_lower_bound, (
            f"accuracy {accuracy:.4f} below bound {args.performance_lower_bound}"
        )


if __name__ == "__main__":
    main()

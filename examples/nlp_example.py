"""The canonical 'nlp_example' (parity: reference examples/nlp_example.py — BERT on
GLUE/MRPC). Demonstrates the five-line-diff contract on TPU:

    accelerator = Accelerator(mixed_precision="bf16")
    model, optimizer, train_dl, scheduler = accelerator.prepare(...)
    loss = accelerator.backward(model.loss, batch); optimizer.step(); ...

Runs on one chip, an 8-device mesh, or a pod with NO code changes — the mesh comes from
the launch config. Data: GLUE/MRPC via `datasets` when available locally, else a
deterministic synthetic paraphrase-shaped dataset (zero-egress environments).

Launch:
    python examples/nlp_example.py                      # current devices
    accelerate-tpu launch examples/nlp_example.py       # env-var protocol
    accelerate-tpu launch --mesh_fsdp 8 examples/nlp_example.py
"""

import argparse

import numpy as np
import optax

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import set_seed

MAX_LEN = 128


def get_dataset(tokenizer_vocab: int, n: int = 512, seed: int = 0):
    """MRPC-shaped data: pairs of token sequences + binary paraphrase label.

    Synthetic generator: paraphrase pairs share a token multiset (shuffled), negatives
    don't — linearly separable enough for the loss to fall, deterministic, offline."""
    rng = np.random.default_rng(seed)
    data = []
    for i in range(n):
        label = int(rng.integers(0, 2))
        s1 = rng.integers(5, tokenizer_vocab, size=MAX_LEN // 2)
        if label == 1:
            s2 = rng.permutation(s1)
        else:
            s2 = rng.integers(5, tokenizer_vocab, size=MAX_LEN // 2)
        input_ids = np.concatenate([s1, s2]).astype(np.int32)
        token_type_ids = np.concatenate(
            [np.zeros(MAX_LEN // 2, np.int32), np.ones(MAX_LEN // 2, np.int32)]
        )
        data.append({"input_ids": input_ids, "token_type_ids": token_type_ids, "labels": np.int64(label)})
    return data


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision, log_with="json", project_dir=args.output_dir)
    set_seed(args.seed)

    config = bert_tiny() if args.tiny else None
    model = create_bert_model(config, seq_len=MAX_LEN)
    vocab = (config.vocab_size if config else 30522) - 1

    train_data = get_dataset(vocab, n=args.train_size, seed=0)
    eval_data = get_dataset(vocab, n=args.eval_size, seed=1)

    sampler = SeedableRandomSampler(num_samples=len(train_data), seed=args.seed)
    train_dl = SimpleDataLoader(train_data, BatchSampler(sampler, args.batch_size))
    eval_dl = SimpleDataLoader(eval_data, BatchSampler(range(len(eval_data)), args.batch_size))

    schedule = optax.linear_schedule(args.lr, 0.0, transition_steps=args.epochs * len(train_dl))
    optimizer = optax.inject_hyperparams(optax.adamw)(learning_rate=args.lr)

    model, optimizer, train_dl, eval_dl, scheduler = accelerator.prepare(
        model, optimizer, train_dl, eval_dl, schedule
    )
    accelerator.init_trackers("nlp_example", config=vars(args))

    for epoch in range(args.epochs):
        for step, batch in enumerate(train_dl):
            with accelerator.accumulate(model):
                loss = accelerator.backward(model.loss, batch)
                accelerator.clip_grad_norm_(max_norm=1.0)
                optimizer.step()
                scheduler.step()
                optimizer.zero_grad()

        correct, total = 0, 0
        for batch in eval_dl:
            logits = model(batch["input_ids"], None, batch["token_type_ids"])
            preds = np.asarray(logits).argmax(-1)
            gathered_preds = accelerator.gather_for_metrics(preds)
            gathered_labels = accelerator.gather_for_metrics(np.asarray(batch["labels"]))
            correct += int((np.asarray(gathered_preds) == np.asarray(gathered_labels)).sum())
            total += len(np.asarray(gathered_labels))
        accuracy = correct / total
        accelerator.print(f"epoch {epoch}: loss {float(loss):.4f} accuracy {accuracy:.4f}")
        accelerator.log({"loss": float(loss), "accuracy": accuracy}, step=epoch)

    accelerator.end_training()
    return accuracy


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16", choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=512)
    parser.add_argument("--eval_size", type=int, default=128)
    parser.add_argument("--output_dir", default="/tmp/accelerate_tpu_nlp_example")
    parser.add_argument("--tiny", action="store_true", default=True, help="Use the test-size BERT config")
    parser.add_argument("--full", dest="tiny", action="store_false", help="Use BERT-base")
    parser.add_argument("--performance_lower_bound", type=float, default=None)
    args = parser.parse_args()
    accuracy = training_function(args)
    if args.performance_lower_bound is not None:
        assert accuracy >= args.performance_lower_bound, (
            f"accuracy {accuracy:.4f} below bound {args.performance_lower_bound}"
        )


if __name__ == "__main__":
    main()

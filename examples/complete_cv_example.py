"""The 'complete' CV example (parity: reference examples/complete_cv_example.py —
the canonical cv_example with every production knob): CLI-selected checkpointing
granularity (`--checkpointing_steps N|epoch`), resume via `--resume_from_checkpoint`,
tracking behind `--with_tracking`, and gathered eval accuracy — all over the native
columnar loader feeding the device plane.

    python examples/complete_cv_example.py --checkpointing_steps epoch
    python examples/complete_cv_example.py --resume_from_checkpoint latest
"""

import argparse

import numpy as np
import optax

from accelerate_tpu import Accelerator, Model
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.native.loader import NativeArrayLoader
from accelerate_tpu.utils import ProjectConfiguration, set_seed
from complete_nlp_example import StepCounter
from cv_example import IMAGE_SIZE, SmallConvNet, classification_loss, get_dataset


def training_function(args):
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        log_with="json" if args.with_tracking else None,
        project_dir=args.output_dir,
        project_config=ProjectConfiguration(automatic_checkpoint_naming=True, total_limit=3),
    )
    set_seed(args.seed)
    import jax
    import jax.numpy as jnp

    module = SmallConvNet()
    params = module.init(jax.random.key(args.seed), jnp.zeros((1, IMAGE_SIZE, IMAGE_SIZE, 3)))
    model = Model.from_flax(module, params, loss_fn=classification_loss)

    train_ds = get_dataset(args.train_size, seed=0)
    eval_ds = get_dataset(args.eval_size, seed=1)
    # Epoch-aware sampler (NOT a fixed one-time permutation): set_epoch(epoch)
    # below reseeds it, so every epoch trains in a fresh order and resume
    # replays the exact order of the interrupted epoch.
    sampler = SeedableRandomSampler(num_samples=len(train_ds), seed=args.seed)
    train_dl = NativeArrayLoader(train_ds, BatchSampler(sampler, args.batch_size))
    eval_dl = NativeArrayLoader(eval_ds, BatchSampler(range(len(eval_ds)), args.batch_size))

    optimizer = optax.adam(args.lr)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(model, optimizer, train_dl, eval_dl)
    if args.with_tracking:
        accelerator.init_trackers("complete_cv_example", config=vars(args))

    checkpointing_steps = args.checkpointing_steps
    if checkpointing_steps is not None and checkpointing_steps != "epoch":
        checkpointing_steps = int(checkpointing_steps)

    counter = StepCounter()
    accelerator.register_for_checkpointing(counter)

    start_epoch = 0
    resume_step = 0
    if args.resume_from_checkpoint:
        # 'latest' -> load_state() with no path (numeric newest-checkpoint
        # resolution; lexicographic listdir breaks past checkpoint_9).
        path = None if args.resume_from_checkpoint == "latest" else args.resume_from_checkpoint
        accelerator.load_state(path)
        start_epoch = counter.overall_step // len(train_dl)
        resume_step = counter.overall_step % len(train_dl)
        accelerator.print(
            f"resumed from {path or 'latest checkpoint'}: epoch {start_epoch}, step {resume_step}"
        )

    if start_epoch >= args.epochs:
        accelerator.print(
            f"nothing to train: checkpoint is at epoch {start_epoch} of {args.epochs} — "
            "raise --epochs to continue"
        )
        return None

    accuracy = 0.0
    for epoch in range(start_epoch, args.epochs):
        # Pin the shuffle epoch explicitly: exact regardless of where in the
        # epoch the checkpoint landed (the skip wrapper inherits the pin).
        train_dl.set_epoch(epoch)
        dl = train_dl
        if epoch == start_epoch and resume_step:
            dl = accelerator.skip_first_batches(train_dl, resume_step)
        total_loss = 0.0
        n_batches = 0
        for batch in dl:
            with accelerator.accumulate(model):
                loss = accelerator.backward(model.loss, batch)
                optimizer.step()
                optimizer.zero_grad()
            # Device-side accumulation: float(loss) here would block on the
            # device every step (tpu-lint TPU111); read once per epoch below.
            total_loss += loss
            n_batches += 1
            counter.overall_step += 1
            if isinstance(checkpointing_steps, int) and counter.overall_step % checkpointing_steps == 0:
                accelerator.save_state()
        if checkpointing_steps == "epoch":
            accelerator.save_state()

        correct, total = 0, 0
        for batch in eval_dl:
            logits = model(batch["pixel_values"])
            preds = accelerator.gather_for_metrics(np.asarray(logits).argmax(-1))
            labels = accelerator.gather_for_metrics(np.asarray(batch["labels"]))
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(labels))
        accuracy = correct / total
        train_loss = float(total_loss) / max(n_batches, 1)
        accelerator.print(f"epoch {epoch}: loss {train_loss:.4f} accuracy {accuracy:.4f}")
        if args.with_tracking:
            accelerator.log(
                {"train_loss": train_loss, "accuracy": accuracy, "step": counter.overall_step},
                step=epoch,
            )

    if args.with_tracking:
        accelerator.end_training()
    return accuracy


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16", choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=512)
    parser.add_argument("--eval_size", type=int, default=128)
    parser.add_argument("--output_dir", default="/tmp/accelerate_tpu_complete_cv")
    parser.add_argument(
        "--checkpointing_steps",
        default=None,
        help="checkpoint every N optimizer steps, or 'epoch' for once per epoch",
    )
    parser.add_argument("--resume_from_checkpoint", default=None, help="path or 'latest'")
    parser.add_argument("--with_tracking", action="store_true")
    args = parser.parse_args()
    acc = training_function(args)
    if acc is not None:  # None = resume had nothing left to train
        assert acc > 0.5, f"complete_cv_example failed to learn (accuracy {acc})"


if __name__ == "__main__":
    main()

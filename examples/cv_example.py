"""The canonical 'cv_example' (parity: reference examples/cv_example.py — image
classification). A small convnet on synthetic class-conditional images (zero-egress
stand-in for the pets dataset); the same five-line-diff Accelerator contract as
nlp_example, with the native columnar loader feeding the device plane.

    python examples/cv_example.py
"""

import argparse

import numpy as np
import optax

import flax.linen as nn
import jax.numpy as jnp

from accelerate_tpu import Accelerator, Model
from accelerate_tpu.data_loader import BatchSampler
from accelerate_tpu.native import ArrayDataset
from accelerate_tpu.native.loader import NativeArrayLoader
from accelerate_tpu.utils import set_seed

IMAGE_SIZE = 32
NUM_CLASSES = 4


class SmallConvNet(nn.Module):
    num_classes: int = NUM_CLASSES

    @nn.compact
    def __call__(self, x):  # [B, H, W, C]
        for features in (16, 32, 64):
            x = nn.Conv(features, (3, 3), strides=(2, 2))(x)
            x = nn.relu(x)
        x = x.mean(axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes)(x)


def classification_loss(params, batch, apply_fn):
    logits = apply_fn(params, batch["pixel_values"])
    logp = nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, batch["labels"][:, None].astype(jnp.int32), axis=-1)[:, 0]
    return nll.mean()


def get_dataset(n=512, seed=0):
    """Class-conditional blobs: class k brightens quadrant k — separable, offline."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, NUM_CLASSES, size=n)
    images = rng.normal(size=(n, IMAGE_SIZE, IMAGE_SIZE, 3)).astype(np.float32) * 0.3
    half = IMAGE_SIZE // 2
    for i, k in enumerate(labels):
        r, c = divmod(int(k), 2)
        images[i, r * half : (r + 1) * half, c * half : (c + 1) * half] += 1.5
    return ArrayDataset({"pixel_values": images, "labels": labels.astype(np.int64)})


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    set_seed(args.seed)
    import jax

    module = SmallConvNet()
    params = module.init(jax.random.key(args.seed), jnp.zeros((1, IMAGE_SIZE, IMAGE_SIZE, 3)))
    model = Model.from_flax(module, params, loss_fn=classification_loss)

    train_ds = get_dataset(args.train_size, seed=0)
    eval_ds = get_dataset(args.eval_size, seed=1)
    perm = np.random.default_rng(args.seed).permutation(len(train_ds))
    train_dl = NativeArrayLoader(train_ds, BatchSampler(perm.tolist(), args.batch_size))
    eval_dl = NativeArrayLoader(eval_ds, BatchSampler(range(len(eval_ds)), args.batch_size))

    optimizer = optax.adam(args.lr)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(model, optimizer, train_dl, eval_dl)

    for epoch in range(args.epochs):
        for batch in train_dl:
            with accelerator.accumulate(model):
                loss = accelerator.backward(model.loss, batch)
                optimizer.step()
                optimizer.zero_grad()
        correct, total = 0, 0
        for batch in eval_dl:
            logits = model(batch["pixel_values"])
            preds = accelerator.gather_for_metrics(np.asarray(logits).argmax(-1))
            labels = accelerator.gather_for_metrics(np.asarray(batch["labels"]))
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(labels))
        accelerator.print(f"epoch {epoch}: loss {float(loss):.4f} accuracy {correct / total:.4f}")
    return correct / total


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16", choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=512)
    parser.add_argument("--eval_size", type=int, default=128)
    args = parser.parse_args()
    acc = training_function(args)
    assert acc > 0.5, f"cv_example failed to learn (accuracy {acc})"

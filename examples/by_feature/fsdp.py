"""by_feature/fsdp (reference analogue: FSDP examples + fsdp_with_peak_mem_tracking):
full parameter/optimizer-state sharding over the "fsdp" mesh axis — the ZeRO-3
equivalent is a sharding spec, not a wrapper class. Peak HBM is logged per epoch.

    python examples/by_feature/fsdp.py --fsdp_size 8
"""

import argparse
import os
import sys

import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import (
    FullyShardedDataParallelPlugin,
    ParallelismConfig,
    set_seed,
)


def peak_hbm_bytes():
    import jax

    stats = jax.local_devices()[0].memory_stats() or {}
    return stats.get("peak_bytes_in_use", 0)


def training_function(args):
    accelerator = Accelerator(
        mixed_precision="bf16",
        parallelism_config=ParallelismConfig(data=-1, fsdp=args.fsdp_size),
        fsdp_plugin=FullyShardedDataParallelPlugin(sharding_strategy="FULL_SHARD"),
    )
    set_seed(args.seed)
    config = bert_tiny()
    model = create_bert_model(config, seq_len=MAX_LEN)
    data = get_dataset(config.vocab_size - 1, n=args.train_size)
    sampler = SeedableRandomSampler(num_samples=len(data), seed=args.seed)
    train_dl = SimpleDataLoader(data, BatchSampler(sampler, args.batch_size))
    optimizer = optax.adamw(args.lr)
    model, optimizer, train_dl = accelerator.prepare(model, optimizer, train_dl)

    for epoch in range(args.epochs):
        for batch in train_dl:
            with accelerator.accumulate(model):
                loss = accelerator.backward(model.loss, batch)
                optimizer.step()
                optimizer.zero_grad()
        accelerator.print(
            f"epoch {epoch}: loss {float(loss):.4f} peak HBM {peak_hbm_bytes() / 2**20:.1f} MiB"
        )


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--fsdp_size", type=int, default=8)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=256)
    training_function(parser.parse_args())

"""by_feature/megatron_lm_gpt_pretraining (parity: reference
examples/by_feature/megatron_lm_gpt_pretraining.py, which drives Megatron-LM's
TP/PP/DP engine): causal-LM pretraining on the NATIVE pipeline instead — the stage
mesh axis + ppermute microbatch schedule (parallel/pipeline.py) replaces Megatron's
1F1B, and tensor/data parallelism come from the same mesh config every other example
uses. No external engine."""

import argparse
import os
import sys

import numpy as np
import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # noqa: E402 (example layout)

from accelerate_tpu import Accelerator
from accelerate_tpu.models.llama import LlamaConfig, LlamaLayeredApply, create_llama_model
from accelerate_tpu.parallel.pipeline import prepare_pipeline
from accelerate_tpu.utils import ParallelismConfig, set_seed

SEQ_LEN = 64


def get_corpus(vocab: int, n: int, seed: int = 0):
    """Synthetic pretraining corpus: token sequences with local structure (each token
    correlates with its predecessor) so next-token loss genuinely falls."""
    rng = np.random.default_rng(seed)
    data = []
    for _ in range(n):
        ids = np.empty(SEQ_LEN, np.int32)
        ids[0] = rng.integers(2, vocab)
        for t in range(1, SEQ_LEN):
            ids[t] = (ids[t - 1] * 31 + 7) % (vocab - 2) + 2 if rng.random() < 0.8 else rng.integers(2, vocab)
        data.append(ids)
    return np.stack(data)


def training_function(args):
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(stage=args.pp_degree, data=-1),
    )
    set_seed(args.seed)
    cfg = LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_hidden_layers=args.pp_degree * args.layers_per_stage,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=SEQ_LEN,
        rope_theta=10000.0,
    )
    model = create_llama_model(cfg, seq_len=SEQ_LEN)
    pp = prepare_pipeline(
        model, LlamaLayeredApply(cfg), accelerator.mesh, num_microbatches=args.num_microbatches
    )
    pp, optimizer = accelerator.prepare(pp, optax.adamw(args.lr))
    accelerator.print(
        f"pipeline: {args.pp_degree} stages x {args.layers_per_stage} layers, "
        f"{args.num_microbatches} microbatches, mesh {dict(accelerator.mesh.shape)}"
    )

    corpus = get_corpus(cfg.vocab_size, n=args.train_size, seed=0)
    losses = []
    for step in range(args.steps):
        idx = np.random.default_rng(step).integers(0, len(corpus), size=args.batch_size)
        batch = {"input_ids": corpus[idx]}
        loss = accelerator.backward(pp.loss, batch, model=pp)
        optimizer.step()
        optimizer.zero_grad()
        # Keep losses on device in the hot loop (a float() per step would sync
        # the host every step — tpu-lint TPU111); read only at print points.
        losses.append(loss)
        if step % 5 == 0:
            accelerator.print(f"step {step}: lm loss {float(losses[-1]):.4f}")
    losses = [float(l) for l in losses]
    accelerator.print(f"pretraining loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    assert losses[-1] < losses[0], "next-token loss did not fall"
    return losses[-1]


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--pp_degree", type=int, default=4)
    parser.add_argument("--layers_per_stage", type=int, default=1)
    parser.add_argument("--num_microbatches", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=8, help="global batch size")
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=256)
    training_function(parser.parse_args())

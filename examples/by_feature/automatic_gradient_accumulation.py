"""by_feature/automatic_gradient_accumulation (parity: reference
examples/by_feature/automatic_gradient_accumulation.py): combine
`find_executable_batch_size` (HBM-OOM retry, reference utils/memory.py:87-158) with
gradient accumulation so the EFFECTIVE batch size stays constant: whenever the
per-step batch halves after an OOM, the accumulation step count doubles."""

import argparse
import os
import sys

import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import set_seed
from accelerate_tpu.utils.memory import find_executable_batch_size


def training_function(args):
    set_seed(args.seed)
    config = bert_tiny()
    data = get_dataset(config.vocab_size - 1, n=args.train_size, seed=0)

    @find_executable_batch_size(starting_batch_size=args.observed_batch_size)
    def inner_training_loop(batch_size):
        # Fresh accelerator per attempt: the accumulation count depends on the
        # batch size this attempt is trying.
        from accelerate_tpu.state import AcceleratorState, GradientState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        accumulation = max(1, args.target_batch_size // batch_size)
        accelerator = Accelerator(
            mixed_precision=args.mixed_precision, gradient_accumulation_steps=accumulation
        )
        accelerator.print(f"trying batch_size={batch_size} x accumulation={accumulation}")
        accelerator.free_memory()
        model = create_bert_model(config, seq_len=MAX_LEN)
        sampler = SeedableRandomSampler(num_samples=len(data), seed=args.seed)
        train_dl = SimpleDataLoader(data, BatchSampler(sampler, batch_size))
        model, optimizer, train_dl = accelerator.prepare(model, optax.adamw(args.lr), train_dl)
        loss = None
        for _ in range(args.epochs):
            for batch in train_dl:
                with accelerator.accumulate(model):
                    loss = accelerator.backward(model.loss, batch)
                    optimizer.step()
                    optimizer.zero_grad()
        accelerator.print(
            f"done: batch_size={batch_size} accumulation={accumulation} "
            f"(effective {batch_size * accumulation}) final loss {float(loss):.4f}"
        )
        return float(loss)

    return inner_training_loop()


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16", choices=["no", "bf16", "fp16"])
    parser.add_argument("--observed_batch_size", type=int, default=32, help="first batch size to try")
    parser.add_argument("--target_batch_size", type=int, default=64, help="effective batch size to preserve")
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=128)
    training_function(parser.parse_args())

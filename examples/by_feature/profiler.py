"""by_feature/profiler (reference analogue: examples/by_feature/profiler.py):
`accelerator.profile()` wraps training steps in an XLA device trace (xplane dump for
TensorBoard/xprof) and `save_memory_profile` snapshots HBM in pprof format."""

import argparse
import os
import sys

import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import set_seed


def training_function(args):
    accelerator = Accelerator(project_dir=args.output_dir)
    set_seed(args.seed)
    config = bert_tiny()
    model = create_bert_model(config, seq_len=MAX_LEN)
    data = get_dataset(config.vocab_size - 1, n=args.train_size)
    sampler = SeedableRandomSampler(num_samples=len(data), seed=args.seed)
    train_dl = SimpleDataLoader(data, BatchSampler(sampler, args.batch_size))
    optimizer = optax.adamw(args.lr)
    model, optimizer, train_dl = accelerator.prepare(model, optimizer, train_dl)

    # Warm up (compile) outside the trace so the profile shows steady-state steps.
    for batch in train_dl:
        accelerator.backward(model.loss, batch)
        optimizer.step()
        optimizer.zero_grad()
        break

    trace_dir = os.path.join(args.output_dir, "profile")
    with accelerator.profile(log_dir=trace_dir):
        for step, batch in enumerate(train_dl):
            loss = accelerator.backward(model.loss, batch)
            optimizer.step()
            optimizer.zero_grad()
            if step + 1 >= args.profile_steps:
                break
    accelerator.save_memory_profile(os.path.join(args.output_dir, "memory.prof"))
    accelerator.print(f"trace written to {trace_dir} (loss {float(loss):.4f})")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--profile_steps", type=int, default=4)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=256)
    parser.add_argument("--output_dir", default="/tmp/accelerate_tpu_profile_example")
    training_function(parser.parse_args())

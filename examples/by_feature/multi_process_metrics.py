"""by_feature/multi_process_metrics (parity: reference
examples/by_feature/multi_process_metrics.py): correct distributed evaluation. The
point demonstrated: use `gather_for_metrics` — NOT `gather` — for eval, because the
loader pads the final uneven batch to keep shapes static and `gather_for_metrics`
drops exactly those duplicated samples (GradientState.remainder contract, reference
accelerator.py:2331-2396)."""

import argparse
import os
import sys

import numpy as np
import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import set_seed


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    set_seed(args.seed)
    config = bert_tiny()
    model = create_bert_model(config, seq_len=MAX_LEN)
    train_data = get_dataset(config.vocab_size - 1, n=args.train_size, seed=0)
    eval_data = get_dataset(config.vocab_size - 1, n=args.eval_size, seed=1)
    if args.eval_size % args.batch_size == 0:
        raise SystemExit(
            f"--eval_size {args.eval_size} is a multiple of --batch_size {args.batch_size}: "
            "pick an uneven size so the padded-final-batch truncation this example "
            "demonstrates actually happens."
        )
    sampler = SeedableRandomSampler(num_samples=len(train_data), seed=args.seed)
    train_dl = SimpleDataLoader(train_data, BatchSampler(sampler, args.batch_size))
    eval_dl = SimpleDataLoader(
        eval_data, BatchSampler(range(len(eval_data)), args.batch_size, drop_last=False)
    )
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), train_dl, eval_dl
    )

    for epoch in range(args.epochs):
        for batch in train_dl:
            loss = accelerator.backward(model.loss, batch)
            optimizer.step()
            optimizer.zero_grad()

        all_preds, all_labels = [], []
        for batch in eval_dl:
            logits = model(batch["input_ids"], None, batch["token_type_ids"])
            # One call gathers the whole (pred, label) tuple and truncates padding.
            preds, labels = accelerator.gather_for_metrics(
                (np.asarray(logits).argmax(-1), np.asarray(batch["labels"]))
            )
            all_preds.append(np.asarray(preds))
            all_labels.append(np.asarray(labels))
        all_preds = np.concatenate(all_preds)
        all_labels = np.concatenate(all_labels)
        assert all_preds.shape[0] == len(eval_data), (
            f"metric sample count {all_preds.shape[0]} != dataset size {len(eval_data)}"
        )
        accuracy = float((all_preds == all_labels).mean())
        accelerator.print(
            f"epoch {epoch}: loss {float(loss):.4f} accuracy {accuracy:.4f} "
            f"({all_preds.shape[0]} samples, exact count)"
        )
    return accuracy


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16", choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=128)
    parser.add_argument("--eval_size", type=int, default=67, help="keep this NOT a multiple of batch_size")
    training_function(parser.parse_args())

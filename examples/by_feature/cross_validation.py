"""by_feature/cross_validation (parity: reference examples/by_feature/cross_validation.py):
k-fold training over the synthetic MRPC-shaped dataset. Each fold trains a fresh
prepared model; fold accuracies are computed with `gather_for_metrics` and the final
report is their mean — the pattern the reference builds with `datasets.concatenate`
and StratifiedKFold, here with plain index folds (zero-egress)."""

import argparse
import os
import sys

import numpy as np
import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import set_seed


def run_fold(accelerator, args, config, data, fold, k):
    n = len(data)
    fold_size = n // k
    eval_idx = list(range(fold * fold_size, (fold + 1) * fold_size))
    train_idx = [i for i in range(n) if i not in set(eval_idx)]
    train_data = [data[i] for i in train_idx]
    eval_data = [data[i] for i in eval_idx]

    model = create_bert_model(config, seq_len=MAX_LEN)
    sampler = SeedableRandomSampler(num_samples=len(train_data), seed=args.seed + fold)
    train_dl = SimpleDataLoader(train_data, BatchSampler(sampler, args.batch_size))
    eval_dl = SimpleDataLoader(eval_data, BatchSampler(range(len(eval_data)), args.batch_size))
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), train_dl, eval_dl
    )
    for _ in range(args.epochs):
        for batch in train_dl:
            accelerator.backward(model.loss, batch)
            optimizer.step()
            optimizer.zero_grad()
    correct, total = 0, 0
    for batch in eval_dl:
        logits = model(batch["input_ids"], None, batch["token_type_ids"])
        preds, labels = accelerator.gather_for_metrics(
            (np.asarray(logits).argmax(-1), np.asarray(batch["labels"]))
        )
        correct += int((np.asarray(preds) == np.asarray(labels)).sum())
        total += len(np.asarray(labels))
    accelerator.free_memory()
    return correct / total


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    set_seed(args.seed)
    config = bert_tiny()
    data = get_dataset(config.vocab_size - 1, n=args.train_size, seed=0)
    accuracies = []
    for fold in range(args.num_folds):
        acc = run_fold(accelerator, args, config, data, fold, args.num_folds)
        accelerator.print(f"fold {fold}: accuracy {acc:.4f}")
        accuracies.append(acc)
    accelerator.print(f"cross-validation mean accuracy {np.mean(accuracies):.4f} over {args.num_folds} folds")
    return float(np.mean(accuracies))


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16", choices=["no", "bf16", "fp16"])
    parser.add_argument("--num_folds", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=192)
    training_function(parser.parse_args())

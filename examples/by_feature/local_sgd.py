"""by_feature/local_sgd (parity: reference examples/by_feature/local_sgd.py): K
independent steps per data-parallel replica, parameters averaged every
`local_sgd_steps` — one cross-replica all-reduce per K steps instead of every step."""

import argparse
import os
import sys

import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, LocalSGD, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import set_seed


def training_function(args):
    accelerator = Accelerator()
    set_seed(args.seed)
    config = bert_tiny()
    model = create_bert_model(config, seq_len=MAX_LEN)
    data = get_dataset(config.vocab_size - 1, n=args.train_size)
    sampler = SeedableRandomSampler(num_samples=len(data), seed=args.seed)
    train_dl = SimpleDataLoader(data, BatchSampler(sampler, args.batch_size))
    optimizer = optax.adamw(args.lr)
    model, optimizer, train_dl = accelerator.prepare(model, optimizer, train_dl)

    with LocalSGD(
        accelerator=accelerator, model=model, local_sgd_steps=args.local_sgd_steps, enabled=True
    ) as local_sgd:
        for epoch in range(args.epochs):
            for batch in train_dl:
                with accelerator.accumulate(model):
                    loss = accelerator.backward(model.loss, batch)
                    optimizer.step()
                    optimizer.zero_grad()
                    local_sgd.step()
            accelerator.print(f"epoch {epoch}: loss {float(loss):.4f}")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--local_sgd_steps", type=int, default=8)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=256)
    training_function(parser.parse_args())

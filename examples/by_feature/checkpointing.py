"""by_feature/checkpointing (parity: reference examples/by_feature/checkpointing.py):
the nlp_example plus `save_state`/`load_state` every epoch and mid-epoch resume via
`skip_first_batches`.

    python examples/by_feature/checkpointing.py --resume_from_checkpoint latest
"""

import argparse
import os
import sys

import numpy as np
import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from complete_nlp_example import StepCounter  # noqa: E402
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import ProjectConfiguration, set_seed


def training_function(args):
    accelerator = Accelerator(
        project_dir=args.output_dir,
        project_config=ProjectConfiguration(automatic_checkpoint_naming=True, total_limit=3),
    )
    set_seed(args.seed)
    config = bert_tiny()
    model = create_bert_model(config, seq_len=MAX_LEN)
    data = get_dataset(config.vocab_size - 1, n=args.train_size)
    sampler = SeedableRandomSampler(num_samples=len(data), seed=args.seed)
    train_dl = SimpleDataLoader(data, BatchSampler(sampler, args.batch_size))
    optimizer = optax.adamw(args.lr)
    model, optimizer, train_dl = accelerator.prepare(model, optimizer, train_dl)

    # The optimizer-step counter rides the checkpoint (save_iteration only
    # counts save_state CALLS — with per-epoch saves it is the epoch count, not
    # the batch position, so resume arithmetic must come from saved state).
    counter = StepCounter()
    accelerator.register_for_checkpointing(counter)

    start_epoch = 0
    resume_step = 0
    if args.resume_from_checkpoint:
        # 'latest' -> load_state() with no path (numeric newest-checkpoint
        # resolution; lexicographic listdir breaks past checkpoint_9).
        path = None if args.resume_from_checkpoint == "latest" else args.resume_from_checkpoint
        accelerator.load_state(path)
        start_epoch = counter.overall_step // len(train_dl)
        resume_step = counter.overall_step % len(train_dl)
        accelerator.print(
            f"resumed from {path or 'latest checkpoint'}: epoch {start_epoch}, step {resume_step}"
        )

    for epoch in range(start_epoch, args.epochs):
        # Pin the shuffle epoch explicitly: exact regardless of where in the
        # epoch the checkpoint landed (the skip wrapper inherits the pin).
        train_dl.set_epoch(epoch)
        dl = train_dl
        if epoch == start_epoch and resume_step:
            dl = accelerator.skip_first_batches(train_dl, resume_step)
        for batch in dl:
            with accelerator.accumulate(model):
                loss = accelerator.backward(model.loss, batch)
                optimizer.step()
                optimizer.zero_grad()
            counter.overall_step += 1
        accelerator.save_state()
        accelerator.print(f"epoch {epoch}: loss {float(loss):.4f} (state saved)")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=256)
    parser.add_argument("--output_dir", default="/tmp/accelerate_tpu_ckpt_example")
    parser.add_argument("--resume_from_checkpoint", default=None)
    training_function(parser.parse_args())

"""by_feature/memory (parity: reference examples/by_feature/memory.py):
`find_executable_batch_size` halves the batch size on OOM and restarts the inner
function — the decorator owns the retry loop, the user code stays linear."""

import argparse
import os
import sys

import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import find_executable_batch_size, set_seed


def training_function(args):
    accelerator = Accelerator()
    set_seed(args.seed)
    config = bert_tiny()
    data = get_dataset(config.vocab_size - 1, n=args.train_size)

    @find_executable_batch_size(starting_batch_size=args.batch_size)
    def inner_training_loop(batch_size):
        accelerator.print(f"Trying batch size: {batch_size}")
        accelerator.free_memory()  # fresh state for each attempt (reference memory.py)
        model = create_bert_model(config, seq_len=MAX_LEN)
        sampler = SeedableRandomSampler(num_samples=len(data), seed=args.seed)
        train_dl = SimpleDataLoader(data, BatchSampler(sampler, batch_size))
        optimizer = optax.adamw(args.lr)
        pmodel, popt, pdl = accelerator.prepare(model, optimizer, train_dl)
        for epoch in range(args.epochs):
            for batch in pdl:
                with accelerator.accumulate(pmodel):
                    loss = accelerator.backward(pmodel.loss, batch)
                    popt.step()
                    popt.zero_grad()
            accelerator.print(f"epoch {epoch}: loss {float(loss):.4f}")
        return batch_size

    used = inner_training_loop()
    accelerator.print(f"Trained with batch size {used}")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=256)
    training_function(parser.parse_args())

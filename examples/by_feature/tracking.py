"""by_feature/tracking (parity: reference examples/by_feature/tracking.py): tracker
fan-out via `init_trackers`/`log`/`end_training`. Uses the JSON/CSV trackers (always
available); pass --log_with tensorboard/wandb when those packages are installed."""

import argparse
import os
import sys

import numpy as np
import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import set_seed


def training_function(args):
    accelerator = Accelerator(log_with=args.log_with, project_dir=args.output_dir)
    set_seed(args.seed)
    config = bert_tiny()
    model = create_bert_model(config, seq_len=MAX_LEN)
    train_data = get_dataset(config.vocab_size - 1, n=args.train_size, seed=0)
    eval_data = get_dataset(config.vocab_size - 1, n=args.eval_size, seed=1)
    sampler = SeedableRandomSampler(num_samples=len(train_data), seed=args.seed)
    train_dl = SimpleDataLoader(train_data, BatchSampler(sampler, args.batch_size))
    eval_dl = SimpleDataLoader(eval_data, BatchSampler(range(len(eval_data)), args.batch_size))
    optimizer = optax.adamw(args.lr)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(model, optimizer, train_dl, eval_dl)

    accelerator.init_trackers("tracking_example", config=vars(args))
    overall_step = 0
    for epoch in range(args.epochs):
        total_loss = 0.0
        for batch in train_dl:
            with accelerator.accumulate(model):
                loss = accelerator.backward(model.loss, batch)
                # Accumulate ON DEVICE: float(loss) here would sync the host
                # every step and serialize dispatch (tpu-lint TPU111).
                total_loss += loss
                optimizer.step()
                optimizer.zero_grad()
            overall_step += 1
        correct, total = 0, 0
        for batch in eval_dl:
            logits = model(batch["input_ids"], None, batch["token_type_ids"])
            preds = accelerator.gather_for_metrics(np.asarray(logits).argmax(-1))
            labels = accelerator.gather_for_metrics(np.asarray(batch["labels"]))
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(labels))
        accelerator.log(
            {"train_loss": float(total_loss) / len(train_dl), "accuracy": correct / total, "epoch": epoch},
            step=overall_step,
        )
        accelerator.print(f"epoch {epoch}: acc {correct / total:.3f}")
    accelerator.end_training()


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--log_with", default="json", help="json, csv, tensorboard, wandb, mlflow, all")
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=256)
    parser.add_argument("--eval_size", type=int, default=64)
    parser.add_argument("--output_dir", default="/tmp/accelerate_tpu_tracking_example")
    training_function(parser.parse_args())

"""by_feature/early_stopping (parity: reference examples/by_feature/early_stopping.py):
the nlp_example plus patience-based early stopping. The break decision is made
cross-process-consistently via the trigger flag (`set_trigger`/`check_trigger`,
reference accelerator.py:2127-2153) so every rank leaves the epoch loop together."""

import argparse
import os
import sys

import numpy as np
import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import set_seed


class EarlyStoppingCallback:
    def __init__(self, min_delta: float = 0.0, patience: int = 2):
        self.min_delta = min_delta
        self.patience = patience
        self.best = float("inf")
        self.counter = 0

    def check(self, eval_loss: float) -> bool:
        if eval_loss < self.best - self.min_delta:
            self.best = eval_loss
            self.counter = 0
            return False
        self.counter += 1
        return self.counter >= self.patience


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    set_seed(args.seed)
    config = bert_tiny()
    model = create_bert_model(config, seq_len=MAX_LEN)
    train_data = get_dataset(config.vocab_size - 1, n=args.train_size, seed=0)
    eval_data = get_dataset(config.vocab_size - 1, n=args.eval_size, seed=1)
    sampler = SeedableRandomSampler(num_samples=len(train_data), seed=args.seed)
    train_dl = SimpleDataLoader(train_data, BatchSampler(sampler, args.batch_size))
    eval_dl = SimpleDataLoader(eval_data, BatchSampler(range(len(eval_data)), args.batch_size))
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        model, optax.adamw(args.lr), train_dl, eval_dl
    )

    stopper = EarlyStoppingCallback(patience=args.patience)
    for epoch in range(args.epochs):
        for batch in train_dl:
            loss = accelerator.backward(model.loss, batch)
            optimizer.step()
            optimizer.zero_grad()
        eval_losses = []
        for batch in eval_dl:
            eval_losses.append(np.asarray(accelerator.gather_for_metrics(model.loss(model.params, batch))))
        eval_loss = float(np.mean(eval_losses))
        accelerator.print(f"epoch {epoch}: train loss {float(loss):.4f} eval loss {eval_loss:.4f}")
        # Decide on the main process; broadcast the decision through the trigger so
        # every rank breaks on the same epoch (a per-rank break would deadlock
        # collectives on a real pod).
        if accelerator.is_main_process and stopper.check(eval_loss):
            accelerator.set_trigger()
        if accelerator.check_trigger():
            accelerator.print(f"early stopping at epoch {epoch} (patience {args.patience})")
            break
    return eval_loss


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16", choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=10)
    parser.add_argument("--patience", type=int, default=2)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=128)
    parser.add_argument("--eval_size", type=int, default=64)
    training_function(parser.parse_args())

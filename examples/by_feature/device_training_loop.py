"""by_feature/device_training_loop: the TPU performance path. One compiled call
runs `steps_per_call` FULL optimizer steps (`lax.scan` over stacked step-batches),
so the per-call host cost — argument processing plus a network round trip on a
tunneled chip — is paid once per K steps instead of every step. That fixed
~10-20 ms/call tax is what held the bs-32 headline config to 0.335 MFU
(docs/concepts/performance.md); the device loop divides it by K, and
`bench.py` auto-selects K=10 for exactly this reason.

No reference counterpart: the reference's per-step backward/step choreography
cannot batch host dispatch; this exists because XLA lets the whole loop live on
device.
"""

import argparse
import os
import sys

import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import set_seed


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    set_seed(args.seed)
    config = bert_tiny()
    model = create_bert_model(config, seq_len=MAX_LEN)
    data = get_dataset(config.vocab_size - 1, n=args.train_size)

    # The loader collates steps_per_call step-batches as ONE [K*b, ...] array:
    # one host->device transfer, one dispatch, K optimizer steps on device.
    sampler = SeedableRandomSampler(num_samples=len(data), seed=args.seed)
    train_dl = SimpleDataLoader(
        data, BatchSampler(sampler, args.batch_size * args.steps_per_call, drop_last=True)
    )
    optimizer = optax.adamw(args.lr)
    model, optimizer, train_dl = accelerator.prepare(model, optimizer, train_dl)

    if len(train_dl) == 0:
        raise SystemExit(
            f"train_size={args.train_size} is smaller than one stacked call "
            f"(batch_size*steps_per_call = {args.batch_size * args.steps_per_call}); "
            "lower --steps_per_call/--batch_size or raise --train_size"
        )
    step_fn = accelerator.train_step(steps_per_call=args.steps_per_call)
    loss = None
    steps = 0
    for epoch in range(args.epochs):
        train_dl.set_epoch(epoch)
        for batch in train_dl:
            loss = step_fn(batch)  # K steps; returns the LAST step's loss
            steps += args.steps_per_call
    accelerator.print(
        f"device training loop: {steps} optimizer steps in {steps // args.steps_per_call} "
        f"dispatches (steps_per_call={args.steps_per_call}) final loss {float(loss):.4f}"
    )


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed_precision", default="bf16", choices=["no", "bf16"])
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument(
        "--steps_per_call",
        type=int,
        default=4,
        help="full optimizer steps scanned per compiled call (bf16 only: dynamic "
        "fp16 loss scaling needs per-step host decisions and is rejected)",
    )
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=256)
    training_function(parser.parse_args())

"""by_feature/schedule_free (parity: reference examples/by_feature/schedule_free.py,
which uses facebookresearch/schedule_free): schedule-free AdamW via
`optax.contrib.schedule_free_adamw` — no LR schedule to configure, but evaluation must
run at the AVERAGED parameters (`schedule_free_eval_params`), which is the one wrinkle
this example demonstrates."""

import argparse
import os
import sys

import numpy as np
import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import set_seed


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    set_seed(args.seed)
    config = bert_tiny()
    model = create_bert_model(config, seq_len=MAX_LEN)
    train_data = get_dataset(config.vocab_size - 1, n=args.train_size, seed=0)
    eval_data = get_dataset(config.vocab_size - 1, n=args.eval_size, seed=1)
    sampler = SeedableRandomSampler(num_samples=len(train_data), seed=args.seed)
    train_dl = SimpleDataLoader(train_data, BatchSampler(sampler, args.batch_size))
    eval_dl = SimpleDataLoader(eval_data, BatchSampler(range(len(eval_data)), args.batch_size))

    optimizer = optax.contrib.schedule_free_adamw(learning_rate=args.lr, warmup_steps=args.warmup_steps)
    model, optimizer, train_dl, eval_dl = accelerator.prepare(model, optimizer, train_dl, eval_dl)

    for epoch in range(args.epochs):
        for batch in train_dl:
            loss = accelerator.backward(model.loss, batch)
            optimizer.step()
            optimizer.zero_grad()

        # Schedule-free: the training params are the fast iterates; metrics belong to
        # the averaged ("x") sequence extracted from the optimizer state.
        eval_params = optax.contrib.schedule_free_eval_params(optimizer.opt_state, model.params)
        correct, total = 0, 0
        for batch in eval_dl:
            logits = model.apply(eval_params, batch["input_ids"], None, batch["token_type_ids"])
            preds, labels = accelerator.gather_for_metrics(
                (np.asarray(logits).argmax(-1), np.asarray(batch["labels"]))
            )
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(labels))
        accelerator.print(
            f"epoch {epoch}: loss {float(loss):.4f} accuracy {correct / total:.4f} (schedule-free eval params)"
        )
    return correct / total


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--mixed_precision", default="bf16", choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--warmup_steps", type=int, default=4)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=128)
    parser.add_argument("--eval_size", type=int, default=64)
    training_function(parser.parse_args())

"""by_feature/sequence_parallelism — long-context training with the sequence
dimension sharded over the `seq` mesh axis and ring attention rotating K/V blocks
via ppermute. This is the capability the reference only reaches through an external
Megatron flag (SURVEY §5); here it is a plugin plus one mesh axis, and the same
script runs unsharded when seq_degree=1."""

import argparse
import os
import sys

import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler
from accelerate_tpu.models import create_llama_model, llama_tiny
from accelerate_tpu.utils import ParallelismConfig, SequenceParallelPlugin, set_seed


def get_corpus(vocab_size: int, seq_len: int, n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    return [
        {"input_ids": rng.integers(1, vocab_size, size=(seq_len,)).astype(np.int32)} for _ in range(n)
    ]


def training_function(args):
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(data=-1, seq=args.seq_degree),
        sequence_parallel_plugin=SequenceParallelPlugin(
            seq_degree=args.seq_degree, mode=args.sp_mode, block_size=args.block_size
        ),
    )
    set_seed(args.seed)
    config = llama_tiny()
    model = create_llama_model(config, seq_len=args.seq_len)
    data = get_corpus(config.vocab_size, args.seq_len, args.train_size, args.seed)
    train_dl = SimpleDataLoader(data, BatchSampler(range(len(data)), args.batch_size, drop_last=True))
    model, optimizer, train_dl = accelerator.prepare(model, optax.adamw(args.lr), train_dl)

    step = accelerator.train_step()
    for epoch in range(args.epochs):
        for batch in train_dl:
            loss = step(batch)
        accelerator.print(f"epoch {epoch}: loss {float(loss):.4f}")

    from accelerate_tpu.ops.attention import LAST_DISPATCH

    accelerator.print(
        f"sequence-parallel training done: seq axis={args.seq_degree}, "
        f"attention dispatch={LAST_DISPATCH}"
    )


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--seq_degree", type=int, default=2, help="Mesh axis size for `seq`")
    parser.add_argument("--sp_mode", default="ring", choices=["ring", "allgather"])
    parser.add_argument("--block_size", type=int, default=16, help="Ring attention block size")
    parser.add_argument("--seq_len", type=int, default=64)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=32)
    training_function(parser.parse_args())

"""by_feature/deepspeed_with_config_support (parity: reference
examples/by_feature/deepspeed_with_config_support.py): train from a DeepSpeed-style
ds_config.json. On TPU the DeepSpeedPlugin is a compatibility shim — the zero stage
and offload devices lower to GSPMD sharding specs + pinned-host placement
(utils/dataclasses.py DeepSpeedPlugin.to_fsdp_plugin), so existing ds_configs keep
working with no DeepSpeed runtime."""

import argparse
import json
import os
import sys

import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from nlp_example import MAX_LEN, get_dataset  # noqa: E402

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler, SeedableRandomSampler
from accelerate_tpu.models import bert_tiny, create_bert_model
from accelerate_tpu.utils import DeepSpeedPlugin, set_seed

DEFAULT_DS_CONFIG = {
    "train_micro_batch_size_per_gpu": 16,
    "gradient_accumulation_steps": 2,
    "gradient_clipping": 1.0,
    "zero_optimization": {
        "stage": 2,
        "offload_optimizer": {"device": "none"},
    },
    "bf16": {"enabled": True},
}


def training_function(args):
    if args.ds_config:
        with open(args.ds_config) as f:
            ds_config = json.load(f)
    else:
        ds_config = DEFAULT_DS_CONFIG
    plugin = DeepSpeedPlugin(hf_ds_config=ds_config)
    accelerator = Accelerator(mixed_precision=args.mixed_precision, deepspeed_plugin=plugin)
    accelerator.print(
        f"ds_config: zero_stage={plugin.zero_stage} -> "
        f"{accelerator.state.fsdp_plugin.sharding_strategy}, "
        f"accumulation={plugin.gradient_accumulation_steps}"
    )
    set_seed(args.seed)
    config = bert_tiny()
    model = create_bert_model(config, seq_len=MAX_LEN)
    data = get_dataset(config.vocab_size - 1, n=args.train_size, seed=0)
    sampler = SeedableRandomSampler(num_samples=len(data), seed=args.seed)
    train_dl = SimpleDataLoader(data, BatchSampler(sampler, args.batch_size))
    model, optimizer, train_dl = accelerator.prepare(model, optax.adamw(args.lr), train_dl)

    for epoch in range(args.epochs):
        for batch in train_dl:
            with accelerator.accumulate(model):
                loss = accelerator.backward(model.loss, batch)
                if plugin.gradient_clipping:
                    accelerator.clip_grad_norm_(max_norm=plugin.gradient_clipping)
                optimizer.step()
                optimizer.zero_grad()
        accelerator.print(f"epoch {epoch}: loss {float(loss):.4f}")
    return float(loss)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--ds_config", default=None, help="path to a DeepSpeed config json")
    parser.add_argument("--mixed_precision", default="bf16", choices=["no", "bf16", "fp16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--train_size", type=int, default=128)
    training_function(parser.parse_args())

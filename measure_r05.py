"""Round-5 measurement suite (run opportunistically on hardware by
tpu_watch_r05.sh; the driver contract stays `bench.py` = one JSON line).

Ordering is the round-4 lesson (verdict, weak #5): the tunnel was down for
most of round 4 and the suite captured 3/11 rows — all three RE-captures of
configs that already had numbers, while every never-before-captured config
(flash A/B, steps_per_call A/B, long-seq scaling, inference) stayed queued.
This list runs NEVER-CAPTURED configs first, so a short tunnel window spends
its minutes on evidence that doesn't exist yet:

  1. steps_per_call K=10 at bs 32 — the fix for the 0.335-MFU default-config
     deficit (bench_suite_r04.jsonl bs32 K=1 row is the baseline)
  2. flash-vs-XLA A/B at seq 1024, equal batch + remat (the Pallas kernel's
     reason to exist; zero hardware numbers through round 4)
  3. big-model inference TTFT/decode (half of BASELINE.json's metric)
  4. the NO-FLAGS bench.py default (bs 64, K=10) — BASELINE.md's north star
     is "the default config >= 0.45 MFU", not a tuned one
  5. llama-1b with bf16 param/moment storage (verdict #6: the round-4 OOM was
     fp32-AdamW-moments self-inflicted; this row exercises the dtype knob)
  6. long-seq flash scaling (2048/4096)
  7. same-day K=1 re-baselines for the A/B deltas
  8. gptj-6b inference LAST and OPTIONAL (6B bf16 + KV cache ~14 GB of the
     16 GB chip; if it doesn't fit it must not stall capturable configs)

Appends to bench_suite_r05.jsonl via measure_r04.run_suite (shared resumable
runner: captured tags skip, error rows never persist so failures retry).
"""

import sys

from measure_r04 import captured_tags, run_suite

OUT_PATH = "bench_suite_r05.jsonl"

CONFIGS = [
    # (tag, argv, timeout_s)
    ("headline bs32 spc10", ["--steps", "500", "--trials", "3", "--batch_size", "32", "--steps_per_call", "10"], 2400),
    (
        "llama-1b seq1024 flash remat",
        ["--model", "llama-1b", "--seq_len", "1024", "--batch_size", "4", "--steps", "100",
         "--trials", "3", "--attention", "flash", "--remat", "dots"],
        3000,
    ),
    (
        "llama-1b seq1024 xla remat",
        ["--model", "llama-1b", "--seq_len", "1024", "--batch_size", "4", "--steps", "100",
         "--trials", "3", "--attention", "xla", "--remat", "dots"],
        3000,
    ),
    ("inference llama-1b", ["--mode", "inference", "--model", "llama-1b"], 1800),
    # bench.py with NO flags: bs 64, steps_per_call auto=10, 500 steps x 3
    # trials — the exact config the driver's BENCH_r05 capture runs.
    ("headline default bs64 spc10", ["--steps", "500", "--trials", "3"], 2400),
    (
        "llama-1b seq1024 bf16-moments remat",
        ["--model", "llama-1b", "--seq_len", "1024", "--batch_size", "4", "--steps", "100",
         "--trials", "3", "--param_dtype", "bfloat16", "--remat", "dots"],
        3000,
    ),
    (
        "llama-1b seq2048 flash remat",
        ["--model", "llama-1b", "--seq_len", "2048", "--batch_size", "2", "--steps", "60",
         "--trials", "2", "--attention", "flash", "--remat", "dots"],
        3000,
    ),
    (
        "llama-1b seq4096 flash remat",
        ["--model", "llama-1b", "--seq_len", "4096", "--batch_size", "1", "--steps", "40",
         "--trials", "2", "--attention", "flash", "--remat", "dots"],
        3000,
    ),
    ("sweep bs64 spc20", ["--steps", "500", "--trials", "3", "--batch_size", "64", "--steps_per_call", "20"], 2400),
    # Same-day K=1 baselines (r04 rows exist, but a same-session pair removes
    # day-to-day tunnel variance from the K=10/20 A/B deltas).
    ("baseline bs32 spc1", ["--steps", "500", "--trials", "3", "--batch_size", "32", "--steps_per_call", "1"], 2400),
    ("baseline bs64 spc1", ["--steps", "500", "--trials", "3", "--batch_size", "64", "--steps_per_call", "1"], 2400),
    ("inference gptj-6b", ["--mode", "inference", "--model", "gptj-6b"], 2700),
]

# Tags the watcher must NOT wait on (see the module docstring).
OPTIONAL = {"inference gptj-6b"}


def required_tags():
    return {tag for tag, _, _ in CONFIGS} - OPTIONAL


def missing_required(out_path=OUT_PATH):
    """Required tags with no persisted row — the watcher's exit condition AND
    its end-of-round 'N rows missing' marker (round-4 lesson: an incomplete
    capture must be loud, not a quiet 'captured 3/11' buried in a log)."""
    return sorted(required_tags() - captured_tags(out_path))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--missing":
        missing = missing_required()
        print("\n".join(missing))
        sys.exit(1 if missing else 0)
    run_suite(CONFIGS, prefix="suite-r05", out_path=OUT_PATH)

"""Round-4 follow-up measurements (run after measure_r04.py).

1. Device-loop A/B (`train_step(steps_per_call=K)`): the bs-32 headline config
   lost ~21 ms/step to per-call host dispatch on the tunneled chip
   (bs32 0.335 MFU vs bs64 0.502 in bench_suite_r04.jsonl); K=10 pays that cost
   once per 10 steps. Captured at equal step counts against the K=1 rows.
2. Flash-vs-XLA at seq 1024 with remat: the bs-4 flash leg OOM'd (llama-1b +
   AdamW fp32 moments is ~15 GB before activations); `--remat dots` drops
   attention residuals so both legs fit on the 16 GB chip at equal batch.
3. Long-seq flash scaling with remat (seq 2048 / 4096).

Appends to bench_suite_r04.jsonl via measure_r04.run_suite (shared resumable
runner).
"""

from measure_r04 import run_suite

CONFIGS = [
    ("headline bs32 spc10", ["--steps", "500", "--trials", "3", "--batch_size", "32", "--steps_per_call", "10"], 2400),
    ("sweep bs64 spc10", ["--steps", "500", "--trials", "3", "--batch_size", "64", "--steps_per_call", "10"], 2400),
    ("sweep bs64 spc20", ["--steps", "500", "--trials", "3", "--batch_size", "64", "--steps_per_call", "20"], 2400),
    (
        "llama-1b seq1024 flash remat",
        ["--model", "llama-1b", "--seq_len", "1024", "--batch_size", "4", "--steps", "100",
         "--trials", "3", "--attention", "flash", "--remat", "dots"],
        3000,
    ),
    (
        "llama-1b seq1024 xla remat",
        ["--model", "llama-1b", "--seq_len", "1024", "--batch_size", "4", "--steps", "100",
         "--trials", "3", "--attention", "xla", "--remat", "dots"],
        3000,
    ),
    (
        "llama-1b seq2048 flash remat",
        ["--model", "llama-1b", "--seq_len", "2048", "--batch_size", "2", "--steps", "60",
         "--trials", "2", "--attention", "flash", "--remat", "dots"],
        3000,
    ),
    (
        "llama-1b seq4096 flash remat",
        ["--model", "llama-1b", "--seq_len", "4096", "--batch_size", "1", "--steps", "40",
         "--trials", "2", "--attention", "flash", "--remat", "dots"],
        3000,
    ),
    # Last on purpose, and OPTIONAL for tpu_watch.sh's exit condition: 6B bf16
    # params + KV cache is ~14 GB of the 16 GB chip, so if it doesn't fit it
    # must not stall the capturable configs every watcher cycle.
    ("inference gptj-6b", ["--mode", "inference", "--model", "gptj-6b"], 2700),
]


if __name__ == "__main__":
    run_suite(CONFIGS, prefix="suite-b")

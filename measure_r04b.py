"""Round-4 follow-up measurements (run after measure_r04.py).

1. Device-loop A/B (`train_step(steps_per_call=K)`): the bs-32 headline config
   lost ~21 ms/step to per-call host dispatch on the tunneled chip
   (bs32 0.335 MFU vs bs64 0.502 in bench_suite_r04.jsonl); K=10 pays that cost
   once per 10 steps. Captured at equal step counts against the K=1 rows.
2. Flash-vs-XLA at seq 1024 with remat: the bs-4 flash leg OOM'd (llama-1b +
   AdamW fp32 moments is ~15 GB before activations); `--remat dots` drops
   attention residuals so both legs fit on the 16 GB chip at equal batch.
3. Long-seq flash scaling with remat (seq 2048 / 4096).

Appends to bench_suite_r04.jsonl like the main suite.
"""

import json
import subprocess
import sys
import time

CONFIGS = [
    ("headline bs32 spc10", ["--steps", "500", "--trials", "3", "--batch_size", "32", "--steps_per_call", "10"], 2400),
    ("sweep bs64 spc10", ["--steps", "500", "--trials", "3", "--batch_size", "64", "--steps_per_call", "10"], 2400),
    ("sweep bs64 spc20", ["--steps", "500", "--trials", "3", "--batch_size", "64", "--steps_per_call", "20"], 2400),
    (
        "llama-1b seq1024 flash remat",
        ["--model", "llama-1b", "--seq_len", "1024", "--batch_size", "4", "--steps", "100",
         "--trials", "3", "--attention", "flash", "--remat", "dots"],
        3000,
    ),
    (
        "llama-1b seq1024 xla remat",
        ["--model", "llama-1b", "--seq_len", "1024", "--batch_size", "4", "--steps", "100",
         "--trials", "3", "--attention", "xla", "--remat", "dots"],
        3000,
    ),
    (
        "llama-1b seq2048 flash remat",
        ["--model", "llama-1b", "--seq_len", "2048", "--batch_size", "2", "--steps", "60",
         "--trials", "2", "--attention", "flash", "--remat", "dots"],
        3000,
    ),
    (
        "llama-1b seq4096 flash remat",
        ["--model", "llama-1b", "--seq_len", "4096", "--batch_size", "1", "--steps", "40",
         "--trials", "2", "--attention", "flash", "--remat", "dots"],
        3000,
    ),
]


def main():
    out_path = "bench_suite_r04.jsonl"
    done = set()
    try:
        with open(out_path) as f:
            for row_line in f:
                try:
                    done.add(__import__("json").loads(row_line).get("tag"))
                except ValueError:
                    pass
    except FileNotFoundError:
        pass
    results = []
    for tag, argv, timeout_s in CONFIGS:
        if tag in done:
            print(f"[suite-b] {tag}: already captured, skipping", file=sys.stderr, flush=True)
            continue
        cmd = [sys.executable, "bench.py", "--no-supervise"] + argv
        print(f"[suite-b] {tag}: {' '.join(cmd)}", file=sys.stderr, flush=True)
        t0 = time.time()
        try:
            proc = subprocess.run(cmd, timeout=timeout_s, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            print(f"[suite-b] {tag}: TIMEOUT >{timeout_s}s", file=sys.stderr, flush=True)
            results.append({"tag": tag, "error": f"timeout>{timeout_s}s"})
            continue
        line = None
        for out_line in (proc.stdout or "").strip().splitlines():
            try:
                parsed = json.loads(out_line)
                if isinstance(parsed, dict) and "metric" in parsed:
                    line = parsed
            except json.JSONDecodeError:
                continue
        if proc.returncode != 0 or line is None:
            print(
                f"[suite-b] {tag}: FAILED rc={proc.returncode}; stderr tail: "
                f"{(proc.stderr or '')[-600:]!r}",
                file=sys.stderr,
                flush=True,
            )
            results.append({"tag": tag, "error": f"rc={proc.returncode}"})
            continue
        line["tag"] = tag
        line["wall_s"] = round(time.time() - t0, 1)
        results.append(line)
        with open(out_path, "a") as f:
            f.write(json.dumps(line) + "\n")
        print(f"[suite-b] {tag}: {json.dumps(line)}", flush=True)
    ok = sum(1 for r in results if "error" not in r)
    print(f"[suite-b] done: {ok}/{len(CONFIGS)} configs captured -> {out_path}", flush=True)


if __name__ == "__main__":
    main()

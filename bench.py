"""Benchmark entry (driver contract): prints ONE JSON line
`{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}`.

Measures training throughput (samples/sec/chip) of BERT-base GLUE-style sequence
classification through the full framework path — prepared model, sharded dataloader,
`accumulate`/`backward`/`step` — i.e. the same code a user runs, not a stripped kernel
loop. That matches BASELINE.json's metric ("samples/sec/chip (GLUE BERT ...)").

`vs_baseline` is measured MFU / 0.45 — the north-star gate from BASELINE.md ("≥45% MFU
... via a native XLA-SPMD backend"); >1.0 beats the target. On hosts where peak FLOPs
for the chip are unknown (e.g. CPU smoke runs) MFU is reported as null and vs_baseline
falls back to samples/sec normalized by a reference-epoch constant.
"""

import argparse
import json
import os
import time

import numpy as np


def inference_bench(args):
    """Big-model-inference metric (reference benchmarks/big_model_inference.py:
    model load + per-token generation latency, README.md:27-37): reports p50 TTFT
    (compiled prefill) and per-token decode latency through the KV-cache path."""
    import jax

    from accelerate_tpu.generation import GenerationConfig, Generator
    from accelerate_tpu.models.llama import create_llama_model, llama_1b, llama_tiny

    on_accel = jax.devices()[0].platform in ("tpu", "gpu")
    model_name = args.model if args.model.startswith("llama") else "llama-1b"
    if not on_accel:
        model_name = "llama-tiny"
    t_load = time.perf_counter()
    cfg = llama_1b() if model_name == "llama-1b" else llama_tiny()
    model = create_llama_model(cfg, seq_len=args.seq_len)
    load_s = time.perf_counter() - t_load

    batch = args.batch_size or 1
    prompt_len = min(args.seq_len, cfg.max_position_embeddings // 2)
    new_tokens = 32
    gen = Generator(model, max_new_tokens=new_tokens, max_length=prompt_len + new_tokens)
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, cfg.vocab_size, (batch, prompt_len)).astype(np.int32)

    # compile both programs
    gen(prompt, GenerationConfig(max_new_tokens=2))

    ttfts = []
    for _ in range(5):
        t0 = time.perf_counter()
        gen(prompt, GenerationConfig(max_new_tokens=1))
        ttfts.append(time.perf_counter() - t0)
    t0 = time.perf_counter()
    out = gen(prompt, GenerationConfig(max_new_tokens=new_tokens))
    jax.block_until_ready(out)
    total = time.perf_counter() - t0
    ttft_p50 = sorted(ttfts)[len(ttfts) // 2]
    per_token = (total - ttft_p50) / max(new_tokens - 1, 1)

    # reference headline: GPT-J-6B fp16 on 2x Titan RTX = 0.05 s/token
    # (benchmarks/README.md:31); vs_baseline = reference / ours (higher is better).
    vs_baseline = 0.05 / per_token if per_token > 0 else 0.0
    result = {
        "metric": f"per-token generation latency ({model_name}, prompt {prompt_len}, bs {batch})",
        "value": round(per_token * 1000, 3),
        "unit": "ms/token",
        "vs_baseline": round(vs_baseline, 4),
        "extra": {
            "ttft_p50_ms": round(ttft_p50 * 1000, 3),
            "model_load_s": round(load_s, 2),
            "device_kind": jax.devices()[0].device_kind,
            "new_tokens": new_tokens,
        },
    }
    print(json.dumps(result))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="bert-base", choices=["bert-base", "bert-tiny", "llama-1b", "llama-tiny"])
    parser.add_argument("--mode", default="train", choices=["train", "inference"])
    parser.add_argument("--batch_size", type=int, default=None, help="per-chip batch size")
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--mixed_precision", default="bf16")
    args = parser.parse_args()

    if args.mode == "inference":
        return inference_bench(args)

    import jax
    import optax

    from accelerate_tpu import Accelerator, SimpleDataLoader
    from accelerate_tpu.data_loader import BatchSampler
    from accelerate_tpu.utils.environment import get_device_peak_flops

    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    n_chips = jax.device_count()
    device_kind = jax.devices()[0].device_kind
    on_accel = jax.devices()[0].platform in ("tpu", "gpu")

    if args.batch_size is None:
        args.batch_size = 32 if on_accel else 4
    if not on_accel and args.model == "bert-base":
        args.steps = min(args.steps, 8)

    if args.model.startswith("bert"):
        from accelerate_tpu.models import bert_base, bert_tiny, create_bert_model

        cfg = bert_base() if args.model == "bert-base" else bert_tiny()
        model = create_bert_model(cfg, seq_len=args.seq_len)
        rng = np.random.default_rng(0)
        global_batch = args.batch_size * n_chips
        n = global_batch * 2
        data = [
            {
                "input_ids": rng.integers(1, cfg.vocab_size, size=(args.seq_len,)).astype(np.int32),
                "labels": np.int64(rng.integers(0, cfg.num_labels)),
            }
            for _ in range(n)
        ]
        num_layers, hidden, ffn = cfg.num_hidden_layers, cfg.hidden_size, cfg.intermediate_size
        vocab = cfg.vocab_size
    else:
        from accelerate_tpu.models.llama import create_llama_model, llama_1b, llama_tiny

        cfg = llama_1b() if args.model == "llama-1b" else llama_tiny()
        model = create_llama_model(cfg, seq_len=args.seq_len)
        rng = np.random.default_rng(0)
        global_batch = args.batch_size * n_chips
        n = global_batch * 2
        data = [
            {"input_ids": rng.integers(1, cfg.vocab_size, size=(args.seq_len,)).astype(np.int32)} for _ in range(n)
        ]
        num_layers, hidden, ffn = cfg.num_hidden_layers, cfg.hidden_size, cfg.intermediate_size
        vocab = cfg.vocab_size

    dl = SimpleDataLoader(data, BatchSampler(range(n), global_batch, drop_last=True))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adamw(1e-4), dl)

    param_count = pmodel.num_parameters

    def one_epoch():
        count = 0
        last_loss = None
        for batch in pdl:
            with accelerator.accumulate(pmodel):
                last_loss = accelerator.backward(pmodel.loss, batch)
                popt.step()
                popt.zero_grad()
            count += 1
        return count, last_loss

    # Warmup (compile)
    steps_done = 0
    while steps_done < args.warmup:
        c, loss = one_epoch()
        steps_done += c
    jax.block_until_ready(pmodel.params)

    # Timed
    t0 = time.perf_counter()
    steps_done = 0
    while steps_done < args.steps:
        c, loss = one_epoch()
        steps_done += c
    jax.block_until_ready(pmodel.params)
    elapsed = time.perf_counter() - t0

    samples = steps_done * global_batch
    samples_per_sec = samples / elapsed
    samples_per_sec_per_chip = samples_per_sec / n_chips

    # Training FLOPs ≈ 6 * non-embedding-params * tokens (fwd 2x + bwd 4x),
    # standard transformer accounting.
    embed_params = vocab * hidden
    flops_per_token = 6 * max(param_count - embed_params, 1)
    tokens_per_sec = samples_per_sec * args.seq_len
    model_flops_per_sec = flops_per_token * tokens_per_sec
    peak = get_device_peak_flops(device_kind) * n_chips
    mfu = (model_flops_per_sec / peak) if peak > 0 else None

    if mfu is not None:
        vs_baseline = mfu / 0.45
    else:
        # CPU smoke fallback: normalize against a nominal 1 sample/sec/chip.
        vs_baseline = samples_per_sec_per_chip / 1.0

    result = {
        "metric": f"samples/sec/chip ({args.model}, seq {args.seq_len}, bs {args.batch_size}/chip, {args.mixed_precision})",
        "value": round(samples_per_sec_per_chip, 3),
        "unit": "samples/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
        "extra": {
            "device_kind": device_kind,
            "n_chips": n_chips,
            "mfu": round(mfu, 4) if mfu is not None else None,
            "tokens_per_sec": round(tokens_per_sec, 1),
            "params": param_count,
            "final_loss": float(loss) if loss is not None else None,
            "steps": steps_done,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()

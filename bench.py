"""Benchmark entry (driver contract): prints ONE JSON line
`{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}`.

Measures training throughput (samples/sec/chip) of BERT-base GLUE-style sequence
classification through the full framework path — prepared model, sharded dataloader,
fused train step — i.e. the same code a user runs, not a stripped kernel loop. That
matches BASELINE.json's metric ("samples/sec/chip (GLUE BERT ...)").

`vs_baseline` is measured MFU / 0.45 — the north-star gate from BASELINE.md ("≥45% MFU
... via a native XLA-SPMD backend"); >1.0 beats the target. On hosts where peak FLOPs
for the chip are unknown (e.g. CPU smoke runs) MFU is reported as null and vs_baseline
falls back to samples/sec normalized by a reference-epoch constant.

Resilience (round-1 postmortem: BENCH_r01 died rc=1 at first TPU backend init):
the default entry is a SUPERVISOR that runs the real bench in a worker subprocess
with a timeout, retries on crash/hang with backoff, and falls back to
JAX_PLATFORMS=cpu on the last attempt so the driver always gets a JSON line.
All diagnostics go to stderr; stdout carries exactly the one JSON line.
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np


def log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


# --------------------------------------------------------------------------- supervisor
#
# Deadline ledger (round-5: the driver's capture window is ~30 min of wall
# clock; round 4 set an 80-min preflight budget and the driver killed the
# supervisor mid-backoff — BENCH_r04.json was rc=124 with NO json line).
# Every phase below is capped by `remaining() - <reserves the later phases
# need>`, so the one JSON line lands before BENCH_DEADLINE_S no matter what
# the tunnel does. Worst-case path and its arithmetic:
#
#   probe hang          <= PREFLIGHT_TIMEOUT (120)
#   backoff budget      <= min(BENCH_PREFLIGHT_BUDGET (600),
#                              remaining - MIN_ATTEMPT - CPU_RESERVE - MARGIN)
#   shortened attempt   <= remaining - CPU_RESERVE - MARGIN
#   CPU fallback        <= remaining - MARGIN
#   diagnostic line     ~0
#
# so time-to-JSON <= BENCH_DEADLINE_S (default 1500 s = 25 min < the window).
# tests/test_bench_contract.py simulates this worst case with a fake clock.
DRIVER_WINDOW_S = 1500  # default BENCH_DEADLINE_S: safely under the ~30-min driver window
CPU_FALLBACK_RESERVE_S = 360  # measured CPU worker (bert-base, 8 steps, 1 vCPU) + margin
FINAL_MARGIN_S = 30  # line emission + process teardown
MIN_ATTEMPT_S = 180  # below this an accelerator attempt can't finish; go straight to CPU

# Tunnel-state memo (round-5 verdict): when a recent probe — this process's or
# the watcher's — already established the tunnel is dead, don't burn the
# backoff budget re-learning it; fast-fail the probe phase and spend the
# window on the CPU fallback instead. The memo lives in a small JSON file
# (BENCH_TUNNEL_STATE_FILE) and expires after BENCH_TUNNEL_MEMO_TTL seconds,
# so a recovered tunnel is re-probed within one TTL.
TUNNEL_MEMO_TTL_S = 900
_DEFAULT_TUNNEL_STATE = os.path.join(
    os.environ.get("TMPDIR", "/tmp"), "accelerate_tpu_tunnel_state.json"
)

# Last-known-good hardware rows embedded in fallback artifacts
# (extra.cached_hardware_evidence): when the tunnel is down for the whole
# round, the driver artifact still carries real TPU numbers with provenance.
CACHED_EVIDENCE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_suite_r04.jsonl")


def _tunnel_state_path():
    return os.environ.get("BENCH_TUNNEL_STATE_FILE", _DEFAULT_TUNNEL_STATE)


def _read_tunnel_state():
    try:
        with open(_tunnel_state_path()) as f:
            state = json.load(f)
        return state if isinstance(state, dict) else None
    except (OSError, ValueError):  # ValueError: JSON errors AND torn-byte utf-8 tears
        return None


def _write_tunnel_state(alive, source="preflight"):
    """Best effort — a memo write must never cost the run its JSON line."""
    path = _tunnel_state_path()
    try:
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"alive": bool(alive), "checked_at": time.time(), "source": source}, f)
        os.replace(tmp, path)
    except OSError as exc:
        log(f"could not persist tunnel state to {path}: {exc}")


def _cached_hardware_evidence():
    """Parse the last-known-good hardware rows (jsonl), tagged with provenance.
    Returns [] when the evidence file is missing/unreadable."""
    path = os.environ.get("BENCH_CACHED_EVIDENCE", CACHED_EVIDENCE_FILE)
    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict) and "metric" in row:
                    row["source"] = os.path.basename(path)
                    rows.append(row)
    except OSError:
        return []
    return rows


def _annotate_line(line: str, events) -> str:
    """Fold the supervisor's structured event ledger into a worker's JSON line
    (extra["supervisor_events"]) so BENCH_* artifacts explain preflight hangs,
    retries and fallbacks after the fact — the r05 postmortem had only prose
    stderr, which the driver doesn't keep. A clean run (no events) passes the
    line through byte-identical."""
    if not events:
        return line
    parsed = json.loads(line)
    parsed.setdefault("extra", {})["supervisor_events"] = list(events)
    return json.dumps(parsed)


def _backend_preflight(timeout_s: int, note=None) -> bool:
    """Can the accelerator backend run ONE tiny op right now? A hung TPU tunnel
    makes backend init block forever; without this probe the supervisor would
    burn attempts x full timeouts (an hour-plus) before its CPU fallback. Cost on
    the healthy path: one extra backend init (~a minute warm) — cheap insurance
    for a once-per-round benchmark; tune with BENCH_PREFLIGHT_TIMEOUT (0 skips)."""
    # Honor an explicit JAX_PLATFORMS before first backend touch: the axon
    # PJRT plugin hooks get_backend and IGNORES the env var, so without the
    # config.update a JAX_PLATFORMS=cpu probe still reaches for the (possibly
    # dead) TPU tunnel and hangs its full timeout. Unset env = probe the real
    # accelerator, which is the point of the preflight.
    probe = (
        "import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
        "p and jax.config.update('jax_platforms', p); "
        "import jax.numpy as jnp; x = jnp.ones((8, 8)) @ jnp.ones((8, 8)); "
        "import numpy as np; print(float(np.asarray(x)[0, 0]))"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", probe], timeout=timeout_s, capture_output=True, text=True
        )
        if r.returncode != 0:
            log(f"preflight probe crashed rc={r.returncode}; stderr tail: {(r.stderr or '')[-800:]!r}")
            if note is not None:
                note("preflight_probe_crashed", rc=r.returncode, timeout_s=round(timeout_s, 1))
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        log(f"preflight probe hung >{timeout_s}s (backend init blocked)")
        if note is not None:
            note("preflight_probe_hung", timeout_s=round(timeout_s, 1))
        return False


def _env_int(name, default):
    """Parse an int env knob, falling back (loudly) on garbage: the supervisor
    must never die on a malformed BENCH_* value before emitting its line —
    rc!=0 with no stdout is the exact artifact this file exists to prevent."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        log(f"ignoring malformed {name}={raw!r}; using default {default}")
        return default


def _run_worker(cmd, env, timeout_s, label, note=None):
    """One worker attempt; returns the parsed-JSON stdout line or None."""
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, env=env, timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        log(f"{label}: worker hung >{timeout_s:.0f}s, killed")
        if note is not None:
            note("worker_hung", label=label, timeout_s=round(float(timeout_s), 1))
        for stream in (e.stderr, e.stdout):  # forward partial logs for diagnosis
            if stream:
                text = stream.decode() if isinstance(stream, bytes) else stream
                sys.stderr.write(text[-4000:])
        return None
    sys.stderr.write(proc.stderr)
    line = None
    for out_line in (proc.stdout or "").strip().splitlines():
        try:
            parsed = json.loads(out_line)
            if isinstance(parsed, dict) and "metric" in parsed:
                line = out_line
        except json.JSONDecodeError:
            continue
    if proc.returncode == 0 and line:
        return line
    log(
        f"{label} failed rc={proc.returncode} after {time.time() - t0:.0f}s; "
        f"stdout tail: {(proc.stdout or '')[-300:]!r}"
    )
    if note is not None:
        note("worker_failed", label=label, rc=proc.returncode,
             elapsed_s=round(time.time() - t0, 1))
    return None


def supervise(argv, total_steps: int = 0):
    """Run the worker with retry/backoff/timeout under a HARD wall-clock
    deadline (BENCH_DEADLINE_S); last resort falls back to CPU, and the one
    JSON line always lands before the deadline (see the ledger above)."""
    start = time.time()
    deadline_s = _env_int("BENCH_DEADLINE_S", DRIVER_WINDOW_S)
    hard_deadline = start + deadline_s
    # Structured event ledger (satellite of the telemetry PR): every preflight
    # failure, backoff wait and fallback decision lands as data in the emitted
    # JSON's extra["supervisor_events"], not just as prose on stderr.
    events = []

    def note(event, **fields):
        entry = {"event": event, "t_s": round(time.time() - start, 1)}
        entry.update(fields)
        events.append(entry)

    def remaining():
        return hard_deadline - time.time()

    attempts = _env_int("BENCH_MAX_ATTEMPTS", 3)
    # Scale the per-attempt timeout with the requested workload so a user-set
    # --steps/--trials can't silently turn every attempt into a timeout kill —
    # but the deadline ledger below still caps every attempt.
    timeout_s = _env_int("BENCH_ATTEMPT_TIMEOUT", max(1500, 300 + 2 * total_steps))
    preflight_timeout = _env_int("BENCH_PREFLIGHT_TIMEOUT", 120)
    preflight_timeout = min(
        preflight_timeout, max(0, int(remaining() - CPU_FALLBACK_RESERVE_S - FINAL_MARGIN_S))
    )
    cpu_fallback_cause = "attempts_exhausted"
    memo = _read_tunnel_state() if preflight_timeout > 0 else None
    memo_ttl = _env_int("BENCH_TUNNEL_MEMO_TTL", TUNNEL_MEMO_TTL_S)
    memo_age = None if memo is None else time.time() - float(memo.get("checked_at", 0) or 0)
    memo_dead = (
        memo is not None
        and memo.get("alive") is False
        and memo_age is not None
        and 0 <= memo_age < memo_ttl
    )
    if memo_dead:
        # The watcher/a previous preflight ALREADY established the tunnel is
        # dead within the memo TTL: fast-fail the probe phase instead of
        # burning the backoff budget re-learning it — the window goes to one
        # shortened accelerator attempt (it may have recovered) + the CPU
        # fallback.
        log(
            f"preflight: memoized tunnel-dead state ({memo_age:.0f}s old, "
            f"source={memo.get('source', '?')}); fast-failing probe phase"
        )
        note("preflight_memoized_dead", age_s=round(memo_age, 1),
             source=str(memo.get("source", "?")))
        attempts = 1
        cpu_fallback_cause = "backend_unresponsive"
    elif preflight_timeout > 0 and not _backend_preflight(preflight_timeout, note=note):
        _write_tunnel_state(False)
        # Backend is down/hung RIGHT NOW. A TPU tunnel outage is usually
        # transient, so retry the CHEAP probe on a backoff schedule — but only
        # up to a budget that still leaves room for one shortened accelerator
        # attempt AND the CPU fallback before the deadline (round-4 postmortem:
        # an 80-min budget here made the driver kill us with no output at all;
        # a tagged CPU line at minute 24 beats a dead artifact at minute 80).
        budget_s = min(
            _env_int("BENCH_PREFLIGHT_BUDGET", 600),
            int(remaining() - MIN_ATTEMPT_S - CPU_FALLBACK_RESERVE_S - FINAL_MARGIN_S),
        )
        backoff_deadline = time.time() + max(0, budget_s)
        delay = 60
        recovered = False
        while time.time() < backoff_deadline:
            wait = min(delay, max(0, backoff_deadline - time.time()))
            log(
                f"preflight: backend down; retrying probe in {wait:.0f}s "
                f"({backoff_deadline - time.time():.0f}s of budget left)"
            )
            note("preflight_retry_wait", wait_s=round(wait, 1))
            time.sleep(wait)
            # Re-probes ESCALATE past the initial 120-s cap (up to 300 s, still
            # inside the ledger): a cold-but-healthy backend init can take
            # minutes, and capping every re-probe at the first probe's timeout
            # would make it permanently unreachable. The ledger term reserves
            # the shortened attempt too — a final-probe overshoot must not eat
            # the one real attempt the dead-tunnel path promises.
            probe_t = min(
                300,
                max(30, int(backoff_deadline - time.time())),
                int(remaining() - MIN_ATTEMPT_S - CPU_FALLBACK_RESERVE_S - FINAL_MARGIN_S),
            )
            if probe_t < 10:
                break
            if _backend_preflight(probe_t, note=note):
                recovered = True
                _write_tunnel_state(True)
                log("preflight: backend recovered; proceeding with full attempts")
                note("preflight_recovered")
                break
            delay = min(delay * 2, 600)
        if not recovered:
            # Budget exhausted and still dead. Keep one real attempt (it may
            # recover mid-run); the ledger cap below already tightens it.
            _write_tunnel_state(False)
            log("preflight: budget exhausted, backend still unresponsive; shortening attempts")
            note("preflight_budget_exhausted", budget_s=round(max(0, budget_s), 1))
            attempts = 1
            cpu_fallback_cause = "backend_unresponsive"
    elif preflight_timeout > 0:
        _write_tunnel_state(True)
    cmd = [sys.executable, os.path.abspath(__file__), "--_worker"] + argv
    for attempt in range(attempts):
        att_timeout = min(timeout_s, remaining() - CPU_FALLBACK_RESERVE_S - FINAL_MARGIN_S)
        if att_timeout < MIN_ATTEMPT_S:
            log(
                f"deadline: {remaining():.0f}s left; skipping remaining accelerator "
                f"attempts to protect the CPU fallback"
            )
            note("attempts_skipped_for_deadline", remaining_s=round(remaining(), 1))
            cpu_fallback_cause = "deadline"
            break
        line = _run_worker(cmd, dict(os.environ), att_timeout, f"attempt {attempt + 1}", note=note)
        if line:
            print(_annotate_line(line, events), flush=True)
            return 0
        if attempt + 1 < attempts:
            delay = min(30 * (attempt + 1), 120)
            # Sleep only if an attempt is still feasible AFTER it — otherwise
            # the backoff just shaves the CPU fallback's reserve for nothing.
            if remaining() - delay - CPU_FALLBACK_RESERVE_S - FINAL_MARGIN_S >= MIN_ATTEMPT_S:
                log(f"retrying in {delay:.0f}s")
                note("retry_wait", wait_s=round(delay, 1))
                time.sleep(delay)
    # CPU fallback: gets whatever time is left (at least 60s even if the
    # deadline math went negative — a line late beats no line).
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    log("final attempt: falling back to JAX_PLATFORMS=cpu")
    note("cpu_fallback", cause=cpu_fallback_cause)
    line = _run_worker(cmd, env, max(60, remaining() - FINAL_MARGIN_S), "cpu fallback", note=note)
    if line:
        # Never let a CPU smoke number masquerade as the chip benchmark
        # (round-2 verdict, weak #4): tag the metric and zero the ratio.
        # (The worker also self-tags "cpu-smoke" off its actual platform;
        # this marks that the supervisor FORCED the fallback.)
        parsed = json.loads(line)
        parsed["metric"] = "cpu-fallback " + parsed["metric"]
        parsed["vs_baseline"] = 0.0
        parsed.setdefault("extra", {})["cpu_fallback"] = True
        parsed["extra"]["cpu_fallback_cause"] = cpu_fallback_cause
        parsed["extra"]["supervisor_events"] = events
        cached = _cached_hardware_evidence()
        if cached:
            # Round-5 verdict: a dead-tunnel round must not produce an
            # evidence-free artifact — carry the last-known-good hardware rows
            # (with provenance) alongside the tagged CPU number.
            parsed["extra"]["cached_hardware_evidence"] = cached
        print(json.dumps(parsed), flush=True)
        return 0
    # Even the CPU fallback failed: emit a diagnostic line so the driver parses *something*.
    extra = {"error": "all attempts failed; see stderr", "supervisor_events": events}
    cached = _cached_hardware_evidence()
    if cached:
        extra["cached_hardware_evidence"] = cached
    print(
        json.dumps(
            {
                "metric": "bench-failed",
                "value": 0.0,
                "unit": "samples/sec/chip",
                "vs_baseline": 0.0,
                "extra": extra,
            }
        ),
        flush=True,
    )
    return 0


# ------------------------------------------------------------------------------ worker
def force_readback(tree) -> float:
    """Trustworthy execution fence: read one element of the first and last array
    leaf back to host (any output of a TPU executable fences the whole program).

    On this TPU backend `jax.block_until_ready()` can return before execution
    finishes (round-2 verdict: a dispatch-only loop 'measured' MFU 3.9), so every
    timed region must end with a data-dependent host read. Indexing `leaf[0,...,0]`
    makes a scalar whose value requires the whole array to exist; `np.asarray`
    forces the device->host transfer of just that scalar.
    """
    import jax

    leaves = [l for l in jax.tree_util.tree_leaves(tree) if hasattr(l, "ndim")]
    # One element of the first and last leaf suffices: a TPU executable's outputs
    # all materialize when the program finishes, so any output fences the program
    # (and, transitively, every step it depends on). Reading every leaf would add
    # hundreds of scalar transfers to the timed region.
    total = 0.0
    for leaf in (leaves[:1] + leaves[-1:] if len(leaves) > 1 else leaves):
        total += float(np.asarray(leaf[(0,) * leaf.ndim]))
    return total


def _peak_memory_gb():
    """Peak device-memory use of the run (the reference benchmarks report peak
    memory alongside every number, benchmarks/measures_util.py) — None where
    the backend doesn't expose memory_stats (e.g. CPU)."""
    import jax

    try:
        stats = jax.devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        return round(peak / 2**30, 3) if peak else None
    except Exception:
        return None


def _last_attention_dispatch():
    from accelerate_tpu.ops import attention

    return attention.LAST_DISPATCH


def inference_bench(args):
    """Big-model-inference metric (reference benchmarks/big_model_inference.py:
    model load + per-token generation latency, README.md:27-37): reports p50 TTFT
    (compiled prefill) and per-token decode latency through the KV-cache path."""
    import jax

    from accelerate_tpu.generation import GenerationConfig, Generator

    on_accel = jax.devices()[0].platform in ("tpu", "gpu")
    families = ("llama", "gptj", "gpt-neox", "opt")
    model_name = args.model if args.model.startswith(families) else "llama-1b"
    if not on_accel:
        # CPU smoke: same family, tiny size.
        fam = next(f for f in families if model_name.startswith(f))
        model_name = f"{fam}-tiny"
    t_load = time.perf_counter()
    # Every decoder family in the reference's benchmark table (benchmarks/
    # README.md:27-37: GPT-J-6B headline 0.05 s/token fp16 on 2x Titan RTX,
    # GPT-NeoX-20B, OPT-30B) is constructible here; bf16 storage on accelerators.
    from accelerate_tpu.models import create_named_model, get_model_family

    _fam, cfg = get_model_family(model_name)
    model = create_named_model(
        model_name, seq_len=args.seq_len, param_dtype="bfloat16" if on_accel else None
    )
    load_s = time.perf_counter() - t_load

    batch = args.batch_size or 1
    prompt_len = min(args.seq_len, cfg.max_position_embeddings // 2)
    new_tokens = 32
    gen = Generator(model, max_new_tokens=new_tokens, max_length=prompt_len + new_tokens)
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, cfg.vocab_size, (batch, prompt_len)).astype(np.int32)

    # Compile every program the timed sections use: prefill, the 1-token decode
    # (TTFT loop), and the full fused decode loop (compiled per max_new).
    force_readback(gen(prompt, GenerationConfig(max_new_tokens=1)))
    force_readback(gen(prompt, GenerationConfig(max_new_tokens=new_tokens)))

    ttfts = []
    for _ in range(5):
        t0 = time.perf_counter()
        force_readback(gen(prompt, GenerationConfig(max_new_tokens=1)))
        ttfts.append(time.perf_counter() - t0)
    t0 = time.perf_counter()
    force_readback(gen(prompt, GenerationConfig(max_new_tokens=new_tokens)))
    total = time.perf_counter() - t0
    ttft_p50 = sorted(ttfts)[len(ttfts) // 2]
    per_token = (total - ttft_p50) / max(new_tokens - 1, 1)
    per_token_fallback = per_token <= 0
    if per_token_fallback:
        # Overhead-dominated run (tiny model on a noisy host): the median
        # 1-token TTFT exceeded the fused full-decode time. Fall back to the
        # whole-decode average (prefill amortized in — tagged in extra, and
        # never fed into the baseline ratio) rather than emitting a negative
        # latency.
        per_token = total / new_tokens

    # reference headline: GPT-J-6B fp16 on 2x Titan RTX = 0.05 s/token
    # (benchmarks/README.md:31); vs_baseline = reference / ours (higher is
    # better). The ratio is only apples-to-apples when the measured model IS
    # gpt-j-6b — for other sizes it is reported as 0 with the raw latency
    # left to speak for itself (a 1B model "beating" a 6B baseline is noise).
    metric = f"per-token generation latency ({model_name}, prompt {prompt_len}, bs {batch})"
    if on_accel and model_name.startswith("gptj-6b") and not per_token_fallback:
        vs_baseline = 0.05 / per_token if per_token > 0 else 0.0
    elif on_accel:
        vs_baseline = 0.0
    else:
        metric = "cpu-smoke " + metric
        vs_baseline = 0.0
    result = {
        "metric": metric,
        "value": round(per_token * 1000, 3),
        "unit": "ms/token",
        "vs_baseline": round(vs_baseline, 4),
        "extra": {
            "ttft_p50_ms": round(ttft_p50 * 1000, 3),
            "model_load_s": round(load_s, 2),
            "device_kind": jax.devices()[0].device_kind,
            "new_tokens": new_tokens,
        },
    }
    if on_accel and not model_name.startswith("gptj-6b"):
        # Distinguish "ratio suppressed" from the CPU-fallback convention of
        # vs_baseline == 0 (docs/concepts/performance.md): this IS a real
        # accelerator number, just not size-matched to the 6B baseline.
        result["extra"]["baseline_note"] = "ratio suppressed: baseline model is gptj-6b"
    if per_token_fallback:
        result["extra"]["per_token_fallback"] = True
    print(json.dumps(result))


def train_bench(args):
    import jax
    import optax

    from accelerate_tpu import Accelerator, SimpleDataLoader
    from accelerate_tpu.data_loader import BatchSampler
    from accelerate_tpu.utils.environment import get_device_peak_flops

    t0 = time.time()
    n_chips = jax.device_count()
    device_kind = jax.devices()[0].device_kind
    on_accel = jax.devices()[0].platform in ("tpu", "gpu")
    log(f"backend up in {time.time() - t0:.1f}s: {n_chips}x {device_kind}")

    compilation_config = None
    if args.remat:
        from accelerate_tpu.utils import CompilationConfig

        compilation_config = CompilationConfig(remat_policy=args.remat)
    fsdp_plugin = None
    if args.param_dtype:
        # Storage-dtype knob (FSDP plugin; a 1-chip fsdp axis shards nothing
        # but the dtype policy still applies): bf16 params+moments halve the
        # optimizer-state HBM — fp32 AdamW moments alone are ~12 GB at 1B
        # params, which is what OOM'd the round-4 llama-1b no-remat legs.
        from accelerate_tpu.utils import FullyShardedDataParallelPlugin

        fsdp_plugin = FullyShardedDataParallelPlugin(param_dtype=args.param_dtype)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        compilation_config=compilation_config,
        fsdp_plugin=fsdp_plugin,
    )
    # Report the dtype the plugin actually APPLIED, not the CLI flag: the
    # ACCELERATE_TPU_FSDP_PARAM_DTYPE env protocol overrides the constructor
    # arg in __post_init__, and a mislabeled row would corrupt the bf16-moments
    # A/B evidence.
    effective_param_dtype = (
        getattr(accelerator.state.fsdp_plugin, "param_dtype", None) or "float32"
    )

    if args.batch_size is None:
        # Headline per-chip batch. BASELINE.md's north star is an MFU floor
        # (>= 0.45), not a fixed batch; 64/chip is the standard BERT-base
        # seq-128 fine-tune size for a 16 GB chip and the best point of the
        # round-4 hardware sweep (bench_suite_r04.jsonl: MFU 0.335 @ bs 32 /
        # 0.502 @ bs 64 / 0.469 @ bs 128 at equal 500-step regions — bs 32
        # steps are too short to hide the tunneled per-call host dispatch).
        args.batch_size = 64 if on_accel else 4
    if not on_accel:
        # CPU runs are smoke/fallback runs (self-tagged below): cap the step
        # count for EVERY model so the supervisor's CPU_FALLBACK_RESERVE_S
        # budget holds under any argv (a 1500-step llama CPU run on 1 vCPU
        # would blow the dead-tunnel deadline and cost the round its line).
        # BENCH_CPU_STEP_CAP overrides; 0 disables.
        cap = _env_int("BENCH_CPU_STEP_CAP", 8)
        if cap > 0 and args.steps > cap:
            log(f"cpu backend: capping steps {args.steps} -> {cap} (BENCH_CPU_STEP_CAP)")
            args.steps = cap
    if args.steps_per_call is None:
        # Auto: small-step configs (bert-base seq 128 runs ~10-40ms/step on one
        # chip) pay one host dispatch + tunnel round trip PER STEP; the scanned
        # device loop (train_step(steps_per_call=K)) pays it once per K steps.
        # Big-step models (llama seq>=1024, ~300ms/step) don't need it. The
        # eager path ignores the knob, and --per_step_readback is a per-STEP
        # sync validation mode — both keep one step per call.
        auto_loop = on_accel and args.model.startswith("bert")
        args.steps_per_call = 10 if (auto_loop and not args.eager and not args.per_step_readback) else 1
    if args.eager and args.steps_per_call > 1:
        log("eager path ignores steps_per_call; forcing 1")
        args.steps_per_call = 1
    if args.per_step_readback and args.steps_per_call > 1:
        log("--per_step_readback syncs every step; forcing steps_per_call=1")
        args.steps_per_call = 1
    spc = max(1, args.steps_per_call)
    if args.steps % spc:
        args.steps = (args.steps // spc + 1) * spc
        log(f"steps rounded up to {args.steps} (multiple of steps_per_call={spc})")

    if args.model.startswith("bert"):
        from accelerate_tpu.models import bert_base, bert_tiny, create_bert_model

        cfg = bert_base() if args.model == "bert-base" else bert_tiny()
        model = create_bert_model(cfg, seq_len=args.seq_len)
        rng = np.random.default_rng(0)
        global_batch = args.batch_size * n_chips
        # Enough data that the timed region is ONE continuous loader pass: epoch
        # restarts tear down the prefetch thread and stall the device every
        # 2 steps otherwise, which benchmarks the restart cost, not training.
        n = global_batch * (args.trials * args.steps + (args.warmup + 2) * spc + 2)
        data = [
            {
                "input_ids": rng.integers(1, cfg.vocab_size, size=(args.seq_len,)).astype(np.int32),
                "labels": np.int64(rng.integers(0, cfg.num_labels)),
            }
            for _ in range(n)
        ]
        hidden = cfg.hidden_size
        vocab = cfg.vocab_size
    else:
        if args.model.startswith("gptj"):
            from accelerate_tpu.models.gptj import create_gptj_model, gptj_tiny

            cfg = gptj_tiny()
            model = create_gptj_model(cfg, seq_len=args.seq_len)
        else:
            from accelerate_tpu.models.llama import create_llama_model, llama_1b, llama_tiny

            cfg = llama_1b() if args.model == "llama-1b" else llama_tiny()
            model = create_llama_model(cfg, seq_len=args.seq_len)
        rng = np.random.default_rng(0)
        global_batch = args.batch_size * n_chips
        n = global_batch * (args.trials * args.steps + (args.warmup + 2) * spc + 2)
        data = [
            {"input_ids": rng.integers(1, cfg.vocab_size, size=(args.seq_len,)).astype(np.int32)} for _ in range(n)
        ]
        hidden = cfg.hidden_size
        vocab = cfg.vocab_size

    # The device-loop mode consumes spc step-batches per call: the loader
    # collates them as ONE [spc*global_batch] array (one transfer per call).
    dl = SimpleDataLoader(data, BatchSampler(range(n), global_batch * spc, drop_last=True))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adamw(1e-4), dl)
    param_count = pmodel.num_parameters

    def batches():
        while True:
            for b in pdl:
                yield b

    stream = batches()

    # Telemetry (docs/observability.md): phase-split the bench loop through the
    # Accelerator's own StepTimeline — data-wait vs dispatch vs explicit
    # readback — and charge backend-compile durations to the goodput ledger so
    # the emitted JSON says where the wall clock went (the r05 hang was
    # invisible precisely because nothing recorded this).
    timeline = accelerator.timeline
    timeline.attach_compile_listener()

    if args.eager:

        def run_steps(n):
            last_loss = None
            for _ in range(n):
                with timeline.phase("data_wait"):
                    batch = next(stream)
                with accelerator.accumulate(pmodel):
                    with timeline.phase("dispatch"):
                        last_loss = accelerator.backward(pmodel.loss, batch)
                        popt.step()
                        popt.zero_grad()
                if args.per_step_readback:
                    with timeline.phase("block"):
                        float(last_loss)
                timeline.step_done()
            return last_loss

    else:
        # train_step() is already timeline-instrumented (dispatch + step_done)
        # by the Accelerator; only the data wait needs marking here.
        step_fn = accelerator.train_step(steps_per_call=spc)

        def run_steps(n):
            last_loss = None
            # n is a step count, always a multiple of spc (steps are rounded up
            # at parse time, warmup is passed as warmup*spc).
            for _ in range(n // spc):
                with timeline.phase("data_wait"):
                    batch = next(stream)
                last_loss = step_fn(batch)
                if args.per_step_readback:
                    # step_fn already closed the step (step_done inside the
                    # Accelerator shim): record_phase attributes the readback
                    # without reopening it.
                    t_block = time.perf_counter()
                    float(last_loss)
                    timeline.record_phase("block", time.perf_counter() - t_block)
            return last_loss

    # Warmup (compile)
    t0 = time.time()
    run_steps(args.warmup * spc)
    force_readback(pmodel.params)
    log(f"warmup+compile {time.time() - t0:.1f}s")

    # Timed. Every region ends in force_readback (NOT block_until_ready — see its
    # docstring); --per_step_readback re-measures with a sync after every step to
    # validate the pipelined number (NOTE: on a tunneled TPU that adds one host
    # round-trip of latency per step, so it lower-bounds rather than reproduces it).
    # Median of `--trials` regions: single regions on the tunneled chip vary ~15%
    # run to run, and the median is robust to a one-off stall in either direction.
    elapsed_trials = []
    loss = None
    for _ in range(args.trials):
        t0 = time.perf_counter()
        loss = run_steps(args.steps)
        force_readback(pmodel.params)
        elapsed_trials.append(time.perf_counter() - t0)
    final_loss = float(loss) if loss is not None else None
    elapsed = sorted(elapsed_trials)[len(elapsed_trials) // 2]
    steps_done = args.steps

    samples = steps_done * global_batch
    samples_per_sec = samples / elapsed
    samples_per_sec_per_chip = samples_per_sec / n_chips

    # Training FLOPs ≈ 6 * non-embedding-params * tokens (fwd 2x + bwd 4x),
    # standard transformer accounting.
    embed_params = vocab * hidden
    flops_per_token = 6 * max(param_count - embed_params, 1)
    tokens_per_sec = samples_per_sec * args.seq_len
    model_flops_per_sec = flops_per_token * tokens_per_sec
    peak = get_device_peak_flops(device_kind) * n_chips
    mfu = (model_flops_per_sec / peak) if peak > 0 else None
    if mfu is not None and mfu > 1.0:
        # MFU above 1.0 is physically impossible — it means the timing fence
        # failed and we measured dispatch, not execution. Refuse to publish it.
        raise RuntimeError(
            f"measured MFU {mfu:.3f} > 1.0 — timing fence failed (dispatch-only "
            f"measurement); refusing to emit an invalid benchmark number"
        )

    # Tag by the ACTUAL platform the worker ran on, not the supervisor's forced
    # env: a worker that silently lands on the CPU backend must never emit an
    # untagged chip number or a nonzero baseline ratio.
    metric = f"samples/sec/chip ({args.model}, seq {args.seq_len}, bs {args.batch_size}/chip, {args.mixed_precision})"
    if mfu is not None:
        vs_baseline = mfu / 0.45
    else:
        metric = "cpu-smoke " + metric
        vs_baseline = 0.0

    # Telemetry block: whole-run (warmup + all trials) phase accounting. The
    # goodput ledger's "compile" entry is the warmup's trace+compile cost; a
    # large unaccounted_s with small phase sums is the r05 signature (the host
    # stalled OUTSIDE the instrumented loop, e.g. backend init).
    def _phase_ms(name):
        hist = accelerator.telemetry.get(f"train_{name}_seconds")
        if hist is None or hist.count == 0:
            return None
        return {
            "count": hist.count,
            "p50_ms": round((hist.quantile(0.5) or 0.0) * 1000, 3),
            "p99_ms": round((hist.quantile(0.99) or 0.0) * 1000, 3),
        }

    phase_stats = {
        name: _phase_ms(name) for name in ("data_wait", "dispatch", "block", "step")
    }
    telemetry_block = {
        "goodput": timeline.goodput(),
        "phases": {name: stats for name, stats in phase_stats.items() if stats is not None},
    }

    result = {
        "metric": metric,
        "value": round(samples_per_sec_per_chip, 3),
        "unit": "samples/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
        "extra": {
            "telemetry": telemetry_block,
            "device_kind": device_kind,
            "n_chips": n_chips,
            "mfu": round(mfu, 4) if mfu is not None else None,
            "tokens_per_sec": round(tokens_per_sec, 1),
            "params": param_count,
            "final_loss": final_loss,
            "steps": steps_done,
            "path": "eager" if args.eager else "fused",
            "steps_per_call": spc,
            "param_dtype": effective_param_dtype,
            "peak_hbm_gb": _peak_memory_gb(),
            # Which attention implementation the model's trace actually used —
            # proves (or disproves) that the flash kernel is on the measured path.
            "attention_impl": _last_attention_dispatch(),
        },
    }
    print(json.dumps(result))


def parse_args(argv):
    parser = argparse.ArgumentParser()
    parser.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument(
        "--model",
        default="bert-base",
        choices=[
            "bert-base",
            "bert-tiny",
            "llama-1b",
            "llama-tiny",
            "gptj-6b",
            "gptj-tiny",
            "gpt-neox-20b",
            "gpt-neox-tiny",
            "opt-30b",
            "opt-tiny",
        ],
    )
    parser.add_argument("--mode", default="train", choices=["train", "inference", "serving"])
    parser.add_argument("--batch_size", type=int, default=None, help="per-chip batch size")
    parser.add_argument("--seq_len", type=int, default=128)
    # 500-step default: a sustained region (round-3 verdict: 100-step windows
    # leave the headline sensitive to warmup/stall artifacts).
    parser.add_argument("--steps", type=int, default=500)
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument(
        "--steps_per_call",
        type=int,
        default=None,
        help="optimizer steps scanned per compiled call (device training loop); "
        "default: 10 for bert on accelerators, else 1",
    )
    parser.add_argument(
        "--attention",
        default="auto",
        choices=["auto", "xla", "flash"],
        help="force the attention implementation on the measured path (A/B the "
        "Pallas flash kernel against the XLA path at seq >= 1024); 'auto' keeps "
        "the dispatcher's choice",
    )
    parser.add_argument("--trials", type=int, default=3, help="timed regions; the median is reported")
    parser.add_argument("--mixed_precision", default="bf16")
    parser.add_argument(
        "--remat",
        default=None,
        choices=["full", "dots"],
        help="per-layer activation checkpointing policy (HBM-tight configs)",
    )
    parser.add_argument(
        "--param_dtype",
        default=None,
        choices=["float32", "bfloat16"],
        help="param/optimizer-moment storage dtype (FSDP plugin knob; bf16 "
        "halves optimizer-state HBM so llama-1b seq-1024 fits the 16 GB chip)",
    )
    parser.add_argument("--eager", action="store_true", help="use the eager backward/step path instead of the fused step")
    parser.add_argument(
        "--per_step_readback",
        action="store_true",
        help="force a host readback after every step (validation mode for the timing fence)",
    )
    parser.add_argument("--no-supervise", action="store_true", help="run in-process (no retry wrapper)")
    return parser.parse_args(argv)


def main():
    argv = sys.argv[1:]
    # --mode serving is routed BEFORE parse_args: the serving bench has its own
    # argument surface (workload shape, slots, chunk — benchmarks/serving_bench.py)
    # that this parser would reject. A pre-parser shares argparse's tokenization
    # (--mode X, --mode=X) and hands the serving bench everything else.
    pre = argparse.ArgumentParser(add_help=False)
    pre.add_argument("--mode")
    pre.add_argument("--zero-ab", action="store_true")
    pre.add_argument("--pipeline-ab", action="store_true")
    known, rest = pre.parse_known_args(argv)
    if known.zero_ab or known.pipeline_ab:
        # Training A/Bs (benchmarks/train_bench.py): 1D-replicated vs 2D-ZeRO
        # (--zero-ab) or 2D-ZeRO vs 3D-MPMD-pipeline (--pipeline-ab) — their
        # own argument surface, same pre-routing as serving/checkpoint.
        if known.mode not in (None, "train"):
            raise SystemExit("--zero-ab/--pipeline-ab are --mode train A/Bs")
        from benchmarks.train_bench import main as train_ab_main

        sys.exit(train_ab_main(rest + (["--pipeline-ab"] if known.pipeline_ab else [])))
    if known.mode == "serving":
        from benchmarks.serving_bench import main as serving_main

        sys.exit(serving_main(rest))
    if known.mode == "checkpoint":
        # Same pre-routing as serving: the checkpoint bench (sync vs async
        # save_state A/B, benchmarks/checkpoint_bench.py) owns its own args.
        from benchmarks.checkpoint_bench import main as checkpoint_main

        sys.exit(checkpoint_main(rest))
    args = parse_args(argv)
    if args.mode == "train" and args.model in ("gptj-6b", "gpt-neox-20b", "opt-30b"):
        # These sizes can't TRAIN on one 16GB chip (params + Adam state alone
        # exceed HBM); they exist for --mode inference, where they are the
        # reference benchmark's own models. Checked BEFORE any jax import.
        raise SystemExit(
            f"{args.model} is inference-only on a single chip: "
            f"run `python bench.py --mode inference --model {args.model}`"
        )
    if args.attention == "flash" and args.mode == "inference":
        # The decode path always threads a KV-cache mask, which the flash kernel
        # rejects by design — the A/B flag is for training benches.
        raise SystemExit("--attention flash applies to --mode train only (decode always carries a mask)")
    if args.attention != "auto":
        os.environ["ACCELERATE_TPU_ATTENTION_IMPL"] = args.attention
    if not args._worker and not args.no_supervise:
        sys.exit(supervise([a for a in argv if a != "--no-supervise"], total_steps=args.trials * args.steps))
    if args.mode == "inference":
        return inference_bench(args)
    return train_bench(args)


if __name__ == "__main__":
    main()

"""Out-of-process worker IPC tests — the protocol layer in ISOLATION.

Everything here runs tier-1/CPU-fast with fake peers (pipes, scripted
transports, an in-process `EngineHost`): no real subprocess is ever spawned.
What is pinned:

  1. framing: length-prefixed JSON round trips; torn/short frames surface as
     `WorkerGone` (dead peer), oversized/undecodable ones as `FrameError`
     (protocol bug), and a silent peer as `FrameTimeout` — three distinct
     failures because the caller handles them differently;
  2. `EngineHost` op dispatch against a real in-process engine: the typed
     error replies (QueueFull/EngineClosed/ValueError/KeyError) that let the
     client re-raise the engine's exact exception types;
  3. `SubprocessEngine` mirror semantics over a scripted fake transport:
     worker-dies-mid-stream escalates to `WorkerGone` from step() (the
     router's replica-death language), heartbeat expiry kills the worker,
     submit() after death raises `EngineClosed` (try-next-replica), and a
     cancel racing a final token adopts the worker's terminal record instead
     of double-finishing;
  4. `WorkerChaos` journal pre-consumption: a respawned worker re-arming the
     same env plan must NOT re-kill itself at the same trigger.
"""

import json
import os
import struct

import numpy as np
import pytest

from accelerate_tpu.worker import (
    FrameError,
    FrameTimeout,
    SubprocessEngine,
    WorkerGone,
    recv_frame,
    request_from_wire,
    request_to_wire,
    result_to_wire,
    send_frame,
)

pytestmark = pytest.mark.fleet


# ------------------------------------------------------------------ framing
def _pipe():
    r, w = os.pipe()
    return r, w


def test_frame_round_trip_and_multiple_frames():
    r, w = _pipe()
    try:
        payloads = [{"op": "ping"}, {"op": "step", "events": [[1, [2, 3]]], "n": 0}]
        for p in payloads:
            send_frame(w, p)
        for p in payloads:
            assert recv_frame(r, timeout_s=5.0) == p
    finally:
        os.close(r), os.close(w)


def test_torn_frame_mid_payload_is_worker_gone():
    r, w = _pipe()
    try:
        # Header promises 100 bytes; the peer dies after 3.
        os.write(w, struct.pack(">I", 100) + b"abc")
        os.close(w)
        with pytest.raises(WorkerGone, match="mid-frame payload"):
            recv_frame(r, timeout_s=5.0)
    finally:
        os.close(r)


def test_eof_at_frame_boundary_is_worker_gone():
    r, w = _pipe()
    os.close(w)
    try:
        with pytest.raises(WorkerGone, match="closed the stream"):
            recv_frame(r, timeout_s=5.0)
    finally:
        os.close(r)


def test_short_header_is_worker_gone():
    r, w = _pipe()
    try:
        os.write(w, b"\x00\x00")  # 2 of 4 header bytes, then death
        os.close(w)
        with pytest.raises(WorkerGone, match="mid-frame header"):
            recv_frame(r, timeout_s=5.0)
    finally:
        os.close(r)


def test_oversized_and_undecodable_frames_are_frame_errors():
    r, w = _pipe()
    try:
        os.write(w, struct.pack(">I", (64 << 20) + 1))
        with pytest.raises(FrameError, match="exceeds"):
            recv_frame(r, timeout_s=5.0)
        bad = b"\xff\xfe not json"
        os.write(w, struct.pack(">I", len(bad)) + bad)
        with pytest.raises(FrameError, match="undecodable"):
            recv_frame(r, timeout_s=5.0)
        with pytest.raises(FrameError, match="exceeds"):
            send_frame(w, {"blob": "x" * (64 << 20)})
    finally:
        os.close(r), os.close(w)


def test_silent_peer_is_frame_timeout():
    r, w = _pipe()
    try:
        with pytest.raises(FrameTimeout):
            recv_frame(r, timeout_s=0.05)
        # ... and a timeout mid-frame (header arrived, payload never does).
        os.write(w, struct.pack(">I", 10) + b"abc")
        with pytest.raises(FrameTimeout, match="payload"):
            recv_frame(r, timeout_s=0.05)
    finally:
        os.close(r), os.close(w)


def test_request_and_result_wire_codecs_round_trip():
    from accelerate_tpu.serving import Request, RequestResult

    req = Request(
        7, np.asarray([3, 1, 4], np.int32), max_new_tokens=5, temperature=0.5,
        repetition_penalty=1.1, eos_token_id=2, deadline_s=3.5,
        tenant="team-a", priority=4,
    )
    back = request_from_wire(json.loads(json.dumps(request_to_wire(req))))
    assert back.request_id == 7 and back.max_new_tokens == 5
    np.testing.assert_array_equal(back.input_ids, [3, 1, 4])
    assert back.temperature == 0.5 and back.eos_token_id == 2
    assert back.deadline_s == 3.5 and back.tenant == "team-a" and back.priority == 4

    res = RequestResult(7, tokens=[1, 2], finished=True, finish_reason="eos")
    wire = result_to_wire(res)
    assert wire == {
        "request_id": 7, "tokens": [1, 2], "finished": True,
        "finish_reason": "eos", "error": None,
    }


# ------------------------------------------------------------------ EngineHost
def _tiny_engine(**overrides):
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
    from accelerate_tpu.serving import ContinuousBatcher

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0,
    )
    model = create_llama_model(cfg, seq_len=32)
    kwargs = dict(num_slots=2, max_length=64, chunk_size=4, max_queue=2,
                  paged=True, page_size=4)
    kwargs.update(overrides)
    return ContinuousBatcher(model, **kwargs)


def test_engine_host_op_round_trip_without_subprocess():
    """The worker side of the protocol against a REAL engine, no process: ops
    map 1:1 to the engine surface and error replies carry typed kinds."""
    from accelerate_tpu.worker import EngineHost

    host = EngineHost(_tiny_engine(), worker_id=3)
    rng = np.random.default_rng(0)
    req = {"op": "submit", "request": request_to_wire(
        __import__("accelerate_tpu.serving", fromlist=["Request"]).Request(
            0, rng.integers(1, 128, (5,)).astype(np.int32), max_new_tokens=4
        )
    )}
    assert host.handle({"op": "ping"})["ok"]
    assert host.handle(req)["ok"]
    # duplicate id -> typed value_error, engine untouched
    dup = host.handle(req)
    assert not dup["ok"] and dup["kind"] == "value_error"
    # queue-full backpressure maps to its own kind (max_queue=2: id 1 fits,
    # ids 2 and 3 overflow the bounded wait queue before any step admits)
    for i in (1, 2, 3):
        reply = host.handle({"op": "submit", "request": {**req["request"], "request_id": i}})
    assert not reply["ok"] and reply["kind"] == "queue_full"
    events, finished = [], []
    while host.engine.pending:
        step = host.handle({"op": "step"})
        assert step["ok"]
        events.extend(step["events"])
        finished.extend(step["finished"])
    assert {f["request_id"] for f in finished} == {0, 1}
    assert all(f["finish_reason"] == "length" for f in finished)
    # the finished list is a DELTA: a second step reports nothing new
    assert host.handle({"op": "step"})["finished"] == []
    streamed = {}
    for rid, toks in events:
        streamed.setdefault(rid, []).extend(toks)
    for f in finished:
        assert streamed[f["request_id"]] == f["tokens"]
    stats = host.handle({"op": "stats"})["stats"]
    assert stats["worker"]["worker_id"] == 3 and stats["worker"]["pid"] == os.getpid()
    released = host.handle({"op": "release", "request_id": 0})
    assert released["ok"] and released["result"]["finish_reason"] == "length"
    missing = host.handle({"op": "release", "request_id": 0})
    assert not missing["ok"] and missing["kind"] == "key_error"
    unknown = host.handle({"op": "frobnicate"})
    assert not unknown["ok"] and unknown["kind"] == "value_error"
    closed = host.handle({"op": "close"})
    assert closed["ok"]
    after = host.handle({"op": "submit", "request": {**req["request"], "request_id": 9}})
    assert not after["ok"] and after["kind"] == "engine_closed"


# ------------------------------------------------------------------ fake transport
class FakeTransport:
    """Scripted worker: a queue of canned replies (or callables computing one
    from the sent message), plus a journal of everything sent."""

    def __init__(self, replies):
        self.replies = list(replies)
        self.sent = []
        self.pid = 4242
        self.killed = False
        self.closed = False

    def send(self, obj):
        if self.killed:
            raise WorkerGone("fake worker killed")
        self.sent.append(obj)

    def recv(self, timeout_s):
        if not self.replies:
            raise WorkerGone("fake worker script exhausted")
        reply = self.replies.pop(0)
        if callable(reply):
            reply = reply(self.sent[-1] if self.sent else None)
        if isinstance(reply, BaseException):
            raise reply
        return reply

    def alive(self):
        return not self.killed

    def kill(self):
        self.killed = True

    def close(self, timeout_s=10.0):
        self.closed = True


READY = {"ok": True, "ready": True, "pid": 4242, "worker_id": 0, "warm": True, "warmed": [1, 2]}


def _fake_engine(*replies, **kwargs):
    return SubprocessEngine(
        {"name": "fake"}, {"max_queue": 4}, _transport=FakeTransport([READY, *replies]),
        **kwargs,
    )


def _ok_submit(msg):
    return {"ok": True, "load": 1, "queue_depth": 0, "pending": True}


def test_fake_worker_submit_step_release_mirror():
    from accelerate_tpu.serving import Request

    eng = _fake_engine(
        _ok_submit,
        {"ok": True, "events": [[5, [10, 11]]], "finished": [],
         "load": 1, "queue_depth": 0, "pending": True},
        {"ok": True, "events": [[5, [12]]],
         "finished": [{"request_id": 5, "tokens": [10, 11, 12], "finished": True,
                       "finish_reason": "length", "error": None}],
         "load": 0, "queue_depth": 0, "pending": False},
        {"ok": True, "result": {"request_id": 5, "tokens": [10, 11, 12], "finished": True,
                                "finish_reason": "length", "error": None}},
    )
    assert eng.ready_info["warmed"] == [1, 2]
    eng.submit(Request(5, np.asarray([1, 2], np.int32), max_new_tokens=3))
    assert eng.load == 1 and eng.pending
    assert eng.step() == [(5, [10, 11])]
    assert eng.results[5].tokens == [10, 11]
    assert eng.step() == [(5, [12])]
    result = eng.results[5]
    assert result.finished and result.finish_reason == "length"
    assert result.tokens == [10, 11, 12]
    assert not eng.pending
    released = eng.release(5)
    assert released is result and 5 not in eng.results


def test_fake_worker_error_kinds_reraise_engine_types():
    from accelerate_tpu.serving import EngineClosed, QueueFull, Request

    eng = _fake_engine(
        {"ok": False, "kind": "queue_full", "error": "full"},
        {"ok": False, "kind": "value_error", "error": "empty prompt"},
        {"ok": False, "kind": "engine_closed", "error": "closed"},
    )
    req = Request(1, np.asarray([1], np.int32), max_new_tokens=2)
    with pytest.raises(QueueFull):
        eng.submit(req)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit(req)
    with pytest.raises(EngineClosed):
        eng.submit(req)
    assert not eng.results  # no mirror is created for a rejected submit


def test_worker_dies_mid_stream_escalates_to_worker_gone():
    """EOF mid-conversation: the step raises WorkerGone (the router's replica
    -death signal), the engine stays pending (so the router WILL step it and
    observe the death), and submit() refuses with EngineClosed so the router
    tries the next replica."""
    from accelerate_tpu.serving import EngineClosed, Request

    eng = _fake_engine(
        _ok_submit,
        {"ok": True, "events": [[1, [7]]], "finished": [],
         "load": 1, "queue_depth": 0, "pending": True},
        WorkerGone("peer closed the stream mid-frame payload (3/100 bytes)"),
    )
    eng.submit(Request(1, np.asarray([1, 2], np.int32), max_new_tokens=4))
    assert eng.step() == [(1, [7])]
    with pytest.raises(WorkerGone):
        eng.step()
    assert eng.transport.killed  # the dead process is reaped, not leaked
    assert eng.pending  # unfinished mirror keeps the replica steppable
    with pytest.raises(EngineClosed):
        eng.submit(Request(2, np.asarray([3], np.int32), max_new_tokens=2))
    with pytest.raises(WorkerGone):
        eng.step()  # dead stays dead: every later step re-raises


def test_heartbeat_expiry_kills_hung_worker():
    """A worker that stops answering inside step_timeout_s is killed and
    surfaced as WorkerGone — a hang and a death are the same failure to the
    fleet."""
    from accelerate_tpu.serving import Request

    eng = _fake_engine(
        _ok_submit,
        FrameTimeout("timed out waiting for frame header (0/4 bytes)"),
        step_timeout_s=0.01,
    )
    eng.submit(Request(1, np.asarray([1], np.int32), max_new_tokens=2))
    with pytest.raises(WorkerGone, match="missed its step deadline"):
        eng.step()
    assert eng.transport.killed


def test_cancel_racing_final_token_adopts_worker_record():
    """cancel() arriving after the worker already finished the request must
    adopt the worker's terminal record (reason + full tokens), return False
    like the engine, and never double-finish."""
    from accelerate_tpu.serving import Request

    eng = _fake_engine(
        _ok_submit,
        {"ok": True, "cancelled": False,
         "result": {"request_id": 1, "tokens": [4, 5, 2], "finished": True,
                    "finish_reason": "eos", "error": None},
         "load": 0, "queue_depth": 0, "pending": False},
    )
    eng.submit(Request(1, np.asarray([1], np.int32), max_new_tokens=8))
    assert eng.cancel(1) is False
    result = eng.results[1]
    assert result.finish_reason == "eos" and result.tokens == [4, 5, 2]
    # and the true-cancel path:
    eng2 = _fake_engine(
        _ok_submit,
        {"ok": True, "cancelled": True,
         "result": {"request_id": 1, "tokens": [9], "finished": True,
                    "finish_reason": "cancelled", "error": None},
         "load": 0, "queue_depth": 0, "pending": False},
    )
    eng2.submit(Request(1, np.asarray([1], np.int32), max_new_tokens=8))
    assert eng2.cancel(1) is True
    assert eng2.results[1].finish_reason == "cancelled"
    assert eng2.results[1].tokens == [9]  # partial tokens adopted from the worker
    with pytest.raises(KeyError):
        eng2.cancel(99)


def test_close_finishes_mirrors_and_closes_transport():
    from accelerate_tpu.serving import EngineClosed, Request

    eng = _fake_engine(
        _ok_submit,
        {"ok": True, "finished": [{"request_id": 1, "tokens": [3], "finished": True,
                                   "finish_reason": "cancelled", "error": None}]},
    )
    eng.submit(Request(1, np.asarray([1], np.int32), max_new_tokens=2))
    results = eng.close()
    assert results[1].finish_reason == "cancelled"
    assert eng.transport.closed and eng.closed
    with pytest.raises(EngineClosed):
        eng.submit(Request(2, np.asarray([1], np.int32), max_new_tokens=2))
    assert eng.step() == []  # closed engine steps to nothing, like the engine
    assert eng.close() is results  # idempotent


# ------------------------------------------------------------------ worker chaos
def test_worker_chaos_preconsumes_journal_on_restart(tmp_path, monkeypatch):
    """The livelock guard: a worker that already fired its SIGKILL (journaled
    before death) and was respawned with the SAME env plan must not fire it
    again at the same trigger."""
    from accelerate_tpu import worker as worker_mod
    from accelerate_tpu.chaos.plan import FaultEvent, FaultPlan
    from accelerate_tpu.worker import WorkerChaos

    kills = []
    monkeypatch.setattr(worker_mod.os, "kill", lambda pid, sig: kills.append((pid, sig)))
    monkeypatch.setattr(worker_mod.time, "sleep", lambda s: None)
    journal = str(tmp_path / "journal.jsonl")
    plan = FaultPlan(name="t", events=[
        FaultEvent(kind="fleet.worker_kill", path_pattern="worker_0", at_call=2),
    ])
    first = WorkerChaos(plan, 0, journal_path=journal)
    first.poll("step")
    assert not kills
    first.poll("step")
    assert len(kills) == 1  # fired at its trigger — and journaled BEFORE the kill
    entries = [json.loads(l) for l in open(journal)]
    assert entries and entries[0]["kind"] == "fleet.worker_kill"
    assert entries[0]["worker"] == "worker_0"

    # The respawn: same plan from env, same journal -> pre-consumed, no re-kill.
    respawn = WorkerChaos(plan, 0, journal_path=journal)
    for _ in range(6):
        respawn.poll("step")
    assert len(kills) == 1
    # A DIFFERENT worker's chaos is unaffected by worker_0's history.
    other = WorkerChaos(plan, 1, journal_path=journal)
    for _ in range(6):
        other.poll("step")
    assert len(kills) == 1  # path_pattern worker_0 never matches worker_1

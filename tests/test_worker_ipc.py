"""Out-of-process worker IPC tests — the protocol layer in ISOLATION.

Everything here runs tier-1/CPU-fast with fake peers (pipes, scripted
transports, an in-process `EngineHost`): no real subprocess is ever spawned.
What is pinned:

  1. framing: length-prefixed JSON round trips; torn/short frames surface as
     `WorkerGone` (dead peer), oversized/undecodable ones as `FrameError`
     (protocol bug), and a silent peer as `FrameTimeout` — three distinct
     failures because the caller handles them differently;
  2. `EngineHost` op dispatch against a real in-process engine: the typed
     error replies (QueueFull/EngineClosed/ValueError/KeyError) that let the
     client re-raise the engine's exact exception types;
  3. `SubprocessEngine` mirror semantics over a scripted fake transport:
     worker-dies-mid-stream escalates to `WorkerGone` from step() (the
     router's replica-death language), heartbeat expiry kills the worker,
     submit() after death raises `EngineClosed` (try-next-replica), and a
     cancel racing a final token adopts the worker's terminal record instead
     of double-finishing;
  4. `WorkerChaos` journal pre-consumption: a respawned worker re-arming the
     same env plan must NOT re-kill itself at the same trigger;
  5. the socket listener's registration handshake against a REAL loopback TCP
     listener (thread, stub engine): epoch validation (a stale link gets a
     typed `stale_epoch` error frame and the live stream is untouched), and
     the half-open corner — a peer that vanished without closing must never
     block a newer registration epoch;
  6. the reconnect state machine over a scripted socket-shaped transport: a
     torn frame enters `reconnecting` (not death), streams reconcile exactly
     once (resume-from-tail / re-dispatch / `replica_lost` on divergence, a
     tear mid-reconcile retries idempotently), cancel() during the outage
     queues the worker-side cancel for after the re-handshake, and only an
     exhausted budget escalates to `WorkerGone`.
"""

import json
import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from accelerate_tpu.worker import (
    PROTOCOL_VERSION,
    FrameError,
    FrameTimeout,
    SocketTransport,
    SubprocessEngine,
    WorkerGone,
    recv_frame,
    request_from_wire,
    request_to_wire,
    result_to_wire,
    send_frame,
)

pytestmark = pytest.mark.fleet


# ------------------------------------------------------------------ framing
def _pipe():
    r, w = os.pipe()
    return r, w


def test_frame_round_trip_and_multiple_frames():
    r, w = _pipe()
    try:
        payloads = [{"op": "ping"}, {"op": "step", "events": [[1, [2, 3]]], "n": 0}]
        for p in payloads:
            send_frame(w, p)
        for p in payloads:
            assert recv_frame(r, timeout_s=5.0) == p
    finally:
        os.close(r), os.close(w)


def test_torn_frame_mid_payload_is_worker_gone():
    r, w = _pipe()
    try:
        # Header promises 100 bytes; the peer dies after 3.
        os.write(w, struct.pack(">I", 100) + b"abc")
        os.close(w)
        with pytest.raises(WorkerGone, match="mid-frame payload"):
            recv_frame(r, timeout_s=5.0)
    finally:
        os.close(r)


def test_eof_at_frame_boundary_is_worker_gone():
    r, w = _pipe()
    os.close(w)
    try:
        with pytest.raises(WorkerGone, match="closed the stream"):
            recv_frame(r, timeout_s=5.0)
    finally:
        os.close(r)


def test_short_header_is_worker_gone():
    r, w = _pipe()
    try:
        os.write(w, b"\x00\x00")  # 2 of 4 header bytes, then death
        os.close(w)
        with pytest.raises(WorkerGone, match="mid-frame header"):
            recv_frame(r, timeout_s=5.0)
    finally:
        os.close(r)


def test_oversized_and_undecodable_frames_are_frame_errors():
    r, w = _pipe()
    try:
        os.write(w, struct.pack(">I", (64 << 20) + 1))
        with pytest.raises(FrameError, match="exceeds"):
            recv_frame(r, timeout_s=5.0)
        bad = b"\xff\xfe not json"
        os.write(w, struct.pack(">I", len(bad)) + bad)
        with pytest.raises(FrameError, match="undecodable"):
            recv_frame(r, timeout_s=5.0)
        with pytest.raises(FrameError, match="exceeds"):
            send_frame(w, {"blob": "x" * (64 << 20)})
    finally:
        os.close(r), os.close(w)


def test_silent_peer_is_frame_timeout():
    r, w = _pipe()
    try:
        with pytest.raises(FrameTimeout):
            recv_frame(r, timeout_s=0.05)
        # ... and a timeout mid-frame (header arrived, payload never does).
        os.write(w, struct.pack(">I", 10) + b"abc")
        with pytest.raises(FrameTimeout, match="payload"):
            recv_frame(r, timeout_s=0.05)
    finally:
        os.close(r), os.close(w)


def test_request_and_result_wire_codecs_round_trip():
    from accelerate_tpu.serving import Request, RequestResult

    req = Request(
        7, np.asarray([3, 1, 4], np.int32), max_new_tokens=5, temperature=0.5,
        repetition_penalty=1.1, eos_token_id=2, deadline_s=3.5,
        tenant="team-a", priority=4,
    )
    back = request_from_wire(json.loads(json.dumps(request_to_wire(req))))
    assert back.request_id == 7 and back.max_new_tokens == 5
    np.testing.assert_array_equal(back.input_ids, [3, 1, 4])
    assert back.temperature == 0.5 and back.eos_token_id == 2
    assert back.deadline_s == 3.5 and back.tenant == "team-a" and back.priority == 4

    res = RequestResult(7, tokens=[1, 2], finished=True, finish_reason="eos")
    wire = result_to_wire(res)
    assert wire == {
        "request_id": 7, "tokens": [1, 2], "finished": True,
        "finish_reason": "eos", "error": None,
    }


# ------------------------------------------------------------------ EngineHost
def _tiny_engine(**overrides):
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
    from accelerate_tpu.serving import ContinuousBatcher

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0,
    )
    model = create_llama_model(cfg, seq_len=32)
    kwargs = dict(num_slots=2, max_length=64, chunk_size=4, max_queue=2,
                  paged=True, page_size=4)
    kwargs.update(overrides)
    return ContinuousBatcher(model, **kwargs)


def test_engine_host_op_round_trip_without_subprocess():
    """The worker side of the protocol against a REAL engine, no process: ops
    map 1:1 to the engine surface and error replies carry typed kinds."""
    from accelerate_tpu.worker import EngineHost

    host = EngineHost(_tiny_engine(), worker_id=3)
    rng = np.random.default_rng(0)
    req = {"op": "submit", "request": request_to_wire(
        __import__("accelerate_tpu.serving", fromlist=["Request"]).Request(
            0, rng.integers(1, 128, (5,)).astype(np.int32), max_new_tokens=4
        )
    )}
    assert host.handle({"op": "ping"})["ok"]
    assert host.handle(req)["ok"]
    # duplicate id -> typed value_error, engine untouched
    dup = host.handle(req)
    assert not dup["ok"] and dup["kind"] == "value_error"
    # queue-full backpressure maps to its own kind (max_queue=2: id 1 fits,
    # ids 2 and 3 overflow the bounded wait queue before any step admits)
    for i in (1, 2, 3):
        reply = host.handle({"op": "submit", "request": {**req["request"], "request_id": i}})
    assert not reply["ok"] and reply["kind"] == "queue_full"
    events, finished = [], []
    while host.engine.pending:
        step = host.handle({"op": "step"})
        assert step["ok"]
        events.extend(step["events"])
        finished.extend(step["finished"])
    assert {f["request_id"] for f in finished} == {0, 1}
    assert all(f["finish_reason"] == "length" for f in finished)
    # the finished list is a DELTA: a second step reports nothing new
    assert host.handle({"op": "step"})["finished"] == []
    streamed = {}
    for rid, toks in events:
        streamed.setdefault(rid, []).extend(toks)
    for f in finished:
        assert streamed[f["request_id"]] == f["tokens"]
    stats = host.handle({"op": "stats"})["stats"]
    assert stats["worker"]["worker_id"] == 3 and stats["worker"]["pid"] == os.getpid()
    released = host.handle({"op": "release", "request_id": 0})
    assert released["ok"] and released["result"]["finish_reason"] == "length"
    missing = host.handle({"op": "release", "request_id": 0})
    assert not missing["ok"] and missing["kind"] == "key_error"
    unknown = host.handle({"op": "frobnicate"})
    assert not unknown["ok"] and unknown["kind"] == "value_error"
    closed = host.handle({"op": "close"})
    assert closed["ok"]
    after = host.handle({"op": "submit", "request": {**req["request"], "request_id": 9}})
    assert not after["ok"] and after["kind"] == "engine_closed"


# ------------------------------------------------------------------ fake transport
class FakeTransport:
    """Scripted worker: a queue of canned replies (or callables computing one
    from the sent message), plus a journal of everything sent."""

    def __init__(self, replies):
        self.replies = list(replies)
        self.sent = []
        self.pid = 4242
        self.killed = False
        self.closed = False

    def send(self, obj):
        if self.killed:
            raise WorkerGone("fake worker killed")
        self.sent.append(obj)

    def recv(self, timeout_s):
        if not self.replies:
            raise WorkerGone("fake worker script exhausted")
        reply = self.replies.pop(0)
        if callable(reply):
            reply = reply(self.sent[-1] if self.sent else None)
        if isinstance(reply, BaseException):
            raise reply
        return reply

    def alive(self):
        return not self.killed

    def kill(self):
        self.killed = True

    def close(self, timeout_s=10.0):
        self.closed = True


READY = {"ok": True, "ready": True, "pid": 4242, "worker_id": 0, "warm": True, "warmed": [1, 2]}


def _fake_engine(*replies, **kwargs):
    return SubprocessEngine(
        {"name": "fake"}, {"max_queue": 4}, _transport=FakeTransport([READY, *replies]),
        **kwargs,
    )


def _ok_submit(msg):
    return {"ok": True, "load": 1, "queue_depth": 0, "pending": True}


def test_fake_worker_submit_step_release_mirror():
    from accelerate_tpu.serving import Request

    eng = _fake_engine(
        _ok_submit,
        {"ok": True, "events": [[5, [10, 11]]], "finished": [],
         "load": 1, "queue_depth": 0, "pending": True},
        {"ok": True, "events": [[5, [12]]],
         "finished": [{"request_id": 5, "tokens": [10, 11, 12], "finished": True,
                       "finish_reason": "length", "error": None}],
         "load": 0, "queue_depth": 0, "pending": False},
        {"ok": True, "result": {"request_id": 5, "tokens": [10, 11, 12], "finished": True,
                                "finish_reason": "length", "error": None}},
    )
    assert eng.ready_info["warmed"] == [1, 2]
    eng.submit(Request(5, np.asarray([1, 2], np.int32), max_new_tokens=3))
    assert eng.load == 1 and eng.pending
    assert eng.step() == [(5, [10, 11])]
    assert eng.results[5].tokens == [10, 11]
    assert eng.step() == [(5, [12])]
    result = eng.results[5]
    assert result.finished and result.finish_reason == "length"
    assert result.tokens == [10, 11, 12]
    assert not eng.pending
    released = eng.release(5)
    assert released is result and 5 not in eng.results


def test_fake_worker_error_kinds_reraise_engine_types():
    from accelerate_tpu.serving import EngineClosed, QueueFull, Request

    eng = _fake_engine(
        {"ok": False, "kind": "queue_full", "error": "full"},
        {"ok": False, "kind": "value_error", "error": "empty prompt"},
        {"ok": False, "kind": "engine_closed", "error": "closed"},
    )
    req = Request(1, np.asarray([1], np.int32), max_new_tokens=2)
    with pytest.raises(QueueFull):
        eng.submit(req)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit(req)
    with pytest.raises(EngineClosed):
        eng.submit(req)
    assert not eng.results  # no mirror is created for a rejected submit


def test_worker_dies_mid_stream_escalates_to_worker_gone():
    """EOF mid-conversation: the step raises WorkerGone (the router's replica
    -death signal), the engine stays pending (so the router WILL step it and
    observe the death), and submit() refuses with EngineClosed so the router
    tries the next replica."""
    from accelerate_tpu.serving import EngineClosed, Request

    eng = _fake_engine(
        _ok_submit,
        {"ok": True, "events": [[1, [7]]], "finished": [],
         "load": 1, "queue_depth": 0, "pending": True},
        WorkerGone("peer closed the stream mid-frame payload (3/100 bytes)"),
    )
    eng.submit(Request(1, np.asarray([1, 2], np.int32), max_new_tokens=4))
    assert eng.step() == [(1, [7])]
    with pytest.raises(WorkerGone):
        eng.step()
    assert eng.transport.killed  # the dead process is reaped, not leaked
    assert eng.pending  # unfinished mirror keeps the replica steppable
    with pytest.raises(EngineClosed):
        eng.submit(Request(2, np.asarray([3], np.int32), max_new_tokens=2))
    with pytest.raises(WorkerGone):
        eng.step()  # dead stays dead: every later step re-raises


def test_heartbeat_expiry_kills_hung_worker():
    """A worker that stops answering inside step_timeout_s is killed and
    surfaced as WorkerGone — a hang and a death are the same failure to the
    fleet."""
    from accelerate_tpu.serving import Request

    eng = _fake_engine(
        _ok_submit,
        FrameTimeout("timed out waiting for frame header (0/4 bytes)"),
        step_timeout_s=0.01,
    )
    eng.submit(Request(1, np.asarray([1], np.int32), max_new_tokens=2))
    with pytest.raises(WorkerGone, match="missed its step deadline"):
        eng.step()
    assert eng.transport.killed


def test_cancel_racing_final_token_adopts_worker_record():
    """cancel() arriving after the worker already finished the request must
    adopt the worker's terminal record (reason + full tokens), return False
    like the engine, and never double-finish."""
    from accelerate_tpu.serving import Request

    eng = _fake_engine(
        _ok_submit,
        {"ok": True, "cancelled": False,
         "result": {"request_id": 1, "tokens": [4, 5, 2], "finished": True,
                    "finish_reason": "eos", "error": None},
         "load": 0, "queue_depth": 0, "pending": False},
    )
    eng.submit(Request(1, np.asarray([1], np.int32), max_new_tokens=8))
    assert eng.cancel(1) is False
    result = eng.results[1]
    assert result.finish_reason == "eos" and result.tokens == [4, 5, 2]
    # and the true-cancel path:
    eng2 = _fake_engine(
        _ok_submit,
        {"ok": True, "cancelled": True,
         "result": {"request_id": 1, "tokens": [9], "finished": True,
                    "finish_reason": "cancelled", "error": None},
         "load": 0, "queue_depth": 0, "pending": False},
    )
    eng2.submit(Request(1, np.asarray([1], np.int32), max_new_tokens=8))
    assert eng2.cancel(1) is True
    assert eng2.results[1].finish_reason == "cancelled"
    assert eng2.results[1].tokens == [9]  # partial tokens adopted from the worker
    with pytest.raises(KeyError):
        eng2.cancel(99)


def test_close_finishes_mirrors_and_closes_transport():
    from accelerate_tpu.serving import EngineClosed, Request

    eng = _fake_engine(
        _ok_submit,
        {"ok": True, "finished": [{"request_id": 1, "tokens": [3], "finished": True,
                                   "finish_reason": "cancelled", "error": None}]},
    )
    eng.submit(Request(1, np.asarray([1], np.int32), max_new_tokens=2))
    results = eng.close()
    assert results[1].finish_reason == "cancelled"
    assert eng.transport.closed and eng.closed
    with pytest.raises(EngineClosed):
        eng.submit(Request(2, np.asarray([1], np.int32), max_new_tokens=2))
    assert eng.step() == []  # closed engine steps to nothing, like the engine
    assert eng.close() is results  # idempotent


# ------------------------------------------------------------------ worker chaos
def test_worker_chaos_preconsumes_journal_on_restart(tmp_path, monkeypatch):
    """The livelock guard: a worker that already fired its SIGKILL (journaled
    before death) and was respawned with the SAME env plan must not fire it
    again at the same trigger."""
    from accelerate_tpu import worker as worker_mod
    from accelerate_tpu.chaos.plan import FaultEvent, FaultPlan
    from accelerate_tpu.worker import WorkerChaos

    kills = []
    monkeypatch.setattr(worker_mod.os, "kill", lambda pid, sig: kills.append((pid, sig)))
    monkeypatch.setattr(worker_mod.time, "sleep", lambda s: None)
    journal = str(tmp_path / "journal.jsonl")
    plan = FaultPlan(name="t", events=[
        FaultEvent(kind="fleet.worker_kill", path_pattern="worker_0", at_call=2),
    ])
    first = WorkerChaos(plan, 0, journal_path=journal)
    first.poll("step")
    assert not kills
    first.poll("step")
    assert len(kills) == 1  # fired at its trigger — and journaled BEFORE the kill
    entries = [json.loads(l) for l in open(journal)]
    assert entries and entries[0]["kind"] == "fleet.worker_kill"
    assert entries[0]["worker"] == "worker_0"

    # The respawn: same plan from env, same journal -> pre-consumed, no re-kill.
    respawn = WorkerChaos(plan, 0, journal_path=journal)
    for _ in range(6):
        respawn.poll("step")
    assert len(kills) == 1
    # A DIFFERENT worker's chaos is unaffected by worker_0's history.
    other = WorkerChaos(plan, 1, journal_path=journal)
    for _ in range(6):
        other.poll("step")
    assert len(kills) == 1  # path_pattern worker_0 never matches worker_1


def test_frame_errors_carry_peer_op_and_byte_context():
    """Satellite diagnostics pin: every framing failure names the peer, the
    op in flight, and the bytes read so far — a partition post-mortem must say
    WHICH worker's WHICH request tore, not just that bytes stopped."""
    r, w = _pipe()
    try:
        os.write(w, struct.pack(">I", 100) + b"abc")
        os.close(w)
        with pytest.raises(WorkerGone) as err:
            recv_frame(r, timeout_s=5.0, peer="10.0.0.9:7007/worker_3", op="step")
        msg = str(err.value)
        assert "peer=10.0.0.9:7007/worker_3" in msg
        assert "op=step" in msg and "3/100 bytes" in msg
    finally:
        os.close(r)
    r2, w2 = _pipe()
    try:
        with pytest.raises(FrameTimeout, match=r"peer=w op=reconcile"):
            recv_frame(r2, timeout_s=0.02, peer="w", op="reconcile")
    finally:
        os.close(r2), os.close(w2)


# ------------------------------------------------------- socket listener
class _StubEngine:
    """Minimal engine surface for listener handshake tests — the register
    path never touches the engine beyond the load view and close()."""

    def __init__(self):
        self.load = 0
        self.queue_depth = 0
        self.pending = False
        self.results = {}
        self.trace_counts = {}
        self.stats = {}
        self.closed = False

    def close(self):
        self.closed = True
        return {}


def _listener_worker(worker_id=0, heartbeat=10.0):
    """A real socket-mode worker loop (loopback listener + serve_listener in a
    daemon thread) over a stub engine; returns (address, thread, exit_codes)."""
    from accelerate_tpu.worker import EngineHost, serve_listener

    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.bind(("127.0.0.1", 0))
    listener.listen(4)
    host = EngineHost(_StubEngine(), worker_id=worker_id)
    codes = []

    def _run():
        try:
            codes.append(serve_listener(host, listener, heartbeat_deadline_s=heartbeat))
        finally:
            listener.close()

    thread = threading.Thread(target=_run, daemon=True)
    thread.start()
    return listener.getsockname(), thread, codes


def test_listener_handshake_and_stale_epoch_rejected():
    """Registration contract over real TCP: a fresh epoch registers and gets
    the identity/attestation ready frame; a SECOND link arriving at an epoch
    that is not newer is a stale controller (e.g. a half-open socket's owner
    waking up after we already re-registered) — it gets a typed `stale_epoch`
    error frame and the live stream keeps serving untouched."""
    addr, thread, codes = _listener_worker(worker_id=4)
    live = SocketTransport(addr, worker_id=4)
    try:
        ready = live.handshake(timeout_s=10.0)
        assert ready["registered"] and ready["worker_id"] == 4
        assert ready["epoch"] == 1 and ready["protocol"] == PROTOCOL_VERSION
        live.send({"op": "ping"})
        assert live.recv(timeout_s=10.0)["ok"]

        # The raw wire view of the rejection: kind `stale_epoch`, typed.
        stale_raw = socket.create_connection(addr, timeout=10.0)
        try:
            send_frame(stale_raw, {
                "op": "register", "protocol": PROTOCOL_VERSION, "epoch": 1,
            }, timeout_s=10.0)
            reply = recv_frame(stale_raw, timeout_s=10.0)
            assert not reply["ok"] and reply["kind"] == "stale_epoch"
            assert "not newer" in reply["error"]
        finally:
            stale_raw.close()
        # ... and the controller-side language for the same rejection.
        stale = SocketTransport(addr, worker_id=4)
        with pytest.raises(WorkerGone, match="refused registration"):
            stale.handshake(timeout_s=10.0)

        # The live link was never disturbed by either stale attempt.
        live.send({"op": "ping"})
        assert live.recv(timeout_s=10.0)["ok"]
    finally:
        live.send({"op": "close"})
        assert live.recv(timeout_s=10.0)["ok"]
        thread.join(timeout=10.0)
        live.sever()
    assert codes == [0]


def test_listener_half_open_connection_yields_to_new_epoch():
    """The half-open corner: the controller's socket dies WITHOUT a FIN
    reaching the worker (peer gone, kernel still calls the connection
    established). The listener must accept the reconnect epoch immediately —
    never blocked behind the dead socket — and serve ops on the new link."""
    addr, thread, codes = _listener_worker(worker_id=2)
    t = SocketTransport(addr, worker_id=2)
    try:
        assert t.handshake(timeout_s=10.0)["epoch"] == 1
        t.send({"op": "ping"})
        assert t.recv(timeout_s=10.0)["ok"]
        # Abandon the socket without closing it: from the worker's side the
        # old conn stays "live" while this controller re-registers.
        half_open, t.sock = t.sock, None
        try:
            ready = t.handshake(timeout_s=10.0)  # epoch bumps to 2
            assert ready["epoch"] == 2
            t.send({"op": "ping"})
            assert t.recv(timeout_s=10.0)["ok"]
        finally:
            half_open.close()
    finally:
        t.send({"op": "close"})
        assert t.recv(timeout_s=10.0)["ok"]
        thread.join(timeout=10.0)
        t.sever()
    assert codes == [0]


# ------------------------------------------------------- reconnect machine
class FakeSocketTransport(FakeTransport):
    """FakeTransport plus the socket-transport verbs the reconnect machinery
    needs (handshake/reconnect/sever/alive). One scripted reply queue drives
    everything in call order: handshakes pop a ready frame (or an exception to
    fail the attempt), op recvs pop replies; a severed link raises WorkerGone
    from send/recv until the next successful handshake."""

    def __init__(self, replies):
        super().__init__(replies)
        self.severed = True  # not connected until the first handshake
        self.epoch = 0

    def _next(self):
        if not self.replies:
            raise WorkerGone("fake worker script exhausted")
        reply = self.replies.pop(0)
        if callable(reply):
            reply = reply(self.sent[-1] if self.sent else None)
        if isinstance(reply, BaseException):
            raise reply
        return reply

    def handshake(self, timeout_s, resume=False):
        self.severed = True
        self.epoch += 1
        ready = self._next()  # an exception here fails the attempt
        self.severed = False
        return ready

    def reconnect(self, timeout_s):
        return self.handshake(timeout_s, resume=True)

    def sever(self):
        self.severed = True

    def send(self, obj):
        if self.killed or self.severed:
            raise WorkerGone("transport link is severed (fake)")
        self.sent.append(obj)

    def recv(self, timeout_s):
        if self.severed:
            raise WorkerGone("transport link is severed (fake)")
        return self._next()


def _fake_socket_engine(*replies, **kwargs):
    kwargs.setdefault("reconnect_deadline_s", 5.0)
    kwargs.setdefault("reconnect_backoff_s", 0.001)
    return SubprocessEngine(
        {"name": "fake"}, {"max_queue": 4}, transport="socket",
        _transport=FakeSocketTransport([READY, *replies]), **kwargs,
    )


def _reconcile_reply(records):
    view = {str(r["request_id"]): r for r in records}
    return {"ok": True, "pid": 4242, "worker_id": 0, "requests": view,
            "load": 0, "queue_depth": 0, "pending": bool(records)}


def _rec(rid, tokens, finished=False, reason=None):
    return {"request_id": rid, "tokens": tokens, "finished": finished,
            "finish_reason": reason, "error": None}


def _drive_reconnect(eng, deadline_s=10.0):
    """step() until the reconnect resolves; returns the resumed events."""
    deadline = time.monotonic() + deadline_s
    while eng.reconnecting and time.monotonic() < deadline:
        events = eng.step()
        if events or not eng.reconnecting:
            return events
        time.sleep(0.002)
    raise AssertionError("reconnect never resolved within the test deadline")


def test_socket_tear_reconnects_and_resumes_streamed_tail():
    """A torn frame on a socket transport is a TRANSPORT fault: the engine
    enters `reconnecting` (process untouched), re-handshakes, and the stream
    resumes from the worker's retained tail — tokens [7] || [8, 9], never
    duplicated, never truncated; the same step() call delivers the tail."""
    from accelerate_tpu.serving import Request

    eng = _fake_socket_engine(
        _ok_submit,
        {"ok": True, "events": [[1, [7]]], "finished": [],
         "load": 1, "queue_depth": 0, "pending": True},
        WorkerGone("torn mid-frame payload (3/100 bytes)"),
        READY,  # the reconnect re-handshake
        _reconcile_reply([_rec(1, [7, 8, 9], finished=True, reason="length")]),
    )
    eng.submit(Request(1, np.asarray([1, 2], np.int32), max_new_tokens=8))
    assert eng.step() == [(1, [7])]
    events = eng.step()  # tear -> reconnecting -> re-handshake -> reconcile
    assert events == [(1, [8, 9])]
    assert not eng.reconnecting and eng.reconnects == 1
    result = eng.results[1]
    assert result.tokens == [7, 8, 9]
    assert result.finished and result.finish_reason == "length"
    assert eng.transport.epoch == 2  # initial handshake + one reconnect
    assert not eng.transport.killed and eng.pid == 4242  # partition != death


def test_reconnect_redispatches_never_streamed_request():
    """A submit whose frames died in the partition (worker never saw it,
    nothing streamed) re-dispatches VERBATIM during reconciliation and then
    streams normally — the request survives the outage with zero tokens
    lost and zero duplicated."""
    from accelerate_tpu.serving import Request

    eng = _fake_socket_engine(
        _ok_submit,
        WorkerGone("torn before the worker saw the submit"),
        READY,
        _reconcile_reply([]),  # the worker has no trace of request 1
        _ok_submit,            # the verbatim re-dispatch
        {"ok": True, "events": [[1, [5]]],
         "finished": [_rec(1, [5], finished=True, reason="length")],
         "load": 0, "queue_depth": 0, "pending": False},
    )
    eng.submit(Request(1, np.asarray([3, 1], np.int32), max_new_tokens=1))
    assert eng.step() == []  # tear -> reconnect -> reconcile -> re-dispatch
    assert not eng.reconnecting and eng.reconnects == 1
    submits = [m for m in eng.transport.sent if m.get("op") == "submit"]
    assert len(submits) == 2 and submits[0] == submits[1], (
        "the re-dispatch must resend the retained wire request verbatim"
    )
    assert not eng.results[1].finished
    assert eng.step() == [(1, [5])]
    assert eng.results[1].finish_reason == "length"


def test_reconnect_divergent_worker_journal_is_replica_lost():
    """If the worker's retained journal does not extend what we already
    streamed, resuming would corrupt the stream: the mirror finishes
    `replica_lost` with its streamed prefix intact — surfaced loss, never a
    silently spliced stream."""
    from accelerate_tpu.serving import Request

    eng = _fake_socket_engine(
        _ok_submit,
        {"ok": True, "events": [[1, [7]]], "finished": [],
         "load": 1, "queue_depth": 0, "pending": True},
        WorkerGone("torn"),
        READY,
        _reconcile_reply([_rec(1, [9, 9])]),  # does not extend [7]
    )
    eng.submit(Request(1, np.asarray([1], np.int32), max_new_tokens=8))
    assert eng.step() == [(1, [7])]
    assert eng.step() == []  # reconcile finished it terminally, no new tokens
    assert not eng.reconnecting
    result = eng.results[1]
    assert result.finished and result.finish_reason == "replica_lost"
    assert result.tokens == [7]  # the streamed prefix is never rewritten


def test_torn_frame_mid_reconcile_retries_idempotently():
    """The nastiest corner: the link tears AGAIN mid-reconciliation, after
    request 1's tail already extended the mirror but before request 2's
    re-dispatch landed. The retry must keep the ORIGINAL budget anchor,
    re-reconcile without duplicating the tail (the mirror already holds it),
    and release the resumed events exactly once, on full success."""
    from accelerate_tpu.serving import Request

    eng = _fake_socket_engine(
        _ok_submit,
        _ok_submit,
        {"ok": True, "events": [[1, [7]]], "finished": [],
         "load": 2, "queue_depth": 0, "pending": True},
        WorkerGone("torn mid-step"),
        READY,                                # attempt 1 re-handshake lands...
        _reconcile_reply([_rec(1, [7, 8])]),  # ...reconcile extends 1's mirror
        WorkerGone("torn again mid-reconcile"),  # ...but 2's re-dispatch tears
        READY,                                # attempt 2
        _reconcile_reply([_rec(1, [7, 8])]),  # tail now empty: no duplication
        _ok_submit,                           # 2's re-dispatch lands
    )
    eng.submit(Request(1, np.asarray([1], np.int32), max_new_tokens=8))
    eng.submit(Request(2, np.asarray([2], np.int32), max_new_tokens=8))
    assert eng.step() == [(1, [7])]
    anchor_before = None
    first = eng.step()  # tear -> attempt 1 -> tears mid-reconcile -> backoff
    anchor_before = eng._rc_since
    assert first == [] and eng.reconnecting
    events = _drive_reconnect(eng)
    assert events == [(1, [8])], "the resumed tail must release exactly once"
    assert eng.reconnects == 1
    assert eng._rc_since == anchor_before or not eng.reconnecting
    assert eng.results[1].tokens == [7, 8]  # extended once, not [7, 8, 8]
    assert not eng.results[2].finished  # re-dispatched, still in flight


def test_cancel_during_reconnect_queues_worker_side_cancel():
    """cancel() racing the outage: the mirror finishes `cancelled` NOW (the
    caller's intent is immediate), and the worker-side cancel is queued for
    delivery right after stream reconciliation — exactly once, after the
    reconcile op, and the reconcile must not resurrect the cancelled mirror."""
    from accelerate_tpu.serving import Request

    eng = _fake_socket_engine(
        _ok_submit,
        WorkerGone("torn"),
        WorkerGone("still partitioned"),  # reconnect attempt 1 fails
        READY,                            # attempt 2 lands
        _reconcile_reply([_rec(1, [4])]),  # worker still generating request 1
        {"ok": True, "cancelled": True, "result": _rec(1, [4], True, "cancelled")},
    )
    eng.submit(Request(1, np.asarray([1], np.int32), max_new_tokens=8))
    assert eng.step() == []  # tear; first reconnect attempt fails
    assert eng.reconnecting
    assert eng.cancel(1) is True  # link down: local cancel, worker-side queued
    result = eng.results[1]
    assert result.finished and result.finish_reason == "cancelled"
    assert _drive_reconnect(eng) == []
    assert eng.reconnects == 1
    ops = [m.get("op") for m in eng.transport.sent]
    assert ops.count("cancel") == 1
    assert ops.index("cancel") > ops.index("reconcile")
    # The reconcile saw the worker still generating [4]; the cancelled mirror
    # keeps its local terminal record — no resurrection, no tail splice.
    assert result.finish_reason == "cancelled" and result.tokens == []


def test_reconnect_budget_exhaustion_escalates_to_worker_gone():
    """Only an EXHAUSTED reconnect budget is a death: after at least one real
    failed attempt past the deadline, step() raises WorkerGone (the router's
    respawn language), the transport is reaped, and submit() refuses with
    EngineClosed like any dead worker."""
    from accelerate_tpu.serving import EngineClosed, Request

    eng = _fake_socket_engine(
        _ok_submit,
        WorkerGone("torn"),
        # Script exhausted from here on: every reconnect attempt fails.
        reconnect_deadline_s=0.05,
    )
    eng.submit(Request(1, np.asarray([1], np.int32), max_new_tokens=4))
    with pytest.raises(WorkerGone, match="reconnect budget exhausted"):
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            eng.step()
            time.sleep(0.005)
    assert not eng.reconnecting and eng.reconnects == 0
    assert eng.transport.killed  # the dead transport is reaped, not leaked
    with pytest.raises(EngineClosed):
        eng.submit(Request(2, np.asarray([1], np.int32), max_new_tokens=2))

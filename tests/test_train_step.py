"""Fused train step tests: the single-dispatch performance path must reproduce the
eager backward/step/zero_grad trajectory exactly (same updates, same scaler and
scheduler semantics), including `lax.scan` microbatch accumulation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils import GradientAccumulationPlugin

from test_training import make_regression_data, make_regression_model


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _run_eager(data, batch_size, accum=1, lr=0.05, max_norm=None, steps_epochs=2):
    _reset()
    accelerator = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(
            num_steps=accum, sync_with_dataloader=False
        )
    )
    model = make_regression_model(seed=0)
    dl = SimpleDataLoader(data, BatchSampler(range(len(data)), batch_size))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(lr), dl)
    losses = []
    for _ in range(steps_epochs):
        for batch in pdl:
            with accelerator.accumulate(pmodel):
                loss = accelerator.backward(pmodel.loss, batch)
                if max_norm is not None:
                    accelerator.clip_grad_norm_(max_norm=max_norm)
                popt.step()
                popt.zero_grad()
            losses.append(float(loss))
    return losses, pmodel.params


def _run_fused(data, batch_size, accum=1, lr=0.05, max_norm=None, steps_epochs=2):
    _reset()
    accelerator = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(
            num_steps=accum, sync_with_dataloader=False
        )
    )
    model = make_regression_model(seed=0)
    # fused mode consumes the full accumulation span in one call
    dl = SimpleDataLoader(data, BatchSampler(range(len(data)), batch_size * accum))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(lr), dl)
    step_fn = accelerator.train_step(max_grad_norm=max_norm)
    losses = []
    for _ in range(steps_epochs):
        for batch in pdl:
            losses.append(float(step_fn(batch)))
    return losses, pmodel.params


def _assert_params_close(a, b, rtol=2e-5, atol=1e-6):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=rtol, atol=atol)


def test_fused_matches_eager_trajectory():
    data = make_regression_data(64, seed=5)
    eager_losses, eager_params = _run_eager(data, batch_size=16)
    fused_losses, fused_params = _run_fused(data, batch_size=16)
    np.testing.assert_allclose(np.array(fused_losses), np.array(eager_losses), rtol=2e-5, atol=1e-6)
    _assert_params_close(fused_params, eager_params)


def test_fused_scan_accumulation_matches_eager_accumulation():
    data = make_regression_data(64, seed=6)
    _, eager_params = _run_eager(data, batch_size=8, accum=4)
    fused_losses, fused_params = _run_fused(data, batch_size=8, accum=4)
    # 64 samples / (8*4) per fused step = 2 steps/epoch
    assert len(fused_losses) == 4
    _assert_params_close(fused_params, eager_params, rtol=1e-4)


def test_fused_clipping_matches_eager_clipping():
    data = make_regression_data(64, seed=7)
    _, eager_params = _run_eager(data, batch_size=16, max_norm=0.5)
    _, fused_params = _run_fused(data, batch_size=16, max_norm=0.5)
    _assert_params_close(fused_params, eager_params, rtol=1e-4)


def test_fused_fp16_clipping_matches_eager():
    """fp16 + clipping: both paths must clip UNSCALED grads (the reference
    unscale-before-clip contract) and land on the same params."""

    def run(fused):
        _reset()
        accelerator = Accelerator(mixed_precision="fp16")
        model = make_regression_model(seed=0)
        data = make_regression_data(64, seed=11)
        dl = SimpleDataLoader(data, BatchSampler(range(64), 16))
        pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
        if fused:
            step_fn = accelerator.train_step(max_grad_norm=0.5)
            for _ in range(2):
                for batch in pdl:
                    step_fn(batch)
        else:
            for _ in range(2):
                for batch in pdl:
                    with accelerator.accumulate(pmodel):
                        accelerator.backward(pmodel.loss, batch)
                        accelerator.clip_grad_norm_(max_norm=0.5)
                        popt.step()
                        popt.zero_grad()
        return pmodel.params

    _assert_params_close(run(fused=True), run(fused=False), rtol=2e-3, atol=1e-4)


def test_fused_fp16_skips_on_overflow():
    _reset()
    accelerator = Accelerator(mixed_precision="fp16")
    model = make_regression_model(seed=0)
    data = make_regression_data(16, seed=8)
    dl = SimpleDataLoader(data, BatchSampler(range(16), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
    step_fn = accelerator.train_step()
    scale_before = popt.scaler.scale
    params_before = jax.tree_util.tree_map(np.asarray, pmodel.params)
    bad = {"x": np.full((8, 1), np.inf, np.float32), "y": np.zeros(8, np.float32)}
    step_fn(bad)
    assert popt.step_was_skipped
    assert popt.scaler.scale < scale_before
    _assert_params_close(pmodel.params, params_before)
    # good batches afterwards recover (the scaler backs off until grads fit fp16)
    good = next(iter(pdl))
    for _ in range(12):
        step_fn(good)
        if not popt.step_was_skipped:
            break
    assert not popt.step_was_skipped


def test_fused_honors_scheduler_lr_override():
    _reset()
    accelerator = Accelerator()
    model = make_regression_model(seed=0)
    data = make_regression_data(32, seed=9)
    dl = SimpleDataLoader(data, BatchSampler(range(32), 8))
    schedule = optax.linear_schedule(0.1, 0.0, 16)
    tx = optax.inject_hyperparams(optax.sgd)(learning_rate=0.1)
    pmodel, popt, pdl, sched = accelerator.prepare(model, tx, dl, schedule)
    step_fn = accelerator.train_step()
    for batch in pdl:
        step_fn(batch)
        sched.step()
    # scheduler advanced and pushed a decayed LR into the fused update
    assert sched.step_count > 0
    assert popt.learning_rate is not None and popt.learning_rate < 0.1


def _run_device_loop(data, batch_size, steps_per_call, lr=0.05, max_norm=None, steps_epochs=2):
    _reset()
    accelerator = Accelerator()
    model = make_regression_model(seed=0)
    # one call consumes steps_per_call full step-batches
    dl = SimpleDataLoader(data, BatchSampler(range(len(data)), batch_size * steps_per_call))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(lr), dl)
    step_fn = accelerator.train_step(max_grad_norm=max_norm, steps_per_call=steps_per_call)
    losses = []
    for _ in range(steps_epochs):
        for batch in pdl:
            losses.append(float(step_fn(batch)))
    return losses, pmodel.params


def test_device_loop_matches_single_step_trajectory():
    """steps_per_call=K (the scanned device training loop) must land on the same
    params as K separate fused calls over the same batches — and its returned
    loss is the LAST scanned step's, i.e. the eager trajectory's K-th loss."""
    data = make_regression_data(64, seed=12)
    single_losses, single_params = _run_fused(data, batch_size=8)
    loop_losses, loop_params = _run_device_loop(data, batch_size=8, steps_per_call=4)
    _assert_params_close(loop_params, single_params)
    # 64/8 = 8 steps/epoch -> 2 calls/epoch; call i returns step 4i+3's loss
    np.testing.assert_allclose(
        np.array(loop_losses), np.array(single_losses[3::4]), rtol=2e-5, atol=1e-6
    )


def test_device_loop_with_clipping_and_accumulation():
    data = make_regression_data(64, seed=13)
    _, ref_params = _run_fused(data, batch_size=4, accum=2, max_norm=0.5)
    _reset()
    accelerator = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(
            num_steps=2, sync_with_dataloader=False
        )
    )
    model = make_regression_model(seed=0)
    # K=2 calls, each spanning 2 steps x (2 microbatches x 4 rows)
    dl = SimpleDataLoader(data, BatchSampler(range(64), 4 * 2 * 2))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
    step_fn = accelerator.train_step(max_grad_norm=0.5, steps_per_call=2)
    for _ in range(2):
        for batch in pdl:
            step_fn(batch)
    _assert_params_close(pmodel.params, ref_params, rtol=1e-4)


def test_device_loop_rejects_dynamic_loss_scaling():
    _reset()
    accelerator = Accelerator(mixed_precision="fp16")
    model = make_regression_model(seed=0)
    data = make_regression_data(16, seed=14)
    dl = SimpleDataLoader(data, BatchSampler(range(16), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
    with pytest.raises(ValueError, match="steps_per_call"):
        accelerator.train_step(steps_per_call=2)


@pytest.mark.parametrize("scheduler_first", [True, False], ids=["sched-then-K", "K-then-sched"])
def test_device_loop_warns_when_scheduler_coarsened(caplog, scheduler_first):
    """steps_per_call=K reads the LR override once per compiled call, so a
    prepared scheduler silently advances in K-step strides (train_step.py
    docstring). That divergence from the per-step contract must be surfaced at
    prepare/build time — in EITHER order — not discovered from a training
    curve (round-4 verdict, weak #8)."""
    _reset()
    accelerator = Accelerator()
    model = make_regression_model(seed=0)
    data = make_regression_data(32, seed=21)
    dl = SimpleDataLoader(data, BatchSampler(range(32), 8 * 2))
    schedule = optax.linear_schedule(0.1, 0.0, 16)
    tx = optax.inject_hyperparams(optax.sgd)(learning_rate=0.1)
    with caplog.at_level("WARNING", logger="accelerate_tpu.accelerator"):
        if scheduler_first:
            pmodel, popt, pdl, sched = accelerator.prepare(model, tx, dl, schedule)
            accelerator.train_step(steps_per_call=2)
        else:
            pmodel, popt, pdl = accelerator.prepare(model, tx, dl)
            accelerator.train_step(steps_per_call=2)
            sched = accelerator.prepare(schedule)
    assert any(
        "steps_per_call=2" in r.getMessage() and "scheduler" in r.getMessage() for r in caplog.records
    ), caplog.records


def test_device_loop_no_scheduler_warning_at_k1(caplog):
    _reset()
    accelerator = Accelerator()
    model = make_regression_model(seed=0)
    data = make_regression_data(16, seed=22)
    dl = SimpleDataLoader(data, BatchSampler(range(16), 8))
    schedule = optax.linear_schedule(0.1, 0.0, 16)
    tx = optax.inject_hyperparams(optax.sgd)(learning_rate=0.1)
    with caplog.at_level("WARNING", logger="accelerate_tpu.accelerator"):
        accelerator.prepare(model, tx, dl, schedule)
        accelerator.train_step()  # K=1: per-step contract intact
    assert not any("steps_per_call" in r.getMessage() for r in caplog.records)


def test_device_loop_requires_divisible_batch():
    _reset()
    accelerator = Accelerator()
    model = make_regression_model(seed=0)
    data = make_regression_data(16, seed=15)
    dl = SimpleDataLoader(data, BatchSampler(range(16), 6))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
    step_fn = accelerator.train_step(steps_per_call=4)
    with pytest.raises(ValueError, match="steps_per_call"):
        step_fn(next(iter(pdl)))


def test_fused_step_marks_sync_boundary():
    _reset()
    accelerator = Accelerator(gradient_accumulation_steps=2)
    model = make_regression_model(seed=0)
    data = make_regression_data(32, seed=10)
    dl = SimpleDataLoader(data, BatchSampler(range(32), 16))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
    step_fn = accelerator.train_step()
    batch = next(iter(pdl))
    step_fn(batch)
    assert accelerator.sync_gradients

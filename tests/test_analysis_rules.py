"""Linter rule coverage: every rule's flag fixture is caught (and ONLY that
rule), every clean fixture lints silent, suppression comments work, and the
`accelerate-tpu analyze` CLI round-trips --json output and exit codes."""

import json
from pathlib import Path

import pytest

from accelerate_tpu.analysis import (
    RULES,
    RULES_BY_ID,
    analyze_paths,
    analyze_source,
    resolve_rule,
)

pytestmark = pytest.mark.analysis

SAMPLES = Path(__file__).resolve().parent / "test_samples" / "analysis"
RULE_IDS = sorted(RULES_BY_ID)


def test_registry_shape():
    assert len(RULES) >= 8  # the acceptance floor; currently 11
    assert len({r.id for r in RULES}) == len(RULES)
    assert len({r.slug for r in RULES}) == len(RULES)
    for rule in RULES:
        assert rule.fixit and rule.summary
        assert resolve_rule(rule.id) is rule
        assert resolve_rule(rule.slug) is rule
        assert resolve_rule(rule.id.lower()) is rule


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_flag_fixture_is_caught(rule_id):
    path = SAMPLES / f"{rule_id.lower()}_flag.py"
    findings = analyze_source(path.read_text(), str(path))
    assert findings, f"{path.name} seeded a {rule_id} hazard the linter missed"
    assert {f.rule_id for f in findings} == {rule_id}, (
        f"{path.name} should trip ONLY {rule_id}: {[(f.rule_id, f.line) for f in findings]}"
    )


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_clean_fixture_is_silent(rule_id):
    path = SAMPLES / f"{rule_id.lower()}_clean.py"
    findings = analyze_source(path.read_text(), str(path))
    assert not findings, (
        f"{path.name} is the sanctioned spelling and must lint clean: "
        f"{[(f.rule_id, f.line) for f in findings]}"
    )


def test_suppression_comments():
    path = SAMPLES / "suppressed.py"
    findings = analyze_source(path.read_text(), str(path))
    assert not findings, [(f.rule_id, f.line) for f in findings]


def test_suppression_variants():
    flagged = "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n"
    assert analyze_source(flagged)  # sanity: hazard present
    by_id = flagged.replace("x.item()", "x.item()  # tpu-lint: disable=TPU101")
    by_slug = flagged.replace("x.item()", "x.item()  # tpu-lint: disable=host-sync-item")
    by_all = flagged.replace("x.item()", "x.item()  # tpu-lint: disable=all")
    file_wide = "# tpu-lint: disable-file=TPU101\n" + flagged
    unknown = flagged.replace("x.item()", "x.item()  # tpu-lint: disable=NOPE123")
    assert not analyze_source(by_id)
    assert not analyze_source(by_slug)
    assert not analyze_source(by_all)
    assert not analyze_source(file_wide)
    assert analyze_source(unknown)  # unknown tokens never silence anything


def test_donated_reuse_respects_frames_and_static_attrs():
    """Regression: a nested function's same-named parameter is a fresh binding
    (neither a reuse nor a rebind), and .shape/.dtype metadata reads of a
    donated array stay legal."""
    shadowed = (
        "import jax\n"
        "def train(step, params, grads):\n"
        "    f = jax.jit(step, donate_argnums=(0,))\n"
        "    out = f(grads)\n"
        "    def helper(grads):\n"
        "        return grads + 1\n"
        "    return out, helper\n"
    )
    assert not analyze_source(shadowed), analyze_source(shadowed)

    metadata = (
        "import jax\n"
        "def train(step, params, grads):\n"
        "    f = jax.jit(step, donate_argnums=(0,))\n"
        "    out = f(grads)\n"
        "    print(grads.shape)\n"
        "    return out\n"
    )
    assert not analyze_source(metadata), analyze_source(metadata)

    # ...but a shadow Store in a nested def must not mask a REAL reuse.
    masked = (
        "import jax\n"
        "def train(step, grads):\n"
        "    f = jax.jit(step, donate_argnums=(0,))\n"
        "    def helper():\n"
        "        grads = 0\n"
        "        return grads\n"
        "    out = f(grads)\n"
        "    return out + grads\n"
    )
    assert [f.rule_id for f in analyze_source(masked)] == ["TPU108"]


def test_closure_capture_ignores_array_accumulators():
    """Regression: `acc += x` may be a traced-array accumulator — only scalar
    counters (`i += 1`) and scalar-literal locals count as closure captures."""
    array_acc = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def make(xs):\n"
        "    total = jnp.zeros(())\n"
        "    for x in xs:\n"
        "        total += x\n"
        "    @jax.jit\n"
        "    def step(y):\n"
        "        return y + total\n"
        "    return step\n"
    )
    assert not analyze_source(array_acc), analyze_source(array_acc)

    counter = (
        "import jax\n"
        "def make(xs):\n"
        "    i = 0\n"
        "    for x in xs:\n"
        "        i += 1\n"
        "    @jax.jit\n"
        "    def step(y):\n"
        "        return y + i\n"
        "    return step\n"
    )
    assert [f.rule_id for f in analyze_source(counter)] == ["TPU105"]


def test_tpu114_router_variants():
    """The Router half of TPU114: an explicit max_queue=None and a missing
    default_deadline_s each flag; the bounded+deadlined spelling is clean; and
    a module with no real jax import is out of scope (host-side tooling that
    merely mentions a Router is not jit-adjacent serving code)."""
    hazard = (
        "import jax\n"
        "from accelerate_tpu.router import Router\n"
        "def fleet(model):\n"
        "    return Router(model, replicas=3, max_queue=None)\n"
    )
    findings = analyze_source(hazard)
    assert [f.rule_id for f in findings] == ["TPU114", "TPU114"]  # queue + deadline
    clean = hazard.replace(
        "max_queue=None", "max_queue=64, default_deadline_s=60.0"
    )
    assert not analyze_source(clean)
    no_jax = hazard.replace("import jax\n", "")
    assert not analyze_source(no_jax)


def test_tpu115_interpret_variant():
    """The kernel-call half of TPU115 (the flag fixture carries the
    attention_impl pin — one finding per fixture): a literal interpret=True on
    a Pallas attention kernel flags (the CPU-test shim on a production call
    site), interpret=None / omitted is clean, a threaded variable is clean,
    and a jax-free module is out of scope."""
    hazard = (
        "import jax\n"
        "from accelerate_tpu.ops.paged_attention import paged_decode_attention\n"
        "def attend(q, pk, pv, tbl, pos):\n"
        "    return paged_decode_attention(q, pk, pv, tbl, pos, interpret=True)\n"
    )
    findings = analyze_source(hazard)
    assert [f.rule_id for f in findings] == ["TPU115"]
    assert not analyze_source(hazard.replace("interpret=True", "interpret=None"))
    assert not analyze_source(hazard.replace("interpret=True", "interpret=interp"))
    assert not analyze_source(hazard.replace(", interpret=True", ""))
    assert not analyze_source(hazard.replace("import jax\n", ""))


def test_tpu115_impl_pin_variants():
    """attention_impl="xla" flags only where the paged kernel applies: an
    explicit paged=False or page_size=0 opt-out is clean (no page table to
    walk), as is threading the impl as a variable (A/B harnesses)."""
    hazard = (
        "import jax\n"
        "from accelerate_tpu.serving import ContinuousBatcher\n"
        "def engine(model):\n"
        '    return ContinuousBatcher(model, max_queue=8, attention_impl="xla")\n'
    )
    assert [f.rule_id for f in analyze_source(hazard)] == ["TPU115"]
    assert not analyze_source(
        hazard.replace('attention_impl="xla"', 'paged=False, attention_impl="xla"')
    )
    assert not analyze_source(
        hazard.replace('attention_impl="xla"', "attention_impl=impl")
    )
    # The config-field spelling (dataclasses.replace / model configs) flags too.
    cfg = (
        "import jax\n"
        "import dataclasses\n"
        "def step_cfg(base):\n"
        '    return dataclasses.replace(base, decode_page_size=4, decode_attention_impl="xla")\n'
    )
    assert [f.rule_id for f in analyze_source(cfg)] == ["TPU115"]
    assert not analyze_source(
        cfg.replace("decode_page_size=4", "decode_page_size=0")
    )
    # A seam call relying on its own page_size=0 default (the contiguous
    # layout, where "xla" is the ONLY legal impl) must not flag — only calls
    # that really thread page geometry, or the paged-by-default constructors.
    seam = (
        "import jax\n"
        "from accelerate_tpu.ops.attention import slot_cache_attention\n"
        "def attend(module, q, k, v, pos):\n"
        '    return slot_cache_attention(module, q, k, v, 32, pos, attention_impl="xla")\n'
    )
    assert not analyze_source(seam)
    paged_seam = seam.replace(
        'attention_impl="xla"', 'page_size=ps, attention_impl="xla"'
    )
    assert [f.rule_id for f in analyze_source(paged_seam)] == ["TPU115"]


def test_tpu116_worker_loop_variants():
    """The looped-recv half of TPU116 (the flag fixture carries the
    serve_worker pin — one finding per fixture): an unbounded recv_frame
    INSIDE a loop flags, a bounded one is clean, a one-shot recv outside any
    loop is clean (handshakes may use their own start timeout), an explicit
    heartbeat_deadline_s=None flags, and a jax-free module is out of scope."""
    hazard = (
        "import jax\n"
        "from accelerate_tpu.worker import recv_frame\n"
        "def pump(stream):\n"
        "    while True:\n"
        "        frame = recv_frame(stream)\n"
    )
    assert [f.rule_id for f in analyze_source(hazard)] == ["TPU116"]
    assert not analyze_source(
        hazard.replace("recv_frame(stream)", "recv_frame(stream, timeout_s=30.0)")
    )
    assert [f.rule_id for f in analyze_source(
        hazard.replace("recv_frame(stream)", "recv_frame(stream, timeout_s=None)")
    )] == ["TPU116"]
    one_shot = (
        "import jax\n"
        "from accelerate_tpu.worker import recv_frame\n"
        "def handshake(stream):\n"
        "    return recv_frame(stream, timeout_s=600.0)\n"
    )
    assert not analyze_source(one_shot)
    explicit_none = (
        "import jax\n"
        "from accelerate_tpu.worker import serve_worker\n"
        "def run(host, r, w):\n"
        "    return serve_worker(host, r, w, heartbeat_deadline_s=None)\n"
    )
    assert [f.rule_id for f in analyze_source(explicit_none)] == ["TPU116"]
    assert not analyze_source(hazard.replace("import jax\n", ""))


def test_tpu122_transport_variants():
    """The variants beyond the flag fixture's three hazards (dial, looped
    recv, bare reconnect loop): a timed dial is clean, an explicit
    timeout=None still flags, a module-wide settimeout legitimizes its recv
    loops (select-based framing arms deadlines away from the recv site), a
    recv with its own timeout_s is clean without settimeout, one-shot
    recv/reconnect outside any loop is clean, a budgeted reconnect attempt
    is clean, and socket-free or jax-free modules are out of scope."""
    dial = (
        "import socket\n"
        "import jax\n"
        "def connect(addr):\n"
        "    return socket.create_connection(addr)\n"
    )
    assert [f.rule_id for f in analyze_source(dial)] == ["TPU122"]
    assert not analyze_source(
        dial.replace("create_connection(addr)", "create_connection(addr, timeout=5.0)")
    )
    assert [f.rule_id for f in analyze_source(
        dial.replace("create_connection(addr)", "create_connection(addr, timeout=None)")
    )] == ["TPU122"]
    pump = (
        "import socket\n"
        "import jax\n"
        "def pump(sock):\n"
        "    while True:\n"
        "        if not sock.recv(4096):\n"
        "            break\n"
    )
    assert [f.rule_id for f in analyze_source(pump)] == ["TPU122"]
    armed = pump.replace(
        "def pump(sock):\n", "def pump(sock):\n    sock.settimeout(5.0)\n"
    )
    assert not analyze_source(armed)
    # a duck-typed transport recv carrying its own deadline needs no settimeout
    assert not analyze_source(
        pump.replace("sock.recv(4096)", "sock.recv(4096, timeout_s=5.0)")
    )
    one_shot = (
        "import socket\n"
        "import jax\n"
        "def peek(sock):\n"
        "    return sock.recv(4096)\n"
    )
    assert not analyze_source(one_shot)
    heal = (
        "import socket\n"
        "import jax\n"
        "def heal(link):\n"
        "    while True:\n"
        "        try:\n"
        "            return link.reconnect()\n"
        "        except OSError:\n"
        "            continue\n"
    )
    assert [f.rule_id for f in analyze_source(heal)] == ["TPU122"]
    assert not analyze_source(
        heal.replace("link.reconnect()", "link.reconnect(timeout_s=2.0)")
    )
    assert not analyze_source(pump.replace("import socket\n", ""))
    assert not analyze_source(pump.replace("import jax\n", ""))


def test_tpu117_variants():
    """The variants beyond the flag fixture's k_scale literal (one finding
    per fixture): a v_scale literal flags, a threaded array variable is
    clean, an int literal flags, a scale kwarg on an unrelated function is
    out of scope (no false positives on generic `k_scale=` spellings),
    kv_cache_dtype literals off the supported set flag in both the engine and
    config spellings, supported literals and variables are clean, and a
    jax-free module is out of scope."""
    hazard = (
        "import jax\n"
        "from accelerate_tpu.ops.paged_attention import paged_verify_attention\n"
        "def attend(q, pk, pv, tbl, pos, ks):\n"
        "    return paged_verify_attention(q, pk, pv, tbl, pos, k_scale=ks, v_scale=0.01)\n"
    )
    assert [f.rule_id for f in analyze_source(hazard)] == ["TPU117"]
    assert not analyze_source(hazard.replace("v_scale=0.01", "v_scale=vs"))
    assert [f.rule_id for f in analyze_source(
        hazard.replace("v_scale=0.01", "v_scale=1")
    )] == ["TPU117"]
    unrelated = (
        "import jax\n"
        "def tune(plotter):\n"
        "    return plotter.draw(k_scale=0.5)\n"
    )
    assert not analyze_source(unrelated)
    engine = (
        "import jax\n"
        "from accelerate_tpu.serving import ContinuousBatcher\n"
        "def build(model):\n"
        '    return ContinuousBatcher(model, max_queue=8, kv_cache_dtype="int4")\n'
    )
    assert [f.rule_id for f in analyze_source(engine)] == ["TPU117"]
    assert not analyze_source(engine.replace('"int4"', '"fp8_e4m3"'))
    assert not analyze_source(engine.replace('"int4"', "dtype_flag"))
    cfg = (
        "import jax\n"
        "import dataclasses\n"
        "def step_cfg(base):\n"
        '    return dataclasses.replace(base, decode_kv_cache_dtype="fp16")\n'
    )
    assert [f.rule_id for f in analyze_source(cfg)] == ["TPU117"]
    assert not analyze_source(cfg.replace('"fp16"', '"bf16"'))
    assert not analyze_source(hazard.replace("import jax\n", ""))


def test_tpu118_variants():
    """Beyond the flag fixture's bare device_put (one finding per fixture):
    a raw-device placement flags, a None placement flags, a NamedSharding /
    derived-shardings / unknown-name placement is clean (precomputed sharding
    pytrees get the benefit of the doubt), a module with NO "model"-axis mesh
    is out of scope however it places things, a Mesh(..., ("model",)) literal
    counts as mesh-spanning the same as serving_tp_mesh, and a jax-free
    module is out of scope."""
    hazard = (
        "import jax\n"
        "from accelerate_tpu.parallel.sharding import serving_tp_mesh\n"
        "def place(params):\n"
        "    mesh = serving_tp_mesh(4)\n"
        "    return jax.device_put(params, jax.devices()[0])\n"
    )
    assert [f.rule_id for f in analyze_source(hazard)] == ["TPU118"]
    assert [f.rule_id for f in analyze_source(
        hazard.replace("jax.devices()[0]", "None")
    )] == ["TPU118"]
    assert not analyze_source(
        hazard.replace("jax.devices()[0]", "NamedSharding(mesh, spec)")
    )
    assert not analyze_source(
        hazard.replace("jax.devices()[0]", "derive_tp_param_shardings(params, mesh, rules)")
    )
    assert not analyze_source(hazard.replace("jax.devices()[0]", "shardings"))
    # No "model"-axis mesh in the module: ordinary single-device placement.
    no_mesh = (
        "import jax\n"
        "def place(params):\n"
        "    return jax.device_put(params)\n"
    )
    assert not analyze_source(no_mesh)
    # A literal Mesh with a "model" axis counts as mesh-spanning too.
    literal_mesh = (
        "import jax\n"
        "from jax.sharding import Mesh\n"
        "def place(params, devices):\n"
        '    mesh = Mesh(devices, ("model",))\n'
        "    return jax.device_put(params)\n"
    )
    assert [f.rule_id for f in analyze_source(literal_mesh)] == ["TPU118"]
    assert not analyze_source(literal_mesh.replace('("model",)', '("data",)'))
    assert not analyze_source(hazard.replace("import jax\n", ""))


def test_tpu119_variants():
    """Beyond the flag fixture's dead table entry (one finding per fixture):
    a live entry whose tokens connect to flax submodule names is clean, an
    f-string name part counts as evidence, an all-generic pattern is skipped
    (can't be judged statically), a literal string-axis PartitionSpec in a
    flax model module flags while the empty PartitionSpec() does not, and
    modules without flax (or without jax) are out of scope however their
    tables look."""
    base = (
        "import jax\n"
        "import flax.linen as nn\n"
        "RULES_SHARDING_RULES = [(r\"{pattern}\", (None, \"model\"))]\n"
        "class Toy(nn.Module):\n"
        "    @nn.compact\n"
        "    def __call__(self, x):\n"
        "        return nn.Dense(4, name=\"wq\")(x)\n"
    )
    dead = base.replace("{pattern}", "query_proj/kernel")
    assert [f.rule_id for f in analyze_source(dead)] == ["TPU119"]
    assert not analyze_source(base.replace("{pattern}", "wq/kernel"))
    # f-string submodule names vouch for the pattern's tokens.
    fstring = (
        "import jax\n"
        "import flax.linen as nn\n"
        "TOY_SHARDING_RULES = [(r\"block_\\d+/kernel\", (None, \"model\"))]\n"
        "class Toy(nn.Module):\n"
        "    @nn.compact\n"
        "    def __call__(self, x):\n"
        "        for i in range(2):\n"
        "            x = nn.Dense(4, name=f\"block_{i}\")(x)\n"
        "        return x\n"
    )
    assert not analyze_source(fstring)
    # All-generic patterns (kernel/embedding/bias...) carry no module identity.
    assert not analyze_source(base.replace("{pattern}", "kernel$"))
    # A literal string-axis PartitionSpec outside the table flags; the empty
    # replicated spec does not.
    literal = (
        "import jax\n"
        "import flax.linen as nn\n"
        "from jax.sharding import PartitionSpec\n"
        "def place():\n"
        "    return PartitionSpec(None, \"model\")\n"
    )
    assert [f.rule_id for f in analyze_source(literal)] == ["TPU119"]
    assert not analyze_source(literal.replace("PartitionSpec(None, \"model\")", "PartitionSpec()"))
    # Tuple-nested axis literals flag too.
    assert [f.rule_id for f in analyze_source(
        literal.replace("PartitionSpec(None, \"model\")", "PartitionSpec((\"data\", \"fsdp\"))")
    )] == ["TPU119"]
    # No flax import: not a model module — rule tables and specs are the
    # derivation layer's business (parallel/sharding.py spells both).
    assert not analyze_source(dead.replace("import flax.linen as nn\n", ""))
    assert not analyze_source(literal.replace("import flax.linen as nn\n", ""))
    # No jax import: out of scope entirely.
    assert not analyze_source(dead.replace("import jax\n", ""))


def test_tpu120_variants():
    """Beyond the flag fixture's bare device_put (one finding per fixture):
    a raw-device placement flags, an explicit NamedSharding(mesh,
    PartitionSpec()) — replicate spelled out — flags, a derived/unknown-name
    placement is clean (precomputed sharding pytrees get the benefit of the
    doubt), a non-opt-state operand is out of scope (that's TPU118's beat,
    and only on "model" meshes), a module with NO data-axis mesh is out of
    scope however it places moments, ParallelismConfig(data=...) and
    Mesh(..., ("data",...)) both count as data-mesh evidence, and a jax-free
    module is out of scope."""
    hazard = (
        "import jax\n"
        "from accelerate_tpu.utils import ParallelismConfig\n"
        "def restore(tx, params):\n"
        "    cfg = ParallelismConfig(data=-1)\n"
        "    opt_state = tx.init(params)\n"
        "    return cfg, jax.device_put(opt_state)\n"
    )
    assert [f.rule_id for f in analyze_source(hazard)] == ["TPU120"]
    assert [f.rule_id for f in analyze_source(
        hazard.replace("jax.device_put(opt_state)",
                       "jax.device_put(opt_state, jax.devices()[0])")
    )] == ["TPU120"]
    # Replicate spelled out: every PartitionSpec in the placement is empty.
    assert [f.rule_id for f in analyze_source(
        hazard.replace("jax.device_put(opt_state)",
                       "jax.device_put(opt_state, NamedSharding(mesh, PartitionSpec()))")
    )] == ["TPU120"]
    # A sharded spec, a derived pytree, or an unknown name: clean.
    assert not analyze_source(
        hazard.replace("jax.device_put(opt_state)",
                       "jax.device_put(opt_state, NamedSharding(mesh, PartitionSpec(\"data\")))")
    )
    assert not analyze_source(
        hazard.replace(
            "jax.device_put(opt_state)",
            "jax.device_put(opt_state, derive_opt_state_shardings(shapes, mesh, "
            "rules=rules, opt_rules=plan.opt_rules))",
        )
    )
    assert not analyze_source(
        hazard.replace("jax.device_put(opt_state)",
                       "jax.device_put(opt_state, opt_shardings)")
    )
    # Not an optimizer-state operand: TPU120 stays quiet (a bare params
    # placement on a data-only mesh is plain data parallelism, not ZeRO's
    # business — and TPU118 only polices "model"-axis meshes).
    assert not analyze_source(
        hazard.replace("opt_state = tx.init(params)\n", "")
        .replace("jax.device_put(opt_state)", "jax.device_put(params)")
    )
    # No data-axis mesh anywhere in the module: out of scope.
    assert not analyze_source(
        hazard.replace(
            "    cfg = ParallelismConfig(data=-1)\n", "    cfg = None\n"
        )
    )
    # A literal Mesh with a "data" axis counts as data-mesh evidence too.
    mesh_hazard = (
        "import jax\n"
        "from jax.sharding import Mesh\n"
        "def restore(adam_state, devices):\n"
        '    mesh = Mesh(devices, ("data",))\n'
        "    return jax.device_put(adam_state, optimizer_state_placement)\n"
    )
    assert not analyze_source(mesh_hazard)  # named placement: benefit of the doubt
    assert [f.rule_id for f in analyze_source(
        mesh_hazard.replace(", optimizer_state_placement", "")
    )] == ["TPU120"]
    assert not analyze_source(
        mesh_hazard.replace(", optimizer_state_placement", "")
        .replace('("data",)', '("stage",)')
    )
    assert not analyze_source(hazard.replace("import jax\n", ""))


def test_tpu121_variants():
    """Beyond the flag fixture's device_get (one finding per fixture): the
    numpy coercion and .block_until_ready() spellings flag too, jnp.asarray
    stays on device and is clean, a non-handoff operand is out of scope, a
    module with no pipeline-mesh evidence is out of scope however it moves
    carries, ParallelismConfig(pipeline=...) and Mesh(..., ("pipeline",))
    both count as pipeline-mesh evidence, and a jax-free module is out of
    scope."""
    hazard = (
        "import jax\n"
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "from accelerate_tpu.parallel import slice_mesh\n"
        "def handoff(mesh, fwd, params, batch):\n"
        '    subs = slice_mesh(mesh, "pipeline")\n'
        "    carry = fwd(params, batch)\n"
        "    return subs, jax.device_get(carry)\n"
    )
    assert [f.rule_id for f in analyze_source(hazard)] == ["TPU121"]
    # The silent device_get: numpy coercion of the carry.
    assert [f.rule_id for f in analyze_source(
        hazard.replace("jax.device_get(carry)", "np.asarray(carry)")
    )] == ["TPU121"]
    assert [f.rule_id for f in analyze_source(
        hazard.replace("jax.device_get(carry)", "np.array(carry)")
    )] == ["TPU121"]
    # Blocking the schedule on the handoff: both spellings.
    assert [f.rule_id for f in analyze_source(
        hazard.replace("jax.device_get(carry)", "carry.block_until_ready()")
    )] == ["TPU121"]
    assert [f.rule_id for f in analyze_source(
        hazard.replace("jax.device_get(carry)", "jax.block_until_ready(carry)")
    )] == ["TPU121"]
    # jnp.asarray stays on device — not a host hop.
    assert not analyze_source(
        hazard.replace("jax.device_get(carry)", "jnp.asarray(carry)")
    )
    # Cotangents and activations are handoff labels too.
    assert [f.rule_id for f in analyze_source(
        hazard.replace("carry", "g_out")
    )] == ["TPU121"]
    # A non-handoff operand (checkpoint pull of merged params): out of scope.
    assert not analyze_source(
        hazard.replace("jax.device_get(carry)", "jax.device_get(merged)")
    )
    # No pipeline-mesh evidence in the module: out of scope.
    assert not analyze_source(
        hazard.replace('    subs = slice_mesh(mesh, "pipeline")\n', "    subs = None\n")
    )
    # ParallelismConfig(pipeline=...) and a literal Mesh with a "pipeline"
    # axis both count as pipeline-mesh evidence.
    for spelling in (
        "    subs = ParallelismConfig(pipeline=2)\n",
        '    subs = Mesh(devices, ("data", "pipeline"))\n',
    ):
        assert [f.rule_id for f in analyze_source(
            hazard.replace('    subs = slice_mesh(mesh, "pipeline")\n', spelling)
        )] == ["TPU121"]
    assert not analyze_source(
        hazard.replace("import jax\n", "").replace("import jax.numpy as jnp\n", "")
        .replace("jax.device_get(carry)", "np.asarray(carry)")
    )


def test_analyze_paths_walks_the_tree():
    findings, scanned = analyze_paths([str(SAMPLES)])
    assert scanned >= 2 * len(RULES) + 1  # flag + clean per rule + suppressed.py
    assert {f.rule_id for f in findings} == set(RULE_IDS)
    per_rule = {rid: [f for f in findings if f.rule_id == rid] for rid in RULE_IDS}
    assert all(len(v) == 1 for v in per_rule.values()), {
        k: len(v) for k, v in per_rule.items() if len(v) != 1
    }
    assert all(f.file.endswith("_flag.py") for f in findings)


def test_analyze_paths_missing_path():
    with pytest.raises(FileNotFoundError):
        analyze_paths(["/nonexistent/really-not-here"])


# ---------------------------------------------------------------------- CLI
def _run_cli(argv, capsys):
    from accelerate_tpu.commands.accelerate_cli import get_command_parser

    parser = get_command_parser()
    args = parser.parse_args(argv)
    with pytest.raises(SystemExit) as excinfo:
        args.func(args)
    out = capsys.readouterr()
    return excinfo.value.code, out.out, out.err


def test_cli_json_round_trip(capsys):
    code, out, _ = _run_cli(["analyze", str(SAMPLES), "--json"], capsys)
    assert code == 1  # error-severity findings exist in the flag fixtures
    payload = json.loads(out)
    assert payload["version"] == 1
    assert payload["files_scanned"] >= 2 * len(RULES)
    assert {f["rule"] for f in payload["findings"]} == set(RULE_IDS)
    sample = payload["findings"][0]
    assert set(sample) == {"file", "line", "col", "rule", "slug", "severity", "message", "fixit"}
    assert payload["counts"]["error"] >= 1 and payload["counts"]["warn"] >= 1


def test_cli_exit_codes(capsys, tmp_path):
    # clean tree -> 0
    clean = tmp_path / "clean.py"
    clean.write_text("def f(x):\n    return x + 1\n")
    code, _, _ = _run_cli(["analyze", str(tmp_path)], capsys)
    assert code == 0

    # warn-only tree: default threshold passes, --fail-on warn gates
    warn_only = tmp_path / "warn.py"
    warn_only.write_text(SAMPLES.joinpath("tpu111_flag.py").read_text())
    code, _, _ = _run_cli(["analyze", str(warn_only)], capsys)
    assert code == 0
    code, _, _ = _run_cli(["analyze", str(warn_only), "--fail-on", "warn"], capsys)
    assert code == 1

    # error finding -> 1 at the default threshold
    err = tmp_path / "err.py"
    err.write_text(SAMPLES.joinpath("tpu101_flag.py").read_text())
    code, _, _ = _run_cli(["analyze", str(err)], capsys)
    assert code == 1

    # bad path -> usage error 2
    code, _, errout = _run_cli(["analyze", str(tmp_path / "missing")], capsys)
    assert code == 2
    assert "no such file" in errout


def test_cli_list_rules(capsys):
    code, out, _ = _run_cli(["analyze", "--list-rules", "."], capsys)
    assert code == 0
    for rule in RULES:
        assert rule.id in out and rule.slug in out

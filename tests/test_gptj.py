"""GPT-J model family: forward/training through the Accelerator, KV-cached decode
parity, HF torch-layout interchange, transformers forward parity, and the
LayeredApply streaming protocol (the reference's GPT-J-6B is its big-model-inference
headline, benchmarks/README.md:31)."""

import numpy as np
import pytest

import jax.numpy as jnp

from accelerate_tpu.models.gptj import (
    GPTJConfig,
    GPTJLayeredApply,
    create_gptj_model,
    gptj_tiny,
)
from accelerate_tpu.utils.hf_loading import convert_hf_state_dict, export_hf_state_dict


def test_forward_shape_and_determinism():
    model = create_gptj_model(gptj_tiny(), seq_len=16)
    ids = jnp.asarray(np.random.default_rng(0).integers(1, 512, (2, 16)), jnp.int32)
    out = model.apply_fn(model.params, ids)
    assert out.shape == (2, 16, 512)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(model.apply_fn(model.params, ids)))


def test_training_through_accelerator_decreases_loss():
    import optax

    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    model = create_gptj_model(gptj_tiny(), seq_len=16)
    pmodel, popt = accelerator.prepare(model, optax.adamw(1e-3))
    step = accelerator.train_step()
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(1, 512, (8, 16)).astype(np.int32)}
    first = float(step(batch))
    for _ in range(10):
        last = float(step(batch))
    assert last < first


def test_cached_greedy_matches_full_context():
    """Decode through the KV cache must equal argmax over the full-context forward
    (same contract as the llama test; proves the cache write path + partial rotary
    positions agree)."""
    from accelerate_tpu.generation import generate

    cfg = gptj_tiny()
    model = create_gptj_model(cfg, seq_len=32)
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, cfg.vocab_size, (2, 8)).astype(np.int32)
    out = np.asarray(generate(model, prompt, max_new_tokens=6))

    # Reference: grow the context one token at a time through the uncached forward.
    ctx = prompt.copy()
    for _ in range(6):
        logits = np.asarray(model.apply_fn(model.params, jnp.asarray(ctx, jnp.int32)))
        nxt = logits[:, -1, :].argmax(-1).astype(np.int32)
        ctx = np.concatenate([ctx, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, ctx)


def test_hf_round_trip_preserves_logits():
    cfg = gptj_tiny()
    model = create_gptj_model(cfg, seq_len=16)
    ids = jnp.asarray(np.random.default_rng(2).integers(1, cfg.vocab_size, (2, 16)), jnp.int32)
    ref = np.asarray(model.apply_fn(model.params, ids))

    flat = export_hf_state_dict(model.params, "gptj", cfg)
    assert flat["transformer.h.0.attn.q_proj.weight"].shape == (128, 128)
    assert "transformer.h.0.mlp.fc_in.bias" in flat
    params2 = convert_hf_state_dict(flat, "gptj", cfg)
    out = np.asarray(model.apply_fn(params2, ids))
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_real_transformers_gptj_matches():
    """Forward parity against HF transformers GPTJForCausalLM (torch CPU) — proves
    the parallel-residual block, interleaved partial rotary, and biased head match
    the published architecture exactly."""
    transformers = pytest.importorskip("transformers")
    import torch

    hf_cfg = transformers.GPTJConfig(
        vocab_size=512,
        n_embd=128,
        n_inner=256,
        n_layer=2,
        n_head=4,
        rotary_dim=16,
        n_positions=256,
        layer_norm_epsilon=1e-5,
        attn_pdrop=0.0,
        embd_pdrop=0.0,
        resid_pdrop=0.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf_model = transformers.GPTJForCausalLM(hf_cfg).eval()
    flat = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    cfg = gptj_tiny()
    params = convert_hf_state_dict(flat, "gptj", cfg)
    model = create_gptj_model(cfg, seq_len=16)

    ids_np = np.random.default_rng(3).integers(1, 512, (2, 16))
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(ids_np)).logits.numpy()
    out = np.asarray(model.apply_fn(params, jnp.asarray(ids_np, jnp.int32)))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_layered_apply_matches_monolithic():
    cfg = gptj_tiny()
    model = create_gptj_model(cfg, seq_len=16)
    layered = GPTJLayeredApply(cfg)
    ids = jnp.asarray(np.random.default_rng(4).integers(1, cfg.vocab_size, (2, 16)), jnp.int32)
    ref = np.asarray(model.apply_fn(model.params, ids))

    prelude, layers, tail = layered.split(model.params)
    assert len(layers) == cfg.num_hidden_layers
    carry = layered.apply_prelude(prelude, ids)
    for lp in layers:
        carry = layered.apply_layer(lp, carry)
    out = np.asarray(layered.apply_tail(tail, carry))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    rejoined = layered.join(prelude, layers, tail)
    out2 = np.asarray(model.apply_fn(rejoined, ids))
    np.testing.assert_array_equal(out2, ref)

"""Tracker registry parity: all 7 reference integrations + in-tree json/csv are
registered, availability-gated, and `filter_trackers` behaves per reference
tracking.py:971 (skip-unavailable with warning, 'all' = available set)."""

import pytest

from accelerate_tpu.tracking import (
    LOGGER_TYPE_TO_CLASS,
    _AVAILABILITY,
    GeneralTracker,
    filter_trackers,
)


def test_registry_covers_reference_integrations():
    # reference tracking.py ships: tensorboard, wandb, comet_ml, aim, mlflow,
    # clearml, dvclive (7) — plus our always-available json/csv
    for name in ["tensorboard", "wandb", "comet_ml", "aim", "mlflow", "clearml", "dvclive", "json", "csv"]:
        assert name in LOGGER_TYPE_TO_CLASS, name
        assert name in _AVAILABILITY, name
        assert issubclass(LOGGER_TYPE_TO_CLASS[name], GeneralTracker)
        assert LOGGER_TYPE_TO_CLASS[name].name == name


def test_filter_skips_unavailable():
    # comet_ml/aim/clearml/dvclive aren't installed in this image: selected
    # explicitly they warn + skip rather than raise
    out = filter_trackers(["json", "comet_ml"], logging_dir="/tmp/x")
    assert out == ["json"]


def test_filter_all_returns_available_only():
    out = filter_trackers("all", logging_dir="/tmp/x")
    assert "json" in out and "csv" in out
    for name in out:
        assert _AVAILABILITY[name]()


def test_unknown_tracker_raises():
    with pytest.raises(ValueError, match="Unknown tracker"):
        filter_trackers("not_a_tracker")

"""Paged KV cache + shared-prefix reuse tests (paging.PagePool, the paged
`ops/attention.update_slot_cache` mode, `utils/operations.tree_gather_pages`/
`tree_scatter_pages`, and the `ContinuousBatcher(paged=True)` engine).

The load-bearing contracts:
  1. the paged scatter/gather ops round-trip against a dense reference,
     including page-boundary writes and arbitrary pool permutations;
  2. greedy decode is TOKEN-IDENTICAL between the paged and contiguous cache
     paths, across slot reuse and shared-prefix scenarios;
  3. slot/page reuse never exposes a prior occupant's tokens;
  4. admission is PAGE-based: request mixes whose worst-case rows exceed the
     old slot capacity are admitted and complete when their actual token
     footprint fits the pool;
  5. the PagePool ledger (refcounts, prefix registrations, LRU eviction) stays
     consistent through every admit/release/reset path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.generation import generate
from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
from accelerate_tpu.paging import SCRATCH_PAGE, PagePool, chain_hashes
from accelerate_tpu.serving import ContinuousBatcher, Request

pytestmark = pytest.mark.paging


def _model(max_pos=64):
    cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=max_pos,
        rope_theta=10000.0,
    )
    return create_llama_model(cfg, seq_len=32)


def _static_reference(model, prompt, max_new, **kwargs):
    out = np.asarray(generate(model, prompt[None, :], max_new_tokens=max_new, **kwargs))
    return out[0, prompt.size:]


# ------------------------------------------------------------------ tree ops


def _fake_caches(rng, layers=2, pages=7, ps=4, h=2, d=3):
    """(pool_tree, dense_struct) with the real leaf names at realistic ranks."""
    pool = {
        f"layer_{i}": {
            "attention": {
                "cached_key": jnp.asarray(rng.normal(size=(pages, ps, h, d)), jnp.float32),
                "cached_value": jnp.asarray(rng.normal(size=(pages, ps, h, d)), jnp.float32),
            }
        }
        for i in range(layers)
    }
    dense_struct = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct((1, 3 * ps, *x.shape[2:]), x.dtype),
        pool,
    )
    for i in range(layers):
        dense_struct[f"layer_{i}"]["attention"]["cache_index"] = jax.ShapeDtypeStruct(
            (), jnp.int32
        )
    return pool, dense_struct


def test_gather_pages_matches_dense_reference():
    """Gathering pages [ids] must equal concatenating those pool pages in table
    order — the dense layout the contiguous path would have held."""
    from accelerate_tpu.utils.operations import tree_gather_pages

    rng = np.random.default_rng(0)
    pool, struct = _fake_caches(rng)
    ids = jnp.asarray([5, 2, 6], jnp.int32)
    dense = tree_gather_pages(pool, struct, ids, jnp.int32(8))
    for i in range(2):
        leaf = pool[f"layer_{i}"]["attention"]["cached_key"]
        expect = np.concatenate([np.asarray(leaf[p]) for p in (5, 2, 6)], axis=0)[None]
        np.testing.assert_array_equal(
            np.asarray(dense[f"layer_{i}"]["attention"]["cached_key"]), expect
        )
        assert int(dense[f"layer_{i}"]["attention"]["cache_index"]) == 8


def test_scatter_pages_roundtrip_and_untouched_pages():
    """scatter(gather(pool)) is the identity on the table's pages and leaves
    every OTHER page bit-for-bit untouched (page-boundary writes stay inside
    their page)."""
    from accelerate_tpu.utils.operations import tree_gather_pages, tree_scatter_pages

    rng = np.random.default_rng(1)
    pool, struct = _fake_caches(rng)
    ids = jnp.asarray([1, 4, 3], jnp.int32)
    dense = tree_gather_pages(pool, struct, ids, jnp.int32(0))
    out = tree_scatter_pages(pool, dense, ids)
    for a, b in zip(jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(pool)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # A modified dense row lands in exactly the right page at the right offset.
    key = dense["layer_0"]["attention"]["cached_key"]
    key = key.at[0, 5].set(99.0)  # logical position 5 = page ids[1]=4, offset 1
    dense["layer_0"]["attention"]["cached_key"] = key
    out = tree_scatter_pages(pool, dense, ids)
    got = np.asarray(out["layer_0"]["attention"]["cached_key"])
    np.testing.assert_array_equal(got[4, 1], np.full((2, 3), 99.0))
    # neighbours of the write untouched
    src = np.asarray(pool["layer_0"]["attention"]["cached_key"])
    np.testing.assert_array_equal(got[4, 0], src[4, 0])
    np.testing.assert_array_equal(got[0], src[0])


def test_paged_slot_write_crosses_page_boundaries():
    """The paged update_slot_cache write lands at pool[table[pos//ps], pos%ps]
    and the gathered read reproduces the dense logical order, for positions on
    both sides of every page boundary."""
    import flax.linen as nn

    from accelerate_tpu.ops.attention import update_slot_cache

    ps, num_pages, P = 4, 6, 3

    class Probe(nn.Module):
        @nn.compact
        def __call__(self, k, v, positions, page_table):
            return update_slot_cache(
                self, k, v, P * ps, positions, page_table=page_table,
                page_size=ps, num_pages=num_pages,
            )

    probe = Probe()
    table = jnp.asarray([[2, 5, 1], [4, 3, 0]], jnp.int32)  # two slots
    cache = None
    rng = np.random.default_rng(2)
    written = {}
    for pos in (0, 3, 4, 7, 8, 11):  # page starts and page ends
        k = jnp.asarray(rng.normal(size=(2, 1, 2, 3)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, 1, 2, 3)), jnp.float32)
        positions = jnp.full((2, 1), pos, jnp.int32)
        variables = {"cache": cache} if cache is not None else {}
        (k_full, v_full, mask), mutated = probe.apply(
            variables, k, v, positions, table, mutable=["cache"]
        )
        cache = mutated["cache"]
        written[pos] = np.asarray(k)
        # the gathered logical view holds every row written so far, in order
        for p_seen, kk in written.items():
            np.testing.assert_array_equal(np.asarray(k_full)[:, p_seen], kk[:, 0])
        # mask admits exactly the written prefix
        np.testing.assert_array_equal(
            np.asarray(mask)[0, 0, 0], np.arange(P * ps) <= pos
        )
    # physical placement: slot 0 wrote pages 2,5,1; slot 1 wrote 4,3,0
    pool_k = np.asarray(cache["cached_key"])
    np.testing.assert_array_equal(pool_k[5, 3], written[7][0, 0])  # slot 0, pos 7
    np.testing.assert_array_equal(pool_k[3, 0], written[4][1, 0])  # slot 1, pos 4


# ------------------------------------------------------------------ parity


def test_paged_contiguous_and_static_parity_with_slot_reuse():
    """Acceptance pin: greedy decode is token-identical between the paged and
    contiguous cache paths across a slot-reuse workload, and both match the
    static Generator."""
    model = _model()
    rng = np.random.default_rng(3)
    lengths = [5, 9, 3, 12, 7, 4]
    budgets = [6, 4, 8, 3, 5, 7]
    prompts = [rng.integers(1, 128, (n,)).astype(np.int32) for n in lengths]
    requests = lambda: [  # noqa: E731 — fresh Request objects per engine
        Request(i, p, max_new_tokens=m) for i, (p, m) in enumerate(zip(prompts, budgets))
    ]
    paged = ContinuousBatcher(model, num_slots=2, max_length=32, chunk_size=4, page_size=8)
    contiguous = ContinuousBatcher(model, num_slots=2, max_length=32, chunk_size=4, paged=False)
    out_p = paged.run(requests())
    out_c = contiguous.run(requests())
    for i, (p, m) in enumerate(zip(prompts, budgets)):
        np.testing.assert_array_equal(out_p[i], out_c[i])
        np.testing.assert_array_equal(out_p[i], _static_reference(model, p, m))
    assert paged.trace_counts["decode_chunk"] == 1
    assert paged.pool.pages_in_use == 0
    assert paged.pool.check_consistency() == []


def test_shared_prefix_parity_and_tokens_saved():
    """Requests sharing a system prompt: greedy outputs stay token-identical to
    the static path AND to a prefix-cache-disabled engine, while the prefix
    cache demonstrably skips prefill work (prefill_tokens_saved > 0)."""
    model = _model()
    rng = np.random.default_rng(4)
    system = rng.integers(1, 128, (13,)).astype(np.int32)  # 3 full pages at ps=4
    prompts = [
        np.concatenate([system, rng.integers(1, 128, (n,)).astype(np.int32)])
        for n in (3, 6, 2, 5)
    ]
    requests = lambda: [Request(i, p, max_new_tokens=5) for i, p in enumerate(prompts)]  # noqa: E731
    cached = ContinuousBatcher(model, num_slots=2, max_length=64, chunk_size=4, page_size=4)
    plain = ContinuousBatcher(
        model, num_slots=2, max_length=64, chunk_size=4, page_size=4, prefix_cache=False
    )
    out_cached = cached.run(requests())
    out_plain = plain.run(requests())
    for i, p in enumerate(prompts):
        np.testing.assert_array_equal(out_cached[i], out_plain[i])
        np.testing.assert_array_equal(out_cached[i], _static_reference(model, p, 5))
    saved = cached.stats["prefix_cache"]["prefill_tokens_saved"]
    assert saved >= 3 * 4 * 3, saved  # 3 later requests x 3 shared pages x 4 tokens
    assert cached.stats["prefix_cache"]["hits"] >= 9
    assert plain.stats["prefix_cache"]["prefill_tokens_saved"] == 0
    # full-prompt page-aligned hit still produces first-token logits: a request
    # whose prompt is EXACTLY the cached pages must recompute its last token
    exact = np.asarray(system[:12])  # exactly 3 pages
    out = cached.run([Request(10, exact, max_new_tokens=4)])
    np.testing.assert_array_equal(out[10], _static_reference(model, exact, 4))


def test_gpt_neox_paged_parity():
    """The paged slot cache is model-layer plumbing for BOTH slot families."""
    import dataclasses

    from accelerate_tpu.models.gpt_neox import create_gpt_neox_model, gpt_neox_tiny

    cfg = dataclasses.replace(gpt_neox_tiny(), max_position_embeddings=64)
    model = create_gpt_neox_model(cfg, seq_len=32)
    rng = np.random.default_rng(5)
    shared = rng.integers(1, cfg.vocab_size, (9,)).astype(np.int32)
    prompts = [
        np.concatenate([shared, rng.integers(1, cfg.vocab_size, (n,)).astype(np.int32)])
        for n in (2, 4)
    ]
    engine = ContinuousBatcher(model, num_slots=2, max_length=32, chunk_size=4, page_size=8)
    outputs = engine.run([Request(i, p, max_new_tokens=5) for i, p in enumerate(prompts)])
    for i, p in enumerate(prompts):
        np.testing.assert_array_equal(outputs[i], _static_reference(model, p, 5))
    assert engine.stats["prefix_cache"]["prefill_tokens_saved"] == 8


def test_slot_reuse_never_exposes_prior_occupants_tokens():
    """A slot's (and its freed pages') next occupant with a SHORTER prompt and
    a longer budget must decode exactly as if the pool were fresh — the masked
    stale K/V from the previous occupant contributes exactly nothing."""
    model = _model()
    rng = np.random.default_rng(6)
    long_prompt = rng.integers(1, 128, (24,)).astype(np.int32)
    short_prompt = rng.integers(1, 128, (3,)).astype(np.int32)
    engine = ContinuousBatcher(model, num_slots=1, max_length=32, chunk_size=4, page_size=4)
    first = engine.run([Request(0, long_prompt, max_new_tokens=6)])
    np.testing.assert_array_equal(first[0], _static_reference(model, long_prompt, 6))
    # same single slot, same pages, different occupant
    second = engine.run([Request(1, short_prompt, max_new_tokens=12)])
    np.testing.assert_array_equal(second[1], _static_reference(model, short_prompt, 12))


def test_repeated_workload_mints_no_new_insert_buckets():
    """Steady-state no-recompile pin for prefix serving: re-serving prompts
    that registered their OWN pages matches deeper (tiny suffixes) — the
    page-size bucket floor must absorb those instead of minting ever-smaller
    insert executables. Pass 2 may deepen matches; pass 3 must compile
    NOTHING new and stay token-identical."""
    model = _model()
    rng = np.random.default_rng(8)
    system = rng.integers(1, 128, (10,)).astype(np.int32)
    prompts = [
        np.concatenate([system, rng.integers(1, 128, (n,)).astype(np.int32)])
        for n in (2, 5, 3)
    ]
    engine = ContinuousBatcher(model, num_slots=2, max_length=64, chunk_size=4, page_size=4)
    outputs = {}
    for round_no in range(3):
        if round_no == 2:
            stable = dict(engine.trace_counts)
        out = engine.run([Request(round_no * 10 + i, p, max_new_tokens=5) for i, p in enumerate(prompts)])
        outputs[round_no] = [out[round_no * 10 + i] for i in range(len(prompts))]
        for i in range(len(prompts)):
            engine.release(round_no * 10 + i)
    assert engine.trace_counts == stable, (stable, engine.trace_counts)
    assert engine.trace_counts["decode_chunk"] == 1
    for i in range(len(prompts)):
        np.testing.assert_array_equal(outputs[0][i], outputs[2][i])


# ------------------------------------------------------------------ admission


def test_page_based_admission_exceeds_old_slot_capacity():
    """Acceptance pin: a pool of 8x8=64 tokens backs FOUR concurrent slots
    whose worst-case rows (4 x max_length 64 = 256 tokens) would have required
    4x the HBM under the contiguous layout — and a fifth request queues on pool
    exhaustion, then completes once pages free (no deadlock, no error)."""
    model = _model()
    rng = np.random.default_rng(7)
    engine = ContinuousBatcher(
        model, num_slots=4, max_length=64, chunk_size=2, page_size=8, num_pages=9
    )
    prompts = [rng.integers(1, 128, (6,)).astype(np.int32) for _ in range(5)]
    for i in range(4):
        engine.submit(Request(i, prompts[i], max_new_tokens=10))  # 2 pages each
    engine.step()
    assert engine.free_slots == 0, "all four requests must be in flight at once"
    assert engine.pool.pages_in_use == 8
    engine.submit(Request(4, prompts[4], max_new_tokens=10))
    engine.step()
    assert not engine.results[4].tokens, "fifth request must wait for pages"
    outputs = engine.run()
    for i in range(5):
        assert engine.results[i].finish_reason == "length"
        np.testing.assert_array_equal(outputs[i], _static_reference(model, prompts[i], 10))
    assert engine.pool.pages_in_use == 0
    assert engine.pool.check_consistency() == []


def test_submit_rejects_requests_larger_than_the_pool():
    model = _model()
    engine = ContinuousBatcher(
        model, num_slots=2, max_length=64, chunk_size=2, page_size=8, num_pages=3
    )
    with pytest.raises(ValueError, match="KV pages"):
        engine.submit(Request(0, np.arange(1, 20, dtype=np.int32), max_new_tokens=8))
    # within the pool: fine
    engine.submit(Request(1, np.arange(1, 9, dtype=np.int32), max_new_tokens=8))
    engine.run()
    assert engine.results[1].finished


# ------------------------------------------------------------------ allocator


def test_chain_hashes_commit_to_the_whole_prefix():
    a = chain_hashes([1, 2, 3, 4, 5, 6, 7, 8, 9], 4)
    b = chain_hashes([1, 2, 3, 4, 5, 6, 7, 8], 4)
    c = chain_hashes([9, 2, 3, 4, 5, 6, 7, 8], 4)
    assert len(a) == 2 and len(b) == 2 and a == b  # partial trailing page unhashed
    assert c[0] != a[0] and c[1] != a[1]  # first-token change breaks EVERY page


def test_page_pool_refcounts_prefix_cache_and_eviction():
    pool = PagePool(num_pages=6, page_size=4)
    hashes = chain_hashes(list(range(8)), 4)
    pages = pool.reserve(3)
    assert pages is not None and SCRATCH_PAGE not in pages
    assert pool.pages_in_use == 3 and pool.pages_free == 2
    pool.register_prefix(hashes, pages)  # first two pages become shareable
    # a second request sharing both prefix pages pins them
    matched = pool.match_prefix(hashes, 2)
    assert matched == pages[:2]
    pool.release(matched)
    pool.release(pages)
    assert pool.pages_in_use == 0
    assert pool.pages_cached == 2 and pool.pages_free == 3  # prefix pages stay cached
    assert pool.check_consistency() == []
    # exhausting the free list evicts cached prefix pages LRU, oldest first
    big = pool.reserve(5)
    assert big is not None and pool.evictions == 2
    assert pool.prefix_entries == 0 and pool.match_prefix(hashes, 2) == []
    pool.release(big)
    assert pool.check_consistency() == []
    # over-reserve refuses without partially draining
    assert pool.reserve(6) is None
    assert pool.pages_free == 5


def test_eviction_trims_cached_prefix_chains_from_the_deep_end():
    """Pool pressure must degrade a cached prefix gracefully: evict the chain
    TAIL first so the surviving head pages still match — evicting the head
    would strand every deeper cached page of the chain unmatchable."""
    pool = PagePool(num_pages=5, page_size=4)
    hashes = chain_hashes(list(range(12)), 4)  # 3-page chain
    pages = pool.reserve(3)
    pool.register_prefix(hashes, pages)
    pool.release(pages)  # chain order in, all three now cached
    assert pool.pages_cached == 3 and pool.pages_free == 1
    taken = pool.reserve(2)  # 1 free + 1 eviction
    assert pool.evictions == 1
    # the DEEPEST page went; the head two still serve a partial match
    assert pool.match_prefix(hashes, 3) == pages[:2]
    pool.release(pages[:2])
    pool.release(taken)
    assert pool.check_consistency() == []


def test_page_pool_reset_forgets_prefixes_and_refuses_bad_release():
    pool = PagePool(num_pages=4, page_size=2)
    hashes = chain_hashes([1, 2, 3, 4], 2)
    pages = pool.reserve(2)
    pool.register_prefix(hashes, pages)
    pool.reset()
    assert pool.pages_in_use == 0 and pool.pages_free == 3
    assert pool.prefix_entries == 0, "reset must forget prefixes (content is gone)"
    assert pool.match_prefix(hashes, 2) == []
    with pytest.raises(ValueError, match="refcount"):
        pool.release([1])
    with pytest.raises(ValueError, match="scratch"):
        pool.release([SCRATCH_PAGE])
    assert pool.check_consistency() == []


def test_admission_bucket_planner_is_a_closed_set():
    """Satellite pin (the serving_bench first-hit recompile fix): over the
    WHOLE admission domain — every prompt length x prefix-match depth x
    several pool geometries — the planned insert bucket is a power of two or
    the single capped top value, the kept prefix still fits the cache window,
    and the suffix still fits the bucket. An open set of matched_len-dependent
    remainder buckets is exactly what used to compile a fresh insert on the
    first deep prefix hit of a timed run."""
    for page_size, padded in ((16, 128), (16, 120), (4, 40), (8, 72), (4, 24)):
        ladder_limit = padded
        for p in range(1, padded + 1):
            for matched in range(0, p // page_size + 1):
                bucket, keep = ContinuousBatcher.plan_admission_bucket(
                    p, matched, page_size, padded
                )
                matched_len = keep * page_size
                assert keep <= matched
                assert p - matched_len <= bucket, (p, matched, bucket, keep)
                assert matched_len + bucket <= padded, (p, matched, bucket, keep)
                assert bucket & (bucket - 1) == 0 or bucket == ladder_limit, (
                    p, matched, bucket,
                )


def test_warm_inserts_precompiles_every_reachable_bucket():
    """After warm_inserts(), NO admission — whatever prompt length or
    prefix-cache depth — compiles a new insert executable, and warming leaves
    engine state untouched (admissions still serve token-identically)."""
    model = _model()
    engine = ContinuousBatcher(model, num_slots=2, max_length=24, chunk_size=4, page_size=4)
    warmed = engine.warm_inserts()
    assert warmed == engine.insert_bucket_ladder() == [1, 2, 4, 8, 16, 24]
    baseline = dict(engine.trace_counts)
    rng = np.random.default_rng(3)
    system = rng.integers(1, 128, (8,)).astype(np.int32)
    rid = 0
    for trial in range(10):
        tail = rng.integers(1, 128, (int(rng.integers(1, 17)),)).astype(np.int32)
        prompt = np.concatenate([system, tail])[:20] if trial % 2 else tail
        out = engine.run([Request(rid, prompt, max_new_tokens=4)])
        reference = _static_reference(model, prompt, 4)
        np.testing.assert_array_equal(np.asarray(out[rid]), reference)
        engine.release(rid)
        rid += 1
    assert engine.trace_counts["insert"] == baseline["insert"], (
        baseline, engine.trace_counts,
    )

"""TraceGuard runtime tests: steady-state serving and train steps hold the
no-recompile / no-guarded-transfer discipline on CPU, a deliberately
shape-unstable loop is caught WITH the executable's name, and the
`Accelerator(analyze=True)` + test_utils fixture wiring works end to end."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.analysis import TraceGuard, TraceGuardViolation
from accelerate_tpu.data_loader import BatchSampler
from accelerate_tpu.test_utils.analysis_fixtures import assert_compiles

from test_training import make_regression_data, make_regression_model

pytestmark = pytest.mark.analysis


def _tiny_llama():
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model

    cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
    )
    return create_llama_model(cfg, seq_len=32)


# ------------------------------------------------------------------ serving
def test_serving_steady_state_is_clean(trace_guard):
    """3+ steady-state ContinuousBatcher.step() iterations: 0 recompiles, 0
    guarded transfers (the acceptance criterion's serving half)."""
    from accelerate_tpu.serving import ContinuousBatcher, Request

    engine = ContinuousBatcher(_tiny_llama(), num_slots=2, max_length=64, chunk_size=4)
    rng = np.random.default_rng(0)
    # Warmup: compile the insert bucket + the one decode-chunk executable.
    for i in range(3):
        engine.submit(Request(i, rng.integers(1, 128, (5,)).astype(np.int32), max_new_tokens=12))
    while engine.pending:
        engine.step()
    for i in range(3):
        engine.release(i)

    # Steady state: same prompt bucket, fresh requests, guard armed.
    for i in range(10, 13):
        engine.submit(Request(i, rng.integers(1, 128, (6,)).astype(np.int32), max_new_tokens=12))
    guard = trace_guard(name="serving-steady")
    engine.trace_guard = guard
    steps = 0
    with guard:
        while engine.pending and steps < 25:
            engine.step()
            steps += 1
    assert steps >= 3
    assert_compiles(guard, exactly=0)
    assert engine.trace_counts["decode_chunk"] == 1  # compiled once, ever
    reasons = {r.finish_reason for r in engine.results.values()}
    assert reasons <= {"eos", "length"}, reasons


# ----------------------------------------------------------------- training
def test_train_step_steady_state_is_clean(trace_guard):
    """3 steady-state fused train-step iterations under the guard: 0/0."""
    data = make_regression_data(n=32)
    accelerator = Accelerator()
    model = make_regression_model(seed=0)
    dl = SimpleDataLoader(data, BatchSampler(range(len(data)), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
    step_fn = accelerator.train_step()
    batches = list(pdl)
    step_fn(batches[0])  # warmup compile

    guard = trace_guard(name="train-steady")
    with guard:
        for batch in batches[1:4]:
            step_fn(batch)
    assert guard.steps == 0  # fixture guards are armed manually, not per-call
    assert_compiles(guard, exactly=0)


def test_accelerator_analyze_wraps_train_step():
    """Accelerator(analyze=True): steady-state steps pass, a shape-unstable
    batch raises TraceGuardViolation naming the recompiled executable."""
    data = make_regression_data(n=48)
    accelerator = Accelerator(analyze=True)
    assert accelerator.trace_guard is not None
    model = make_regression_model(seed=0)
    dl = SimpleDataLoader(data, BatchSampler(range(len(data)), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
    step_fn = accelerator.train_step()
    batches = list(pdl)
    for batch in batches[:5]:  # warmup allowance (2) + 3 guarded steady steps
        step_fn(batch)
    assert accelerator.trace_guard.steps == 3
    assert accelerator.trace_guard.total_recompiles == 0
    assert accelerator.trace_guard.host_transfers == 0

    # A shape-unstable batch (different batch dim) in steady state = caught.
    small = {k: v[:5] for k, v in batches[0].items()}
    with pytest.raises(TraceGuardViolation) as excinfo:
        step_fn(small)
    assert "fused" in str(excinfo.value)  # the executable is named
    assert excinfo.value.report.total_recompiles >= 1


# ------------------------------------------------------------ guard mechanics
def test_unstable_loop_is_caught_and_named():
    xs = [jnp.ones(n) for n in (4, 5, 6)]

    def unstable_step(x):
        return (x * 2).sum()

    f = jax.jit(unstable_step)
    f(xs[0])  # warmup one shape
    with pytest.raises(TraceGuardViolation) as excinfo:
        with TraceGuard(name="unstable"):
            for x in xs[1:]:
                f(x)
    msg = str(excinfo.value)
    assert "unstable_step" in msg and "recompiled" in msg
    assert excinfo.value.report.compiles.get("unstable_step") == 2


def test_record_mode_counts_without_raising():
    xs = [jnp.ones(n) for n in (3, 7)]
    f = jax.jit(lambda x: x + 1)
    guard = TraceGuard(on_violation="record", name="record-mode")
    with guard:
        for x in xs:
            f(x)
    assert guard.total_recompiles == 2
    assert guard.compiles  # per-executable ledger populated


def test_wrap_warmup_allowance():
    f = jax.jit(lambda x: (x * 3).sum())
    xs = [jnp.ones(4), jnp.ones(9)]
    guard = TraceGuard(name="wrapped")
    wrapped = guard.wrap(f, warmup=1)
    wrapped(xs[0])  # warmup: compile allowed
    wrapped(xs[0])
    wrapped(xs[0])
    assert guard.steps == 2 and guard.total_recompiles == 0
    with pytest.raises(TraceGuardViolation):
        wrapped(xs[1])


def test_transfer_guard_catches_implicit_transfer():
    """Raw numpy leaking into a warm jitted call = implicit h2d = caught; the
    sanctioned jnp.asarray push passes."""
    f = jax.jit(lambda x: x * 2)
    warm = jnp.ones(3)
    f(warm)
    guard = TraceGuard(name="transfers")
    with guard:
        f(jnp.asarray(np.ones(3, np.float32)))  # explicit: sanctioned
    with pytest.raises(Exception) as excinfo:
        with TraceGuard(name="transfers-2", on_violation="record"):
            f(np.ones(3, np.float32))  # implicit: guarded at the call site
    assert TraceGuard.is_transfer_violation(excinfo.value)


def test_observe_classifies_and_records():
    guard = TraceGuard(on_violation="record")
    assert not guard.observe(ValueError("unrelated"))
    fake = RuntimeError(
        "INVALID_ARGUMENT: Disallowed host-to-device transfer: aval=ShapedArray(int32[])"
    )
    assert guard.observe(fake)
    assert guard.host_transfers == 1


def test_disarmed_guard_ignores_outside_compiles():
    """Regression: guards must leave the monitoring fan-out on exit — compiles
    OUTSIDE the armed window must never reach the ledger, and per-step
    re-arming (wrap) must not accumulate stale registrations."""
    from accelerate_tpu.analysis.trace_guard import _ARMED_GUARDS

    guard = TraceGuard(on_violation="raise", name="disarmed")
    f = jax.jit(lambda x: x - 1)
    x = jnp.ones(4)
    f(x)  # warmup
    wrapped = guard.wrap(f, warmup=1)
    for _ in range(5):
        wrapped(x)
    assert guard not in _ARMED_GUARDS
    jax.jit(lambda x: x * 5)(x)  # unrelated compile, no guard armed
    assert guard.total_recompiles == 0
    wrapped(x)  # steady step after the unrelated compile: must NOT raise


def test_wrapped_transfer_violation_counted_once():
    """Regression: a guarded transfer inside a wrap()ped call is observe()d by
    __exit__ exactly once, not double-counted by the wrapper."""
    f = jax.jit(lambda x: x * 2)
    f(jnp.ones(3))
    guard = TraceGuard(on_violation="record", name="once")
    wrapped = guard.wrap(f, warmup=0)
    with pytest.raises(Exception) as excinfo:
        wrapped(np.ones(3, np.float32))  # implicit h2d: guarded at the site
    assert TraceGuard.is_transfer_violation(excinfo.value)
    assert guard.host_transfers == 1, guard.transfer_violations


def test_guard_restores_logging_state():
    before = bool(jax.config.jax_log_compiles)
    with TraceGuard(on_violation="record"):
        assert bool(jax.config.jax_log_compiles) is True
    assert bool(jax.config.jax_log_compiles) is before

"""Hook engine tests (reference tests/test_hooks.py, 401 LoC): attach/detach, ordering,
SequentialHook, append chaining, CpuOffload round-trips, and arg/output rewriting."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.hooks import (
    CpuOffload,
    ModelHook,
    SequentialHook,
    add_hook_to_module,
    cpu_offload_with_hook,
    remove_hook_from_module,
)
from accelerate_tpu.modeling import Model


def _model(scale=2.0):
    params = {"w": jnp.asarray([scale])}

    def apply_fn(p, x):
        return x * p["w"]

    return Model.from_fn(apply_fn, params)


class PlusOne(ModelHook):
    def post_forward(self, model, output):
        return output + 1


class TimesTwoInput(ModelHook):
    def pre_forward(self, model, params, args, kwargs):
        return params, tuple(a * 2 for a in args), kwargs


class Recorder(ModelHook):
    def __init__(self, log, tag):
        self.log = log
        self.tag = tag

    def init_hook(self, model):
        self.log.append(f"init:{self.tag}")
        return model

    def pre_forward(self, model, params, args, kwargs):
        self.log.append(f"pre:{self.tag}")
        return params, args, kwargs

    def post_forward(self, model, output):
        self.log.append(f"post:{self.tag}")
        return output

    def detach_hook(self, model):
        self.log.append(f"detach:{self.tag}")
        return model


def test_add_and_remove_hook():
    m = _model()
    x = jnp.asarray([3.0])
    assert float(m.apply_fn(m.params, x)[0]) == 6.0
    add_hook_to_module(m, PlusOne())
    assert float(m.apply_fn(m.params, x)[0]) == 7.0
    remove_hook_from_module(m)
    assert float(m.apply_fn(m.params, x)[0]) == 6.0
    assert m._atl_hook is None


def test_pre_forward_rewrites_args():
    m = _model()
    add_hook_to_module(m, TimesTwoInput())
    assert float(m.apply_fn(m.params, jnp.asarray([3.0]))[0]) == 12.0


def test_sequential_hook_order():
    log = []
    m = _model()
    hook = SequentialHook(Recorder(log, "a"), Recorder(log, "b"))
    add_hook_to_module(m, hook)
    m.apply_fn(m.params, jnp.asarray([1.0]))
    remove_hook_from_module(m)
    assert log == ["init:a", "init:b", "pre:a", "pre:b", "post:a", "post:b", "detach:a", "detach:b"]


def test_append_chains_hooks():
    m = _model()
    add_hook_to_module(m, PlusOne())
    add_hook_to_module(m, PlusOne(), append=True)
    # (x*w) + 1 + 1
    assert float(m.apply_fn(m.params, jnp.asarray([3.0]))[0]) == 8.0


def test_cpu_offload_hook_round_trip():
    m = _model()
    m, handle = cpu_offload_with_hook(m)
    # params live on host between calls
    assert isinstance(jax.tree_util.tree_leaves(m.params)[0], np.ndarray) or not hasattr(
        jax.tree_util.tree_leaves(m.params)[0], "devices"
    )
    out = m.apply_fn(m.params, jnp.asarray([2.0]))
    assert float(out[0]) == 4.0
    handle.offload()
    handle.remove()
    assert m._atl_hook is None


def test_prev_module_hook_offloads_predecessor():
    a = _model(2.0)
    b = _model(3.0)
    a, handle_a = cpu_offload_with_hook(a)
    b, handle_b = cpu_offload_with_hook(b, prev_module_hook=handle_a)
    x = jnp.asarray([1.0])
    a.apply_fn(a.params, x)
    # running b triggers handle_a.offload() first — must not error, outputs correct
    out = b.apply_fn(b.params, x)
    assert float(out[0]) == 3.0


def test_profiler_writes_trace(tmp_path):
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    accelerator = Accelerator()
    with accelerator.profile(log_dir=str(tmp_path)):
        jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    import glob
    import os

    files = glob.glob(os.path.join(str(tmp_path), "**", "*"), recursive=True)
    assert any("xplane" in f or f.endswith(".pb") or f.endswith(".json.gz") for f in files), files

"""Every accepted FSDP knob must have observable behavior (round-3 verdict item 2;
reference semantics: accelerator.py:1460-1545 activation checkpointing + low-precision
params, dataclasses.py:1173-1203 auto-wrap policies).

Covers: activation_checkpointing (per-layer remat lowers compiled temp memory),
param_dtype (storage dtype), reduce_dtype (accumulation-buffer dtype and its
numerical effect), auto_wrap_policy TRANSFORMER_BASED_WRAP / SIZE_BASED_WRAP /
NO_WRAP (which params join the fsdp shard group), state_dict_type (export layout),
and the env-protocol round trip for all of them.
"""

import dataclasses as dc
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu import Accelerator, Model
from accelerate_tpu.utils import FullyShardedDataParallelPlugin, ParallelismConfig


def _bert(seq_len=32):
    from accelerate_tpu.models import bert_tiny, create_bert_model

    return create_bert_model(bert_tiny(), seq_len=seq_len)


def _batch(bs=8, seq=32, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": rng.integers(1, 500, size=(bs, seq)).astype(np.int32),
        "labels": rng.integers(0, 2, size=(bs,)).astype(np.int64),
    }


# ------------------------------------------------------------ activation checkpointing
def test_activation_checkpointing_lowers_compiled_temp_memory():
    """The knob must CHANGE THE PROGRAM: remat appears in the grad jaxpr and the
    compiled temp allocation shrinks (reference applies checkpoint_wrapper per
    FSDP block, accelerator.py:1460-1474)."""
    from accelerate_tpu.models.llama import causal_lm_loss, create_llama_model, llama_tiny

    cfg = dc.replace(llama_tiny(), num_hidden_layers=4)
    model = create_llama_model(cfg, seq_len=128)
    ids = jnp.ones((8, 128), jnp.int32)

    def loss(p):
        return causal_lm_loss(p, {"input_ids": ids}, lambda p_, i, am=None: model.apply_fn(p_, i))

    def compile_grad(remat_policy):
        from accelerate_tpu.ops.remat import remat_scope

        if remat_policy is None:
            return jax.jit(jax.grad(loss)).lower(model.params).compile()
        with remat_scope(remat_policy):
            return jax.jit(jax.grad(loss)).lower(model.params).compile()

    base = compile_grad(None).memory_analysis().temp_size_in_bytes
    remat = compile_grad("full").memory_analysis().temp_size_in_bytes
    assert remat < base, f"remat must lower temp memory: {remat} !< {base}"


def test_plugin_activation_checkpointing_reaches_prepared_model():
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(data=1, fsdp=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(activation_checkpointing=True, min_num_params=1),
    )
    pmodel = accelerator.prepare(_bert())
    assert pmodel.remat_policy == "full"
    batch = _batch()
    jaxpr = jax.make_jaxpr(lambda p: pmodel.loss(p, batch))(pmodel.params)
    assert "remat" in str(jaxpr), "prepared model's loss must trace layers under remat"
    # and the model still trains
    popt = accelerator.prepare(optax.adam(1e-3))
    loss = accelerator.backward(pmodel.loss, batch)
    popt.step()
    assert np.isfinite(float(loss))


# ------------------------------------------------------------------------ param_dtype
def test_param_dtype_controls_storage_dtype():
    accelerator = Accelerator(
        mixed_precision="bf16",
        fsdp_plugin=FullyShardedDataParallelPlugin(param_dtype="bfloat16", min_num_params=1),
    )
    pmodel = accelerator.prepare(_bert())
    float_leaves = [
        l for l in jax.tree_util.tree_leaves(pmodel.params) if jnp.issubdtype(l.dtype, jnp.floating)
    ]
    assert float_leaves and all(l.dtype == jnp.bfloat16 for l in float_leaves)
    # training step end-to-end: grads/opt-state follow the bf16 storage dtype
    popt = accelerator.prepare(optax.adam(1e-3))
    step = accelerator.train_step(model=pmodel)
    loss = step(_batch())
    assert np.isfinite(float(loss))
    new_float = [
        l for l in jax.tree_util.tree_leaves(pmodel.params) if jnp.issubdtype(l.dtype, jnp.floating)
    ]
    assert all(l.dtype == jnp.bfloat16 for l in new_float), "update must preserve param_dtype"


# ----------------------------------------------------------------------- reduce_dtype
def test_reduce_dtype_keeps_accumulation_exact():
    """With bf16 params, accumulating k microbatch gradients in bf16 rolls tiny
    contributions off the mantissa; reduce_dtype='float32' must keep them. This is
    the knob's observable behavior, not a config echo."""
    import flax.linen as nn

    class Scalar(nn.Module):
        @nn.compact
        def __call__(self, x):
            w = self.param("w", nn.initializers.ones, ())
            return w * x

    module = Scalar()
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), module.init(jax.random.key(0), jnp.ones(()))
    )

    def loss_fn(p, batch, apply_fn=None):
        # grad wrt w is mean(x): first microbatch 1.0, later ones 2**-10 each —
        # in bf16, 1.0 + 2**-10 rounds back to 1.0.
        return jnp.mean(module.apply(p, batch["x"]))

    def run(reduce_dtype):
        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        for cls in (PartialState, AcceleratorState, GradientState):
            cls._reset_state()
        plugin = FullyShardedDataParallelPlugin(reduce_dtype=reduce_dtype, min_num_params=10**9)
        accelerator = Accelerator(fsdp_plugin=plugin)
        model = Model.from_fn(module.apply, params, loss_fn=loss_fn)
        pmodel = accelerator.prepare(model)
        popt = accelerator.prepare(optax.sgd(1.0))
        # Per-microbatch grads after the 1/k scale: [1.0, 2**-9 x7]. Sequential
        # bf16 accumulation rounds each 1.0 + 2**-9 back to 1.0 (eps at 1.0 is
        # 2**-8); an fp32 buffer keeps 1 + 7*2**-9, which survives the final
        # cast back to bf16 (rounds to 1.015625).
        x = np.full((8,), 2.0**-6, np.float32)
        x[0] = 8.0
        step = accelerator.train_step(model=pmodel, accumulation_steps=8)
        step({"x": jnp.asarray(x, jnp.bfloat16)})
        return float(jax.tree_util.tree_leaves(pmodel.params)[0])

    w_bf16 = run(None)
    w_fp32 = run("float32")
    assert w_bf16 == 0.0, "bf16 accumulation must roll the tiny contributions off"
    assert abs(w_fp32 - (1.0 - 1.015625)) < 1e-6, f"fp32 buffer must keep them: {w_fp32}"


def test_eager_accumulation_buffer_uses_reduce_dtype():
    accelerator = Accelerator(
        fsdp_plugin=FullyShardedDataParallelPlugin(param_dtype="bfloat16", reduce_dtype="float32")
    )
    pmodel = accelerator.prepare(_bert())
    popt = accelerator.prepare(optax.adam(1e-3))
    accelerator.backward(pmodel.loss, _batch())
    grads = popt.grads
    assert all(
        l.dtype == jnp.float32
        for l in jax.tree_util.tree_leaves(grads)
        if jnp.issubdtype(l.dtype, jnp.floating)
    ), "eager accumulation buffer must hold reduce_dtype"
    popt.step()  # update must still work (grads cast back to param dtype inside)


# ------------------------------------------------------------------- auto_wrap_policy
def test_transformer_based_wrap_restricts_sharding_to_matching_paths():
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(data=1, fsdp=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            auto_wrap_policy="TRANSFORMER_BASED_WRAP",
            transformer_cls_names_to_wrap=["layer_"],
            min_num_params=1,
        ),
    )
    pmodel = accelerator.prepare(_bert())
    from accelerate_tpu.parallel.sharding import tree_paths_and_leaves

    flat, _ = tree_paths_and_leaves(pmodel.params)
    layer_sharded = [p for p, l in flat if "layer_" in p and "fsdp" in str(l.sharding.spec)]
    non_layer_sharded = [p for p, l in flat if "layer_" not in p and "fsdp" in str(l.sharding.spec)]
    assert layer_sharded, "transformer layers must shard over fsdp"
    assert not non_layer_sharded, f"non-wrapped params must stay replicated: {non_layer_sharded}"


def test_no_wrap_shards_everything_divisible():
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(data=1, fsdp=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(auto_wrap_policy="NO_WRAP"),
    )
    pmodel = accelerator.prepare(_bert())
    from accelerate_tpu.parallel.sharding import tree_paths_and_leaves

    flat, _ = tree_paths_and_leaves(pmodel.params)
    # Even small-but-divisible params (e.g. 128-wide biases < the 2**16 default
    # threshold) shard: NO_WRAP is one root unit, no size cutoff.
    small_sharded = [
        p
        for p, l in flat
        if l.size < 2**16 and l.ndim >= 1 and l.shape[-1] % 8 == 0 and "fsdp" in str(l.sharding.spec)
    ]
    assert small_sharded, "NO_WRAP must ignore the size threshold"


def test_transformer_wrap_without_names_rejected():
    with pytest.raises(ValueError, match="transformer_cls_names_to_wrap"):
        FullyShardedDataParallelPlugin(auto_wrap_policy="TRANSFORMER_BASED_WRAP")


# ------------------------------------------------------------------- env-var protocol
def test_fsdp_knob_env_round_trip(monkeypatch):
    monkeypatch.setenv("ACCELERATE_TPU_FSDP_AUTO_WRAP_POLICY", "TRANSFORMER_BASED_WRAP")
    monkeypatch.setenv("ACCELERATE_TPU_FSDP_TRANSFORMER_CLS_TO_WRAP", "layer_,block_")
    monkeypatch.setenv("ACCELERATE_TPU_FSDP_PARAM_DTYPE", "bfloat16")
    monkeypatch.setenv("ACCELERATE_TPU_FSDP_REDUCE_DTYPE", "float32")
    monkeypatch.setenv("ACCELERATE_TPU_FSDP_SYNC_MODULE_STATES", "false")
    plugin = FullyShardedDataParallelPlugin()
    assert plugin.auto_wrap_policy == "TRANSFORMER_BASED_WRAP"
    assert plugin.transformer_cls_names_to_wrap == ["layer_", "block_"]
    assert plugin.param_dtype == "bfloat16"
    assert plugin.reduce_dtype == "float32"
    assert plugin.sync_module_states is False


def test_bad_param_dtype_rejected():
    with pytest.raises(ValueError, match="param_dtype"):
        FullyShardedDataParallelPlugin(param_dtype="float64")


# ------------------------------------------------------------------- state_dict_type
def test_save_model_sharded_safetensors_round_trip(tmp_path):
    """save_model writes (sharded) safetensors + index for an fsdp-sharded model;
    the export loads back identical (round-3 verdict item 9)."""
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(data=1, fsdp=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(min_num_params=1),
    )
    pmodel = accelerator.prepare(_bert())
    out = tmp_path / "export"
    # Tiny shard budget forces the multi-file + index layout.
    accelerator.save_model(pmodel, str(out), max_shard_size=200_000)
    from accelerate_tpu.utils.constants import SAFE_WEIGHTS_INDEX_NAME

    assert (out / SAFE_WEIGHTS_INDEX_NAME).exists(), "sharded export must write the index"
    shards = list(out.glob("model-*.safetensors"))
    assert len(shards) > 1, "200kB budget must split this model"

    from accelerate_tpu.checkpointing import load_model_safetensors

    restored = load_model_safetensors(str(out))
    orig_flat, _ = jax.tree_util.tree_flatten(jax.tree_util.tree_map(np.asarray, pmodel.params))
    rest_flat, _ = jax.tree_util.tree_flatten(restored)
    assert len(orig_flat) == len(rest_flat)
    for a, b in zip(orig_flat, rest_flat):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_save_model_single_file_when_under_budget(tmp_path):
    accelerator = Accelerator()
    pmodel = accelerator.prepare(_bert())
    out = tmp_path / "export"
    accelerator.save_model(pmodel, str(out))
    from accelerate_tpu.utils.constants import SAFE_WEIGHTS_NAME

    assert (out / SAFE_WEIGHTS_NAME).exists()
    from accelerate_tpu.checkpointing import load_model_safetensors

    restored = load_model_safetensors(str(out))
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.tree_util.tree_map(np.asarray, pmodel.params)),
        jax.tree_util.tree_leaves(restored),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_parse_size_fractional():
    from accelerate_tpu.checkpointing import _parse_size

    assert _parse_size("0.5GB") == 500_000_000
    assert _parse_size("1.5MB") == 1_500_000
    assert _parse_size(1234) == 1234


def test_param_dtype_preserved_through_chunked_offload():
    """The chunked-offload group updates must not promote bf16 params/opt-state to
    fp32 (the inv-scale + reduce_dtype hazards, caught in round-4 review)."""
    accelerator = Accelerator(
        mixed_precision="bf16",
        fsdp_plugin=FullyShardedDataParallelPlugin(
            param_dtype="bfloat16",
            reduce_dtype="float32",
            offload_optimizer_state=True,
            min_num_params=1,
        ),
    )
    pmodel = accelerator.prepare(_bert())
    popt = accelerator.prepare(optax.adam(1e-3))
    step = accelerator.train_step(model=pmodel)
    loss = step(_batch())
    assert np.isfinite(float(loss))
    for leaf in jax.tree_util.tree_leaves(pmodel.params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16
    for leaf in jax.tree_util.tree_leaves(popt.opt_state):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating) and leaf.ndim > 0:
            assert leaf.dtype == jnp.bfloat16, "offloaded opt state must keep the param dtype"

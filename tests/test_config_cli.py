"""`accelerate-tpu config` questionnaire + launch-env wiring (round-2 verdict, missing #2).

Reference pattern: the questionnaire (commands/config/cluster.py) writes a YAML that
`launch` reads back (`_validate_launch_command`, commands/launch.py:900-1065); here a
scripted stdin drives the full interactive flow end-to-end (the menu widget degrades
to numbered prompts off-TTY, which is exactly the scriptable path).
"""

import os
import subprocess
import sys

import yaml

from accelerate_tpu.commands.config import DEFAULT_CONFIG, load_config_file, write_basic_config
from accelerate_tpu.commands.launch import add_launch_args, build_launch_env


def run_config(tmp_path, answers):
    config_file = tmp_path / "config.yaml"
    result = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli", "config", "--config_file", str(config_file)],
        input="\n".join(answers) + "\n",
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "PYTHONPATH": os.pathsep.join(sys.path)},
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "configuration saved at" in result.stdout
    with open(config_file) as f:
        return yaml.safe_load(f), result


def test_questionnaire_default_flow(tmp_path):
    # Enter on every prompt = accept every default.
    config, _ = run_config(tmp_path, [""] * 12)
    assert config["compute_environment"] == "LOCAL_MACHINE"
    assert config["distributed_type"] == "XLA_SPMD"
    assert config["mixed_precision"] == "bf16"
    assert config["num_processes"] == 1
    assert config["mesh"] == DEFAULT_CONFIG["mesh"]
    assert "fsdp_config" not in config


def test_questionnaire_full_flow(tmp_path):
    answers = [
        "1",          # TPU pod
        "8",          # num host processes
        "10.0.0.2:8476",  # coordinator
        "y",          # tpu_use_cluster
        "v5e-pod",    # tpu_name
        "us-east5-b",  # tpu_zone
        "pip install -e .; echo ready",  # worker setup commands
        "y",          # customize mesh
        "-1", "4", "2", "2", "1", "1",  # data fsdp model seq expert stage
        "y",          # use FSDP
        "1",          # SHARD_GRAD_OP
        "2048",       # min_num_params
        "y",          # cpu_offload
        "y",          # activation checkpointing
        "0",          # SHARDED_STATE_DICT
        "0",          # ring attention (seq axis = 2 -> SP section auto-entered)
        "256",        # block size
        "0",          # bf16
        "n",          # downcast
        "4",          # grad accumulation
        "/tmp/xla-cache",  # compilation cache
        "y",          # debug
    ]
    config, _ = run_config(tmp_path, answers)
    assert config["compute_environment"] == "TPU_POD"
    assert config["num_processes"] == 8
    assert config["coordinator_address"] == "10.0.0.2:8476"
    assert config["tpu_use_cluster"] is True
    assert config["tpu_name"] == "v5e-pod"
    assert config["tpu_zone"] == "us-east5-b"
    assert config["tpu_commands"] == ["pip install -e .", "echo ready"]
    assert config["mesh"] == {"data": -1, "fsdp": 4, "model": 2, "seq": 2, "expert": 1, "stage": 1}
    assert config["fsdp_config"] == {
        "sharding_strategy": "SHARD_GRAD_OP",
        "min_num_params": 2048,
        "cpu_offload": True,
        "activation_checkpointing": True,
        "state_dict_type": "SHARDED_STATE_DICT",
    }
    assert config["sequence_parallel_config"] == {"mode": "ring", "block_size": 256}
    assert config["mixed_precision"] == "bf16"
    assert config["gradient_accumulation_steps"] == 4
    assert config["compilation_cache"] == "/tmp/xla-cache"
    assert config["debug"] is True


def _launch_args(extra=()):
    import argparse

    parser = argparse.ArgumentParser(allow_abbrev=False)
    add_launch_args(parser)
    return parser.parse_args([*extra, "train.py"])


def test_launch_env_consumes_questionnaire_yaml(tmp_path):
    """The YAML the questionnaire writes must round-trip into the worker-side env
    protocol (ACCELERATE_TPU_*) that the plugins' __post_init__ reads."""
    config_file = str(tmp_path / "config.yaml")
    write_basic_config(
        config_file,
        mixed_precision="bf16",
        mesh={"data": -1, "fsdp": 4, "model": 1, "seq": 2, "expert": 1, "stage": 1},
        gradient_accumulation_steps=4,
        fsdp_config={
            "sharding_strategy": "SHARD_GRAD_OP",
            "min_num_params": 2048,
            "cpu_offload": True,
            "activation_checkpointing": True,
            "state_dict_type": "SHARDED_STATE_DICT",
        },
        sequence_parallel_config={"mode": "ring", "block_size": 256},
        compilation_cache="/tmp/xla-cache",
        debug=True,
    )
    env = build_launch_env(_launch_args(), load_config_file(config_file))
    assert env["ACCELERATE_TPU_MIXED_PRECISION"] == "bf16"
    assert env["ACCELERATE_TPU_GRADIENT_ACCUMULATION_STEPS"] == "4"
    assert env["ACCELERATE_TPU_MESH_FSDP"] == "4"
    assert env["ACCELERATE_TPU_MESH_SEQ"] == "2"
    assert env["ACCELERATE_TPU_USE_FSDP"] == "1"
    assert env["ACCELERATE_TPU_FSDP_SHARDING_STRATEGY"] == "SHARD_GRAD_OP"
    assert env["ACCELERATE_TPU_FSDP_MIN_NUM_PARAMS"] == "2048"
    assert env["ACCELERATE_TPU_FSDP_OFFLOAD_PARAMS"] == "true"
    assert env["ACCELERATE_TPU_FSDP_ACTIVATION_CHECKPOINTING"] == "true"
    assert env["ACCELERATE_TPU_SP_MODE"] == "ring"
    assert env["ACCELERATE_TPU_SP_BLOCK_SIZE"] == "256"
    assert env["ACCELERATE_TPU_COMPILATION_CACHE"] == "/tmp/xla-cache"
    assert env["ACCELERATE_TPU_DEBUG_MODE"] == "1"


def test_plugins_read_launch_env(tmp_path, monkeypatch):
    """Worker side of the protocol: a FSDP plugin built under the launch env picks up
    every questionnaire field."""
    monkeypatch.setenv("ACCELERATE_TPU_FSDP_SHARDING_STRATEGY", "SHARD_GRAD_OP")
    monkeypatch.setenv("ACCELERATE_TPU_FSDP_MIN_NUM_PARAMS", "2048")
    monkeypatch.setenv("ACCELERATE_TPU_FSDP_OFFLOAD_PARAMS", "true")
    monkeypatch.setenv("ACCELERATE_TPU_FSDP_ACTIVATION_CHECKPOINTING", "true")
    from accelerate_tpu.utils import FullyShardedDataParallelPlugin

    plugin = FullyShardedDataParallelPlugin()
    assert plugin.sharding_strategy == "SHARD_GRAD_OP"
    assert plugin.min_num_params == 2048
    assert plugin.cpu_offload is True
    assert plugin.activation_checkpointing is True

"""Tests for the big-model machinery (parity: reference tests/test_big_modeling.py 1017
+ tests/test_modeling_utils.py 773 — planner math on tiny models, dispatch + execution
equivalence)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.big_modeling import (
    DispatchedModel,
    cpu_offload,
    disk_offload,
    dispatch_model,
    init_empty_weights,
    load_checkpoint_and_dispatch,
)
from accelerate_tpu.models.llama import LlamaLayeredApply, create_llama_model, llama_tiny
from accelerate_tpu.utils.modeling import (
    calculate_maximum_sizes,
    clean_device_map,
    compute_module_sizes,
    dtype_byte_size,
    get_max_memory,
    group_into_blocks,
    infer_auto_device_map,
    named_parameter_shapes,
    parse_memory_string,
)
from accelerate_tpu.utils.offload import (
    OffloadedWeightsLoader,
    load_offloaded_weight,
    offload_weight,
    save_offload_index,
)


@pytest.fixture(scope="module")
def tiny_llama():
    return create_llama_model(llama_tiny(), seq_len=16)


def test_init_empty_weights_is_shapes_only(tiny_llama):
    shapes = init_empty_weights(tiny_llama.module, jnp.zeros((1, 16), jnp.int32))
    leaves = jax.tree_util.tree_leaves(shapes)
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in leaves)
    # matches the real params' shapes
    real = jax.tree_util.tree_leaves(tiny_llama.params)
    assert [l.shape for l in leaves] == [tuple(r.shape) for r in real]


def test_compute_module_sizes(tiny_llama):
    sizes = compute_module_sizes(tiny_llama.params)
    total = sizes[""]
    assert total == sum(int(np.prod(l.shape)) * 4 for l in jax.tree_util.tree_leaves(tiny_llama.params))
    assert sizes["params/layer_0"] == sizes["params/layer_1"]


def test_parse_memory_string():
    assert parse_memory_string("1KB") == 1000
    assert parse_memory_string("1KiB") == 1024
    assert parse_memory_string("2.5GB") == 2_500_000_000


def test_dtype_byte_size():
    from accelerate_tpu.utils.dataclasses import CustomDtype

    assert dtype_byte_size(jnp.bfloat16.dtype if hasattr(jnp.bfloat16, "dtype") else jnp.zeros(1, jnp.bfloat16).dtype) == 2
    assert dtype_byte_size(CustomDtype.INT4) == 0.5


def test_infer_auto_device_map_tiers(tiny_llama):
    sizes = compute_module_sizes(tiny_llama.params)
    layer_size = sizes["params/layer_0"]
    embed_size = sizes["params/embed_tokens"]
    # Budget: device 0 fits the embed block + headroom only → layers spill to cpu/disk
    budget = {0: embed_size + 2 * layer_size + 1024, "cpu": layer_size + 1024, "disk": float("inf")}
    dmap = infer_auto_device_map(tiny_llama.params, budget)
    tiers = set(dmap.values())
    assert 0 in tiers and "cpu" in tiers and "disk" in tiers
    # declaration order: embed placed first, on device
    assert dmap["params/embed_tokens"] == 0


def test_infer_auto_device_map_all_fits(tiny_llama):
    dmap = infer_auto_device_map(tiny_llama.params, {0: float("inf"), "cpu": float("inf"), "disk": float("inf")})
    assert set(dmap.values()) == {0}


def test_clean_device_map():
    dmap = {"params/layer_0": 0, "params/layer_1": 0, "params/embed": 0}
    assert clean_device_map(dmap) == {"": 0}
    dmap2 = {"params/a/x": 0, "params/a/y": 0, "params/b": "cpu"}
    cleaned = clean_device_map(dmap2)
    assert cleaned == {"params/a": 0, "params/b": "cpu"}


def test_offload_store_roundtrip(tmp_path):
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    wb = jnp.ones((2, 2), dtype=jnp.bfloat16) * 1.5
    index = offload_weight(w, "a/b", str(tmp_path))
    index = offload_weight(wb, "a/c", str(tmp_path), index)
    save_offload_index(index, str(tmp_path))
    loader = OffloadedWeightsLoader(save_folder=str(tmp_path))
    np.testing.assert_array_equal(np.asarray(loader["a/b"]), w)
    got = loader["a/c"]
    assert str(np.asarray(got).dtype) == "bfloat16"
    np.testing.assert_array_equal(np.asarray(got, dtype=np.float32), np.full((2, 2), 1.5))


def test_dispatched_all_resident_matches_plain(tiny_llama):
    ids = np.arange(32, dtype=np.int32).reshape(2, 16) % 500
    expected = tiny_llama.apply_fn(tiny_llama.params, ids)
    dm = dispatch_model(tiny_llama, {"": 0})
    got = dm(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-5, atol=1e-5)


def test_cpu_offload_streamed_matches_plain(tiny_llama):
    ids = np.arange(32, dtype=np.int32).reshape(2, 16) % 500
    expected = tiny_llama.apply_fn(tiny_llama.params, ids)
    dm = cpu_offload(tiny_llama, layered=LlamaLayeredApply(llama_tiny()))
    got = dm(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-5, atol=1e-5)


def test_disk_offload_streamed_matches_plain(tiny_llama, tmp_path):
    ids = np.arange(32, dtype=np.int32).reshape(2, 16) % 500
    expected = tiny_llama.apply_fn(tiny_llama.params, ids)
    dm = disk_offload(tiny_llama, str(tmp_path), layered=LlamaLayeredApply(llama_tiny()))
    assert dm.resident_fraction == 0.0
    got = dm(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-5, atol=1e-5)


def test_mixed_tier_dispatch(tiny_llama, tmp_path):
    """Embed on device, layer_0 on cpu, layer_1 on disk — the realistic tiering."""
    ids = np.arange(32, dtype=np.int32).reshape(2, 16) % 500
    expected = tiny_llama.apply_fn(tiny_llama.params, ids)
    dmap = {
        "params/embed_tokens": 0,
        "params/layer_0": "cpu",
        "params/layer_1": "disk",
        "params/final_norm": 0,
        "params/lm_head": "cpu",
    }
    dm = dispatch_model(tiny_llama, dmap, offload_folder=str(tmp_path), layered=LlamaLayeredApply(llama_tiny()))
    got = dm(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-5, atol=1e-5)
    assert 0.0 < dm.resident_fraction < 1.0


def test_load_checkpoint_and_dispatch_auto(tiny_llama, tmp_path):
    from accelerate_tpu.checkpointing import save_pytree

    ckpt = str(tmp_path / "weights.npz")
    save_pytree(tiny_llama.params, ckpt)
    dm = load_checkpoint_and_dispatch(
        tiny_llama,
        checkpoint=ckpt,
        device_map="auto",
        layered=LlamaLayeredApply(llama_tiny()),
        offload_folder=str(tmp_path / "offload"),
    )
    ids = np.arange(32, dtype=np.int32).reshape(2, 16) % 500
    expected = tiny_llama.apply_fn(tiny_llama.params, ids)
    np.testing.assert_allclose(np.asarray(dm(ids)), np.asarray(expected), rtol=1e-5, atol=1e-5)


def test_streamed_tied_embeddings(tmp_path):
    from accelerate_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, tie_word_embeddings=True,
    )
    model = create_llama_model(cfg, seq_len=8)
    ids = np.arange(16, dtype=np.int32).reshape(2, 8) % 256
    expected = model.apply_fn(model.params, ids)
    dm = cpu_offload(model, layered=LlamaLayeredApply(cfg))
    got = dm(ids)
    assert got.shape == expected.shape  # logits, not hidden states
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-5, atol=1e-5)


def test_streamed_scan_layers(tmp_path):
    from accelerate_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0, scan_layers=True,
    )
    model = create_llama_model(cfg, seq_len=8)
    ids = np.arange(16, dtype=np.int32).reshape(2, 8) % 256
    expected = model.apply_fn(model.params, ids)
    dm = cpu_offload(model, layered=LlamaLayeredApply(cfg))
    got = dm(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-5, atol=1e-5)


def test_calculate_maximum_sizes(tiny_llama):
    total, (largest, name) = calculate_maximum_sizes(tiny_llama.params)
    assert total > largest > 0
    assert name  # some block identified


def test_dispatched_generate_matches_resident_greedy():
    """Greedy generation through the tiered forward (the reference's big-model
    inference benchmark shape) must match generation from the fully-resident
    model."""
    import numpy as np

    import jax.numpy as jnp

    from accelerate_tpu.big_modeling import cpu_offload
    from accelerate_tpu.models.llama import LlamaLayeredApply, create_llama_model, llama_tiny

    cfg = llama_tiny()
    model = create_llama_model(cfg, seq_len=32)
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, cfg.vocab_size, (2, 5)).astype(np.int32)

    # resident reference: grow context through the plain forward
    ids = prompt.copy()
    for _ in range(4):
        logits = np.asarray(model.apply_fn(model.params, jnp.asarray(ids, jnp.int32)))
        ids = np.concatenate([ids, logits[:, -1, :].argmax(-1).astype(np.int32)[:, None]], axis=1)

    dispatched = cpu_offload(model, LlamaLayeredApply(cfg))
    out = np.asarray(dispatched.generate(prompt, max_new_tokens=4))
    np.testing.assert_array_equal(out, ids)


def test_dispatched_generate_eos_per_row():
    """Rows that hit EOS pad with EOS while others continue; the loop exits as
    soon as EVERY row finished (each extra step re-streams the offloaded model)."""
    import numpy as np

    import jax.numpy as jnp

    from accelerate_tpu.big_modeling import cpu_offload
    from accelerate_tpu.models.llama import LlamaLayeredApply, create_llama_model, llama_tiny

    cfg = llama_tiny()
    model = create_llama_model(cfg, seq_len=32)
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, cfg.vocab_size, (2, 5)).astype(np.int32)
    dispatched = cpu_offload(model, LlamaLayeredApply(cfg))

    # Use an identical prompt for both rows: they emit the same first token, so
    # picking it as EOS finishes EVERY row at step 1 — the loop must early-exit.
    prompt = np.broadcast_to(prompt[:1], prompt.shape).copy()
    first = np.asarray(dispatched.generate(prompt, max_new_tokens=1))[:, -1]
    eos = int(first[0])
    out = np.asarray(dispatched.generate(prompt, max_new_tokens=6, eos_token_id=eos))
    assert (out[:, 5:] == eos).all(), "finished rows must pad with eos"
    assert out.shape[1] == 5 + 1, f"loop must stop once every row finished: {out.shape}"


def test_dispatched_generate_padded_batch_matches_per_row():
    """A right-padded batch of unequal-length prompts with attention_mask must
    produce, row for row, the same continuations as generating each prompt alone
    (round-3 advice: padding was silently attended before)."""
    import numpy as np

    from accelerate_tpu.big_modeling import cpu_offload
    from accelerate_tpu.models.llama import LlamaLayeredApply, create_llama_model, llama_tiny

    cfg = llama_tiny()
    model = create_llama_model(cfg, seq_len=32)
    rng = np.random.default_rng(7)
    dispatched = cpu_offload(model, LlamaLayeredApply(cfg))

    long_p = rng.integers(1, cfg.vocab_size, (1, 7)).astype(np.int32)
    short_p = rng.integers(1, cfg.vocab_size, (1, 4)).astype(np.int32)
    ref_long = np.asarray(dispatched.generate(long_p, max_new_tokens=3))
    ref_short = np.asarray(dispatched.generate(short_p, max_new_tokens=3))

    batch = np.zeros((2, 7), np.int32)
    batch[0] = long_p[0]
    batch[1, :4] = short_p[0]
    mask = np.zeros((2, 7), np.int32)
    mask[0] = 1
    mask[1, :4] = 1
    out = np.asarray(dispatched.generate(batch, max_new_tokens=3, attention_mask=mask))
    np.testing.assert_array_equal(out[0, :10], ref_long[0])
    np.testing.assert_array_equal(out[1, :7], ref_short[0])


def test_dispatched_generate_left_padded_mask_rejected():
    import numpy as np
    import pytest

    from accelerate_tpu.big_modeling import cpu_offload
    from accelerate_tpu.models.llama import LlamaLayeredApply, create_llama_model, llama_tiny

    cfg = llama_tiny()
    model = create_llama_model(cfg, seq_len=32)
    dispatched = cpu_offload(model, LlamaLayeredApply(cfg))
    batch = np.ones((1, 6), np.int32)
    mask = np.array([[0, 0, 1, 1, 1, 1]], np.int32)  # left-padded
    with pytest.raises(ValueError, match="right-padded"):
        dispatched.generate(batch, max_new_tokens=2, attention_mask=mask)


def test_dispatched_generate_zero_new_tokens_returns_prompt():
    import numpy as np

    from accelerate_tpu.big_modeling import cpu_offload
    from accelerate_tpu.models.llama import LlamaLayeredApply, create_llama_model, llama_tiny

    cfg = llama_tiny()
    model = create_llama_model(cfg, seq_len=32)
    dispatched = cpu_offload(model, LlamaLayeredApply(cfg))
    prompt = np.ones((1, 5), np.int32)
    out = np.asarray(dispatched.generate(prompt, max_new_tokens=0))
    np.testing.assert_array_equal(out, prompt)

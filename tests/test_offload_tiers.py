"""Host-offload tiers (ZeRO-offload parity, reference accelerator.py:1563-1785 +
dataclasses.py:704-719): optimizer state / params requested onto the host tier must
actually carry the backend's host memory kind ("pinned_host" where a distinct host
space exists; CPU backends expose only "unpinned_host", their default space — see
parallel.sharding.host_memory_kind), and training must match the non-offload
trajectory in both the eager and fused paths."""

import numpy as np
import pytest

import jax
import optax

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.parallel.sharding import device_memory_kind, host_memory_kind
from accelerate_tpu.utils import DeepSpeedPlugin, FullyShardedDataParallelPlugin

# The kinds the offload tiers lower to ON THIS BACKEND: strict two-tier
# checking on TPU/GPU ("pinned_host" vs "device"); on CPU both resolve to
# "unpinned_host" (one memory space), so the assertions degrade to exercising
# the full offload code path rather than distinguishing tiers.
HOST_KIND = host_memory_kind()
DEVICE_KIND = device_memory_kind()

from test_training import make_regression_data, make_regression_model


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _leaf_kinds(tree):
    return {
        getattr(leaf.sharding, "memory_kind", None)
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "sharding")
    }


def _train(plugin, fused, data, epochs=2):
    _reset()
    accelerator = Accelerator(fsdp_plugin=plugin)
    model = make_regression_model(seed=0)
    dl = SimpleDataLoader(data, BatchSampler(range(len(data)), 16))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adam(0.05), dl)
    if fused:
        step_fn = accelerator.train_step()
        for _ in range(epochs):
            for batch in pdl:
                step_fn(batch)
    else:
        for _ in range(epochs):
            for batch in pdl:
                with accelerator.accumulate(pmodel):
                    accelerator.backward(pmodel.loss, batch)
                    popt.step()
                    popt.zero_grad()
    return pmodel, popt


def _params_close(a, b, rtol=2e-5, atol=1e-6):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=rtol, atol=atol)


@pytest.mark.parametrize("fused", [False, True], ids=["eager", "fused"])
def test_optimizer_state_offload_matches_baseline(fused):
    data = make_regression_data(64, seed=20)
    plugin_off = FullyShardedDataParallelPlugin(
        sharding_strategy="SHARD_GRAD_OP", offload_optimizer_state=True, min_num_params=0
    )
    pmodel_off, popt_off = _train(plugin_off, fused, data)
    assert popt_off.offload_opt_state
    assert _leaf_kinds(popt_off.opt_state) == {HOST_KIND}
    assert _leaf_kinds(pmodel_off.params) == {DEVICE_KIND}

    plugin_base = FullyShardedDataParallelPlugin(
        sharding_strategy="SHARD_GRAD_OP", min_num_params=0
    )
    pmodel_base, popt_base = _train(plugin_base, fused, data)
    assert not popt_base.offload_opt_state
    _params_close(pmodel_off.params, pmodel_base.params)
    _params_close(popt_off.opt_state, popt_base.opt_state)


@pytest.mark.parametrize("fused", [False, True], ids=["eager", "fused"])
def test_param_offload_matches_baseline(fused):
    data = make_regression_data(64, seed=21)
    plugin_off = FullyShardedDataParallelPlugin(
        sharding_strategy="FULL_SHARD", cpu_offload=True, min_num_params=0
    )
    pmodel_off, popt_off = _train(plugin_off, fused, data)
    assert pmodel_off.offload_params and popt_off.offload_opt_state
    assert _leaf_kinds(pmodel_off.params) == {HOST_KIND}
    assert _leaf_kinds(popt_off.opt_state) == {HOST_KIND}

    plugin_base = FullyShardedDataParallelPlugin(sharding_strategy="FULL_SHARD", min_num_params=0)
    pmodel_base, _ = _train(plugin_base, fused, data)
    _params_close(pmodel_off.params, pmodel_base.params)


def test_offloaded_forward_works():
    _reset()
    accelerator = Accelerator(
        fsdp_plugin=FullyShardedDataParallelPlugin(cpu_offload=True, min_num_params=0)
    )
    model = make_regression_model(seed=0)
    pmodel = accelerator.prepare(model)
    out = pmodel({"x": np.ones((4, 1), np.float32)}["x"])
    assert np.asarray(out).shape == (4, 1)


def test_deepspeed_offload_config_lowers_to_host_tier():
    """A ZeRO-offload ds_config must actually produce pinned_host placement
    (round-1 gap: parsed then silently ignored)."""
    _reset()
    ds = DeepSpeedPlugin(
        hf_ds_config={
            "zero_optimization": {"stage": 2, "offload_optimizer": {"device": "cpu"}}
        }
    )
    fsdp = ds.to_fsdp_plugin()
    assert fsdp.offload_optimizer_state and not fsdp.offload_params
    accelerator = Accelerator(fsdp_plugin=fsdp)
    model = make_regression_model(seed=0)
    pmodel, popt = accelerator.prepare(model, optax.adam(0.01))
    assert popt.offload_opt_state
    assert _leaf_kinds(popt.opt_state) == {HOST_KIND}
    assert not pmodel.offload_params


def test_offloaded_load_state_dict_does_not_alias():
    """load_state_dict(state_dict()) on a host-offloaded model must copy: the next
    donated update would otherwise delete the caller's arrays through the alias."""
    data = make_regression_data(32, seed=23)
    plugin = FullyShardedDataParallelPlugin(cpu_offload=True, min_num_params=0)
    _reset()
    accelerator = Accelerator(fsdp_plugin=plugin)
    model = make_regression_model(seed=0)
    dl = SimpleDataLoader(data, BatchSampler(range(32), 16))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adam(0.05), dl)
    snapshot = pmodel.state_dict()
    pmodel.load_state_dict(snapshot)
    step_fn = accelerator.train_step()
    for batch in pdl:
        step_fn(batch)
    # the snapshot's buffers must still be alive and readable
    for leaf in jax.tree_util.tree_leaves(snapshot):
        np.asarray(leaf)


def test_checkpoint_roundtrip_with_offload(tmp_path):
    data = make_regression_data(32, seed=22)
    plugin = FullyShardedDataParallelPlugin(
        sharding_strategy="SHARD_GRAD_OP", offload_optimizer_state=True, min_num_params=0
    )
    _reset()
    accelerator = Accelerator(fsdp_plugin=plugin)
    model = make_regression_model(seed=0)
    dl = SimpleDataLoader(data, BatchSampler(range(32), 16))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adam(0.05), dl)
    step_fn = accelerator.train_step()
    for batch in pdl:
        step_fn(batch)
    accelerator.save_state(str(tmp_path / "ckpt"))
    want = jax.tree_util.tree_map(np.asarray, popt.opt_state)
    for batch in pdl:
        step_fn(batch)
    accelerator.load_state(str(tmp_path / "ckpt"))
    got = jax.tree_util.tree_map(np.asarray, popt.opt_state)
    for a, b in zip(jax.tree_util.tree_leaves(want), jax.tree_util.tree_leaves(got)):
        np.testing.assert_allclose(a, b)
    # restored state must land back on the host tier and keep training
    assert _leaf_kinds(popt.opt_state) == {HOST_KIND}
    for batch in pdl:
        step_fn(batch)


@pytest.mark.parametrize("fused", [False, True], ids=["eager", "fused"])
def test_chunked_multi_group_matches_baseline(fused, monkeypatch):
    """The chunked offload update (one program per param group — the thing that lets
    llama-1b's 12GB Adam state train on a 16GB chip) must match the non-offload
    trajectory when forced into one-leaf-per-group mode."""
    monkeypatch.setenv("ACCELERATE_TPU_OFFLOAD_CHUNK_MB", "0")
    data = make_regression_data(48, seed=3)
    pm_off, po_off = _train(
        FullyShardedDataParallelPlugin(sharding_strategy="NO_SHARD", offload_optimizer_state=True),
        fused,
        data,
    )
    assert po_off.offload_opt_state
    assert len(po_off._jit_cache["chunk_groups"]) > 1, "chunking not exercised"
    assert _leaf_kinds(po_off.opt_state) == {HOST_KIND}
    _reset()
    monkeypatch.delenv("ACCELERATE_TPU_OFFLOAD_CHUNK_MB")
    pm_base, po_base = _train(FullyShardedDataParallelPlugin(sharding_strategy="NO_SHARD"), fused, data)
    _params_close(pm_off.params, pm_base.params)
    _params_close(po_off.opt_state, po_base.opt_state)


def test_chunked_update_with_scheduler_lr(monkeypatch):
    """LR override (AcceleratedScheduler) must reach every group program."""
    monkeypatch.setenv("ACCELERATE_TPU_OFFLOAD_CHUNK_MB", "0")
    data = make_regression_data(32, seed=4)

    def run(offload):
        _reset()
        plugin = FullyShardedDataParallelPlugin(
            sharding_strategy="NO_SHARD", offload_optimizer_state=offload
        )
        accelerator = Accelerator(fsdp_plugin=plugin)
        model = make_regression_model(seed=0)
        dl = SimpleDataLoader(data, BatchSampler(range(len(data)), 16))
        schedule = optax.linear_schedule(0.1, 0.0, transition_steps=8)
        pmodel, popt, psched, pdl = accelerator.prepare(
            model, optax.inject_hyperparams(optax.sgd)(learning_rate=0.1), schedule, dl
        )
        for _ in range(2):
            for batch in pdl:
                accelerator.backward(pmodel.loss, batch)
                popt.step()
                psched.step()
                popt.zero_grad()
        return pmodel

    pm_off = run(offload=True)
    monkeypatch.delenv("ACCELERATE_TPU_OFFLOAD_CHUNK_MB")
    pm_base = run(offload=False)
    _params_close(pm_off.params, pm_base.params)


# ---------------------------------------------------------------- disk (NVMe) tier
@pytest.mark.parametrize("fused", [False, True], ids=["eager", "fused"])
def test_disk_optimizer_state_matches_baseline(fused, tmp_path, monkeypatch):
    """Optimizer state resident on DISK (DeepSpeed NVMe parity): multi-group
    chunked updates through the blob store must reproduce the in-memory
    trajectory exactly, with the state actually on disk (no device arrays held)."""
    monkeypatch.setenv("ACCELERATE_TPU_OFFLOAD_CHUNK_MB", "0")  # force multi-group
    data = make_regression_data(64, seed=21)
    plugin_disk = FullyShardedDataParallelPlugin(
        sharding_strategy="SHARD_GRAD_OP",
        offload_optimizer_device="disk",
        offload_dir=str(tmp_path / "optstate"),
        min_num_params=0,
    )
    pmodel_disk, popt_disk = _train(plugin_disk, fused, data)
    from accelerate_tpu.optimizer import DiskOptState

    assert popt_disk.offload_opt_state
    assert isinstance(popt_disk.opt_state, DiskOptState)
    assert (tmp_path / "optstate" / "weights.bin").exists(), "state must live in the blob"
    assert len(popt_disk._jit_cache["chunk_groups"]) > 1, "chunk budget must force multi-group"

    monkeypatch.delenv("ACCELERATE_TPU_OFFLOAD_CHUNK_MB")
    plugin_base = FullyShardedDataParallelPlugin(
        sharding_strategy="SHARD_GRAD_OP", min_num_params=0
    )
    pmodel_base, popt_base = _train(plugin_base, fused, data)
    _params_close(pmodel_disk.params, pmodel_base.params)
    _params_close(popt_disk.opt_state.materialize(), popt_base.opt_state)


def test_disk_tier_checkpoint_roundtrip(tmp_path):
    """save_state/load_state through the disk tier: materialize -> npz -> load
    back into the blob; training continues bit-identically."""
    data = make_regression_data(32, seed=22)
    plugin = FullyShardedDataParallelPlugin(
        sharding_strategy="SHARD_GRAD_OP",
        offload_optimizer_device="nvme",  # alias accepted
        offload_dir=str(tmp_path / "optstate"),
        min_num_params=0,
    )
    _reset()
    accelerator = Accelerator(fsdp_plugin=plugin, project_dir=str(tmp_path / "proj"))
    model = make_regression_model(seed=0)
    dl = SimpleDataLoader(data, BatchSampler(range(len(data)), 16))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adam(0.05), dl)
    step_fn = accelerator.train_step()
    for batch in pdl:
        step_fn(batch)
    state_before = popt.opt_state.materialize()
    ckpt = accelerator.save_state(str(tmp_path / "ckpt"))
    for batch in pdl:
        step_fn(batch)  # mutate past the snapshot
    accelerator.load_state(ckpt)
    state_after = popt.opt_state.materialize()
    _params_close(state_after, state_before, rtol=0, atol=0)


def test_deepspeed_nvme_config_lowers_to_disk_tier():
    plugin = DeepSpeedPlugin(
        zero_stage=2, offload_optimizer_device="nvme"
    ).to_fsdp_plugin()
    assert plugin.offload_optimizer_device == "disk"


def test_disk_tier_llama_on_virtual_mesh(tmp_path, monkeypatch):
    """llama on the 8-device virtual mesh with FULL_SHARD params + disk-resident
    optimizer state: multi-group streaming through the fused path, finite losses,
    moments sharded-derivable and stored in the blob."""
    monkeypatch.setenv("ACCELERATE_TPU_OFFLOAD_CHUNK_MB", "0")
    from accelerate_tpu.models.llama import create_llama_model, llama_tiny
    from accelerate_tpu.optimizer import DiskOptState
    from accelerate_tpu.utils import ParallelismConfig

    _reset()
    accelerator = Accelerator(
        mixed_precision="bf16",
        parallelism_config=ParallelismConfig(data=2, fsdp=4),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            sharding_strategy="FULL_SHARD",
            min_num_params=1024,
            offload_optimizer_device="disk",
            offload_dir=str(tmp_path / "optstate"),
        ),
    )
    model = create_llama_model(llama_tiny(), seq_len=32)
    rng = np.random.default_rng(0)
    data = [{"input_ids": rng.integers(1, 500, size=(32,)).astype(np.int32)} for _ in range(16)]
    dl = SimpleDataLoader(data, BatchSampler(range(16), 16))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adamw(1e-3), dl)
    assert isinstance(popt.opt_state, DiskOptState)
    assert len(popt._jit_cache["chunk_groups"]) > 1
    step_fn = accelerator.train_step()
    losses = []
    for _ in range(2):
        for batch in pdl:
            losses.append(float(step_fn(batch)))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0]
    blob = tmp_path / "optstate" / "weights.bin"
    # Adam moments for every param live in the blob: 2 slots (mu, nu) x params.
    param_bytes = sum(
        l.size * l.dtype.itemsize for l in jax.tree_util.tree_leaves(pmodel.params)
    )
    assert blob.stat().st_size >= 2 * param_bytes


def test_disk_tier_poisoned_after_failed_step(tmp_path, monkeypatch):
    """A step that fails after some groups' write-backs must poison the disk
    state (blob ahead of params) so a blind retry errors instead of silently
    double-applying moment updates; load_state_dict clears the poison."""
    monkeypatch.setenv("ACCELERATE_TPU_OFFLOAD_CHUNK_MB", "0")
    data = make_regression_data(32, seed=23)
    plugin = FullyShardedDataParallelPlugin(
        sharding_strategy="SHARD_GRAD_OP",
        offload_optimizer_device="disk",
        offload_dir=str(tmp_path / "optstate"),
        min_num_params=0,
    )
    _reset()
    accelerator = Accelerator(fsdp_plugin=plugin)
    model = make_regression_model(seed=0)
    dl = SimpleDataLoader(data, BatchSampler(range(len(data)), 16))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adam(0.05), dl)
    batch = next(iter(pdl))
    accelerator.backward(pmodel.loss, batch)
    snapshot = popt.opt_state.materialize()

    # Inject a failure into the second group's write-back.
    orig_write = popt.opt_state.write_group
    calls = {"n": 0}

    def failing_write(paths, state):
        calls["n"] += 1
        if calls["n"] == 2:
            raise IOError("disk full")
        return orig_write(paths, state)

    popt.opt_state.write_group = failing_write
    with pytest.raises(IOError, match="disk full"):
        popt.step()
    popt.opt_state.write_group = orig_write
    assert popt.opt_state.poisoned
    accelerator.backward(pmodel.loss, batch)
    with pytest.raises(RuntimeError, match="inconsistent"):
        popt.step()
    popt.load_state_dict({"opt_state": snapshot, "scaler": None})
    assert not popt.opt_state.poisoned
    popt.step()  # recovers


def test_disk_tier_reinit_does_not_grow_blob(tmp_path):
    """Re-initializing into the same offload_dir must start a fresh blob, not
    append a full second copy of the state (restart-leak guard)."""
    data = make_regression_data(32, seed=24)
    sizes = []
    for _ in range(2):
        plugin = FullyShardedDataParallelPlugin(
            sharding_strategy="SHARD_GRAD_OP",
            offload_optimizer_device="disk",
            offload_dir=str(tmp_path / "optstate"),
            min_num_params=0,
        )
        _train(plugin, True, data, epochs=1)
        sizes.append((tmp_path / "optstate" / "weights.bin").stat().st_size)
    assert sizes[1] == sizes[0], f"blob grew across restarts: {sizes}"

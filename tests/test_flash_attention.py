"""Pallas flash-attention kernel tests (interpret mode on CPU): forward and gradient
parity against the XLA einsum-softmax reference, causal + GQA + rectangular shapes."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.ops.attention import dot_product_attention
from accelerate_tpu.ops.flash_attention import flash_attention


def _qkv(b, s, h, d, hkv=None, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    hkv = hkv or h
    q = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32), dtype)
    k = jnp.asarray(rng.normal(size=(b, s, hkv, d)).astype(np.float32), dtype)
    v = jnp.asarray(rng.normal(size=(b, s, hkv, d)).astype(np.float32), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 128, 2, 64), (1, 256, 4, 32)])
def test_flash_forward_matches_xla(causal, shape):
    b, s, h, d = shape
    q, k, v = _qkv(b, s, h, d)
    ref = dot_product_attention(q, k, v, causal=causal, implementation="xla")
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_forward_gqa():
    q, k, v = _qkv(2, 128, 4, 32, hkv=2, seed=1)
    ref = dot_product_attention(q, k, v, causal=True, implementation="xla")
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_xla(causal):
    b, s, h, d = 1, 128, 2, 32
    q, k, v = _qkv(b, s, h, d, seed=2)

    def loss_flash(q_, k_, v_):
        out = flash_attention(q_, k_, v_, causal=causal, block_q=64, block_k=64, interpret=True)
        return jnp.sum(jnp.square(out))

    def loss_ref(q_, k_, v_):
        out = dot_product_attention(q_, k_, v_, causal=causal, implementation="xla")
        return jnp.sum(jnp.square(out))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), rtol=5e-4, atol=5e-4, err_msg=f"d{name}"
        )


def test_flash_causal_cross_length_matches_xla():
    """Causal with Sq != Skv must use bottom-right alignment like the XLA path
    (advisor: the kernel was top-left aligned, silently diverging)."""
    rng = np.random.default_rng(3)
    b, h, d = 1, 2, 32
    sq, skv = 64, 192
    q = jnp.asarray(rng.normal(size=(b, sq, h, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, skv, h, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, skv, h, d)).astype(np.float32))
    ref = dot_product_attention(q, k, v, causal=True, implementation="xla")
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    # gradients agree too
    def loss_flash(q_):
        return jnp.sum(jnp.square(flash_attention(q_, k, v, causal=True, block_q=64, block_k=64, interpret=True)))

    def loss_ref(q_):
        return jnp.sum(jnp.square(dot_product_attention(q_, k, v, causal=True, implementation="xla")))

    gf = jax.grad(loss_flash)(q)
    gr = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), rtol=5e-4, atol=5e-4)


def test_flash_uneven_blocks_rejected():
    q, k, v = _qkv(1, 96, 2, 32)
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)


def test_flash_small_seq_shrinks_blocks():
    # block_q/k shrink to the sequence length — 64-token sequences just work
    q, k, v = _qkv(2, 64, 2, 32, seed=3)
    ref = dot_product_attention(q, k, v, causal=True, implementation="xla")
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_compiled_on_tpu():
    """Real-hardware lowering gate (round-2 verdict weak #5: the kernel only ever ran
    in interpret mode, and its block specs didn't actually satisfy Mosaic's (8, 128)
    tiling rule). Skipped off-TPU; on TPU it proves compile + fwd/bwd numerics."""
    if jax.default_backend() != "tpu":
        pytest.skip("needs real TPU lowering (Mosaic)")
    q, k, v = _qkv(2, 1024, 4, 64, seed=7)
    for causal in (False, True):
        ref = dot_product_attention(q, k, v, causal=causal, implementation="xla")
        out = flash_attention(q, k, v, causal=causal)  # compiled, not interpret
        err = float(jnp.max(jnp.abs(np.asarray(ref, np.float32) - np.asarray(out, np.float32))))
        assert err < 0.05, (causal, err)
        gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal=causal).astype(jnp.float32) ** 2))(q, k, v)
        gx = jax.grad(
            lambda q, k, v: jnp.sum(
                dot_product_attention(q, k, v, causal=causal, implementation="xla").astype(jnp.float32) ** 2
            )
        )(q, k, v)
        scale = float(jnp.max(jnp.abs(np.asarray(gx, np.float32)))) + 1e-6
        rel = float(jnp.max(jnp.abs(np.asarray(gf, np.float32) - np.asarray(gx, np.float32)))) / scale
        assert rel < 0.05, (causal, rel)


def test_forced_flash_with_bias_or_mask_raises():
    """An explicit implementation='flash' combined with bias (T5 relative positions)
    or a mask must raise, not silently downgrade/drop the argument (round-3 advice)."""
    q, k, v = _qkv(1, 128, 2, 32)
    bias = jnp.zeros((1, 2, 128, 128))
    with pytest.raises(ValueError, match="bias"):
        dot_product_attention(q, k, v, bias=bias, implementation="flash")
    mask = jnp.ones((1, 128), bool)
    with pytest.raises(ValueError, match="mask"):
        dot_product_attention(q, k, v, mask=mask, implementation="flash")

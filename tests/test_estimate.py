"""estimate-memory command (round-2 verdict, missing #4): the reference builds
meta-models from the Hub (estimate.py:63-137); here the same mechanism runs on the
torch meta device from local configs (zero-egress), with closed-form fallback and a
clean offline error for unreachable Hub ids."""

import json

import pytest

from accelerate_tpu.commands.estimate import (
    create_empty_model,
    estimate_parameters_from_hf_config,
    gather_data,
    sizes_from_meta_model,
)


class _Args:
    def __init__(self, model_name, dtypes=("float32",), trust_remote_code=False):
        self.model_name = model_name
        self.dtypes = list(dtypes)
        self.trust_remote_code = trust_remote_code


@pytest.fixture(scope="module")
def bert_config_dir(tmp_path_factory):
    import transformers

    d = tmp_path_factory.mktemp("bert_cfg")
    cfg = transformers.BertConfig(
        vocab_size=1000, hidden_size=64, num_hidden_layers=2, num_attention_heads=2, intermediate_size=128
    )
    cfg.save_pretrained(d)
    return str(d)


def test_meta_model_measured_sizes(bert_config_dir):
    """The meta-model path must measure EXACT parameter counts (torch meta device,
    no weight bytes), matching a real instantiation."""
    import transformers

    meta = create_empty_model(bert_config_dir)
    total, largest = sizes_from_meta_model(meta)
    real = transformers.AutoModel.from_config(transformers.AutoConfig.from_pretrained(bert_config_dir))
    real_total = sum(p.numel() for p in real.parameters()) + sum(b.numel() for b in real.buffers())
    assert total == real_total
    assert 0 < largest < total
    assert not any(p.device.type != "meta" for p in meta.parameters()), "weights were materialized"


def test_meta_model_respects_architectures(tmp_path):
    """Configs from real checkpoints carry `architectures`; the task-specific Auto
    class must be used (concrete classes have no from_config — would AttributeError)."""
    import transformers

    cfg = transformers.LlamaConfig(
        vocab_size=1000,
        hidden_size=64,
        num_hidden_layers=2,
        num_attention_heads=2,
        num_key_value_heads=2,
        intermediate_size=128,
        architectures=["LlamaForCausalLM"],
    )
    cfg.save_pretrained(tmp_path)
    meta = create_empty_model(str(tmp_path))
    assert type(meta).__name__ == "LlamaForCausalLM"
    total, largest = sizes_from_meta_model(meta)
    assert total > largest > 0


def test_gather_data_local_dir(bert_config_dir):
    total, rows = gather_data(_Args(bert_config_dir))
    assert rows[0]["total_size"] == total * 4
    assert rows[0]["training_size"] == total * 16
    assert 0 < rows[0]["largest_layer"] < rows[0]["total_size"]


def test_gather_data_in_tree_name():
    total, rows = gather_data(_Args("llama-1b"))
    assert 1e9 < total < 2e9  # ~1.5B params
    assert rows[0]["total_size"] == total * 4


def test_gather_data_raw_config_json(tmp_path):
    cfg = {
        "model_type": "llama",
        "vocab_size": 1024,
        "hidden_size": 128,
        "num_hidden_layers": 2,
        "intermediate_size": 256,
        "num_attention_heads": 4,
        "hidden_act": "silu",
        "tie_word_embeddings": True,
    }
    p = tmp_path / "config.json"
    p.write_text(json.dumps(cfg))
    total_closed, _ = estimate_parameters_from_hf_config(cfg)
    total, _rows = gather_data(_Args(str(p)))
    # A bare config.json file takes either the meta path (if transformers accepts
    # the parent dir) or closed form; both must land in the same ballpark.
    assert 0.5 * total_closed < total < 2 * total_closed


def test_offline_hub_id_fails_cleanly(monkeypatch):
    # HF_HUB_OFFLINE is read at import time, so patch the resolution call itself:
    # no network I/O from the suite, and the offline handling path is what runs.
    import transformers

    def _offline(*a, **k):
        raise OSError("We couldn't connect to 'https://huggingface.co' (simulated offline)")

    monkeypatch.setattr(transformers.AutoConfig, "from_pretrained", _offline)
    with pytest.raises(RuntimeError, match="Hub is unreachable|Could not resolve"):
        gather_data(_Args("some-org/nonexistent-model-xyz"))


def test_closed_form_flan_t5_encoder_decoder():
    """A real HF flan-t5-xl-shaped config.json (num_layers = ENCODER count, no
    num_encoder_layers key) must estimate ~2.85B params, not the ~1.9B a halved
    encoder produced before the encoder-decoder accounting fix."""
    from accelerate_tpu.commands.estimate import estimate_parameters_from_hf_config

    cfg = {
        "model_type": "t5",
        "vocab_size": 32128,
        "d_model": 2048,
        "d_kv": 64,
        "d_ff": 5120,
        "num_layers": 24,
        "num_decoder_layers": 24,
        "num_heads": 32,
        "is_encoder_decoder": True,
        "feed_forward_proj": "gated-gelu",
        "tie_word_embeddings": False,
    }
    # flan-t5-xl is 2.85B params
    total, _largest = estimate_parameters_from_hf_config(cfg)
    assert 2.6e9 < total < 3.1e9, total

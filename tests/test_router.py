"""Replicated serving fleet tests (router.Router / ReplicaSet).

Pins the front-end's load-bearing contracts:

  1. greedy outputs through the fleet are token-identical to the static
     `Generator` path (routing adds scheduling, never different math);
  2. cancel() and per-request deadlines PROPAGATE to the owning replica and
     produce the same terminal finish_reason as the single-engine path;
  3. a replica failure re-dispatches only never-streamed requests — a request
     that already emitted tokens surfaces `finish_reason="replica_lost"`,
     never a duplicated stream;
  4. the health machine ejects a dead replica, never routes to it while
     ejected, and rejoins it through cooldown + probation;
  5. `swap_weights` rolls the fleet one replica at a time (capacity >= N-1
     throughout) and post-swap outputs match the NEW weights exactly.
"""

import numpy as np
import pytest

from accelerate_tpu.generation import generate
from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
from accelerate_tpu.router import ROUTER_FINISH_REASONS, ReplicaSet, Router
from accelerate_tpu.serving import FINISH_REASONS, QueueFull, Request

pytestmark = pytest.mark.router


def _model(seed: int = 0):
    import jax

    cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
    )
    return create_llama_model(cfg, rng=jax.random.key(seed), seq_len=32)


def _static_reference(model, prompt, max_new, **kwargs):
    out = np.asarray(generate(model, prompt[None, :], max_new_tokens=max_new, **kwargs))
    return out[0, prompt.size:]


def _router(model, **overrides):
    kwargs = dict(
        replicas=2, num_slots=2, max_length=64, chunk_size=4, max_queue=16,
        default_deadline_s=60.0, rejoin_cooldown_s=0.01, probation_steps=1,
        stall_degrade_s=None,
    )
    kwargs.update(overrides)
    return Router(model, **kwargs)


class _ReplicaDeath(BaseException):
    """Stand-in for a worker death escaping the engine (chaos uses InjectedKill)."""


def _kill_replica(router, index):
    """Make replica `index`'s next engine step die like a SIGKILLed worker."""
    engine = router.replica_set.replicas[index].engine

    def dead_step():
        raise _ReplicaDeath(f"replica {index} killed")

    engine.step = dead_step


def test_finish_reason_vocabulary():
    assert set(ROUTER_FINISH_REASONS) == set(FINISH_REASONS) | {"replica_lost"}


def test_greedy_parity_and_least_loaded_spread():
    """Mixed workload over 2 replicas: every output token-identical to the
    static path, and least-loaded routing actually used the whole fleet."""
    model = _model()
    rng = np.random.default_rng(0)
    router = _router(model)
    lengths = [3, 5, 9, 12, 6, 4]
    budgets = [6, 4, 8, 3, 5, 7]
    prompts = [rng.integers(1, 128, (n,)).astype(np.int32) for n in lengths]
    outputs = router.run(
        [Request(i, p, max_new_tokens=m) for i, (p, m) in enumerate(zip(prompts, budgets))]
    )
    for i, (p, m) in enumerate(zip(prompts, budgets)):
        np.testing.assert_array_equal(outputs[i], _static_reference(model, p, m))
    assert {entry["replica"] for entry in router.routing_log} == {0, 1}
    reasons = router.stats["finish_reasons"]
    assert reasons["length"] + reasons["eos"] == len(prompts)


def test_cancel_propagates_to_owning_replica():
    """cancel() reaches the replica that owns the request — queued or
    in-flight — and yields the single-engine terminal reason `cancelled`
    (partial tokens kept); the slot is serviceable again afterwards."""
    model = _model()
    rng = np.random.default_rng(1)
    router = _router(model, replicas=2, num_slots=1)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    for i in range(3):  # 2 in flight (one per replica), 1 queued
        router.submit(Request(i, prompt, max_new_tokens=24))
    router.step()
    inflight = next(i for i in range(2) if router.results[i].tokens)
    assert router.cancel(inflight) is True
    assert router.results[inflight].finish_reason == "cancelled"
    assert router.results[inflight].tokens, "partial tokens must be kept"
    assert router.cancel(2) is True  # cancelled while queued: no tokens
    assert router.results[2].finish_reason == "cancelled"
    assert router.results[2].tokens == []
    assert router.cancel(inflight) is False  # already finished
    with pytest.raises(KeyError):
        router.cancel(99)
    # the engine-side attempts are gone: slots free up and new work serves
    router.run()
    outputs = router.run([Request(10, prompt, max_new_tokens=4)])
    np.testing.assert_array_equal(outputs[10], _static_reference(model, prompt, 4))


def test_deadline_propagates_same_reason_as_single_engine():
    """Deadlines ride down to the owning replica's engine (queued requests
    expire without a slot; in-flight ones keep partial tokens) and surface the
    SAME terminal reason as the single-engine path: `timeout`."""
    model = _model()
    rng = np.random.default_rng(2)
    router = _router(model, replicas=2, num_slots=1)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    router.submit(Request(0, prompt, max_new_tokens=4, deadline_s=0.0))  # already expired
    router.submit(Request(1, prompt, max_new_tokens=24))
    router.step()
    # Force the in-flight request's ENGINE-side deadline into the past: the
    # propagation under test is engine-enforced, not router-side bookkeeping.
    tracked = router._tracked[1]
    attempt = next(a for a in tracked["attempts"] if not a["done"])
    engine = router.replica_set.replicas[attempt["replica"]].engine
    assert attempt["engine_id"] in engine._deadlines, "deadline did not reach the replica"
    partial = len(router.results[1].tokens)
    engine._deadlines[attempt["engine_id"]] = 0.0
    router.run()
    assert router.results[0].finish_reason == "timeout"
    assert router.results[0].tokens == []
    assert router.results[1].finish_reason == "timeout"
    assert len(router.results[1].tokens) >= partial  # partials kept
    # default_deadline_s applies when the request carries none
    assert router._tracked and router.default_deadline_s == 60.0


def test_replica_death_redispatches_only_never_streamed():
    """The safe re-dispatch rule: when a replica dies, its streamed request
    surfaces `replica_lost` (tokens kept, not duplicated), its queued/
    never-streamed requests complete on the surviving replica with exact
    greedy parity, and `router_retries_total` counts them."""
    model = _model()
    rng = np.random.default_rng(3)
    router = _router(model, replicas=2, num_slots=1, max_retries=2)
    prompts = [rng.integers(1, 128, (4 + i,)).astype(np.int32) for i in range(4)]
    for i, p in enumerate(prompts):
        router.submit(Request(i, p, max_new_tokens=10))
    router.step()  # 0 and 1 in flight (one per replica); 2, 3 queued
    victim_rid = 0 if router.results[0].tokens else 1
    victim_replica = next(
        a["replica"] for a in router._tracked[victim_rid]["attempts"]
    )
    queued_on_victim = [
        rid for rid in range(2, 4)
        if router._tracked[rid]["attempts"]
        and router._tracked[rid]["attempts"][0]["replica"] == victim_replica
        and not router.results[rid].tokens
    ]
    _kill_replica(router, victim_replica)
    outputs = router.run()
    assert router.results[victim_rid].finish_reason == "replica_lost"
    assert router.results[victim_rid].tokens, "streamed tokens must be kept"
    for rid in queued_on_victim:
        assert router.results[rid].finish_reason == "length"
        np.testing.assert_array_equal(
            outputs[rid], _static_reference(model, prompts[rid], 10)
        )
    assert router.stats["retries"] >= len(queued_on_victim)
    assert router.stats["ejected"] == 1


def test_never_routes_to_ejected_then_rejoins():
    """An ejected replica takes no traffic; after cooldown + probation it is
    live again and serves with exact parity."""
    import time

    model = _model()
    rng = np.random.default_rng(4)
    router = _router(model, replicas=2, rejoin_cooldown_s=0.05, probation_steps=1)
    prompt = rng.integers(1, 128, (5,)).astype(np.int32)
    router.run([Request(0, prompt, max_new_tokens=3)])
    _kill_replica(router, 0)
    router.submit(Request(1, prompt, max_new_tokens=3))
    router.step()  # the dead replica is discovered the first time it steps
    router.run()
    mark = len(router.routing_log)
    assert router.replica_states[0] == "ejected"
    # traffic while ejected lands on replica 1 only
    outputs = router.run([Request(i, prompt, max_new_tokens=3) for i in range(2, 5)])
    for entry in list(router.routing_log)[mark:]:
        assert entry["replica"] == 1
    for i in range(2, 5):
        np.testing.assert_array_equal(outputs[i], _static_reference(model, prompt, 3))
    # cooldown elapses -> rejoining (engine rebuilt) -> probation -> live
    time.sleep(0.06)
    router.step()
    assert router.replica_states[0] in ("rejoining", "live")
    router.step()
    router.step()
    assert router.replica_states[0] == "live"
    outputs = router.run([Request(10, prompt, max_new_tokens=3)])
    np.testing.assert_array_equal(outputs[10], _static_reference(model, prompt, 3))


def test_hedge_duplicates_queued_request_without_duplicate_stream():
    """TTFT hedging: a request stuck queued behind a long request is
    duplicated onto the other replica; exactly one copy's tokens are ever
    forwarded and the result matches the static path."""
    model = _model()
    rng = np.random.default_rng(5)
    router = _router(model, replicas=2, num_slots=1, hedge_after_s=0.0)
    long_prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    short_prompt = rng.integers(1, 128, (5,)).astype(np.int32)
    # Fill BOTH replicas' slots, then queue one more: it can't admit anywhere,
    # so the hedge sweep fires for it on the next step.
    router.submit(Request(0, long_prompt, max_new_tokens=24))
    router.submit(Request(1, long_prompt, max_new_tokens=24))
    router.step()
    router.submit(Request(2, short_prompt, max_new_tokens=4))
    outputs = router.run()
    assert router.stats["hedges"] >= 1
    np.testing.assert_array_equal(outputs[2], _static_reference(model, short_prompt, 4))
    assert router.results[2].finish_reason == "length"
    # both engine-side copies are gone (no orphaned slots/results)
    for replica in router.replica_set.replicas:
        assert not replica.engine.pending


def test_swap_weights_rolls_fleet_without_capacity_collapse():
    """Rolling weight swap: during the swap at most ONE replica is unroutable
    at a time (capacity >= N-1), in-flight work finishes, and post-swap
    outputs are token-identical to the static path on the NEW params."""
    model_a = _model(seed=0)
    model_b = _model(seed=7)
    rng = np.random.default_rng(6)
    router = _router(model_a, replicas=3)
    prompt = rng.integers(1, 128, (6,)).astype(np.int32)
    ref_a = _static_reference(model_a, prompt, 4)
    ref_b = _static_reference(model_b, prompt, 4)
    assert not np.array_equal(ref_a, ref_b), "seeds must differ for the swap pin"
    router.submit(Request(0, prompt, max_new_tokens=4))
    router.swap_weights(model_b)
    assert not router.swap_in_progress
    # in-flight work finished (on old or new weights — never dropped)
    assert router.results[0].finished
    # every replica drained exactly once, one at a time
    drains = [e for e in router.replica_set.state_log if e["to"] == "draining"]
    assert len(drains) == 3
    unroutable = 0
    for entry in router.replica_set.state_log:
        if entry["to"] in ("draining", "ejected"):
            unroutable += 1
            assert unroutable <= 1, "fleet fell below N-1 capacity during the swap"
        elif entry["from"] in ("draining", "ejected"):
            unroutable -= 1
    outputs = router.run([Request(1, prompt, max_new_tokens=4)])
    np.testing.assert_array_equal(outputs[1], ref_b)


def test_queue_full_across_fleet_and_duplicate_ids():
    model = _model()
    rng = np.random.default_rng(7)
    router = _router(model, replicas=2, num_slots=1, max_queue=1)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    router.submit(Request(0, prompt, max_new_tokens=4))
    router.submit(Request(1, prompt, max_new_tokens=4))
    router.step()  # both admitted into slots; queues are empty again
    router.submit(Request(2, prompt, max_new_tokens=4))  # r0 queue full
    router.submit(Request(3, prompt, max_new_tokens=4))  # r1 queue full
    with pytest.raises(QueueFull):
        router.submit(Request(9, prompt, max_new_tokens=4))
    with pytest.raises(ValueError, match="duplicate"):
        router.submit(Request(0, prompt, max_new_tokens=4))
    with pytest.raises(ValueError, match="slot capacity"):
        router.submit(Request(10, rng.integers(1, 128, (70,)).astype(np.int32),
                              max_new_tokens=8))
    router.run()
    assert all(router.results[i].finish_reason == "length" for i in range(4))
    # release frees the id for reuse, like the engine
    first = router.release(0)
    assert first.finished and 0 not in router.results
    outputs = router.run([Request(0, prompt, max_new_tokens=4)])
    np.testing.assert_array_equal(outputs[0], np.asarray(first.tokens, np.int32))


def test_drain_and_close_lifecycle():
    from accelerate_tpu.serving import EngineClosed

    model = _model()
    rng = np.random.default_rng(8)
    router = _router(model)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    router.submit(Request(0, prompt, max_new_tokens=4))
    results = router.drain()
    assert results[0].finished and not router.pending
    router.submit(Request(1, prompt, max_new_tokens=24))
    router.step()
    results = router.close()
    assert results[1].finish_reason == "cancelled" and results[1].tokens
    assert router.closed
    with pytest.raises(EngineClosed):
        router.submit(Request(2, prompt, max_new_tokens=4))
    assert router.step() == []
    assert router.close() is results or router.close() == results  # idempotent


def test_replica_set_validation_and_env_default(monkeypatch):
    from accelerate_tpu.router import SERVE_REPLICAS_ENV, default_replicas

    model = _model()
    with pytest.raises(ValueError, match="at least one replica"):
        ReplicaSet(model, replicas=0)
    monkeypatch.delenv(SERVE_REPLICAS_ENV, raising=False)
    assert default_replicas() == 2
    monkeypatch.setenv(SERVE_REPLICAS_ENV, "5")
    assert default_replicas() == 5
    monkeypatch.setenv(SERVE_REPLICAS_ENV, "bogus")
    assert default_replicas() == 2


def test_serve_cli_round_trip(capsys):
    """`accelerate-tpu serve` end to end: JSON result lines on stdout, exit 0,
    replica fleet sized by the flag."""
    from accelerate_tpu.commands.accelerate_cli import get_command_parser

    parser = get_command_parser()
    args = parser.parse_args([
        "serve", "--model", "llama-tiny", "--replicas", "2", "--requests", "3",
        "--max-new", "4", "--num-slots", "2", "--prompt-max", "8",
    ])
    with pytest.raises(SystemExit) as exit_info:
        args.func(args)
    assert exit_info.value.code == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    import json

    records = [json.loads(l) for l in lines]
    assert len(records) == 3
    assert all(r["finish_reason"] == "length" and len(r["tokens"]) == 4 for r in records)

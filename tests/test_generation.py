"""KV-cached generation tests: cached greedy decode must match no-cache full-context
argmax token-for-token (the cache-correctness gold test), plus sampling, EOS early
stop, GQA, and capacity validation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.generation import GenerationConfig, Generator, generate
from accelerate_tpu.models.llama import LlamaConfig, create_llama_model


def _model(layers=2, heads=4, kv_heads=2):
    cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=layers,
        num_attention_heads=heads,
        num_key_value_heads=kv_heads,
        max_position_embeddings=64,
        rope_theta=10000.0,
    )
    return create_llama_model(cfg, seq_len=32)


def _greedy_no_cache(model, input_ids, n):
    """Reference: full forward over the whole (growing) context each step."""
    ids = np.asarray(input_ids)
    for _ in range(n):
        logits = np.asarray(model.apply_fn(model.params, jnp.asarray(ids, jnp.int32)))
        nxt = logits[:, -1, :].argmax(-1).astype(np.int32)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    return ids


def test_cached_greedy_matches_full_context():
    model = _model()
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 128, (2, 8)).astype(np.int32)
    ref = _greedy_no_cache(model, prompt, 10)
    out = np.asarray(generate(model, prompt, max_new_tokens=10))
    np.testing.assert_array_equal(out, ref)


@pytest.mark.slow
def test_cached_greedy_matches_full_context_gqa_deep():
    model = _model(layers=3, heads=4, kv_heads=1)
    prompt = np.random.default_rng(1).integers(1, 128, (1, 5)).astype(np.int32)
    ref = _greedy_no_cache(model, prompt, 8)
    out = np.asarray(generate(model, prompt, max_new_tokens=8))
    np.testing.assert_array_equal(out, ref)


def test_generator_reuse_and_shapes():
    model = _model()
    gen = Generator(model, max_new_tokens=6)
    p1 = np.random.default_rng(2).integers(1, 128, (2, 8)).astype(np.int32)
    p2 = np.random.default_rng(3).integers(1, 128, (2, 8)).astype(np.int32)
    o1 = gen(p1, GenerationConfig(max_new_tokens=6))
    o2 = gen(p2, GenerationConfig(max_new_tokens=6))
    assert o1.shape == o2.shape == (2, 14)
    assert not np.array_equal(np.asarray(o1), np.asarray(o2))


def test_sampling_respects_rng_and_temperature():
    model = _model()
    prompt = np.random.default_rng(4).integers(1, 128, (1, 6)).astype(np.int32)
    gen = Generator(model, max_new_tokens=8)
    cfg = GenerationConfig(max_new_tokens=8, do_sample=True, temperature=1.5, top_k=20)
    a = np.asarray(gen(prompt, cfg, rng=jax.random.key(1)))
    b = np.asarray(gen(prompt, cfg, rng=jax.random.key(1)))
    c = np.asarray(gen(prompt, cfg, rng=jax.random.key(2)))
    np.testing.assert_array_equal(a, b)  # same key, same draw
    assert not np.array_equal(a, c)


def test_eos_early_stop():
    model = _model()
    prompt = np.random.default_rng(5).integers(1, 128, (1, 4)).astype(np.int32)
    # find the first greedy token and use it as "eos": generation stops after it
    first = np.asarray(generate(model, prompt, max_new_tokens=1))[0, -1]
    out = np.asarray(generate(model, prompt, max_new_tokens=10, eos_token_id=int(first)))
    assert out.shape[1] == prompt.shape[1] + 1


def test_finished_rows_padded_after_eos():
    """In a batch, rows that hit EOS emit pad/eos afterwards, not live samples
    (advisor: finished sequences carried post-EOS garbage)."""
    model = _model()
    rng = np.random.default_rng(7)
    prompt = rng.integers(1, 128, (2, 4)).astype(np.int32)
    # pick row 0's first greedy token as eos so row 0 finishes immediately
    first = np.asarray(generate(model, prompt, max_new_tokens=1))[:, -1]
    eos = int(first[0])
    if int(first[1]) == eos:
        pytest.skip("both rows emit the same first token; can't distinguish")
    out = np.asarray(
        generate(model, prompt, max_new_tokens=6, eos_token_id=eos, pad_token_id=0)
    )
    row0_gen = out[0, prompt.shape[1]:]
    # first generated token is eos, everything after must be the pad id
    assert row0_gen[0] == eos
    assert (row0_gen[1:] == 0).all()


def test_cache_capacity_validation():
    model = _model()
    gen = Generator(model, max_new_tokens=4, max_length=8)
    prompt = np.random.default_rng(6).integers(1, 128, (1, 8)).astype(np.int32)
    with pytest.raises(ValueError, match="no room"):
        gen(prompt, GenerationConfig(max_new_tokens=4))


def _scan_model(family):
    import dataclasses

    if family == "llama":
        from accelerate_tpu.models.llama import LlamaConfig, create_llama_model

        cfg = LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
            rope_theta=10000.0, scan_layers=True,
        )
        return create_llama_model(cfg, seq_len=32)
    if family == "gptj":
        from accelerate_tpu.models.gptj import create_gptj_model, gptj_tiny

        return create_gptj_model(dataclasses.replace(gptj_tiny(), scan_layers=True), seq_len=32)
    if family == "gpt_neox":
        from accelerate_tpu.models.gpt_neox import create_gpt_neox_model, gpt_neox_tiny

        return create_gpt_neox_model(dataclasses.replace(gpt_neox_tiny(), scan_layers=True), seq_len=32)
    from accelerate_tpu.models.opt import create_opt_model, opt_tiny

    return create_opt_model(dataclasses.replace(opt_tiny(), scan_layers=True), seq_len=32)


@pytest.mark.parametrize("family", ["llama", "gptj", "gpt_neox", "opt"])
def test_scan_layers_cached_decode_matches_full_context(family):
    """nn.scan-stacked layers must compose with the KV cache (every family's scan
    declares a cache axis); decode through it equals argmax over the full-context
    forward. Regression: the scans previously omitted the cache collection and
    decode raised ScopeCollectionNotFound."""
    model = _scan_model(family)
    prompt = np.random.default_rng(0).integers(1, 512, (2, 8)).astype(np.int32)
    out = np.asarray(generate(model, prompt, max_new_tokens=4))
    np.testing.assert_array_equal(out, _greedy_no_cache(model, prompt, 4))


def test_top_p_nucleus_restricts_support():
    """top_p keeps the smallest descending-prob prefix reaching the mass: the
    unit-level _sample must never draw outside the nucleus, the top token must
    always survive even with tiny top_p, and the fused decode loop accepts the
    knob (HF order: top_k first, then top_p)."""
    from accelerate_tpu.generation import _sample

    # [1, 5] logits with probs ~ [0.57, 0.21, 0.12, 0.064, 0.035]
    logits = jnp.asarray([[4.0, 3.0, 2.45, 1.8, 1.2]], jnp.float32)
    cfg = GenerationConfig(do_sample=True, top_p=0.7)
    draws = set()
    rng = jax.random.key(0)
    for _ in range(64):
        tok, rng = _sample(logits, cfg, rng)
        draws.add(int(tok[0]))
    assert draws <= {0, 1}, draws  # 0.57+0.21 covers 0.7; token 2 is outside the nucleus
    # degenerate top_p: the argmax always survives (min_tokens_to_keep=1,
    # including top_p=0.0 which would otherwise mask the whole vocab)
    for p in (1e-6, 0.0):
        tok, _ = _sample(logits, GenerationConfig(do_sample=True, top_p=p), jax.random.key(1))
        assert int(tok[0]) == 0, p
    # end-to-end through the fused loop: runs, deterministic per key
    model = _model()
    prompt = np.random.default_rng(6).integers(1, 128, (1, 6)).astype(np.int32)
    gen = Generator(model, max_new_tokens=6)
    cfg = GenerationConfig(max_new_tokens=6, do_sample=True, top_k=40, top_p=0.9)
    a = np.asarray(gen(prompt, cfg, rng=jax.random.key(7)))
    b = np.asarray(gen(prompt, cfg, rng=jax.random.key(7)))
    np.testing.assert_array_equal(a, b)
    # Cache-key regression: configs differing ONLY in top_p through the SAME
    # Generator must not share a compiled sampler (top_p shapes the program;
    # omitting it from the decode-cache key served a stale 0.9-nucleus sampler
    # for the 1e-9 config when this feature first landed).
    tiny = np.asarray(
        gen(prompt, GenerationConfig(max_new_tokens=6, do_sample=True, top_p=1e-9), rng=jax.random.key(8))
    )
    greedy = np.asarray(gen(prompt, GenerationConfig(max_new_tokens=6)))
    np.testing.assert_array_equal(tiny, greedy)


@pytest.mark.parametrize("family", ["llama", "opt"])
def test_left_padded_ragged_batch_matches_per_row(family):
    """HF left-pad convention: a batch of ragged prompts padded on the LEFT with
    attention_mask must generate, row for row, exactly what each prompt produces
    alone (pins the persistent cache pad mask, cumsum positions — rotary for
    llama, the learned-offset embedding for opt — and the per-row decode
    position base)."""
    if family == "llama":
        model = _model()
        vocab = 128
    else:
        from accelerate_tpu.models.opt import create_opt_model, opt_tiny

        model = create_opt_model(opt_tiny(), seq_len=32)
        vocab = opt_tiny().vocab_size
    rng = np.random.default_rng(11)
    short = rng.integers(1, vocab, (1, 5)).astype(np.int32)
    long = rng.integers(1, vocab, (1, 9)).astype(np.int32)
    # left-pad the short prompt to the long length
    pad = np.zeros((1, 4), np.int32)
    batch = np.concatenate([np.concatenate([pad, short], axis=1), long], axis=0)
    mask = np.ones_like(batch)
    mask[0, :4] = 0

    gen = Generator(model, max_new_tokens=6)
    out = np.asarray(gen(batch, GenerationConfig(max_new_tokens=6), attention_mask=mask))
    ref_short = np.asarray(gen(short, GenerationConfig(max_new_tokens=6)))
    ref_long = np.asarray(gen(long, GenerationConfig(max_new_tokens=6)))
    np.testing.assert_array_equal(out[0, 9:], ref_short[0, 5:])
    np.testing.assert_array_equal(out[1, 9:], ref_long[0, 9:])
    # the one-shot convenience accepts the mask too
    out2 = np.asarray(generate(model, batch, max_new_tokens=6, attention_mask=mask))
    np.testing.assert_array_equal(out2, out)
    # right-padded masks are rejected loudly, not silently wrong
    bad = np.ones_like(mask)
    bad[0, -2:] = 0
    with pytest.raises(ValueError, match="LEFT-padding"):
        gen(batch, GenerationConfig(max_new_tokens=2), attention_mask=bad)


def test_repetition_penalty_matches_hf_processor_and_reduces_repeats():
    """The penalty math must equal transformers' RepetitionPenaltyLogitsProcessor
    (CTRL semantics: seen positive logits /p, negative *p), and end-to-end a
    strong penalty must change greedy output and strictly reduce token reuse."""
    from accelerate_tpu.generation import _apply_repetition_penalty

    transformers = pytest.importorskip("transformers")
    import torch

    rng = np.random.default_rng(13)
    logits = rng.normal(size=(2, 32)).astype(np.float32)
    seen = np.zeros((2, 32), bool)
    seen[0, [3, 7, 9]] = True
    seen[1, [0, 31]] = True
    ours = np.asarray(
        _apply_repetition_penalty(jnp.asarray(logits), jnp.asarray(seen), 1.7)
    )
    proc = transformers.generation.logits_process.RepetitionPenaltyLogitsProcessor(1.7)
    for row in range(2):
        ids = torch.tensor([np.nonzero(seen[row])[0].tolist()])
        ref = proc(ids, torch.tensor(logits[row:row+1])).numpy()
        np.testing.assert_allclose(ours[row:row+1], ref, rtol=1e-6)

    # end-to-end: greedy with a large penalty diverges from plain greedy and
    # repeats fewer tokens over a long horizon
    model = _model()
    prompt = np.random.default_rng(14).integers(1, 128, (1, 6)).astype(np.int32)
    gen = Generator(model, max_new_tokens=16)
    plain = np.asarray(gen(prompt, GenerationConfig(max_new_tokens=16)))[0, 6:]
    pen = np.asarray(
        gen(prompt, GenerationConfig(max_new_tokens=16, repetition_penalty=5.0))
    )[0, 6:]
    assert not np.array_equal(plain, pen)
    assert len(set(pen.tolist())) >= len(set(plain.tolist())), (plain, pen)
    # penalty=1.0 config still hits the plain program (cache-key separation)
    again = np.asarray(gen(prompt, GenerationConfig(max_new_tokens=16)))[0, 6:]
    np.testing.assert_array_equal(plain, again)


def test_repetition_penalty_with_left_padded_batch():
    """Penalty + ragged left-pad together: pad slots (token id 0) must NOT seed
    the seen set — each padded row generates exactly what it generates alone
    under the same penalty."""
    model = _model()
    rng = np.random.default_rng(15)
    short = rng.integers(1, 128, (1, 4)).astype(np.int32)
    long = rng.integers(1, 128, (1, 7)).astype(np.int32)
    batch = np.concatenate(
        [np.concatenate([np.zeros((1, 3), np.int32), short], axis=1), long]
    )
    mask = np.ones_like(batch)
    mask[0, :3] = 0
    cfg = GenerationConfig(max_new_tokens=8, repetition_penalty=2.5)
    gen = Generator(model, max_new_tokens=8)
    out = np.asarray(gen(batch, cfg, attention_mask=mask))
    np.testing.assert_array_equal(out[0, 7:], np.asarray(gen(short, cfg))[0, 4:])
    np.testing.assert_array_equal(out[1, 7:], np.asarray(gen(long, cfg))[0, 7:])


# ---------------------------------------------------------------------------
# Sampler semantics pinned on fixed logits (serving.ContinuousBatcher reuses
# _sample verbatim, so these hand-computed expectations are the serving
# sampler's contract too): top_k -> top_p -> categorical, penalty upstream.
# ---------------------------------------------------------------------------


def _sample_support(logits_row, config, draws=256):
    """The set of token ids `_sample` can emit for one fixed logits row:
    categorical draws are independent per batch row, so one tiled call gives
    `draws` independent samples."""
    from accelerate_tpu.generation import _sample

    tiled = jnp.tile(jnp.asarray(logits_row, jnp.float32)[None, :], (draws, 1))
    toks, _ = _sample(tiled, config, jax.random.key(0))
    return set(np.asarray(toks).tolist())


def test_sampler_greedy_ignores_filters():
    from accelerate_tpu.generation import _sample

    logits = jnp.asarray([[0.1, 2.0, -1.0, 0.5]])
    cfg = GenerationConfig(do_sample=False, top_k=1, top_p=0.01, temperature=9.0)
    tok, _ = _sample(logits, cfg, jax.random.key(0))
    assert int(tok[0]) == 1


def test_sampler_top_k_support_is_k_largest():
    # distinct ascending logits: top_k=3 keeps exactly ids {3, 4, 5}
    logits = np.log([0.02, 0.03, 0.05, 0.1, 0.3, 0.5])
    cfg = GenerationConfig(do_sample=True, top_k=3)
    assert _sample_support(logits, cfg) == {3, 4, 5}


def test_sampler_top_p_uses_exclusive_cumulative_mass():
    # probs [0.5, 0.3, 0.15, 0.05]: a token survives iff the mass STRICTLY
    # before it (descending order) is < top_p — so top_p=0.5 keeps only id 0
    # (id 1's exclusive mass is exactly 0.5), top_p=0.81 keeps {0, 1, 2}.
    logits = np.log([0.5, 0.3, 0.15, 0.05])
    assert _sample_support(logits, GenerationConfig(do_sample=True, top_p=0.5)) == {0}
    assert _sample_support(logits, GenerationConfig(do_sample=True, top_p=0.51)) == {0, 1}
    assert _sample_support(logits, GenerationConfig(do_sample=True, top_p=0.81)) == {0, 1, 2}


def test_sampler_top_p_nonpositive_keeps_top_token():
    # min_tokens_to_keep=1 (HF semantics): top_p <= 0 would otherwise mask the
    # whole vocabulary and sample uniform gibberish from all -1e30 logits.
    logits = np.log([0.25, 0.4, 0.2, 0.15])
    assert _sample_support(logits, GenerationConfig(do_sample=True, top_p=0.0)) == {1}
    assert _sample_support(logits, GenerationConfig(do_sample=True, top_p=-1.0)) == {1}


def test_sampler_top_k_applies_before_top_p():
    # probs [0.4, 0.3, 0.2, 0.1], top_k=3, top_p=0.75.
    #   k first (our order): survivors {0,1,2} renormalize to [4/9, 3/9, 2/9];
    #     exclusive cums [0, 0.444, 0.777] -> 0.777 >= 0.75 kills id 2 -> {0, 1}.
    #   p first (the wrong order) would keep {0,1,2} (raw exclusive cums
    #     [0, 0.4, 0.7] all < 0.75) and top_k=3 would not shrink it.
    logits = np.log([0.4, 0.3, 0.2, 0.1])
    cfg = GenerationConfig(do_sample=True, top_k=3, top_p=0.75)
    assert _sample_support(logits, cfg) == {0, 1}


def test_sampler_temperature_preserves_support_and_argmax():
    logits = np.log([0.02, 0.03, 0.05, 0.1, 0.3, 0.5])
    hot = GenerationConfig(do_sample=True, top_k=2, temperature=5.0)
    cold = GenerationConfig(do_sample=True, top_k=2, temperature=0.05)
    assert _sample_support(logits, hot) == {4, 5}
    # near-zero temperature concentrates ALL mass on the argmax
    assert _sample_support(logits, cold) == {5}


def test_repetition_penalty_divides_positive_multiplies_negative():
    from accelerate_tpu.generation import _apply_repetition_penalty

    logits = jnp.asarray([[2.0, -2.0, 1.0, -1.0]])
    seen = jnp.asarray([[True, True, False, False]])
    out = np.asarray(_apply_repetition_penalty(logits, seen, 2.0))
    np.testing.assert_allclose(out, [[1.0, -4.0, 1.0, -1.0]])


def test_repetition_penalty_applies_before_filtering():
    """Fused-loop pick order: penalty -> temperature/top_k -> draw. A penalized
    argmax must lose to the runner-up even under top_k=1 (if filtering ran
    first, the penalized token would be the only candidate left)."""
    from accelerate_tpu.generation import _apply_repetition_penalty, _sample

    logits = jnp.asarray([[3.0, 2.5, 0.1]])
    seen = jnp.asarray([[True, False, False]])
    cfg = GenerationConfig(do_sample=True, top_k=1)
    penalized = _apply_repetition_penalty(logits, seen, 2.0)  # token 0: 3.0 -> 1.5
    tok, _ = _sample(penalized, cfg, jax.random.key(0))
    assert int(tok[0]) == 1


# ---------------------------------------------------------------------------
# Module-level generate() executable cache
# ---------------------------------------------------------------------------


def test_generate_convenience_caches_warm_executables(monkeypatch):
    """Repeated convenience `generate()` calls must NOT rebuild (and recompile)
    a Generator: same model + same max_new_tokens bucket hits the warm cache."""
    from accelerate_tpu import generation

    generation._GENERATOR_CACHE.clear()
    model = _model()
    builds = []
    orig_init = generation.Generator.__init__

    def counting_init(self, *args, **kwargs):
        builds.append(1)
        return orig_init(self, *args, **kwargs)

    monkeypatch.setattr(generation.Generator, "__init__", counting_init)
    prompt = np.random.default_rng(20).integers(1, 128, (1, 6)).astype(np.int32)
    a = np.asarray(generate(model, prompt, max_new_tokens=5))
    b = np.asarray(generate(model, prompt, max_new_tokens=5))
    assert len(builds) == 1, "second call rebuilt the Generator"
    np.testing.assert_array_equal(a, b)
    # ANY budget stays warm: the Generator's cache capacity doesn't depend on
    # max_new_tokens (the fused loop buckets per call)
    generate(model, prompt, max_new_tokens=20)
    assert len(builds) == 1
    # the cached generator's prefill traced exactly once across all three calls
    (_, cached_gen), = generation._GENERATOR_CACHE.values()
    assert cached_gen._prefill._cache_size() == 1
    # a DIFFERENT model identity must not share programs
    model2 = _model()
    generate(model2, prompt, max_new_tokens=5)
    assert len(builds) == 2
    # a DEAD model must not pin its Generator (params + executables): the
    # weakref finalizer evicts its entry at collection
    import gc

    del model2
    gc.collect()
    assert len(generation._GENERATOR_CACHE) == 1
    # rebinding model.params (the train-then-sample pattern) must REBUILD —
    # a cached Generator holding the old pytree would decode with stale weights
    model.params = jax.tree_util.tree_map(lambda x: x + 0.5, model.params)
    stale_free = np.asarray(generate(model, prompt, max_new_tokens=5))
    assert len(builds) == 3
    fresh = generation.Generator(model, max_new_tokens=5)(
        jnp.asarray(prompt), GenerationConfig(max_new_tokens=5)
    )
    np.testing.assert_array_equal(stale_free, np.asarray(fresh))
    generation._GENERATOR_CACHE.clear()

"""Fault-tolerance tests: supervisor restart budget + signal forwarding, preemption
latch, Accelerator.check_preemption saving state and exiting 143, and the launch CLI
--max_restarts path (the elastic machinery the reference delegates to torchrun)."""

import os
import signal
import subprocess
import sys
import tempfile
import textwrap
import time

import pytest

from accelerate_tpu.fault_tolerance import PREEMPTED_EXIT_CODE, PreemptionHandler, Supervisor
from accelerate_tpu.test_utils.testing import cpu_mesh_env

CRASHY = """
import os, sys
marker = sys.argv[1]
fail_times = int(sys.argv[2])
n = 0
if os.path.exists(marker):
    with open(marker) as f:
        n = int(f.read())
with open(marker, "w") as f:
    f.write(str(n + 1))
sys.exit(1 if n < fail_times else 0)
"""


def _script(tmp, name, body):
    path = os.path.join(tmp, name)
    with open(path, "w") as f:
        f.write(textwrap.dedent(body))
    return path


def test_supervisor_restarts_until_success():
    with tempfile.TemporaryDirectory() as d:
        script = _script(d, "crashy.py", CRASHY)
        marker = os.path.join(d, "attempts")
        sup = Supervisor([sys.executable, script, marker, "2"], max_restarts=5, backoff_seconds=0.01, monitor_interval=0.05)
        code = sup.run()
        assert code == 0
        assert sup.restart_count == 2
        with open(marker) as f:
            assert f.read() == "3"  # two failures + one success


def test_supervisor_respects_budget():
    with tempfile.TemporaryDirectory() as d:
        script = _script(d, "crashy.py", CRASHY)
        marker = os.path.join(d, "attempts")
        sup = Supervisor([sys.executable, script, marker, "99"], max_restarts=2, backoff_seconds=0.01, monitor_interval=0.05)
        code = sup.run()
        assert code == 1
        with open(marker) as f:
            assert f.read() == "3"  # initial + 2 restarts


def test_supervisor_treats_preemption_exit_as_final():
    with tempfile.TemporaryDirectory() as d:
        script = _script(d, "preempt.py", f"import sys; sys.exit({PREEMPTED_EXIT_CODE})")
        sup = Supervisor([sys.executable, script], max_restarts=5, monitor_interval=0.05)
        assert sup.run() == PREEMPTED_EXIT_CODE
        assert sup.restart_count == 0


def test_preemption_handler_latch():
    handler = PreemptionHandler()
    try:
        assert not handler.preemption_requested
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.1)
        assert handler.preemption_requested
        handler.reset()
        assert not handler.preemption_requested
    finally:
        handler.uninstall()


PREEMPT_TRAIN = """
import os, signal, sys, time
import numpy as np
import optax
from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler
from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

out_dir = sys.argv[1]
accelerator = Accelerator(project_dir=out_dir)
accelerator.register_preemption_checkpoint(os.path.join(out_dir, "preempt_ckpt"))
data = [RegressionDataset(length=32)[i] for i in range(32)]
dl = SimpleDataLoader(data, BatchSampler(range(32), 8))
model, opt, pdl = accelerator.prepare(RegressionModel(), optax.sgd(0.05), dl)
print("READY", flush=True)
for epoch in range(10000):
    for batch in pdl:
        accelerator.backward(model.loss, batch)
        opt.step(); opt.zero_grad()
        accelerator.check_preemption()
    time.sleep(0.05)
"""


@pytest.mark.slow_launch
def test_check_preemption_saves_and_exits_143():
    with tempfile.TemporaryDirectory() as d:
        script = _script(d, "train.py", PREEMPT_TRAIN)
        proc = subprocess.Popen(
            [sys.executable, script, d],
            env=cpu_mesh_env(),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        # wait for steady state
        for line in proc.stdout:
            if "READY" in line:
                break
        time.sleep(1.0)
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert proc.returncode == PREEMPTED_EXIT_CODE, proc.stdout.read()
        ckpt = os.path.join(d, "preempt_ckpt")
        assert os.path.isdir(ckpt) and os.listdir(ckpt), "preemption checkpoint missing"


@pytest.mark.slow_launch
def test_launch_cli_max_restarts():
    with tempfile.TemporaryDirectory() as d:
        script = _script(d, "crashy.py", CRASHY)
        marker = os.path.join(d, "attempts")
        result = subprocess.run(
            [
                sys.executable,
                "-m",
                "accelerate_tpu.commands.accelerate_cli",
                "launch",
                "--max_restarts",
                "3",
                script,
                marker,
                "1",
            ],
            env=cpu_mesh_env(),
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert result.returncode == 0, result.stdout + result.stderr
        with open(marker) as f:
            assert f.read() == "2"

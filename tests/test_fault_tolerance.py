"""Fault-tolerance tests: supervisor restart budget + signal forwarding, preemption
latch, Accelerator.check_preemption saving state and exiting 143, and the launch CLI
--max_restarts path (the elastic machinery the reference delegates to torchrun)."""

import os
import signal
import subprocess
import sys
import tempfile
import textwrap
import time

import pytest

from accelerate_tpu.fault_tolerance import PREEMPTED_EXIT_CODE, PreemptionHandler, Supervisor
from accelerate_tpu.test_utils.testing import cpu_mesh_env

CRASHY = """
import os, sys
marker = sys.argv[1]
fail_times = int(sys.argv[2])
n = 0
if os.path.exists(marker):
    with open(marker) as f:
        n = int(f.read())
with open(marker, "w") as f:
    f.write(str(n + 1))
sys.exit(1 if n < fail_times else 0)
"""


def _script(tmp, name, body):
    path = os.path.join(tmp, name)
    with open(path, "w") as f:
        f.write(textwrap.dedent(body))
    return path


def test_supervisor_restarts_until_success():
    with tempfile.TemporaryDirectory() as d:
        script = _script(d, "crashy.py", CRASHY)
        marker = os.path.join(d, "attempts")
        sup = Supervisor([sys.executable, script, marker, "2"], max_restarts=5, backoff_seconds=0.01, monitor_interval=0.05)
        code = sup.run()
        assert code == 0
        assert sup.restart_count == 2
        with open(marker) as f:
            assert f.read() == "3"  # two failures + one success


def test_supervisor_respects_budget():
    with tempfile.TemporaryDirectory() as d:
        script = _script(d, "crashy.py", CRASHY)
        marker = os.path.join(d, "attempts")
        sup = Supervisor([sys.executable, script, marker, "99"], max_restarts=2, backoff_seconds=0.01, monitor_interval=0.05)
        code = sup.run()
        assert code == 1
        with open(marker) as f:
            assert f.read() == "3"  # initial + 2 restarts


def test_supervisor_treats_preemption_exit_as_final():
    with tempfile.TemporaryDirectory() as d:
        script = _script(d, "preempt.py", f"import sys; sys.exit({PREEMPTED_EXIT_CODE})")
        sup = Supervisor([sys.executable, script], max_restarts=5, monitor_interval=0.05)
        assert sup.run() == PREEMPTED_EXIT_CODE
        assert sup.restart_count == 0


@pytest.mark.faults
def test_preemption_handler_off_main_thread_degrades_to_noop():
    """`signal.signal` is main-thread-only in CPython: constructing the handler
    from a worker thread (notebook executors, launcher threads) must degrade to
    a warn + permanently-unset latch instead of crashing the training script
    `register_preemption_checkpoint` is trying to protect."""
    import threading

    prev_disposition = signal.getsignal(signal.SIGTERM)
    box = {}

    def build():
        try:
            box["handler"] = PreemptionHandler()
        except BaseException as exc:  # pragma: no cover - the regression itself
            box["error"] = exc

    t = threading.Thread(target=build)
    t.start()
    t.join()
    assert "error" not in box, f"off-main-thread construction raised {box.get('error')!r}"
    handler = box["handler"]
    assert handler.installed is False
    assert handler.preemption_requested is False
    handler.uninstall()  # no-op, must not raise
    # the degraded handler never latched anything, so the main thread's SIGTERM
    # disposition is untouched
    assert signal.getsignal(signal.SIGTERM) == prev_disposition


@pytest.mark.faults
def test_supervisor_crash_loop_detection_stops_early():
    """A child that dies instantly with the SAME exit code every time (import
    error, bad flag, missing checkpoint) is a deterministic failure: after
    `crash_loop_threshold` identical fast crashes the supervisor must abort
    with a tagged diagnostic instead of grinding through a 50-restart backoff
    schedule."""
    sup = Supervisor(
        [sys.executable, "-c", "raise SystemExit(7)"],
        max_restarts=50,
        backoff_seconds=0.01,
        max_backoff_seconds=0.05,
        monitor_interval=0.05,
        crash_loop_threshold=3,
        crash_loop_min_uptime=30.0,  # python startup counts as "immediate" here
    )
    code = sup.run()
    assert code == 7
    assert sup.crash_loop_detected is True
    assert sup.restart_count == 2, "threshold=3 means: initial crash + 2 restarts, then abort"


@pytest.mark.faults
def test_supervisor_crash_loop_requires_identical_exit_codes():
    """Alternating exit codes are NOT a crash loop (a flaky-but-varied failure
    may still be healed by a restart): detection must reset on a code change
    and the budget path decides instead."""
    with tempfile.TemporaryDirectory() as d:
        marker = os.path.join(d, "n")
        body = (
            "import os, sys\n"
            "n = int(open(sys.argv[1]).read()) if os.path.exists(sys.argv[1]) else 0\n"
            "open(sys.argv[1], 'w').write(str(n + 1))\n"
            "sys.exit(7 if n % 2 == 0 else 8)\n"
        )
        script = _script(d, "alternating.py", body)
        sup = Supervisor(
            [sys.executable, script, marker],
            max_restarts=5,
            backoff_seconds=0.01,
            monitor_interval=0.05,
            crash_loop_threshold=3,
            crash_loop_min_uptime=30.0,
        )
        code = sup.run()
        assert sup.crash_loop_detected is False
        assert sup.restart_count == 5, "budget, not the crash-loop detector, must end this run"
        assert code in (7, 8)


@pytest.mark.faults
def test_supervisor_slow_failures_are_not_a_crash_loop():
    """Identical exit codes from a child that lived past the uptime floor are a
    workload problem, not a crash loop — restarts may genuinely help."""
    sup = Supervisor(
        [sys.executable, "-c", "raise SystemExit(7)"],
        max_restarts=4,
        backoff_seconds=0.01,
        monitor_interval=0.05,
        crash_loop_threshold=3,
        crash_loop_min_uptime=0.0,  # nothing is "immediate": detector never arms
    )
    code = sup.run()
    assert code == 7
    assert sup.crash_loop_detected is False
    assert sup.restart_count == 4


@pytest.mark.faults
def test_supervisor_crash_loop_detection_can_be_disabled():
    sup = Supervisor(
        [sys.executable, "-c", "raise SystemExit(7)"],
        max_restarts=6,
        backoff_seconds=0.01,
        monitor_interval=0.05,
        crash_loop_threshold=0,
        crash_loop_min_uptime=30.0,
    )
    assert sup.run() == 7
    assert sup.crash_loop_detected is False
    assert sup.restart_count == 6


@pytest.mark.faults
def test_supervisor_backoff_is_capped():
    """A tight crash loop with a big restart budget must never sleep unboundedly:
    linear backoff saturates at `max_backoff_seconds`."""
    sup = Supervisor(["true"], max_restarts=1000, backoff_seconds=2.0, max_backoff_seconds=5.0)
    sup.restart_count = 1
    assert sup._next_backoff() == 2.0
    sup.restart_count = 2
    assert sup._next_backoff() == 4.0
    sup.restart_count = 500  # would be 1000 s uncapped
    assert sup._next_backoff() == 5.0


def test_supervisor_wait_blocks_without_busy_polling():
    """The monitor must block in `child.wait()` rather than poll at
    `monitor_interval`: a child that exits instantly ends supervision in far
    less wall time than even one poll interval would allow."""
    t0 = time.perf_counter()
    sup = Supervisor([sys.executable, "-c", "raise SystemExit(0)"], max_restarts=0, monitor_interval=30.0)
    assert sup.run() == 0
    assert time.perf_counter() - t0 < 25.0, "run() appears to sleep on monitor_interval"


GRACEFUL_CHILD = """
import signal, sys, time
signal.signal(signal.SIGTERM, lambda *a: sys.exit(143))
open(sys.argv[1], "w").close()  # handler installed: safe to preempt
time.sleep(60)
"""


def _sigterm_self_once_ready(ready_path):
    """Background thread: SIGTERM this process once the child reports its own
    signal disposition is installed (a fixed timer races python startup)."""
    import threading

    def fire():
        deadline = time.perf_counter() + 30
        while not os.path.exists(ready_path) and time.perf_counter() < deadline:
            time.sleep(0.05)
        os.kill(os.getpid(), signal.SIGTERM)

    threading.Thread(target=fire, daemon=True).start()


@pytest.mark.faults
def test_forwarded_sigterm_exit_observed_well_within_grace():
    """Regression: the signal handler used to call child.wait() while the
    interrupted monitor wait held Popen._waitpid_lock, so even a child that
    exited instantly on SIGTERM stalled the FULL grace period and then got
    spuriously SIGKILLed. The handler must only forward + stamp the deadline;
    the monitor loop observes the graceful 143 within ~monitor_interval."""
    with tempfile.TemporaryDirectory() as d:
        script = _script(d, "graceful.py", GRACEFUL_CHILD)
        ready = os.path.join(d, "ready")
        sup = Supervisor(
            [sys.executable, script, ready],
            max_restarts=0,
            grace_period=30.0,  # the stall the old code paid in full
            monitor_interval=0.1,
        )
        _sigterm_self_once_ready(ready)
        t0 = time.perf_counter()
        code = sup.run()
        elapsed = time.perf_counter() - t0
    assert code == PREEMPTED_EXIT_CODE, f"child's graceful exit lost (got {code})"
    assert elapsed < 15.0, f"supervisor stalled {elapsed:.1f}s — grace-period deadlock regressed"


@pytest.mark.faults
def test_grace_period_expiry_hard_kills_stubborn_child():
    """A child that ignores SIGTERM is hard-killed one monitor cycle after the
    grace deadline, not left running forever."""
    with tempfile.TemporaryDirectory() as d:
        script = _script(
            d, "stubborn.py",
            "import signal, sys, time\nsignal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            'open(sys.argv[1], "w").close()\ntime.sleep(60)\n',
        )
        ready = os.path.join(d, "ready")
        sup = Supervisor(
            [sys.executable, script, ready], max_restarts=0, grace_period=1.0, monitor_interval=0.1
        )
        _sigterm_self_once_ready(ready)
        t0 = time.perf_counter()
        code = sup.run()
        elapsed = time.perf_counter() - t0
    assert code == -signal.SIGKILL
    assert elapsed < 20.0


def test_preemption_handler_latch():
    handler = PreemptionHandler()
    try:
        assert not handler.preemption_requested
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.1)
        assert handler.preemption_requested
        handler.reset()
        assert not handler.preemption_requested
    finally:
        handler.uninstall()


PREEMPT_TRAIN = """
import os, signal, sys, time
import numpy as np
import optax
from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler
from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel

out_dir = sys.argv[1]
accelerator = Accelerator(project_dir=out_dir)
accelerator.register_preemption_checkpoint(os.path.join(out_dir, "preempt_ckpt"))
data = [RegressionDataset(length=32)[i] for i in range(32)]
dl = SimpleDataLoader(data, BatchSampler(range(32), 8))
model, opt, pdl = accelerator.prepare(RegressionModel(), optax.sgd(0.05), dl)
print("READY", flush=True)
for epoch in range(10000):
    for batch in pdl:
        accelerator.backward(model.loss, batch)
        opt.step(); opt.zero_grad()
        accelerator.check_preemption()
    time.sleep(0.05)
"""


@pytest.mark.slow_launch
def test_check_preemption_saves_and_exits_143():
    with tempfile.TemporaryDirectory() as d:
        script = _script(d, "train.py", PREEMPT_TRAIN)
        proc = subprocess.Popen(
            [sys.executable, script, d],
            env=cpu_mesh_env(),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        # wait for steady state
        for line in proc.stdout:
            if "READY" in line:
                break
        time.sleep(1.0)
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert proc.returncode == PREEMPTED_EXIT_CODE, proc.stdout.read()
        ckpt = os.path.join(d, "preempt_ckpt")
        assert os.path.isdir(ckpt) and os.listdir(ckpt), "preemption checkpoint missing"


@pytest.mark.slow_launch
def test_launch_cli_max_restarts():
    with tempfile.TemporaryDirectory() as d:
        script = _script(d, "crashy.py", CRASHY)
        marker = os.path.join(d, "attempts")
        result = subprocess.run(
            [
                sys.executable,
                "-m",
                "accelerate_tpu.commands.accelerate_cli",
                "launch",
                "--max_restarts",
                "3",
                script,
                marker,
                "1",
            ],
            env=cpu_mesh_env(),
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert result.returncode == 0, result.stdout + result.stderr
        with open(marker) as f:
            assert f.read() == "2"


@pytest.mark.faults
def test_supervisor_no_forward_progress_crash_loop():
    """The uptime detector's complement: a child that runs for a while, dies
    with varying codes, but never advances the progress token (no new
    published checkpoint) is a livelock — `progress_fn` +
    `no_progress_threshold` must abort with the `no_forward_progress`
    diagnostic instead of burning the restart budget."""
    with tempfile.TemporaryDirectory() as d:
        marker = os.path.join(d, "n")
        body = (
            "import os, sys\n"
            "n = int(open(sys.argv[1]).read()) if os.path.exists(sys.argv[1]) else 0\n"
            "open(sys.argv[1], 'w').write(str(n + 1))\n"
            "raise SystemExit(10 + (n % 2))\n"  # varying codes: uptime detector stays quiet
        )
        sup = Supervisor(
            [sys.executable, "-c", body, marker],
            max_restarts=50,
            backoff_seconds=0.01,
            max_backoff_seconds=0.05,
            monitor_interval=0.05,
            crash_loop_min_uptime=0.0,  # disable the fast-exit detector
            progress_fn=lambda: None,   # nothing ever progresses
            no_progress_threshold=3,
        )
        code = sup.run()
        assert sup.crash_loop_detected is True
        assert sup.crash_loop_reason == "no_forward_progress"
        assert sup.restart_count < 10, "detector must stop well inside the budget"


@pytest.mark.faults
def test_supervisor_progress_resets_no_progress_counter():
    """A child that DOES advance the progress token on every attempt never
    trips the detector — the budget path decides as before."""
    with tempfile.TemporaryDirectory() as d:
        marker = os.path.join(d, "n")
        body = (
            "import os, sys\n"
            "n = int(open(sys.argv[1]).read()) if os.path.exists(sys.argv[1]) else 0\n"
            "open(sys.argv[1], 'w').write(str(n + 1))\n"
            "raise SystemExit(0 if n >= 5 else 9)\n"
        )

        def progress():
            return open(marker).read() if os.path.exists(marker) else None

        sup = Supervisor(
            [sys.executable, "-c", body, marker],
            max_restarts=10,
            backoff_seconds=0.01,
            monitor_interval=0.05,
            crash_loop_min_uptime=0.0,
            progress_fn=progress,
            no_progress_threshold=2,
        )
        code = sup.run()
        assert code == 0
        assert sup.crash_loop_detected is False
        assert sup.restart_count == 5

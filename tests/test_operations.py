"""Tests for L2 collectives (parity: reference test_utils/scripts/test_ops.py +
tests/test_utils.py operations coverage). Single-host: collectives degenerate to
identities with correct structure handling; sharded-global-array paths exercise the
SPMD semantics on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.state import AcceleratorState
from accelerate_tpu.utils import operations as ops


def test_recursively_apply_structure():
    data = {"a": np.ones(2), "b": [np.zeros(3), (np.ones(1),)], "c": "keep"}
    out = ops.recursively_apply(lambda t: t + 1, data)
    assert out["c"] == "keep"
    np.testing.assert_array_equal(out["a"], np.full(2, 2.0))
    np.testing.assert_array_equal(out["b"][1][0], np.full(1, 2.0))
    assert isinstance(out["b"][1], tuple)


def test_honor_type_namedtuple():
    from collections import namedtuple

    Point = namedtuple("Point", ["x", "y"])
    p = Point(np.ones(2), np.zeros(2))
    out = ops.recursively_apply(lambda t: t * 2, p)
    assert isinstance(out, Point)
    np.testing.assert_array_equal(out.x, np.full(2, 2.0))


def test_send_to_device():
    batch = {"x": np.ones((2, 2)), "y": [np.zeros(3)]}
    out = ops.send_to_device(batch)
    assert isinstance(out["x"], jax.Array)
    assert isinstance(out["y"][0], jax.Array)


def test_send_to_device_skip_keys():
    batch = {"x": np.ones((2, 2)), "meta": np.zeros(1)}
    out = ops.send_to_device(batch, skip_keys=["meta"])
    assert isinstance(out["x"], jax.Array)
    assert isinstance(out["meta"], np.ndarray)


def test_gather_single_process():
    out = ops.gather({"t": np.arange(4)})
    np.testing.assert_array_equal(out["t"], np.arange(4))


def test_gather_global_sharded_array():
    state = AcceleratorState()
    mesh = state.mesh
    x = jnp.arange(16.0).reshape(8, 2)
    x = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    out = ops.gather(x)
    np.testing.assert_array_equal(np.asarray(out), np.arange(16.0).reshape(8, 2))


def test_gather_object_single():
    assert ops.gather_object(["a"]) == ["a"]


def test_broadcast_object_list_single():
    objs = [1, "two", {"three": 3}]
    out = ops.broadcast_object_list(objs)
    assert out == [1, "two", {"three": 3}]


def test_reduce_mean_sum():
    x = np.full((2, 2), 4.0)
    np.testing.assert_array_equal(ops.reduce(x, "sum"), x)
    np.testing.assert_array_equal(ops.reduce(x, "mean"), x)
    np.testing.assert_array_equal(ops.reduce(x, "sum", scale=0.5), x / 2)


def test_pad_across_processes_noop_single():
    x = np.ones((3, 2))
    out = ops.pad_across_processes(x, dim=0)
    np.testing.assert_array_equal(out, x)


def test_pad_input_tensors():
    batch = {"x": np.arange(10).reshape(5, 2)}
    out = ops.pad_input_tensors(batch, batch_size=5, num_processes=4)
    assert out["x"].shape == (8, 2)
    np.testing.assert_array_equal(out["x"][5], out["x"][4])


def test_find_batch_size():
    assert ops.find_batch_size({"a": [np.ones((7, 2))]}) == 7
    assert ops.find_batch_size([]) is None


def test_concatenate():
    parts = [{"x": np.ones((2, 3))}, {"x": np.zeros((3, 3))}]
    out = ops.concatenate(parts)
    assert out["x"].shape == (5, 3)


def test_convert_to_fp32():
    data = {"h": jnp.ones(2, dtype=jnp.bfloat16), "f": jnp.ones(2, dtype=jnp.float32), "s": "str"}
    out = ops.convert_to_fp32(data)
    assert out["h"].dtype == jnp.float32
    assert out["f"].dtype == jnp.float32
    assert out["s"] == "str"


def test_listify():
    assert ops.listify({"a": np.arange(3)}) == {"a": [0, 1, 2]}


def test_get_data_structure():
    s = ops.get_data_structure({"a": np.ones((2, 3), dtype=np.float32)})
    assert s["a"]["shape"] == (2, 3)
    assert "float32" in s["a"]["dtype"]

"""Exhaustive shard-math tests for the data pipeline (parity: reference
tests/test_data_loader.py, which enumerates expected index lists for every
split/even/drop combination — same strategy here, fresh expectations derived from this
framework's documented contracts)."""

import numpy as np
import pytest

import jax

from accelerate_tpu.data_loader import (
    BatchSampler,
    BatchSamplerShard,
    DataLoaderDispatcher,
    DataLoaderShard,
    IterableDatasetShard,
    SeedableRandomSampler,
    SimpleDataLoader,
    SkipBatchSampler,
    prepare_data_loader,
    skip_first_batches,
)
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState


def make_batches(n, batch_size, drop_last=False):
    return BatchSampler(range(n), batch_size, drop_last=drop_last)


def shards(n, batch_size, num_processes, **kwargs):
    sampler = make_batches(n, batch_size, drop_last=kwargs.pop("drop_last", False))
    return [
        list(BatchSamplerShard(sampler, num_processes=num_processes, process_index=i, **kwargs))
        for i in range(num_processes)
    ]


class TestBatchSamplerShardNoSplit:
    def test_exact_division(self):
        # 24 samples, batch 4, 2 procs: 6 batches, strided assignment
        result = shards(24, 4, 2)
        assert result[0] == [[0, 1, 2, 3], [8, 9, 10, 11], [16, 17, 18, 19]]
        assert result[1] == [[4, 5, 6, 7], [12, 13, 14, 15], [20, 21, 22, 23]]

    def test_even_batches_pads_short_final_batch(self):
        # 21 samples, batch 4, 2 procs: batches [..],[..],[..],[..],[..],[20] (short)
        result = shards(21, 4, 2)
        # All batches must be size 4 and both procs have equal counts
        assert all(len(b) == 4 for proc in result for b in proc)
        assert len(result[0]) == len(result[1]) == 3
        # Padding cycles from the epoch start
        assert result[1][-1][0] == 20

    def test_even_batches_pads_missing_process_batch(self):
        # 20 samples, batch 4, 3 procs: 5 batches -> group of 2 left; proc 2 padded
        result = shards(20, 4, 3)
        assert len(result[0]) == len(result[1]) == len(result[2]) == 2
        assert all(len(b) == 4 for proc in result for b in proc)
        # proc2's final batch is fabricated from epoch-start samples
        assert result[2][1] == [0, 1, 2, 3]

    def test_uneven_batches(self):
        result = shards(20, 4, 3, even_batches=False)
        # 5 batches: proc0 gets 2, proc1 gets 2, proc2 gets 1
        assert [len(r) for r in result] == [2, 2, 1]
        flat = [i for proc in result for batch in proc for i in batch]
        assert sorted(flat) == list(range(20))

    def test_drop_last(self):
        # 21 samples, batch 4, 2 procs, drop_last: short batch dropped -> 5 full batches
        # -> incomplete final group dropped -> 2 steps each
        result = shards(21, 4, 2, drop_last=True)
        assert [len(r) for r in result] == [2, 2]
        assert result[0] == [[0, 1, 2, 3], [8, 9, 10, 11]]

    def test_coverage_union(self):
        # Every real sample appears somewhere
        for n in (17, 24, 31):
            for p in (2, 3, 4):
                result = shards(n, 4, p)
                flat = {i for proc in result for batch in proc for i in batch}
                assert flat == set(range(n)), (n, p)

    def test_len_matches_iteration(self):
        sampler = make_batches(21, 4)
        for p in (1, 2, 3):
            for i in range(p):
                s = BatchSamplerShard(sampler, num_processes=p, process_index=i)
                assert len(list(s)) == len(s), (p, i)


class TestBatchSamplerShardSplit:
    def test_exact(self):
        # global batch 8 split over 2 procs -> each proc gets 4 of every batch
        result = shards(16, 8, 2, split_batches=True)
        assert result[0] == [[0, 1, 2, 3], [8, 9, 10, 11]]
        assert result[1] == [[4, 5, 6, 7], [12, 13, 14, 15]]

    def test_short_final_padded(self):
        result = shards(18, 8, 2, split_batches=True)
        assert all(len(b) == 4 for proc in result for b in proc)
        assert len(result[0]) == 3
        # final global batch [16,17] padded with epoch-start samples
        assert result[0][2] == [16, 17, 0, 1]
        assert result[1][2] == [2, 3, 4, 5]

    def test_batch_size_not_divisible_raises(self):
        sampler = make_batches(16, 6)
        with pytest.raises(ValueError):
            BatchSamplerShard(sampler, num_processes=4, process_index=0, split_batches=True)


class TestIterableDatasetShard:
    def test_even(self):
        shard0 = list(IterableDatasetShard(range(16), batch_size=2, num_processes=2, process_index=0))
        shard1 = list(IterableDatasetShard(range(16), batch_size=2, num_processes=2, process_index=1))
        assert shard0 == [0, 1, 4, 5, 8, 9, 12, 13]
        assert shard1 == [2, 3, 6, 7, 10, 11, 14, 15]

    def test_tail_padded(self):
        shard0 = list(IterableDatasetShard(range(5), batch_size=2, num_processes=2, process_index=0))
        shard1 = list(IterableDatasetShard(range(5), batch_size=2, num_processes=2, process_index=1))
        assert len(shard0) == len(shard1) == 4
        union = set(shard0) | set(shard1)
        assert set(range(5)) <= union

    def test_split_batches_mode(self):
        # batch_size is global (4); each proc gets 2 per batch
        shard0 = list(IterableDatasetShard(range(8), batch_size=4, num_processes=2, process_index=0, split_batches=True))
        assert shard0 == [0, 1, 4, 5]

    def test_drop_last(self):
        shard0 = list(IterableDatasetShard(range(5), batch_size=2, num_processes=2, process_index=0, drop_last=True))
        assert shard0 == [0, 1]


class TestSeedableSampler:
    def test_deterministic_and_epoch_varying(self):
        s1 = SeedableRandomSampler(num_samples=10, seed=42)
        s2 = SeedableRandomSampler(num_samples=10, seed=42)
        e0a, e0b = list(s1), list(s2)
        assert e0a == e0b
        assert list(s1) == e0a  # standalone: same order until set_epoch
        s1.set_epoch(1)
        e1 = list(s1)
        assert e1 != e0a
        assert sorted(e1) == list(range(10))

    def test_state_roundtrip(self):
        s = SeedableRandomSampler(num_samples=10, seed=1, epoch=3)
        state = s.state_dict()
        s2 = SeedableRandomSampler(num_samples=10, seed=0)
        s2.load_state_dict(state)
        assert list(s2) == list(SeedableRandomSampler(num_samples=10, seed=1, epoch=3))


def _toy_dataset(n=24, dim=3):
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(n, dim)).astype(np.float32)
    ys = (2 * xs.sum(-1) + 3).astype(np.float32)
    return [{"x": xs[i], "y": ys[i]} for i in range(n)]


class TestDataLoaderShard:
    def test_yields_global_arrays_with_sharding(self):
        AcceleratorState()
        data = _toy_dataset(24)
        loader = SimpleDataLoader(data, BatchSampler(range(24), 8))
        dl = prepare_data_loader(loader)
        batches = list(dl)
        assert len(batches) == 3
        assert isinstance(batches[0]["x"], jax.Array)
        assert batches[0]["x"].shape == (8, 3)
        # sharded over the 8 data-axis devices
        assert len(batches[0]["x"].sharding.device_set) == 8

    def test_end_of_dataloader_and_remainder(self):
        AcceleratorState()
        data = _toy_dataset(20)
        loader = SimpleDataLoader(data, BatchSampler(range(20), 8))
        dl = prepare_data_loader(loader)
        gs = GradientState()
        ends = []
        for batch in dl:
            ends.append(gs.end_of_dataloader)
        assert ends == [False, False, True]
        # After iteration finishes the dataloader deregisters
        assert not gs.in_dataloader

    def test_remainder_value(self):
        AcceleratorState()
        data = _toy_dataset(20)
        loader = SimpleDataLoader(data, BatchSampler(range(20), 8))
        dl = prepare_data_loader(loader)
        gs = GradientState()
        for batch in dl:
            pass
        assert dl.remainder == 20 % 8

    def test_device_placement_off(self):
        data = _toy_dataset(8)
        loader = SimpleDataLoader(data, BatchSampler(range(8), 4))
        dl = prepare_data_loader(loader, put_on_device=False)
        b = next(iter(dl))
        assert isinstance(b["x"], np.ndarray)

    def test_skip_first_batches(self):
        AcceleratorState()
        data = _toy_dataset(24)
        loader = SimpleDataLoader(data, BatchSampler(range(24), 8))
        dl = prepare_data_loader(loader)
        all_batches = [np.asarray(b["x"]) for b in dl]
        skipped = skip_first_batches(dl, 2)
        rest = [np.asarray(b["x"]) for b in skipped]
        assert len(rest) == 1
        np.testing.assert_array_equal(rest[0], all_batches[2])

    def test_prefetch_size_zero_is_synchronous(self):
        """prefetch_size=0 now means NO producer thread (it used to be silently
        clamped to 1): batches are processed inline on the consumer thread, and
        the one-batch lookahead contract (end_of_dataloader before the final
        yield) still holds."""
        import threading

        AcceleratorState()
        data = _toy_dataset(24)
        loader = SimpleDataLoader(data, BatchSampler(range(24), 8))
        dl = prepare_data_loader(loader, prefetch_size=0)
        assert dl.prefetch_size == 0
        gs = GradientState()
        consumer = threading.get_ident()
        seen_threads = set()
        orig = dl._process_batch

        def spying(batch):
            seen_threads.add(threading.get_ident())
            return orig(batch)

        dl._process_batch = spying
        ends = [gs.end_of_dataloader for _ in dl]
        assert ends == [False, False, True]
        assert seen_threads == {consumer}  # no producer thread ran
        # and the stream is identical to the threaded path
        dl_threaded = prepare_data_loader(
            SimpleDataLoader(data, BatchSampler(range(24), 8)), prefetch_size=2
        )
        for a, b in zip(dl, dl_threaded):
            np.testing.assert_array_equal(np.asarray(a["x"]), np.asarray(b["x"]))

    def test_set_epoch_reshuffles(self):
        data = _toy_dataset(16)
        sampler = SeedableRandomSampler(num_samples=16, seed=7)
        loader = SimpleDataLoader(data, BatchSampler(sampler, 8))
        dl = prepare_data_loader(loader, put_on_device=False)
        first = [np.asarray(b["x"]) for b in dl]
        second = [np.asarray(b["x"]) for b in dl]
        assert not all(np.array_equal(a, b) for a, b in zip(first, second))


class TestTorchLoaderIntegration:
    def test_torch_loader_prepared(self):
        torch = pytest.importorskip("torch")
        from torch.utils.data import DataLoader, TensorDataset

        AcceleratorState()
        xs = torch.arange(48, dtype=torch.float32).reshape(24, 2)
        ys = torch.arange(24, dtype=torch.float32)
        dl = DataLoader(TensorDataset(xs, ys), batch_size=8, shuffle=False)
        prepared = prepare_data_loader(dl)
        batches = list(prepared)
        assert len(batches) == 3
        x0, y0 = batches[0]
        assert isinstance(x0, jax.Array) and x0.shape == (8, 2)
        np.testing.assert_array_equal(np.asarray(y0), np.arange(8.0))

    def test_torch_loader_seedable_shuffle_deterministic(self):
        torch = pytest.importorskip("torch")
        from torch.utils.data import DataLoader, TensorDataset

        AcceleratorState()
        xs = torch.arange(16, dtype=torch.float32).reshape(16, 1)
        ds = TensorDataset(xs)
        dl1 = prepare_data_loader(DataLoader(ds, batch_size=4, shuffle=True), data_seed=11)
        dl2 = prepare_data_loader(DataLoader(ds, batch_size=4, shuffle=True), data_seed=11)
        b1 = [np.asarray(b[0]) for b in dl1]
        b2 = [np.asarray(b[0]) for b in dl2]
        for a, b in zip(b1, b2):
            np.testing.assert_array_equal(a, b)


class TestDispatcher:
    def test_single_process_dispatch_matches_shard(self):
        AcceleratorState()
        data = _toy_dataset(16)
        loader = SimpleDataLoader(data, BatchSampler(range(16), 8))
        dl = prepare_data_loader(loader, dispatch_batches=True)
        assert isinstance(dl, DataLoaderDispatcher)
        batches = list(dl)
        assert len(batches) == 2
        assert isinstance(batches[0]["x"], jax.Array)
        assert batches[0]["x"].shape == (8, 3)

    def test_dispatch_end_of_dataloader(self):
        AcceleratorState()
        data = _toy_dataset(16)
        loader = SimpleDataLoader(data, BatchSampler(range(16), 8))
        dl = prepare_data_loader(loader, dispatch_batches=True)
        gs = GradientState()
        ends = [gs.end_of_dataloader for _ in dl]
        assert ends == [False, True]


class TestSkipBatchSampler:
    def test_skip(self):
        sampler = make_batches(24, 4)
        skipper = SkipBatchSampler(sampler, 2)
        assert list(skipper) == [[8, 9, 10, 11], [12, 13, 14, 15], [16, 17, 18, 19], [20, 21, 22, 23]]
        assert len(skipper) == 4

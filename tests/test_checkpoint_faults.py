"""Fault-injection tests for the crash-safe checkpoint layer.

Pins the resilience contract of `checkpointing.py` + `CheckpointManager` +
`Accelerator.save_state/load_state`:

  1. a kill at ANY point during a save never publishes a checkpoint that
     `load_state` accepts — the staging-dir rename is the single commit point;
  2. digest verification catches torn/corrupted artifacts (truncated `.npz`,
     flipped bytes) instead of half-reading them;
  3. resume via `"latest"` falls back past a corrupt newest checkpoint to the
     last verified one, and the next save replaces the torn directory and
     rotates correctly.

Scripted faults ride the chaos injectors (`accelerate_tpu.chaos`) — declarative
`FaultPlan`s at the seams the code owns — instead of ad-hoc monkeypatching;
only byte-level corruption of files already on disk stays manual. All tests are
CPU-only, subprocess-free and fast (tier-1; `-m faults` or `-m chaos` selects
them).
"""

import json
import os

import numpy as np
import pytest

import optax

from accelerate_tpu import Accelerator, SimpleDataLoader
from accelerate_tpu.chaos import (
    ChaosSession,
    FaultEvent,
    FaultPlan,
    FilesystemInjector,
    InjectedKill,
)
from accelerate_tpu.checkpointing import (
    CHECKPOINT_MANIFEST_NAME,
    LATEST_POINTER_NAME,
    CheckpointCorruptError,
    CheckpointManager,
    atomic_write,
    atomic_write_bytes,
    load_pytree,
    save_pytree,
    verify_checkpoint_dir,
    write_checkpoint_manifest,
)
from accelerate_tpu.data_loader import BatchSampler
from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel
from accelerate_tpu.utils import ProjectConfiguration

pytestmark = [pytest.mark.faults, pytest.mark.chaos]


# ------------------------------------------------------------------ file-level atomicity
def test_atomic_write_preserves_previous_content_on_failure(tmp_path):
    """A writer that dies mid-stream must leave the previous complete file (and
    no temp litter) — the byte-offset half of the torn-write guarantee."""
    target = tmp_path / "state.json"
    atomic_write(str(target), lambda f: f.write(b"old-complete"))

    class MidWriteKill(RuntimeError):
        pass

    def torn_writer(f):
        f.write(b"new-but-")
        raise MidWriteKill("killed mid-write")

    with pytest.raises(MidWriteKill):
        atomic_write(str(target), torn_writer)
    assert target.read_bytes() == b"old-complete"
    assert os.listdir(tmp_path) == ["state.json"], "temp litter left behind"


def test_load_pytree_rejects_truncated_npz(tmp_path):
    tree = {"w": np.arange(64, dtype=np.float32), "b": np.ones((8,), np.float32)}
    base = str(tmp_path / "model")
    save_pytree(tree, base)
    npz = base + ".npz"
    size = os.path.getsize(npz)
    with open(npz, "r+b") as f:
        f.truncate(size // 2)
    with pytest.raises(CheckpointCorruptError, match="SHA-256 mismatch"):
        load_pytree(base)


def test_load_pytree_rejects_flipped_bytes(tmp_path):
    """Silent bit rot (same length, different bytes) is caught too — length
    checks alone would miss it."""
    tree = {"w": np.arange(64, dtype=np.float32)}
    base = str(tmp_path / "model")
    save_pytree(tree, base)
    npz = base + ".npz"
    data = bytearray(open(npz, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(npz, "wb") as f:
        f.write(bytes(data))
    with pytest.raises(CheckpointCorruptError):
        load_pytree(base)


# ------------------------------------------------------------------ directory-level commit
def _write_artifacts(names):
    def write_fn(staging):
        for name in names:
            with open(os.path.join(staging, name), "w") as f:
                f.write(f"payload:{name}")

    return write_fn


def test_manager_commit_layout_and_latest_pointer(tmp_path):
    manager = CheckpointManager(str(tmp_path))
    path = manager.save(0, _write_artifacts(["model.npz", "optimizer.npz"]))
    assert os.path.basename(path) == "checkpoint_0"
    assert verify_checkpoint_dir(path)
    with open(os.path.join(str(tmp_path), LATEST_POINTER_NAME)) as f:
        assert f.read() == "checkpoint_0"
    with open(os.path.join(path, CHECKPOINT_MANIFEST_NAME)) as f:
        manifest = json.load(f)
    assert set(manifest["files"]) == {"model.npz", "optimizer.npz"}
    assert manager.resolve("latest") == path


def test_manager_rotation_keeps_last_n(tmp_path):
    manager = CheckpointManager(str(tmp_path), keep_last_n=2)
    for step in range(4):
        manager.save(step, _write_artifacts([f"a{step}.bin"]))
    assert [s for s, _ in manager.checkpoints()] == [2, 3]
    assert manager.resolve("latest").endswith("checkpoint_3")


@pytest.mark.parametrize("artifacts_before_kill", [0, 1, 2])
def test_kill_between_any_two_artifact_writes_never_publishes(tmp_path, artifacts_before_kill):
    """The acceptance-criterion sweep, on the chaos injectors: a scripted
    rename-window kill interrupts the save at each artifact in turn. Whatever
    the offset, the in-flight checkpoint must never become visible and `latest`
    must keep resolving to the previous verified save. (`InjectedKill` is a
    BaseException: even a SIGKILL-like non-Exception path must not commit.)"""
    manager = CheckpointManager(str(tmp_path))
    good = manager.save(0, _write_artifacts(["model.npz", "optimizer.npz"]))

    plan = FaultPlan(events=[
        FaultEvent(kind="fs.crash_in_rename", path_pattern="part*.bin",
                   at_call=artifacts_before_kill + 1),
    ])

    def atomic_write_fn(staging):
        for i in range(3):
            atomic_write_bytes(os.path.join(staging, f"part{i}.bin"), b"payload")

    with FilesystemInjector(ChaosSession(plan)):
        with pytest.raises(InjectedKill):
            manager.save(1, atomic_write_fn)
    # the torn save is invisible: no checkpoint_1, latest still the good one
    assert [s for s, _ in manager.checkpoints()] == [0]
    assert manager.resolve("latest") == good
    with open(os.path.join(str(tmp_path), LATEST_POINTER_NAME)) as f:
        assert f.read() == "checkpoint_0"
    # staging litter is ignorable and reapable; a retry then lands cleanly
    manager.clean_staging()
    assert manager.save(1, _write_artifacts(["model.npz"])) != good
    assert [s for s, _ in manager.checkpoints()] == [0, 1]


def test_latest_verified_falls_back_past_torn_newest(tmp_path):
    manager = CheckpointManager(str(tmp_path))
    good = manager.save(0, _write_artifacts(["model.npz"]))
    torn = manager.save(1, _write_artifacts(["model.npz"]))
    with open(os.path.join(torn, "model.npz"), "w") as f:
        f.write("truncat")  # digest no longer matches
    assert not verify_checkpoint_dir(torn)
    assert manager.latest_verified() == good
    assert manager.resolve("latest") == good
    # naming the bad checkpoint explicitly is a hard error, not a silent fallback
    with pytest.raises(CheckpointCorruptError):
        manager.resolve(torn)


def test_missing_artifact_fails_verification(tmp_path):
    manager = CheckpointManager(str(tmp_path))
    path = manager.save(0, _write_artifacts(["model.npz", "optimizer.npz"]))
    os.unlink(os.path.join(path, "optimizer.npz"))
    assert not verify_checkpoint_dir(path)
    assert manager.latest_verified() is None
    with pytest.raises(FileNotFoundError, match="no verified checkpoint"):
        manager.resolve("latest")


def test_save_refuses_to_clobber_verified_but_replaces_torn(tmp_path):
    manager = CheckpointManager(str(tmp_path))
    path = manager.save(0, _write_artifacts(["model.npz"]))
    with pytest.raises(ValueError, match="already exists"):
        manager.save(0, _write_artifacts(["model.npz"]))
    # tear it, and the same step becomes replaceable (the post-fallback resave)
    with open(os.path.join(path, "model.npz"), "w") as f:
        f.write("torn")
    replaced = manager.save(0, _write_artifacts(["model.npz"]))
    assert replaced == path and verify_checkpoint_dir(replaced)


def test_legacy_pre_manifest_checkpoints_survive_an_upgrade(tmp_path):
    """An in-place upgrade finds checkpoints written BEFORE the manifest
    discipline (no MANIFEST.json). They must stay resumable as a last resort,
    must not be destroyed newest-first by rotation, and must never be clobbered
    by a colliding save — while digest-verified checkpoints always win."""
    for step in (0, 1):  # legacy layout: bare dirs, no manifest
        legacy = tmp_path / f"checkpoint_{step}"
        legacy.mkdir()
        (legacy / "model.npz").write_text(f"legacy payload {step}")
    manager = CheckpointManager(str(tmp_path), keep_last_n=2)
    # nothing verifies, but resume still lands on the NEWEST legacy checkpoint
    assert manager.resolve("latest") == str(tmp_path / "checkpoint_1")
    # a colliding save refuses to silently destroy a legacy checkpoint
    with pytest.raises(ValueError, match="already exists"):
        manager.save(1, _write_artifacts(["model.npz"]))
    # new saves append; once one verifies, it wins over every legacy dir
    new = manager.save(manager.next_step(), _write_artifacts(["model.npz"]))
    assert manager.resolve("latest") == new
    # rotation ages legacy checkpoints out OLDEST-first, like any checkpoint
    assert [s for s, _ in manager.checkpoints()] == [1, 2]


def test_transient_io_errors_retry_with_backoff(tmp_path):
    """The publish sequence retries OSErrors (full-disk blips, NFS hiccups)
    instead of dying on the first one — scripted as two transient EIOs on the
    checkpoint-directory publish rename."""
    manager = CheckpointManager(str(tmp_path), retries=3, backoff_seconds=0.0)
    plan = FaultPlan(events=[
        FaultEvent(kind="fs.io_error", path_pattern="checkpoint_0", times=2,
                   args={"errno": "EIO"}),
    ])
    session = ChaosSession(plan)
    with FilesystemInjector(session):
        path = manager.save(0, _write_artifacts(["model.npz"]))
    assert session.counts() == {"fs.io_error": 2}
    assert verify_checkpoint_dir(path)
    assert manager.resolve("latest") == path


def test_publish_retry_after_pointer_write_failure_is_idempotent(tmp_path):
    """Chaos-surfaced bug, fixed this PR: a transient failure on the `latest`
    pointer write lands AFTER the directory rename. The retry used to re-run
    `os.replace` on the vanished staging dir and raise FileNotFoundError out of
    a save whose checkpoint was already fully committed."""
    manager = CheckpointManager(str(tmp_path), retries=3, backoff_seconds=0.0)
    plan = FaultPlan(events=[
        FaultEvent(kind="fs.io_error", path_pattern=LATEST_POINTER_NAME, at_call=1),
    ])
    with FilesystemInjector(ChaosSession(plan)):
        path = manager.save(0, _write_artifacts(["model.npz"]))
    assert verify_checkpoint_dir(path)
    assert manager.resolve("latest") == path
    with open(os.path.join(str(tmp_path), LATEST_POINTER_NAME)) as f:
        assert f.read() == "checkpoint_0"


def test_rotation_survives_rmtree_raising_after_partial_delete(tmp_path, monkeypatch):
    """Chaos-surfaced bug, fixed this PR: rotation's retry used to re-run
    `shutil.rmtree` on a directory the failed first attempt had already
    removed, so the FileNotFoundError retried until exhaustion and failed a
    save whose rotation had effectively succeeded."""
    import shutil as _shutil

    manager = CheckpointManager(str(tmp_path), keep_last_n=1, retries=3, backoff_seconds=0.0)
    manager.save(0, _write_artifacts(["a.bin"]))
    real_rmtree = _shutil.rmtree
    state = {"armed": True}

    def delete_then_raise(path, **kwargs):
        if state["armed"] and os.path.basename(path) == "checkpoint_0":
            state["armed"] = False
            real_rmtree(path)  # the deletion itself succeeded...
            raise OSError("transient error reported after the delete")
        return real_rmtree(path, **kwargs)

    monkeypatch.setattr(_shutil, "rmtree", delete_then_raise)
    path = manager.save(1, _write_artifacts(["a.bin"]))
    assert [s for s, _ in manager.checkpoints()] == [1]
    assert verify_checkpoint_dir(path)


def test_verify_checkpoint_dir_survives_bitflipped_manifest(tmp_path):
    """Chaos-surfaced bug, fixed this PR: one flipped byte can make
    MANIFEST.json invalid UTF-8 — verification must read that as 'does not
    verify' and resolution must fall back, not crash with UnicodeDecodeError."""
    manager = CheckpointManager(str(tmp_path))
    good = manager.save(0, _write_artifacts(["model.npz"]))
    flipped = manager.save(1, _write_artifacts(["model.npz"]))
    manifest = os.path.join(flipped, CHECKPOINT_MANIFEST_NAME)
    data = bytearray(open(manifest, "rb").read())
    data[len(data) // 2] = 0xFF  # invalid UTF-8 continuation byte
    with open(manifest, "wb") as f:
        f.write(bytes(data))
    assert verify_checkpoint_dir(flipped) is False
    assert manager.resolve("latest") == good


def test_write_checkpoint_manifest_skips_staging_and_temp_litter(tmp_path):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    (ckpt / "model.npz").write_text("payload")
    (ckpt / "model.npz.tmp-123").write_text("litter from a killed writer")
    (ckpt / ".tmp-checkpoint_9").mkdir()
    (ckpt / ".tmp-checkpoint_9" / "x").write_text("staging litter")
    write_checkpoint_manifest(str(ckpt))
    with open(ckpt / CHECKPOINT_MANIFEST_NAME) as f:
        manifest = json.load(f)
    assert set(manifest["files"]) == {"model.npz"}
    assert verify_checkpoint_dir(str(ckpt))


# ------------------------------------------------------------------ Accelerator-level resume
def _prepared_accelerator(project_dir, total_limit=None):
    accelerator = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(project_dir),
            automatic_checkpoint_naming=True,
            total_limit=total_limit,
        )
    )
    data = [RegressionDataset(length=16)[i] for i in range(16)]
    dl = SimpleDataLoader(data, BatchSampler(range(16), 8))
    model, opt, pdl = accelerator.prepare(RegressionModel(), optax.sgd(0.05), dl)
    return accelerator, model, opt, pdl


def _train_one_pass(accelerator, model, opt, pdl):
    for batch in pdl:
        accelerator.backward(model.loss, batch)
        opt.step()
        opt.zero_grad()


def _params(model):
    import jax

    return [np.asarray(x) for x in jax.tree_util.tree_leaves(model.params)]


def test_load_state_latest_falls_back_past_torn_newest_checkpoint(tmp_path):
    """The end-to-end resume story: train, save, train, save, tear the newest
    checkpoint at the byte level — `load_state("latest")` must land on the
    previous verified checkpoint's exact parameters, and the next `save_state`
    must replace the torn directory with a verified one."""
    accelerator, model, opt, pdl = _prepared_accelerator(tmp_path, total_limit=3)

    _train_one_pass(accelerator, model, opt, pdl)
    accelerator.save_state()  # checkpoint_0
    params_at_0 = _params(model)
    _train_one_pass(accelerator, model, opt, pdl)
    accelerator.save_state()  # checkpoint_1
    _train_one_pass(accelerator, model, opt, pdl)
    assert not all(np.array_equal(a, b) for a, b in zip(_params(model), params_at_0))

    # tear checkpoint_1: truncate its model payload mid-file
    ckpt1 = os.path.join(str(tmp_path), "checkpoints", "checkpoint_1")
    npz = os.path.join(ckpt1, "model.npz")
    with open(npz, "r+b") as f:
        f.truncate(os.path.getsize(npz) // 2)
    assert not verify_checkpoint_dir(ckpt1)

    accelerator.load_state("latest")  # falls back to checkpoint_0
    for got, want in zip(_params(model), params_at_0):
        np.testing.assert_array_equal(got, want)
    # numbering resumed after the restored checkpoint: the next save replaces
    # the torn checkpoint_1 with a verified one and latest advances onto it
    assert accelerator.save_iteration == 1
    path = accelerator.save_state()
    assert path == ckpt1 and verify_checkpoint_dir(path)
    manager = accelerator.checkpoint_manager()
    assert manager.resolve("latest") == path


def test_save_state_rotates_to_total_limit_and_latest_tracks(tmp_path):
    accelerator, model, opt, pdl = _prepared_accelerator(tmp_path, total_limit=2)
    for _ in range(3):
        _train_one_pass(accelerator, model, opt, pdl)
        accelerator.save_state()
    manager = accelerator.checkpoint_manager()
    assert [s for s, _ in manager.checkpoints()] == [1, 2]
    assert manager.resolve("latest").endswith("checkpoint_2")
    assert all(verify_checkpoint_dir(p) for _, p in manager.checkpoints())


def test_explicit_dir_save_state_writes_manifest_and_verifies(tmp_path):
    """The non-automatic path keeps the old API (write into the named dir) but
    now finishes with a digest manifest, so explicit checkpoints verify too."""
    accelerator, model, opt, pdl = _prepared_accelerator(tmp_path)
    accelerator.project_configuration.automatic_checkpoint_naming = False
    _train_one_pass(accelerator, model, opt, pdl)
    out = accelerator.save_state(str(tmp_path / "explicit_ckpt"))
    assert verify_checkpoint_dir(out)
    saved = _params(model)
    _train_one_pass(accelerator, model, opt, pdl)
    accelerator.load_state(out)
    for got, want in zip(_params(model), saved):
        np.testing.assert_array_equal(got, want)
    # corrupt it and the explicit load refuses instead of half-reading
    npz = os.path.join(out, "model.npz")
    with open(npz, "r+b") as f:
        f.truncate(os.path.getsize(npz) // 2)
    with pytest.raises(CheckpointCorruptError):
        accelerator.load_state(out)

"""Numerics tests for sequence-parallel ring attention: the sharded ring must match
dense single-device attention to float tolerance (causal + bidirectional + GQA), and a
sequence-parallel training step must run through the Accelerator."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.ops.attention import dot_product_attention
from accelerate_tpu.parallel.mesh import build_mesh
from accelerate_tpu.parallel.ring_attention import sequence_parallel_attention
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils import ParallelismConfig, SequenceParallelPlugin


def _qkv(b=2, s=32, h=4, hkv=None, d=16, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, hkv or h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, hkv or h, d)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("mode", ["ring", "allgather"])
@pytest.mark.slow
def test_ring_matches_dense(causal, mode):
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv()
    dense = dot_product_attention(q, k, v, causal=causal, implementation="xla")
    ring = sequence_parallel_attention(q, k, v, mesh=mesh, causal=causal, mode=mode)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_ring_under_jit_and_grad():
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv(s=16)

    def loss_ring(q, k, v):
        return jnp.sum(sequence_parallel_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True, implementation="xla") ** 2)

    g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense), rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_ring_with_tp_heads():
    """2D attention parallelism: heads over "model", sequence over "seq"."""
    mesh = build_mesh(ParallelismConfig(data=1, model=2, seq=4))
    q, k, v = _qkv(h=4)
    dense = dot_product_attention(q, k, v, causal=True, implementation="xla")
    ring = sequence_parallel_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_auto_dispatch_via_accelerator_state():
    """Models get ring attention automatically when the (built) mesh has a seq axis."""
    state = AcceleratorState(
        parallelism_config=ParallelismConfig(data=2, seq=4),
        sequence_parallel_plugin=SequenceParallelPlugin(seq_degree=4),
    )
    state.mesh  # dispatch requires the mesh to exist; forwards never build it lazily
    q, k, v = _qkv()
    out_auto = dot_product_attention(q, k, v, causal=True)  # should route to ring
    out_dense = dot_product_attention(q, k, v, causal=True, implementation="xla")
    np.testing.assert_allclose(np.asarray(out_auto), np.asarray(out_dense), rtol=2e-5, atol=2e-5)
    # and the routed path really is the ring: the sharded output spec names "seq"
    from accelerate_tpu.parallel.ring_attention import sequence_parallel_attention

    out = sequence_parallel_attention(q, k, v, mesh=state.mesh, causal=True)
    assert "seq" in str(out.sharding.spec)


def test_no_dispatch_without_built_mesh():
    """A forward pass must not build the mesh or mutate global state."""
    assert AcceleratorState._shared_state == {}
    q, k, v = _qkv()
    dot_product_attention(q, k, v, causal=True)
    assert AcceleratorState._shared_state == {}, "attention op must not initialize AcceleratorState"


@pytest.mark.slow
def test_ring_gqa():
    """GQA: ring rotates hkv-sized blocks; numerics must still match dense."""
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv(h=8, hkv=2)
    dense = dot_product_attention(q, k, v, causal=True, implementation="xla")
    ring = sequence_parallel_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_sequence_parallel_training_step():
    """End-to-end: a Llama step with the seq axis active trains through the Accelerator."""
    import optax

    from accelerate_tpu import Accelerator, SimpleDataLoader
    from accelerate_tpu.data_loader import BatchSampler
    from accelerate_tpu.models.llama import create_llama_model, llama_tiny

    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(data=2, seq=4),
        sequence_parallel_plugin=SequenceParallelPlugin(seq_degree=4),
    )
    assert accelerator.mesh.shape["seq"] == 4
    model = create_llama_model(llama_tiny(), seq_len=32)
    rng = np.random.default_rng(0)
    data = [{"input_ids": rng.integers(1, 500, size=(32,)).astype(np.int32)} for _ in range(8)]
    dl = SimpleDataLoader(data, BatchSampler(range(8), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adam(1e-3), dl)
    for batch in pdl:
        with accelerator.accumulate(pmodel):
            loss = accelerator.backward(pmodel.loss, batch)
            popt.step()
            popt.zero_grad()
    assert np.isfinite(float(loss))


# ------------------------------------------------------------- segment-id masking
@pytest.mark.parametrize("mode", ["ring", "allgather"])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_segment_ids_match_dense(mode, causal):
    """Packed-sequence masking must ride the sequence-parallel path (round-3
    verdict: masked variants used to silently fall back) and equal the dense
    segment-masked reference."""
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv(s=32)
    rng = np.random.default_rng(3)
    # 2-4 packed segments per row, contiguous (sorted) ids
    seg = np.sort(rng.integers(0, 3, size=(2, 32)), axis=1).astype(np.int32)
    seg = jnp.asarray(seg)
    dense = dot_product_attention(q, k, v, causal=causal, implementation="xla", segment_ids=seg)
    ring = sequence_parallel_attention(
        q, k, v, mesh=mesh, causal=causal, mode=mode, segment_ids=seg
    )
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_ring_segment_ids_grads_match_dense():
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv(s=16)
    seg = jnp.asarray(np.repeat([[0, 1]], 2, axis=0).repeat(8, axis=1))  # two segments

    def loss_ring(q, k, v):
        return jnp.sum(
            sequence_parallel_attention(q, k, v, mesh=mesh, causal=True, segment_ids=seg) ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(
            dot_product_attention(q, k, v, causal=True, implementation="xla", segment_ids=seg) ** 2
        )

    g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense), rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_segment_ids_dispatch_through_model_seam():
    """dot_product_attention with segment_ids on a seq mesh must dispatch to the
    ring (LAST_DISPATCH), not silently fall back to dense."""
    from accelerate_tpu.ops import attention as attn_mod
    from accelerate_tpu.state import AcceleratorState

    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    AcceleratorState._shared_state["_mesh"] = mesh
    try:
        q, k, v = _qkv(s=32)
        seg = jnp.asarray(np.zeros((2, 32), np.int32))
        out = dot_product_attention(q, k, v, causal=True, segment_ids=seg)
        assert attn_mod.LAST_DISPATCH == "ring", attn_mod.LAST_DISPATCH
        dense = dot_product_attention(q, k, v, causal=True, implementation="xla", segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense), rtol=2e-5, atol=2e-5)
    finally:
        AcceleratorState._reset_state()


# ------------------------------------------------------------- flash-through ring
@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(causal):
    """The flash-through ring (Pallas per-block kernels + lse combine) must equal
    dense attention — forward."""
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv(s=64)
    dense = dot_product_attention(q, k, v, causal=causal, implementation="xla")
    ring = sequence_parallel_attention(q, k, v, mesh=mesh, causal=causal, use_flash=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_ring_flash_gqa_matches_dense():
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv(s=64, h=4, hkv=2)
    dense = dot_product_attention(q, k, v, causal=True, implementation="xla")
    ring = sequence_parallel_attention(q, k, v, mesh=mesh, causal=True, use_flash=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_grads_match_dense(causal):
    """The custom-VJP ring backward (per-block flash bwd against the global lse,
    dk/dv rotating home) must equal dense-attention gradients."""
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv(s=32, h=2, d=16)

    def loss_ring(q, k, v):
        return jnp.sum(
            sequence_parallel_attention(q, k, v, mesh=mesh, causal=causal, use_flash=True) ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=causal, implementation="xla") ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_ring_flash_at_128_aligned_locals_matches_dense():
    """Forced flash-through at real (128-aligned) local lengths matches dense.
    (Auto-dispatch additionally requires a TPU backend — on CPU the interpret-mode
    kernel would be slower than the einsum ring, so auto stays einsum here.)"""
    mesh = build_mesh(ParallelismConfig(data=1, seq=8))
    q, k, v = _qkv(b=1, s=1024, h=1, d=8, seed=9)
    dense = dot_product_attention(q, k, v, causal=True, implementation="xla")
    ring = sequence_parallel_attention(q, k, v, mesh=mesh, causal=True, use_flash=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_use_flash_with_allgather_mode_rejected():
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv(s=32)
    with pytest.raises(ValueError, match="mode='ring'"):
        sequence_parallel_attention(q, k, v, mesh=mesh, mode="allgather", use_flash=True)


@pytest.mark.slow
def test_long_context_8k_ring_correctness():
    """Long-context correctness at 8k tokens over an 8-way virtual seq axis: the
    einsum ring (segment-masked) and the dense reference agree. Small head dims
    keep the dense reference feasible on the CPU host."""
    mesh = build_mesh(ParallelismConfig(data=1, seq=8))
    rng = np.random.default_rng(0)
    s = 8192
    q = jnp.asarray(rng.normal(size=(1, s, 1, 8)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(1, s, 1, 8)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(1, s, 1, 8)).astype(np.float32))
    seg = jnp.asarray(np.sort(rng.integers(0, 4, size=(1, s)), axis=1).astype(np.int32))
    dense = dot_product_attention(q, k, v, causal=True, implementation="xla", segment_ids=seg)
    ring = sequence_parallel_attention(q, k, v, mesh=mesh, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=3e-5, atol=3e-5)


def test_use_flash_with_segments_rejected():
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv(s=32)
    seg = jnp.asarray(np.zeros((2, 32), np.int32))
    with pytest.raises(ValueError, match="use_flash"):
        sequence_parallel_attention(q, k, v, mesh=mesh, segment_ids=seg, use_flash=True)


def test_dense_mask_under_sp_mesh_warns_loudly(caplog):
    """An arbitrary dense mask cannot ride the ring; under an active seq mesh
    the silent replicated-XLA fallback (round-4 verdict weak #4) must WARN so
    the O(S^2) surprise is visible — and stay silent when no SP mesh exists."""
    state = AcceleratorState(
        parallelism_config=ParallelismConfig(data=2, seq=4),
        sequence_parallel_plugin=SequenceParallelPlugin(seq_degree=4),
    )
    state.mesh
    q, k, v = _qkv()
    from accelerate_tpu.ops import attention as attention_mod

    attention_mod._SP_BYPASS_WARNED.clear()  # once-per-process guard; reset for the test
    mask = np.ones((q.shape[0], 1, q.shape[1], k.shape[1]), bool)
    with caplog.at_level("WARNING", logger="accelerate_tpu.ops.attention"):
        dot_product_attention(q, k, v, mask=jnp.asarray(mask))
        dot_product_attention(q, k, v, mask=jnp.asarray(mask))  # second call: deduped
    warned = [r for r in caplog.records if "REPLICATED" in r.getMessage()]
    assert len(warned) == 1, f"expected exactly one deduped warning, got {len(warned)}"
    from accelerate_tpu.ops import attention

    assert attention.LAST_DISPATCH == "xla"  # the fallback really ran replicated
    # causal (no dense mask) still rides the ring, no warning
    caplog.clear()
    with caplog.at_level("WARNING", logger="accelerate_tpu.ops.attention"):
        dot_product_attention(q, k, v, causal=True)
    assert not any("REPLICATED" in r.getMessage() for r in caplog.records)
    assert attention.LAST_DISPATCH in ("ring", "allgather")

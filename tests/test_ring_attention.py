"""Numerics tests for sequence-parallel ring attention: the sharded ring must match
dense single-device attention to float tolerance (causal + bidirectional + GQA), and a
sequence-parallel training step must run through the Accelerator."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.ops.attention import dot_product_attention
from accelerate_tpu.parallel.mesh import build_mesh
from accelerate_tpu.parallel.ring_attention import sequence_parallel_attention
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils import ParallelismConfig, SequenceParallelPlugin


def _qkv(b=2, s=32, h=4, hkv=None, d=16, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, hkv or h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, hkv or h, d)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("mode", ["ring", "allgather"])
def test_ring_matches_dense(causal, mode):
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv()
    dense = dot_product_attention(q, k, v, causal=causal, implementation="xla")
    ring = sequence_parallel_attention(q, k, v, mesh=mesh, causal=causal, mode=mode)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_ring_under_jit_and_grad():
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv(s=16)

    def loss_ring(q, k, v):
        return jnp.sum(sequence_parallel_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True, implementation="xla") ** 2)

    g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense), rtol=1e-4, atol=1e-4)


def test_ring_with_tp_heads():
    """2D attention parallelism: heads over "model", sequence over "seq"."""
    mesh = build_mesh(ParallelismConfig(data=1, model=2, seq=4))
    q, k, v = _qkv(h=4)
    dense = dot_product_attention(q, k, v, causal=True, implementation="xla")
    ring = sequence_parallel_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_auto_dispatch_via_accelerator_state():
    """Models get ring attention automatically when the (built) mesh has a seq axis."""
    state = AcceleratorState(
        parallelism_config=ParallelismConfig(data=2, seq=4),
        sequence_parallel_plugin=SequenceParallelPlugin(seq_degree=4),
    )
    state.mesh  # dispatch requires the mesh to exist; forwards never build it lazily
    q, k, v = _qkv()
    out_auto = dot_product_attention(q, k, v, causal=True)  # should route to ring
    out_dense = dot_product_attention(q, k, v, causal=True, implementation="xla")
    np.testing.assert_allclose(np.asarray(out_auto), np.asarray(out_dense), rtol=2e-5, atol=2e-5)
    # and the routed path really is the ring: the sharded output spec names "seq"
    from accelerate_tpu.parallel.ring_attention import sequence_parallel_attention

    out = sequence_parallel_attention(q, k, v, mesh=state.mesh, causal=True)
    assert "seq" in str(out.sharding.spec)


def test_no_dispatch_without_built_mesh():
    """A forward pass must not build the mesh or mutate global state."""
    assert AcceleratorState._shared_state == {}
    q, k, v = _qkv()
    dot_product_attention(q, k, v, causal=True)
    assert AcceleratorState._shared_state == {}, "attention op must not initialize AcceleratorState"


def test_ring_gqa():
    """GQA: ring rotates hkv-sized blocks; numerics must still match dense."""
    mesh = build_mesh(ParallelismConfig(data=2, seq=4))
    q, k, v = _qkv(h=8, hkv=2)
    dense = dot_product_attention(q, k, v, causal=True, implementation="xla")
    ring = sequence_parallel_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_sequence_parallel_training_step():
    """End-to-end: a Llama step with the seq axis active trains through the Accelerator."""
    import optax

    from accelerate_tpu import Accelerator, SimpleDataLoader
    from accelerate_tpu.data_loader import BatchSampler
    from accelerate_tpu.models.llama import create_llama_model, llama_tiny

    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(data=2, seq=4),
        sequence_parallel_plugin=SequenceParallelPlugin(seq_degree=4),
    )
    assert accelerator.mesh.shape["seq"] == 4
    model = create_llama_model(llama_tiny(), seq_len=32)
    rng = np.random.default_rng(0)
    data = [{"input_ids": rng.integers(1, 500, size=(32,)).astype(np.int32)} for _ in range(8)]
    dl = SimpleDataLoader(data, BatchSampler(range(8), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adam(1e-3), dl)
    for batch in pdl:
        with accelerator.accumulate(pmodel):
            loss = accelerator.backward(pmodel.loss, batch)
            popt.step()
            popt.zero_grad()
    assert np.isfinite(float(loss))

"""Test harness: force an 8-device host-CPU platform (the debug_launcher equivalent —
SURVEY §4 implication (b)) and reset the Borg singletons around every test (parity:
reference test_utils/testing.py:427-438 AccelerateTestCase)."""

import os

# Must run before jax initializes its backends.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("ACCELERATE_TPU_TESTING", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: repeated suite runs skip recompiles (VERDICT r1
# weak #8: 13m38s wall was mostly compile time).
_cache_dir = os.environ.setdefault(
    "ACCELERATE_TPU_TEST_JIT_CACHE", os.path.expanduser("~/.cache/accelerate_tpu_test_jit")
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402


# Marker REGISTRATION lives in pytest.ini (the single registry, honored even for
# files collected without this conftest); this hook only wires the implications.
def pytest_collection_modifyitems(config, items):
    # slow_launch / serving_soak imply slow: `-m "not slow"` is THE fast-tier switch.
    for item in items:
        if (
            item.get_closest_marker("slow_launch") or item.get_closest_marker("serving_soak")
        ) and not item.get_closest_marker("slow"):
            item.add_marker(pytest.mark.slow)


# The analysis trace-guard fixture ships in test_utils (post-install parity);
# re-exporting it here makes `trace_guard` available to every test in tests/.
from accelerate_tpu.test_utils.analysis_fixtures import trace_guard  # noqa: E402, F401


@pytest.fixture(autouse=True)
def reset_singletons():
    yield
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

"""Pipeline-parallel correctness: the shard_map 1F1B-style scan must match the plain
single-program model loss/grads/logits to float tolerance, and a pipelined training
step must run end-to-end through Accelerator.backward + AcceleratedOptimizer on the
8-device CPU mesh (the PP equivalent of reference Megatron/PiPPy coverage)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models.llama import (
    LlamaConfig,
    LlamaLayeredApply,
    causal_lm_loss,
    create_llama_model,
)
from accelerate_tpu.parallel.mesh import build_mesh
from accelerate_tpu.parallel.pipeline import (
    PipelinedModel,
    default_causal_lm_logits_loss,
    prepare_pipeline,
)
from accelerate_tpu.utils import ParallelismConfig


def _tiny_cfg(layers=4):
    return LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=layers,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
    )


def _batch(global_b=8, s=16, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": jnp.asarray(rng.integers(1, vocab, size=(global_b, s)), jnp.int32)}


@pytest.mark.parametrize("mesh_cfg", [dict(stage=4, data=2), dict(stage=2, data=4)])
def test_pipeline_loss_matches_reference(mesh_cfg):
    mesh = build_mesh(ParallelismConfig(**mesh_cfg))
    model = create_llama_model(_tiny_cfg(), seq_len=16)
    batch = _batch()

    ref_loss = causal_lm_loss(model.params, batch, model.apply_fn)

    pp = PipelinedModel(model, LlamaLayeredApply(_tiny_cfg()), mesh, num_microbatches=2)
    pp_loss = jax.jit(pp.loss)(pp.params, batch)
    np.testing.assert_allclose(float(pp_loss), float(ref_loss), rtol=1e-5, atol=1e-5)


def test_pipeline_forward_matches_reference():
    mesh = build_mesh(ParallelismConfig(stage=4, data=2))
    cfg = _tiny_cfg()
    model = create_llama_model(cfg, seq_len=16)
    batch = _batch()

    ref_logits = model.apply_fn(model.params, batch["input_ids"])
    pp = prepare_pipeline(model, LlamaLayeredApply(cfg), mesh, num_microbatches=2)
    logits = pp(batch)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits), rtol=2e-5, atol=2e-5)


def test_pipeline_grads_match_reference():
    mesh = build_mesh(ParallelismConfig(stage=4, data=2))
    cfg = _tiny_cfg()
    model = create_llama_model(cfg, seq_len=16)
    batch = _batch()
    layered = LlamaLayeredApply(cfg)
    pp = PipelinedModel(model, layered, mesh, num_microbatches=2)

    ref_grads = jax.grad(lambda p: causal_lm_loss(p, batch, model.apply_fn))(model.params)
    pp_grads = jax.jit(jax.grad(lambda p: pp.loss(p, batch)))(pp.params)

    # Compare in the merged (original-model) layout.
    from accelerate_tpu.parallel.pipeline import unstack_layer_params

    merged = layered.join(
        pp_grads["prelude"], unstack_layer_params(pp_grads["layers"], pp.num_layers), pp_grads["tail"]
    )
    flat_ref, _ = jax.tree_util.tree_flatten_with_path(ref_grads)
    flat_pp = dict(
        (jax.tree_util.keystr(k), v) for k, v in jax.tree_util.tree_flatten_with_path(merged)[0]
    )
    for key_path, ref_leaf in flat_ref:
        key = jax.tree_util.keystr(key_path)
        np.testing.assert_allclose(
            np.asarray(flat_pp[key]), np.asarray(ref_leaf), rtol=5e-4, atol=5e-4, err_msg=key
        )


def test_pipeline_training_step_through_accelerator():
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

    cfg = _tiny_cfg()
    accelerator = Accelerator(parallelism_config=ParallelismConfig(stage=4, data=2))
    model = create_llama_model(cfg, seq_len=16)
    pp = prepare_pipeline(model, LlamaLayeredApply(cfg), accelerator.mesh, num_microbatches=2)
    pp, optimizer = accelerator.prepare(pp, optax.adam(1e-3))

    losses = []
    batch = _batch(seed=0)
    for step in range(4):
        loss = accelerator.backward(pp.loss, batch, model=pp)
        optimizer.step()
        optimizer.zero_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0], f"pipelined training did not descend: {losses}"


def test_pipeline_rejects_uneven_layers():
    mesh = build_mesh(ParallelismConfig(stage=4, data=2))
    cfg = _tiny_cfg(layers=3)
    model = create_llama_model(cfg, seq_len=16)
    with pytest.raises(ValueError, match="not divisible"):
        PipelinedModel(model, LlamaLayeredApply(cfg), mesh, num_microbatches=2)


def test_prepare_pippy_inference_pads_and_matches():
    from accelerate_tpu.inference import prepare_pippy
    from accelerate_tpu.state import AcceleratorState

    mesh = build_mesh(ParallelismConfig(stage=4, data=2))
    AcceleratorState._shared_state["_mesh"] = mesh
    cfg = _tiny_cfg()
    model = create_llama_model(cfg, seq_len=16)
    infer = prepare_pippy(model, layered=LlamaLayeredApply(cfg), mesh=mesh, num_microbatches=2)

    # 7 is not divisible by data(2)*microbatches(2): exercises the pad+truncate path.
    batch = _batch(global_b=7, seed=3)
    ref_logits = model.apply_fn(model.params, batch["input_ids"])
    logits = infer(batch)
    assert logits.shape[0] == 7
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits), rtol=2e-5, atol=2e-5)


def test_pipeline_loss_token_weighted_with_uneven_masking():
    """Label masking concentrated in some microbatches: the pipelined loss must still be
    the global token-weighted mean (not a mean of per-microbatch means)."""
    mesh = build_mesh(ParallelismConfig(stage=4, data=2))
    cfg = _tiny_cfg()
    model = create_llama_model(cfg, seq_len=16)
    rng = np.random.default_rng(7)
    ids = rng.integers(1, 256, size=(8, 16)).astype(np.int32)
    labels = ids.copy()
    labels[:3] = -1          # first samples fully masked
    labels[3:, 8:] = -1      # others half masked
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}

    ref_loss = causal_lm_loss(model.params, batch, model.apply_fn)
    pp = PipelinedModel(model, LlamaLayeredApply(cfg), mesh, num_microbatches=2)
    pp_loss = jax.jit(pp.loss)(pp.params, batch)
    np.testing.assert_allclose(float(pp_loss), float(ref_loss), rtol=1e-5, atol=1e-5)


def test_pipeline_tied_embeddings_grads_match_reference():
    """Tied lm head: the tied weight is stored once (prelude) and its gradient must be
    the SUM of the embedding-lookup and lm-head contributions, exactly as in the
    unpipelined model."""
    mesh = build_mesh(ParallelismConfig(stage=4, data=2))
    cfg = _tiny_cfg()
    cfg = LlamaConfig(**{**cfg.__dict__, "tie_word_embeddings": True})
    model = create_llama_model(cfg, seq_len=16)
    batch = _batch()
    layered = LlamaLayeredApply(cfg)
    pp = PipelinedModel(model, layered, mesh, num_microbatches=2)

    # the tied weight lives only in the prelude
    assert "embed_tokens" not in pp.params["tail"].get("params", {})

    ref_loss = causal_lm_loss(model.params, batch, model.apply_fn)
    pp_loss = jax.jit(pp.loss)(pp.params, batch)
    np.testing.assert_allclose(float(pp_loss), float(ref_loss), rtol=1e-5, atol=1e-5)

    ref_grads = jax.grad(lambda p: causal_lm_loss(p, batch, model.apply_fn))(model.params)
    pp_grads = jax.jit(jax.grad(lambda p: pp.loss(p, batch)))(pp.params)

    ref_embed = np.asarray(ref_grads["params"]["embed_tokens"]["embedding"])
    pp_embed = np.asarray(pp_grads["prelude"]["params"]["embed_tokens"]["embedding"])
    np.testing.assert_allclose(pp_embed, ref_embed, rtol=5e-4, atol=5e-4)

    # merged layout round-trips to the original structure
    merged = pp.merged_params()
    assert jax.tree_util.tree_structure(merged) == jax.tree_util.tree_structure(model.params)

"""Pipeline-parallel correctness: the shard_map 1F1B-style scan must match the plain
single-program model loss/grads/logits to float tolerance, and a pipelined training
step must run end-to-end through Accelerator.backward + AcceleratedOptimizer on the
8-device CPU mesh (the PP equivalent of reference Megatron/PiPPy coverage)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models.llama import (
    LlamaConfig,
    LlamaLayeredApply,
    causal_lm_loss,
    create_llama_model,
)
from accelerate_tpu.parallel.mesh import build_mesh
from accelerate_tpu.parallel.pipeline import (
    PipelinedModel,
    default_causal_lm_logits_loss,
    prepare_pipeline,
)
from accelerate_tpu.utils import ParallelismConfig


def _tiny_cfg(layers=4):
    return LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=layers,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
    )


def _batch(global_b=8, s=16, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": jnp.asarray(rng.integers(1, vocab, size=(global_b, s)), jnp.int32)}


@pytest.mark.parametrize("mesh_cfg", [dict(stage=4, data=2), dict(stage=2, data=4)])
def test_pipeline_loss_matches_reference(mesh_cfg):
    mesh = build_mesh(ParallelismConfig(**mesh_cfg))
    model = create_llama_model(_tiny_cfg(), seq_len=16)
    batch = _batch()

    ref_loss = causal_lm_loss(model.params, batch, model.apply_fn)

    pp = PipelinedModel(model, LlamaLayeredApply(_tiny_cfg()), mesh, num_microbatches=2)
    pp_loss = jax.jit(pp.loss)(pp.params, batch)
    np.testing.assert_allclose(float(pp_loss), float(ref_loss), rtol=1e-5, atol=1e-5)


def test_pipeline_forward_matches_reference():
    mesh = build_mesh(ParallelismConfig(stage=4, data=2))
    cfg = _tiny_cfg()
    model = create_llama_model(cfg, seq_len=16)
    batch = _batch()

    ref_logits = model.apply_fn(model.params, batch["input_ids"])
    pp = prepare_pipeline(model, LlamaLayeredApply(cfg), mesh, num_microbatches=2)
    logits = pp(batch)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits), rtol=2e-5, atol=2e-5)


def test_pipeline_grads_match_reference():
    mesh = build_mesh(ParallelismConfig(stage=4, data=2))
    cfg = _tiny_cfg()
    model = create_llama_model(cfg, seq_len=16)
    batch = _batch()
    layered = LlamaLayeredApply(cfg)
    pp = PipelinedModel(model, layered, mesh, num_microbatches=2)

    ref_grads = jax.grad(lambda p: causal_lm_loss(p, batch, model.apply_fn))(model.params)
    pp_grads = jax.jit(jax.grad(lambda p: pp.loss(p, batch)))(pp.params)

    # Compare in the merged (original-model) layout.
    from accelerate_tpu.parallel.pipeline import unstack_layer_params

    merged = layered.join(
        pp_grads["prelude"], unstack_layer_params(pp_grads["layers"], pp.num_layers), pp_grads["tail"]
    )
    flat_ref, _ = jax.tree_util.tree_flatten_with_path(ref_grads)
    flat_pp = dict(
        (jax.tree_util.keystr(k), v) for k, v in jax.tree_util.tree_flatten_with_path(merged)[0]
    )
    for key_path, ref_leaf in flat_ref:
        key = jax.tree_util.keystr(key_path)
        np.testing.assert_allclose(
            np.asarray(flat_pp[key]), np.asarray(ref_leaf), rtol=5e-4, atol=5e-4, err_msg=key
        )


def test_pipeline_training_step_through_accelerator():
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

    cfg = _tiny_cfg()
    accelerator = Accelerator(parallelism_config=ParallelismConfig(stage=4, data=2))
    model = create_llama_model(cfg, seq_len=16)
    pp = prepare_pipeline(model, LlamaLayeredApply(cfg), accelerator.mesh, num_microbatches=2)
    pp, optimizer = accelerator.prepare(pp, optax.adam(1e-3))

    losses = []
    batch = _batch(seed=0)
    for step in range(4):
        loss = accelerator.backward(pp.loss, batch, model=pp)
        optimizer.step()
        optimizer.zero_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0], f"pipelined training did not descend: {losses}"


def test_pipeline_rejects_uneven_layers():
    mesh = build_mesh(ParallelismConfig(stage=4, data=2))
    cfg = _tiny_cfg(layers=3)
    model = create_llama_model(cfg, seq_len=16)
    with pytest.raises(ValueError, match="not divisible"):
        PipelinedModel(model, LlamaLayeredApply(cfg), mesh, num_microbatches=2)


def test_prepare_pippy_inference_pads_and_matches():
    from accelerate_tpu.inference import prepare_pippy
    from accelerate_tpu.state import AcceleratorState

    mesh = build_mesh(ParallelismConfig(stage=4, data=2))
    AcceleratorState._shared_state["_mesh"] = mesh
    cfg = _tiny_cfg()
    model = create_llama_model(cfg, seq_len=16)
    infer = prepare_pippy(model, layered=LlamaLayeredApply(cfg), mesh=mesh, num_microbatches=2)

    # 7 is not divisible by data(2)*microbatches(2): exercises the pad+truncate path.
    batch = _batch(global_b=7, seed=3)
    ref_logits = model.apply_fn(model.params, batch["input_ids"])
    logits = infer(batch)
    assert logits.shape[0] == 7
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits), rtol=2e-5, atol=2e-5)


def test_pipeline_loss_token_weighted_with_uneven_masking():
    """Label masking concentrated in some microbatches: the pipelined loss must still be
    the global token-weighted mean (not a mean of per-microbatch means)."""
    mesh = build_mesh(ParallelismConfig(stage=4, data=2))
    cfg = _tiny_cfg()
    model = create_llama_model(cfg, seq_len=16)
    rng = np.random.default_rng(7)
    ids = rng.integers(1, 256, size=(8, 16)).astype(np.int32)
    labels = ids.copy()
    labels[:3] = -1          # first samples fully masked
    labels[3:, 8:] = -1      # others half masked
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}

    ref_loss = causal_lm_loss(model.params, batch, model.apply_fn)
    pp = PipelinedModel(model, LlamaLayeredApply(cfg), mesh, num_microbatches=2)
    pp_loss = jax.jit(pp.loss)(pp.params, batch)
    np.testing.assert_allclose(float(pp_loss), float(ref_loss), rtol=1e-5, atol=1e-5)


def test_pipeline_tied_embeddings_grads_match_reference():
    """Tied lm head: the tied weight is stored once (prelude) and its gradient must be
    the SUM of the embedding-lookup and lm-head contributions, exactly as in the
    unpipelined model."""
    mesh = build_mesh(ParallelismConfig(stage=4, data=2))
    cfg = _tiny_cfg()
    cfg = LlamaConfig(**{**cfg.__dict__, "tie_word_embeddings": True})
    model = create_llama_model(cfg, seq_len=16)
    batch = _batch()
    layered = LlamaLayeredApply(cfg)
    pp = PipelinedModel(model, layered, mesh, num_microbatches=2)

    # the tied weight lives only in the prelude
    assert "embed_tokens" not in pp.params["tail"].get("params", {})

    ref_loss = causal_lm_loss(model.params, batch, model.apply_fn)
    pp_loss = jax.jit(pp.loss)(pp.params, batch)
    np.testing.assert_allclose(float(pp_loss), float(ref_loss), rtol=1e-5, atol=1e-5)

    ref_grads = jax.grad(lambda p: causal_lm_loss(p, batch, model.apply_fn))(model.params)
    pp_grads = jax.jit(jax.grad(lambda p: pp.loss(p, batch)))(pp.params)

    ref_embed = np.asarray(ref_grads["params"]["embed_tokens"]["embedding"])
    pp_embed = np.asarray(pp_grads["prelude"]["params"]["embed_tokens"]["embedding"])
    np.testing.assert_allclose(pp_embed, ref_embed, rtol=5e-4, atol=5e-4)

    # merged layout round-trips to the original structure
    merged = pp.merged_params()
    assert jax.tree_util.tree_structure(merged) == jax.tree_util.tree_structure(model.params)


# ------------------------------------------------------- encoder-decoder (T5) pipeline
def _t5_batch(global_b=16, se=12, sd=6, seed=0):
    from accelerate_tpu.models.t5 import t5_tiny

    cfg = t5_tiny()
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, cfg.vocab_size, (global_b, sd)).astype(np.int32)
    labels[:, 4:] = -100  # ragged label masking must stay token-weight exact
    return cfg, {
        "input_ids": jnp.asarray(rng.integers(1, cfg.vocab_size, (global_b, se)), jnp.int32),
        "decoder_input_ids": jnp.asarray(
            rng.integers(1, cfg.vocab_size, (global_b, sd)), jnp.int32
        ),
        "labels": jnp.asarray(labels),
    }


def test_t5_pipeline_loss_and_forward_match_reference():
    """The two-phase ring (encoder pass -> promote -> decoder pass with
    cross-attention) must equal the plain seq2seq forward/loss exactly — the
    in-tree replacement for Megatron's T5 pipeline schedule (reference
    utils/megatron_lm.py:702,1004-1010)."""
    from accelerate_tpu.models.t5 import T5PipelineApply, create_t5_model, seq2seq_lm_loss, t5_tiny

    cfg, batch = _t5_batch()
    model = create_t5_model(cfg, seq_len=16)
    mesh = build_mesh(ParallelismConfig(stage=2, data=4))

    ref_loss = float(seq2seq_lm_loss(model.params, batch, model.apply_fn))
    pp = PipelinedModel(model, T5PipelineApply(cfg), mesh, num_microbatches=2)
    assert pp.is_encoder_decoder
    pp_loss = float(jax.jit(pp.loss)(pp.params, batch))
    np.testing.assert_allclose(pp_loss, ref_loss, rtol=1e-5, atol=1e-5)

    logits_ref = np.asarray(
        model.apply_fn(model.params, batch["input_ids"], batch["decoder_input_ids"])
    )
    np.testing.assert_allclose(np.asarray(pp(batch)), logits_ref, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_t5_pipeline_grads_match_reference():
    from accelerate_tpu.models.t5 import T5PipelineApply, create_t5_model, seq2seq_lm_loss, t5_tiny
    from accelerate_tpu.parallel.pipeline import unstack_layer_params

    cfg, batch = _t5_batch(seed=3)
    model = create_t5_model(cfg, seq_len=16)
    mesh = build_mesh(ParallelismConfig(stage=2, data=4))
    pp = PipelinedModel(model, T5PipelineApply(cfg), mesh, num_microbatches=2)

    g_ref = jax.grad(lambda p: seq2seq_lm_loss(p, batch, model.apply_fn))(model.params)
    g_pp = jax.grad(lambda p: pp.loss(p, batch))(pp.params)
    layered = T5PipelineApply(cfg)
    merged = layered.join(
        g_pp["prelude"],
        unstack_layer_params(g_pp["enc_layers"], cfg.num_layers),
        unstack_layer_params(g_pp["dec_layers"], cfg.num_decoder_layers),
        g_pp["tail"],
    )
    for a, b in zip(jax.tree_util.tree_leaves(g_ref), jax.tree_util.tree_leaves(merged)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=2e-3, atol=2e-4)


def test_t5_pipeline_trains_through_accelerator():
    """tiny-T5 trains over stage=2 through the standard Accelerator path (the
    round-3 verdict's 'T5 cannot pipeline' gap, closed)."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models.t5 import T5PipelineApply, create_t5_model
    from accelerate_tpu.parallel.pipeline import prepare_pipeline

    cfg, batch = _t5_batch(seed=7)
    accelerator = Accelerator(parallelism_config=ParallelismConfig(stage=2, data=4))
    model = create_t5_model(cfg, seq_len=16)
    pp = prepare_pipeline(model, T5PipelineApply(cfg), num_microbatches=2)
    pmodel, popt = accelerator.prepare(pp, optax.adam(3e-3))
    losses = []
    for _ in range(8):
        loss = accelerator.backward(pmodel.loss, batch)
        popt.step()
        popt.zero_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses
    # merged params round-trip back into the plain model layout
    merged = pmodel.merged_params()
    out = model.apply_fn(merged, batch["input_ids"], batch["decoder_input_ids"])
    assert np.isfinite(np.asarray(out)).all()


def test_mixed_structure_layered_apply_points_to_pipeline_protocol():
    from accelerate_tpu.models.t5 import T5LayeredApply, create_t5_model, t5_tiny

    cfg = t5_tiny()
    model = create_t5_model(cfg, seq_len=16)
    mesh = build_mesh(ParallelismConfig(stage=2, data=4))
    with pytest.raises(NotImplementedError, match="T5PipelineApply"):
        PipelinedModel(model, T5LayeredApply(cfg), mesh, num_microbatches=2)

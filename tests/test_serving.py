"""Continuous-batching serving engine tests (serving.ContinuousBatcher).

Pins the three load-bearing contracts:
  1. ONE decode executable across admissions with varying prompt lengths
     (admission compiles per-bucket inserts, never the chunk program);
  2. in-flight batching: a late-arriving request starts decoding before an
     earlier long request finishes;
  3. greedy outputs are token-identical to the static `Generator` path —
     serving reuses a verified sampler and a verified cache discipline.
"""

import numpy as np
import pytest

import jax

from accelerate_tpu.generation import GenerationConfig, Generator, generate
from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
from accelerate_tpu.serving import ContinuousBatcher, Request


def _model():
    cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
    )
    return create_llama_model(cfg, seq_len=32)


def _static_reference(model, prompt, max_new, **kwargs):
    """Per-request static path: the generated suffix from the fused Generator."""
    out = np.asarray(generate(model, prompt[None, :], max_new_tokens=max_new, **kwargs))
    return out[0, prompt.size:]


def test_decode_compiled_once_across_mixed_admissions():
    """Varying prompt lengths hit different insert buckets but the decode chunk
    program — the one that runs for the lifetime of the server — never retraces."""
    model = _model()
    rng = np.random.default_rng(0)
    engine = ContinuousBatcher(model, num_slots=2, max_length=64, chunk_size=4)
    lengths = [3, 5, 9, 17, 6, 30]
    requests = [
        Request(i, rng.integers(1, 128, (n,)).astype(np.int32), max_new_tokens=4)
        for i, n in enumerate(lengths)
    ]
    engine.run(requests)
    assert engine.trace_counts["decode_chunk"] == 1
    assert engine._chunk_fn._cache_size() == 1
    # buckets: 3->4, 5->8, 9->16, 17->32, 6->8, 30->32 => {4, 8, 16, 32}
    assert engine.trace_counts["insert"] == 4
    assert set(engine._insert_fns) == {4, 8, 16, 32}
    assert all(r.finished for r in engine.results.values())


def test_late_arrival_starts_before_long_request_finishes():
    model = _model()
    rng = np.random.default_rng(1)
    engine = ContinuousBatcher(model, num_slots=2, max_length=64, chunk_size=4)
    long_prompt = rng.integers(1, 128, (6,)).astype(np.int32)
    engine.submit(Request(0, long_prompt, max_new_tokens=24))
    engine.step()  # request 0 admitted and decoding
    assert not engine.results[0].finished

    # LATE arrival while 0 is mid-flight: it must be admitted into the free slot
    # and stream tokens before 0 completes.
    late_prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    engine.submit(Request(1, late_prompt, max_new_tokens=3))
    events = engine.step()
    assert any(rid == 1 for rid, _ in events), "late request produced no tokens this cycle"
    assert not engine.results[0].finished, "long request should still be in flight"

    outputs = engine.run()  # drain
    assert engine.results[0].finished and engine.results[1].finished
    np.testing.assert_array_equal(outputs[1], _static_reference(model, late_prompt, 3))
    np.testing.assert_array_equal(outputs[0], _static_reference(model, long_prompt, 24))


def test_greedy_parity_with_static_generator_mixed_workload():
    """Every request's greedy tokens are identical to the static Generator path,
    across mixed prompt lengths / budgets and slot reuse."""
    model = _model()
    rng = np.random.default_rng(2)
    lengths = [5, 9, 3, 12, 7]
    budgets = [6, 4, 8, 3, 5]
    prompts = [rng.integers(1, 128, (n,)).astype(np.int32) for n in lengths]
    engine = ContinuousBatcher(model, num_slots=2, max_length=32, chunk_size=4)
    outputs = engine.run(
        [Request(i, p, max_new_tokens=m) for i, (p, m) in enumerate(zip(prompts, budgets))]
    )
    for i, (p, m) in enumerate(zip(prompts, budgets)):
        np.testing.assert_array_equal(outputs[i], _static_reference(model, p, m))


def test_greedy_parity_gpt_neox_family():
    """The slot-cache decode path is model-layer plumbing (llama AND gpt_neox
    gained the per-row cache write): pin parity on the second family too."""
    import dataclasses

    from accelerate_tpu.models.gpt_neox import create_gpt_neox_model, gpt_neox_tiny

    cfg = dataclasses.replace(gpt_neox_tiny(), max_position_embeddings=64)
    model = create_gpt_neox_model(cfg, seq_len=32)
    rng = np.random.default_rng(8)
    prompts = [rng.integers(1, cfg.vocab_size, (n,)).astype(np.int32) for n in (4, 9)]
    engine = ContinuousBatcher(model, num_slots=2, max_length=32, chunk_size=4)
    outputs = engine.run([Request(i, p, max_new_tokens=5) for i, p in enumerate(prompts)])
    for i, p in enumerate(prompts):
        np.testing.assert_array_equal(outputs[i], _static_reference(model, p, 5))


def test_eos_stops_slot_and_matches_static_path():
    model = _model()
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, 128, (6,)).astype(np.int32)
    # pick a token the greedy continuation actually emits so EOS triggers mid-run
    free_run = _static_reference(model, prompt, 8)
    eos = int(free_run[len(free_run) // 2])
    ref = _static_reference(model, prompt, 8, eos_token_id=eos)
    engine = ContinuousBatcher(model, num_slots=2, max_length=32, chunk_size=3)
    outputs = engine.run([Request(0, prompt, max_new_tokens=8, eos_token_id=eos)])
    np.testing.assert_array_equal(outputs[0], ref)
    assert engine.results[0].finish_reason == "eos"
    assert outputs[0][-1] == eos


def test_repetition_penalty_rides_per_slot():
    model = _model()
    rng = np.random.default_rng(4)
    prompt = rng.integers(1, 128, (6,)).astype(np.int32)
    engine = ContinuousBatcher(
        model, num_slots=2, max_length=32, chunk_size=4, use_repetition_penalty=True
    )
    outputs = engine.run(
        [
            Request(0, prompt, max_new_tokens=8, repetition_penalty=1.7),
            Request(1, prompt, max_new_tokens=8, repetition_penalty=1.0),
        ]
    )
    np.testing.assert_array_equal(
        outputs[0], _static_reference(model, prompt, 8, repetition_penalty=1.7)
    )
    np.testing.assert_array_equal(outputs[1], _static_reference(model, prompt, 8))
    # one decode executable even with the presence carry
    assert engine.trace_counts["decode_chunk"] == 1


def test_fewer_decode_iterations_than_static_batching():
    """The headline win: a mixed workload completes in fewer total decode loop
    iterations than static batching. Greedy with no EOS is fully deterministic:
    the static fused loop runs exactly (max_new_of_batch - 1) body iterations per
    batch (the first token comes from prefill), while continuous batching serves
    the short requests inside the long request's shadow."""
    model = _model()
    rng = np.random.default_rng(5)
    budgets = [32, 2, 2, 2, 2, 2, 2, 2]
    prompts = [rng.integers(1, 128, (4,)).astype(np.int32) for _ in budgets]
    num_slots = 2

    # static: batches of `num_slots` in arrival order, each runs to the max budget
    static_iterations = sum(
        max(budgets[i : i + num_slots]) - 1 for i in range(0, len(budgets), num_slots)
    )

    engine = ContinuousBatcher(model, num_slots=num_slots, max_length=64, chunk_size=4)
    outputs = engine.run(
        [Request(i, p, max_new_tokens=m) for i, (p, m) in enumerate(zip(prompts, budgets))]
    )
    assert all(r.finished for r in engine.results.values())
    assert engine.stats["decode_steps"] < static_iterations, (
        engine.stats,
        static_iterations,
    )
    # and the work was not dropped: every request got its full budget
    for i, m in enumerate(budgets):
        assert outputs[i].size == m


def test_streaming_drain_preserves_per_request_order():
    """The packed (slot_id, token) buffer drains time-major: concatenating a
    request's stream events reproduces its final token sequence exactly."""
    model = _model()
    rng = np.random.default_rng(6)
    prompts = [rng.integers(1, 128, (n,)).astype(np.int32) for n in (5, 8, 3)]
    engine = ContinuousBatcher(model, num_slots=2, max_length=32, chunk_size=3)
    for i, p in enumerate(prompts):
        engine.submit(Request(i, p, max_new_tokens=6))
    streamed = {i: [] for i in range(len(prompts))}
    while engine.pending:
        for rid, toks in engine.step():
            streamed[rid].extend(toks)
    for i in range(len(prompts)):
        np.testing.assert_array_equal(
            np.asarray(streamed[i], np.int32), np.asarray(engine.results[i].tokens, np.int32)
        )
        assert engine.results[i].first_token_time is not None
        assert engine.results[i].finish_time >= engine.results[i].first_token_time


def test_admission_rejects_oversized_and_duplicate_requests():
    model = _model()
    engine = ContinuousBatcher(model, num_slots=2, max_length=16, chunk_size=2)
    prompt = np.arange(1, 13, dtype=np.int32)  # 12 tokens
    with pytest.raises(ValueError, match="slot capacity"):
        engine.submit(Request(0, prompt, max_new_tokens=8))
    engine.submit(Request(1, prompt[:4], max_new_tokens=4))
    with pytest.raises(ValueError, match="duplicate"):
        engine.submit(Request(1, prompt[:4], max_new_tokens=4))
    with pytest.raises(ValueError, match="in flight"):
        engine.release(1)  # not finished yet
    engine.run()
    # release frees host memory AND the id for reuse (long-running servers)
    first = engine.release(1)
    assert first.finished and 1 not in engine.results
    engine.submit(Request(1, prompt[:4], max_new_tokens=4))
    outputs = engine.run()
    np.testing.assert_array_equal(outputs[1], np.asarray(first.tokens, np.int32))


def test_tree_scatter_gather_roundtrip():
    """tree_gather_rows inverts tree_scatter_rows on the live CONTIGUOUS engine
    cache, and non-slot leaves (scalars like cache_index) pass through
    untouched — the debugging contract both helpers document. (The paged
    layout's pool gather/scatter twins are pinned in tests/test_paging.py.)"""
    import jax.numpy as jnp

    from accelerate_tpu.utils.operations import tree_gather_rows, tree_scatter_rows

    model = _model()
    engine = ContinuousBatcher(model, num_slots=3, max_length=32, chunk_size=2, paged=False)
    engine.run([Request(0, np.arange(1, 6, dtype=np.int32), max_new_tokens=3)])
    row = tree_gather_rows(engine._cache, 1)
    for leaf in jax.tree_util.tree_leaves(row):
        if leaf.ndim >= 4:  # cached_key/value [1, L, h, d]
            assert leaf.shape[0] == 1
    scattered = tree_scatter_rows(engine._cache, row, jnp.int32(1))
    for a, b in zip(
        jax.tree_util.tree_leaves(scattered), jax.tree_util.tree_leaves(engine._cache)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------- fault isolation
# The serving-hardening contract: the engine degrades PER-REQUEST (deadline,
# cancel, backpressure, admission/step errors), never per-process.


@pytest.mark.faults
def test_queued_deadline_expires_without_occupying_a_slot():
    model = _model()
    rng = np.random.default_rng(10)
    engine = ContinuousBatcher(model, num_slots=1, max_length=32, chunk_size=2)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    engine.submit(Request(0, prompt, max_new_tokens=4, deadline_s=0.0))  # already expired
    engine.submit(Request(1, prompt, max_new_tokens=4))
    outputs = engine.run()
    assert engine.results[0].finish_reason == "timeout"
    assert engine.results[0].tokens == []  # never admitted
    assert engine.results[1].finish_reason == "length"
    np.testing.assert_array_equal(outputs[1], _static_reference(model, prompt, 4))


@pytest.mark.faults
def test_inflight_deadline_keeps_partial_tokens_and_frees_slot():
    model = _model()
    rng = np.random.default_rng(11)
    engine = ContinuousBatcher(model, num_slots=1, max_length=64, chunk_size=2)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    engine.submit(Request(0, prompt, max_new_tokens=24, deadline_s=1000.0))
    engine.step()  # admitted + some decode progress
    partial = len(engine.results[0].tokens)
    assert partial >= 1 and not engine.results[0].finished
    engine._deadlines[0] = 0.0  # force the wall clock past the deadline
    engine.step()
    result = engine.results[0]
    assert result.finish_reason == "timeout"
    assert len(result.tokens) >= partial  # partial output kept, never discarded
    assert engine.free_slots == 1  # the slot is serviceable again
    # and the freed slot serves the next request with exact greedy parity
    engine.submit(Request(1, prompt, max_new_tokens=4))
    outputs = engine.run()
    np.testing.assert_array_equal(outputs[1], _static_reference(model, prompt, 4))


@pytest.mark.faults
def test_cancel_queued_and_inflight_requests():
    model = _model()
    rng = np.random.default_rng(12)
    engine = ContinuousBatcher(model, num_slots=1, max_length=64, chunk_size=2)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    engine.submit(Request(0, prompt, max_new_tokens=24))
    engine.submit(Request(1, prompt, max_new_tokens=4))
    engine.step()  # 0 in flight, 1 queued
    assert engine.cancel(1) is True  # cancel while queued: no tokens at all
    assert engine.results[1].finish_reason == "cancelled"
    assert engine.results[1].tokens == []
    assert engine.cancel(0) is True  # cancel mid-flight: partial tokens kept
    assert engine.results[0].finish_reason == "cancelled"
    assert engine.results[0].tokens and engine.free_slots == 1
    assert engine.cancel(0) is False  # already finished
    with pytest.raises(KeyError):
        engine.cancel(99)
    engine.submit(Request(2, prompt, max_new_tokens=4))
    outputs = engine.run()
    np.testing.assert_array_equal(outputs[2], _static_reference(model, prompt, 4))


@pytest.mark.faults
def test_bounded_queue_raises_queue_full():
    from accelerate_tpu.serving import QueueFull

    model = _model()
    rng = np.random.default_rng(13)
    engine = ContinuousBatcher(model, num_slots=1, max_length=32, chunk_size=2, max_queue=2)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    engine.submit(Request(0, prompt, max_new_tokens=4))
    engine.submit(Request(1, prompt, max_new_tokens=4))
    with pytest.raises(QueueFull):
        engine.submit(Request(2, prompt, max_new_tokens=4))
    assert 2 not in engine.results, "rejected request must leave no result entry"
    engine.step()  # admission drains the queue; capacity opens up
    engine.submit(Request(2, prompt, max_new_tokens=4))
    engine.run()
    assert engine.stats["queue_peak"] == 2
    assert all(engine.results[i].finish_reason == "length" for i in range(3))


@pytest.mark.faults
def test_insert_error_isolated_to_one_request():
    """A device error while admitting ONE request (here: its bucket's insert
    executable dies) errors only that request; every other request still
    matches the static path token-for-token."""
    model = _model()
    rng = np.random.default_rng(14)
    engine = ContinuousBatcher(model, num_slots=2, max_length=64, chunk_size=2)
    good_a = rng.integers(1, 128, (4,)).astype(np.int32)   # bucket 4
    poison = rng.integers(1, 128, (7,)).astype(np.int32)   # bucket 8
    good_b = rng.integers(1, 128, (3,)).astype(np.int32)   # bucket 4

    real_insert_fn = engine._insert_fn

    def poisoned_insert_fn(bucket):
        if bucket == 8:
            raise RuntimeError("injected transient device error")
        return real_insert_fn(bucket)

    engine._insert_fn = poisoned_insert_fn
    outputs = engine.run(
        [
            Request(0, good_a, max_new_tokens=4),
            Request(1, poison, max_new_tokens=4),
            Request(2, good_b, max_new_tokens=4),
        ]
    )
    assert engine.results[1].finish_reason == "error"
    assert "injected transient device error" in engine.results[1].error
    assert engine.results[1].tokens == []
    np.testing.assert_array_equal(outputs[0], _static_reference(model, good_a, 4))
    np.testing.assert_array_equal(outputs[2], _static_reference(model, good_b, 4))
    assert engine.stats["finish_reasons"]["error"] == 1
    assert engine.stats["finish_reasons"]["length"] == 2


@pytest.mark.faults
def test_chunk_dispatch_failure_errors_inflight_but_engine_survives():
    """The blast-radius exception: the ONE shared decode executable dying takes
    every in-flight request with it — but the engine stays up and the next
    admission serves correctly from freshly-rebuilt cache rows."""
    model = _model()
    rng = np.random.default_rng(15)
    engine = ContinuousBatcher(model, num_slots=2, max_length=64, chunk_size=2)
    prompts = [rng.integers(1, 128, (4,)).astype(np.int32) for _ in range(2)]
    for i, p in enumerate(prompts):
        engine.submit(Request(i, p, max_new_tokens=8))
    engine.step()  # both admitted and decoding

    real_chunk_fn = engine._chunk_fn
    engine._chunk_fn = lambda *a, **k: (_ for _ in ()).throw(RuntimeError("XLA dispatch died"))
    engine.step()
    engine._chunk_fn = real_chunk_fn

    for i in range(2):
        assert engine.results[i].finish_reason == "error"
        assert "XLA dispatch died" in engine.results[i].error
        assert engine.results[i].tokens, "partial tokens must be kept"
    assert engine.free_slots == 2 and not engine.pending

    engine.submit(Request(2, prompts[0], max_new_tokens=4))
    outputs = engine.run()
    np.testing.assert_array_equal(outputs[2], _static_reference(model, prompts[0], 4))


@pytest.mark.faults
def test_close_cancels_everything_and_refuses_new_work():
    from accelerate_tpu.serving import EngineClosed

    model = _model()
    rng = np.random.default_rng(16)
    engine = ContinuousBatcher(model, num_slots=1, max_length=64, chunk_size=2)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    engine.submit(Request(0, prompt, max_new_tokens=24))
    engine.submit(Request(1, prompt, max_new_tokens=4))
    engine.step()  # 0 in flight, 1 still queued
    results = engine.close()
    assert results[0].finish_reason == "cancelled" and results[0].tokens
    assert results[1].finish_reason == "cancelled" and not results[1].tokens
    assert engine.closed and not engine.pending
    with pytest.raises(EngineClosed):
        engine.submit(Request(2, prompt, max_new_tokens=4))
    assert engine.step() == []  # post-close step is a no-op
    assert engine.close() is results or engine.close() == results  # idempotent


@pytest.mark.faults
def test_drain_finishes_everything_then_reopens():
    model = _model()
    rng = np.random.default_rng(17)
    engine = ContinuousBatcher(model, num_slots=2, max_length=32, chunk_size=2)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    engine.submit(Request(0, prompt, max_new_tokens=4))
    results = engine.drain()
    assert results[0].finished and not engine.pending
    # drain is a flush, not a shutdown: the engine takes new work afterwards
    engine.submit(Request(1, prompt, max_new_tokens=4))
    outputs = engine.run()
    np.testing.assert_array_equal(outputs[1], _static_reference(model, prompt, 4))


@pytest.mark.faults
def test_mixed_adversarial_workload_engine_stays_up():
    """The acceptance-criterion mix: well-formed, oversized, deadline-expiring
    and cancelled requests together. Every well-formed request finishes with
    token-identical greedy output, the stats ledger accounts for every request,
    and the engine ends the run alive and empty."""
    model = _model()
    rng = np.random.default_rng(18)
    engine = ContinuousBatcher(model, num_slots=2, max_length=32, chunk_size=2)
    well_formed = {i: rng.integers(1, 128, (3 + i,)).astype(np.int32) for i in range(3)}
    for i, p in well_formed.items():
        engine.submit(Request(i, p, max_new_tokens=4))
    with pytest.raises(ValueError, match="slot capacity"):  # oversized: rejected synchronously
        engine.submit(Request(10, rng.integers(1, 128, (30,)).astype(np.int32), max_new_tokens=8))
    engine.submit(Request(11, well_formed[0], max_new_tokens=8, deadline_s=0.0))  # expires
    engine.submit(Request(12, well_formed[1], max_new_tokens=8))
    engine.cancel(12)  # cancelled while queued
    outputs = engine.run()
    for i, p in well_formed.items():
        np.testing.assert_array_equal(outputs[i], _static_reference(model, p, 4))
    assert engine.results[11].finish_reason == "timeout"
    assert engine.results[12].finish_reason == "cancelled"
    reasons = engine.stats["finish_reasons"]
    assert reasons["length"] == 3 and reasons["timeout"] == 1 and reasons["cancelled"] == 1
    assert sum(reasons.values()) == len(engine.results)
    assert engine.free_slots == engine.num_slots and not engine.pending and not engine.closed


@pytest.mark.serving_soak
def test_serving_soak_large_mixed_workload():
    """Soak: dozens of mixed requests through few slots; everything matches the
    static path and the decode program still compiled exactly once."""
    model = _model()
    rng = np.random.default_rng(7)
    engine = ContinuousBatcher(model, num_slots=4, max_length=64, chunk_size=8)
    requests = []
    for i in range(24):
        n = int(rng.integers(2, 24))
        m = int(rng.integers(2, 16))
        requests.append(
            Request(i, rng.integers(1, 128, (n,)).astype(np.int32), max_new_tokens=m)
        )
    outputs = engine.run(requests)
    assert engine.trace_counts["decode_chunk"] == 1
    for req in requests:
        np.testing.assert_array_equal(
            outputs[req.request_id],
            _static_reference(model, np.asarray(req.input_ids), req.max_new_tokens),
        )

"""LocalSGD tests (reference local_sgd.py:19-102 contract, TPU-native mechanism).

Key invariant exploited for exactness: with `local_sgd_steps=1`, each replica takes one
step on its local gradient and the params are immediately averaged —
mean_i(p - lr*g_i) = p - lr*mean_i(g_i) — which equals plain synced-DP SGD exactly.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu import Accelerator, LocalSGD, SimpleDataLoader
from accelerate_tpu.data_loader import BatchSampler
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

from test_training import make_regression_data, make_regression_model


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _run(local_sgd_steps=None, n=64, batch=16, lr=0.05):
    _reset()
    accelerator = Accelerator()
    model = make_regression_model(seed=0)
    dl = SimpleDataLoader(make_regression_data(n, seed=7), BatchSampler(range(n), batch))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(lr), dl)
    losses = []
    if local_sgd_steps is None:
        for batch_ in pdl:
            loss = accelerator.backward(pmodel.loss, batch_)
            popt.step()
            popt.zero_grad()
            losses.append(float(loss))
        return losses, pmodel.params
    with LocalSGD(accelerator=accelerator, model=pmodel, local_sgd_steps=local_sgd_steps) as local_sgd:
        for batch_ in pdl:
            loss = accelerator.backward(pmodel.loss, batch_)
            popt.step()
            popt.zero_grad()
            local_sgd.step()
            losses.append(float(loss))
    return losses, pmodel.params


def test_local_sgd_k1_matches_synced_dp():
    """K=1 LocalSGD (avg after every local step) must equal plain DP training exactly."""
    losses_dp, params_dp = _run(local_sgd_steps=None)
    losses_k1, params_k1 = _run(local_sgd_steps=1)
    np.testing.assert_allclose(np.array(losses_k1), np.array(losses_dp), rtol=2e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(params_k1), jax.tree_util.tree_leaves(params_dp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6)


def test_local_sgd_exit_restores_shapes_and_loss():
    """On exit the replica axis is gone and the model trains normally again."""
    _reset()
    accelerator = Accelerator()
    model = make_regression_model(seed=0)
    dl = SimpleDataLoader(make_regression_data(32, seed=2), BatchSampler(range(32), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
    orig_shapes = jax.tree_util.tree_map(lambda x: x.shape, pmodel.params)
    with LocalSGD(accelerator=accelerator, model=pmodel, local_sgd_steps=2) as local_sgd:
        for batch_ in pdl:
            accelerator.backward(pmodel.loss, batch_)
            popt.step()
            popt.zero_grad()
            local_sgd.step()
        if local_sgd.enabled:
            # mid-context: params carry the leading replica axis
            lead = jax.tree_util.tree_leaves(pmodel.params)[0]
            assert lead.shape[0] == local_sgd.dp
    assert jax.tree_util.tree_map(lambda x: x.shape, pmodel.params) == orig_shapes
    # trains fine post-exit
    for batch_ in pdl:
        loss = accelerator.backward(pmodel.loss, batch_)
        popt.step()
        popt.zero_grad()
    assert np.isfinite(float(loss))


def test_local_sgd_replicas_diverge_then_converge():
    """Between syncs replica rows differ; at the K-step boundary they are equal."""
    _reset()
    accelerator = Accelerator()
    model = make_regression_model(seed=0)
    n, batch = 64, 16
    dl = SimpleDataLoader(make_regression_data(n, seed=9), BatchSampler(range(n), batch))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
    with LocalSGD(accelerator=accelerator, model=pmodel, local_sgd_steps=2) as local_sgd:
        if not local_sgd.enabled:
            pytest.skip("needs >1 data-parallel device")
        it = iter(pdl)
        accelerator.backward(pmodel.loss, next(it))
        popt.step()
        popt.zero_grad()
        local_sgd.step()  # step 1: no sync yet
        kernel = np.asarray(jax.tree_util.tree_leaves(pmodel.params)[0])
        assert not np.allclose(kernel[0], kernel[1])
        accelerator.backward(pmodel.loss, next(it))
        popt.step()
        popt.zero_grad()
        local_sgd.step()  # step 2: sync boundary
        kernel = np.asarray(jax.tree_util.tree_leaves(pmodel.params)[0])
        for r in range(1, kernel.shape[0]):
            np.testing.assert_allclose(kernel[0], kernel[r], rtol=1e-6, atol=1e-7)


def test_local_sgd_rejects_model_sharding():
    from accelerate_tpu.utils import ParallelismConfig

    _reset()
    accelerator = Accelerator(parallelism_config=ParallelismConfig(data=1, fsdp=8))
    model = make_regression_model(seed=0)
    dl = SimpleDataLoader(make_regression_data(32), BatchSampler(range(32), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
    with pytest.raises(NotImplementedError):
        LocalSGD(accelerator=accelerator, model=pmodel, local_sgd_steps=2)

"""Chaos-subsystem tests: the acceptance sweeps of the fault-injection tentpole.

Pins, on CPU inside tier-1 time:

  1. plan semantics — JSON round trip, the ``ACCELERATE_TPU_FAULT_PLAN`` env
     protocol, trigger evaluation (step / call-count / path / times);
  2. the SIGKILL sweep — a kill at EVERY step boundary of an 8-step supervised
     run resumes exactly from the last committed checkpoint;
  3. the torn-write sweep — post-commit corruption at a range of byte offsets
     of a checkpoint MANIFEST (and the npz payload) never gets a torn
     checkpoint resolved by `resolve("latest")`;
  4. commit-window faults — SIGTERM landing inside the staged-dir commit,
     crashes inside the rename window, transient EIO during publish (the
     retry-idempotency bug this PR fixed);
  5. serving chaos — an injected dispatch stall + queue-full burst drains with
     every request carrying a terminal finish_reason, and the engine keeps
     serving after a dispatch failure;
  6. the CLI contract — `accelerate-tpu chaos run` exits 0 on a clean plan and
     non-zero on the seeded-regression fixture (the harness can tell a broken
     stack from a healthy one);
  7. telemetry reconciliation — `chaos_injected_total{kind=...}` matches the
     injection journal and injected downtime lands in the goodput ledger.
"""

import json
import os
import sys

import numpy as np
import pytest

from accelerate_tpu.chaos import (
    FAULT_PLAN_ENV,
    ChaosRunner,
    ChaosSession,
    FakeClock,
    FaultEvent,
    FaultPlan,
    InvariantReport,
    builtin_plans,
)

pytestmark = pytest.mark.chaos


# ------------------------------------------------------------------ plan + triggers
def test_plan_json_round_trip():
    plan = FaultPlan(
        name="rt", seed=7,
        events=[
            FaultEvent(kind="proc.sigkill", at_step=3),
            FaultEvent(kind="fs.torn_write", path_pattern="MANIFEST.json", at_call=2,
                       args={"offset": 17}, times=2),
        ],
        notes="round trip",
    )
    restored = FaultPlan.from_json(plan.to_json())
    assert restored == plan
    assert restored.events[1].args == {"offset": 17}


def test_plan_rejects_unknown_kind_and_fields():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultEvent(kind="fs.does_not_exist")
    with pytest.raises(ValueError, match="unknown FaultEvent field"):
        FaultEvent.from_dict({"kind": "proc.sigkill", "at_stepp": 3})


def test_plan_env_protocol_inline_and_file(tmp_path):
    plan = FaultPlan(name="envp", events=[FaultEvent(kind="proc.sigterm", at_step=1)])
    # inline JSON
    restored = FaultPlan.from_env({FAULT_PLAN_ENV: plan.to_json(indent=None)})
    assert restored == plan
    # file path
    path = plan.save(str(tmp_path / "plan.json"))
    assert FaultPlan.from_env({FAULT_PLAN_ENV: path}) == plan
    # unset -> no chaos armed
    assert FaultPlan.from_env({}) is None


def test_trigger_semantics_call_step_path_times():
    plan = FaultPlan(events=[
        FaultEvent(kind="fs.io_error", path_pattern="model.npz*", at_call=2),
        FaultEvent(kind="proc.sigkill", at_step=5),
        FaultEvent(kind="fs.slow_fsync", path_pattern="*.bin", times=2),
    ])
    session = ChaosSession(plan, clock=FakeClock())
    # path-triggered events never fire at step sites and vice versa
    assert session.fire("fs.io_error", step=1) == []
    assert session.fire("proc.sigkill", path="/x/model.npz") == []
    # at_call counts MATCHING calls only
    assert session.fire("fs.io_error", path="/ck/model.npz") == []       # matching call 1: no fire
    assert session.fire("fs.io_error", path="/ck/optimizer.npz") == []   # non-matching: not counted
    assert len(session.fire("fs.io_error", path="/ck/model.npz")) == 1   # matching call 2: fires
    assert session.counts().get("fs.io_error", 0) == 1
    # step trigger
    assert session.fire("proc.sigkill", step=4) == []
    assert len(session.fire("proc.sigkill", step=5)) == 1
    assert session.fire("proc.sigkill", step=5) == []  # times=1 exhausted
    # times=2 fires twice, then disarms
    assert len(session.fire("fs.slow_fsync", path="a.bin")) == 1
    assert len(session.fire("fs.slow_fsync", path="b.bin")) == 1
    assert session.fire("fs.slow_fsync", path="c.bin") == []
    # every firing counted in the registry
    assert session.registry.value("chaos_injected_total", {"kind": "fs.slow_fsync"}) == 2


def test_multi_seam_kinds_stay_disjoint():
    """`proc.sigterm` has two seams (step boundary, artifact write). An event
    without a `path_pattern` belongs to the step seam only — the write seam
    (which passes require_pattern) must neither fire it nor advance its call
    counter, so `at_call` counts one seam's calls, never an interleaving."""
    plan = FaultPlan(events=[FaultEvent(kind="proc.sigterm", at_call=2)])
    session = ChaosSession(plan, clock=FakeClock())
    # artifact-write seam: not evaluated at all for a pattern-less event
    assert session.fire("proc.sigterm", path="/ck/model.npz", require_pattern=True) == []
    assert session.fire("proc.sigterm", path="/ck/model.npz", require_pattern=True) == []
    # step seam: the 2nd STEP call fires — write-seam calls did not count
    assert session.fire("proc.sigterm", step=0) == []
    assert len(session.fire("proc.sigterm", step=1)) == 1


def test_after_s_trigger_with_fake_clock():
    clock = FakeClock()
    plan = FaultPlan(events=[FaultEvent(kind="serve.dispatch_stall", after_s=10.0)])
    session = ChaosSession(plan, clock=clock)
    assert session.fire("serve.dispatch_stall") == []
    clock.sleep(11.0)
    assert len(session.fire("serve.dispatch_stall")) == 1


# ------------------------------------------------------------------ train sweeps
def test_sigkill_at_every_boundary_of_8_step_run_resumes_exactly(tmp_path):
    """THE acceptance sweep: one run, a SIGKILL scripted at every one of the 8
    step boundaries — nine attempts, eight resumes, each landing exactly on the
    last committed checkpoint (step + parameter digest)."""
    plan = FaultPlan(
        name="kill-every-boundary",
        events=[FaultEvent(kind="proc.sigkill", at_step=k) for k in range(8)],
    )
    runner = ChaosRunner(plan)
    report = runner.run_train(str(tmp_path), steps=8, max_restarts=16)
    assert report.ok, report.render_text()
    assert len(report.injections) == 8
    by_name = {c.name: c for c in report.checks}
    assert by_name["resume_exactness"].details["resumes"] == 8
    assert by_name["restart_budget"].details["restarts"] == 8
    assert by_name["restart_budget"].details["completed"] is True


@pytest.mark.parametrize(
    "target,args",
    [
        ("MANIFEST.json", {"offset": 0}),
        ("MANIFEST.json", {"offset_frac": 0.5}),
        ("MANIFEST.json", {"offset_frac": 0.9, "flip": True}),
        ("model.npz", {"offset": 1}),
        ("model.npz", {"offset_frac": 0.5, "flip": True}),
    ],
)
def test_torn_write_sweep_never_resolves_torn_checkpoint(tmp_path, target, args):
    """Post-commit corruption at a range of byte offsets — truncation and bit
    flips, on the checkpoint MANIFEST and the model payload. Resume after the
    kill must fall back past the torn newest checkpoint, and the re-save must
    replace it with one that verifies."""
    plan = FaultPlan(
        name="torn-sweep",
        events=[
            FaultEvent(kind="fs.torn_write", path_pattern=target, at_call=2, args=args),
            FaultEvent(kind="proc.sigkill", at_step=1),
        ],
    )
    runner = ChaosRunner(plan)
    report = runner.run_train(str(tmp_path), steps=4)
    assert report.ok, report.render_text()
    by_name = {c.name: c for c in report.checks}
    assert by_name["no_torn_resolved"].details["resumes"] == 1
    # the terminal state re-verified independently: latest committed step is the last one
    assert by_name["no_torn_resolved"].details["final_verified_latest_step"] == 3


def test_sigterm_inside_staged_commit_preempts_gracefully(tmp_path):
    """SIGTERM delivered while an artifact is mid-commit inside the staging dir
    (the expected-bug window): the latch must not tear the commit — the save
    completes, the run preempts gracefully at the boundary, and the resume is
    exact."""
    plan = FaultPlan(
        name="sigterm-mid-commit",
        events=[FaultEvent(kind="proc.sigterm", path_pattern="model.npz*", at_call=3)],
    )
    runner = ChaosRunner(plan)
    report = runner.run_train(str(tmp_path), steps=4)
    assert report.ok, report.render_text()
    assert [e["kind"] for e in report.injections] == ["proc.sigterm"]


def test_crash_in_rename_window_of_staged_manifest(tmp_path):
    """A kill between the payload fsync and the rename of the staged MANIFEST:
    the checkpoint never becomes visible, the retry (next attempt) lands the
    same step cleanly."""
    plan = FaultPlan(
        name="rename-crash",
        events=[FaultEvent(kind="fs.crash_in_rename", path_pattern="MANIFEST.json", at_call=2)],
    )
    report = ChaosRunner(plan).run_train(str(tmp_path), steps=4)
    assert report.ok, report.render_text()


def test_transient_eio_on_latest_pointer_does_not_lose_commit(tmp_path):
    """Regression pin for the publish-retry idempotency fix: a transient EIO on
    the `latest` pointer write lands AFTER the directory rename; the retry used
    to re-run `os.replace` on the vanished staging dir and fail a save whose
    checkpoint was already committed."""
    plan = FaultPlan(
        name="pointer-eio",
        events=[FaultEvent(kind="fs.io_error", path_pattern="latest", at_call=2,
                           args={"errno": "EIO"})],
    )
    report = ChaosRunner(plan).run_train(str(tmp_path), steps=3)
    assert report.ok, report.render_text()
    assert report.injections and report.injections[0]["kind"] == "fs.io_error"


def test_enospc_on_staged_manifest_write_retries(tmp_path):
    plan = FaultPlan(
        name="manifest-enospc",
        events=[FaultEvent(kind="fs.io_error", path_pattern="MANIFEST.json", at_call=1,
                           args={"errno": "ENOSPC"})],
    )
    report = ChaosRunner(plan).run_train(str(tmp_path), steps=3)
    assert report.ok, report.render_text()


def test_chaos_counters_reconcile_with_goodput_ledger(tmp_path):
    """Satellite pin: a chaos run's injected-fault counters reconcile with the
    goodput-ledger entries it produces — the slow-fsync delay shows up in the
    'checkpoint' cause, resumes charge 'restart', and every injection journal
    entry has a matching `chaos_injected_total` count."""
    plan = FaultPlan(
        name="ledger",
        events=[
            FaultEvent(kind="fs.slow_fsync", path_pattern="model.npz*", at_call=1,
                       args={"delay_s": 0.05}),
            FaultEvent(kind="proc.sigkill", at_step=1),
        ],
    )
    runner = ChaosRunner(plan)
    report = runner.run_train(str(tmp_path), steps=3)
    assert report.ok, report.render_text()
    ledger_check = next(c for c in report.checks if c.name == "ledger_reconciles")
    details = ledger_check.details
    assert details["registry_matches_journal"] is True
    assert details["injected_counts"] == {"fs.slow_fsync": 1, "proc.sigkill": 1}
    assert details["goodput_ledger_s"]["checkpoint"] >= 0.045  # the injected stall, -10% tolerance
    assert details["goodput_ledger_s"].get("restart", 0.0) > 0.0  # the resume charged
    # the counters are real registry instruments, visible in the snapshot
    counter_rows = [m for m in report.metrics if m["name"] == "chaos_injected_total"]
    assert {row["labels"]["kind"]: row["value"] for row in counter_rows} == {
        "fs.slow_fsync": 1.0, "proc.sigkill": 1.0,
    }


def test_seeded_regression_fixture_goes_red(tmp_path):
    """The harness must detect a broken stack: with digest verification
    neutered and a torn newest manifest, resolve() hands resume a torn
    checkpoint — the independent invariant checker flags it and the report
    comes back violated."""
    report = ChaosRunner(builtin_plans()["seeded-regression"]).run_train(str(tmp_path), steps=4)
    assert not report.ok
    failed = {c.name for c in report.violated}
    assert "no_torn_resolved" in failed


# ------------------------------------------------------------------ supervised subprocess
def test_supervised_run_with_real_signals_resumes_via_env_protocol(tmp_path):
    """End-to-end: the real `Supervisor` over the real subprocess workload, the
    plan propagated via ACCELERATE_TPU_FAULT_PLAN. A REAL SIGTERM at step 1
    exercises the PreemptionHandler → preemption checkpoint → exit 143 → respawn
    handoff; a REAL SIGKILL at step 3 exercises the crash-restart path. Both
    resumes are exact and the run completes inside the budget."""
    plan = FaultPlan(name="supervised-signals", events=[
        FaultEvent(kind="proc.sigterm", at_step=1),
        FaultEvent(kind="proc.sigkill", at_step=3),
    ])
    runner = ChaosRunner(plan)
    report = runner.run_supervised_train(str(tmp_path), steps=5, max_restarts=3)
    assert report.ok, report.render_text()
    supervisor_check = next(c for c in report.checks if c.name == "supervisor")
    assert supervisor_check.details["restarts"] == 1
    assert supervisor_check.details["preemption_handoffs"] == 1
    # the workload journaled both injections before the faults landed
    assert sorted(e["kind"] for e in report.injections) == ["proc.sigkill", "proc.sigterm"]
    resumes = next(c for c in report.checks if c.name == "resume_exactness").details["resumes"]
    assert resumes == 2


def test_supervised_mesh_2d_keeps_zero_state_sharded_across_restart(tmp_path):
    """The 2D-training chaos sweep: the subprocess workload trains the small
    MLP on the ("data", "model") mesh with sharding_rules="auto" (planner 2D
    plan, ZeRO data-sharded Adam moments), a REAL SIGKILL forces a restart,
    and the `zero_state_sharded` invariant holds across every attempt AND the
    post-restore state — a resume that silently replicated the moments would
    train identically while spending data_n x the optimizer HBM."""
    plan = FaultPlan(name="supervised-2d-kill", events=[
        FaultEvent(kind="proc.sigkill", at_step=1),
    ])
    runner = ChaosRunner(plan)
    report = runner.run_supervised_train(
        str(tmp_path), steps=3, max_restarts=3, mesh_2d=True
    )
    assert report.ok, report.render_text()
    zero_check = next(c for c in report.checks if c.name == "zero_state_sharded")
    assert zero_check.passed, zero_check.details
    # Both the pre-fault attempt and the post-restart attempt journaled their
    # layout, and the resume record itself carries the restored verdict.
    assert zero_check.details["records"] >= 3
    resumes = next(c for c in report.checks if c.name == "resume_exactness").details["resumes"]
    assert resumes == 1


def test_mpmd_injected_kill_resumes_nonuniform_layout_exactly(tmp_path):
    """Chaos on the MPMD pipeline runtime: an `InjectedKill` at a step
    boundary ends the attempt exactly like a SIGKILL ends a process; the
    'respawn' rebuilds the ("data", "model", "pipeline") mesh from scratch,
    reloads the last published checkpoint into the per-stage trees
    (`load_state_dict` re-places every stage on its own submesh), and the
    restored params hash EXACTLY to the killed attempt's last save — with the
    NON-uniform stage layout (`stage_layout_evidence`) identical across the
    restart, and training continuing on the restored state."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs an 8-device mesh (forced CPU devices)")
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.chaos.injectors import InjectedKill, StepBoundaryInjector
    from accelerate_tpu.chaos.runner import params_digest, stage_layout_evidence
    from accelerate_tpu.checkpointing import load_pytree, save_pytree
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
    from accelerate_tpu.parallel.sharding import data_spec
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import ParallelismConfig, set_seed
    from jax.sharding import NamedSharding

    cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=3,
        num_attention_heads=2, num_key_value_heads=2, max_position_embeddings=32,
        rope_theta=10000.0, tie_word_embeddings=False,
    )

    def spawn():
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        set_seed(0)
        bundle = create_llama_model(cfg, seq_len=8)
        bundle.sharding_rules = "auto"
        acc = Accelerator(
            parallelism_config=ParallelismConfig(data=2, model=2, pipeline=2)
        )
        model, _ = acc.prepare(bundle, optax.adam(1e-3))
        return acc, model

    rng = np.random.default_rng(0)
    acc, model = spawn()
    layout = stage_layout_evidence(model)
    assert layout["nonuniform"], layout  # 3 layers / 2 stages: [1, 2] or [2, 1]
    sharding = NamedSharding(acc.mesh, data_spec(acc.mesh))
    batches = [
        jax.device_put({"input_ids": rng.integers(0, 64, (8, 8)).astype(np.int32)}, sharding)
        for _ in range(4)
    ]

    plan = FaultPlan(name="mpmd-kill", events=[FaultEvent(kind="proc.sigkill", at_step=1)])
    boundary = StepBoundaryInjector(ChaosSession(plan), hard=False)
    step_fn = acc.train_step()
    digests = {}
    killed_at = None
    try:
        for step in range(4):
            jax.block_until_ready(step_fn(batches[step]))
            save_pytree(model.state_dict(), str(tmp_path / f"step{step}.npz"))
            digests[step] = params_digest(model)
            boundary.poll(step)
    except InjectedKill:
        killed_at = step
    assert killed_at == 1 and 1 in digests

    # Respawn: fresh state objects, fresh mesh, fresh plan — then restore.
    acc2, model2 = spawn()
    assert stage_layout_evidence(model2) == layout
    model2.load_state_dict(load_pytree(str(tmp_path / f"step{killed_at}.npz")))
    assert params_digest(model2) == digests[killed_at]
    step_fn2 = acc2.train_step()
    loss = float(step_fn2(batches[killed_at + 1]))
    assert np.isfinite(loss)


# ------------------------------------------------------------------ serving chaos
def test_dispatch_stall_and_queue_burst_drain_with_terminal_reasons(tmp_path):
    """The serving acceptance sweep: an injected dispatch stall + a queue-full
    burst against a bounded queue + one dispatch failure — the drain finishes
    with EVERY accepted request carrying a terminal finish_reason, the queue
    never exceeds its cap, and requests submitted after the failure complete
    normally."""
    plan = FaultPlan(
        name="serve-sweep",
        events=[
            FaultEvent(kind="serve.dispatch_stall", at_call=2, args={"delay_s": 0.02}),
            FaultEvent(kind="serve.queue_burst", at_step=1, args={"count": 6}),
            FaultEvent(kind="serve.dispatch_error", at_call=4),
        ],
    )
    runner = ChaosRunner(plan)
    report = runner.run_serve(num_requests=6, max_queue=3)
    assert report.ok, report.render_text()
    by_name = {c.name: c for c in report.checks}
    terminal = by_name["terminal_finish_reasons"].details
    assert terminal["rejected_queue_full"] > 0, "burst never hit the queue bound"
    assert terminal["accepted"] >= 6
    assert by_name["queue_bounded"].details["queue_peak"] <= 3
    assert by_name["engine_recovered"].details.get("requests_after_error", 0) >= 2


def test_consumed_donation_on_chunk_dispatch_recovers():
    """Regression pin WITH TEETH for the donated-cache rebuild: the injected
    chunk failure also deletes the donated cache buffers (what a real
    accelerator dispatch failure does — CPU alone can't model it, donation is
    ignored there). Without the engine's rebuild-on-abort fix, every admission
    after the failure dies on deleted buffers and recovery probes error."""
    plan = FaultPlan(
        name="chunk-consumes-donation",
        events=[FaultEvent(kind="serve.dispatch_error", at_call=2,
                           args={"consume_donated": True})],
    )
    report = ChaosRunner(plan).run_serve(num_requests=4, max_queue=4)
    assert report.ok, report.render_text()
    recovered = next(c for c in report.checks if c.name == "engine_recovered")
    assert recovered.details["requests_after_error"] >= 2


def test_consumed_donation_on_insert_recovers():
    """The insert fn donates (cache, presence) too: an admission dispatch that
    failed AFTER consuming them poisons every slot, so the engine must widen to
    the blast-radius recovery (error in-flight + rebuild) instead of pretending
    the failure was isolated — then keep serving."""
    plan = FaultPlan(
        name="insert-consumes-donation",
        events=[FaultEvent(kind="serve.insert_error", at_call=2,
                           args={"consume_donated": True})],
    )
    report = ChaosRunner(plan).run_serve(num_requests=4, max_queue=4)
    assert report.ok, report.render_text()
    recovered = next(c for c in report.checks if c.name == "engine_recovered")
    assert recovered.details["requests_after_error"] >= 2


def test_insert_error_is_isolated_to_one_request():
    plan = FaultPlan(
        name="insert-error",
        events=[FaultEvent(kind="serve.insert_error", at_call=2)],
    )
    report = ChaosRunner(plan).run_serve(num_requests=4, max_queue=4)
    assert report.ok, report.render_text()
    # exactly one admission errored; everything else completed normally
    finished = next(
        m for m in report.metrics
        if m["name"] == "serving_requests_finished_total" and m["labels"].get("reason") == "error"
    )
    assert finished["value"] == 1.0


def test_consumed_donation_rebuilds_page_pool_without_leaks():
    """The paged-KV extension of the consume_donated sweeps: the run_serve
    workload serves shared-prefix traffic through a PAGED engine, an injected
    chunk failure deletes the donated pool buffers mid-flight (live refcounts,
    live prefix registrations), and recovery must rebuild the page pool AND the
    host ledger — `pages_in_use == 0` after drain, no page both cached and
    free, and no prefix registration resurrecting a page whose content died
    with the rebuild."""
    plan = FaultPlan(
        name="chunk-consumes-donation-paged",
        events=[FaultEvent(kind="serve.dispatch_error", at_call=3,
                           args={"consume_donated": True})],
    )
    report = ChaosRunner(plan).run_serve(num_requests=8, max_queue=6)
    assert report.ok, report.render_text()
    ledger = next(c for c in report.checks if c.name == "page_ledger")
    assert ledger.details["pages_in_use_after_drain"] == 0
    assert ledger.details["consistency_problems"] == []
    # the workload really exercised the paged machinery, not a vacuous pass
    assert ledger.details["pages_total"] > 0


def test_consumed_donation_recovers_with_speculation_enabled():
    """The speculative chunk widens the blast radius's state surface: the
    draft/verify loop carries a per-slot context history and every paged
    admission reserves a draft window. An injected chunk failure that consumes
    the donated cache must rebuild the speculative state too — history
    reseeded per admission, window pages released with the request — and the
    post-recovery probes must complete through the draft/verify executable,
    with the page ledger closing at zero."""
    plan = FaultPlan(
        name="chunk-consumes-donation-speculative",
        events=[FaultEvent(kind="serve.dispatch_error", at_call=3,
                           args={"consume_donated": True})],
    )
    report = ChaosRunner(plan).run_serve(num_requests=8, max_queue=6, speculative=True)
    assert report.ok, report.render_text()
    recovered = next(c for c in report.checks if c.name == "engine_recovered")
    assert recovered.details["requests_after_error"] >= 2
    ledger = next(c for c in report.checks if c.name == "page_ledger")
    assert ledger.details["pages_in_use_after_drain"] == 0
    assert ledger.details["consistency_problems"] == []
    # the sweep drove the speculative executable, not the plain chunk
    steps = next(
        m for m in report.metrics if m["name"] == "serving_spec_verify_steps_total"
    )
    assert steps["value"] > 0


def test_consumed_donation_recovers_on_the_contiguous_layout_too():
    """paged=False remains a supported fallback (and the only option for model
    families without pool-cache support): its blast-radius recovery must stay
    chaos-covered, not just the paged default's."""
    plan = FaultPlan(
        name="chunk-consumes-donation-contiguous",
        events=[FaultEvent(kind="serve.dispatch_error", at_call=2,
                           args={"consume_donated": True})],
    )
    report = ChaosRunner(plan).run_serve(num_requests=4, max_queue=4, paged=False)
    assert report.ok, report.render_text()
    recovered = next(c for c in report.checks if c.name == "engine_recovered")
    assert recovered.details["requests_after_error"] >= 2
    ledger = next(c for c in report.checks if c.name == "page_ledger")
    assert ledger.details.get("note") == "contiguous engine (no pool)"


# ------------------------------------------------------- kernel-path serving chaos
@pytest.mark.kernels
def test_smoke_serve_sweep_on_the_kernel_path():
    """The smoke-serve acceptance sweep (stall + queue burst + dispatch
    failure) with `attention_impl="pallas_paged"`: the fused page-walk kernels
    ride inside the one decode executable, so every serving invariant —
    terminal finish_reasons, bounded queue, post-failure recovery — must hold
    unchanged with the kernel on the hot path."""
    plan = builtin_plans()["smoke-serve"]
    report = ChaosRunner(plan).run_serve(
        num_requests=6, max_queue=3, attention_impl="pallas_paged"
    )
    assert report.ok, report.render_text()
    by_name = {c.name: c for c in report.checks}
    assert by_name["terminal_finish_reasons"].details["accepted"] >= 6
    assert by_name["queue_bounded"].details["queue_peak"] <= 3
    assert by_name["engine_recovered"].details.get("requests_after_error", 0) >= 2


def test_smoke_serve_sweep_on_the_quantized_pool():
    """The smoke-serve acceptance sweep with `kv_cache_dtype="int8"`: fault
    paths must exercise the QUANTIZED page pool — dispatch stalls, queue
    bursts, and the blast-radius dispatch failure all land on an engine whose
    pool pages are int8 with per-page-per-head scale pools, and recovery must
    rebuild pools AND scales from zeros with the page ledger still closed."""
    plan = builtin_plans()["smoke-serve"]
    report = ChaosRunner(plan).run_serve(
        num_requests=6, max_queue=3, kv_cache_dtype="int8"
    )
    assert report.ok, report.render_text()
    by_name = {c.name: c for c in report.checks}
    assert by_name["terminal_finish_reasons"].details["accepted"] >= 6
    assert by_name["queue_bounded"].details["queue_peak"] <= 3
    assert by_name["engine_recovered"].details.get("requests_after_error", 0) >= 2
    ledger = by_name["page_ledger"]
    assert ledger.details["pages_in_use_after_drain"] == 0
    assert ledger.details["consistency_problems"] == []


@pytest.mark.tp
def test_smoke_serve_sweep_on_a_tensor_parallel_engine():
    """The smoke-serve acceptance sweep with `tp=2`: the engine spans a
    2-device submesh (Megatron-sharded weights, KV pool sharded by KV head),
    and every serving invariant holds unchanged — PLUS the new
    `tp_pool_sharded` check: fault recovery must leave the live pools sharded
    on the submesh, never silently replicated."""
    plan = builtin_plans()["smoke-serve"]
    report = ChaosRunner(plan).run_serve(num_requests=6, max_queue=3, tp=2)
    assert report.ok, report.render_text()
    by_name = {c.name: c for c in report.checks}
    assert by_name["terminal_finish_reasons"].details["accepted"] >= 6
    assert by_name["engine_recovered"].details.get("requests_after_error", 0) >= 2
    sharded = by_name["tp_pool_sharded"]
    assert sharded.details["mesh_devices"] == 2
    assert sharded.details["sharded_leaves"] > 0
    assert sharded.details["unsharded_leaves"] == []


@pytest.mark.tp
def test_consumed_donation_recovers_sharded_on_the_tp_submesh():
    """Blast-radius recovery on a mesh-spanning engine: the injected chunk
    failure deletes the donated SHARDED pool mid-flight; the rebuild must
    recreate the pools (and, int8, the scale pools) from zeros ON THE
    SUBMESH — `tp_pool_sharded` fails on a replicated rebuild — with the
    page ledger closed and post-recovery traffic served by the same warm
    sharded executables."""
    plan = FaultPlan(
        name="chunk-consumes-donation-tp",
        events=[FaultEvent(kind="serve.dispatch_error", at_call=3,
                           args={"consume_donated": True})],
    )
    report = ChaosRunner(plan).run_serve(
        num_requests=8, max_queue=6, tp=2, kv_cache_dtype="int8"
    )
    assert report.ok, report.render_text()
    recovered = next(c for c in report.checks if c.name == "engine_recovered")
    assert recovered.details["requests_after_error"] >= 2
    ledger = next(c for c in report.checks if c.name == "page_ledger")
    assert ledger.details["pages_in_use_after_drain"] == 0
    assert ledger.details["consistency_problems"] == []
    sharded = next(c for c in report.checks if c.name == "tp_pool_sharded")
    assert sharded.passed, sharded.details


@pytest.mark.kernels
def test_consumed_donation_recovers_on_the_quantized_kernel_path():
    """Blast-radius recovery on the quantized KERNEL path: the injected chunk
    failure deletes the donated int8 pool (and its scale pools) mid-flight;
    the rebuild must recreate both from zeros and post-recovery traffic must
    run through the same compiled fused-dequant decode executable — identical
    shapes/dtypes, so the warm executable serves the rebuilt operands."""
    plan = FaultPlan(
        name="chunk-consumes-donation-quantized-kernel",
        events=[FaultEvent(kind="serve.dispatch_error", at_call=3,
                           args={"consume_donated": True})],
    )
    report = ChaosRunner(plan).run_serve(
        num_requests=8, max_queue=6, attention_impl="pallas_paged",
        kv_cache_dtype="int8",
    )
    assert report.ok, report.render_text()
    recovered = next(c for c in report.checks if c.name == "engine_recovered")
    assert recovered.details["requests_after_error"] >= 2
    ledger = next(c for c in report.checks if c.name == "page_ledger")
    assert ledger.details["pages_in_use_after_drain"] == 0
    assert ledger.details["consistency_problems"] == []


@pytest.mark.kernels
def test_consumed_donation_recovers_on_the_kernel_path():
    """Blast-radius recovery rebuilds the KERNEL-path executables identically:
    an injected chunk failure deletes the donated pool buffers mid-flight, the
    engine rebuilds the page pool from zeros, and post-recovery requests must
    complete through the same compiled pallas_paged decode program — page
    ledger closed, no retrace (the rebuilt operands have identical shapes, so
    the warm executable serves them)."""
    plan = FaultPlan(
        name="chunk-consumes-donation-kernel",
        events=[FaultEvent(kind="serve.dispatch_error", at_call=3,
                           args={"consume_donated": True})],
    )
    report = ChaosRunner(plan).run_serve(
        num_requests=8, max_queue=6, attention_impl="pallas_paged"
    )
    assert report.ok, report.render_text()
    recovered = next(c for c in report.checks if c.name == "engine_recovered")
    assert recovered.details["requests_after_error"] >= 2
    ledger = next(c for c in report.checks if c.name == "page_ledger")
    assert ledger.details["pages_in_use_after_drain"] == 0
    assert ledger.details["consistency_problems"] == []
    assert ledger.details["pages_total"] > 0


@pytest.mark.kernels
@pytest.mark.speculative
def test_consumed_donation_recovers_with_speculation_on_the_kernel_path():
    """The speculative sweep with the block-verify KERNEL on the verify seam:
    consumed-donation recovery must rebuild the draft/verify state (history
    reseeded, window pages released) and drive post-recovery traffic through
    the same compiled kernel-path verify executable."""
    plan = FaultPlan(
        name="chunk-consumes-donation-speculative-kernel",
        events=[FaultEvent(kind="serve.dispatch_error", at_call=3,
                           args={"consume_donated": True})],
    )
    report = ChaosRunner(plan).run_serve(
        num_requests=8, max_queue=6, speculative=True, attention_impl="pallas_paged"
    )
    assert report.ok, report.render_text()
    recovered = next(c for c in report.checks if c.name == "engine_recovered")
    assert recovered.details["requests_after_error"] >= 2
    ledger = next(c for c in report.checks if c.name == "page_ledger")
    assert ledger.details["pages_in_use_after_drain"] == 0
    assert ledger.details["consistency_problems"] == []
    steps = next(
        m for m in report.metrics if m["name"] == "serving_spec_verify_steps_total"
    )
    assert steps["value"] > 0


def test_insert_failure_releases_reserved_pages():
    """An isolated insert failure (no donation consumed) must return the pages
    it reserved for the doomed request — a leak here exhausts the pool after
    enough transient admission errors, a failure mode the dense layout never
    had."""
    plan = FaultPlan(
        name="insert-error-paged-ledger",
        events=[FaultEvent(kind="serve.insert_error", at_call=2)],
    )
    report = ChaosRunner(plan).run_serve(num_requests=8, max_queue=6)
    assert report.ok, report.render_text()
    ledger = next(c for c in report.checks if c.name == "page_ledger")
    assert ledger.details["pages_in_use_after_drain"] == 0
    assert ledger.details["consistency_problems"] == []


# ------------------------------------------------------------------ CLI contract
def _run_cli(capsys, *argv):
    from accelerate_tpu.commands.accelerate_cli import get_command_parser

    parser = get_command_parser()
    args = parser.parse_args(list(argv))
    with pytest.raises(SystemExit) as excinfo:
        args.func(args)
    out = capsys.readouterr().out
    return excinfo.value.code, out


def test_cli_list_faults(capsys):
    code, out = _run_cli(capsys, "chaos", "list-faults")
    assert code == 0
    for kind in ("fs.torn_write", "proc.sigkill", "serve.queue_burst"):
        assert kind in out


def test_cli_run_clean_plan_exits_0_and_report_round_trips(capsys, tmp_path):
    report_path = str(tmp_path / "report.json")
    code, out = _run_cli(
        capsys, "chaos", "run", "--plan", "smoke-train", "--steps", "4",
        "--base-dir", str(tmp_path / "run"), "--json", "--report-out", report_path,
    )
    assert code == 0, out
    emitted = json.loads(out)
    assert emitted["ok"] is True and emitted["workload"] == "train"
    # a stored report re-renders with the same verdict/exit code
    loaded = InvariantReport.load(report_path)
    assert loaded.ok and loaded.to_dict()["checks"] == emitted["checks"]
    code2, _ = _run_cli(capsys, "chaos", "report", report_path)
    assert code2 == 0


def test_cli_run_seeded_regression_exits_nonzero(capsys, tmp_path):
    code, out = _run_cli(
        capsys, "chaos", "run", "--plan", "seeded-regression", "--steps", "4",
        "--base-dir", str(tmp_path / "run"),
    )
    assert code == 1
    assert "INVARIANTS VIOLATED" in out
    assert "no_torn_resolved" in out


def test_cli_bad_plan_exits_2(capsys, tmp_path):
    code, _ = _run_cli(capsys, "chaos", "run", "--plan", str(tmp_path / "missing.json"))
    assert code == 2
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"events": [{"kind": "nope"}]}))
    code, _ = _run_cli(capsys, "chaos", "run", "--plan", str(bad))
    assert code == 2


def test_launch_exports_fault_plan_env(tmp_path):
    """`accelerate-tpu launch --fault_plan` joins the env protocol exactly like
    --profile_dir does."""
    import argparse

    from accelerate_tpu.commands.launch import add_launch_args, build_launch_env

    parser = argparse.ArgumentParser()
    add_launch_args(parser)
    plan_file = str(tmp_path / "plan.json")
    args = parser.parse_args(["--fault_plan", plan_file, "script.py"])
    env = build_launch_env(args, {})
    assert env[FAULT_PLAN_ENV] == plan_file


# ------------------------------------------------------------------ async-commit sweeps
def test_async_sigkill_at_every_boundary_resumes_exactly(tmp_path):
    """The async analogue of THE acceptance sweep: SIGKILL at every step
    boundary of an 8-step run whose every save runs through the background
    committer. A kill with a commit in flight aborts it (a dead process cannot
    publish); every resume still lands exactly on the last PUBLISHED
    checkpoint, and no torn checkpoint ever resolves."""
    plan = FaultPlan(
        name="async-kill-every-boundary",
        workload="async-train",
        events=[FaultEvent(kind="proc.sigkill", at_step=k) for k in range(8)],
    )
    runner = ChaosRunner(plan)
    report = runner.run_train(str(tmp_path), steps=8, max_restarts=16, async_save=True)
    assert report.ok, report.render_text()
    assert report.workload == "async-train"
    by_name = {c.name: c for c in report.checks}
    # 8 kills -> 8 restarts. The step-0 commit legitimately races its abort
    # (the kill lands the instant the save is accepted): when it aborted,
    # attempt 2 has nothing to resume FROM — 7 resumes; when it published in
    # time — 8. Every resume that happened must be exact either way.
    assert by_name["resume_exactness"].details["resumes"] in (7, 8)
    assert by_name["restart_budget"].details["restarts"] == 8
    assert by_name["restart_budget"].details["completed"] is True


def test_async_kill_with_commit_in_flight_never_corrupts_previous(tmp_path):
    """ISSUE acceptance boundary 'commit in flight': a slowed background commit
    is provably still running when the step-boundary SIGKILL lands. The abort
    keeps it from publishing; the previously published checkpoint must be the
    verified latest the next attempt resumes from."""
    plan = FaultPlan(
        name="async-kill-in-flight",
        workload="async-train",
        events=[
            # Stall step-1's commit (model.npz write #2) for longer than the
            # boundary takes to kill; the commit is mid-fsync when the run dies.
            FaultEvent(kind="fs.slow_fsync", path_pattern="model.npz", at_call=2,
                       args={"delay_s": 0.3}),
            FaultEvent(kind="proc.sigkill", at_step=1),
        ],
    )
    report = ChaosRunner(plan).run_train(str(tmp_path), steps=4, async_save=True)
    assert report.ok, report.render_text()
    by_name = {c.name: c for c in report.checks}
    # resumed exactly once, from a checkpoint that independently verifies
    assert by_name["resume_exactness"].details["resumes"] == 1
    assert by_name["no_torn_resolved"].details["final_verified_latest_step"] == 3


def test_async_committer_killed_in_rename_window_surfaces_and_recovers(tmp_path):
    """Boundary 'commit mid-write': the committer dies inside an artifact's
    rename window (InjectedKill on the committer thread). The death surfaces at
    the next step boundary like a process kill, the unpublished commit leaves
    only staging litter, and the restart chain completes."""
    plan = FaultPlan(
        name="async-rename-crash",
        workload="async-train",
        events=[FaultEvent(kind="fs.crash_in_rename", path_pattern="optimizer.npz*", at_call=3)],
    )
    report = ChaosRunner(plan).run_train(str(tmp_path), steps=4, async_save=True)
    assert report.ok, report.render_text()
    assert [e["kind"] for e in report.injections] == ["fs.crash_in_rename"]


def test_async_kill_in_publish_rename_window(tmp_path):
    """Boundary 'publish mid-rename': the committer dies between the staged
    manifest write and the directory rename — the checkpoint is fully on disk
    in staging but must never become visible; the previous one stays latest."""
    plan = FaultPlan(
        name="async-publish-crash",
        workload="async-train",
        events=[FaultEvent(kind="fs.crash_in_rename", path_pattern="checkpoint_2", at_call=1)],
    )
    report = ChaosRunner(plan).run_train(str(tmp_path), steps=4, async_save=True)
    assert report.ok, report.render_text()


def test_async_post_publish_torn_write_falls_back(tmp_path):
    """Boundary 'post-publish': corruption lands AFTER an async commit
    published. resolve() must fall back past the torn newest checkpoint on the
    next resume, async exactly like sync."""
    plan = FaultPlan(
        name="async-torn",
        workload="async-train",
        events=[
            FaultEvent(kind="fs.torn_write", path_pattern="model.npz", at_call=2,
                       args={"offset": 1}),
            FaultEvent(kind="proc.sigkill", at_step=1),
        ],
    )
    report = ChaosRunner(plan).run_train(str(tmp_path), steps=4, async_save=True)
    assert report.ok, report.render_text()
    by_name = {c.name: c for c in report.checks}
    assert by_name["no_torn_resolved"].details["resumes"] == 1


def test_async_eio_exhaustion_is_a_commit_failure_crash(tmp_path):
    """Boundary 'commit I/O failure': every write of one step's model artifact
    raises EIO, exhausting the manager's retries inside the background commit.
    The failure surfaces as CheckpointCommitError on the next save's barrier —
    counted as a crash, restarted, run completes."""
    plan = FaultPlan(
        name="async-eio",
        workload="async-train",
        # times=4 with no at_call: the first model.npz write AND its 3 retries
        # all fail — the manager's retry budget is exhausted inside the commit.
        events=[FaultEvent(kind="fs.io_error", path_pattern="model.npz", times=4,
                           args={"errno": "EIO"})],
    )
    report = ChaosRunner(plan).run_train(str(tmp_path), steps=4, async_save=True)
    assert report.ok, report.render_text()
    assert all(e["kind"] == "fs.io_error" for e in report.injections)
    assert len(report.injections) == 4  # initial try + 3 retries, all scripted


def test_smoke_async_ckpt_builtin_plan_is_green(tmp_path):
    """The shipped async-checkpoint chaos fixture holds every invariant."""
    plan = builtin_plans()["smoke-async-ckpt"]
    assert plan.workload == "async-train"
    report = ChaosRunner(plan).run_train(str(tmp_path), steps=6, async_save=True)
    assert report.ok, report.render_text()


def test_supervised_async_preemption_flushes_commits(tmp_path):
    """End-to-end with real signals: the subprocess workload saves through the
    background committer, a REAL SIGTERM lands mid-run, and check_preemption's
    flush + synchronous preemption save hand off cleanly (exit 143, exact
    resume, completion)."""
    plan = FaultPlan(name="supervised-async-term", events=[
        FaultEvent(kind="fs.slow_fsync", path_pattern="model.npz", at_call=2,
                   args={"delay_s": 0.2}),
        FaultEvent(kind="proc.sigterm", at_step=1),
    ])
    runner = ChaosRunner(plan)
    report = runner.run_supervised_train(str(tmp_path), steps=4, async_save=True)
    assert report.ok, report.render_text()
    supervisor_check = next(c for c in report.checks if c.name == "supervisor")
    assert supervisor_check.details["preemption_handoffs"] == 1


def test_cli_run_smoke_async_ckpt_uses_plan_workload(capsys, tmp_path):
    """`chaos run --plan smoke-async-ckpt` picks the plan's own workload
    (async-train) without an explicit --workload flag and exits 0."""
    code, out = _run_cli(
        capsys, "chaos", "run", "--plan", "smoke-async-ckpt", "--steps", "5",
        "--base-dir", str(tmp_path / "run"), "--json",
    )
    assert code == 0, out
    emitted = json.loads(out)
    assert emitted["ok"] is True
    assert emitted["workload"] == "async-train"
    assert emitted["plan"]["workload"] == "async-train"


def test_cli_list_faults_lists_builtin_plans(capsys):
    code, out = _run_cli(capsys, "chaos", "list-faults")
    assert code == 0
    for name in ("smoke-train", "smoke-serve", "smoke-async-ckpt", "seeded-regression"):
        assert name in out
    assert "workload=async-train" in out


# ------------------------------------------------------------------ router sweeps
@pytest.mark.router
def test_smoke_router_builtin_plan_is_green():
    """The acceptance sweep: N=3 replicas under live traffic with a stall, a
    poisoned dispatch AND a kill of distinct replicas — every request reaches
    a terminal finish_reason, no token stream duplicates, the fleet recovers,
    and the router never routed to an ejected replica."""
    plan = builtin_plans()["smoke-router"]
    report = ChaosRunner(plan).run_router(num_requests=10, replicas=3)
    assert report.ok, report.render_text()
    kinds = {e["kind"] for e in report.injections}
    assert {"router.replica_kill", "router.replica_stall", "router.replica_poison"} <= kinds
    names = {c.name for c in report.checks}
    assert {"terminal_finish_reasons", "no_duplicate_streams", "fleet_recovered",
            "no_route_to_ejected", "ledger_reconciles"} <= names


@pytest.mark.router
def test_router_kill_mid_traffic_redispatch_and_recovery():
    """A lone kill of the busiest replica mid-traffic: re-dispatch/replica_lost
    semantics hold and the killed replica is back by drain."""
    plan = FaultPlan(
        name="kill-only", seed=3,
        events=[
            FaultEvent(kind="serve.queue_burst", at_step=1, args={"count": 6}),
            FaultEvent(kind="router.replica_kill", path_pattern="replica_0", at_call=3),
        ],
    )
    report = ChaosRunner(plan).run_router(num_requests=8, replicas=3)
    assert report.ok, report.render_text()
    assert any(e["kind"] == "router.replica_kill" for e in report.injections)


@pytest.mark.router
def test_router_hedging_under_stall():
    """A stalled replica with hedging armed: the hedge copy wins without
    duplicating a stream (the no_duplicate_streams invariant is the pin)."""
    plan = FaultPlan(
        name="stall-hedge", seed=5,
        events=[
            FaultEvent(kind="serve.queue_burst", at_step=1, args={"count": 8}),
            FaultEvent(kind="router.replica_stall", path_pattern="replica_1", at_call=1,
                       args={"delay_s": 0.05}, times=3),
        ],
    )
    report = ChaosRunner(plan).run_router(
        num_requests=8, replicas=2, hedge_after_s=0.0
    )
    assert report.ok, report.render_text()


@pytest.mark.router
def test_cli_run_router_workload(capsys, tmp_path):
    from accelerate_tpu.commands.accelerate_cli import get_command_parser

    report_path = tmp_path / "router_report.json"
    parser = get_command_parser()
    args = parser.parse_args([
        "chaos", "run", "--plan", "smoke-router", "--requests", "8",
        "--replicas", "3", "--json", "--report-out", str(report_path),
    ])
    with pytest.raises(SystemExit) as exit_info:
        args.func(args)
    assert exit_info.value.code == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["workload"] == "router" and payload["ok"]
    assert InvariantReport.load(str(report_path)).ok


# ------------------------------------------------------------------ fleet sweeps
@pytest.mark.fleet
def test_fleet_real_sigkill_mid_traffic_sweep(tmp_path):
    """THE out-of-process acceptance sweep: a real worker PROCESS takes a real
    SIGKILL mid-traffic (worker-side, via the env-propagated plan). Every
    request reaches a terminal reason, no stream duplicates, the respawned
    worker rejoins WARM and serves post-fault traffic, the autoscaler
    converges back to its floor after the burst, and the worker-side journal
    reconciles against the observed process death."""
    plan = FaultPlan(
        name="fleet-kill", seed=1, workload="fleet",
        events=[
            FaultEvent(kind="serve.queue_burst", at_step=1, args={"count": 6}),
            FaultEvent(kind="fleet.worker_kill", path_pattern="worker_0", at_call=3),
        ],
    )
    report = ChaosRunner(plan).run_fleet(
        num_requests=8, replicas=2, workdir=str(tmp_path)
    )
    assert report.ok, report.render_text()
    names = {c.name for c in report.checks}
    assert {"terminal_finish_reasons", "no_duplicate_streams", "fleet_recovered",
            "no_route_to_ejected", "worker_restart_rejoins_warm",
            "ledger_reconciles", "autoscaler_converges"} <= names
    restart = next(c for c in report.checks if c.name == "worker_restart_rejoins_warm")
    assert restart.details["observed_deaths"] >= 1
    ledger = next(c for c in report.checks if c.name == "ledger_reconciles")
    assert ledger.details["worker_journal_kills"] == {"worker_0": 1}
    # The journal entry was durably written BEFORE the SIGKILL landed.
    journal = [json.loads(l) for l in open(tmp_path / "fleet_chaos_journal.jsonl")]
    assert any(e["kind"] == "fleet.worker_kill" and e["worker"] == "worker_0"
               for e in journal)


@pytest.mark.fleet
def test_fleet_worker_stall_surfaces_as_heartbeat_death(tmp_path):
    """A worker stalled past the controller's step timeout is
    indistinguishable from a dead one: the client kills it, the router ejects
    and respawns it warm, and the invariants hold — hang detection by
    TIMEOUT, not cooperation."""
    plan = FaultPlan(
        name="fleet-stall", seed=2, workload="fleet",
        events=[
            # The burst spreads load across the fleet: least-loaded routing
            # with drip-fed traffic would otherwise keep worker_1 idle and the
            # stall trigger (counting ITS OWN step ops) would never arm.
            FaultEvent(kind="serve.queue_burst", at_step=1, args={"count": 6}),
            FaultEvent(kind="fleet.worker_stall", path_pattern="worker_1", at_call=2,
                       args={"delay_s": 30.0}),
        ],
    )
    report = ChaosRunner(plan).run_fleet(
        num_requests=6, replicas=2, autoscale=False, step_timeout_s=3.0,
        workdir=str(tmp_path),
    )
    assert report.ok, report.render_text()
    assert "autoscaler_converges" not in {c.name for c in report.checks}
    ledger = next(c for c in report.checks if c.name == "ledger_reconciles")
    assert ledger.details["observed_deaths"].get("worker_1", 0) >= 1


@pytest.mark.fleet
def test_smoke_fleet_plan_and_workload_inference():
    """The builtin plan round-trips, the CLI infers the fleet workload from
    fleet.* kinds, and the catalog documents the new fault kinds."""
    from accelerate_tpu.chaos.injectors import catalog
    from accelerate_tpu.commands.chaos import _infer_workload

    plan = builtin_plans()["smoke-fleet"]
    assert plan.workload == "fleet"
    assert FaultPlan.from_json(plan.to_json()).to_dict() == plan.to_dict()
    bare = FaultPlan(name="x", events=[
        FaultEvent(kind="fleet.worker_kill", path_pattern="worker_0", at_call=1),
    ])
    assert _infer_workload(bare) == "fleet"
    assert {"fleet.worker_kill", "fleet.worker_stall"} <= set(catalog())


@pytest.mark.fleet
def test_fleet_partition_sweep_over_socket_transport(tmp_path):
    """THE network-chaos acceptance sweep (socket transport): a healable
    partition, injected latency past the frame deadline, and two link flaps —
    every stream stays exactly-once across the reconnects, the controller's
    reconnect counters reconcile against the workers' re-registration
    journal, and a HEALED partition never increments a respawn counter."""
    plan = builtin_plans()["partition-fleet"]
    report = ChaosRunner(plan).run_fleet(
        num_requests=8, replicas=2, transport="socket", workdir=str(tmp_path)
    )
    assert report.ok, report.render_text()
    names = {c.name for c in report.checks}
    assert {"terminal_finish_reasons", "no_duplicate_streams", "fleet_recovered",
            "reconnect_reconciles", "partition_is_not_death"} <= names
    reconciles = next(c for c in report.checks if c.name == "reconnect_reconciles")
    assert reconciles.details["controller_reconnects"] >= 1
    assert (reconciles.details["journaled_reregisters"]
            >= reconciles.details["controller_reconnects"])
    not_death = next(c for c in report.checks if c.name == "partition_is_not_death")
    assert not_death.details["net_attributed_deaths"] == 0
    assert not_death.details["escalation_expected"] is False
    # Workers journaled each accepted re-registration (epoch > 1) durably.
    journal = [json.loads(l) for l in open(tmp_path / "fleet_chaos_journal.jsonl")]
    reregisters = [e for e in journal if e["kind"] == "net.reregister"]
    assert reregisters and all(e["epoch"] >= 2 for e in reregisters)


@pytest.mark.fleet
def test_fleet_partition_past_budget_escalates_to_warm_respawn(tmp_path):
    """A partition window LONGER than `reconnect_deadline_s` must exhaust the
    reconnect budget and escalate through the ordinary death path: the worker
    is respawned warm and rejoins — and the invariants expect that death
    instead of forbidding it."""
    plan = FaultPlan(
        name="partition-escalates", seed=0, workload="fleet",
        events=[FaultEvent(kind="net.partition", path_pattern="worker_0",
                           at_call=4, args={"window_s": 30.0})],
    )
    report = ChaosRunner(plan).run_fleet(
        num_requests=6, replicas=2, transport="socket",
        reconnect_deadline_s=0.6, autoscale=False, workdir=str(tmp_path),
    )
    assert report.ok, report.render_text()
    not_death = next(c for c in report.checks if c.name == "partition_is_not_death")
    assert not_death.details["escalation_expected"] is True
    assert not_death.details["net_attributed_deaths"] >= 1


def test_net_faults_require_socket_transport():
    """net.* kinds damage the socket seam: the fleet workload must reject
    them on the pipe transport up front (no silently-vacuous sweep), and the
    CLI infers workload/transport from them."""
    from accelerate_tpu.chaos.injectors import catalog
    from accelerate_tpu.commands.chaos import _infer_workload

    plan = builtin_plans()["partition-fleet"]
    with pytest.raises(ValueError, match="transport='socket'"):
        ChaosRunner(plan).run_fleet(num_requests=2, replicas=2, transport="pipe")
    assert _infer_workload(FaultPlan(name="x", events=[
        FaultEvent(kind="net.partition", path_pattern="worker_0", at_call=1),
    ])) == "fleet"
    assert {"net.partition", "net.slow", "net.flap"} <= set(catalog())


def test_session_preconsume_blocks_refire_but_not_other_events():
    """`ChaosSession.preconsume` (the worker-restart livelock guard at the
    session layer): consumed firings count against `times`, at_call counters
    advance to the trigger, and path-mismatched or other-kind events are
    untouched."""
    plan = FaultPlan(name="p", events=[
        FaultEvent(kind="fleet.worker_kill", path_pattern="worker_0", at_call=2),
        FaultEvent(kind="fleet.worker_stall", path_pattern="worker_1", at_call=1),
    ])
    session = ChaosSession(plan)
    session.preconsume("fleet.worker_kill", 1, path="worker_0")
    for _ in range(4):
        assert session.fire("fleet.worker_kill", path="worker_0") == []
    # the OTHER worker's stall still fires normally
    assert len(session.fire("fleet.worker_stall", path="worker_1")) == 1
    # a preconsume that matches nothing is a no-op, not an error
    session.preconsume("fleet.worker_kill", 3, path="worker_9")
    # An event with firings LEFT (times=2, one consumed) must keep counting
    # fresh calls: the restarted process's at_call trigger still arms for the
    # remaining firing instead of being disarmed forever.
    plan2 = FaultPlan(name="p2", events=[
        FaultEvent(kind="fleet.worker_kill", path_pattern="worker_0", at_call=2, times=2),
    ])
    session2 = ChaosSession(plan2)
    session2.preconsume("fleet.worker_kill", 1, path="worker_0")
    assert session2.fire("fleet.worker_kill", path="worker_0") == []  # call 1
    assert len(session2.fire("fleet.worker_kill", path="worker_0")) == 1  # call 2: 2nd firing
    assert session2.fire("fleet.worker_kill", path="worker_0") == []  # budget exhausted


# ------------------------------------------------------------------ crash-loop livelock
def test_async_at_step_kill_livelock_surfaces_crash_loop(tmp_path):
    """The PR-9 livelock regression (at_step SIGKILL + async saves, re-armed
    every attempt): the same step is killed before its commit can ever
    publish. The runner must detect the no-forward-progress loop, stop early,
    and tag a `crash_loop` diagnostic — not grind the whole restart budget."""
    plan = FaultPlan(
        name="livelock",
        events=[FaultEvent(kind="proc.sigkill", at_step=1, times=0)],
    )
    report = ChaosRunner(plan).run_train(
        str(tmp_path), steps=4, async_save=True, max_restarts=16
    )
    diags = [d for d in report.diagnostics if d.get("tag") == "crash_loop"]
    assert diags, report.render_text()
    assert diags[0]["why"] == "no_forward_progress"
    budget = next(c for c in report.checks if c.name == "restart_budget")
    assert budget.details["restarts"] < 16, "detector must stop the sweep early"
    assert not report.ok  # a livelocked plan is honestly red
    # round trip: the diagnostic survives save/load
    path = str(tmp_path / "report.json")
    report.save(path)
    assert InvariantReport.load(path).diagnostics == report.diagnostics


def test_single_kill_sweep_does_not_false_positive_crash_loop(tmp_path):
    """A legitimate recovery chain (one kill, checkpoint published, resume
    makes progress) must NOT trip the detector."""
    plan = FaultPlan(name="one-kill", events=[FaultEvent(kind="proc.sigkill", at_step=1)])
    report = ChaosRunner(plan).run_train(str(tmp_path), steps=4, async_save=True)
    assert report.ok, report.render_text()
    assert not report.diagnostics

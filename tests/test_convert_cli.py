"""`accelerate-tpu convert` / `merge` checkpoint tooling: HF<->native round trips
through the real CLI preserve logits exactly; sharded checkpoints consolidate."""

import os
import subprocess
import sys

import numpy as np

import jax.numpy as jnp

from accelerate_tpu.test_utils.testing import cpu_mesh_env


def _cli(*args):
    result = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli", *args],
        env=cpu_mesh_env(),
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    return result.stdout


def test_convert_round_trip_gptj(tmp_path):
    from accelerate_tpu.checkpointing import load_pytree
    from accelerate_tpu.models.gptj import create_gptj_model, gptj_tiny
    from accelerate_tpu.utils.hf_loading import save_hf_checkpoint

    cfg = gptj_tiny()
    model = create_gptj_model(cfg, seq_len=16)
    hf_path = str(tmp_path / "hf.safetensors")
    save_hf_checkpoint(model.params, "gptj", cfg, hf_path)

    native = str(tmp_path / "native")
    out = _cli("convert", hf_path, native, "--model_type", "gptj", "--model", "gptj-tiny")
    assert "from_hf" in out

    params = load_pytree(native)
    ids = jnp.asarray(np.random.default_rng(0).integers(1, cfg.vocab_size, (2, 8)), jnp.int32)
    np.testing.assert_allclose(
        np.asarray(model.apply_fn(params, ids)),
        np.asarray(model.apply_fn(model.params, ids)),
        rtol=1e-6,
        atol=1e-6,
    )

    # and back out to HF layout
    hf2 = str(tmp_path / "hf2.safetensors")
    _cli("convert", native, hf2, "--model_type", "gptj", "--model", "gptj-tiny", "--direction", "to_hf")
    from accelerate_tpu.utils.hf_loading import load_hf_state_dict

    flat = load_hf_state_dict(hf2)
    assert "transformer.h.0.attn.q_proj.weight" in flat


def test_convert_rejects_family_mismatch(tmp_path):
    result = subprocess.run(
        [
            sys.executable,
            "-m",
            "accelerate_tpu.commands.accelerate_cli",
            "convert",
            "x",
            "y",
            "--model_type",
            "llama",
            "--model",
            "gptj-tiny",
        ],
        env=cpu_mesh_env(),
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert result.returncode != 0
    assert "is a 'gptj' config" in result.stderr


def test_cli_model_type_choices_match_interchange_registry():
    """The argparse choices list is a static copy of the interchange keys (kept
    static so --help stays lazy-import fast); this pins them together."""
    import argparse

    from accelerate_tpu.commands.convert import register_subcommand
    from accelerate_tpu.utils.hf_loading import _FROM_HF, _TO_HF

    parser = argparse.ArgumentParser()
    sub = register_subcommand(parser.add_subparsers())
    choices = next(a for a in sub._actions if a.dest == "model_type").choices
    assert set(choices) == set(_FROM_HF) == set(_TO_HF)


def test_merge_consolidates_sharded_checkpoint(tmp_path):
    from accelerate_tpu.checkpointing import load_pytree, save_sharded

    tree = {
        "a": np.arange(12, dtype=np.float32).reshape(3, 4),
        "nested": {"b": np.ones((2, 2), np.float32)},
    }
    shard_dir = str(tmp_path / "sharded")
    os.makedirs(shard_dir)
    save_sharded(tree, shard_dir)
    out = str(tmp_path / "merged")
    _cli("merge", shard_dir, out)
    merged = load_pytree(out)
    np.testing.assert_array_equal(merged["a"], tree["a"])
    np.testing.assert_array_equal(merged["nested"]["b"], tree["nested"]["b"])

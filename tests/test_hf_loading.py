"""HF checkpoint interchange tests: export→import round-trips preserve logits exactly
for llama and mixtral; torch-layout checkpoints (HF transformers llama) load and match
the transformers reference forward when the package is importable; torch .bin files
also load."""

import json
import os
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
from accelerate_tpu.models.mixtral import create_mixtral_model, mixtral_tiny
from accelerate_tpu.utils.hf_loading import (
    convert_hf_state_dict,
    export_hf_state_dict,
    load_hf_checkpoint_in_model,
    load_hf_state_dict,
    save_hf_checkpoint,
)


def _tiny_llama():
    return LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
    )


def test_llama_round_trip_preserves_logits():
    cfg = _tiny_llama()
    model = create_llama_model(cfg, seq_len=16)
    ids = jnp.asarray(np.random.default_rng(0).integers(1, 128, (2, 16)), jnp.int32)
    ref = np.asarray(model.apply_fn(model.params, ids))

    flat = export_hf_state_dict(model.params, "llama", cfg)
    assert flat["model.layers.0.self_attn.q_proj.weight"].shape == (32, 32)  # [out, in]
    params2 = convert_hf_state_dict(flat, "llama", cfg)
    out = np.asarray(model.apply_fn(params2, ids))
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_mixtral_round_trip_preserves_logits():
    cfg = mixtral_tiny()
    model = create_mixtral_model(cfg, seq_len=16)
    ids = jnp.asarray(np.random.default_rng(1).integers(1, cfg.vocab_size, (2, 16)), jnp.int32)
    ref = np.asarray(model.apply_fn(model.params, ids))

    flat = export_hf_state_dict(model.params, "mixtral", cfg)
    assert f"model.layers.0.block_sparse_moe.experts.0.w1.weight" in flat
    params2 = convert_hf_state_dict(flat, "mixtral", cfg)
    out = np.asarray(model.apply_fn(params2, ids))
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_safetensors_file_round_trip():
    cfg = _tiny_llama()
    model = create_llama_model(cfg, seq_len=16)
    ids = jnp.asarray(np.random.default_rng(2).integers(1, 128, (1, 16)), jnp.int32)
    ref = np.asarray(model.apply_fn(model.params, ids))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.safetensors")
        save_hf_checkpoint(model.params, "llama", cfg, path)
        model2 = create_llama_model(cfg, rng=jax.random.key(99), seq_len=16)
        load_hf_checkpoint_in_model(model2, path, "llama", config=cfg)
        out = np.asarray(model2.apply_fn(model2.params, ids))
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_bf16_export_is_real_bf16():
    """bf16 checkpoints must record dtype BF16, not U16 (advisor finding): the file
    has to load back as bfloat16 in HF transformers and in load_hf_state_dict."""
    import ml_dtypes
    import jax.tree_util as jtu
    from safetensors import safe_open

    cfg = _tiny_llama()
    model = create_llama_model(cfg, seq_len=16)
    bf16_params = jtu.tree_map(
        lambda a: np.asarray(a).astype(ml_dtypes.bfloat16), model.params
    )
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.safetensors")
        save_hf_checkpoint(bf16_params, "llama", cfg, path)
        with safe_open(path, framework="np") as f:
            meta = f.metadata()
            name = next(iter(f.keys()))
            assert f.get_tensor(name).dtype == ml_dtypes.bfloat16
        assert not meta or "bfloat16_as_uint16" not in (meta or {})
        loaded = load_hf_state_dict(path)
        assert all(v.dtype == ml_dtypes.bfloat16 for v in loaded.values())


def test_torch_bin_round_trip():
    torch = pytest.importorskip("torch")
    cfg = _tiny_llama()
    model = create_llama_model(cfg, seq_len=16)
    flat = export_hf_state_dict(model.params, "llama", cfg)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "pytorch_model.bin")
        torch.save({k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in flat.items()}, path)
        loaded = load_hf_state_dict(path)
    for k, v in flat.items():
        np.testing.assert_array_equal(loaded[k], v)


def test_sharded_index_loading():
    from safetensors.numpy import save_file

    cfg = _tiny_llama()
    model = create_llama_model(cfg, seq_len=16)
    flat = export_hf_state_dict(model.params, "llama", cfg)
    keys = sorted(flat.keys())
    half = len(keys) // 2
    with tempfile.TemporaryDirectory() as d:
        save_file({k: flat[k] for k in keys[:half]}, os.path.join(d, "model-00001.safetensors"))
        save_file({k: flat[k] for k in keys[half:]}, os.path.join(d, "model-00002.safetensors"))
        weight_map = {k: "model-00001.safetensors" for k in keys[:half]}
        weight_map.update({k: "model-00002.safetensors" for k in keys[half:]})
        with open(os.path.join(d, "model.safetensors.index.json"), "w") as f:
            json.dump({"weight_map": weight_map}, f)
        loaded = load_hf_state_dict(d)
    assert set(loaded.keys()) == set(flat.keys())


def test_real_transformers_llama_matches():
    """Forward parity against the actual HF transformers implementation (torch CPU)."""
    transformers = pytest.importorskip("transformers")
    import torch

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
    flat = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    # HF ties rotary buffers etc. out of state_dict; our loader only needs weights
    cfg = _tiny_llama()
    params = convert_hf_state_dict(flat, "llama", cfg)
    model = create_llama_model(cfg, seq_len=16)

    ids_np = np.random.default_rng(3).integers(1, 128, (2, 16))
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(ids_np)).logits.numpy()
    out = np.asarray(model.apply_fn(params, jnp.asarray(ids_np, jnp.int32)))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)

"""Tests for the L1 state core (parity: reference tests/test_state_checkpointing.py +
singleton behavior assertions scattered through tests/test_accelerator.py)."""

import numpy as np
import pytest

from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils import DistributedType, GradientAccumulationPlugin, ParallelismConfig


def test_partial_state_topology():
    state = PartialState()
    assert state.num_processes == 1
    assert state.process_index == 0
    assert state.is_main_process
    assert state.is_local_main_process
    assert state.num_devices == 8
    assert state.local_device_count == 8
    assert state.distributed_type == DistributedType.XLA_SPMD


def test_partial_state_is_borg():
    a = PartialState()
    b = PartialState()
    assert a.__dict__ is b.__dict__


def test_wait_for_everyone_no_hang():
    PartialState().wait_for_everyone()


def test_split_between_processes_single():
    state = PartialState()
    with state.split_between_processes([1, 2, 3]) as inputs:
        assert inputs == [1, 2, 3]


def test_on_main_process_decorator():
    state = PartialState()
    calls = []

    @state.on_main_process
    def fn():
        calls.append(1)

    fn()
    assert calls == [1]


def test_accelerator_state_mixed_precision():
    state = AcceleratorState(mixed_precision="bf16")
    assert state.mixed_precision == "bf16"
    import jax.numpy as jnp

    assert state.compute_dtype == jnp.bfloat16
    # Re-init with a conflicting value raises
    with pytest.raises(ValueError):
        AcceleratorState(mixed_precision="fp16")


def test_accelerator_state_mesh_default():
    state = AcceleratorState()
    mesh = state.mesh
    assert mesh.shape["data"] == 8
    assert mesh.shape["fsdp"] == 1
    assert mesh.size == 8


def test_accelerator_state_mesh_custom():
    state = AcceleratorState(parallelism_config=ParallelismConfig(data=2, fsdp=2, model=2))
    mesh = state.mesh
    assert mesh.shape["data"] == 2
    assert mesh.shape["fsdp"] == 2
    assert mesh.shape["model"] == 2


def test_parallelism_config_resolve():
    cfg = ParallelismConfig(data=-1, model=2)
    sizes = cfg.resolve(8)
    assert sizes["data"] == 4 and sizes["model"] == 2
    with pytest.raises(ValueError):
        ParallelismConfig(data=3, model=2).resolve(8)
    with pytest.raises(ValueError):
        ParallelismConfig(data=-1, model=-1)


def test_gradient_state_contract():
    gs = GradientState(GradientAccumulationPlugin(num_steps=4))
    assert gs.num_steps == 4
    assert gs.sync_gradients is True
    assert gs.end_of_dataloader is False
    assert gs.remainder == -1

    class FakeDL:
        end_of_dataloader = True
        remainder = 3

    dl = FakeDL()
    gs._add_dataloader(dl)
    assert gs.in_dataloader
    assert gs.end_of_dataloader is True
    assert gs.remainder == 3
    gs._remove_dataloader(dl)
    assert not gs.in_dataloader


def test_state_reset():
    PartialState()
    assert PartialState().initialized
    PartialState._reset_state()
    assert PartialState._shared_state == {}

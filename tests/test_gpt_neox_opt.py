"""GPT-NeoX and OPT model families: training through the Accelerator, KV-cache
decode parity, HF interchange round-trips, transformers forward parity, and the
LayeredApply streaming protocol — completing the reference's big-model-inference
benchmark table (GPT-J ✓, GPT-NeoX-20B benchmarks/README.md:33, OPT-30B :36)."""

import numpy as np
import pytest

import jax.numpy as jnp

from accelerate_tpu.models.gpt_neox import (
    GPTNeoXLayeredApply,
    create_gpt_neox_model,
    gpt_neox_tiny,
)
from accelerate_tpu.models.opt import OPTLayeredApply, create_opt_model, opt_tiny
from accelerate_tpu.utils.hf_loading import convert_hf_state_dict, export_hf_state_dict

FAMILIES = {
    "gpt_neox": (create_gpt_neox_model, gpt_neox_tiny, GPTNeoXLayeredApply),
    "opt": (create_opt_model, opt_tiny, OPTLayeredApply),
}


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_training_decreases_loss(family):
    import optax

    from accelerate_tpu import Accelerator

    create, tiny, _ = FAMILIES[family]
    accelerator = Accelerator()
    model = create(tiny(), seq_len=16)
    pmodel, popt = accelerator.prepare(model, optax.adamw(1e-3))
    step = accelerator.train_step()
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(1, 512, (8, 16)).astype(np.int32)}
    first = float(step(batch))
    for _ in range(10):
        last = float(step(batch))
    assert last < first


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_cached_greedy_matches_full_context(family):
    from accelerate_tpu.generation import generate

    create, tiny, _ = FAMILIES[family]
    cfg = tiny()
    model = create(cfg, seq_len=32)
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, cfg.vocab_size, (2, 8)).astype(np.int32)
    out = np.asarray(generate(model, prompt, max_new_tokens=6))

    ctx = prompt.copy()
    for _ in range(6):
        logits = np.asarray(model.apply_fn(model.params, jnp.asarray(ctx, jnp.int32)))
        nxt = logits[:, -1, :].argmax(-1).astype(np.int32)
        ctx = np.concatenate([ctx, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, ctx)


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_hf_round_trip_preserves_logits(family):
    create, tiny, _ = FAMILIES[family]
    cfg = tiny()
    model = create(cfg, seq_len=16)
    ids = jnp.asarray(np.random.default_rng(2).integers(1, cfg.vocab_size, (2, 16)), jnp.int32)
    ref = np.asarray(model.apply_fn(model.params, ids))

    flat = export_hf_state_dict(model.params, family, cfg)
    params2 = convert_hf_state_dict(flat, family, cfg)
    out = np.asarray(model.apply_fn(params2, ids))
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_layered_apply_matches_monolithic(family):
    create, tiny, layered_cls = FAMILIES[family]
    cfg = tiny()
    model = create(cfg, seq_len=16)
    layered = layered_cls(cfg)
    ids = jnp.asarray(np.random.default_rng(4).integers(1, cfg.vocab_size, (2, 16)), jnp.int32)
    ref = np.asarray(model.apply_fn(model.params, ids))

    prelude, layers, tail = layered.split(model.params)
    assert len(layers) == cfg.num_hidden_layers
    carry = layered.apply_prelude(prelude, ids)
    for lp in layers:
        carry = layered.apply_layer(lp, carry)
    out = np.asarray(layered.apply_tail(tail, carry))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    rejoined = layered.join(prelude, layers, tail)
    out2 = np.asarray(model.apply_fn(rejoined, ids))
    np.testing.assert_array_equal(out2, ref)


def test_real_transformers_gpt_neox_matches():
    """Forward parity vs HF GPTNeoXForCausalLM: pins the dual-norm parallel
    residual, half-split partial rotary, fused-QKV interchange layout, and exact
    (erf) gelu."""
    transformers = pytest.importorskip("transformers")
    import torch

    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_hidden_layers=2,
        num_attention_heads=4,
        rotary_pct=0.25,
        max_position_embeddings=256,
        use_parallel_residual=True,
        layer_norm_eps=1e-5,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf_model = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    flat = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    cfg = gpt_neox_tiny()
    params = convert_hf_state_dict(flat, "gpt_neox", cfg)
    model = create_gpt_neox_model(cfg, seq_len=16)

    ids_np = np.random.default_rng(3).integers(1, 512, (2, 16))
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(ids_np)).logits.numpy()
    out = np.asarray(model.apply_fn(params, jnp.asarray(ids_np, jnp.int32)))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_real_transformers_opt_matches():
    """Forward parity vs HF OPTForCausalLM: pins pre-LN ordering, the +2 learned
    position offset, ReLU, and the tied lm_head."""
    transformers = pytest.importorskip("transformers")
    import torch

    hf_cfg = transformers.OPTConfig(
        vocab_size=512,
        hidden_size=128,
        ffn_dim=256,
        num_hidden_layers=2,
        num_attention_heads=4,
        max_position_embeddings=256,
        do_layer_norm_before=True,
        dropout=0.0,
        attention_dropout=0.0,
        activation_function="relu",
        word_embed_proj_dim=128,
        tie_word_embeddings=True,
    )
    torch.manual_seed(0)
    hf_model = transformers.OPTForCausalLM(hf_cfg).eval()
    flat = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    cfg = opt_tiny()
    params = convert_hf_state_dict(flat, "opt", cfg)
    model = create_opt_model(cfg, seq_len=16)

    ids_np = np.random.default_rng(3).integers(1, 512, (2, 16))
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(ids_np)).logits.numpy()
    out = np.asarray(model.apply_fn(params, jnp.asarray(ids_np, jnp.int32)))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_registry_entries():
    from accelerate_tpu.models import get_model_config

    assert get_model_config("gpt-neox-20b")["hidden_size"] == 6144
    assert get_model_config("opt-30b")["hidden_size"] == 7168

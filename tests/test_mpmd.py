"""The MPMD pipeline runtime (parallel/mpmd.py) + the 3D ("data", "model",
"pipeline") planner dispatch: planner-emitted NON-uniform stage plans finally
have an executor.

The acceptance pins:

  - **end-to-end 3D** — `Accelerator.prepare(sharding_rules="auto")` on a
    ("data", "model", "pipeline") CPU mesh plans a non-uniform [2, 3] stage
    assignment (5 layers, 2 stages), places it, and trains at loss parity
    (drift ≤ 2e-4) with the 2D auto baseline on llama AND gpt_neox — the
    1F1B schedule, GPipe recompute, and per-microbatch grad accumulation
    must not change the math;
  - **compiled once, device-resident** — every stage program (forward,
    split, backward, optimizer update, zero, finalize) holds exactly ONE
    cache entry after the steady state, and TraceGuard records 0 recompiles
    / 0 host transfers around the stepping loop (stage handoffs are pure d2d
    `device_put`s between submeshes);
  - **predicted-vs-live** — the plan's busiest-stage per-chip param/opt
    bytes match the runtime's live shardings;
  - **byte balance beats count balance** — a deliberately imbalanced
    layer-bytes model splits off-center (the equal-count split is only the
    special case where every layer weighs the same);
  - **bubble term** — `pipeline_bubble_terms` recovers the classic
    (P-1)/(M+P-1) for uniform stages, grows under imbalance, and rides
    `MPMDTrainPlan.to_json()["pipeline"]` into the plan CLI;
  - **3D search** — `search_train_meshes` over the full axis product finds a
    pipeline mesh that matches-or-beats the best 2D mesh on modeled step
    time for a flop-dominated workload (the cpu-smoke chip);
  - **unsupported shapes fail loudly** — tied embeddings and families
    without a LayeredApply raise at prepare time, not mid-schedule.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

import jax

from accelerate_tpu.models.gpt_neox import GPTNeoXConfig, create_gpt_neox_model
from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
from accelerate_tpu.parallel.planner import (
    CHIPS,
    default_num_microbatches,
    pipeline_bubble_terms,
    plan_mpmd_train_sharding,
    plan_train_sharding,
    search_train_meshes,
)

pytestmark = pytest.mark.planner

needs_mesh8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs an 8-device mesh (forced CPU devices)"
)

SEQ = 16
BATCH = 8


def _llama5() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=5,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )


def _gpt_neox5() -> GPTNeoXConfig:
    return GPTNeoXConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=5,
        num_attention_heads=4,
        max_position_embeddings=64,
    )


#: family key -> (5-layer config factory, bundle creator). Five layers over
#: two pipeline stages force the NON-uniform [2, 3] assignment — the shape
#: the SPMD stage runner rejects and this runtime exists to execute.
FAMILIES = {
    "llama": (_llama5, create_llama_model),
    "gpt_neox": (_gpt_neox5, create_gpt_neox_model),
}


def _reset_state():
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _run_training(family, mode, *, steps=3):
    """One end-to-end pass through Accelerator.prepare + train_step on either
    the 2D auto mesh ("2d": data=4, model=2) or the 3D MPMD mesh ("3d":
    data=2, model=2, pipeline=2). Returns (losses, model, accelerator, guard)."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.parallel.sharding import data_spec
    from accelerate_tpu.utils import ParallelismConfig, set_seed
    from jax.sharding import NamedSharding

    _reset_state()
    set_seed(0)
    cfg_factory, create = FAMILIES[family]
    cfg = cfg_factory()
    bundle = create(cfg, seq_len=SEQ)
    bundle.sharding_rules = "auto"
    if mode == "3d":
        pcfg = ParallelismConfig(data=2, model=2, pipeline=2)
    else:
        pcfg = ParallelismConfig(data=-1, model=2)
    accelerator = Accelerator(parallelism_config=pcfg)
    model, opt = accelerator.prepare(bundle, optax.adam(1e-3))

    rng = np.random.default_rng(0)
    sharding = NamedSharding(accelerator.mesh, data_spec(accelerator.mesh))
    batches = [
        jax.device_put(
            {"input_ids": rng.integers(0, cfg.vocab_size, (BATCH, SEQ)).astype(np.int32)},
            sharding,
        )
        for _ in range(1 + steps)
    ]
    step_fn = accelerator.train_step()
    jax.block_until_ready(step_fn(batches[0]))  # warmup / compile

    guard = TraceGuard(name=f"mpmd-{family}-{mode}", on_violation="record")
    raw = []
    with guard:
        for batch in batches[1:]:
            raw.append(step_fn(batch))
        jax.block_until_ready(raw[-1])
    return [float(l) for l in raw], model, accelerator, guard


# ------------------------------------------------------------- end to end 3D
@needs_mesh8
@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_prepare_auto_3d_nonuniform_trains_at_parity(family):
    """The ISSUE's acceptance path end-to-end: prepare(sharding_rules="auto")
    on a 3-axis mesh routes through the MPMD planner + runtime, executes the
    NON-uniform [2, 3] plan, and matches the 2D baseline's loss trajectory
    with 0 recompiles / 0 host transfers and every stage program compiled
    exactly once."""
    losses_2d, _, _, guard_2d = _run_training(family, "2d")
    losses_3d, model, _, guard_3d = _run_training(family, "3d")

    assert getattr(model, "is_mpmd", False)
    counts = [
        model.plan.stage_plan.assignment.count(s)
        for s in range(model.plan.num_stages)
    ]
    assert sorted(counts) == [2, 3], counts  # non-uniform, the point of MPMD

    for guard, tag in ((guard_2d, "2d"), (guard_3d, "3d")):
        assert guard.total_recompiles == 0, (tag, guard.report().summary())
        assert guard.host_transfers == 0, (tag, guard.transfer_violations)

    drift = max(abs(a - b) for a, b in zip(losses_2d, losses_3d))
    assert drift <= 2e-4, (losses_2d, losses_3d)

    # Compiled-once-per-stage pin: 1F1B re-dispatches the SAME executables
    # every microbatch and every step.
    counts_by_program = model.compiled_program_counts()
    assert counts_by_program and all(
        n == 1 for n in counts_by_program.values()
    ), counts_by_program

    # Predicted-vs-live: busiest-stage per-chip bytes off the live shardings.
    live = model.live_per_chip_bytes()
    predicted = model.plan.cost
    assert (
        abs(predicted.per_chip_param_bytes - live["per_chip_param_bytes"])
        / live["per_chip_param_bytes"]
        <= 0.01
    ), (predicted.per_chip_param_bytes, live)
    assert (
        abs(predicted.per_chip_opt_bytes - live["per_chip_opt_bytes"])
        / live["per_chip_opt_bytes"]
        <= 0.01
    ), (predicted.per_chip_opt_bytes, live)


@needs_mesh8
def test_prepare_auto_3d_rejects_unsupported_models():
    """Unsupported shapes fail at PREPARE time with an error naming the fix:
    tied embeddings would span the first and last submeshes (NotImplemented,
    points at the SPMD runner), and a family without a LayeredApply (mixtral)
    can't byte-balance layers at all (ValueError from layered_for_model)."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models.mixtral import create_mixtral_model, mixtral_tiny
    from accelerate_tpu.utils import ParallelismConfig, set_seed

    _reset_state()
    set_seed(0)
    import dataclasses

    tied = dataclasses.replace(_llama5(), tie_word_embeddings=True)
    bundle = create_llama_model(tied, seq_len=SEQ)
    bundle.sharding_rules = "auto"
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(data=2, model=2, pipeline=2)
    )
    with pytest.raises(NotImplementedError, match="[Tt]ied"):
        accelerator.prepare(bundle, optax.adam(1e-3))

    _reset_state()
    set_seed(0)
    moe = create_mixtral_model(mixtral_tiny(), seq_len=SEQ)
    moe.sharding_rules = "auto"
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(data=2, model=2, pipeline=2)
    )
    with pytest.raises(ValueError, match="LayeredApply"):
        accelerator.prepare(moe, optax.adam(1e-3))


# --------------------------------------------------------------- planner 3D
def _synthetic_layers(byte_factors, hidden=64):
    """prelude/layers/tail numpy trees where layer i's weight bytes scale by
    byte_factors[i] — the shape the byte-balanced partition must see through."""
    z = lambda *shape: np.zeros(shape, np.float32)
    prelude = {"params": {"embed_tokens": {"embedding": z(256, hidden)}}}
    layers = [
        {"params": {"mlp": {"kernel": z(hidden, hidden * f)}}} for f in byte_factors
    ]
    tail = {"params": {"final_norm": {"scale": z(hidden)}, "lm_head": {"kernel": z(hidden, 256)}}}
    return prelude, layers, tail


def test_mpmd_plan_balances_bytes_not_counts():
    """A deliberately imbalanced layer-bytes model: one layer 8x the rest.
    The byte-balanced assignment isolates the heavy layer instead of
    splitting 3/3, and per-stage bytes come out closer to even than the
    equal-count split would. Planned on an abstract {axis: size} mesh — no
    devices needed."""
    prelude, layers, tail = _synthetic_layers([8, 1, 1, 1, 1, 1])
    plan = plan_mpmd_train_sharding(
        prelude, layers, tail,
        {"data": 2, "model": 2, "pipeline": 2},
        batch=BATCH, seq=SEQ,
    )
    counts = [plan.stage_plan.assignment.count(s) for s in range(2)]
    assert counts == [1, 5], counts  # the heavy layer rides alone
    assert plan.stage_plan.imbalance < 8 / 2  # far better than count-balance
    # The per-stage rules tables target the stage-tree paths the runtime
    # places (layer_<i> / prelude / tail), one table per stage.
    assert len(plan.stages) == 2
    assert plan.stage_rules(0) and plan.stage_rules(1)


def test_bubble_terms_uniform_recovers_classic_and_imbalance_grows_it():
    P, M = 4, 8
    wall, bubble = pipeline_bubble_terms([1.0] * P, M)
    assert wall == pytest.approx(M + P - 1)
    assert bubble == pytest.approx((P - 1) / (M + P - 1))
    _, skewed = pipeline_bubble_terms([1.0, 1.0, 1.0, 2.0], M)
    assert skewed > bubble  # every stage paces on the slowest
    # The p2p hop that does not hide under compute stretches the wall.
    wall_p2p, _ = pipeline_bubble_terms([1.0] * P, M, p2p_time_s=3.0)
    assert wall_p2p == pytest.approx(wall + 3.0)
    assert default_num_microbatches(8, 2) == 4  # largest divisor <= 2P


def test_mpmd_plan_json_carries_bubble_account():
    prelude, layers, tail = _synthetic_layers([1] * 5)
    plan = plan_mpmd_train_sharding(
        prelude, layers, tail,
        {"data": 2, "model": 2, "pipeline": 2},
        batch=BATCH, seq=SEQ,
    )
    payload = plan.to_json()
    pipe = payload["pipeline"]
    assert pipe["num_stages"] == 2 and pipe["num_layers"] == 5
    assert sorted(pipe["stage_layer_counts"]) == [2, 3]
    assert 0.0 <= pipe["bubble_fraction"] < 1.0
    assert pipe["p2p_bytes_per_microbatch"] > 0
    assert pipe["num_microbatches"] == default_num_microbatches(BATCH, 2)
    assert len(payload["stages"]) == 2
    assert payload["predicted"]["step_time_s"] > 0
    json.dumps(payload)  # the CLI embeds this verbatim


def _tp_walled_model(layers=8, dim=250):
    """A model tensor parallelism can't scale: every matmul dim is 2·odd, so
    TP shards by 2 and then hits the divisibility wall — model=4/8 candidates
    leave the big leaves replicated and their per-chip flop account high.
    Pipeline stages keep cutting per-chip parameters where TP can't, which is
    exactly the regime the 3D search exists to find (AMP, arXiv:2210.07297)."""
    z = lambda *shape: np.zeros(shape, np.float32)
    prelude = {"params": {"embed_tokens": {"embedding": z(2 * 127, dim)}}}
    layer_list = [
        {"params": {"mlp": {"kernel": z(dim, dim)}}} for _ in range(layers)
    ]
    tail = {"params": {"lm_head": {"kernel": z(dim, 2 * 127)}}}
    full = {"params": dict(prelude["params"])}
    for i, lp in enumerate(layer_list):
        full["params"][f"layer_{i}"] = lp["params"]
    full["params"].update(tail["params"])
    return full, (prelude, layer_list, tail)


@needs_mesh8
def test_search_train_meshes_3d_matches_or_beats_2d():
    """The AMP-style product search acceptance: for a flop-dominated workload
    whose dims stop TP at degree 2 (every matmul dim 2·odd), the pipeline
    axis keeps cutting per-chip parameters where "model" can't — the best 3D
    candidate's modeled step time beats the best 2D mesh, and the 1F1B
    bubble term is priced in when it does."""
    params, layered_split = _tp_walled_model()
    results = search_train_meshes(
        params,
        jax.devices()[:8],
        batch=BATCH,
        seq=SEQ,
        layered_split=layered_split,
        chip=CHIPS["cpu-smoke"],
    )
    assert results, "search emitted no candidate meshes"
    two_d = [p for axes, p in results if axes["pipeline"] == 1]
    three_d = [p for axes, p in results if axes["pipeline"] > 1]
    assert two_d and three_d, [axes for axes, _ in results]
    best_2d = min(p.cost.step_time_s for p in two_d)
    best_3d = min(p.cost.step_time_s for p in three_d)
    assert best_3d <= best_2d, (best_3d, best_2d)
    # The winning 3D plan still carries its bubble honestly (> 0).
    winner = min(three_d, key=lambda p: p.cost.step_time_s)
    assert winner.bubble_fraction > 0.0
    # Ranking is by modeled total cost, best first.
    costs = [p.cost.total for _, p in results]
    assert costs == sorted(costs)


def test_plan_train_sharding_pipeline_needs_layered_split():
    with pytest.raises(ValueError, match="layered_split"):
        plan_train_sharding(
            {"params": {"w": np.zeros((8, 8), np.float32)}},
            {"data": 2, "pipeline": 2},
            batch=BATCH,
            seq=SEQ,
        )


# ------------------------------------------------------------------ CLI seam
@needs_mesh8
def test_plan_cli_train_mesh_pipeline_json(capsys):
    """`accelerate-tpu plan <model> --mesh data=2,model=2,pipeline=2 --json
    --live`: the payload carries the pipeline block (stages, bubble, p2p),
    one rules table per stage, and live busiest-stage bytes matching the
    prediction."""
    from accelerate_tpu.commands.accelerate_cli import get_command_parser

    parser = get_command_parser()
    args = parser.parse_args(
        ["plan", "llama-tiny", "--mesh", "data=2,model=2,pipeline=2",
         "--batch", str(BATCH), "--seq-len", str(SEQ), "--json", "--live"]
    )
    payload = args.func(args)
    out = json.loads(capsys.readouterr().out)
    assert out["mesh"] == {"data": 2, "model": 2, "pipeline": 2}
    pipe = out["plan"]["pipeline"]
    assert pipe["num_stages"] == 2
    assert 0.0 <= pipe["bubble_fraction"] < 1.0
    assert len(out["plan"]["stages"]) == 2
    # llama-tiny (2 layers, 2 stages) splits uniformly; the hand-table
    # comparison is absent (no hand-written 3D table exists to lose to).
    assert "hand_rules" not in out
    for tree in ("params", "grads", "opt_state"):
        row = out["live"][tree]
        assert row["error_pct"] <= 1.0, (tree, row)
    # The returned payload is the same object the CLI printed (modulo JSON
    # tuple->list coercion on the rules tables).
    assert payload["mesh"] == out["mesh"]
    assert payload["plan"]["pipeline"] == out["plan"]["pipeline"]


@needs_mesh8
def test_plan_cli_refine_times_train_step(capsys):
    """`--refine-top-k` on a training mesh times the fused train-step twin
    (grads + optimizer update), not the one-token forward: measurements come
    back positive and the refine is recorded in the payload."""
    from accelerate_tpu.commands.accelerate_cli import get_command_parser

    parser = get_command_parser()
    args = parser.parse_args(
        ["plan", "llama-tiny", "--mesh", "data=2,model=2",
         "--batch", str(BATCH), "--seq-len", str(SEQ),
         "--refine-top-k", "2", "--json"]
    )
    args.func(args)
    out = json.loads(capsys.readouterr().out)
    seconds = out["refine_measurements_s"]
    assert 1 <= len(seconds) <= 2
    assert all(s > 0 for s in seconds)


# --------------------------------------------------- review regression pins
@needs_mesh8
def test_train_step_rejects_batch_indivisible_by_microbatches():
    """A global batch that isn't a multiple of the plan's num_microbatches
    must FAIL, not silently drop the remainder rows (rows % M != 0) or run
    zero-row microbatches (rows < M: loss_sum=0, weight=0 — a no-op step
    with no error)."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.parallel.sharding import data_spec
    from accelerate_tpu.utils import ParallelismConfig, set_seed
    from jax.sharding import NamedSharding

    _reset_state()
    set_seed(0)
    bundle = create_llama_model(_llama5(), seq_len=SEQ)
    bundle.sharding_rules = "auto"
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(data=2, model=2, pipeline=2)
    )
    model, _ = accelerator.prepare(bundle, optax.adam(1e-3))
    M = model.num_microbatches
    assert M > 1  # the guard below must actually bite

    rng = np.random.default_rng(0)
    sharding = NamedSharding(accelerator.mesh, data_spec(accelerator.mesh))
    step_fn = accelerator.train_step()

    def batch_of(rows):
        return jax.device_put(
            {"input_ids": rng.integers(0, 256, (rows, SEQ)).astype(np.int32)}, sharding
        )

    with pytest.raises(ValueError, match="num_microbatches"):
        step_fn(batch_of(M + 2))  # rows % M != 0: would drop rows
    with pytest.raises(ValueError, match="num_microbatches"):
        step_fn(batch_of(2))  # rows < M: would run empty microbatches


@needs_mesh8
def test_prepare_sizes_microbatches_from_coprepared_dataloader():
    """prepare(model, opt, dataloader) peeks at the loader's batch size BEFORE
    planning, so the MPMD microbatch schedule divides the batch the user will
    actually feed — not the hardcoded planning default of 8."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.parallel.sharding import data_spec
    from accelerate_tpu.utils import ParallelismConfig, set_seed
    from jax.sharding import NamedSharding

    _reset_state()
    set_seed(0)
    bundle = create_llama_model(_llama5(), seq_len=SEQ)
    bundle.sharding_rules = "auto"
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(data=2, model=2, pipeline=2)
    )
    rng = np.random.default_rng(0)
    rows = 12  # NOT a multiple of the old hardcoded planning batch's M=4
    dataset = [
        {"input_ids": rng.integers(0, 256, (SEQ,)).astype(np.int32)} for _ in range(rows * 2)
    ]
    loader = SimpleDataLoader(dataset, BatchSampler(range(len(dataset)), batch_size=rows))
    model, _, _ = accelerator.prepare(bundle, optax.adam(1e-3), loader)

    # workload.batch is the per-microbatch size; M * it is the planned global batch.
    assert model.num_microbatches * model.plan.workload.batch == rows
    assert rows % model.num_microbatches == 0
    sharding = NamedSharding(accelerator.mesh, data_spec(accelerator.mesh))
    batch = jax.device_put(
        {"input_ids": rng.integers(0, 256, (rows, SEQ)).astype(np.int32)}, sharding
    )
    step_fn = accelerator.train_step()
    assert np.isfinite(float(step_fn(batch)))


@needs_mesh8
def test_eval_forward_keeps_training_programs_compiled_once():
    """Eval pushes the FULL batch while training pushes microbatch shapes —
    the eval path must use its own eval_fwd{k} programs, or every shared
    fwd{k} grows a second cache entry (breaking the compiled-once audit and
    reading as recompiles under an armed TraceGuard)."""
    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.parallel.sharding import data_spec
    from jax.sharding import NamedSharding

    losses, model, accelerator, _ = _run_training("llama", "3d", steps=1)
    rng = np.random.default_rng(1)
    sharding = NamedSharding(accelerator.mesh, data_spec(accelerator.mesh))
    batch = jax.device_put(
        {"input_ids": rng.integers(0, 256, (BATCH, SEQ)).astype(np.int32)}, sharding
    )
    logits = model(batch)  # compiles eval_fwd{k}, shapes now warm
    assert logits.shape[0] == BATCH

    guard = TraceGuard(name="mpmd-eval-interleave", on_violation="record")
    step_fn = accelerator.train_step()
    with guard:
        step_fn(batch)
        out = model(batch)  # eval interleaved with training
        jax.block_until_ready(out)
    assert guard.total_recompiles == 0, guard.report().summary()

    counts = model.compiled_program_counts()
    assert any(name.startswith("eval_fwd") for name in counts), counts
    assert all(n == 1 for n in counts.values()), counts


@needs_mesh8
def test_optimizer_single_mesh_surface_rejected_on_mpmd():
    """The wrapper holds NO single-mesh opt_state on the MPMD route (it lives
    per stage, owned by the model) — step()/clipping/state accessors must
    raise the clear pointer at Accelerator.train_step(), not fail deep inside
    the update machinery on opt_state=None."""
    _, _, accelerator, _ = _run_training("llama", "3d", steps=1)
    (opt,) = accelerator._optimizers
    assert opt.is_mpmd and opt.opt_state is None
    for call in (
        opt.step,
        lambda: opt.accumulate_grads({}),
        lambda: opt.clip_grad_norm_(1.0),
        lambda: opt.clip_grad_value_(1.0),
        opt.state_dict,
        lambda: opt.load_state_dict({}),
        lambda: opt.set_learning_rate(1e-4),
    ):
        with pytest.raises(NotImplementedError, match="train_step"):
            call()


@needs_mesh8
def test_prepare_mpmd_threads_bf16_and_rejects_fsdp():
    """Accelerator settings the 2D route honors must not be dropped silently:
    mixed_precision='bf16' threads compute_dtype into the stage programs (the
    step runs and params stay full precision), and an fsdp_plugin — which has
    no per-stage twin — is rejected loudly at prepare time."""
    import jax.numpy as jnp
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.parallel.sharding import data_spec
    from accelerate_tpu.utils import (
        FullyShardedDataParallelPlugin,
        ParallelismConfig,
        set_seed,
    )
    from jax.sharding import NamedSharding

    _reset_state()
    set_seed(0)
    bundle = create_llama_model(_llama5(), seq_len=SEQ)
    bundle.sharding_rules = "auto"
    accelerator = Accelerator(
        mixed_precision="bf16",
        parallelism_config=ParallelismConfig(data=2, model=2, pipeline=2),
    )
    model, _ = accelerator.prepare(bundle, optax.adam(1e-3))
    assert model.autocast_enabled and model.compute_dtype == jnp.bfloat16
    rng = np.random.default_rng(0)
    sharding = NamedSharding(accelerator.mesh, data_spec(accelerator.mesh))
    batch = jax.device_put(
        {"input_ids": rng.integers(0, 256, (BATCH, SEQ)).astype(np.int32)}, sharding
    )
    step_fn = accelerator.train_step()
    assert np.isfinite(float(step_fn(batch)))
    # Master params stay full precision; only the stage compute casts.
    leaves = jax.tree_util.tree_leaves(model.stage_params[0])
    assert all(l.dtype != jnp.bfloat16 for l in leaves if jnp.issubdtype(l.dtype, jnp.floating))

    _reset_state()
    set_seed(0)
    bundle = create_llama_model(_llama5(), seq_len=SEQ)
    bundle.sharding_rules = "auto"
    accelerator = Accelerator(
        parallelism_config=ParallelismConfig(data=2, model=2, pipeline=2),
        fsdp_plugin=FullyShardedDataParallelPlugin(min_num_params=1),
    )
    with pytest.raises(NotImplementedError, match="fsdp"):
        accelerator.prepare(bundle, optax.adam(1e-3))


def test_plan_cli_pipeline_refine_rejected():
    """--refine-top-k times single-mesh plans; combining it with a pipeline
    mesh points at the bench A/B instead of silently measuring nothing."""
    from accelerate_tpu.commands.accelerate_cli import get_command_parser

    parser = get_command_parser()
    args = parser.parse_args(
        ["plan", "llama-tiny", "--mesh", "data=2,model=2,pipeline=2",
         "--refine-top-k", "2", "--json"]
    )
    with pytest.raises(SystemExit, match="pipeline-ab"):
        args.func(args)

"""The sharding-strategy planner (parallel/planner.py): the cost-model search
that replaces the hand-written partition tables as the SOURCE of sharding
decisions (`sharding_rules="auto"`), with the family tables demoted to parity
oracles.

The acceptance pins:

  - **legality** — every candidate spec the enumerator returns passes the
    same `_check_tp_divisible` gate placement enforces (a planner choice can
    never hit the indivisible-rule hard error);
  - **cost-model sanity** — per-chip bytes never exceed the replicated
    footprint, and modeled cost is non-increasing in mesh size for nets whose
    dims shard cleanly;
  - **planner-vs-hand parity** — on llama + gpt_neox at tp in {2, 4} the auto
    plan matches or beats the hand tables on modeled cost, and the auto
    ENGINE reproduces hand-rule greedy tokens exactly at 0 recompiles /
    0 host transfers with decode compiled once;
  - **round-trip** — the emitted rules table feeds
    `derive_tp_param_shardings` unchanged, and predicted per-chip bytes match
    the live `tree_device_nbytes` within 10% on the forced CPU mesh;
  - **measure-and-refine** — `refine_plans` returns the measured-best of the
    top-k candidates (cost model proposes, hardware disposes).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from accelerate_tpu.models.gpt_neox import (
    GPT_NEOX_SHARDING_RULES,
    GPTNeoXConfig,
    create_gpt_neox_model,
)
from accelerate_tpu.models.llama import LLAMA_SHARDING_RULES, LlamaConfig, create_llama_model
from accelerate_tpu.parallel.planner import (
    Workload,
    candidate_specs,
    emit_rules,
    measure_forward_step,
    plan_serving_sharding,
    plan_sharding,
    refine_plans,
    resolve_sharding_rules,
    score_rules,
)
from accelerate_tpu.parallel.sharding import (
    _check_tp_divisible,
    derive_tp_param_shardings,
    serving_tp_mesh,
    tree_device_nbytes,
    tree_paths_and_leaves,
)
from accelerate_tpu.serving import ContinuousBatcher, Request

pytestmark = pytest.mark.planner

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs a >= 4-device mesh (forced CPU devices)"
)


def tiny_llama():
    return create_llama_model(
        LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, rope_theta=10000.0,
        ),
        seq_len=32,
    )


def tiny_neox():
    return create_gpt_neox_model(
        GPTNeoXConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64,
        ),
        seq_len=32,
    )


_MODELS = {"llama": (tiny_llama, LLAMA_SHARDING_RULES), "gpt_neox": (tiny_neox, GPT_NEOX_SHARDING_RULES)}
_CACHE = {}


def get_model(family):
    if family not in _CACHE:
        _CACHE[family] = _MODELS[family][0]()
    return _CACHE[family]


def make_requests(n=4, max_new=8):
    return [
        Request(i, list(range(3 + i, 10 + i)) + [2, 5, 2, 5], max_new_tokens=max_new)
        for i in range(n)
    ]


def wide_net(hidden=256, vocab=4096, inter=1024, layers=2):
    """A cleanly-shardable transformer-shaped params tree (plain numpy — the
    planner only reads shapes/dtypes), wide enough that weight bytes dominate
    activation collectives at every mesh size under test."""
    z = lambda *shape: np.zeros(shape, np.float32)
    params = {"embed_tokens": {"embedding": z(vocab, hidden)}}
    for i in range(layers):
        params[f"layer_{i}"] = {
            "attention": {
                "wq": {"kernel": z(hidden, hidden)},
                "wk": {"kernel": z(hidden, hidden)},
                "wv": {"kernel": z(hidden, hidden)},
                "wo": {"kernel": z(hidden, hidden)},
            },
            "mlp": {
                "w_up": {"kernel": z(hidden, inter)},
                "w_down": {"kernel": z(inter, hidden)},
            },
            "norm": {"scale": z(hidden)},
        }
    params["lm_head"] = {"kernel": z(hidden, vocab)}
    return {"params": params}


# ------------------------------------------------------------------ legality
@needs_mesh
def test_candidate_specs_divisibility_property():
    """Property sweep: every candidate the enumerator returns passes the
    placement-time divisibility gate; every divisible single-axis placement
    IS enumerated; 1-D leaves only replicate."""
    rng = np.random.default_rng(0)
    mesh = serving_tp_mesh(4)
    dims = [1, 2, 3, 4, 6, 8, 12, 16, 31, 64, 96]
    for _ in range(200):
        ndim = int(rng.integers(1, 4))
        shape = tuple(int(rng.choice(dims)) for _ in range(ndim))
        cands = candidate_specs("params/x/kernel", shape, mesh, axes=("model",))
        assert () in cands  # replicate is always legal
        for spec in cands:
            _check_tp_divisible("params/x/kernel", shape, spec, mesh)  # must not raise
        if ndim == 1:
            assert cands == [()]
            continue
        for dim, d in enumerate(shape):
            # Full-rank specs, trailing Nones kept: (model, None) not
            # (model,) — the quantized-scale derivation reads the LAST entry
            # as the kernel's output axis.
            expect = [None] * ndim
            expect[dim] = "model"
            if d % 4 == 0 and d >= 4:
                assert tuple(expect) in cands, (shape, dim)
            else:
                assert tuple(expect) not in cands, (shape, dim)


def test_emit_rules_suffix_grouping_and_conflicts():
    """Same-suffix leaves that agree collapse into one (^|/)suffix(/|$) rule;
    a conflicting suffix falls back to full-path rules emitted FIRST so
    first-match-wins keeps them authoritative; replicated leaves get no rule."""
    assignment = {
        "params/layer_0/attention/wq/kernel": (None, "model"),
        "params/layer_1/attention/wq/kernel": (None, "model"),
        "params/layer_0/norm/scale": (),
        "params/a/odd/kernel": ("model",),
        "params/b/odd/kernel": (),
    }
    rules = emit_rules(assignment)
    patterns = [p for p, _ in rules]
    assert "(^|/)wq/kernel(/|$)" in patterns
    assert not any("norm" in p for p in patterns)
    # the conflicting "odd/kernel" suffix: exact rule for the sharded leaf
    # only, and it precedes the grouped rules.
    assert patterns[0].startswith("^params/a/odd/kernel")
    assert not any(p == "(^|/)odd/kernel(/|$)" for p in patterns)
    # the emitted shapes feed re.search-based matching: the quantized
    # {"q","scale"} children of a kernel keep matching their kernel's rule.
    import re

    assert re.search("(^|/)wq/kernel(/|$)", "params/layer_0/attention/wq/kernel/q")


def test_resolve_sharding_rules_seam():
    mesh = {"model": 2}
    params = wide_net(hidden=32, vocab=64, inter=64, layers=1)
    rules, plan = resolve_sharding_rules("auto", params, mesh)
    assert plan is not None and rules == plan.rules and rules
    explicit = [("wq/kernel", (None, "model"))]
    assert resolve_sharding_rules(explicit, params, mesh) == (explicit, None)
    assert resolve_sharding_rules(None, params, mesh) == (None, None)
    assert resolve_sharding_rules("rules", params, mesh) == (None, None)
    with pytest.raises(ValueError, match="auto"):
        resolve_sharding_rules("magic", params, mesh)


# ---------------------------------------------------------------- cost model
def test_cost_model_bytes_and_mesh_monotonicity():
    """Per-chip bytes never exceed the replicated footprint (and land within
    [total/N, total]); modeled cost is non-increasing in mesh size for a
    cleanly-shardable net — more chips never price WORSE, because
    replicate-everything is always in the candidate set."""
    params = wide_net()
    total = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, params)
        )
    )
    costs = []
    for n in (1, 2, 4, 8):
        plan = plan_sharding(params, {"model": n}, workload=Workload(batch=4, seq=1))
        assert plan.cost.per_chip_param_bytes <= total * (1 + 1e-9)
        assert plan.cost.per_chip_param_bytes >= total / n * (1 - 1e-9)
        costs.append(plan.cost.total)
    for prev, nxt in zip(costs, costs[1:]):
        assert nxt <= prev * (1 + 1e-9), costs


def test_cost_model_prices_optimizer_state_and_kv_pool():
    params = wide_net(hidden=64, vocab=256, inter=128, layers=1)
    lean = plan_sharding(params, {"model": 2}, workload=Workload(batch=2))
    heavy = plan_sharding(
        params, {"model": 2},
        workload=Workload(batch=2, kv_pool_bytes=1 << 20, opt_bytes_per_param=8.0),
    )
    assert heavy.cost.per_chip_kv_bytes == (1 << 20) / 2
    assert heavy.cost.per_chip_opt_bytes > 0 == lean.cost.per_chip_opt_bytes
    assert heavy.cost.per_chip_total_bytes > lean.cost.per_chip_total_bytes


# ------------------------------------------------------- planner vs the hand
@pytest.mark.parametrize("family", ["llama", "gpt_neox"])
@pytest.mark.parametrize("tp", [2, 4])
def test_auto_plan_matches_or_beats_hand_rules_on_modeled_cost(family, tp):
    """The headline: on llama + gpt_neox at tp in {2,4}, the auto plan's
    modeled cost never exceeds the hand table's under the same cost model —
    and it shards at least as many leaves (no silent replication the hand
    rules would have caught). Abstract mesh: no devices needed."""
    model = get_model(family)
    hand_rules = _MODELS[family][1]
    cfg = model.module.config
    mesh = {"model": tp}
    plan = plan_serving_sharding(
        model.params, mesh, cfg,
        num_slots=2, padded_length=64, paged=True, page_size=4, num_pages=33,
    )
    hand = score_rules(model.params, mesh, hand_rules, workload=plan.workload)
    assert plan.cost.total <= hand.cost.total * (1 + 1e-9), (
        family, tp, plan.cost.total, hand.cost.total
    )
    auto_sharded = sum(1 for l in plan.leaves if l.spec)
    hand_sharded = sum(1 for l in hand.leaves if l.spec)
    assert auto_sharded >= hand_sharded, (auto_sharded, hand_sharded)


@needs_mesh
@pytest.mark.parametrize("family,tp", [("llama", 2), ("gpt_neox", 2), ("gpt_neox", 4)])
def test_auto_engine_token_parity_and_discipline(family, tp):
    """sharding_rules="auto" end to end: greedy tokens IDENTICAL to the
    hand-ruled engine (tp divides each family's KV heads in this matrix: the
    llama tiny config has 2, gpt_neox 4), ONE decode executable across mixed
    admissions, and a warm engine's steady state at 0 recompiles / 0 guarded
    host transfers."""
    from accelerate_tpu.analysis import TraceGuard

    model = get_model(family)
    hand = ContinuousBatcher(model, num_slots=2, chunk_size=4, page_size=4, tp=tp)
    base = hand.run(make_requests())
    auto = ContinuousBatcher(
        model, num_slots=2, chunk_size=4, page_size=4, tp=tp, sharding_rules="auto"
    )
    auto.warm_inserts()
    out = auto.run(make_requests())
    assert set(out) == set(base)
    for rid in base:
        assert np.array_equal(base[rid], out[rid]), (family, tp, rid)
    assert auto.trace_counts["decode_chunk"] == 1, auto.trace_counts
    with TraceGuard(name=f"planner-steady-{family}-tp{tp}") as guard:
        auto.run(
            [Request(100 + i, list(range(2 + i, 12 + i)), max_new_tokens=6) for i in range(4)]
        )
    assert guard.total_recompiles == 0 and guard.host_transfers == 0, guard.report().summary()
    assert auto.trace_counts["decode_chunk"] == 1


@needs_mesh
@pytest.mark.parametrize("weight_dtype", ["bf16", "int8"])
def test_round_trip_rules_and_predicted_bytes(weight_dtype):
    """The plan round-trip: the emitted table feeds
    `derive_tp_param_shardings` UNCHANGED and reproduces the engine's live
    placements leaf for leaf; predicted per-chip param bytes match the live
    `tree_device_nbytes` within 10% (exactly, in practice, on the CPU mesh —
    including the int8 engines, whose quantized {"q","scale"} entries the
    cost model prices explicitly)."""
    model = get_model("llama")
    engine = ContinuousBatcher(
        model, num_slots=2, chunk_size=4, page_size=4, tp=2,
        sharding_rules="auto", weight_dtype=weight_dtype,
    )
    plan = engine.sharding_plan
    assert plan is not None and plan.rules

    # emitted rules -> derive_tp_param_shardings, byte-compatible with the
    # engine's own placement (same seam, same table).
    shardings = derive_tp_param_shardings(engine.params, engine.mesh, plan.rules)
    flat_live, _ = tree_paths_and_leaves(engine.params)
    flat_derived, _ = tree_paths_and_leaves(shardings)
    for (path, leaf), (dpath, derived) in zip(flat_live, flat_derived):
        assert path == dpath
        assert leaf.sharding.spec == derived.spec, (path, leaf.sharding.spec, derived.spec)

    if weight_dtype == "int8":
        # The quantized-entry contract (PR 13/14): `q` shards like its
        # kernel; the per-output-channel `scale` follows the kernel's OUTPUT
        # dim — so the planner's row-parallel rules MUST keep their trailing
        # None ((model, None), not (model,)) or wo/w_down scales would shard.
        report = engine.tp_sharding_report()["params"]
        col = [p for p in report if p.endswith("wq/kernel/scale")]
        row = [p for p in report if p.endswith(("wo/kernel/scale", "w_down/kernel/scale"))]
        assert col and row
        for path in col:
            assert "model" in report[path], (path, report[path])
        for path in row:
            assert "model" not in report[path], (path, report[path])

    device = engine.mesh.devices.flat[0]
    live = tree_device_nbytes(engine.params, device)
    predicted = plan.cost.per_chip_param_bytes
    assert abs(predicted - live) / live <= 0.10, (predicted, live)

    # the 60%-of-ideal footprint floor the bench asserts, pinned here too.
    replicated = sum(
        int(np.prod(np.shape(l))) * np.dtype(l.dtype).itemsize
        for l in jax.tree_util.tree_leaves(engine.params)
    )
    assert replicated / live >= 1.0 + 0.6 * (2 - 1)


# ------------------------------------------------------------------ CLI seam
def test_plan_cli_text_and_json(capsys):
    """`accelerate-tpu plan` end to end (device-free eval_shape path): the
    text report carries the rules table and predictions, the --json payload
    round-trips with the auto-vs-hand comparison."""
    import json

    from accelerate_tpu.commands.accelerate_cli import get_command_parser

    parser = get_command_parser()
    args = parser.parse_args(["plan", "llama-tiny", "--tp", "2"])
    args.func(args)
    out = capsys.readouterr().out
    assert "emitted rules table" in out and "predicted per-chip HBM" in out
    assert "matches or beats" in out

    args = parser.parse_args(["plan", "gpt-neox-tiny", "--tp", "4", "--json"])
    args.func(args)
    payload = json.loads(capsys.readouterr().out)
    assert payload["auto_beats_hand"] is True
    assert payload["plan"]["rules"] and payload["plan"]["predicted"]["per_chip_param_bytes"] > 0
    assert payload["plan"]["mesh_axes"] == {"model": 4}


@needs_mesh
def test_plan_cli_refine_measures(capsys):
    """--refine-top-k on the live mesh: measurements are reported and the
    chosen plan carries a measured step time (K=1 still measures)."""
    import json

    from accelerate_tpu.commands.accelerate_cli import get_command_parser

    parser = get_command_parser()
    args = parser.parse_args(["plan", "llama-tiny", "--tp", "2", "--refine-top-k", "2", "--json"])
    args.func(args)
    payload = json.loads(capsys.readouterr().out)
    measured = payload["refine_measurements_s"]
    assert len(measured) >= 1 and all(s > 0 for s in measured)
    assert payload["plan"]["measured_step_s"] == min(measured)


@needs_mesh
def test_engine_refine_kwarg_measures_and_holds_parity():
    """ContinuousBatcher(sharding_rules="auto", sharding_refine_top_k=K):
    the engine's plan is the measured-best candidate (measured_step_s
    stamped) and decode stays token-identical to the hand-ruled engine."""
    model = get_model("llama")
    engine = ContinuousBatcher(
        model, num_slots=2, chunk_size=4, page_size=4, tp=2,
        sharding_rules="auto", sharding_refine_top_k=2,
    )
    assert engine.sharding_plan is not None
    assert engine.sharding_plan.measured_step_s is not None
    base = ContinuousBatcher(model, num_slots=2, chunk_size=4, page_size=4, tp=1).run(
        make_requests()
    )
    out = engine.run(make_requests())
    for rid in base:
        assert np.array_equal(base[rid], out[rid]), rid


# --------------------------------------------------------- measure-and-refine
def test_refine_picks_measured_best_mechanics():
    """Selection is by MEASURED time, not modeled cost: with a measure_fn
    that inverts the model's ranking, refine returns the model's worst."""
    params = wide_net(hidden=64, vocab=256, inter=128, layers=1)
    plans = plan_sharding(params, {"model": 2}, workload=Workload(batch=2), top_k=3)
    assert len(plans) >= 2
    modeled_order = sorted(range(len(plans)), key=lambda i: plans[i].cost.total)
    times = {id(p): float(len(plans) - rank) for rank, i in enumerate(modeled_order) for p in [plans[i]]}
    best, measured = refine_plans(plans, lambda p: times[id(p)])
    assert len(measured) == len(plans)
    assert best is plans[modeled_order[-1]]  # the modeled-worst measured fastest
    assert best.measured_step_s == min(t for _, t in measured)


@needs_mesh
def test_refine_measures_real_forwards_on_cpu_mesh():
    """measure-and-refine against the real backend: each top-k candidate's
    params are placed by its rules on the forced 8-device CPU mesh, a
    one-token forward compiles and times, and the returned best is the
    measured argmin."""
    model = get_model("llama")
    cfg = model.module.config
    mesh = serving_tp_mesh(2)
    plans = plan_serving_sharding(
        model.params, mesh, cfg,
        num_slots=2, padded_length=64, paged=True, page_size=4, num_pages=33,
        top_k=3,
    )
    assert len(plans) >= 2
    best, measured = refine_plans(
        plans,
        lambda p: measure_forward_step(model.apply_fn, model.params, mesh, p.rules, batch=1),
    )
    assert all(seconds > 0 for _, seconds in measured)
    assert best.measured_step_s == min(seconds for _, seconds in measured)
    assert best in plans

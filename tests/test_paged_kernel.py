"""Pallas paged-decode & block-verify kernel pins (`ops/paged_attention.py`).

CPU tier-1 coverage via Pallas interpret mode at tiny shapes (the
`ring_attention.py` pattern): the kernels that fuse the page-table gather into
the serving hot loop are pinned against the XLA gather oracle —
kernel==oracle numerics per dtype (f32 tight, bf16 tolerance-bounded), greedy
token parity through `serving.ContinuousBatcher` across page sizes / ragged
cache lengths / prefix-shared pages / speculative draft blocks, scratch-page
rows contributing exact zeros, and the decode-compiled-once discipline with
the kernel on the decode path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
from accelerate_tpu.ops.paged_attention import (
    paged_decode_attention,
    paged_verify_attention,
)
from accelerate_tpu.serving import ContinuousBatcher, Request

pytestmark = pytest.mark.kernels


# ----------------------------------------------------------------- kernel-level
def _random_pool(rng, num_pages, page_size, hkv, d, dtype=np.float32):
    k = rng.normal(size=(num_pages, page_size, hkv, d)).astype(dtype)
    v = rng.normal(size=(num_pages, page_size, hkv, d)).astype(dtype)
    return k, v


def _oracle(q, pool_k, pool_v, table, positions):
    """The XLA gather path, re-derived in numpy/f64-free f32: gather the
    slot's pages into logical order, repeat KV heads for GQA, mask
    ``cols <= positions[i, j]``, exact two-pass softmax."""
    b, s, hq, d = q.shape
    ps = pool_k.shape[1]
    hkv = pool_k.shape[2]
    L = table.shape[1] * ps
    kf = pool_k[table].reshape(b, L, hkv, d).astype(np.float32)
    vf = pool_v[table].reshape(b, L, hkv, d).astype(np.float32)
    reps = hq // hkv
    kf, vf = np.repeat(kf, reps, axis=2), np.repeat(vf, reps, axis=2)
    scores = np.einsum("bqhd,bkhd->bhqk", q.astype(np.float32), kf) / np.sqrt(d)
    cols = np.arange(L)[None, None, None, :]
    scores = np.where(cols <= positions[:, None, :, None], scores, -1e30)
    scores -= scores.max(-1, keepdims=True)
    probs = np.exp(scores)
    probs /= probs.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", probs, vf)


@pytest.mark.parametrize("page_size", [4, 8, 16])
def test_decode_kernel_matches_oracle_f32(page_size):
    """Single-query paged decode vs the gather oracle across page sizes and
    ragged cache lengths (first position, page boundaries, full window)."""
    rng = np.random.default_rng(0)
    B, Hq, Hkv, D, P = 4, 4, 2, 8, 3
    N = B * P + 1
    pool_k, pool_v = _random_pool(rng, N, page_size, Hkv, D)
    table = np.arange(1, N).reshape(B, P).astype(np.int32)
    L = P * page_size
    # Ragged lengths: pos 0 (one valid cell), a page-boundary-1, mid, full.
    pos = np.array([[0], [page_size - 1], [L // 2], [L - 1]], np.int32)
    q = rng.normal(size=(B, 1, Hq, D)).astype(np.float32)
    out = np.asarray(
        paged_decode_attention(
            jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v),
            jnp.asarray(table), jnp.asarray(pos),
        )
    )
    np.testing.assert_allclose(out, _oracle(q, pool_k, pool_v, table, pos), atol=2e-5)


def test_decode_kernel_bf16_within_tolerance():
    rng = np.random.default_rng(1)
    B, Hq, Hkv, D, P, page_size = 3, 4, 2, 8, 3, 4
    N = B * P + 1
    pool_k, pool_v = _random_pool(rng, N, page_size, Hkv, D)
    table = np.arange(1, N).reshape(B, P).astype(np.int32)
    pos = np.array([[3], [7], [11]], np.int32)
    q = rng.normal(size=(B, 1, Hq, D)).astype(np.float32)
    out = np.asarray(
        paged_decode_attention(
            jnp.asarray(q, jnp.bfloat16),
            jnp.asarray(pool_k, jnp.bfloat16),
            jnp.asarray(pool_v, jnp.bfloat16),
            jnp.asarray(table), jnp.asarray(pos),
        ).astype(jnp.float32)
    )
    expect = _oracle(q, pool_k, pool_v, table, pos)
    # bf16 inputs: ~7 bits of mantissa on the operands; accumulation is f32.
    np.testing.assert_allclose(out, expect, atol=3e-2, rtol=3e-2)


@pytest.mark.parametrize("s", [2, 4, 5])
def test_verify_kernel_matches_oracle(s):
    """Block-verify (the speculative [B, s] variant): per-query
    ``cols <= positions[i, j]`` masks across draft-block widths."""
    rng = np.random.default_rng(2)
    B, Hq, Hkv, D, P, page_size = 3, 4, 2, 8, 4, 4
    N = B * P + 1
    pool_k, pool_v = _random_pool(rng, N, page_size, Hkv, D)
    table = np.arange(1, N).reshape(B, P).astype(np.int32)
    base = np.array([0, 5, 9], np.int32)
    pos = base[:, None] + np.arange(s)[None, :].astype(np.int32)
    q = rng.normal(size=(B, s, Hq, D)).astype(np.float32)
    out = np.asarray(
        paged_verify_attention(
            jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v),
            jnp.asarray(table), jnp.asarray(pos),
        )
    )
    np.testing.assert_allclose(out, _oracle(q, pool_k, pool_v, table, pos), atol=2e-5)


def test_mha_shape_no_gqa_grouping():
    """Hq == Hkv (the gpt_neox shape, G = 1) walks the same kernel."""
    rng = np.random.default_rng(3)
    B, H, D, P, page_size = 2, 4, 8, 2, 4
    N = B * P + 1
    pool_k, pool_v = _random_pool(rng, N, page_size, H, D)
    table = np.arange(1, N).reshape(B, P).astype(np.int32)
    pos = np.array([[2], [6]], np.int32)
    q = rng.normal(size=(B, 1, H, D)).astype(np.float32)
    out = np.asarray(
        paged_decode_attention(
            jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v),
            jnp.asarray(table), jnp.asarray(pos),
        )
    )
    np.testing.assert_allclose(out, _oracle(q, pool_k, pool_v, table, pos), atol=2e-5)


def test_scratch_page_rows_contribute_zero():
    """Poison the scratch page (page 0) with huge values: outputs must not
    move — table entries past a slot's reservation point at page 0, and the
    positional mask keeps every scratch cell invisible."""
    rng = np.random.default_rng(4)
    B, Hq, Hkv, D, P, page_size = 2, 4, 2, 8, 4, 4
    N = 6
    pool_k, pool_v = _random_pool(rng, N, page_size, Hkv, D)
    # Short slots: trailing table entries at the scratch page.
    table = np.array([[1, 2, 0, 0], [3, 0, 0, 0]], np.int32)
    pos = np.array([[6], [2]], np.int32)
    q = rng.normal(size=(B, 1, Hq, D)).astype(np.float32)

    def run(pk, pv):
        return np.asarray(
            paged_decode_attention(
                jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv),
                jnp.asarray(table), jnp.asarray(pos),
            )
        )

    clean = run(pool_k, pool_v)
    poisoned_k, poisoned_v = pool_k.copy(), pool_v.copy()
    poisoned_k[0] = 1e4
    poisoned_v[0] = 1e4
    np.testing.assert_array_equal(clean, run(poisoned_k, poisoned_v))


def test_prefix_shared_pages_read_identically():
    """Two slots whose tables share the same head pages (the prefix cache's
    layout) must each read the shared content exactly as if it were private."""
    rng = np.random.default_rng(5)
    Hq, Hkv, D, P, page_size = 4, 2, 8, 3, 4
    N = 8
    pool_k, pool_v = _random_pool(rng, N, page_size, Hkv, D)
    # Rows share pages 1-2 (a cached system prompt), then diverge.
    table = np.array([[1, 2, 3], [1, 2, 4]], np.int32)
    pos = np.array([[10], [11]], np.int32)
    q = rng.normal(size=(2, 1, Hq, D)).astype(np.float32)
    out = np.asarray(
        paged_decode_attention(
            jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v),
            jnp.asarray(table), jnp.asarray(pos),
        )
    )
    np.testing.assert_allclose(out, _oracle(q, pool_k, pool_v, table, pos), atol=2e-5)


# -------------------------------------------------------------- program-level
def _tiny_config(**overrides):
    base = dict(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0,
    )
    base.update(overrides)
    return LlamaConfig(**base)


def test_verify_program_kernel_matches_xla():
    """`make_causal_programs(verify_block=True)` built over two module
    variants that differ ONLY in `decode_attention_impl`: scoring the same
    token block through the same page tables must produce matching [B, s, V]
    logits (and identical argmax — the token the accept loop consumes)."""
    import dataclasses

    from accelerate_tpu.generation import make_causal_programs

    model = create_llama_model(_tiny_config(), seq_len=16)
    num_pages = 9
    step_cfg = dataclasses.replace(
        model.module.config, decode_cache_length=16, decode_slot_cache=True,
        decode_page_size=4, decode_num_pages=num_pages,
    )
    rng = np.random.default_rng(6)
    B, s = 2, 3
    tokens = jnp.asarray(rng.integers(1, 128, (B, s)), jnp.int32)
    positions = jnp.asarray(np.broadcast_to(np.arange(s), (B, s)), jnp.int32)
    table = jnp.asarray(np.array([[1, 2, 3, 4], [5, 6, 7, 8]], np.int32))
    params = model.params if "params" in model.params else {"params": model.params}
    logits = {}
    for impl in ("xla", "pallas_paged"):
        module = type(model.module)(
            dataclasses.replace(step_cfg, decode_attention_impl=impl)
        )
        _, _, verify = make_causal_programs(
            module, lambda p: p, step_mask_operand=True, verify_block=True
        )
        cache = jax.tree_util.tree_map(
            lambda leaf: jnp.zeros(leaf.shape, leaf.dtype),
            jax.eval_shape(
                lambda p: module.apply(
                    p, tokens, table, positions, mutable=["cache"]
                )[1]["cache"],
                params,
            ),
        )
        out, _cache = jax.jit(verify)(params, cache, tokens, positions, table)
        logits[impl] = np.asarray(out)
    np.testing.assert_allclose(logits["xla"], logits["pallas_paged"], atol=2e-4)
    np.testing.assert_array_equal(
        logits["xla"].argmax(-1), logits["pallas_paged"].argmax(-1)
    )


# --------------------------------------------------------------- engine-level
def _mixed_requests(rng, n, vocab=128, prompt_lo=3, prompt_hi=20, new_lo=2, new_hi=10):
    return [
        Request(
            i,
            rng.integers(1, vocab, (int(rng.integers(prompt_lo, prompt_hi)),)).astype(np.int32),
            max_new_tokens=int(rng.integers(new_lo, new_hi)),
        )
        for i in range(n)
    ]


def _run_engine(model, requests, **kwargs):
    engine = ContinuousBatcher(model, max_queue=len(requests) + 2, **kwargs)
    results = engine.run(
        [Request(r.request_id, r.input_ids, max_new_tokens=r.max_new_tokens) for r in requests]
    )
    return engine, {rid: list(map(int, toks)) for rid, toks in results.items()}


@pytest.mark.parametrize("page_size", [4, 8, 16])
def test_engine_greedy_token_parity_across_page_sizes(page_size):
    """The serving pin: greedy outputs through `ContinuousBatcher` are
    token-IDENTICAL (f32) between the kernel path and the XLA oracle, across
    page sizes and ragged prompt/budget mixes — and the kernel-path decode
    still compiles exactly once across mixed admissions."""
    model = create_llama_model(_tiny_config(), seq_len=32)
    rng = np.random.default_rng(7)
    requests = _mixed_requests(rng, 6)
    common = dict(num_slots=2, max_length=64, chunk_size=4, page_size=page_size)
    _, xla_tokens = _run_engine(model, requests, attention_impl="xla", **common)
    engine, kernel_tokens = _run_engine(
        model, requests, attention_impl="pallas_paged", **common
    )
    assert kernel_tokens == xla_tokens
    assert engine.trace_counts["decode_chunk"] == 1
    assert engine.attention_impl == "pallas_paged"
    assert engine.stats["attention_impl"] == "pallas_paged"


def test_engine_parity_with_prefix_cache_hits():
    """Prefix-shared pages on the kernel path: the second wave of requests
    reuses the first wave's registered system-prompt pages (prefix hits > 0)
    and still matches the oracle token-for-token."""
    model = create_llama_model(_tiny_config(), seq_len=32)
    rng = np.random.default_rng(8)
    system = rng.integers(1, 128, (9,)).astype(np.int32)
    # Two waves over the same shared system prompt: wave 1 registers its
    # pages, wave 2 hits them. Prompts fixed up front so both impls serve
    # byte-identical traffic.
    waves = [
        [
            np.concatenate([system, rng.integers(1, 128, (3 + i,)).astype(np.int32)])
            for i in range(4)
        ]
        for _ in range(2)
    ]
    tokens = {}
    engines = {}
    for impl in ("xla", "pallas_paged"):
        engine = ContinuousBatcher(
            model, num_slots=2, max_length=64, chunk_size=4, page_size=4,
            attention_impl=impl, max_queue=16,
        )
        out = {}
        for w, prompts in enumerate(waves):
            out.update(
                engine.run(
                    [Request(w * 4 + i, p, max_new_tokens=5) for i, p in enumerate(prompts)]
                )
            )
        tokens[impl] = {k: list(map(int, v)) for k, v in out.items()}
        engines[impl] = engine
    assert tokens["pallas_paged"] == tokens["xla"]
    stats = engines["pallas_paged"].stats
    assert stats["prefix_cache"]["hits"] > 0, "prefix path never exercised"
    assert engines["pallas_paged"].trace_counts["decode_chunk"] == 1


def test_engine_parity_speculative_draft_blocks():
    """Speculative decoding through the block-verify KERNEL: spec-on kernel
    == spec-on oracle == spec-off kernel, token for token (the accept loop's
    greedy property survives the kernel swap), with drafts really accepted."""
    model = create_llama_model(_tiny_config(), seq_len=32)
    rng = np.random.default_rng(9)
    motif = rng.integers(1, 128, (5,))
    prompts = [
        np.tile(motif, 4).astype(np.int32)[: int(rng.integers(8, 16))] for _ in range(4)
    ]
    reqs = lambda: [Request(i, p, max_new_tokens=8) for i, p in enumerate(prompts)]
    runs = {}
    for label, kwargs in {
        "spec_kernel": dict(speculative=True, draft_tokens=3, attention_impl="pallas_paged"),
        "spec_xla": dict(speculative=True, draft_tokens=3, attention_impl="xla"),
        "plain_kernel": dict(attention_impl="pallas_paged"),
    }.items():
        engine = ContinuousBatcher(
            model, num_slots=2, max_length=64, chunk_size=3, page_size=4,
            max_queue=8, **kwargs,
        )
        runs[label] = {
            rid: list(map(int, toks)) for rid, toks in engine.run(reqs()).items()
        }
        if label == "spec_kernel":
            spec = engine.stats["speculative"]
            assert spec["verify_steps"] > 0
            assert engine.trace_counts["decode_chunk"] == 1
    assert runs["spec_kernel"] == runs["spec_xla"] == runs["plain_kernel"]


def test_engine_parity_gpt_neox():
    """The second slot-cache family (Hq == Hkv, partial rotary) through the
    kernel path: greedy token parity with its own oracle."""
    from accelerate_tpu.models.gpt_neox import GPTNeoXConfig, create_gpt_neox_model

    cfg = GPTNeoXConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, rotary_pct=0.5, max_position_embeddings=64,
    )
    model = create_gpt_neox_model(cfg, seq_len=16)
    rng = np.random.default_rng(10)
    requests = _mixed_requests(rng, 4, prompt_hi=12, new_hi=6)
    common = dict(num_slots=2, max_length=32, chunk_size=4, page_size=4)
    _, xla_tokens = _run_engine(model, requests, attention_impl="xla", **common)
    engine, kernel_tokens = _run_engine(
        model, requests, attention_impl="pallas_paged", **common
    )
    assert kernel_tokens == xla_tokens
    assert engine.trace_counts["decode_chunk"] == 1


# ------------------------------------------------------------------ guardrails
def test_pallas_paged_requires_paged_cache():
    model = create_llama_model(_tiny_config(), seq_len=16)
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(
            model, num_slots=2, max_length=32, paged=False,
            attention_impl="pallas_paged", max_queue=4,
        )
    with pytest.raises(ValueError, match="attention_impl"):
        ContinuousBatcher(
            model, num_slots=2, max_length=32, attention_impl="mosaic", max_queue=4
        )

"""Docs integrity: internal links resolve and documented imports exist (the docs
equivalent of the example-drift harness — stale docs are worse than no docs)."""

import os
import re

DOCS = os.path.join(os.path.dirname(__file__), "..", "docs")


def _md_files():
    for root, _dirs, files in os.walk(DOCS):
        for f in files:
            if f.endswith(".md"):
                yield os.path.join(root, f)


def test_internal_links_resolve():
    broken = []
    for path in _md_files():
        with open(path) as f:
            text = f.read()
        for target in re.findall(r"\]\(([^)#]+\.md)\)", text):
            if target.startswith("http"):
                continue
            resolved = os.path.normpath(os.path.join(os.path.dirname(path), target))
            if not os.path.exists(resolved):
                broken.append(f"{os.path.relpath(path, DOCS)} -> {target}")
    assert not broken, broken


def test_documented_imports_exist():
    """Every `from accelerate_tpu... import X` line in a docs code fence imports."""
    import importlib

    pattern = re.compile(r"^from (accelerate_tpu[\w.]*) import \(?([\w, \n#>\-\[\]]+?)\)?$", re.M)
    failures = []
    for path in _md_files():
        with open(path) as f:
            text = f.read()
        for mod_name, names in pattern.findall(text):
            try:
                mod = importlib.import_module(mod_name)
            except ImportError as exc:
                failures.append(f"{os.path.basename(path)}: import {mod_name}: {exc}")
                continue
            for name in names.split(","):
                name = name.split("#")[0].strip()
                if not name or not name.isidentifier():
                    continue
                if not hasattr(mod, name):
                    failures.append(f"{os.path.basename(path)}: {mod_name}.{name} missing")
    assert not failures, failures


def test_readme_and_index_cover_docs_pages():
    """docs/index.md must link every docs page (no orphaned pages)."""
    with open(os.path.join(DOCS, "index.md")) as f:
        index = f.read()
    missing = []
    for path in _md_files():
        rel = os.path.relpath(path, DOCS)
        if rel == "index.md":
            continue
        if rel not in index:
            missing.append(rel)
    assert not missing, f"pages not linked from docs/index.md: {missing}"

"""Managed-cloud launch path (the reference's SageMaker equivalent, GCP-shaped:
sagemaker_launcher commands/launch.py:880 + config questionnaire sagemaker.py).
The plan is asserted through dry-run; the executor through a recorded fake
subprocess.run — no gcloud/network in CI."""

import argparse
import os
import subprocess

import pytest

from accelerate_tpu.commands.cloud import CloudJobConfig, plan_cloud_job
from accelerate_tpu.commands.launch import add_launch_args, launch_command

from test_config_cli import run_config


def _args(extra=()):
    parser = argparse.ArgumentParser(allow_abbrev=False)
    add_launch_args(parser)
    return parser.parse_args([*extra, "train.py", "--lr", "3e-4"])


def _cfg(**overrides):
    block = {"project": "my-proj", "name": "job1", **overrides}
    return CloudJobConfig({"cloud_config": block}, _args())


def test_plan_queued_resource_full_lifecycle():
    plan = plan_cloud_job(_cfg(spot=True, output_gcs="gs://bkt/run1"), ["train.py", "--lr", "3e-4"])
    tags = [t for t, _ in plan]
    assert tags == ["provision", "poll", "clean", "sync", "run", "collect", "teardown"]
    provision = dict(plan)["provision"]
    assert "queued-resources" in provision and "--spot" in provision
    assert "v5litepod-8" in provision  # default accelerator type
    run_cmd = dict(plan)["run"]
    assert run_cmd[-1].endswith("python -m accelerate_tpu.commands.launch train.py --lr 3e-4")
    assert "--worker" in run_cmd and "all" in run_cmd
    teardown = dict(plan)["teardown"]
    assert "delete" in teardown and "job1" in teardown


def test_plan_direct_create_no_teardown():
    plan = plan_cloud_job(_cfg(use_queued_resource=False, teardown=False), ["t.py"])
    tags = [t for t, _ in plan]
    assert tags == ["provision", "clean", "sync", "run"]  # no poll (direct), no teardown
    assert "tpu-vm" in dict(plan)["provision"]


def test_plan_setup_commands_ordered():
    plan = plan_cloud_job(_cfg(setup_commands=["pip install -e .", "echo ok"]), ["t.py"])
    tags = [t for t, _ in plan]
    assert tags.index("sync") < tags.index("setup") < tags.index("run")
    setups = [cmd[-1] for t, cmd in plan if t == "setup"]
    assert setups == ["pip install -e .", "echo ok"]


def test_cloud_requires_project():
    with pytest.raises(ValueError, match="project"):
        CloudJobConfig({}, _args())


def test_remote_run_args_are_shell_quoted():
    plan = plan_cloud_job(_cfg(), ["train.py", "--run_name", "my run; rm -rf /"])
    run_cmd = dict(plan)["run"][-1]
    assert "'my run; rm -rf /'" in run_cmd


def test_remote_config_strips_cloud_block_and_folds_cli_flags():
    """The staged config must not re-provision on the slice, and local CLI launch
    flags must survive the hop."""
    from accelerate_tpu.commands.cloud import build_remote_config

    args = _args(["--mixed_precision", "bf16", "--mesh_fsdp", "8", "--debug"])
    remote = build_remote_config(
        args,
        {
            "compute_environment": "GCP_CLOUD",
            "cloud_config": {"project": "p"},
            "mesh": {"data": -1, "model": 2},
            "gradient_accumulation_steps": 2,
        },
    )
    assert "cloud_config" not in remote and "compute_environment" not in remote
    assert remote["mixed_precision"] == "bf16"
    assert remote["mesh"] == {"data": -1, "model": 2, "fsdp": 8}
    assert remote["gradient_accumulation_steps"] == 2
    assert remote["debug"] is True


def test_launch_command_cloud_dry_run(tmp_path, capsys):
    """`launch --cloud --dry_run` goes through the real dispatch and prints the plan;
    CLI flags override the config block."""
    import yaml

    config_file = tmp_path / "c.yaml"
    config_file.write_text(
        yaml.safe_dump(
            {
                "compute_environment": "GCP_CLOUD",
                "cloud_config": {"project": "p1", "zone": "us-east5-b", "name": "nightly"},
            }
        )
    )
    args = _args(
        ["--config_file", str(config_file), "--dry_run", "--cloud_accelerator_type", "v5litepod-16"]
    )
    plan = launch_command(args)
    out = capsys.readouterr().out
    assert "[provision]" in out and "[teardown]" in out
    assert any("v5litepod-16" in " ".join(cmd) for _, cmd in plan)
    assert any("us-east5-b" in " ".join(cmd) for _, cmd in plan)


def test_questionnaire_cloud_flow(tmp_path):
    answers = [
        "2",            # GCP Cloud TPU
        "nightly-job",  # name
        "proj-7",       # project
        "",             # zone default
        "v5litepod-32",  # accelerator type
        "",             # runtime version default
        "y",            # queued resource
        "y",            # spot
        "gs://bkt/out",  # output gcs
        "y",            # teardown
        "",             # customize mesh? (no)
        "",             # fsdp? (no)
        "",             # sp? (no)
        "",             # precision default (bf16)
        "",             # downcast
        "",             # grad accumulation
        "",             # compile cache
        "",             # debug
    ]
    config, _ = run_config(tmp_path, answers)
    assert config["compute_environment"] == "GCP_CLOUD"
    assert config["cloud_config"] == {
        "name": "nightly-job",
        "project": "proj-7",
        "zone": "us-central2-b",
        "accelerator_type": "v5litepod-32",
        "runtime_version": "tpu-ubuntu2204-base",
        "use_queued_resource": True,
        "spot": True,
        "output_gcs": "gs://bkt/out",
        "teardown": True,
    }


class _FakeRun:
    """Records executed commands; scripted failures by tag substring."""

    def __init__(self, fail_containing=(), describe_states=()):
        self.calls = []
        self.fail_containing = list(fail_containing)
        self.describe_states = list(describe_states)

    def __call__(self, cmd, **kwargs):
        joined = " ".join(cmd)
        self.calls.append(joined)
        rc = 0
        stdout = ""
        if "describe" in joined:
            stdout = self.describe_states.pop(0) if self.describe_states else "ACTIVE"
        for marker in self.fail_containing:
            if marker in joined:
                rc = 1
        if rc and kwargs.get("check"):
            raise subprocess.CalledProcessError(rc, cmd)
        return subprocess.CompletedProcess(cmd, rc, stdout=stdout, stderr="")


def _run_launcher(tmp_path, monkeypatch, fake, **block):
    import yaml

    from accelerate_tpu.commands import cloud

    monkeypatch.setattr(cloud.subprocess, "run", fake)
    monkeypatch.setattr(cloud.time, "sleep", lambda s: None)
    monkeypatch.chdir(tmp_path)
    config_file = tmp_path / "c.yaml"
    config_file.write_text(
        yaml.safe_dump(
            {
                "compute_environment": "GCP_CLOUD",
                "cloud_config": {"project": "p", "name": "j", "output_gcs": "gs://b/o", **block},
            }
        )
    )
    args = _args(["--config_file", str(config_file)])
    from accelerate_tpu.commands.launch import launch_command

    return launch_command(args)


def test_executor_failure_still_collects_and_tears_down(tmp_path, monkeypatch):
    """A failed remote run must NOT skip artifact collection or slice teardown
    (billing + diagnosis), and the ORIGINAL failure propagates (not a wrapper)."""
    fake = _FakeRun(fail_containing=["accelerate_tpu.commands.launch"])
    with pytest.raises(subprocess.CalledProcessError):
        _run_launcher(tmp_path, monkeypatch, fake)
    assert any("gsutil -m rsync" in c for c in fake.calls), "collect must run on failure"
    assert any("delete" in c for c in fake.calls), "teardown must run on failure"
    # ordering: collect before teardown
    collect_i = next(i for i, c in enumerate(fake.calls) if "gsutil" in c)
    delete_i = next(i for i, c in enumerate(fake.calls) if "delete" in c)
    assert collect_i < delete_i
    # the staged config must not linger in cwd
    assert not os.path.exists(tmp_path / ".accelerate_tpu_job_config.yaml")


def test_executor_collect_failure_fails_launcher_after_teardown(tmp_path, monkeypatch):
    fake = _FakeRun(fail_containing=["gsutil"])
    with pytest.raises(RuntimeError, match="artifact collection failed"):
        _run_launcher(tmp_path, monkeypatch, fake)
    assert any("delete" in c for c in fake.calls), "teardown must still run"


def test_executor_poll_waits_for_active(tmp_path, monkeypatch):
    fake = _FakeRun(describe_states=["PROVISIONING", "PROVISIONING", "ACTIVE"])
    _run_launcher(tmp_path, monkeypatch, fake)
    assert sum("describe" in c for c in fake.calls) == 3
    assert any("ssh" in c and "accelerate_tpu.commands.launch" in c for c in fake.calls)


def test_executor_provision_failure_still_tears_down(tmp_path, monkeypatch):
    """`gcloud ... create` can create the resource and still exit non-zero (client
    timeout, transient API error after creation): the partially-created billing
    slice must be torn down anyway (round-3 advice, medium)."""
    fake = _FakeRun(fail_containing=["create"])
    with pytest.raises(subprocess.CalledProcessError):
        _run_launcher(tmp_path, monkeypatch, fake)
    assert any("delete" in c for c in fake.calls), "teardown must run after a failed provision"

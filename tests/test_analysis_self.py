"""Self-lint pin: the repo's own hot-path discipline is CI-enforced, not
folklore. `accelerate analyze accelerate_tpu examples` must report zero
error-severity findings — the exact gate `--fail-on error` applies — and any
intentional exception must carry an explicit `# tpu-lint: disable=` comment."""

from pathlib import Path

import pytest

from accelerate_tpu.analysis import analyze_paths, severity_at_least

pytestmark = pytest.mark.analysis

REPO = Path(__file__).resolve().parents[1]


def test_repo_has_zero_error_findings():
    findings, scanned = analyze_paths([str(REPO / "accelerate_tpu"), str(REPO / "examples")])
    assert scanned > 80, f"suspiciously few files scanned ({scanned}) — wrong root?"
    errors = [f for f in findings if severity_at_least(f.severity, "error")]
    assert not errors, "error-severity TPU hazards in the repo:\n" + "\n".join(
        f"  {f.file}:{f.line}: {f.rule_id} {f.message}" for f in errors
    )


def test_repo_warnings_stay_bounded():
    """Warns don't gate CI, but silent growth means discipline drift: this pin
    forces each new warn-level hazard to be either fixed or suppressed with an
    explicit justification comment at the site."""
    findings, _ = analyze_paths([str(REPO / "accelerate_tpu"), str(REPO / "examples")])
    warns = [f for f in findings if f.severity == "warn"]
    assert len(warns) == 0, "unsuppressed warn-level findings:\n" + "\n".join(
        f"  {f.file}:{f.line}: {f.rule_id} {f.message}" for f in warns
    )


def test_benchmarks_and_bench_entry_are_error_free():
    """The bench drivers run with the TraceGuard armed — they must hold the
    same static discipline they enforce at runtime."""
    findings, scanned = analyze_paths([str(REPO / "benchmarks"), str(REPO / "bench.py")])
    assert scanned >= 3
    errors = [f for f in findings if f.severity == "error"]
    assert not errors, [(f.file, f.line, f.rule_id) for f in errors]


def test_chaos_subsystem_is_warn_clean():
    """The chaos injectors wrap the checkpoint commit path and the serving
    dispatch seam — a host-sync or recompile hazard inside an injector would
    perturb exactly the recovery behavior it exists to test. Warn-clean, like
    telemetry."""
    findings, scanned = analyze_paths([str(REPO / "accelerate_tpu" / "chaos")])
    assert scanned >= 5, f"chaos subsystem missing files? scanned {scanned}"
    flagged = [f for f in findings if severity_at_least(f.severity, "warn")]
    assert not flagged, "warn+ TPU hazards in chaos:\n" + "\n".join(
        f"  {f.file}:{f.line}: {f.rule_id} {f.message}" for f in flagged
    )


def test_paging_module_is_warn_clean():
    """The page-pool allocator + prefix cache sit BETWEEN decode dispatches on
    the serving hot path: a device touch inside `PagePool` (a stray jnp op, a
    host sync on pool state) would serialize admission against the device and
    trip the bench's armed TraceGuard. Warn-clean, and the scan must actually
    see the module — a silent rename would make this pin vacuous."""
    findings, scanned = analyze_paths([str(REPO / "accelerate_tpu" / "paging.py")])
    assert scanned == 1, f"paging module missing? scanned {scanned}"
    flagged = [f for f in findings if severity_at_least(f.severity, "warn")]
    assert not flagged, "warn+ TPU hazards in paging:\n" + "\n".join(
        f"  {f.file}:{f.line}: {f.rule_id} {f.message}" for f in flagged
    )


def test_speculative_path_is_warn_clean():
    """The draft/verify machinery is traced INSIDE the decode executables —
    the drafter, the accept loop, and the serving/generation integrations must
    be warn-clean: a stray host sync or jit hazard here would serialize every
    verify step against the host, the exact overhead speculation exists to
    amortize away. The scan pins the three files that carry the path so a
    rename can't make the gate vacuous."""
    roots = [
        REPO / "accelerate_tpu" / "speculative.py",
        REPO / "accelerate_tpu" / "serving.py",
        REPO / "accelerate_tpu" / "generation.py",
    ]
    findings, scanned = analyze_paths([str(r) for r in roots])
    assert scanned == 3, f"speculative-path files missing? scanned {scanned}"
    flagged = [f for f in findings if severity_at_least(f.severity, "warn")]
    assert not flagged, "warn+ TPU hazards on the speculative path:\n" + "\n".join(
        f"  {f.file}:{f.line}: {f.rule_id} {f.message}" for f in flagged
    )


def test_router_is_warn_clean():
    """The replicated-serving front-end sits between callers and every engine
    dispatch: a host-sync or recompile hazard in the router would serialize
    the WHOLE fleet, and an unbounded queue there (its own rule, TPU114)
    would defeat the backpressure it exists to provide. Warn-clean, and the
    scan must actually see the module so a rename can't make the pin vacuous."""
    findings, scanned = analyze_paths([str(REPO / "accelerate_tpu" / "router.py")])
    assert scanned == 1, f"router module missing? scanned {scanned}"
    flagged = [f for f in findings if severity_at_least(f.severity, "warn")]
    assert not flagged, "warn+ TPU hazards in router:\n" + "\n".join(
        f"  {f.file}:{f.line}: {f.rule_id} {f.message}" for f in flagged
    )


def test_worker_module_is_warn_clean():
    """The out-of-process worker pin: accelerate_tpu/worker.py — the IPC
    framing, the worker loop, and the SubprocessEngine proxy — stays
    warn-clean under the full registry INCLUDING its own rules (TPU116 and
    TPU122): the module that defines the heartbeat/timeout discipline must
    itself pass it (every looped recv bounded, serve_worker called with an
    explicit heartbeat deadline), and the module that defines the socket
    transport must pass the bounded-wire-wait rule it motivated (timed
    create_connection dials, deadline-armed reads, reconnect attempts
    budgeted by the state machine, never a bare retry loop)."""
    findings, scanned = analyze_paths([str(REPO / "accelerate_tpu" / "worker.py")])
    assert scanned == 1, f"worker module missing? scanned {scanned}"
    flagged = [f for f in findings if severity_at_least(f.severity, "warn")]
    assert not flagged, "warn+ TPU hazards in worker:\n" + "\n".join(
        f"  {f.file}:{f.line}: {f.rule_id} {f.message}" for f in flagged
    )


def test_kernel_serving_path_is_warn_clean_at_22_rules():
    """The Pallas kernel path pin: `ops/` (the kernels + the dispatch seams +
    the quantization module), the kernel-touching serving/generation files,
    and the TP sharding + planner + MPMD-runtime modules stay warn-clean
    under the FULL 22-rule registry — including TPU115, so nothing in the
    shipped tree pins a paged decode program to the gather oracle or forces
    interpret mode outside tests; TPU117, so no shipped quantization seam
    bakes a scale literal or an off-set kv_cache_dtype into a program;
    TPU118, so the mesh-spanning serving engine itself never places a
    params/pool tree without a NamedSharding; TPU119 (re-audited when the
    registry grew 18 -> 19 for it), so no shipped rules table carries a dead
    entry and no model module hides a per-leaf PartitionSpec outside its
    table; TPU120 (the 19 -> 20 re-audit), so the sharding/planner seams
    that EMIT the ZeRO opt-state tables never themselves park a replicated
    moments tree on a data mesh; and TPU121 (the 20 -> 21 re-audit), so the
    MPMD pipeline runtime that OWNS the stage-handoff discipline never
    itself pulls an inter-stage carry through the host — every handoff in
    parallel/mpmd.py is a jax.device_put onto the next stage's submesh; and
    TPU122 (the 21 -> 22 re-audit), so the one module on this path that
    touches sockets keeps every wire wait bounded — the serving/generation
    files here never dial, recv, or reconnect without a deadline (the
    socket transport itself lives in worker.py, pinned warn-clean by
    test_worker_module_is_warn_clean under the same rule: its
    create_connection dials carry timeouts and its reconnect attempts run
    inside the budgeted state machine TPU122's fixit prescribes). The
    rule-count assert keeps this test honest: if the registry grows, this
    pin re-evaluates the kernel path under the new rule instead of silently
    gating against a stale set."""
    from accelerate_tpu.analysis import RULES

    assert len(RULES) == 22, "rule registry changed — re-audit the kernel-path pin"
    roots = [
        REPO / "accelerate_tpu" / "ops",
        REPO / "accelerate_tpu" / "serving.py",
        REPO / "accelerate_tpu" / "generation.py",
        REPO / "accelerate_tpu" / "parallel" / "sharding.py",
        REPO / "accelerate_tpu" / "parallel" / "planner.py",
        REPO / "accelerate_tpu" / "parallel" / "mpmd.py",
    ]
    findings, scanned = analyze_paths([str(r) for r in roots])
    assert scanned >= 8, f"kernel-path files missing? scanned {scanned}"
    flagged = [f for f in findings if severity_at_least(f.severity, "warn")]
    assert not flagged, "warn+ TPU hazards on the kernel path:\n" + "\n".join(
        f"  {f.file}:{f.line}: {f.rule_id} {f.message}" for f in flagged
    )


def test_telemetry_subsystem_is_warn_clean():
    """The observability layer rides the serving/train hot paths — it must be
    completely clean at WARN level, not just error-free: a host-sync or
    recompile hazard inside a metrics call would perturb the very loop it
    measures. (The repo-wide pins above include this tree; the explicit root
    keeps the gate loud if the walk roots ever change.)"""
    findings, scanned = analyze_paths([str(REPO / "accelerate_tpu" / "telemetry")])
    assert scanned >= 5, f"telemetry subsystem missing files? scanned {scanned}"
    flagged = [f for f in findings if severity_at_least(f.severity, "warn")]
    assert not flagged, "warn+ TPU hazards in telemetry:\n" + "\n".join(
        f"  {f.file}:{f.line}: {f.rule_id} {f.message}" for f in flagged
    )

"""The 2D training planner (parallel/planner.plan_train_sharding): the
("data", "model") search with ZeRO weight-update sharding — optimizer moments
placed along "data" even where the params replicate — plus the planner-emitted
pipeline stage assignment (plan_pipeline_stages).

The acceptance pins:

  - **legality** — every 2D spec the planner emits (params AND moments)
    divides its dimension by the product of the mesh axes it names, and uses
    only axes the mesh has;
  - **ZeRO accounting** — modeled per-chip optimizer bytes beat the
    replicated footprint by ~the data-axis degree; big replicated params get
    a data-sharded moment twin (role "zero-opt"); the emitted opt_rules
    table round-trips through `derive_opt_state_shardings` to live
    placements whose measured bytes match the prediction;
  - **planner-vs-hand parity** — on llama + gpt_neox the 2D auto plan
    matches or beats the hand family table on modeled cost under the SAME
    training workload (score_rules prices the hand table's grad sync too);
  - **HBM forcing** — on a fake chip too small for the replicated layout the
    plan sheds the overflow (model-sharded params + data-sharded moments)
    while the replicated scoring overflows;
  - **decode unaffected** — serving workloads (opt_bytes_per_param=0) emit
    no opt_rules and price zero optimizer bytes;
  - **end-to-end** — `Accelerator.prepare(sharding_rules="auto")` on the 2D
    CPU mesh trains at loss parity with the 1D replicated baseline, with
    moments live-sharded along "data", 0 recompiles / 0 host transfers in
    steady state, and predicted per-chip bytes matching the live trees.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

import jax

from accelerate_tpu.models.gpt_neox import GPT_NEOX_SHARDING_RULES
from accelerate_tpu.models.llama import LLAMA_SHARDING_RULES
from accelerate_tpu.parallel.planner import (
    Workload,
    default_chip,
    plan_pipeline_stages,
    plan_sharding,
    plan_train_sharding,
    score_rules,
)
from accelerate_tpu.parallel.sharding import tree_device_nbytes

pytestmark = pytest.mark.planner

needs_mesh8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs an 8-device mesh (forced CPU devices)"
)

MESH_2D = {"data": 4, "model": 2}


def wide_net(hidden=256, vocab=4096, inter=1024, layers=2):
    """A cleanly-shardable transformer-shaped params tree (plain numpy — the
    planner only reads shapes/dtypes) with one large REPLICATED leaf
    (big_bias: 1D, matmul-unshardable, above the ZeRO size floor) so the
    moments-shard-where-params-replicate path is always exercised."""
    z = lambda *shape: np.zeros(shape, np.float32)
    params = {"embed_tokens": {"embedding": z(vocab, hidden)}}
    for i in range(layers):
        params[f"layer_{i}"] = {
            "attention": {
                "wq": {"kernel": z(hidden, hidden)},
                "wo": {"kernel": z(hidden, hidden)},
            },
            "mlp": {
                "w_up": {"kernel": z(hidden, inter)},
                "w_down": {"kernel": z(inter, hidden)},
            },
            "norm": {"scale": z(hidden)},
            "big_bias": {"bias": z(vocab)},
        }
    params["lm_head"] = {"kernel": z(hidden, vocab)}
    return {"params": params}


def _replicated_opt_bytes(params, opt_bytes_per_param=8.0):
    return sum(
        int(np.prod(np.shape(l))) * opt_bytes_per_param
        for l in jax.tree_util.tree_leaves(params)
    )


def _spec_axes(spec):
    for dim in spec:
        if dim is None:
            continue
        for ax in dim if isinstance(dim, tuple) else (dim,):
            yield ax


# ------------------------------------------------------------------ legality
def test_2d_specs_divisible_and_on_mesh_axes():
    """Every emitted spec — param and moment — names only mesh axes and
    divides its dimension by the product of the axes it stacks there (the
    same gate `_check_tp_divisible` enforces at placement time, so a planner
    choice can never hit the indivisible-rule hard error)."""
    plan = plan_train_sharding(wide_net(), MESH_2D, batch=8, seq=128)
    for leaf in plan.leaves:
        for spec in (leaf.spec, leaf.opt_spec):
            assert set(_spec_axes(spec)) <= set(MESH_2D), (leaf.path, spec)
            assert len(spec) <= len(leaf.shape), (leaf.path, spec)
            for dim_idx, dim in enumerate(spec):
                if dim is None:
                    continue
                axes = dim if isinstance(dim, tuple) else (dim,)
                factor = int(np.prod([MESH_2D[a] for a in axes]))
                assert leaf.shape[dim_idx] % factor == 0, (leaf.path, spec)


# ------------------------------------------------------------- ZeRO account
def test_zero_moments_shard_where_params_replicate():
    """The weight-update-sharding core: the big replicated leaf (big_bias)
    keeps a replicated PARAM spec but gets a "data"-sharded MOMENT spec (role
    zero-opt); model-sharded kernels get the data axis merged into their
    sharded dim; and the modeled per-chip optimizer bytes land near
    replicated / (data * model) — far below the replicated footprint."""
    params = wide_net()
    plan = plan_train_sharding(params, MESH_2D, batch=8, seq=128)
    by_path = {l.path: l for l in plan.leaves}

    bias = by_path["params/layer_0/big_bias/bias"]
    assert bias.spec == ()
    assert bias.opt_spec == ("data",)
    assert bias.role == "zero-opt"

    # A model-sharded kernel: moments add "data" onto the sharded dim.
    kernels = [l for l in plan.leaves if "model" in set(_spec_axes(l.spec))]
    assert kernels, "no model-sharded kernels in the 2D plan"
    for leaf in kernels:
        assert "data" in set(_spec_axes(leaf.opt_spec)), (leaf.path, leaf.opt_spec)

    # Tiny leaves (norm scales, below the ZeRO floor) stay replicated — a
    # shard smaller than a flit costs more in collective latency than it saves.
    norm = by_path["params/layer_0/norm/scale"]
    assert norm.opt_spec == ()

    replicated = _replicated_opt_bytes(params)
    assert plan.cost.per_chip_opt_bytes < replicated / 4  # >= the data degree
    assert plan.opt_rules, "2D training plan must emit an opt_rules table"
    # Moment patterns are anchored (^|/) so they match inside 0/mu/<path>.
    assert all(p.startswith("(^|/)") for p, _ in plan.opt_rules)


def test_serving_plans_emit_no_opt_rules():
    """Decode is unaffected: a serving workload (opt_bytes_per_param=0) is
    not training, prices zero optimizer bytes, and emits no opt_rules — the
    1-axis serving planner's output is byte-identical to before the 2D
    extension."""
    assert not Workload().is_training
    plan = plan_sharding(wide_net(), {"model": 2}, axes=("model",))
    assert plan.opt_rules == []
    assert plan.cost.per_chip_opt_bytes == 0.0
    assert all(l.opt_spec == l.spec for l in plan.leaves)


# ------------------------------------------------------------ vs hand rules
@pytest.mark.parametrize(
    "family, hand_rules",
    [("llama", LLAMA_SHARDING_RULES), ("gpt_neox", GPT_NEOX_SHARDING_RULES)],
)
def test_2d_plan_matches_or_beats_hand_rules(family, hand_rules):
    """Apples to apples on the real family trees: the 2D auto plan's modeled
    cost is <= the hand table's under the SAME training workload —
    score_rules prices the hand table's data-axis grad sync exactly the way
    the search prices its candidates, so neither side skips a term."""
    from test_planner import get_model

    params = jax.eval_shape(lambda p: p, get_model(family).params)
    plan = plan_train_sharding(params, MESH_2D, batch=8, seq=64)
    hand = score_rules(params, MESH_2D, hand_rules, workload=plan.workload)
    assert plan.cost.total <= hand.cost.total, (plan.cost.total, hand.cost.total)
    # The hand table has no opt-state twin: moments follow params, so its
    # per-chip optimizer bytes can never beat the ZeRO plan's.
    assert plan.cost.per_chip_opt_bytes <= hand.cost.per_chip_opt_bytes


def test_small_chip_forces_sharded_plan():
    """HBM forcing: on a fake chip whose HBM fits the sharded layout but not
    the replicated one, the plan sheds the overflow — model-sharded params,
    data-sharded moments, zero modeled overflow — while pricing the
    fully-replicated table on the same chip overflows. (The overflow penalty
    dominates the objective, so "model does not fit one chip" can never pick
    the replicated layout.)"""
    params = wide_net()
    # Footprints on this net (fp32 leaves, so nbytes honor the real dtype):
    # fully sharded ~10.3 MB, fully replicated ~41 MB. 12 MB sits between.
    chip = dataclasses.replace(default_chip(), hbm_bytes=12e6)
    plan = plan_train_sharding(params, MESH_2D, batch=8, seq=128, chip=chip)
    assert plan.cost.hbm_overflow_bytes == 0.0
    assert any("model" in set(_spec_axes(l.spec)) for l in plan.leaves)
    assert plan.cost.per_chip_opt_bytes < _replicated_opt_bytes(params)

    replicated = score_rules(params, MESH_2D, [], chip=chip, workload=plan.workload)
    assert replicated.cost.hbm_overflow_bytes > 0.0
    assert plan.cost.total < replicated.cost.total


# ----------------------------------------------------------- pipeline stages
def test_plan_pipeline_stages_uniform_and_balanced():
    """The stage planner: equal-weight layers split into the uniform
    equal-count assignment (what the SPMD runner executes); heterogeneous
    weights get the DP's balanced contiguous split, which beats the naive
    equal-count split on max per-stage bytes; assignments are contiguous and
    non-decreasing; degenerate shapes raise."""
    z = lambda n: {"w": np.zeros((n, 4), np.float32)}
    uniform = plan_pipeline_stages([z(8)] * 8, 4)
    assert uniform.uniform and uniform.num_stages == 4
    assert uniform.assignment == [0, 0, 1, 1, 2, 2, 3, 3]
    assert uniform.imbalance == 1.0
    assert uniform.stage_layers(1) == [2, 3]
    assert uniform.rules and uniform.rules[0][1] == ("stage",)

    # One heavy layer: the DP isolates it instead of pairing it.
    heavy = plan_pipeline_stages([z(100), z(1), z(1), z(1)], 2)
    assert heavy.assignment == [0, 1, 1, 1]
    naive_max = max(100 + 1, 1 + 1)  # equal-count [0,0,1,1] split
    assert max(heavy.per_stage_bytes) < naive_max * z(1)["w"].itemsize * 4

    with pytest.raises(ValueError, match="must be positive"):
        plan_pipeline_stages([z(1)], 0)
    with pytest.raises(ValueError, match="cannot split"):
        plan_pipeline_stages([z(1)] * 3, 4)


# -------------------------------------------------------------- end to end
def _reset_state():
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _run_training(family_name, mode, *, steps=3, seq_len=16, global_batch=8, tp=2):
    """One end-to-end pass through Accelerator.prepare + train_step. Returns
    (losses, prepared model, prepared optimizer, accelerator, guard)."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.models import CREATE_BY_FAMILY, get_model_family
    from accelerate_tpu.parallel.sharding import data_spec
    from accelerate_tpu.utils import ParallelismConfig, set_seed
    from jax.sharding import NamedSharding

    _reset_state()
    set_seed(0)
    family, cfg = get_model_family(family_name)
    bundle = CREATE_BY_FAMILY[family](cfg, seq_len=seq_len)
    if mode == "2d":
        bundle.sharding_rules = "auto"
        pcfg = ParallelismConfig(data=-1, model=tp)
    else:
        pcfg = ParallelismConfig(data=-1)
    accelerator = Accelerator(parallelism_config=pcfg)
    model, opt = accelerator.prepare(bundle, optax.adam(1e-3))

    rng = np.random.default_rng(0)
    sharding = NamedSharding(accelerator.mesh, data_spec(accelerator.mesh))
    batches = [
        jax.device_put(
            {"input_ids": rng.integers(0, cfg.vocab_size, (global_batch, seq_len)).astype(np.int32)},
            sharding,
        )
        for _ in range(1 + steps)
    ]
    step_fn = accelerator.train_step()
    jax.block_until_ready(step_fn(batches[0]))  # warmup / compile

    guard = TraceGuard(name=f"planner2d-{family_name}-{mode}", on_violation="record")
    raw = []
    with guard:
        for batch in batches[1:]:
            raw.append(step_fn(batch))
        jax.block_until_ready(raw[-1])
    return [float(l) for l in raw], model, opt, accelerator, guard


@needs_mesh8
@pytest.mark.parametrize("family_name", ["llama-tiny", "gpt-neox-tiny"])
def test_prepare_auto_2d_trains_at_parity_with_zero_sharded_state(family_name):
    """The ISSUE's acceptance path: prepare(sharding_rules="auto") on the 2D
    CPU mesh — the auto plan places fp32 moments sharded along "data" (live,
    not just modeled), trains the SAME loss trajectory as the 1D replicated
    baseline (the layout must not change the math), keeps the steady state at
    0 recompiles / 0 host transfers, and its predicted per-chip bytes match
    the live `tree_device_nbytes` for params and optimizer state."""
    losses_1d, _, opt_1d, _, guard_1d = _run_training(family_name, "1d")
    losses_2d, model, opt_2d, accelerator, guard_2d = _run_training(family_name, "2d")

    for guard, tag in ((guard_1d, "1d"), (guard_2d, "2d")):
        assert guard.total_recompiles == 0, (tag, guard.report().summary())
        assert guard.host_transfers == 0, (tag, guard.transfer_violations)

    drift = max(abs(a - b) for a, b in zip(losses_1d, losses_2d))
    assert drift <= 2e-4, (losses_1d, losses_2d)

    # Live moments sharded along "data" (ZeRO), not merely planned.
    data_sharded = [
        l
        for l in jax.tree_util.tree_leaves(opt_2d.opt_state)
        if hasattr(l, "sharding") and "data" in set(_spec_axes(l.sharding.spec))
    ]
    assert data_sharded, "no live opt-state leaf is sharded along the data axis"

    dev0 = jax.devices()[0]
    live_opt_2d = tree_device_nbytes(opt_2d.opt_state, dev0)
    live_opt_1d = tree_device_nbytes(opt_1d.opt_state, dev0)
    assert live_opt_2d < live_opt_1d / 4, (live_opt_2d, live_opt_1d)

    # Predicted-vs-live round trip: re-run the deterministic planner the
    # prepare() seam ran and compare its account against the live trees.
    sizes = {k: v for k, v in dict(accelerator.mesh.shape).items() if k in MESH_2D}
    plan = plan_train_sharding(
        jax.eval_shape(lambda p: p, model.params), sizes, batch=8, seq=512
    )
    live_params = tree_device_nbytes(model.params, dev0)
    assert abs(plan.cost.per_chip_param_bytes - live_params) / live_params <= 0.01
    # Adam carries a replicated count scalar the byte model rounds away.
    assert abs(plan.cost.per_chip_opt_bytes - live_opt_2d) / live_opt_2d <= 0.01


# ------------------------------------------------------------------ CLI seam
def test_plan_cli_train_mesh_json(capsys):
    """`accelerate-tpu plan <model> --mesh data=4,model=2 --json`: the payload
    carries the opt_rules table, the three-tree byte predictions, and the
    hand-table comparison verdict."""
    import json

    from accelerate_tpu.commands.accelerate_cli import get_command_parser

    parser = get_command_parser()
    args = parser.parse_args(
        ["plan", "llama-tiny", "--mesh", "data=4,model=2", "--json"]
    )
    args.func(args)
    payload = json.loads(capsys.readouterr().out)
    assert payload["mesh"] == {"data": 4, "model": 2}
    assert payload["plan"]["opt_rules"], "training plan JSON must carry opt_rules"
    assert payload["plan"]["predicted"]["per_chip_opt_bytes"] > 0
    assert payload["auto_beats_hand"] is True


@needs_mesh8
def test_plan_cli_train_mesh_live(capsys):
    """--live places params, grads, and a fresh Adam state per the plan on
    the real 8-device CPU mesh and reports predicted-vs-live per-chip bytes:
    params and grads exact, optimizer state within 1% (the replicated count
    scalar)."""
    import json

    from accelerate_tpu.commands.accelerate_cli import get_command_parser

    parser = get_command_parser()
    args = parser.parse_args(
        ["plan", "llama-tiny", "--mesh", "data=4,model=2", "--live", "--json"]
    )
    args.func(args)
    payload = json.loads(capsys.readouterr().out)
    live = payload["live"]
    assert live["params"]["error_pct"] == 0.0
    assert live["grads"]["error_pct"] == 0.0
    assert live["opt_state"]["error_pct"] <= 1.0

"""Out-of-process serving fleet tests: subprocess-worker parity, autoscaling,
admission control, and the TTFT-quantile hedge trigger.

The real-subprocess tests keep the fleet tiny (one or two workers over the
32-hidden llama) so they stay inside the fast tier; everything scheduling-
sensitive (autoscaler timing) runs on in-process engines under a `FakeClock`
so the pins are deterministic, not wall-clock races.
"""

import numpy as np
import pytest

from accelerate_tpu.chaos.injectors import FakeClock
from accelerate_tpu.generation import generate
from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
from accelerate_tpu.router import Router
from accelerate_tpu.serving import QueueFull, Request

pytestmark = pytest.mark.fleet


def _model(seed: int = 0):
    import jax

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0,
    )
    return create_llama_model(cfg, rng=jax.random.key(seed), seq_len=32)


def _static_reference(model, prompt, max_new):
    out = np.asarray(generate(model, prompt[None, :], max_new_tokens=max_new))
    return out[0, prompt.size:]


# ------------------------------------------------------------------ subprocess parity
def test_subprocess_fleet_token_parity_and_weight_swap(tmp_path):
    """THE out-of-process acceptance pin: a Router over a real subprocess
    worker produces greedy outputs token-identical to the in-process Router
    AND the static Generator on the same prompts (params move by file, never
    re-derived), and a rolling `swap_weights` reaches the worker over IPC —
    post-swap outputs match the NEW weights exactly."""
    model_a = _model(seed=0)
    model_b = _model(seed=7)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 128, (n,)).astype(np.int32) for n in (3, 6, 10, 5)]
    budgets = [5, 4, 6, 3]
    requests = lambda: [  # noqa: E731
        Request(i, p, max_new_tokens=m) for i, (p, m) in enumerate(zip(prompts, budgets))
    ]
    kwargs = dict(
        replicas=1, num_slots=2, max_length=64, chunk_size=4, max_queue=16,
        default_deadline_s=120.0, stall_degrade_s=None,
    )
    inproc = Router(model_a, **kwargs)
    ref_out = inproc.run(requests())
    inproc.close()

    fleet = Router(
        model_a, out_of_process=True,
        worker_kwargs=dict(workdir=str(tmp_path), step_timeout_s=120.0),
        **kwargs,
    )
    try:
        worker = fleet.replica_set.replicas[0].engine
        assert worker.ready_info["warm"] and worker.ready_info["warmed"]
        out = fleet.run(requests())
        for i, (p, m) in enumerate(zip(prompts, budgets)):
            np.testing.assert_array_equal(out[i], ref_out[i])
            np.testing.assert_array_equal(out[i], _static_reference(model_a, p, m))
        # Worker-side health is visible through the proxy's stats surface.
        stats = fleet.stats["per_replica"][0]
        assert stats["worker"]["pid"] == worker.pid
        assert stats["finish_reasons"]["length"] + stats["finish_reasons"]["eos"] == 4
        # Rolling weight swap over IPC: params ship by file handoff.
        for rid in list(fleet.results):
            fleet.release(rid)
        fleet.swap_weights(model_b)
        swapped = fleet.run([Request(100, prompts[0], max_new_tokens=5)])
        np.testing.assert_array_equal(
            swapped[100], _static_reference(model_b, prompts[0], 5)
        )
    finally:
        fleet.close()


def test_socket_fleet_token_parity_guard_and_weight_swap(tmp_path):
    """The socket-transport acceptance pin: the same fleet served over
    loopback TCP (the worker self-listens and announces, the controller dials
    and registers) is greedy-token-identical to the PIPE fleet and the static
    Generator on the same prompts, holds the per-worker TraceGuard at
    0 recompiles / 0 host transfers across the post-warm serving window, and
    a rolling `swap_weights` reaches the listening worker over the socket
    (params by digest-verified file handoff, like the pipe path)."""
    model_a = _model(seed=0)
    model_b = _model(seed=7)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 128, (n,)).astype(np.int32) for n in (3, 6, 10, 5)]
    budgets = [5, 4, 6, 3]
    requests = lambda: [  # noqa: E731
        Request(i, p, max_new_tokens=m) for i, (p, m) in enumerate(zip(prompts, budgets))
    ]
    kwargs = dict(
        replicas=1, num_slots=2, max_length=64, chunk_size=4, max_queue=16,
        default_deadline_s=120.0, stall_degrade_s=None,
    )
    pipe = Router(
        model_a, out_of_process=True,
        worker_kwargs=dict(workdir=str(tmp_path / "pipe"), step_timeout_s=120.0),
        **kwargs,
    )
    try:
        pipe_out = pipe.run(requests())
    finally:
        pipe.close()

    fleet = Router(
        model_a, out_of_process=True,
        worker_kwargs=dict(
            workdir=str(tmp_path / "sock"), step_timeout_s=120.0,
            transport="socket", guard=True,
        ),
        **kwargs,
    )
    try:
        worker = fleet.replica_set.replicas[0].engine
        assert worker.transport_kind == "socket"
        # The registration ready frame: identity + protocol + warm attestation.
        assert worker.ready_info["registered"] and worker.ready_info["epoch"] == 1
        assert worker.ready_info["warm"] and worker.ready_info["warmed"]
        fleet.run(requests())  # warm pass: decode/prefix executables compile here
        for rid in list(fleet.results):
            fleet.release(rid)
        assert worker.reset_guard(), "worker spawned without --guard"
        out = fleet.run(requests())
        for i, (p, m) in enumerate(zip(prompts, budgets)):
            np.testing.assert_array_equal(out[i], pipe_out[i])
            np.testing.assert_array_equal(out[i], _static_reference(model_a, p, m))
        guard = fleet.stats["per_replica"][0]["worker"]["guard"]
        assert guard == {"recompiles": 0, "host_transfers": 0}, (
            f"socket serving window regressed the 0/0 discipline: {guard}"
        )
        # Rolling weight swap over the socket: params ship by file + digest.
        for rid in list(fleet.results):
            fleet.release(rid)
        fleet.swap_weights(model_b)
        swapped = fleet.run([Request(100, prompts[0], max_new_tokens=5)])
        np.testing.assert_array_equal(
            swapped[100], _static_reference(model_b, prompts[0], 5)
        )
        assert worker.transport.alive() and worker.reconnects == 0, (
            "a clean socket serve must never have torn or respawned"
        )
    finally:
        fleet.close()


# ------------------------------------------------------------------ autoscaler
def _fake_clock_router(model, **overrides):
    clock = FakeClock()
    kwargs = dict(
        replicas=1, num_slots=1, max_length=64, chunk_size=4, max_queue=16,
        default_deadline_s=1e9, stall_degrade_s=None, heartbeat_timeout_s=None,
        min_replicas=1, max_replicas=3, autoscale_queue_high=1.0,
        autoscale_cooldown_s=2.0, idle_retire_s=10.0, clock=clock.perf_counter,
    )
    kwargs.update(overrides)
    return Router(model, **kwargs), clock


def test_autoscaler_scales_up_under_pressure_and_retires_idle_fakeclock():
    """The deterministic autoscaler pin: queue pressure grows the fleet (one
    replica per cooldown window, never past max_replicas), the drained-idle
    fleet retires back to min_replicas one idle window at a time, retired
    replicas never take traffic, and the whole schedule is FakeClock-driven —
    no wall-clock in any decision."""
    model = _model()
    router, clock = _fake_clock_router(model)
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    for i in range(8):  # 1 slot, queue depth >> autoscale_queue_high * 1
        router.submit(Request(i, prompt, max_new_tokens=6))
    assert router.active_replicas == 1
    router.step()
    assert router.active_replicas == 2, "queue pressure must add a replica"
    # Cooldown gates the next addition: stepping inside the window adds none.
    router.step()
    assert router.active_replicas == 2
    clock.sleep(2.5)  # past autoscale_cooldown_s
    router.step()
    assert router.active_replicas == 3
    clock.sleep(2.5)
    router.step()
    assert router.active_replicas == 3, "max_replicas is a hard ceiling"
    while router.pending:
        router.step()
    # Deterministic idle retirement: nothing retires inside the idle window...
    router.step()
    clock.sleep(9.0)
    router.step()
    assert router.active_replicas == 3
    # ... one replica retires per full idle window, newest first, down to min.
    clock.sleep(1.5)
    router.step()
    assert router.active_replicas == 2
    assert router.replica_states[2] == "retired"
    clock.sleep(10.5)
    router.step()
    assert router.active_replicas == 1
    assert router.replica_states[1] == "retired"
    clock.sleep(30.0)
    router.step()
    assert router.active_replicas == 1, "min_replicas is the floor"
    stats = router.stats["autoscale"]
    assert stats["scale_ups"] == 2 and stats["scale_downs"] == 2
    # Post-scale traffic still serves with exact parity on the survivor (the
    # fresh queued request may legitimately re-trigger a scale-up — the point
    # here is correctness of the surviving fleet, not the counter).
    out = router.run([Request(50, prompt, max_new_tokens=4)])
    np.testing.assert_array_equal(out[50], _static_reference(model, prompt, 4))
    assert not any(
        e["replica"] in (1, 2) and e["t"] > next(
            s["t"] for s in router.replica_set.state_log
            if s["to"] == "retired" and s["replica"] == e["replica"]
        )
        for e in router.routing_log
    ), "routing decision landed on a retired replica"
    router.close()


def test_autoscaler_ttft_signal_scales_up():
    """The TTFT-histogram half of the scale-up signal: a p99 above
    autoscale_ttft_target_s grows the fleet even with an empty queue."""
    model = _model()
    router, clock = _fake_clock_router(
        model, autoscale_ttft_target_s=0.5, hedge_min_samples=4,
    )
    for _ in range(4):
        router._m_ttft.observe(2.0)  # the live histogram says TTFT is terrible
    router.step()
    assert router.active_replicas == 2
    assert router.stats["autoscale"]["scale_ups"] == 1
    router.close()


# ------------------------------------------------------------------ admission control
def test_tenant_admission_bounds_one_tenants_burst():
    """One tenant's burst degrades into bounded queueing for THAT tenant:
    tenant A saturates the fleet and its own router-level queue (QueueFull for
    A at its bound), while tenant B still admits and completes — never a
    fleet-wide rejection."""
    model = _model()
    router = Router(
        model, replicas=1, num_slots=1, max_length=64, chunk_size=4,
        max_queue=1, default_deadline_s=120.0, stall_degrade_s=None,
        tenant_queue_limit=2,
    )
    rng = np.random.default_rng(2)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    accepted_a = []
    rejected_a = 0
    for i in range(8):  # way past slot(1) + engine queue(1) + tenant queue(2)
        try:
            router.submit(Request(i, prompt, max_new_tokens=4, tenant="a"))
            accepted_a.append(i)
        except QueueFull as exc:
            rejected_a += 1
            assert "'a'" in str(exc), "the rejection must name the bursting tenant"
    # Direct capacity before any step is the engine's bounded queue (1), then
    # tenant a's router-level queue (2): 3 accepted, the rest rejected at A's
    # own bound.
    assert rejected_a == 5 and len(accepted_a) == 3
    # Tenant B is NOT rejected by A's burst.
    router.submit(Request(100, prompt, max_new_tokens=4, tenant="b"))
    outputs = router.run()
    for i in accepted_a + [100]:
        assert router.results[i].finish_reason == "length"
        np.testing.assert_array_equal(outputs[i], _static_reference(model, prompt, 4))
    admission = router.stats["admission"]
    assert admission["rejected"] == {"a": 5}
    assert not admission["queued"]
    router.close()


def test_priority_dispatches_before_lower_priority_tenants():
    """Strict priority across tenant queues: with the fleet saturated, a
    high-priority request queued at the router dispatches before earlier
    lower-priority ones; equal-priority tenants round-robin (fair share)."""
    model = _model()
    router = Router(
        model, replicas=1, num_slots=1, max_length=64, chunk_size=4,
        max_queue=1, default_deadline_s=120.0, stall_degrade_s=None,
        tenant_queue_limit=4,
    )
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    router.submit(Request(0, prompt, max_new_tokens=8, tenant="a"))   # occupies the slot
    router.submit(Request(1, prompt, max_new_tokens=4, tenant="a"))   # engine queue
    router.submit(Request(2, prompt, max_new_tokens=4, tenant="a"))            # router queue, prio 0
    router.submit(Request(3, prompt, max_new_tokens=4, tenant="b", priority=5))  # router queue, prio 5
    router.run()
    admits = [e["request_id"] for e in router.routing_log if e["kind"] == "admit"]
    assert admits.index(3) < admits.index(2), (
        f"priority-5 tenant b must dispatch before tenant a's earlier request: {admits}"
    )
    assert all(router.results[i].finish_reason == "length" for i in range(4))
    router.close()


def test_admission_disabled_keeps_fleet_wide_queue_full_contract():
    """tenant_queue_limit=None (the default) preserves PR 10's contract
    exactly: a saturated fleet raises QueueFull for everyone."""
    model = _model()
    router = Router(
        model, replicas=1, num_slots=1, max_length=64, chunk_size=4,
        max_queue=1, default_deadline_s=120.0, stall_degrade_s=None,
    )
    rng = np.random.default_rng(4)
    prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    router.submit(Request(0, prompt, max_new_tokens=4))
    router.step()  # 0 admitted into the slot; the engine queue is free again
    router.submit(Request(1, prompt, max_new_tokens=4))
    with pytest.raises(QueueFull):
        router.submit(Request(2, prompt, max_new_tokens=4))
    assert "admission" not in router.stats
    router.run()
    router.close()


# ------------------------------------------------------------------ hedge quantile
def test_hedge_quantile_threshold_derivation():
    """hedge_quantile derives the trigger from the LIVE TTFT histogram:
    disabled below the sample floor, tracking the observed quantile above it;
    static hedge_after_s still wins when that spelling is used, and the two
    are mutually exclusive."""
    model = _model()
    router = Router(
        model, replicas=1, num_slots=1, max_length=64, chunk_size=4,
        max_queue=8, default_deadline_s=120.0, stall_degrade_s=None,
        hedge_quantile=0.95, hedge_min_samples=10,
    )
    assert router.hedge_threshold() is None, "cold histogram must not hedge"
    for _ in range(9):
        router._m_ttft.observe(0.010)
    assert router.hedge_threshold() is None, "below the sample floor"
    router._m_ttft.observe(0.010)
    threshold = router.hedge_threshold()
    assert threshold is not None and 0.005 <= threshold <= 0.05, threshold
    # The threshold is LIVE: a latency regression moves it, no retuning.
    for _ in range(30):
        router._m_ttft.observe(1.0)
    assert router.hedge_threshold() > 0.5
    router.close()

    static = Router(
        model, replicas=1, num_slots=1, max_queue=8, default_deadline_s=120.0,
        max_length=64, stall_degrade_s=None, hedge_after_s=0.25,
    )
    assert static.hedge_threshold() == 0.25
    static.close()

    with pytest.raises(ValueError, match="not both"):
        Router(model, replicas=1, max_queue=8, default_deadline_s=60.0,
               max_length=64, hedge_after_s=1.0, hedge_quantile=0.9)
    with pytest.raises(ValueError, match="quantile"):
        Router(model, replicas=1, max_queue=8, default_deadline_s=60.0,
               max_length=64, hedge_quantile=1.5)


def test_hedge_quantile_fires_and_never_duplicates_stream():
    """Behavioral: with a warm histogram whose quantile is ~0, a stuck queued
    request hedges onto the second replica exactly like the static-threshold
    path — one winner, no duplicated tokens (the PR 10 invariant under the
    new trigger)."""
    model = _model()
    router = Router(
        model, replicas=2, num_slots=1, max_length=64, chunk_size=4,
        max_queue=16, default_deadline_s=120.0, stall_degrade_s=None,
        rejoin_cooldown_s=0.01, probation_steps=1,
        hedge_quantile=0.5, hedge_min_samples=4,
    )
    for _ in range(4):
        router._m_ttft.observe(1e-9)  # warm histogram: hedge threshold ~ 0
    rng = np.random.default_rng(5)
    long_prompt = rng.integers(1, 128, (4,)).astype(np.int32)
    short_prompt = rng.integers(1, 128, (5,)).astype(np.int32)
    router.submit(Request(0, long_prompt, max_new_tokens=24))
    router.submit(Request(1, long_prompt, max_new_tokens=24))
    router.step()
    router.submit(Request(2, short_prompt, max_new_tokens=4))
    outputs = router.run()
    assert router.stats["hedges"] >= 1
    np.testing.assert_array_equal(outputs[2], _static_reference(model, short_prompt, 4))
    assert router.results[2].finish_reason == "length"
    for replica in router.replica_set.replicas:
        assert not replica.engine.pending
    router.close()

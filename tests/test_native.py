"""Native data-plane tests: build + bind, gather parity with numpy fancy indexing,
async double buffering, offload store round-trip with prefetch, and the fallback path
(ACCELERATE_TPU_DISABLE_NATIVE)."""

import os
import tempfile

import numpy as np
import pytest

from accelerate_tpu.native import (
    ArrayDataset,
    NativeGatherPool,
    NativeOffloadStore,
    native_available,
)
from accelerate_tpu.native.loader import NativeArrayLoader


def _columns(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": rng.integers(0, 1000, size=(n, 16)).astype(np.int32),
        "labels": rng.integers(0, 2, size=(n,)).astype(np.int64),
        "x": rng.normal(size=(n, 8)).astype(np.float32),
    }


def test_native_builds_and_loads():
    assert native_available(), "g++ toolchain present in image; native build must work"


def test_gather_matches_numpy():
    cols = _columns()
    pool = NativeGatherPool(num_threads=3)
    assert pool.native
    idx = [5, 0, 63, 17, 17, 2]
    out = pool.gather(cols, idx)
    for k in cols:
        np.testing.assert_array_equal(out[k], cols[k][np.asarray(idx)])
    pool.close()


def test_async_double_buffering():
    cols = _columns(seed=1)
    pool = NativeGatherPool(num_threads=2)
    t1 = pool.submit(cols, [0, 1, 2, 3])
    t2 = pool.submit(cols, [4, 5, 6, 7])
    b1 = pool.wait(t1)
    b2 = pool.wait(t2)
    np.testing.assert_array_equal(b1["x"], cols["x"][:4])
    np.testing.assert_array_equal(b2["x"], cols["x"][4:8])
    pool.close()


def test_native_array_loader_iterates_batches():
    from accelerate_tpu.data_loader import BatchSampler

    cols = _columns(n=32, seed=2)
    ds = ArrayDataset(cols)
    assert len(ds) == 32
    assert set(ds[3].keys()) == set(cols.keys())
    loader = NativeArrayLoader(ds, BatchSampler(range(32), 8))
    batches = list(loader)
    assert len(batches) == 4
    got = np.concatenate([b["input_ids"] for b in batches])
    np.testing.assert_array_equal(got, cols["input_ids"])


def test_native_loader_through_prepare_data_loader():
    """The native loader slots into the framework's device plane unchanged."""
    from accelerate_tpu.data_loader import BatchSampler, prepare_data_loader
    from accelerate_tpu.state import PartialState

    PartialState()
    cols = _columns(n=32, seed=3)
    loader = NativeArrayLoader(ArrayDataset(cols), BatchSampler(range(32), 8))
    prepared = prepare_data_loader(loader)
    seen = []
    for batch in prepared:
        seen.append(np.asarray(batch["labels"]))
    np.testing.assert_array_equal(np.concatenate(seen), cols["labels"])


def test_offload_store_round_trip_and_prefetch():
    tensors = {
        "layer0/kernel": np.random.default_rng(0).normal(size=(64, 32)).astype(np.float32),
        "layer0/bias": np.arange(32, dtype=np.float32),
        "layer1/kernel": np.random.default_rng(1).normal(size=(32, 16)).astype(np.bfloat16()
        if hasattr(np, "bfloat16")
        else np.float16),
    }
    with tempfile.TemporaryDirectory() as d:
        store = NativeOffloadStore(d, num_threads=2)
        store.save(tensors)
        # fresh open (exercises the index reload)
        store2 = NativeOffloadStore(d, num_threads=2)
        assert set(store2.keys()) == set(tensors.keys())
        store2.prefetch("layer0/kernel")
        for name, ref in tensors.items():
            got = store2.read(name)
            np.testing.assert_array_equal(got, ref)
        store.close()
        store2.close()


def test_empty_gather_does_not_hang():
    """Zero-subtask tickets complete immediately (advisor: Submit([]) used to deadlock)."""
    cols = _columns(n=8, seed=5)
    pool = NativeGatherPool(num_threads=2)
    out = pool.gather(cols, [])
    for k in cols:
        assert out[k].shape[0] == 0
    t = pool.submit(cols, [])
    out2 = pool.wait(t)
    assert out2["x"].shape[0] == 0
    pool.close()


def test_empty_store_read_and_prefetch():
    with tempfile.TemporaryDirectory() as d:
        store = NativeOffloadStore(d, num_threads=2)
        store.save({"empty": np.zeros((0, 4), dtype=np.float32)})
        got = store.read("empty")
        assert got.shape == (0, 4)
        store.prefetch("empty")
        got = store.read("empty")
        assert got.shape == (0, 4)
        store.close()


def test_prefetch_failure_surfaces_ioerror():
    """A prefetch whose pread fails raises on read() instead of returning garbage."""
    with tempfile.TemporaryDirectory() as d:
        store = NativeOffloadStore(d, num_threads=2)
        store.save({"w": np.arange(1024, dtype=np.float32)})
        if store.lib is None:
            pytest.skip("native lib unavailable")
        # Corrupt the index so the read runs past EOF (short read).
        store.index["w"]["offset"] = 10**9
        store.prefetch("w")
        with pytest.raises(IOError):
            store.read("w")
        store.close()


def test_fallback_without_native(monkeypatch):
    import importlib

    import accelerate_tpu.native as native_mod

    monkeypatch.setenv("ACCELERATE_TPU_DISABLE_NATIVE", "1")
    monkeypatch.setattr(native_mod, "_LIB", None)
    pool = NativeGatherPool(num_threads=2)
    assert not pool.native
    cols = _columns(n=8, seed=4)
    out = pool.gather(cols, [1, 3])
    np.testing.assert_array_equal(out["x"], cols["x"][[1, 3]])
    # async API also works (synchronously) in fallback
    t = pool.submit(cols, [0, 2])
    np.testing.assert_array_equal(pool.wait(t)["x"], cols["x"][[0, 2]])


def test_simple_loader_columnar_fast_path_through_prepare():
    """The DEFAULT journey: SimpleDataLoader over an ArrayDataset routes batch
    assembly through the native gather pool (no per-row Python loop), bit-identical
    to the per-row path, surviving the prepare() rebuild with a sharded sampler."""
    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader, prepare_data_loader
    from accelerate_tpu.state import PartialState

    PartialState()
    cols = _columns(n=32, seed=5)
    ds = ArrayDataset(cols)
    loader = SimpleDataLoader(ds, BatchSampler(range(32), 8))
    prepared = prepare_data_loader(loader)
    batches = [ {k: np.asarray(v) for k, v in b.items()} for b in prepared ]
    base = prepared.base_loader
    assert isinstance(base, SimpleDataLoader) and base._columnar()
    if native_available():
        assert base._gather_pool is not None and base._gather_pool.native

    # Per-row Python reference: identical batches.
    rowwise = SimpleDataLoader(list(ds[i] for i in range(32)), BatchSampler(range(32), 8))
    for got, ref in zip(batches, rowwise, strict=True):
        for k in cols:
            np.testing.assert_array_equal(got[k], ref[k])


def test_simple_loader_columnar_survives_skip_first_batches():
    from accelerate_tpu.data_loader import (
        BatchSampler,
        SimpleDataLoader,
        prepare_data_loader,
        skip_first_batches,
    )
    from accelerate_tpu.state import PartialState

    PartialState()
    cols = _columns(n=32, seed=6)
    loader = SimpleDataLoader(ArrayDataset(cols), BatchSampler(range(32), 8))
    prepared = prepare_data_loader(loader)
    resumed = skip_first_batches(prepared, 2)
    assert resumed.base_loader._columnar(), "index-plane skip must keep the columnar path"
    seen = [np.asarray(b["labels"]) for b in resumed]
    np.testing.assert_array_equal(np.concatenate(seen), cols["labels"][16:])


def test_abandoned_iterator_waits_inflight_ticket():
    """Early `break` out of a columnar loader must not leave an in-flight gather
    ticket whose destination buffers get freed under the C++ threads. The finally
    in iter_gather_batches waits it; afterwards the pool must be idle (a fresh
    synchronous gather completes correctly)."""
    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader

    cols = _columns(n=64, seed=7)
    loader = SimpleDataLoader(ArrayDataset(cols), BatchSampler(range(64), 8))
    for i, batch in enumerate(loader):
        if i == 1:
            break  # abandon mid-epoch with a ticket in flight
    import gc

    gc.collect()  # would segfault/corrupt if the ticket were still running
    pool = loader._gather_pool
    got = pool.gather(loader.dataset.columns, [0, 5, 9])
    np.testing.assert_array_equal(got["labels"], cols["labels"][[0, 5, 9]])


def test_redispatch_same_folder_resets_blob(tmp_path):
    """Re-dispatching into the same offload_folder must start a fresh blob, not
    append a second copy of the spilled weights (rerun-leak guard)."""
    from accelerate_tpu.big_modeling import disk_offload
    from accelerate_tpu.models.llama import LlamaLayeredApply, create_llama_model, llama_tiny

    model = create_llama_model(llama_tiny(), seq_len=16)
    layered = LlamaLayeredApply(llama_tiny())
    sizes = []
    for _ in range(2):
        disk_offload(model, str(tmp_path), layered=layered)
        sizes.append((tmp_path / "weights.bin").stat().st_size)
    assert sizes[1] == sizes[0], f"blob grew across re-dispatch: {sizes}"

"""Runtime-telemetry tests: metrics-registry semantics (buckets, quantiles,
thread safety — including under a concurrent ContinuousBatcher submit/drain
load), step-timeline/goodput arithmetic on a fake clock, profiler-manager
trigger/window mechanics against a stub backend, exporter round-trips
(Prometheus text, JSONL, stdlib HTTP), and the tier-1 pin that the INSTRUMENTED
serving path still holds the 0-recompile / 0-host-transfer discipline."""

import json
import threading
import time

import numpy as np
import pytest

from accelerate_tpu.telemetry import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsHTTPServer,
    MetricsRegistry,
    ProfilerManager,
    StepTimeline,
    TrackerBridge,
    log_spaced_buckets,
    parse_prometheus_text,
    to_prometheus_text,
    write_jsonl_snapshot,
    write_prometheus_textfile,
)

pytestmark = pytest.mark.telemetry


def _tiny_llama():
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model

    cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
    )
    return create_llama_model(cfg, seq_len=32)


# ------------------------------------------------------------------ histogram
def test_log_spaced_buckets_shape():
    buckets = log_spaced_buckets(1e-4, 100.0, per_decade=4)
    assert buckets == tuple(sorted(set(buckets)))
    assert buckets[0] == pytest.approx(1e-4)
    assert buckets[-1] >= 100.0
    # 6 decades * 4/decade + the closing bound: bounded memory by construction.
    assert len(buckets) == 25
    assert DEFAULT_LATENCY_BUCKETS == buckets


def test_histogram_bucket_property_every_observation_lands_once():
    """Property over random workloads: bucket counts partition the
    observations — sum(counts) == N for any inputs, including values outside
    [lo, hi] (the overflow bucket absorbs the top, the first bucket the
    bottom)."""
    rng = np.random.default_rng(0)
    for trial in range(5):
        registry = MetricsRegistry()
        hist = registry.histogram(f"h{trial}", buckets=log_spaced_buckets(1e-3, 10.0, 3))
        values = np.exp(rng.normal(-2.0, 2.5, size=500))  # spills both ends
        for v in values:
            hist.observe(float(v))
        counts = hist.bucket_counts()
        assert sum(counts) == hist.count == 500
        assert hist.sum == pytest.approx(float(values.sum()), rel=1e-9)
        # cumulative monotonicity (what the Prometheus _bucket series encodes)
        cumulative = np.cumsum(counts)
        assert (np.diff(cumulative) >= 0).all()


def test_histogram_quantile_within_bucket_resolution():
    """The interpolated quantile can never be off by more than one bucket:
    estimate and true percentile fall in the same (or adjacent) log bucket, so
    their ratio is bounded by the bucket width 10^(1/per_decade)."""
    rng = np.random.default_rng(1)
    per_decade = 4
    width = 10 ** (1 / per_decade)
    registry = MetricsRegistry()
    hist = registry.histogram("lat", buckets=log_spaced_buckets(1e-4, 100.0, per_decade))
    values = np.exp(rng.normal(np.log(0.05), 1.0, size=2000))
    for v in values:
        hist.observe(float(v))
    for q in (0.1, 0.5, 0.9, 0.99):
        true = float(np.percentile(values, q * 100))
        est = hist.quantile(q)
        assert est is not None
        assert est / true < width * 1.01 and true / est < width * 1.01, (q, est, true)


def test_histogram_quantile_edge_cases():
    registry = MetricsRegistry()
    hist = registry.histogram("edge", buckets=(1.0, 10.0))
    assert hist.quantile(0.5) is None  # empty
    hist.observe(1e9)  # overflow-only
    assert hist.quantile(0.99) == 10.0  # clamped to the top finite bound
    with pytest.raises(ValueError):
        hist.quantile(1.5)


def test_instruments_reject_device_like_values():
    """The zero-device-sync gate: anything that is not a host int/float is
    refused (a jax array would hide a blocking readback inside a metrics
    call)."""
    registry = MetricsRegistry()
    with pytest.raises(TypeError):
        registry.counter("c").inc(np.ones(3))  # array-like
    with pytest.raises(TypeError):
        registry.histogram("h").observe("0.5")
    with pytest.raises(TypeError):
        registry.gauge("g").set(True)  # bool is not a measurement
    registry.histogram("h").observe(np.float64(0.5))  # numpy scalar IS a float


# ------------------------------------------------------------------- registry
def test_registry_get_or_create_identity_and_kind_conflicts():
    registry = MetricsRegistry()
    a = registry.counter("requests_total", labels={"reason": "eos"})
    b = registry.counter("requests_total", labels={"reason": "eos"})
    c = registry.counter("requests_total", labels={"reason": "length"})
    assert a is b and a is not c
    with pytest.raises(ValueError):
        registry.gauge("requests_total", labels={"reason": "eos"})
    with pytest.raises(ValueError):
        registry.counter("bad name!")
    a.inc()
    assert registry.value("requests_total", {"reason": "eos"}) == 1
    assert registry.value("requests_total", {"reason": "length"}) == 0


def test_registry_thread_safety_exact_counts():
    """8 writers x 5000 increments + concurrent histogram observes: totals are
    exact (no lost updates), which is the property the serving engine relies
    on when submit() runs on request-handler threads."""
    registry = MetricsRegistry()
    counter = registry.counter("hits_total")
    hist = registry.histogram("lat_seconds")

    def hammer():
        for i in range(5000):
            counter.inc()
            hist.observe(0.001 * (1 + i % 7))

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert counter.value == 8 * 5000
    assert hist.count == 8 * 5000
    assert sum(hist.bucket_counts()) == 8 * 5000


def test_registry_under_concurrent_serving_submit_drain():
    """The satellite's integration load: a producer thread submits requests
    while the main thread drains the engine — every registry count balances
    afterwards (submitted == finished == TTFT observations; no torn or lost
    updates between the two threads)."""
    from accelerate_tpu.serving import ContinuousBatcher, Request

    engine = ContinuousBatcher(_tiny_llama(), num_slots=2, max_length=64, chunk_size=4)
    rng = np.random.default_rng(2)
    n = 10
    prompts = [rng.integers(1, 128, (int(rng.integers(3, 9)),)).astype(np.int32) for _ in range(n)]

    def producer():
        for i, p in enumerate(prompts):
            engine.submit(Request(i, p, max_new_tokens=6))
            time.sleep(0.002)

    thread = threading.Thread(target=producer)
    thread.start()
    deadline = time.monotonic() + 60
    while (thread.is_alive() or engine.pending) and time.monotonic() < deadline:
        engine.step()
    thread.join()
    assert all(r.finished for r in engine.results.values())
    registry = engine.metrics
    assert registry.value("serving_requests_submitted_total") == n
    finished = sum(engine.stats["finish_reasons"].values())
    assert finished == n
    assert registry.get("serving_ttft_seconds").count == n
    assert engine.stats["finish_reasons"]["length"] == n  # EOS-free workload
    assert registry.value("serving_slot_utilization") == 0.0  # all drained


# ------------------------------------------------------------------- timeline
def test_step_timeline_phases_and_goodput_arithmetic():
    clock = {"t": 100.0}
    registry = MetricsRegistry()
    timeline = StepTimeline(registry, prefix="train", clock=lambda: clock["t"])

    for _ in range(3):
        with timeline.phase("data_wait"):
            clock["t"] += 0.5
        with timeline.phase("dispatch"):
            clock["t"] += 1.5
        timeline.step_done()
    timeline.charge("checkpoint", 4.0)
    clock["t"] += 2.0  # unaccounted host time

    report = timeline.goodput()
    assert report["steps"] == 3
    assert report["total_s"] == pytest.approx(8.0)  # 3*(0.5+1.5) + 2.0
    assert report["productive_s"] == pytest.approx(6.0)
    assert report["lost_s"] == {"checkpoint": 4.0}
    assert report["unaccounted_s"] == pytest.approx(0.0)  # lost overlaps clamped at 0
    assert report["goodput"] == pytest.approx(6.0 / 8.0)
    assert report["phase_s"]["data_wait"] == pytest.approx(1.5)
    assert report["phase_s"]["dispatch"] == pytest.approx(4.5)
    assert registry.value("train_steps_total") == 3
    assert registry.get("train_step_seconds").count == 3
    assert registry.value("train_lost_seconds_total", {"cause": "checkpoint"}) == pytest.approx(4.0)
    assert registry.value("train_goodput_ratio") == pytest.approx(6.0 / 8.0)

    timeline.reset()
    assert timeline.goodput()["steps"] == 0
    with pytest.raises(ValueError):
        timeline.charge("checkpoint", -1.0)


def test_step_timeline_folds_trace_guard_ledger():
    from accelerate_tpu.analysis import TraceGuard

    registry = MetricsRegistry()
    timeline = StepTimeline(registry, prefix="train")
    guard = TraceGuard(on_violation="record", name="t")
    guard.compiles["fused_step"] = 2
    guard.transfer_violations.append("Disallowed device-to-host transfer ...")
    timeline.observe_trace_guard(guard)
    timeline.observe_trace_guard(guard)  # idempotent folding, not double-count
    assert registry.value("train_recompiles_total") == 2
    assert registry.value("train_guarded_transfers_total") == 1


# ------------------------------------------------------------------- profiler
class _StubProfiler:
    def __init__(self):
        self.calls = []
        self.tracing = False

    def start_trace(self, log_dir):
        assert not self.tracing
        self.tracing = True
        self.calls.append(("start", log_dir))

    def stop_trace(self):
        assert self.tracing
        self.tracing = False
        self.calls.append(("stop",))

    def save_device_memory_profile(self, path):
        with open(path, "w") as f:
            f.write("pprof")
        self.calls.append(("memory", path))


def test_profiler_touch_file_trigger_and_fixed_window(tmp_path):
    clock = {"t": 0.0}
    stub = _StubProfiler()
    manager = ProfilerManager(
        log_dir=str(tmp_path),
        capture_seconds=5.0,
        poll_every=1,
        backend=stub,
        clock=lambda: clock["t"],
    )
    assert manager.enabled and not manager.poll()  # no trigger yet

    (tmp_path / "CAPTURE").touch()
    assert manager.poll() is True  # trigger consumed, window opened
    assert not (tmp_path / "CAPTURE").exists()
    assert stub.tracing
    clock["t"] += 4.0
    assert manager.poll() is True  # window still open
    clock["t"] += 2.0
    assert manager.poll() is False  # 6s > 5s window: auto-closed
    assert not stub.tracing
    assert manager.registry.value("profiler_captures_total") == 1
    assert manager.registry.value("profiler_active") == 0


def test_profiler_signal_latch_trace_scope_and_memory(tmp_path):
    stub = _StubProfiler()
    manager = ProfilerManager(log_dir=str(tmp_path), poll_every=1, backend=stub)
    manager.request_capture()  # what the SIGUSR2 handler latches
    assert manager.poll() is True
    assert manager.stop() is True and manager.stop() is False  # idempotent

    with manager.trace(subdir="scoped") as target:
        assert target.endswith("scoped") and stub.tracing
    assert not stub.tracing

    path = manager.save_memory_snapshot()
    assert path is not None and ("memory", path) in stub.calls

    disabled = ProfilerManager(log_dir=None, backend=stub)
    assert not disabled.enabled
    assert disabled.start() is None and disabled.poll() is False
    assert disabled.save_memory_snapshot() is None


def test_profiler_from_env_reads_launch_protocol(tmp_path, monkeypatch):
    monkeypatch.setenv("ACCELERATE_TPU_PROFILE_DIR", str(tmp_path / "prof"))
    manager = ProfilerManager.from_env(install_signal=False, backend=_StubProfiler())
    assert manager.enabled and manager.log_dir == str(tmp_path / "prof")
    monkeypatch.delenv("ACCELERATE_TPU_PROFILE_DIR")
    assert not ProfilerManager.from_env(backend=_StubProfiler()).enabled


# ------------------------------------------------------------------- exporters
def _populated_registry():
    registry = MetricsRegistry()
    registry.counter("reqs_total", help="requests", labels={"reason": "eos"}).inc(3)
    registry.counter("reqs_total", labels={"reason": "length"}).inc(7)
    registry.gauge("queue_depth", help="waiting").set(2)
    hist = registry.histogram("ttft_seconds", help="ttft", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        hist.observe(v)
    return registry


def test_prometheus_text_round_trip():
    registry = _populated_registry()
    parsed = parse_prometheus_text(to_prometheus_text(registry))
    assert parsed["reqs_total"]["type"] == "counter"
    assert parsed["reqs_total"]["samples"][(("reason", "eos"),)] == 3
    assert parsed["reqs_total"]["samples"][(("reason", "length"),)] == 7
    assert parsed["queue_depth"]["samples"][()] == 2
    buckets = parsed["ttft_seconds_bucket"]["samples"]
    assert buckets[(("le", "0.01"),)] == 1
    assert buckets[(("le", "0.1"),)] == 3
    assert buckets[(("le", "1"),)] == 4
    assert buckets[(("le", "+Inf"),)] == 5
    assert parsed["ttft_seconds_count"]["samples"][()] == 5
    assert parsed["ttft_seconds_sum"]["samples"][()] == pytest.approx(5.605)


def test_prometheus_label_escapes_round_trip():
    """Hostile label values (quotes, newlines, literal backslash-n, commas)
    survive the wire: decoding must be one left-to-right pass — sequential
    replace() corrupts a literal backslash followed by 'n'."""
    registry = MetricsRegistry()
    nasty = ['a"b', "line\nbreak", r"back\slash", r"literal\n", "comma,inside", "\\"]
    for i, value in enumerate(nasty):
        registry.counter("odd_total", labels={"v": value}).inc(i + 1)
    parsed = parse_prometheus_text(to_prometheus_text(registry))
    samples = parsed["odd_total"]["samples"]
    for i, value in enumerate(nasty):
        assert samples[(("v", value),)] == i + 1, value


def test_log_spaced_buckets_cover_hi_on_fractional_decades():
    buckets = log_spaced_buckets(1e-4, 90.0, per_decade=4)
    assert buckets[-1] >= 90.0  # values in (last_bound, hi] must not overflow


def test_timeline_record_phase_does_not_reopen_step():
    clock = {"t": 0.0}
    timeline = StepTimeline(MetricsRegistry(), prefix="t", clock=lambda: clock["t"])
    with timeline.phase("dispatch"):
        clock["t"] += 1.0
    timeline.step_done()
    timeline.record_phase("block", 0.5)  # post-step readback attribution
    assert timeline._step_open_since is None
    clock["t"] += 0.5
    report = timeline.goodput()
    assert report["phase_s"]["block"] == pytest.approx(0.5)
    assert report["productive_s"] == pytest.approx(1.0)  # block did not inflate the next step


def test_prometheus_textfile_and_jsonl(tmp_path):
    registry = _populated_registry()
    prom = tmp_path / "metrics.prom"
    write_prometheus_textfile(registry, str(prom))
    assert "reqs_total" in prom.read_text()

    jsonl = tmp_path / "snapshots.jsonl"
    write_jsonl_snapshot(registry, str(jsonl), step=1)
    registry.gauge("queue_depth").set(9)
    write_jsonl_snapshot(registry, str(jsonl), step=2, run="r06")
    lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert len(lines) == 2 and lines[1]["step"] == 2 and lines[1]["run"] == "r06"
    by_name = {m["name"]: m for m in lines[1]["metrics"] if m["name"] == "queue_depth"}
    assert by_name["queue_depth"]["value"] == 9
    hist_entries = [m for m in lines[0]["metrics"] if m["kind"] == "histogram"]
    assert hist_entries and sum(hist_entries[0]["bucket_counts"]) == hist_entries[0]["count"]


def test_metrics_http_server_serves_prometheus_text():
    import urllib.request

    registry = _populated_registry()
    server = MetricsHTTPServer(registry, port=0)
    try:
        with urllib.request.urlopen(server.url, timeout=10) as resp:
            body = resp.read().decode()
        parsed = parse_prometheus_text(body)
        assert parsed["reqs_total"]["samples"][(("reason", "eos"),)] == 3
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://{server.host}:{server.port}/nope", timeout=10)
    finally:
        server.close()


def test_tracker_bridge_flattens_through_accelerator_log():
    class FakeAccelerator:
        telemetry = _populated_registry()

        def __init__(self):
            self.logged = []

        def log(self, values, step=None, log_kwargs=None):
            self.logged.append((values, step))

    accelerator = FakeAccelerator()
    bridge = TrackerBridge(accelerator)
    values = bridge.publish(step=7)
    assert accelerator.logged[0][1] == 7
    assert values["telemetry/reqs_total.reason=eos"] == 3
    assert values["telemetry/ttft_seconds.count"] == 5
    assert "telemetry/ttft_seconds.p50" in values


# ------------------------------------------ serving integration (acceptance)
def test_instrumented_serving_steady_state_holds_0_0_and_exports(trace_guard):
    """The acceptance pin: with full telemetry wired in, steady-state serving
    still measures 0 recompiles / 0 guarded host transfers, and a
    Prometheus-text snapshot of the TTFT/inter-token histograms and queue/slot
    gauges round-trips through export.py with the exact counts the engine
    recorded."""
    from accelerate_tpu.serving import ContinuousBatcher, Request
    from accelerate_tpu.test_utils.analysis_fixtures import assert_compiles

    engine = ContinuousBatcher(_tiny_llama(), num_slots=2, max_length=64, chunk_size=4)
    rng = np.random.default_rng(3)
    for i in range(3):  # warmup: compile insert bucket + the one chunk program
        engine.submit(Request(i, rng.integers(1, 128, (5,)).astype(np.int32), max_new_tokens=8))
    while engine.pending:
        engine.step()

    guard = trace_guard(name="telemetry-serving")
    engine.trace_guard = guard
    for i in range(10, 14):
        engine.submit(Request(i, rng.integers(1, 128, (6,)).astype(np.int32), max_new_tokens=8))
    with guard:
        while engine.pending:
            engine.step()
    assert_compiles(guard, exactly=0)
    assert guard.host_transfers == 0
    assert engine.trace_counts["decode_chunk"] == 1

    registry = engine.metrics
    ttft = registry.get("serving_ttft_seconds")
    inter = registry.get("serving_inter_token_seconds")
    assert ttft.count == 7 and inter.count > 0
    parsed = parse_prometheus_text(to_prometheus_text(registry))
    assert parsed["serving_ttft_seconds_count"]["samples"][()] == 7
    assert parsed["serving_inter_token_seconds_count"]["samples"][()] == inter.count
    assert parsed["serving_queue_depth"]["samples"][()] == 0
    assert parsed["serving_slots_in_use"]["samples"][()] == 0
    reasons = {
        labels[0][1]: v
        for labels, v in parsed["serving_requests_finished_total"]["samples"].items()
    }
    assert sum(reasons.values()) == 7
    # stats stays the back-compat view over the same instruments
    assert engine.stats["inserts"] == 7
    assert engine.stats["finish_reasons"]["error"] == 0


def test_accelerator_owns_telemetry_and_instruments_train_step():
    """Accelerator construction wires registry + timeline + profiler; the
    fused step bumps the step counter and times the dispatch phase without
    changing results."""
    import optax

    from accelerate_tpu import Accelerator, SimpleDataLoader
    from accelerate_tpu.data_loader import BatchSampler

    from test_training import make_regression_data, make_regression_model

    accelerator = Accelerator()
    assert accelerator.telemetry is accelerator.timeline.registry
    assert not accelerator.profiler.enabled  # env protocol not armed
    data = make_regression_data(n=32)
    model = make_regression_model(seed=0)
    dl = SimpleDataLoader(data, BatchSampler(range(len(data)), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.sgd(0.05), dl)
    step_fn = accelerator.train_step()
    for batch in pdl:
        step_fn(batch)
    registry = accelerator.telemetry
    assert registry.value("train_steps_total") == 4
    assert registry.get("train_dispatch_seconds").count == 4
    assert accelerator.timeline.goodput()["steps"] == 4

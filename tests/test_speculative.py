"""Speculative-decode tests (speculative.py + the Generator/ContinuousBatcher
draft-then-verify paths).

Pins the three load-bearing contracts:
  1. the n-gram drafter only ever proposes verbatim continuations of observed
     context (never out-of-vocab, never past the observed length), and
     degrades to valid_len == 0 — plain decode — on degenerate input;
  2. greedy output is TOKEN-IDENTICAL with speculation on vs off, across
     {llama, gpt_neox} x {paged, contiguous} serving engines, slot reuse,
     EOS inside a verified block, and the static Generator loop — the
     verification invariant that makes the speedup safe to ship;
  3. the no-recompile discipline survives: one decode executable for the
     engine lifetime with speculation enabled, and the speedup is a measured
     number (accepted_tokens_per_step) wired through the metrics registry.
"""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from accelerate_tpu.generation import GenerationConfig, Generator, generate
from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
from accelerate_tpu.serving import ContinuousBatcher, Request
from accelerate_tpu.speculative import greedy_accept_length, propose_ngram_drafts

pytestmark = pytest.mark.speculative


def _model(max_pos=64):
    cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=max_pos,
        rope_theta=10000.0,
    )
    return create_llama_model(cfg, seq_len=32)


def _neox_model(max_pos=64):
    from accelerate_tpu.models.gpt_neox import create_gpt_neox_model, gpt_neox_tiny

    cfg = dataclasses.replace(gpt_neox_tiny(), max_position_embeddings=max_pos)
    return create_gpt_neox_model(cfg, seq_len=32)


def _static_reference(model, prompt, max_new, **kwargs):
    out = np.asarray(generate(model, prompt[None, :], max_new_tokens=max_new, **kwargs))
    return out[0, prompt.size :]


# ------------------------------------------------------------------- drafter
def test_drafter_proposals_are_continuations_of_observed_context():
    """Property sweep: for random histories, every proposal within valid_len
    is the verbatim continuation of the most recent earlier occurrence of the
    trailing n-gram — i.e. drafts[:j] == history[match+m : match+m+j]. In
    particular every proposed token was OBSERVED (in-context, in-vocab)."""
    rng = np.random.default_rng(0)
    for trial in range(50):
        h = int(rng.integers(8, 40))
        hist_len = int(rng.integers(3, h + 1))
        k = int(rng.integers(1, 6))
        m = int(rng.integers(1, 4))
        # small alphabet so n-gram collisions actually happen
        hist = np.zeros((1, h), np.int32)
        hist[0, :hist_len] = rng.integers(1, 6, hist_len)
        drafts, valid = (
            np.asarray(x)
            for x in propose_ngram_drafts(jnp.asarray(hist), jnp.asarray([hist_len], jnp.int32), k, m)
        )
        v = int(valid[0])
        assert 0 <= v <= k
        if v == 0:
            continue
        tail = hist[0, hist_len - m : hist_len]
        # reference: most recent strictly-earlier occurrence of the tail n-gram
        starts = [
            i for i in range(hist_len - m)
            if np.array_equal(hist[0, i : i + m], tail)
        ]
        assert starts, "drafter proposed but no real n-gram match exists"
        j = max(starts)
        expect = hist[0, j + m : min(j + m + k, hist_len)]
        assert v == len(expect[:k]) or v == min(k, hist_len - (j + m))
        np.testing.assert_array_equal(drafts[0, :v], hist[0, j + m : j + m + v])
        assert set(drafts[0, :v]).issubset(set(hist[0, :hist_len].tolist()))


def test_drafter_degenerates_to_no_proposals():
    """No match, context shorter than the n-gram, or a fresh 1-token context
    all yield valid_len == 0 — the verify step then emits exactly one token,
    like plain decode."""
    # all-distinct tokens: the trailing bigram never occurred before
    hist = np.arange(1, 11, dtype=np.int32)[None, :]
    _, valid = propose_ngram_drafts(jnp.asarray(hist), jnp.asarray([10], jnp.int32), 4, 2)
    assert int(np.asarray(valid)[0]) == 0
    # context shorter than the n-gram
    _, valid = propose_ngram_drafts(jnp.asarray(hist), jnp.asarray([1], jnp.int32), 4, 2)
    assert int(np.asarray(valid)[0]) == 0


def test_drafter_respects_observed_length_bound():
    """A match right before the tail has fewer than k observed continuation
    tokens: valid_len must stop at the observed boundary, never proposing the
    unknown future."""
    # history: A B C A B  (tail bigram A B matched at 0, continuation = C only... )
    hist = np.asarray([[7, 8, 9, 7, 8, 0, 0, 0]], np.int32)
    drafts, valid = propose_ngram_drafts(jnp.asarray(hist), jnp.asarray([5], jnp.int32), 4, 2)
    # match at start 0; continuations observed: history[2:5] = [9, 7, 8]
    assert int(np.asarray(valid)[0]) == 3
    np.testing.assert_array_equal(np.asarray(drafts)[0, :3], [9, 7, 8])


def test_drafter_prefers_most_recent_match():
    # bigram (1,2) occurs at 0 (-> 3) and at 4 (-> 5); the tail occurrence at
    # 8 must match position 4's continuation, not position 0's.
    hist = np.asarray([[1, 2, 3, 9, 1, 2, 5, 9, 1, 2]], np.int32)
    drafts, valid = propose_ngram_drafts(jnp.asarray(hist), jnp.asarray([10], jnp.int32), 2, 2)
    assert int(np.asarray(valid)[0]) == 2
    np.testing.assert_array_equal(np.asarray(drafts)[0], [5, 9])


def test_greedy_accept_length_masks_and_prefixes():
    drafts = jnp.asarray([[4, 5, 6], [4, 5, 6], [4, 9, 6], [4, 5, 6]], jnp.int32)
    greedy = jnp.asarray([[4, 5, 6], [4, 5, 9], [4, 5, 6], [4, 5, 6]], jnp.int32)
    valid = jnp.asarray([3, 3, 3, 1], jnp.int32)
    got = np.asarray(greedy_accept_length(drafts, greedy, valid))
    # full match; mismatch at 2; mismatch at 1 (prefix rule, 6==6 at 2 is moot);
    # full match but only 1 valid proposal
    np.testing.assert_array_equal(got, [3, 2, 1, 1])


# ----------------------------------------------------- serving parity sweep
@pytest.mark.parametrize("family", ["llama", "gpt_neox"])
@pytest.mark.parametrize("paged", [True, False], ids=["paged", "contiguous"])
def test_serving_greedy_parity_spec_vs_nonspec(family, paged):
    """THE verification invariant: greedy tokens are identical with
    speculation on vs off, per request, across mixed prompt lengths/budgets
    and slot reuse — for both model families and both cache layouts."""
    model = _model() if family == "llama" else _neox_model()
    vocab = model.module.config.vocab_size
    rng = np.random.default_rng(11)
    lengths = [5, 9, 3, 12, 7]
    budgets = [6, 4, 8, 3, 5]
    prompts = [rng.integers(1, vocab, (n,)).astype(np.int32) for n in lengths]
    requests = lambda: [  # noqa: E731 — rebuilt per engine (ids reused)
        Request(i, p, max_new_tokens=m) for i, (p, m) in enumerate(zip(prompts, budgets))
    ]
    plain = ContinuousBatcher(model, num_slots=2, max_length=32, chunk_size=4, paged=paged)
    spec = ContinuousBatcher(
        model, num_slots=2, max_length=32, chunk_size=4, paged=paged,
        speculative=True, draft_tokens=3,
    )
    ref = plain.run(requests())
    got = spec.run(requests())
    for i in range(len(prompts)):
        np.testing.assert_array_equal(got[i], ref[i])
        assert spec.results[i].finish_reason == plain.results[i].finish_reason


@pytest.mark.parametrize("paged", [True, False], ids=["paged", "contiguous"])
def test_eos_inside_verified_block_matches_one_token_path(paged):
    """Satellite bugfix pin: an accepted EOS inside a verified block must end
    the request THERE — tail discarded, result ending with the EOS token, the
    same `_trim_at_eos` semantics as the one-token path. draft_tokens=4 with
    chunk_size=3 makes blocks regularly straddle the EOS."""
    model = _model()
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, 128, (6,)).astype(np.int32)
    free_run = _static_reference(model, prompt, 16)
    eos = int(free_run[len(free_run) // 2])
    ref = _static_reference(model, prompt, 16, eos_token_id=eos)
    engine = ContinuousBatcher(
        model, num_slots=2, max_length=32, chunk_size=3, paged=paged,
        speculative=True, draft_tokens=4,
    )
    outputs = engine.run([Request(0, prompt, max_new_tokens=16, eos_token_id=eos)])
    np.testing.assert_array_equal(outputs[0], ref)
    assert engine.results[0].finish_reason == "eos"
    assert outputs[0][-1] == eos
    # the discarded tail must not count against anything: a fresh request in
    # the reused slot still matches its own reference
    prompt2 = rng.integers(1, 128, (4,)).astype(np.int32)
    outputs = engine.run([Request(1, prompt2, max_new_tokens=6)])
    np.testing.assert_array_equal(outputs[1], _static_reference(model, prompt2, 6))


def test_decode_compiled_once_with_speculation():
    """The no-recompile discipline survives speculation: one decode executable
    across mixed admissions, insert buckets unchanged, and every accept/reject
    decision a traced op — `trace_counts` is the trace-time witness."""
    model = _model()
    rng = np.random.default_rng(0)
    engine = ContinuousBatcher(
        model, num_slots=2, max_length=64, chunk_size=4, speculative=True, draft_tokens=4
    )
    lengths = [3, 5, 9, 17, 6, 30]
    engine.run(
        [
            Request(i, rng.integers(1, 128, (n,)).astype(np.int32), max_new_tokens=4)
            for i, n in enumerate(lengths)
        ]
    )
    assert engine.trace_counts["decode_chunk"] == 1
    assert engine._chunk_fn._cache_size() == 1
    assert all(r.finished for r in engine.results.values())


def test_accepted_tokens_per_step_is_measured_and_exceeds_one():
    """The speedup is a measured number, not a claim: on a repetitive workload
    (tiny-model greedy decode collapses into loops, prompt-lookup's best case)
    the engine's accepted_tokens_per_step must exceed 1.0, the ledger must
    reconcile (drafted == accepted + rejected), and the histogram must carry
    one observation per verify step."""
    model = _model()
    rng = np.random.default_rng(2)
    engine = ContinuousBatcher(
        model, num_slots=2, max_length=64, chunk_size=4, speculative=True, draft_tokens=4
    )
    engine.run(
        [
            Request(i, rng.integers(1, 128, (6,)).astype(np.int32), max_new_tokens=40)
            for i in range(4)
        ]
    )
    spec = engine.stats["speculative"]
    assert spec["accepted_tokens_per_step"] is not None
    assert spec["accepted_tokens_per_step"] > 1.0, spec
    assert spec["drafted"] == spec["accepted"] + spec["rejected"]
    hist = engine.metrics.get("serving_spec_accepted_tokens")
    assert hist is not None and hist.count == spec["verify_steps"]
    # tokens conservation: every result token came from a verify step (steps +
    # accepted drafts) or was a request's insert-sampled first token
    emitted = sum(len(r.tokens) for r in engine.results.values())
    assert emitted == spec["verify_steps"] + spec["accepted"] + len(engine.results)


def test_speculative_admission_reserves_the_draft_window():
    """Paged admission counts the draft window against the reservation: with
    page_size 4, an (8 prompt + 8 new) request needs 4 pages plain but 5 with
    a 4-token draft window — so a pool of 9 usable pages fits two plain
    requests at once but only one speculative one. Both engines still finish
    everything (reserve-on-admit queues, never deadlocks), token-identically."""
    model = _model()
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 128, (8,)).astype(np.int32) for _ in range(2)]
    requests = lambda: [Request(i, p, max_new_tokens=8) for i, p in enumerate(prompts)]  # noqa: E731

    def peak_pages(**kwargs):
        engine = ContinuousBatcher(
            model, num_slots=2, max_length=32, chunk_size=2,
            page_size=4, num_pages=10, prefix_cache=False, **kwargs,
        )
        for r in requests():
            engine.submit(r)
        peak = 0
        while engine.pending:
            engine.step()
            peak = max(peak, engine.pool.pages_in_use)
        outs = {rid: np.asarray(r.tokens, np.int32) for rid, r in engine.results.items()}
        assert engine.pool.pages_in_use == 0
        return peak, outs

    plain_peak, ref = peak_pages()
    spec_peak, got = peak_pages(speculative=True, draft_tokens=4)
    assert plain_peak == 8, plain_peak  # both requests in flight, 4 pages each
    assert spec_peak == 5, spec_peak  # window forces one-at-a-time admission
    for i in range(2):
        np.testing.assert_array_equal(got[i], ref[i])


def test_submit_rejects_when_draft_window_exceeds_pool():
    model = _model()
    engine = ContinuousBatcher(
        model, num_slots=1, max_length=32, chunk_size=2,
        page_size=4, num_pages=5, speculative=True, draft_tokens=4,
    )
    prompt = np.arange(1, 9, dtype=np.int32)
    # 8 prompt + 5 new + 4 window = 17 tokens -> 5 pages > 4 usable
    with pytest.raises(ValueError, match="draft-window"):
        engine.submit(Request(0, prompt, max_new_tokens=5))
    # the same request fits once the window is accounted for
    engine.submit(Request(1, prompt, max_new_tokens=4))
    engine.run()
    assert engine.results[1].finished


def test_speculative_config_validation():
    model = _model()
    with pytest.raises(ValueError, match="greedy-only"):
        ContinuousBatcher(model, num_slots=1, max_length=32, speculative=True, do_sample=True)
    with pytest.raises(ValueError, match="repetition"):
        ContinuousBatcher(
            model, num_slots=1, max_length=32, speculative=True, use_repetition_penalty=True
        )
    with pytest.raises(ValueError, match="draft_tokens"):
        ContinuousBatcher(model, num_slots=1, max_length=32, speculative=True, draft_tokens=0)
    gen = Generator(model, max_new_tokens=8, max_length=32)
    prompt = np.arange(1, 7, dtype=np.int32)[None, :]
    with pytest.raises(ValueError, match="greedy-only"):
        gen(prompt, GenerationConfig(max_new_tokens=4, draft_tokens=2, do_sample=True))
    with pytest.raises(ValueError, match="repetition_penalty"):
        gen(prompt, GenerationConfig(max_new_tokens=4, draft_tokens=2, repetition_penalty=1.5))


# ------------------------------------------------------------ static Generator
def test_generator_speculative_parity_single_and_batch():
    """The fused static loop's draft/verify variant is token-identical to the
    plain loop — batch-1 (full speedup) and batch-3 (lockstep minimum)."""
    model = _model(max_pos=128)
    gen = Generator(model, max_new_tokens=48, max_length=128)
    for seed, (b, n) in enumerate([(1, 48), (3, 24), (1, 7)]):
        p = np.random.default_rng(seed).integers(1, 128, (b, 8)).astype(np.int32)
        ref = np.asarray(gen(p, GenerationConfig(max_new_tokens=n)))
        spec = np.asarray(gen(p, GenerationConfig(max_new_tokens=n, draft_tokens=4)))
        np.testing.assert_array_equal(spec, ref)


def test_generator_speculative_eos_and_trim_parity():
    model = _model(max_pos=128)
    gen = Generator(model, max_new_tokens=48, max_length=128)
    p = np.random.default_rng(0).integers(1, 128, (1, 8)).astype(np.int32)
    free = np.asarray(gen(p, GenerationConfig(max_new_tokens=48)))[0, 8:]
    eos = int(free[len(free) // 2])
    ref = np.asarray(gen(p, GenerationConfig(max_new_tokens=48, eos_token_id=eos)))
    spec = np.asarray(gen(p, GenerationConfig(max_new_tokens=48, eos_token_id=eos, draft_tokens=4)))
    np.testing.assert_array_equal(spec, ref)  # incl. _trim_at_eos truncation


def test_generator_speculative_ragged_left_padded_batch():
    """Left-padded ragged prompts ride the speculative loop too: pads sit in
    the drafter's physical history, but acceptance requires the model's own
    argmax, so parity is unconditional."""
    model = _model(max_pos=128)
    gen = Generator(model, max_new_tokens=16, max_length=128)
    rng = np.random.default_rng(9)
    ids = np.zeros((2, 8), np.int32)
    mask = np.zeros((2, 8), np.int32)
    for row, n in enumerate((5, 8)):
        ids[row, 8 - n :] = rng.integers(1, 128, (n,))
        mask[row, 8 - n :] = 1
    ref = np.asarray(gen(ids, GenerationConfig(max_new_tokens=12), attention_mask=mask))
    spec = np.asarray(
        gen(ids, GenerationConfig(max_new_tokens=12, draft_tokens=3), attention_mask=mask)
    )
    np.testing.assert_array_equal(spec, ref)


def test_generator_one_executable_per_bucket_across_prompt_lengths():
    """Varying prompt lengths must reuse the one compiled speculative loop per
    bucket (the history operand is max_length-sized precisely so prompt width
    never leaks into the decode signature)."""
    model = _model(max_pos=128)
    gen = Generator(model, max_new_tokens=16, max_length=128)
    cfg = GenerationConfig(max_new_tokens=16, draft_tokens=3)
    for n in (4, 6, 11):
        p = np.random.default_rng(n).integers(1, 128, (1, n)).astype(np.int32)
        np.testing.assert_array_equal(
            np.asarray(gen(p, cfg))[0, n:],
            _static_reference(model, p[0], 16),
        )
    assert len([k for k in gen._decode_cache if k[5] == 3]) == 1  # one spec program


def test_seq2seq_rejects_speculation():
    from accelerate_tpu.generation import Seq2SeqGenerator
    from accelerate_tpu.models.t5 import create_t5_model, t5_tiny

    model = create_t5_model(t5_tiny(), seq_len=16)
    gen = Seq2SeqGenerator(model, max_new_tokens=4)
    with pytest.raises(ValueError, match="causal-LM only"):
        gen(np.ones((1, 4), np.int32), GenerationConfig(max_new_tokens=2, draft_tokens=2))

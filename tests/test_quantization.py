"""Quantization tests (reference analogue: bnb int8/4-bit loading, utils/bnb.py):
round-trip error bounds, packing size accounting, jit-compatibility of QuantTensor
pytrees, skip rules, and an end-to-end quantized Llama forward close to the dense one."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.utils.quantization import (
    QuantTensor,
    QuantizationConfig,
    dequantize_params,
    load_and_quantize_model,
    quantize_int4,
    quantize_int8,
    quantize_nf4,
    quantize_params,
    quantized_nbytes,
)


def _w(shape, seed=0, scale=0.02):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape).astype(np.float32) * scale)


def test_int8_round_trip():
    w = _w((64, 32))
    q = quantize_int8(w)
    err = np.abs(np.asarray(q.dequantize(jnp.float32)) - np.asarray(w))
    # per-channel absmax/127 bounds the error at half a step
    col_absmax = np.abs(np.asarray(w)).max(axis=0)
    assert (err <= col_absmax / 127.0 * 0.5001 + 1e-8).all()
    assert q.q.dtype == jnp.int8
    assert q.nbytes_quantized < w.size * 4 / 3.5  # ~4x smaller than fp32 (+scales)


@pytest.mark.parametrize("quant", [quantize_int4, quantize_nf4])
def test_4bit_round_trip(quant):
    w = _w((48, 32), seed=1)
    q = quant(w, block_size=64)
    deq = np.asarray(q.dequantize(jnp.float32))
    assert deq.shape == w.shape
    # 4-bit: coarse, but relative error must stay bounded
    rel = np.abs(deq - np.asarray(w)).mean() / np.abs(np.asarray(w)).mean()
    assert rel < 0.2, rel
    # two values per byte + one fp32 scale per 64-block
    expected_bytes = w.size // 2 + (w.size // 64) * 4
    assert q.nbytes_quantized == expected_bytes


def test_4bit_round_trip_with_padding():
    w = _w((5, 7), seed=2)  # 35 elements: forces padding to the 64-block
    for quant in (quantize_int4, quantize_nf4):
        q = quant(w, block_size=64)
        assert q.dequantize(jnp.float32).shape == w.shape


def test_quant_tensor_is_jittable_pytree():
    w = _w((32, 16))
    q = quantize_nf4(w)
    leaves, treedef = jax.tree_util.tree_flatten(q)
    assert len(leaves) == 2  # q + scale only; metadata is static
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt.kind == "nf4" and rebuilt.shape == (32, 16)

    @jax.jit
    def matmul(qt, x):
        return x @ qt.dequantize(jnp.bfloat16).astype(jnp.float32)

    out = matmul(q, jnp.ones((4, 32)))
    assert out.shape == (4, 16)


def test_quantize_params_skip_rules():
    params = {"params": {"layer_0": {"kernel": _w((16, 16))}, "lm_head": {"kernel": _w((16, 8))}, "norm": {"scale": _w((16,))}}}
    cfg = QuantizationConfig(load_in_8bit=True, skip_modules=["lm_head"])
    qp = quantize_params(params, cfg)
    assert isinstance(qp["params"]["layer_0"]["kernel"], QuantTensor)
    assert not isinstance(qp["params"]["lm_head"]["kernel"], QuantTensor)  # skipped
    assert not isinstance(qp["params"]["norm"]["scale"], QuantTensor)  # 1-D: kept dense
    deq = dequantize_params(qp, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(deq["params"]["lm_head"]["kernel"]), np.asarray(params["params"]["lm_head"]["kernel"])
    )


def test_quantized_model_end_to_end():
    from accelerate_tpu.models.llama import create_llama_model, llama_tiny

    model = create_llama_model(llama_tiny(), seq_len=16)
    ids = jnp.asarray(np.random.default_rng(0).integers(1, 500, (2, 16)), jnp.int32)
    dense_logits = np.asarray(model.apply_fn(model.params, ids), dtype=np.float32)

    qmodel = load_and_quantize_model(
        model, QuantizationConfig(load_in_8bit=True, compute_dtype=jnp.float32)
    )
    q_logits = np.asarray(jax.jit(qmodel.apply_fn)(qmodel.params, ids), dtype=np.float32)
    assert q_logits.shape == dense_logits.shape
    # int8 per-channel keeps logits close; compare top-1 predictions + numeric drift
    agree = (q_logits.argmax(-1) == dense_logits.argmax(-1)).mean()
    assert agree > 0.9, agree
    drift = np.abs(q_logits - dense_logits).mean() / (np.abs(dense_logits).mean() + 1e-9)
    assert drift < 0.2, drift

    # memory: quantized params must be well under half the dense fp32 footprint
    dense_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(model.params))
    assert quantized_nbytes(qmodel.params) < dense_bytes / 2

    # loss path still works
    loss = qmodel.loss_fn(qmodel.params, {"input_ids": ids}, qmodel.apply_fn)
    loss = loss[0] if isinstance(loss, tuple) else loss
    assert np.isfinite(float(loss))


def test_quantization_config_validation():
    with pytest.raises(ValueError):
        QuantizationConfig(load_in_8bit=True, load_in_4bit=True)
    with pytest.raises(ValueError):
        QuantizationConfig(load_in_4bit=True, quant_type="fp3")
    assert not QuantizationConfig().enabled


def test_quantized_generation_matches_dense_greedy():
    """Generation straight off a quantized bundle (the reference's bnb int8
    serving path): the Generator must dequantize inside its compiled programs.
    Regression: QuantTensor leaves previously hit the raw flax module and raised
    TypeError."""
    from accelerate_tpu.generation import generate
    from accelerate_tpu.models.llama import create_llama_model, llama_tiny

    model = create_llama_model(llama_tiny(), seq_len=32)
    qmodel = load_and_quantize_model(
        model, QuantizationConfig(load_in_8bit=True, compute_dtype=jnp.float32)
    )
    prompt = np.random.default_rng(0).integers(1, 500, (2, 8)).astype(np.int32)
    q_out = np.asarray(generate(qmodel, prompt, max_new_tokens=4))
    dense_out = np.asarray(generate(model, prompt, max_new_tokens=4))
    assert q_out.shape == dense_out.shape
    # compare only the GENERATED suffix (the echoed prompt always matches);
    # int8 per-channel keeps greedy decoding close on a tiny model
    q_gen, dense_gen = q_out[:, 8:], dense_out[:, 8:]
    assert (q_gen == dense_gen).mean() > 0.6, (q_gen, dense_gen)


# ======================================================================
# Serving quantization (ops/quantization.py): int8 weight-only matmuls and
# the int8/fp8 paged KV pool with per-page-per-head scales — round-trip
# bounds, kernel-vs-oracle numerics, engine logit/token budgets, and the
# decode-compiled-once discipline with quantized operands.
# ======================================================================

import dataclasses

from accelerate_tpu.ops.quantization import (
    KV_CACHE_DTYPES,
    WEIGHT_DTYPES,
    dequantize_kv_pages,
    kv_quant_spec,
    quantize_kv_pages,
    quantize_params_int8,
    quantized_pool_write,
    weight_autocast,
)


def _kv_blocks(shape, seed=0, scale=1.0):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape).astype(np.float32) * scale
    )


@pytest.mark.parametrize("kv_dtype", ["int8", "fp8_e4m3"])
def test_kv_page_round_trip_bounds(kv_dtype):
    """Whole-page quantize/dequant (the insert path) stays within the dtype's
    quantization-step bound: int8 within half a step of the per-page-per-head
    scale; fp8 e4m3 within ~2^-4 relative of the page amax (3 mantissa bits)."""
    spec = kv_quant_spec(kv_dtype)
    blocks = _kv_blocks((5, 4, 2, 8), seed=0, scale=0.7)
    q, scales = quantize_kv_pages(blocks, spec)
    assert q.dtype == spec[0] and scales.shape == (5, 2)
    deq = np.asarray(dequantize_kv_pages(q[None], scales[None], jnp.float32))[0]
    err = np.abs(deq - np.asarray(blocks))
    step = np.broadcast_to(np.asarray(scales)[:, None, :, None], err.shape)
    if kv_dtype == "int8":
        assert (err <= step * 0.5001 + 1e-8).all()
    else:
        amax = np.abs(np.asarray(blocks)).max(axis=(1, 3), keepdims=True)
        assert (err <= np.broadcast_to(amax, err.shape) * 0.07 + 1e-8).all()


@pytest.mark.parametrize("kv_dtype", ["int8", "fp8_e4m3"])
def test_quantized_pool_write_maintains_scale_invariant(kv_dtype):
    """The decode write path's invariant: after any sequence of incremental
    token writes — including magnitude GROWTH mid-page, which forces the
    scale-raise + in-dispatch requant — every written row dequantizes back
    within a small multiple of the final page scale (requant adds at most
    half a step per growth event)."""
    spec = kv_quant_spec(kv_dtype)
    num_pages, ps, h, d = 4, 4, 2, 8
    pool = jnp.zeros((num_pages, ps, h, d), spec[0])
    scale = jnp.zeros((num_pages, h), jnp.float32)
    rng = np.random.default_rng(0)
    written = {}
    for t in range(ps):
        x = rng.normal(size=(1, 1, h, d)).astype(np.float32) * (0.1 * (4.0 ** t))
        pid = jnp.asarray([[1]], jnp.int32)
        off = jnp.asarray([[t]], jnp.int32)
        pool, scale = quantized_pool_write(pool, scale, jnp.asarray(x), pid, off, spec)
        written[t] = x[0, 0]
    final_scale = np.asarray(scale)[1]  # [h]
    for t, x in written.items():
        deq = np.asarray(pool[1, t].astype(jnp.float32)) * final_scale[:, None]
        err = np.abs(deq - x)
        if kv_dtype == "int8":
            # ps growth events max: half a step each plus the final half step.
            assert (err <= final_scale[:, None] * (0.5 * (ps + 1)) + 1e-8).all(), (t, err.max())
        else:
            assert (err <= np.abs(x).max() * 0.15 + final_scale[:, None] + 1e-8).all(), (t, err.max())
    # A fresh occupant's offset-0 write RESETS the page scale: stale large
    # scales from a previous request never coarsen the next one.
    small = np.full((1, 1, h, d), 1e-3, np.float32)
    pool, scale = quantized_pool_write(
        pool, scale, jnp.asarray(small), jnp.asarray([[1]], jnp.int32),
        jnp.asarray([[0]], jnp.int32), spec,
    )
    assert (np.asarray(scale)[1] < final_scale + 1e-12).all()
    assert (np.asarray(scale)[1] <= 1e-3 / spec[1] + 1e-9).all()


@pytest.mark.kernels
@pytest.mark.parametrize("kv_dtype", ["int8", "fp8_e4m3"])
def test_paged_kernels_match_dequant_oracle(kv_dtype):
    """The fused-dequant Pallas kernels (interpret mode) against the
    dequantize-then-attend XLA oracle on the SAME quantized pool: decode and
    block-verify outputs must match to float tolerance — the dequant moved
    inside the page-streaming loop, not the math."""
    from accelerate_tpu.ops.paged_attention import (
        paged_decode_attention,
        paged_verify_attention,
    )

    rng = np.random.default_rng(1)
    B, Hq, Hkv, D, ps, P, NP = 2, 4, 2, 8, 4, 3, 8
    spec = kv_quant_spec(kv_dtype)
    kq, ks = quantize_kv_pages(_kv_blocks((NP, ps, Hkv, D), 1), spec)
    vq, vs = quantize_kv_pages(_kv_blocks((NP, ps, Hkv, D), 2), spec)
    tbl = jnp.asarray([[1, 2, 3], [4, 5, 0]], jnp.int32)
    kd = np.asarray(dequantize_kv_pages(kq[None], ks[None], jnp.float32))[0]
    vd = np.asarray(dequantize_kv_pages(vq[None], vs[None], jnp.float32))[0]
    karr = kd[np.asarray(tbl)].reshape(B, P * ps, Hkv, D)
    varr = vd[np.asarray(tbl)].reshape(B, P * ps, Hkv, D)

    def oracle(qarr, positions):
        s_blk = qarr.shape[1]
        out = np.zeros(qarr.shape, np.float32)
        for b in range(B):
            for j in range(s_blk):
                for hh in range(Hq):
                    kk, vv = karr[b, :, hh // 2, :], varr[b, :, hh // 2, :]
                    s = (qarr[b, j, hh] @ kk.T) / np.sqrt(D)
                    s = np.where(np.arange(P * ps) <= positions[b, j], s, -1e30)
                    p = np.exp(s - s.max())
                    out[b, j, hh] = (p / p.sum()) @ vv
        return out

    q1 = rng.normal(size=(B, 1, Hq, D)).astype(np.float32)
    pos1 = np.asarray([[9], [5]])
    got = np.asarray(paged_decode_attention(
        jnp.asarray(q1), kq, vq, tbl, jnp.asarray(pos1), k_scale=ks, v_scale=vs
    ))
    np.testing.assert_allclose(got, oracle(q1, pos1), atol=2e-5)

    q3 = rng.normal(size=(B, 3, Hq, D)).astype(np.float32)
    pos3 = np.asarray([[7, 8, 9], [3, 4, 5]])
    got = np.asarray(paged_verify_attention(
        jnp.asarray(q3), kq, vq, tbl, jnp.asarray(pos3), k_scale=ks, v_scale=vs
    ))
    np.testing.assert_allclose(got, oracle(q3, pos3), atol=2e-5)


def _drive_step_logits(model, kv_dtype, tokens, page_size=8):
    """Run the serving STEP program (paged slot cache, one token at a time)
    over a fixed token sequence and return the per-step logits — the
    program-level harness for the decode logit-error budget."""
    import jax

    from accelerate_tpu.generation import make_causal_programs
    from accelerate_tpu.models.llama import LlamaForCausalLM

    B, T = tokens.shape
    P = 4
    cfg = dataclasses.replace(
        model.module.config, decode_cache_length=P * page_size,
        decode_slot_cache=True, decode_page_size=page_size,
        decode_num_pages=B * P + 1, decode_kv_cache_dtype=kv_dtype,
    )
    module = LlamaForCausalLM(cfg)
    resolve = lambda p: p
    _, step, _ = make_causal_programs(
        module, resolve, step_mask_operand=True, verify_block=True
    )
    table = jnp.asarray(
        np.arange(1, B * P + 1, dtype=np.int32).reshape(B, P)
    )
    shapes = jax.eval_shape(
        lambda p: module.apply(
            p, jnp.zeros((B, 1), jnp.int32), table, jnp.zeros((B, 1), jnp.int32),
            mutable=["cache"],
        )[1]["cache"],
        model.params,
    )
    cache = jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    step = jax.jit(step, donate_argnums=(1,))
    logits_out = []
    for t in range(T):
        logits, cache = step(
            model.params, cache, jnp.asarray(tokens[:, t]),
            jnp.asarray(np.full(B, t, np.int32)), table,
        )
        logits_out.append(np.asarray(logits, np.float32))
    return np.stack(logits_out, axis=1)  # [B, T, V]


@pytest.mark.parametrize("kv_dtype", ["int8", "fp8_e4m3"])
def test_quantized_decode_logit_error_budget(kv_dtype):
    """The decode logit-error budget at the program level: the same token
    sequence driven through the paged step program on a bf16 (unquantized)
    pool vs the quantized pool. Cache quantization perturbs logits only
    through the attention read — the pinned budget is what the engine-level
    token-agreement tests ride on."""
    from accelerate_tpu.models.llama import create_llama_model, llama_tiny

    model = create_llama_model(llama_tiny(), seq_len=16)
    tokens = np.random.default_rng(0).integers(1, 500, (2, 12)).astype(np.int32)
    base = _drive_step_logits(model, "bf16", tokens)
    quant = _drive_step_logits(model, kv_dtype, tokens)
    max_err = np.abs(base - quant).max()
    # fp8 e4m3 carries 3 mantissa bits vs int8's ~7 significant bits, so its
    # budget is proportionally looser (measured ~0.26 vs ~0.15 at this size).
    budget = 0.25 if kv_dtype == "int8" else 0.45
    assert max_err < budget, f"{kv_dtype} decode logit error {max_err} over budget"
    agree = (base.argmax(-1) == quant.argmax(-1)).mean()
    # Random tiny-model logits are near-flat, so hair-thin argmax margins flip
    # under fp8's coarser steps — the floor tracks the logit budget above.
    floor = 0.9 if kv_dtype == "int8" else 0.8
    assert agree >= floor, f"{kv_dtype} greedy argmax agreement {agree}"


def test_quantized_engine_greedy_token_budget():
    """Engine-level accuracy budget: bf16 vs quantized engines on the same
    greedy workload. The bf16-vs-bf16 path is exact (pinned by
    test_serving.py); quantized paths must keep first tokens exact when only
    the CACHE is quantized (insert logits never read the quantized pool for
    a fresh prompt) and stay within a token-agreement budget overall."""
    from accelerate_tpu.models.llama import create_llama_model, llama_tiny
    from accelerate_tpu.serving import ContinuousBatcher, Request

    model = create_llama_model(llama_tiny(), seq_len=32)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, 500, (int(rng.integers(3, 20)),)).astype(np.int32)
        for _ in range(6)
    ]

    def run(**kw):
        eng = ContinuousBatcher(
            model, num_slots=3, max_length=64, chunk_size=4, page_size=8,
            max_queue=16, **kw,
        )
        out = eng.run([Request(i, p, max_new_tokens=8) for i, p in enumerate(prompts)])
        return {i: [int(t) for t in out[i]] for i in out}

    base = run()

    def agreement(other):
        pairs = [(x, y) for i in base for x, y in zip(base[i], other[i])]
        return sum(x == y for x, y in pairs) / len(pairs)

    for kv_dtype in ("int8", "fp8_e4m3"):
        quant = run(kv_cache_dtype=kv_dtype)
        assert all(base[i][0] == quant[i][0] for i in base), (
            f"{kv_dtype}: first token must be exact (fresh-prompt insert logits "
            "never read the quantized pool)"
        )
        assert agreement(quant) >= 0.6, kv_dtype
    w8 = run(weight_dtype="int8")
    assert agreement(w8) >= 0.6
    both = run(weight_dtype="int8", kv_cache_dtype="int8")
    assert agreement(both) >= 0.6


def test_quantized_decode_compiled_once_and_guarded():
    """The compiled-once pin with quantized operands: an int8-weights +
    int8-KV engine serves mixed admissions (fresh prompts, prefix-hit waves,
    varied lengths) with the decode chunk traced EXACTLY once, and — after
    warmup — zero recompiles and zero guarded host transfers. Dtypes are
    static config; scales ride the cache pytree as traced operands."""
    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.models.llama import create_llama_model, llama_tiny
    from accelerate_tpu.serving import ContinuousBatcher, Request

    model = create_llama_model(llama_tiny(), seq_len=32)
    rng = np.random.default_rng(1)
    prefix = rng.integers(1, 500, (8,)).astype(np.int32)

    def wave(base_id):
        reqs = []
        for i in range(5):
            tail = rng.integers(1, 500, (int(rng.integers(2, 12)),)).astype(np.int32)
            ids = np.concatenate([prefix, tail]) if i % 2 else tail
            reqs.append(Request(base_id + i, ids, max_new_tokens=6))
        return reqs

    eng = ContinuousBatcher(
        model, num_slots=2, max_length=48, chunk_size=4, page_size=8,
        max_queue=16, weight_dtype="int8", kv_cache_dtype="int8",
    )
    eng.warm_inserts()
    eng.run(wave(0))
    eng.run(wave(100))
    guard = TraceGuard(
        transfer_guard="disallow", on_violation="record", name="quant-decode-pin"
    )
    eng.trace_guard = guard
    with guard:
        eng.run(wave(200))
    assert eng.trace_counts["decode_chunk"] == 1, eng.trace_counts
    assert guard.total_recompiles == 0 and guard.host_transfers == 0, (
        guard.report().summary()
    )
    assert eng.kv_pool_itemsize == 1  # int8 pool really is 1 byte/value


def test_quantized_engine_validation():
    """Config validation: off-set dtypes and the quantized-contiguous combo
    fail loudly at construction, and weight quantization is idempotent across
    the params setter (the swap_weights seam re-assigns raw params)."""
    from accelerate_tpu.models.llama import create_llama_model, llama_tiny
    from accelerate_tpu.serving import ContinuousBatcher

    model = create_llama_model(llama_tiny(), seq_len=16)
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        ContinuousBatcher(model, max_queue=4, kv_cache_dtype="int4")
    with pytest.raises(ValueError, match="weight_dtype"):
        ContinuousBatcher(model, max_queue=4, weight_dtype="fp4")
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(model, max_queue=4, paged=False, kv_cache_dtype="int8")
    assert "int8" in KV_CACHE_DTYPES and "int8" in WEIGHT_DTYPES
    eng = ContinuousBatcher(
        model, max_queue=4, max_length=32, page_size=8, weight_dtype="int8"
    )
    q_once = eng.params
    eng.params = model.params  # the rolling-swap seam: raw params in
    leaf = eng.params["params"]["lm_head"]["kernel"]
    assert isinstance(leaf, dict) and leaf["q"].dtype == jnp.int8
    eng.params = eng.params  # already-quantized trees pass through unchanged
    assert eng.params["params"]["lm_head"]["kernel"]["q"].dtype == jnp.int8
    del q_once


@pytest.mark.router
def test_quantized_fleet_serves_with_zero_recompiles():
    """The fleet half of the discipline pin: a Router over quantized engines
    (int8 weights + int8 KV riding `engine_kwargs`) serves token streams
    identical to a single quantized engine, holds 0 recompiles / 0 guarded
    host transfers across the fleet after warmup, and a rolling
    `swap_weights` with RAW params re-quantizes at the engine's params
    setter without poisoning the compiled programs."""
    from accelerate_tpu.analysis import TraceGuard
    from accelerate_tpu.models.llama import create_llama_model, llama_tiny
    from accelerate_tpu.router import Router
    from accelerate_tpu.serving import ContinuousBatcher, Request

    model = create_llama_model(llama_tiny(), seq_len=32)
    rng = np.random.default_rng(2)
    prompts = [
        rng.integers(1, 500, (int(rng.integers(3, 16)),)).astype(np.int32)
        for _ in range(6)
    ]
    kwargs = dict(
        num_slots=2, max_length=48, chunk_size=4, page_size=8,
        weight_dtype="int8", kv_cache_dtype="int8",
    )
    single = ContinuousBatcher(model, max_queue=16, **kwargs)
    expected = single.run([Request(i, p, max_new_tokens=6) for i, p in enumerate(prompts)])

    router = Router(
        model, replicas=2, max_queue=16, default_deadline_s=60.0, **kwargs
    )
    router.warm_inserts()

    def serve(base_id):
        for i, p in enumerate(prompts):
            router.submit(Request(base_id + i, p, max_new_tokens=6))
        while router.pending:
            router.step()
        out = {i: [int(t) for t in router.results[base_id + i].tokens] for i in range(len(prompts))}
        for i in range(len(prompts)):
            router.release(base_id + i)
        return out

    serve(0)  # warm both replicas' decode chunks
    guard = TraceGuard(
        transfer_guard="disallow", on_violation="record", name="quant-fleet-pin"
    )
    with guard:
        got = serve(100)
    assert guard.total_recompiles == 0 and guard.host_transfers == 0, (
        guard.report().summary()
    )
    for i in range(len(prompts)):
        assert got[i] == [int(t) for t in expected[i]], i
    # Rolling swap with RAW (unquantized) params: the engine params setter
    # must re-quantize, and the warm executables must keep serving.
    router.swap_weights(model.params, wait=True)
    swapped = serve(200)
    for i in range(len(prompts)):
        assert swapped[i] == [int(t) for t in expected[i]], i
    router.close()


@pytest.mark.parametrize("kv_dtype", ["int8", "fp8_e4m3"])
def test_recycled_page_stale_content_never_inflates_insert_scales(kv_dtype):
    """Regression: the paged insert gathers a recycled private page's STALE
    dequantized content into the dense cache; before the quantized
    write-back, `tree_zero_cache_tail` must zero rows past the prompt so a
    prior occupant with much larger K/V magnitudes cannot inflate the
    boundary page's amax scale and coarsen the new request's real rows.
    Reproduced at the seam with controlled magnitudes: stale 100.0-scale
    content beyond a 0.01-scale prompt's rows must leave the round-trip
    within the half-step bound of the VALID rows' own scale — without the
    zeroing, the stored scale is ~10,000x too coarse and the real rows
    round to zero."""
    from accelerate_tpu.utils.operations import tree_zero_cache_tail

    spec = kv_quant_spec(kv_dtype)
    valid_len, page_size = 5, 8
    dense = {"cached_key": jnp.ones((1, 16, 2, 4), jnp.float32) * 100.0}
    small = np.random.default_rng(0).normal(size=(valid_len, 2, 4)).astype(np.float32) * 0.01
    dense["cached_key"] = dense["cached_key"].at[0, :valid_len].set(jnp.asarray(small))

    zeroed = tree_zero_cache_tail(dense, valid_len)
    assert np.abs(np.asarray(zeroed["cached_key"])[0, valid_len:]).max() == 0.0
    np.testing.assert_allclose(np.asarray(zeroed["cached_key"])[0, :valid_len], small)

    # The insert's write-back: whole-page quantization of the zeroed dense
    # blocks. The boundary page's scale must reflect only the valid rows.
    blocks = np.asarray(zeroed["cached_key"])[0].reshape(2, page_size, 2, 4)
    q, scales = quantize_kv_pages(jnp.asarray(blocks), spec)
    deq = np.asarray(dequantize_kv_pages(q[None], scales[None], jnp.float32))[0]
    err = np.abs(deq[0, :valid_len] - small)
    valid_scale = np.abs(small).max(axis=(0, 2)) / spec[1]  # per-head, valid rows only
    assert (np.asarray(scales)[0] <= valid_scale + 1e-12).all(), (
        "boundary-page scale inflated past the valid rows' own amax"
    )
    if kv_dtype == "int8":
        assert (err <= valid_scale[None, :, None] * 0.5001 + 1e-8).all()
    else:
        # fp8 is a relative quantizer: ~2^-4 of the value plus the subnormal
        # floor at this scale — tight only because the scale stayed honest.
        assert (err <= np.abs(small) * 0.07 + valid_scale[None, :, None] * 0.01 + 1e-8).all()
    # Control: WITHOUT the zeroing the stale tail owns the scale (the bug).
    q_bad, scales_bad = quantize_kv_pages(
        jnp.asarray(np.asarray(dense["cached_key"])[0].reshape(2, page_size, 2, 4)), spec
    )
    assert (np.asarray(scales_bad)[0] > valid_scale * 100).all()

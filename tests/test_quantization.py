"""Quantization tests (reference analogue: bnb int8/4-bit loading, utils/bnb.py):
round-trip error bounds, packing size accounting, jit-compatibility of QuantTensor
pytrees, skip rules, and an end-to-end quantized Llama forward close to the dense one."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.utils.quantization import (
    QuantTensor,
    QuantizationConfig,
    dequantize_params,
    load_and_quantize_model,
    quantize_int4,
    quantize_int8,
    quantize_nf4,
    quantize_params,
    quantized_nbytes,
)


def _w(shape, seed=0, scale=0.02):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape).astype(np.float32) * scale)


def test_int8_round_trip():
    w = _w((64, 32))
    q = quantize_int8(w)
    err = np.abs(np.asarray(q.dequantize(jnp.float32)) - np.asarray(w))
    # per-channel absmax/127 bounds the error at half a step
    col_absmax = np.abs(np.asarray(w)).max(axis=0)
    assert (err <= col_absmax / 127.0 * 0.5001 + 1e-8).all()
    assert q.q.dtype == jnp.int8
    assert q.nbytes_quantized < w.size * 4 / 3.5  # ~4x smaller than fp32 (+scales)


@pytest.mark.parametrize("quant", [quantize_int4, quantize_nf4])
def test_4bit_round_trip(quant):
    w = _w((48, 32), seed=1)
    q = quant(w, block_size=64)
    deq = np.asarray(q.dequantize(jnp.float32))
    assert deq.shape == w.shape
    # 4-bit: coarse, but relative error must stay bounded
    rel = np.abs(deq - np.asarray(w)).mean() / np.abs(np.asarray(w)).mean()
    assert rel < 0.2, rel
    # two values per byte + one fp32 scale per 64-block
    expected_bytes = w.size // 2 + (w.size // 64) * 4
    assert q.nbytes_quantized == expected_bytes


def test_4bit_round_trip_with_padding():
    w = _w((5, 7), seed=2)  # 35 elements: forces padding to the 64-block
    for quant in (quantize_int4, quantize_nf4):
        q = quant(w, block_size=64)
        assert q.dequantize(jnp.float32).shape == w.shape


def test_quant_tensor_is_jittable_pytree():
    w = _w((32, 16))
    q = quantize_nf4(w)
    leaves, treedef = jax.tree_util.tree_flatten(q)
    assert len(leaves) == 2  # q + scale only; metadata is static
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt.kind == "nf4" and rebuilt.shape == (32, 16)

    @jax.jit
    def matmul(qt, x):
        return x @ qt.dequantize(jnp.bfloat16).astype(jnp.float32)

    out = matmul(q, jnp.ones((4, 32)))
    assert out.shape == (4, 16)


def test_quantize_params_skip_rules():
    params = {"params": {"layer_0": {"kernel": _w((16, 16))}, "lm_head": {"kernel": _w((16, 8))}, "norm": {"scale": _w((16,))}}}
    cfg = QuantizationConfig(load_in_8bit=True, skip_modules=["lm_head"])
    qp = quantize_params(params, cfg)
    assert isinstance(qp["params"]["layer_0"]["kernel"], QuantTensor)
    assert not isinstance(qp["params"]["lm_head"]["kernel"], QuantTensor)  # skipped
    assert not isinstance(qp["params"]["norm"]["scale"], QuantTensor)  # 1-D: kept dense
    deq = dequantize_params(qp, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(deq["params"]["lm_head"]["kernel"]), np.asarray(params["params"]["lm_head"]["kernel"])
    )


def test_quantized_model_end_to_end():
    from accelerate_tpu.models.llama import create_llama_model, llama_tiny

    model = create_llama_model(llama_tiny(), seq_len=16)
    ids = jnp.asarray(np.random.default_rng(0).integers(1, 500, (2, 16)), jnp.int32)
    dense_logits = np.asarray(model.apply_fn(model.params, ids), dtype=np.float32)

    qmodel = load_and_quantize_model(
        model, QuantizationConfig(load_in_8bit=True, compute_dtype=jnp.float32)
    )
    q_logits = np.asarray(jax.jit(qmodel.apply_fn)(qmodel.params, ids), dtype=np.float32)
    assert q_logits.shape == dense_logits.shape
    # int8 per-channel keeps logits close; compare top-1 predictions + numeric drift
    agree = (q_logits.argmax(-1) == dense_logits.argmax(-1)).mean()
    assert agree > 0.9, agree
    drift = np.abs(q_logits - dense_logits).mean() / (np.abs(dense_logits).mean() + 1e-9)
    assert drift < 0.2, drift

    # memory: quantized params must be well under half the dense fp32 footprint
    dense_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(model.params))
    assert quantized_nbytes(qmodel.params) < dense_bytes / 2

    # loss path still works
    loss = qmodel.loss_fn(qmodel.params, {"input_ids": ids}, qmodel.apply_fn)
    loss = loss[0] if isinstance(loss, tuple) else loss
    assert np.isfinite(float(loss))


def test_quantization_config_validation():
    with pytest.raises(ValueError):
        QuantizationConfig(load_in_8bit=True, load_in_4bit=True)
    with pytest.raises(ValueError):
        QuantizationConfig(load_in_4bit=True, quant_type="fp3")
    assert not QuantizationConfig().enabled


def test_quantized_generation_matches_dense_greedy():
    """Generation straight off a quantized bundle (the reference's bnb int8
    serving path): the Generator must dequantize inside its compiled programs.
    Regression: QuantTensor leaves previously hit the raw flax module and raised
    TypeError."""
    from accelerate_tpu.generation import generate
    from accelerate_tpu.models.llama import create_llama_model, llama_tiny

    model = create_llama_model(llama_tiny(), seq_len=32)
    qmodel = load_and_quantize_model(
        model, QuantizationConfig(load_in_8bit=True, compute_dtype=jnp.float32)
    )
    prompt = np.random.default_rng(0).integers(1, 500, (2, 8)).astype(np.int32)
    q_out = np.asarray(generate(qmodel, prompt, max_new_tokens=4))
    dense_out = np.asarray(generate(model, prompt, max_new_tokens=4))
    assert q_out.shape == dense_out.shape
    # compare only the GENERATED suffix (the echoed prompt always matches);
    # int8 per-channel keeps greedy decoding close on a tiny model
    q_gen, dense_gen = q_out[:, 8:], dense_out[:, 8:]
    assert (q_gen == dense_gen).mean() > 0.6, (q_gen, dense_gen)

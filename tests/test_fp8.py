"""fp8 tests (reference analogue: TE fp8_autocast conversion + MS-AMP,
utils/transformer_engine.py / accelerator.py:1922): quantize/matmul accuracy, custom
VJP gradients, the Dense interceptor, and end-to-end fp8 training via Accelerator.

On CPU XLA emulates fp8 dtypes, so numerics are the real e4m3/e5m2 grids."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu.ops.fp8 import (
    E4M3,
    E5M2,
    Fp8Dense,
    fp8_autocast,
    fp8_matmul,
    quantize_fp8,
)


def test_quantize_fp8_round_trip():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(32, 16)).astype(np.float32))
    q, scale = quantize_fp8(x, E4M3)
    assert q.dtype == E4M3
    recon = q.astype(jnp.float32) * scale
    rel = np.abs(np.asarray(recon) - np.asarray(x)).mean() / np.abs(np.asarray(x)).mean()
    assert rel < 0.05, rel  # e4m3 has ~2 decimal digits


def test_fp8_matmul_close_to_fp32():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 64)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32) * 0.05)
    ref = np.asarray(x @ w)
    out = np.asarray(fp8_matmul(x, w))
    rel = np.abs(out - ref).mean() / np.abs(ref).mean()
    assert rel < 0.06, rel


def test_fp8_matmul_grads_flow():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 16)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32) * 0.1)

    def loss(w_):
        return jnp.sum(jnp.square(fp8_matmul(x, w_)))

    g = jax.grad(loss)(w)
    g_ref = jax.grad(lambda w_: jnp.sum(jnp.square(x @ w_)))(w)
    rel = np.abs(np.asarray(g) - np.asarray(g_ref)).mean() / np.abs(np.asarray(g_ref)).mean()
    assert rel < 0.1, rel
    assert np.isfinite(np.asarray(g)).all()


def test_fp8_autocast_intercepts_dense():
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(8, name="d")(x)

    net = Net()
    x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 16)).astype(np.float32))
    params = net.init(jax.random.key(0), x)
    ref = net.apply(params, x)
    with fp8_autocast():
        out = net.apply(params, x)
    # must differ (quantized) but stay close
    assert not np.allclose(np.asarray(out), np.asarray(ref), atol=0)
    rel = np.abs(np.asarray(out) - np.asarray(ref)).mean() / (np.abs(np.asarray(ref)).mean() + 1e-9)
    assert rel < 0.1, rel


def test_fp8_dense_module():
    x = jnp.asarray(np.random.default_rng(4).normal(size=(4, 16)).astype(np.float32))
    layer = Fp8Dense(8)
    params = layer.init(jax.random.key(0), x)
    out = jax.jit(layer.apply)(params, x)
    assert out.shape == (4, 8)
    assert np.isfinite(np.asarray(out)).all()


def test_fp8_training_through_accelerator():
    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.models.bert import bert_tiny, create_bert_model
    from accelerate_tpu.utils import FP8RecipeKwargs

    accelerator = Accelerator(mixed_precision="fp8", kwargs_handlers=[FP8RecipeKwargs()])
    model = create_bert_model(bert_tiny(), seq_len=16)
    rng = np.random.default_rng(0)
    data = [
        {
            "input_ids": rng.integers(1, 500, size=(16,)).astype(np.int32),
            "labels": np.int32(rng.integers(0, 2)),
        }
        for _ in range(16)
    ]
    dl = SimpleDataLoader(data, BatchSampler(range(16), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adam(1e-3), dl)
    assert pmodel.fp8_recipe is not None
    losses = []
    for batch in pdl:
        out = accelerator.backward(pmodel.loss, batch)
        loss = out[0] if isinstance(out, tuple) else out
        popt.step()
        popt.zero_grad()
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses

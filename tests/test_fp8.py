"""fp8 tests (reference analogue: TE fp8_autocast conversion + MS-AMP,
utils/transformer_engine.py / accelerator.py:1922): quantize/matmul accuracy, custom
VJP gradients, the Dense interceptor, and end-to-end fp8 training via Accelerator.

On CPU XLA emulates fp8 dtypes, so numerics are the real e4m3/e5m2 grids."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu.ops.fp8 import (
    E4M3,
    E5M2,
    Fp8Dense,
    fp8_autocast,
    fp8_matmul,
    quantize_fp8,
)


def test_quantize_fp8_round_trip():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(32, 16)).astype(np.float32))
    q, scale = quantize_fp8(x, E4M3)
    assert q.dtype == E4M3
    recon = q.astype(jnp.float32) * scale
    rel = np.abs(np.asarray(recon) - np.asarray(x)).mean() / np.abs(np.asarray(x)).mean()
    assert rel < 0.05, rel  # e4m3 has ~2 decimal digits


def test_fp8_matmul_close_to_fp32():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 64)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32) * 0.05)
    ref = np.asarray(x @ w)
    out = np.asarray(fp8_matmul(x, w))
    rel = np.abs(out - ref).mean() / np.abs(ref).mean()
    assert rel < 0.06, rel


def test_fp8_matmul_grads_flow():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 16)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32) * 0.1)

    def loss(w_):
        return jnp.sum(jnp.square(fp8_matmul(x, w_)))

    g = jax.grad(loss)(w)
    g_ref = jax.grad(lambda w_: jnp.sum(jnp.square(x @ w_)))(w)
    rel = np.abs(np.asarray(g) - np.asarray(g_ref)).mean() / np.abs(np.asarray(g_ref)).mean()
    assert rel < 0.1, rel
    assert np.isfinite(np.asarray(g)).all()


def test_fp8_autocast_intercepts_dense():
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(8, name="d")(x)

    net = Net()
    x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 16)).astype(np.float32))
    params = net.init(jax.random.key(0), x)
    ref = net.apply(params, x)
    with fp8_autocast():
        out = net.apply(params, x)
    # must differ (quantized) but stay close
    assert not np.allclose(np.asarray(out), np.asarray(ref), atol=0)
    rel = np.abs(np.asarray(out) - np.asarray(ref)).mean() / (np.abs(np.asarray(ref)).mean() + 1e-9)
    assert rel < 0.1, rel


def test_fp8_dense_module():
    x = jnp.asarray(np.random.default_rng(4).normal(size=(4, 16)).astype(np.float32))
    layer = Fp8Dense(8)
    params = layer.init(jax.random.key(0), x)
    out = jax.jit(layer.apply)(params, x)
    assert out.shape == (4, 8)
    assert np.isfinite(np.asarray(out)).all()


def test_fp8_training_through_accelerator():
    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import BatchSampler, SimpleDataLoader
    from accelerate_tpu.models.bert import bert_tiny, create_bert_model
    from accelerate_tpu.utils import FP8RecipeKwargs

    accelerator = Accelerator(mixed_precision="fp8", kwargs_handlers=[FP8RecipeKwargs()])
    model = create_bert_model(bert_tiny(), seq_len=16)
    rng = np.random.default_rng(0)
    data = [
        {
            "input_ids": rng.integers(1, 500, size=(16,)).astype(np.int32),
            "labels": np.int32(rng.integers(0, 2)),
        }
        for _ in range(16)
    ]
    dl = SimpleDataLoader(data, BatchSampler(range(16), 8))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adam(1e-3), dl)
    assert pmodel.fp8_recipe is not None
    losses = []
    for batch in pdl:
        out = accelerator.backward(pmodel.loss, batch)
        loss = out[0] if isinstance(out, tuple) else out
        popt.step()
        popt.zero_grad()
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses


# ---------------------------------------------------------------- delayed scaling
def test_delayed_cold_start_uses_unit_scale():
    """Zeroed histories (no amax observed yet) must behave like scale=1.0 —
    TE's init — not divide by an epsilon-scale and blow up."""
    from accelerate_tpu.ops.fp8 import fp8_matmul_delayed, init_fp8_meta

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 32)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(32, 16)).astype(np.float32) * 0.1)
    out = fp8_matmul_delayed(x, w, init_fp8_meta(4))
    ref = x @ w
    rel = np.abs(np.asarray(out - ref)).mean() / (np.abs(np.asarray(ref)).mean() + 1e-9)
    assert np.isfinite(np.asarray(out)).all()
    assert rel < 0.25, rel  # unit scale is coarse for ~N(0,1) inputs but must stay sane


def test_delayed_meta_cotangent_is_the_rolled_history():
    """The meta argument's 'gradient' IS the updated meta: histories shifted
    one slot with this step's observed amaxes (x/w from forward, g from
    backward) appended."""
    from accelerate_tpu.ops.fp8 import fp8_matmul_delayed, init_fp8_meta

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 8)).astype(np.float32) * 3.0)
    w = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32) * 0.5)
    meta = init_fp8_meta(3)
    meta = {k: v.at[-1].set(0.125) for k, v in meta.items()}  # sentinel to watch shift

    def loss(x_, w_, meta_):
        return jnp.sum(fp8_matmul_delayed(x_, w_, meta_) ** 2)

    _, new_meta = jax.grad(loss, argnums=(0, 2))(x, w, meta)
    assert new_meta["x_amax_history"][-1] == pytest.approx(float(jnp.max(jnp.abs(x))), rel=1e-6)
    assert new_meta["w_amax_history"][-1] == pytest.approx(float(jnp.max(jnp.abs(w))), rel=1e-6)
    assert float(new_meta["g_amax_history"][-1]) > 0.0
    # previous entries shifted left: the sentinel moved from slot -1 to slot -2
    for k in ("x_amax_history", "w_amax_history", "g_amax_history"):
        assert new_meta[k][-2] == pytest.approx(0.125)


def test_delayed_warm_history_matches_dynamic():
    """After the window has seen the live amaxes, delayed scales equal dynamic
    scales for stationary inputs — outputs must agree tightly."""
    from accelerate_tpu.ops.fp8 import fp8_matmul, fp8_matmul_delayed, init_fp8_meta

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(16, 64)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32) * 0.2)
    meta = init_fp8_meta(4)

    def loss(x_, w_, meta_):
        return jnp.sum(fp8_matmul_delayed(x_, w_, meta_))

    for _ in range(3):  # warm the window on the same tensors
        _, meta = jax.grad(loss, argnums=(0, 2))(x, w, meta)
    warm = fp8_matmul_delayed(x, w, meta)
    dyn = fp8_matmul(x, w)
    np.testing.assert_allclose(np.asarray(warm), np.asarray(dyn), rtol=1e-5, atol=1e-5)


def test_delayed_scale_uses_window_max_and_saturates():
    """A shrinking activation keeps the WINDOW max (TE semantics: scale covers
    the recent past), and a growing one beyond the stale scale saturates
    instead of overflowing."""
    from accelerate_tpu.ops.fp8 import fp8_matmul_delayed, init_fp8_meta

    w = jnp.eye(4, dtype=jnp.float32)
    meta = init_fp8_meta(4)
    big = jnp.full((1, 4), 100.0, jnp.float32)

    def loss(x_, w_, meta_):
        return jnp.sum(fp8_matmul_delayed(x_, w_, meta_))

    _, meta = jax.grad(loss, argnums=(0, 2))(big, w, meta)
    assert float(meta["x_amax_history"][-1]) == pytest.approx(100.0)
    # 100 is in the window: small inputs still use scale 100/448 (window max)
    small_out = fp8_matmul_delayed(jnp.full((1, 4), 1.0, jnp.float32), w, meta)
    assert np.asarray(small_out).max() == pytest.approx(1.0, rel=0.2)  # coarser grid, still ~1
    # 1e6 overflows the stale scale: saturating cast clips at 448*scale, no inf/nan
    huge_out = fp8_matmul_delayed(jnp.full((1, 4), 1e6, jnp.float32), w, meta)
    assert np.isfinite(np.asarray(huge_out)).all()


def test_autocast_delayed_owns_module_histories():
    """Recipe scaling='delayed' under fp8_autocast: forward histories live in
    the Dense's own fp8_meta collection, update when the caller marks it
    mutable (training), and freeze at eval."""
    import flax.linen as nn

    from accelerate_tpu.ops.fp8 import fp8_autocast
    from accelerate_tpu.utils import FP8RecipeKwargs

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(8)(nn.relu(nn.Dense(16)(x)))

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(4, 12)).astype(np.float32) * 2.0)
    net = Net()
    recipe = FP8RecipeKwargs(scaling="delayed", amax_history_len=4)
    with fp8_autocast(recipe):
        variables = net.init(jax.random.key(0), x)
        out1, mut = net.apply(variables, x, mutable=["fp8_meta"])
        metas = jax.tree_util.tree_leaves(mut["fp8_meta"])
        assert metas and all(m.shape == (4,) for m in metas)
        assert any(float(jnp.max(m)) > 0 for m in metas)  # observed amaxes recorded
        # warmed second pass: histories now drive the scales; eval (immutable) works
        variables = {**variables, **mut}
        out2 = net.apply(variables, x)
    assert np.isfinite(np.asarray(out1)).all() and np.isfinite(np.asarray(out2)).all()


def test_dynamic_vs_delayed_accuracy_measured():
    """The limitations-doc claim, pinned by measurement: on matched tensors,
    per-step dynamic scaling quantizes at least as tightly as a warm delayed
    window (it tracks THIS tensor's amax, not the window max of the past), and
    on drifting magnitudes it is strictly tighter."""
    from accelerate_tpu.ops.fp8 import fp8_matmul, fp8_matmul_delayed, init_fp8_meta

    rng = np.random.default_rng(5)
    w = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32) * 0.2)

    def qerr(out, ref):
        return float(np.abs(np.asarray(out - ref)).mean() / (np.abs(np.asarray(ref)).mean() + 1e-9))

    def loss(x_, w_, meta_):
        return jnp.sum(fp8_matmul_delayed(x_, w_, meta_))

    meta = init_fp8_meta(8)
    # drift: magnitudes decay 10x over the run (warmup spikes then settle — the
    # shape where a window max overshoots the live tensor)
    dyn_errs, del_errs = [], []
    for step in range(10):
        scale = 10.0 * (0.1 ** (step / 9))
        x = jnp.asarray(rng.normal(size=(16, 64)).astype(np.float32) * scale)
        ref = x @ w
        dyn_errs.append(qerr(fp8_matmul(x, w), ref))
        del_errs.append(qerr(fp8_matmul_delayed(x, w, meta), ref))
        _, meta = jax.grad(loss, argnums=(0, 2))(x, w, meta)
    assert np.mean(dyn_errs) <= np.mean(del_errs) * 1.05, (np.mean(dyn_errs), np.mean(del_errs))


def test_delayed_through_prepared_model_warns_frozen_histories(caplog):
    """The prepared-model path has no mutable fp8_meta channel: a TE-ported
    delayed recipe would silently train on frozen cold scales — must warn."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.models.bert import bert_tiny, create_bert_model
    from accelerate_tpu.utils import FP8RecipeKwargs

    accelerator = Accelerator(
        mixed_precision="fp8", kwargs_handlers=[FP8RecipeKwargs(scaling="delayed")]
    )
    model = create_bert_model(bert_tiny(), seq_len=16)
    with caplog.at_level("WARNING", logger="accelerate_tpu.modeling"):
        accelerator.prepare(model)
    assert any("frozen" in r.getMessage() for r in caplog.records), caplog.records


def test_delayed_most_recent_algo_tracks_last_step():
    """amax_compute_algo='most_recent' (TE field, now honored): after a spike
    leaves, the scale follows the LAST observed amax immediately, while 'max'
    stays pinned to the window max."""
    from accelerate_tpu.ops.fp8 import _history_scale, fp8_matmul_delayed, init_fp8_meta

    w = jnp.eye(4, dtype=jnp.float32)
    meta = init_fp8_meta(4)

    def loss(x_, w_, meta_):
        return jnp.sum(fp8_matmul_delayed(x_, w_, meta_))

    _, meta = jax.grad(loss, argnums=(0, 2))(jnp.full((1, 4), 100.0, jnp.float32), w, meta)
    _, meta = jax.grad(loss, argnums=(0, 2))(jnp.full((1, 4), 1.0, jnp.float32), w, meta)
    # window holds [0, 0, 100, 1]: max -> 100-based scale; most_recent -> 1-based
    s_max = float(_history_scale(meta["x_amax_history"], 448.0, "max"))
    s_recent = float(_history_scale(meta["x_amax_history"], 448.0, "most_recent"))
    assert s_max == pytest.approx(100.0 / 448.0, rel=1e-5)
    assert s_recent == pytest.approx(1.0 / 448.0, rel=1e-5)
    # and the op threads the algo through to the quantization grid
    out_recent = fp8_matmul_delayed(jnp.full((1, 4), 1.0, jnp.float32), w, meta, True, "most_recent")
    out_max = fp8_matmul_delayed(jnp.full((1, 4), 1.0, jnp.float32), w, meta, True, "max")
    err_recent = abs(float(out_recent[0, 0]) - 1.0)
    err_max = abs(float(out_max[0, 0]) - 1.0)
    assert err_recent <= err_max

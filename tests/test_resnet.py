"""ResNet model-family tests: shapes, DP training through the Accelerator (loss falls,
batch_stats untouched by the optimizer), and the ResNet-50 config's parameter count
sanity (≈25.5M)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu.models.resnet import (
    ResNetConfig,
    create_resnet_model,
    resnet50,
    resnet_tiny,
)


def test_forward_shapes():
    model = create_resnet_model(resnet_tiny(), image_size=32)
    x = jnp.zeros((2, 32, 32, 3))
    logits = model.apply_fn(model.params, x)
    assert logits.shape == (2, 4)


def test_resnet50_param_count():
    model = create_resnet_model(resnet50(), image_size=32)
    n = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(model.params["params"]))
    assert 25.0e6 < n < 26.0e6, n  # torchvision resnet50 = 25.56M


def test_dp_training_learns_and_preserves_batch_stats():
    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import BatchSampler
    from accelerate_tpu.native import ArrayDataset
    from accelerate_tpu.native.loader import NativeArrayLoader

    rng = np.random.default_rng(0)
    n, size = 64, 16
    labels = rng.integers(0, 4, size=n)
    images = rng.normal(size=(n, size, size, 3)).astype(np.float32) * 0.1
    half = size // 2
    for i, k in enumerate(labels):
        r, c = divmod(int(k), 2)
        images[i, r * half : (r + 1) * half, c * half : (c + 1) * half] += 2.0

    accelerator = Accelerator()
    model = create_resnet_model(resnet_tiny(), image_size=size)
    ds = ArrayDataset({"pixel_values": images, "labels": labels.astype(np.int64)})
    dl = NativeArrayLoader(ds, BatchSampler(range(n), 16))
    pmodel, popt, pdl = accelerator.prepare(model, optax.adam(2e-3), dl)
    stats_before = jax.tree_util.tree_map(np.asarray, pmodel.params["batch_stats"])
    losses = []
    for epoch in range(6):
        for batch in pdl:
            loss = accelerator.backward(pmodel.loss, batch)
            popt.step()
            popt.zero_grad()
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    stats_after = pmodel.params["batch_stats"]
    for a, b in zip(jax.tree_util.tree_leaves(stats_before), jax.tree_util.tree_leaves(stats_after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

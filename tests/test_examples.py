"""Example-as-test (reference tests/test_examples.py pattern: every by_feature script
must actually run). Each example runs as a subprocess on the 8-device virtual CPU mesh
with tiny sizes; asserts on exit code + expected output markers."""

import os
import subprocess
import sys

import pytest

from accelerate_tpu.test_utils.testing import cpu_mesh_env

EXAMPLES_DIR = os.path.join(os.path.dirname(__file__), "..", "examples")


def _run(rel_path, *extra):
    cmd = [sys.executable, os.path.join(EXAMPLES_DIR, rel_path), *extra]
    result = subprocess.run(cmd, env=cpu_mesh_env(), capture_output=True, text=True, timeout=560)
    assert result.returncode == 0, f"{rel_path} failed:\n{result.stdout}\n{result.stderr}"
    return result.stdout


@pytest.mark.slow_launch
def test_nlp_example():
    out = _run("nlp_example.py", "--train_size", "128", "--eval_size", "64", "--epochs", "2")
    assert "accuracy" in out


@pytest.mark.slow_launch
def test_cv_example():
    out = _run("cv_example.py", "--epochs", "3")
    assert "accuracy" in out


@pytest.mark.slow_launch
@pytest.mark.parametrize(
    "script,args,marker",
    [
        ("gradient_accumulation.py", ["--train_size", "64"], "accumulation"),
        ("local_sgd.py", ["--train_size", "64"], "loss"),
        ("memory.py", ["--train_size", "64"], "Trained with batch size"),
        ("fsdp.py", ["--train_size", "64"], "peak HBM"),
        ("profiler.py", ["--train_size", "64"], "trace written"),
        ("tracking.py", ["--train_size", "64"], "acc"),
    ],
)
def test_by_feature_examples(script, args, marker):
    out = _run(os.path.join("by_feature", script), *args)
    assert marker in out, out


@pytest.mark.slow_launch
def test_checkpointing_example_resume():
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        _run("by_feature/checkpointing.py", "--train_size", "64", "--output_dir", d, "--epochs", "1")
        out = _run(
            "by_feature/checkpointing.py",
            "--train_size",
            "64",
            "--output_dir",
            d,
            "--epochs",
            "2",
            "--resume_from_checkpoint",
            "latest",
        )
        assert "resumed from" in out

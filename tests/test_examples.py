"""Example-as-test (reference tests/test_examples.py pattern: every by_feature script
must actually run). Each example runs as a subprocess on the 8-device virtual CPU mesh
with tiny sizes; asserts on exit code + expected output markers."""

import os
import sys

import pytest

from accelerate_tpu.test_utils.testing import cpu_mesh_env, execute_subprocess

EXAMPLES_DIR = os.path.join(os.path.dirname(__file__), "..", "examples")


def _run(rel_path, *extra):
    cmd = [sys.executable, os.path.join(EXAMPLES_DIR, rel_path), *extra]
    result = execute_subprocess(cmd, env=cpu_mesh_env(), timeout=560)
    return result.stdout


@pytest.mark.slow_launch
def test_nlp_example():
    out = _run("nlp_example.py", "--train_size", "128", "--eval_size", "64", "--epochs", "2")
    assert "accuracy" in out


@pytest.mark.slow_launch
def test_cv_example():
    out = _run("cv_example.py", "--epochs", "3")
    assert "accuracy" in out


@pytest.mark.slow_launch
@pytest.mark.parametrize(
    "script,args,marker",
    [
        ("gradient_accumulation.py", ["--train_size", "64"], "accumulation"),
        ("local_sgd.py", ["--train_size", "64"], "loss"),
        ("memory.py", ["--train_size", "64"], "Trained with batch size"),
        ("fsdp.py", ["--train_size", "64"], "peak HBM"),
        ("profiler.py", ["--train_size", "64"], "trace written"),
        ("tracking.py", ["--train_size", "64"], "acc"),
    ],
)
def test_by_feature_examples(script, args, marker):
    out = _run(os.path.join("by_feature", script), *args)
    assert marker in out, out


@pytest.mark.slow_launch
@pytest.mark.parametrize(
    "script,args,marker",
    [
        ("early_stopping.py", ["--train_size", "64", "--eval_size", "32", "--epochs", "6", "--patience", "1"], "eval loss"),
        ("cross_validation.py", ["--train_size", "96", "--epochs", "1", "--num_folds", "2"], "cross-validation mean accuracy"),
        ("multi_process_metrics.py", ["--train_size", "64", "--eval_size", "35", "--epochs", "1"], "exact count"),
        ("automatic_gradient_accumulation.py", ["--train_size", "64", "--epochs", "1"], "effective"),
        ("schedule_free.py", ["--train_size", "64", "--eval_size", "32", "--epochs", "1"], "schedule-free eval params"),
        ("deepspeed_with_config_support.py", ["--train_size", "64", "--epochs", "1"], "zero_stage=2 -> SHARD_GRAD_OP"),
        ("megatron_lm_gpt_pretraining.py", ["--steps", "12", "--train_size", "64"], "pretraining loss"),
        ("sequence_parallelism.py", ["--train_size", "32"], "attention dispatch=ring"),
        ("device_training_loop.py", ["--train_size", "64", "--epochs", "1"], "dispatches (steps_per_call=4)"),
    ],
)
def test_new_by_feature_examples(script, args, marker):
    out = _run(os.path.join("by_feature", script), *args)
    assert marker in out, out


@pytest.mark.slow_launch
@pytest.mark.parametrize(
    "script,args,marker",
    [
        ("distributed_inference.py", ["--num_prompts", "4", "--prompt_len", "16", "--max_new_tokens", "8"], "completions across"),
        ("pippy_pipeline.py", ["--batch_size", "4"], "pipeline inference"),
        ("quantized_inference.py", ["--bits", "8"], "at the quantized footprint"),
    ],
)
def test_inference_examples(script, args, marker):
    out = _run(os.path.join("inference", script), *args)
    assert marker in out, out


# ---- drift harness (reference ExampleDifferenceTests / test_utils/examples.py:63) ----
FEATURE_MARKERS = {
    "gradient_accumulation.py": ["accumulate(", "gradient_accumulation_steps"],
    "local_sgd.py": ["LocalSGD"],
    "memory.py": ["find_executable_batch_size"],
    "fsdp.py": ["FullyShardedDataParallelPlugin"],
    "profiler.py": ["profile"],
    "tracking.py": ["init_trackers", "accelerator.log"],
    "checkpointing.py": ["save_state", "load_state"],
    "early_stopping.py": ["set_trigger", "check_trigger"],
    "cross_validation.py": ["gather_for_metrics", "fold"],
    "multi_process_metrics.py": ["gather_for_metrics"],
    "automatic_gradient_accumulation.py": ["find_executable_batch_size", "gradient_accumulation_steps"],
    "schedule_free.py": ["schedule_free_adamw", "schedule_free_eval_params"],
    "deepspeed_with_config_support.py": ["DeepSpeedPlugin", "hf_ds_config"],
    "megatron_lm_gpt_pretraining.py": ["prepare_pipeline", "num_microbatches"],
    "sequence_parallelism.py": ["SequenceParallelPlugin", "seq_degree"],
    "device_training_loop.py": ["steps_per_call"],
}


def test_example_difference_harness():
    """Every by_feature script must keep the canonical example shape (dataset reuse,
    training_function, argparse main, prepare()) and actually exercise its feature —
    the structural version of the reference's line-diff (test_utils/examples.py:63)."""
    from accelerate_tpu.test_utils.examples import check_example_shape

    by_feature = os.path.join(EXAMPLES_DIR, "by_feature")
    scripts = sorted(f for f in os.listdir(by_feature) if f.endswith(".py"))
    assert set(scripts) == set(FEATURE_MARKERS), (
        f"by_feature scripts and FEATURE_MARKERS disagree: {set(scripts) ^ set(FEATURE_MARKERS)}"
    )
    problems = {}
    for script in scripts:
        p = check_example_shape(os.path.join(by_feature, script), FEATURE_MARKERS[script])
        if p:
            problems[script] = p
    assert not problems, problems


@pytest.mark.slow_launch
def test_complete_nlp_example_checkpoint_resume():
    """The 'complete' variant must exercise its whole knob set in one run:
    epoch-granular checkpointing, then a resumed continuation with tracking."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        out = _run(
            "complete_nlp_example.py",
            "--train_size", "128", "--eval_size", "64", "--epochs", "1",
            "--checkpointing_steps", "epoch", "--output_dir", d,
        )
        assert "accuracy" in out
        out = _run(
            "complete_nlp_example.py",
            "--train_size", "128", "--eval_size", "64", "--epochs", "2",
            "--checkpointing_steps", "epoch", "--output_dir", d,
            "--resume_from_checkpoint", "latest", "--with_tracking",
        )
        assert "resumed from" in out and "accuracy" in out


@pytest.mark.slow_launch
def test_complete_cv_example_checkpoint_resume():
    """Exercise the CV variant's whole knob set, not just the train loop:
    epoch-granular save, then resume + tracking. Default 512-row dataset (like
    test_cv_example): 96 rows underfit the quadrant task and trip the script's
    learning assert."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        out = _run(
            "complete_cv_example.py",
            "--epochs", "1", "--checkpointing_steps", "epoch", "--output_dir", d,
        )
        assert "accuracy" in out
        out = _run(
            "complete_cv_example.py",
            "--epochs", "2", "--checkpointing_steps", "epoch", "--output_dir", d,
            "--resume_from_checkpoint", "latest", "--with_tracking",
        )
        assert "resumed from" in out and "accuracy" in out


@pytest.mark.slow_launch
def test_checkpointing_example_resume():
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        _run("by_feature/checkpointing.py", "--train_size", "64", "--output_dir", d, "--epochs", "1")
        out = _run(
            "by_feature/checkpointing.py",
            "--train_size",
            "64",
            "--output_dir",
            d,
            "--epochs",
            "2",
            "--resume_from_checkpoint",
            "latest",
        )
        assert "resumed from" in out

"""Tensor-parallel decode: one `ContinuousBatcher` spanning a forced
multi-device CPU mesh (`tests/conftest.py` exports
``--xla_force_host_platform_device_count=8``, the same harness the
`parallel/mesh.py` tests use).

The acceptance pins:

  - **token parity** — greedy decode is token-IDENTICAL tp==N vs tp==1
    across {llama, gpt_neox} x {paged, contiguous} x {speculative on/off} x
    {bf16, int8 KV}: GSPMD partitioning is a layout change, never a numerics
    change (and the Pallas page-walk kernels, shard_mapped over the KV-head
    grid, hold the same identity);
  - **compiled-once discipline** — the ONE decode executable survives mixed
    admissions with sharded operands, and a warm engine's steady state is 0
    recompiles / 0 guarded host transfers under TraceGuard;
  - **sharding audit** — every rule-matched weight leaf and every KV pool
    leaf carries the "model" axis in its LIVE sharding (no silent full
    replication — TPU118's runtime complement), scalars/page-tables stay
    replicated, and per-chip weight+pool bytes drop ~1/N;
  - **composition** — `router.Router` treats a mesh-spanning engine as one
    replica: disjoint TP device groups per replica, rolling `swap_weights`
    re-sharding at the engine's params setter.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from accelerate_tpu.models.gpt_neox import GPTNeoXConfig, create_gpt_neox_model
from accelerate_tpu.models.llama import LlamaConfig, create_llama_model
from accelerate_tpu.serving import ContinuousBatcher, Request

pytestmark = pytest.mark.tp

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs a >= 4-device mesh (forced CPU devices)"
)


def tiny_llama():
    return create_llama_model(
        LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, rope_theta=10000.0,
        ),
        seq_len=32,
    )


def tiny_neox():
    return create_gpt_neox_model(
        GPTNeoXConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64,
        ),
        seq_len=32,
    )


_MODELS = {"llama": tiny_llama, "gpt_neox": tiny_neox}
_MODEL_CACHE = {}


def get_model(family):
    if family not in _MODEL_CACHE:
        _MODEL_CACHE[family] = _MODELS[family]()
    return _MODEL_CACHE[family]


def make_requests(n=4, max_new=8):
    return [
        Request(i, list(range(3 + i, 10 + i)) + [2, 5, 2, 5], max_new_tokens=max_new)
        for i in range(n)
    ]


def run_engine(model, tp, **kwargs):
    engine = ContinuousBatcher(model, num_slots=2, chunk_size=4, tp=tp, **kwargs)
    out = engine.run(make_requests())
    return engine, out


def assert_parity(a, b, tag=""):
    assert set(a) == set(b)
    for rid in a:
        assert np.array_equal(a[rid], b[rid]), (tag, rid, a[rid], b[rid])


# --------------------------------------------------------------------- parity
@needs_mesh
@pytest.mark.parametrize("family", ["llama", "gpt_neox"])
@pytest.mark.parametrize(
    "variant",
    [
        {"page_size": 4},
        {"paged": False},
        {"page_size": 4, "speculative": True, "draft_tokens": 3},
        {"paged": False, "speculative": True, "draft_tokens": 3},
        {"page_size": 4, "kv_cache_dtype": "int8"},
        {"page_size": 4, "kv_cache_dtype": "int8", "speculative": True, "draft_tokens": 3},
    ],
    ids=["paged", "contiguous", "paged-spec", "contiguous-spec", "int8kv", "int8kv-spec"],
)
def test_tp_token_parity(family, variant):
    """Greedy decode tp==2 vs tp==1: token-identical across the whole
    {family} x {layout} x {speculative} x {kv dtype} matrix (int8 KV is
    paged-only by engine contract, so the contiguous axis carries bf16)."""
    model = get_model(family)
    _, base = run_engine(model, tp=1, **variant)
    _, spanned = run_engine(model, tp=2, **variant)
    assert_parity(base, spanned, tag=(family, variant))


@needs_mesh
def test_tp4_parity_across_families():
    """tp=4 (one KV head... per shard for gpt_neox; llama's 2 KV heads split
    further constraints, so llama runs tp=2 and neox the full tp=4): deeper
    submeshes hold the same identity."""
    neox = get_model("gpt_neox")
    _, base = run_engine(neox, tp=1, page_size=4)
    _, spanned = run_engine(neox, tp=4, page_size=4)
    assert_parity(base, spanned, tag="neox-tp4")


@needs_mesh
def test_tp_parity_pallas_kernels():
    """The fused page-walk kernels under shard_map over the KV-head grid
    (interpret mode on CPU) match the tp=1 kernel path token for token —
    and so does the speculative verify kernel."""
    model = get_model("llama")
    for variant in (
        {"page_size": 4, "attention_impl": "pallas_paged"},
        {"page_size": 4, "attention_impl": "pallas_paged", "speculative": True, "draft_tokens": 3},
        {"page_size": 4, "attention_impl": "pallas_paged", "kv_cache_dtype": "int8"},
    ):
        _, base = run_engine(model, tp=1, **variant)
        _, spanned = run_engine(model, tp=2, **variant)
        assert_parity(base, spanned, tag=("pallas", variant))


@needs_mesh
def test_tp_int8_weights_parity():
    """int8 weight-only quantization composes: the quantized {"q", "scale"}
    entries shard by their kernel's Megatron rule and decode stays
    token-identical to the single-device int8 engine."""
    model = get_model("llama")
    _, base = run_engine(model, tp=1, page_size=4, weight_dtype="int8")
    _, spanned = run_engine(model, tp=2, page_size=4, weight_dtype="int8")
    assert_parity(base, spanned, tag="int8-weights")


# ----------------------------------------------------------------- discipline
@needs_mesh
def test_tp_decode_compiled_once_and_zero_recompiles():
    """The compiled-once pin with sharded operands: one decode executable
    across mixed admissions, and a warm engine's steady state is 0
    recompiles / 0 guarded host transfers under an armed TraceGuard."""
    from accelerate_tpu.analysis import TraceGuard

    model = get_model("llama")
    engine = ContinuousBatcher(model, num_slots=2, chunk_size=4, page_size=4, tp=2)
    engine.warm_inserts()
    engine.run(make_requests())
    assert engine.trace_counts["decode_chunk"] == 1, engine.trace_counts
    inserts_before = engine.trace_counts["insert"]
    with TraceGuard(name="tp-steady") as guard:
        engine.run(
            [Request(100 + i, list(range(2 + i, 12 + i)), max_new_tokens=6) for i in range(4)]
        )
    assert guard.total_recompiles == 0 and guard.host_transfers == 0, guard.report().summary()
    assert engine.trace_counts["decode_chunk"] == 1
    assert engine.trace_counts["insert"] == inserts_before  # warm ladder held


# -------------------------------------------------------------- sharding audit
@needs_mesh
def test_tp_sharding_audit_no_unintended_replication():
    """Per-leaf audit off the LIVE arrays: every rule-matched kernel leaf and
    every KV pool leaf carries the "model" axis, scalars replicate, and the
    per-chip weight+pool footprint drops ~1/2 at tp=2."""
    model = get_model("llama")
    base = ContinuousBatcher(model, num_slots=2, chunk_size=4, page_size=4, tp=1)
    engine = ContinuousBatcher(model, num_slots=2, chunk_size=4, page_size=4, tp=2)
    report = engine.tp_sharding_report()

    sharded_kernels = [
        path for path, spec in report["params"].items()
        if "kernel" in path or "embedding" in path
    ]
    assert sharded_kernels, "no weight leaves found"
    for path in sharded_kernels:
        assert "model" in report["params"][path], (path, report["params"][path])
    # Norm scales replicate (no rule matches them).
    norm_leaves = [p for p in report["params"] if "norm" in p]
    assert norm_leaves
    for path in norm_leaves:
        assert "model" not in report["params"][path], (path, report["params"][path])

    for path, spec in report["cache"].items():
        leaf = path.rsplit("/", 1)[-1]
        if leaf in ("cached_key", "cached_value", "key_scale", "value_scale"):
            assert "model" in spec, (path, spec)
        else:
            assert "model" not in spec, (path, spec)

    ratio = (base.per_device_weight_nbytes + base.per_device_kv_cache_nbytes) / (
        engine.per_device_weight_nbytes + engine.per_device_kv_cache_nbytes
    )
    assert ratio >= 1.6, f"per-chip footprint only dropped {ratio:.2f}x at tp=2"


@needs_mesh
def test_tp_quantized_scale_leaves_follow_kernel_rule():
    """Quantized {"q", "scale"} entries: `q` shards exactly like the kernel it
    replaced; the per-output-channel `scale` vector follows the kernel's
    OUTPUT dim — sharded for column-parallel (wq/w_gate), replicated for
    row-parallel (wo/w_down)."""
    model = get_model("llama")
    engine = ContinuousBatcher(
        model, num_slots=2, chunk_size=4, page_size=4, tp=2, weight_dtype="int8"
    )
    params = engine.tp_sharding_report()["params"]
    col = [p for p in params if p.endswith("wq/kernel/scale")]
    row = [p for p in params if p.endswith("wo/kernel/scale")]
    assert col and row
    for path in col:
        assert "model" in params[path], (path, params[path])
    for path in row:
        assert "model" not in params[path], (path, params[path])
    for path in [p for p in params if p.endswith("kernel/q")]:
        assert "model" in params[path], (path, params[path])


@needs_mesh
def test_tp_blast_radius_rebuilds_sharded_pools():
    """The donated-cache rebuild (`_abort_in_flight`) must reconstruct the
    pools SHARDED on the submesh — a replicated rebuild would keep serving
    correct tokens at N x the per-chip HBM."""
    model = get_model("llama")
    engine = ContinuousBatcher(model, num_slots=2, chunk_size=4, page_size=4, tp=2)
    engine.run(make_requests(n=2))
    engine._abort_in_flight(RuntimeError("synthetic blast radius"))
    for path, spec in engine.tp_sharding_report()["cache"].items():
        if path.rsplit("/", 1)[-1] in ("cached_key", "cached_value"):
            assert "model" in spec, (path, spec)
    # ...and the rebuilt engine still serves, token-identically.
    probes = [
        Request(200 + i, list(range(3 + i, 10 + i)) + [2, 5, 2, 5], max_new_tokens=8)
        for i in range(2)
    ]
    out = engine.run(probes)
    _, base = run_engine(model, tp=1, page_size=4)
    for i in range(2):
        assert np.array_equal(out[200 + i], base[i])


# ----------------------------------------------------------------- validation
@needs_mesh
def test_tp_validation_errors():
    model = get_model("llama")
    with pytest.raises(ValueError, match="KV head"):
        ContinuousBatcher(model, num_slots=2, tp=4, page_size=4)  # 2 KV heads % 4
    with pytest.raises(ValueError):
        ContinuousBatcher(model, num_slots=2, tp=0, page_size=4)
    import dataclasses

    bare = dataclasses.replace(model, sharding_rules=None)
    with pytest.raises(ValueError, match="sharding_rules"):
        ContinuousBatcher(bare, num_slots=2, tp=2, page_size=4)


@needs_mesh
def test_tp_swap_weights_reshards_at_setter():
    """The one-seam params setter: assigning raw params to a TP engine lands
    them sharded (the rolling-deploy path), and decode continues
    token-identically after the swap."""
    model = get_model("llama")
    engine = ContinuousBatcher(model, num_slots=2, chunk_size=4, page_size=4, tp=2)
    before = engine.run(make_requests(n=2))
    engine.params = model.params  # raw tree, as swap_weights hands it over
    for path, spec in engine.tp_sharding_report()["params"].items():
        if path.endswith("wq/kernel"):
            assert "model" in spec, (path, spec)
    after = engine.run(
        [Request(50 + i, list(range(3 + i, 10 + i)) + [2, 5, 2, 5], max_new_tokens=8) for i in range(2)]
    )
    for i in range(2):
        assert np.array_equal(before[i], after[50 + i])


# ----------------------------------------------------------------- composition
@needs_mesh
@pytest.mark.router
def test_router_over_tp_engines_smoke():
    """A mesh-spanning engine is ONE replica: the fleet assigns disjoint TP
    device groups per replica, serves and drains normally, and the rolling
    `swap_weights` re-shards at each engine's params setter."""
    from accelerate_tpu.router import Router

    model = get_model("llama")
    router = Router(
        model, replicas=2, max_queue=8, default_deadline_s=60.0,
        num_slots=2, chunk_size=4, page_size=4, tp=2,
    )
    try:
        groups = [
            tuple(d.id for d in replica.engine.mesh.devices.flat)
            for replica in router.replica_set.replicas
        ]
        assert len(set(groups)) == len(groups), f"TP groups overlap: {groups}"
        for i in range(6):
            router.submit(Request(i, list(range(3 + i, 10 + i)), max_new_tokens=6))
        while router.pending:
            router.step()
        assert all(
            r.finished and r.finish_reason in ("eos", "length")
            for r in router.results.values()
        )
        router.swap_weights(model.params)
        assert all(not rep.dead for rep in router.replica_set.replicas)
    finally:
        router.close()

"""Run the bundled launched scripts (reference pattern: tests spawn
test_utils/scripts/* via execute_subprocess — testing.py:501-560, test_multigpu.py).

Covers three topologies: the 8-device virtual CPU mesh (single process), a real
2-process rendezvous via debug_launcher, and the `accelerate-tpu test` CLI path.
"""

import os
import subprocess
import sys

import pytest

from accelerate_tpu.test_utils.testing import cpu_mesh_env, run_test_script


@pytest.mark.slow_launch
def test_script_on_virtual_mesh():
    result = run_test_script("test_script.py")
    assert "All checks passed." in result.stdout


@pytest.mark.slow_launch
def test_sync_script_on_virtual_mesh():
    result = run_test_script("test_sync.py")
    assert "All sync checks passed." in result.stdout


@pytest.mark.slow_launch
def test_ops_script_on_virtual_mesh():
    result = run_test_script("test_ops.py")
    assert "All op checks passed." in result.stdout


@pytest.mark.slow_launch
def test_ops_script_multiprocess():
    """Real 2-process run: object plane, debug-mode verifier, uneven pad all exercised
    across actual process boundaries."""
    from accelerate_tpu import debug_launcher
    from accelerate_tpu.test_utils.scripts.test_ops import main

    debug_launcher(main, num_processes=2)


@pytest.mark.slow_launch
def test_sync_script_multiprocess():
    """Gradient accumulation / sync semantics across 2 real coordinated processes
    (grad-equality at boundaries with allgather-backed reads)."""
    from accelerate_tpu import debug_launcher
    from accelerate_tpu.test_utils.scripts.test_sync import main

    debug_launcher(main, num_processes=2)


@pytest.mark.slow_launch
def test_everything_script_multiprocess():
    """The FULL everything-script across 2 real coordinated processes — training
    loss-parity, dispatch loader, resume, gather_for_metrics, trigger, sharded
    sampler: the whole contract surface across actual process boundaries, not
    just a topology check."""
    from accelerate_tpu import debug_launcher
    from accelerate_tpu.commands.test import _script_main

    debug_launcher(_script_main, num_processes=2)


@pytest.mark.slow_launch
def test_cli_test_command():
    result = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli", "test", "--cpu"],
        env=cpu_mesh_env(),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "success" in result.stdout

"""Async + per-host-sharded checkpointing: the snapshot-then-commit contract.

Pins, on CPU inside tier-1 time:

  1. `AsyncCommitter` mechanics — one in-flight commit, submit barriers on the
     previous one, a FAILED commit surfaces at the next barrier (never silently
     dropped), `abort_and_join` stops an in-flight commit before publish;
  2. `CheckpointManager.next_step` race-safety — a step staged by a background
     committer (invisible on disk until the publish rename) is already taken,
     and two overlapping saves of the SAME step are refused;
  3. the Accelerator round trips — async save == sync load parity, sharded
     save -> single-host gather-on-load parity, async+sharded combined;
  4. the goodput property — an async save charges ONLY its blocking portion to
     the ledger's `checkpoint` cause; the (injected-slow) commit lands in
     `checkpoint_async_commit_seconds` instead. The same injected delay under
     a sync save charges the ledger in full — the A/B the bench reports;
  5. failure modes — repeated EIO exhausts the commit's retries and raises
     `CheckpointCommitError` from the NEXT save; a committer killed mid-commit
     leaves the PREVIOUS published checkpoint as the loadable latest;
  6. the per-host shard layout — manifest/digest verification covers host
     subdirectories, a simulated two-host checkpoint gathers to exact parity,
     and a torn shard file fails directory verification;
  7. `launch --async_save/--sharded_save` join the env protocol.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from accelerate_tpu.chaos.injectors import FilesystemInjector, ChaosSession, InjectedKill
from accelerate_tpu.chaos.plan import FaultEvent, FaultPlan
from accelerate_tpu.chaos.runner import params_digest
from accelerate_tpu.checkpointing import (
    AsyncCommitter,
    CheckpointCommitError,
    CheckpointManager,
    is_sharded_checkpoint_dir,
    load_pytree_gathered,
    save_pytree_host_shards,
    save_pytree_shards,
    shard_host_dir,
    snapshot_pytree,
    snapshot_shards,
    verify_checkpoint_dir,
    write_checkpoint_manifest,
)

pytestmark = pytest.mark.checkpoint_async


def build_accelerator(base_dir, async_save=False, sharded_save=False, total_limit=None, seed=0):
    import optax

    from accelerate_tpu import Accelerator, SimpleDataLoader
    from accelerate_tpu.data_loader import BatchSampler
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel
    from accelerate_tpu.utils import ProjectConfiguration

    accelerator = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(base_dir), automatic_checkpoint_naming=True, total_limit=total_limit
        ),
        async_save=async_save,
        sharded_save=sharded_save,
    )
    n = 16
    data = [RegressionDataset(length=n, seed=seed)[i] for i in range(n)]
    dl = SimpleDataLoader(data, BatchSampler(range(n), 8))
    model, opt, pdl = accelerator.prepare(RegressionModel(), optax.sgd(0.05), dl)
    return accelerator, model, opt, pdl


def train_steps(accelerator, model, opt, pdl, steps, save_each=True):
    stream = (b for _ in iter(int, 1) for b in pdl)
    paths = []
    for _ in range(steps):
        batch = next(stream)
        accelerator.backward(model.loss, batch)
        opt.step()
        opt.zero_grad()
        if save_each:
            paths.append(accelerator.save_state())
    stream.close()
    return paths


# ------------------------------------------------------------------ committer mechanics
def test_committer_serializes_commits_and_surfaces_failure_at_barrier():
    committer = AsyncCommitter()
    order = []
    committer.submit(lambda abort: (time.sleep(0.05), order.append("first")), "first")
    # submit barriers on the previous commit: "first" lands before "second" starts
    committer.submit(lambda abort: order.append("second"), "second")
    committer.wait()
    assert order == ["first", "second"]

    def fails(abort):
        raise OSError("disk on fire")

    committer.submit(fails, "third")
    with pytest.raises(CheckpointCommitError, match="disk on fire"):
        committer.submit(lambda abort: None, "fourth")
    # the failure is consumed at the barrier that surfaced it — not re-raised forever
    committer.wait()


def test_committer_poll_surfaces_only_process_death_class():
    committer = AsyncCommitter()

    def killed(abort):
        raise InjectedKill("chaos: kill inside commit")

    committer.submit(killed, "killed")
    time.sleep(0.05)
    with pytest.raises(InjectedKill):
        committer.poll()

    committer = AsyncCommitter()
    committer.submit(lambda abort: (_ for _ in ()).throw(OSError("eio")), "eio")
    time.sleep(0.05)
    committer.poll()  # ordinary Exception keeps to the barrier contract
    with pytest.raises(CheckpointCommitError):
        committer.wait()


def test_committer_abort_stops_commit_before_publish(tmp_path):
    manager = CheckpointManager(str(tmp_path))
    committer = AsyncCommitter()
    entered = threading.Event()
    release = threading.Event()

    def write_fn(staging):
        entered.set()
        release.wait(timeout=5)
        with open(os.path.join(staging, "artifact.bin"), "wb") as f:
            f.write(b"x" * 16)

    committer.submit(lambda abort: manager.save(7, write_fn, abort=abort), "ckpt7")
    assert entered.wait(timeout=5)
    committer._abort.set()
    release.set()
    error = committer.abort_and_join()
    assert isinstance(error, CheckpointCommitError)
    # aborted BEFORE the publish rename: no checkpoint_7, only staging litter
    assert manager.checkpoints() == []
    with pytest.raises(CheckpointCommitError):
        committer.submit(lambda abort: None, "after-abort")  # single-use after abort


# ------------------------------------------------------------------ next_step race safety
def test_next_step_counts_inflight_background_saves(tmp_path):
    """Satellite regression: two overlapping saves must never mint the same
    step. A save staged by the background committer is invisible to the
    directory listing until its publish rename — next_step() must count it."""
    manager = CheckpointManager(str(tmp_path))
    started = threading.Event()
    release = threading.Event()

    def slow_write(staging):
        started.set()
        release.wait(timeout=5)
        with open(os.path.join(staging, "artifact.bin"), "wb") as f:
            f.write(b"a" * 8)

    worker = threading.Thread(target=lambda: manager.save(0, slow_write))
    worker.start()
    try:
        assert started.wait(timeout=5)
        # nothing published yet — the OLD next_step() returned 0 here (collision)
        assert manager.checkpoints() == []
        assert manager.next_step() == 1
        # overlapping save of the SAME in-flight step is refused outright
        with pytest.raises(ValueError, match="already has a save in flight"):
            manager.save(0, lambda staging: None)
    finally:
        release.set()
        worker.join(timeout=5)
    assert manager.next_step() == 1
    assert [step for step, _ in manager.checkpoints()] == [0]


def test_two_overlapping_accelerator_saves_publish_distinct_steps(tmp_path):
    accelerator, model, opt, pdl = build_accelerator(tmp_path, async_save=True)
    train_steps(accelerator, model, opt, pdl, 1, save_each=False)
    first = accelerator.save_state()
    second = accelerator.save_state()  # barriers on the first commit
    accelerator.drain_checkpoints()
    assert first != second
    assert os.path.isdir(first) and os.path.isdir(second)
    assert verify_checkpoint_dir(first) and verify_checkpoint_dir(second)


# ------------------------------------------------------------------ round trips
@pytest.mark.parametrize("sharded", [False, True], ids=["flat", "sharded"])
def test_async_save_round_trips_through_sync_load(tmp_path, sharded):
    accelerator, model, opt, pdl = build_accelerator(
        tmp_path, async_save=True, sharded_save=sharded
    )
    train_steps(accelerator, model, opt, pdl, 3)
    accelerator.drain_checkpoints()
    digest = params_digest(model)

    fresh, model2, opt2, pdl2 = build_accelerator(tmp_path)
    fresh.load_state("latest")
    assert params_digest(model2) == digest
    # the next save after resume does not collide with existing checkpoints
    path = fresh.save_state()
    assert verify_checkpoint_dir(path)


def test_sharded_layout_and_manifest(tmp_path):
    accelerator, model, opt, pdl = build_accelerator(tmp_path, sharded_save=True)
    train_steps(accelerator, model, opt, pdl, 1)
    ckpt = accelerator.checkpoint_manager().resolve("latest")
    assert is_sharded_checkpoint_dir(ckpt)
    host = os.path.join(ckpt, shard_host_dir(0))
    assert os.path.isfile(os.path.join(host, "model.npz"))
    assert os.path.isfile(os.path.join(host, "SHARD_DONE"))
    manifest = json.load(open(os.path.join(ckpt, "MANIFEST.json")))
    assert manifest["sharded"] == {"num_hosts": 1, "hosts": [shard_host_dir(0)]}
    # the directory manifest digests the host subdir's files too
    assert any(rel.startswith(shard_host_dir(0) + os.sep) for rel in manifest["files"])
    assert verify_checkpoint_dir(ckpt)
    # a torn shard payload fails directory verification
    target = os.path.join(host, "model.npz")
    with open(target, "r+b") as f:
        f.truncate(os.path.getsize(target) // 2)
    assert not verify_checkpoint_dir(ckpt)


def test_simulated_two_host_checkpoint_gathers_to_parity(tmp_path):
    """The multi-host layout, exercised without multiple processes: two hosts
    each write only their row slice of every leaf; gather-on-load must
    reassemble the exact full tree (the single-host pod-recovery path)."""
    import jax

    rng = np.random.default_rng(0)
    full = {
        "w": rng.standard_normal((8, 6)).astype(np.float32),
        "inner": {"b": rng.standard_normal((4,)).astype(np.float32)},
    }
    _, treedef = jax.tree_util.tree_flatten(full)
    for host, rows in ((0, (0, 4)), (1, (4, 8))):
        host_dir = tmp_path / shard_host_dir(host)
        os.makedirs(host_dir)
        entries = [
            {
                "path": "inner/b",
                "global_shape": [4],
                "dtype": np.dtype(np.float32),
                # replicated small leaf: both hosts write the whole thing
                "shards": [([[0, 4]], full["inner"]["b"])],
            },
            {
                "path": "w",
                "global_shape": [8, 6],
                "dtype": np.dtype(np.float32),
                "shards": [([[rows[0], rows[1]], [0, 6]], full["w"][rows[0]:rows[1]])],
            },
        ]
        leaf_treedef = jax.tree_util.tree_structure({"inner": {"b": 0}, "w": 0})
        save_pytree_shards(entries, leaf_treedef, str(host_dir / "model.npz"), host)
    gathered = load_pytree_gathered(str(tmp_path), "model.npz")
    np.testing.assert_array_equal(gathered["w"], full["w"])
    np.testing.assert_array_equal(gathered["inner"]["b"], full["inner"]["b"])
    # and the directory-level manifest covers both hosts' files
    write_checkpoint_manifest(str(tmp_path), step=0)
    assert verify_checkpoint_dir(str(tmp_path))


def test_snapshot_pytree_is_a_true_copy(tmp_path):
    import jax.numpy as jnp

    tree = {"a": jnp.arange(8.0), "b": np.arange(4, dtype=np.int32)}
    snap = snapshot_pytree(tree)
    assert isinstance(snap["a"], np.ndarray)
    snap["b"][0] = 99  # mutating the snapshot must not touch the original
    assert tree["b"][0] == 0 or snap["b"] is not tree["b"]
    entries, _ = snapshot_shards(tree)
    assert {e["path"] for e in entries} == {"a", "b"}
    save_pytree_host_shards(tree, str(tmp_path / shard_host_dir(0) / "t.npz"))
    out = load_pytree_gathered(str(tmp_path), "t.npz")
    np.testing.assert_array_equal(out["a"], np.arange(8.0))


# ------------------------------------------------------------------ goodput property
def test_async_save_charges_only_blocking_time(tmp_path):
    """THE satellite property: with a 0.4 s injected fsync stall, a SYNC save
    charges >= 0.4 s to the ledger's `checkpoint` cause; the SAME stall under
    an ASYNC save leaves the blocking charge far below it, with the stall
    showing up in `checkpoint_async_commit_seconds` instead."""
    delay = 0.4
    results = {}
    for mode in ("sync", "async"):
        base = tmp_path / mode
        plan = FaultPlan(events=[
            FaultEvent(kind="fs.slow_fsync", path_pattern="model.npz", at_call=1,
                       args={"delay_s": delay}),
        ])
        session = ChaosSession(plan)
        accelerator, model, opt, pdl = build_accelerator(base, async_save=(mode == "async"))
        with FilesystemInjector(session):
            train_steps(accelerator, model, opt, pdl, 1)
            accelerator.drain_checkpoints()
        results[mode] = {
            "lost_checkpoint_s": accelerator.timeline.goodput()["lost_s"].get("checkpoint", 0.0),
            "commit_s": accelerator._m_ckpt_commit_seconds.sum,
            "commits": accelerator._m_ckpt_commit_seconds.count,
        }
    assert results["sync"]["lost_checkpoint_s"] >= 0.9 * delay
    assert results["sync"]["commits"] == 0
    assert results["async"]["lost_checkpoint_s"] <= 0.5 * delay
    assert results["async"]["commits"] == 1
    assert results["async"]["commit_s"] >= 0.9 * delay


# ------------------------------------------------------------------ failure surfacing
def test_failed_async_commit_surfaces_on_next_save(tmp_path):
    """Repeated EIO on the model artifact exhausts the manager's retries inside
    the background commit; the NEXT save's barrier must raise — a failed async
    commit is never silently dropped."""
    plan = FaultPlan(events=[
        FaultEvent(kind="fs.io_error", path_pattern="model.npz", times=0,
                   args={"errno": "EIO"}),
    ])
    session = ChaosSession(plan)
    accelerator, model, opt, pdl = build_accelerator(tmp_path, async_save=True)
    with FilesystemInjector(session):
        train_steps(accelerator, model, opt, pdl, 1)
        time.sleep(0.05)
        with pytest.raises(CheckpointCommitError):
            # the barrier of the next save surfaces the dead commit
            train_steps(accelerator, model, opt, pdl, 1)
    # the failed step never published
    assert accelerator.checkpoint_manager().checkpoints() == []


def test_kill_mid_background_commit_keeps_previous_checkpoint_loadable(tmp_path):
    """ISSUE acceptance: a kill during a background commit never corrupts the
    previously published checkpoint. The committer of step 1 dies inside the
    model artifact's rename window; checkpoint_0 must stay the verified,
    loadable latest."""
    plan = FaultPlan(events=[
        FaultEvent(kind="fs.crash_in_rename", path_pattern="model.npz", at_call=2),
    ])
    session = ChaosSession(plan)
    accelerator, model, opt, pdl = build_accelerator(tmp_path, async_save=True)
    digests = []
    with FilesystemInjector(session):
        for _ in range(2):
            train_steps(accelerator, model, opt, pdl, 1, save_each=False)
            digests.append(params_digest(model))
            accelerator.save_state()
        with pytest.raises(InjectedKill):
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                accelerator.poll_async_checkpoint()
                time.sleep(0.01)
        # process-death semantics: the dying run aborts its committer
        accelerator.abort_async_checkpoint()
    manager = accelerator.checkpoint_manager()
    resolved = manager.resolve("latest")
    assert resolved.endswith("checkpoint_0")
    assert verify_checkpoint_dir(resolved)
    fresh, model2, _opt2, _pdl2 = build_accelerator(tmp_path)
    fresh.load_state("latest")
    assert params_digest(model2) == digests[0]


def test_preemption_flushes_inflight_commit(tmp_path):
    """PreemptionHandler path: check_preemption() drains the in-flight commit
    before writing the preemption checkpoint, so the handoff never races a
    background commit."""
    import signal

    accelerator, model, opt, pdl = build_accelerator(tmp_path, async_save=True)
    handler = accelerator.register_preemption_checkpoint(exit_on_save=False)
    train_steps(accelerator, model, opt, pdl, 1)
    os.kill(os.getpid(), signal.SIGTERM)
    assert handler.preemption_requested
    assert accelerator.check_preemption() is True
    # both the async step-0 save and the preemption save are committed + verified
    manager = accelerator.checkpoint_manager()
    steps = [step for step, path in manager.checkpoints() if verify_checkpoint_dir(path)]
    assert steps == [0, 1]
    handler.uninstall()


# ------------------------------------------------------------------ CLI env protocol
def test_launch_exports_async_and_sharded_save_env(tmp_path):
    import argparse

    from accelerate_tpu.commands.launch import add_launch_args, build_launch_env

    parser = argparse.ArgumentParser()
    add_launch_args(parser)
    args = parser.parse_args(["--async_save", "--sharded_save", "script.py"])
    env = build_launch_env(args, {})
    assert env["ACCELERATE_TPU_ASYNC_SAVE"] == "1"
    assert env["ACCELERATE_TPU_SHARDED_SAVE"] == "1"
    # and the Accelerator-side default reads them
    args = parser.parse_args(["script.py"])
    env = build_launch_env(args, {"async_save": True})
    assert env["ACCELERATE_TPU_ASYNC_SAVE"] == "1"


# ------------------------------------------------------------------ adaptive cadence
@pytest.mark.checkpoint_async
class TestAdaptiveSaveInterval:
    """The goodput-driven cadence controller (ROADMAP 4b): pure observation ->
    arithmetic, driven here by a chaos FakeClock ledger."""

    def _controller(self, **kw):
        from accelerate_tpu.checkpointing import AdaptiveSaveInterval

        return AdaptiveSaveInterval(**kw)

    def test_no_cadence_before_first_step_observation(self):
        ctl = self._controller(lost_checkpoint_s=10.0)
        assert ctl.interval is None
        assert not ctl.should_save(10_000)

    def test_budget_cap_from_fakeclock_ledger(self):
        from accelerate_tpu.chaos import FakeClock

        clock = FakeClock()
        ctl = self._controller(lost_checkpoint_s=10.0, overhead_fraction=0.1)
        for _ in range(20):
            t0 = clock.perf_counter()
            clock.sleep(0.1)  # a 100ms step
            ctl.observe_step(clock.perf_counter() - t0)
        # 10s of acceptable lost work / 0.1s steps -> save every 100 steps
        assert ctl.interval == 100
        assert ctl.should_save(100) and not ctl.should_save(99)
        # a cheap save (0.5s at 10% overhead -> floor 50) does not change it
        t0 = clock.perf_counter()
        clock.sleep(0.5)
        ctl.observe_save(clock.perf_counter() - t0)
        assert ctl.interval == 100

    def test_smaller_budget_saves_more_often_and_slower_steps_too(self):
        a = self._controller(lost_checkpoint_s=10.0)
        b = self._controller(lost_checkpoint_s=2.0)
        for ctl in (a, b):
            for _ in range(5):
                ctl.observe_step(0.1)
        assert b.interval < a.interval
        c = self._controller(lost_checkpoint_s=10.0)
        for _ in range(5):
            c.observe_step(0.4)  # slower steps -> fewer steps inside the budget
        assert c.interval < a.interval

    def test_expensive_saves_stretch_past_an_unaffordable_budget(self):
        ctl = self._controller(lost_checkpoint_s=10.0, overhead_fraction=0.1)
        for _ in range(10):
            ctl.observe_step(0.1)
        for _ in range(30):
            ctl.observe_save(5.0)  # 5s saves: the 10s budget is unaffordable
        # overhead floor 5/(0.1*0.1)=500 beats the 100-step budget cap
        assert ctl.interval == 500

    def test_fixed_interval_mode_and_validation(self):
        ctl = self._controller(fixed_interval=7)
        assert ctl.interval == 7
        assert ctl.should_save(7) and not ctl.should_save(6)
        with pytest.raises(ValueError):
            self._controller(lost_checkpoint_s=0.0)
        with pytest.raises(ValueError):
            self._controller(overhead_fraction=1.5)
        with pytest.raises(ValueError):
            self._controller(fixed_interval=0)

    def test_ema_tracks_drifting_step_time(self):
        ctl = self._controller(lost_checkpoint_s=10.0, ema=0.5)
        for _ in range(10):
            ctl.observe_step(0.1)
        fast = ctl.interval
        for _ in range(10):
            ctl.observe_step(1.0)  # the run slowed down 10x
        assert ctl.interval < fast


@pytest.mark.checkpoint_async
def test_accelerator_auto_save_interval_drives_maybe_save_state(tmp_path):
    """End to end: `Accelerator(save_interval="auto")` saves through
    `maybe_save_state()` on the controller's cadence and feeds the measured
    (goodput-ledger) save cost back into it."""
    import optax

    from accelerate_tpu import Accelerator, SimpleDataLoader
    from accelerate_tpu.data_loader import BatchSampler
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel
    from accelerate_tpu.utils import ProjectConfiguration

    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True
        ),
        save_interval="auto",
        lost_checkpoint_s=0.001,  # microscopic budget: a save is due immediately
    )
    data = [RegressionDataset(length=8, seed=0)[i] for i in range(8)]
    model, opt, pdl = acc.prepare(
        RegressionModel(), optax.sgd(0.05), SimpleDataLoader(data, BatchSampler(range(8), 4))
    )
    saved = []
    for _ in range(3):
        for batch in pdl:
            acc.backward(model.loss, batch)
            opt.step()
            opt.zero_grad()
        path = acc.maybe_save_state()
        if path:
            saved.append(path)
    ctl = acc.save_controller
    # the first due boundary saved, and the controller learned the real cost
    assert saved and ctl.saves_observed == len(saved)
    assert ctl.avg_save_s is not None and ctl.avg_save_s > 0
    assert ctl.steps_observed >= 2
    assert os.path.isdir(saved[0])


@pytest.mark.checkpoint_async
def test_accelerator_fixed_save_interval(tmp_path):
    import optax

    from accelerate_tpu import Accelerator, SimpleDataLoader
    from accelerate_tpu.data_loader import BatchSampler
    from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel
    from accelerate_tpu.utils import ProjectConfiguration

    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True
        ),
        save_interval=2,
    )
    data = [RegressionDataset(length=8, seed=0)[i] for i in range(8)]
    model, opt, pdl = acc.prepare(
        RegressionModel(), optax.sgd(0.05), SimpleDataLoader(data, BatchSampler(range(8), 4))
    )
    saves = 0
    for _ in range(6):
        for batch in pdl:
            acc.backward(model.loss, batch)
            opt.step()
            opt.zero_grad()
        if acc.maybe_save_state():
            saves += 1
    assert saves == 3  # every 2nd of 6 boundaries

    plain = Accelerator(project_config=ProjectConfiguration(project_dir=str(tmp_path)))
    with pytest.raises(RuntimeError, match="save_interval"):
        plain.maybe_save_state()

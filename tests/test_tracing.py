"""Request-scoped tracing tests: span lifecycle/nesting semantics, the
flight-recorder ring (eviction order, streamed JSONL, touch-file dumps),
Perfetto trace-event export schema, hang-watchdog firing on a stalled fake
step, cross-process trace-id propagation through a real Supervisor child, the
serving engine's submit->finish span coverage, the goodput unaccounted-time
alarm, and the chaos smoke-serve dump carrying injected faults as events."""

import json
import os
import sys

import numpy as np
import pytest

from accelerate_tpu.telemetry import (
    FlightRecorder,
    Tracer,
    collect_trace_dir,
    read_span_jsonl,
    to_trace_events,
)
from accelerate_tpu.telemetry.flight_recorder import DUMP_TOUCH_FILE
from accelerate_tpu.telemetry.tracing import TRACE_DIR_ENV, TRACE_ID_ENV, TRACE_PARENT_ENV

pytestmark = pytest.mark.tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ span semantics
def test_span_lifecycle_and_nesting():
    recorder = FlightRecorder()
    tracer = Tracer(recorder=recorder, category="test")
    with tracer.span("outer", a=1) as outer:
        assert tracer.current_span is outer
        outer.event("milestone", note="hi")
        with tracer.span("inner") as inner:
            assert inner.parent_id == outer.span_id
        assert tracer.current_span is outer
    assert tracer.current_span is None

    records = recorder.records()
    assert [r["name"] for r in records] == ["inner", "outer"]  # completion order
    outer_rec = records[1]
    assert outer_rec["attrs"] == {"a": 1}
    assert outer_rec["events"][0]["name"] == "milestone"
    assert outer_rec["trace_id"] == records[0]["trace_id"] == tracer.trace_id
    assert outer_rec["end_unix"] >= outer_rec["start_unix"]
    # idempotent end: a double-ended span records exactly once
    span = tracer.start_span("solo")
    span.end()
    span.end()
    assert [r["name"] for r in recorder.records()].count("solo") == 1


def test_span_error_annotation_and_propagation():
    tracer = Tracer(recorder=FlightRecorder())
    with pytest.raises(RuntimeError):
        with tracer.span("doomed"):
            raise RuntimeError("boom")
    (record,) = tracer.recorder.records()
    assert "boom" in record["attrs"]["error"]
    assert tracer.current_span is None  # the stack unwound


def test_annotation_host_value_gate():
    """The runtime half of TPU112: a device-array-shaped value (anything
    non-host) must raise before it can hide a blocking readback."""
    tracer = Tracer(recorder=FlightRecorder())
    with pytest.raises(TypeError, match="host values"):
        tracer.start_span("bad", payload=np.ones(3))
    span = tracer.start_span("ok", n=3, f=0.5, s="x", b=True, none=None)
    with pytest.raises(TypeError, match="host values"):
        span.event("bad", arr=[1, 2])
    span.end()


# ------------------------------------------------------------------ flight recorder
def test_ring_buffer_eviction_order():
    recorder = FlightRecorder(capacity=4)
    tracer = Tracer(recorder=recorder)
    for i in range(10):
        tracer.start_span("s", idx=i).end()
    records = recorder.records()
    assert len(records) == 4
    assert [r["attrs"]["idx"] for r in records] == [6, 7, 8, 9]  # oldest evicted first
    assert recorder.registry.value("trace_spans_recorded_total") == 10
    assert recorder.registry.value("trace_spans_evicted_total") == 6


def test_streamed_jsonl_survives_torn_tail(tmp_path):
    trace_dir = str(tmp_path / "trace")
    recorder = FlightRecorder(log_dir=trace_dir)
    tracer = Tracer(recorder=recorder)
    open_span = tracer.start_span("unfinished")  # streamed as span_start only
    tracer.start_span("done").end()
    tracer.event("marker", k=1)
    stream = os.path.join(trace_dir, f"spans_{os.getpid()}.jsonl")
    with open(stream, "a") as f:
        f.write('{"kind": "span", "name": "torn')  # a killed writer's last line
    records = read_span_jsonl(stream)
    kinds = {(r["kind"], r["name"]) for r in records}
    assert ("span_start", "unfinished") in kinds
    assert ("span", "done") in kinds
    assert ("event", "marker") in kinds
    assert not any(r.get("name") == "torn" for r in records)
    assert collect_trace_dir(trace_dir) == sorted(
        records, key=lambda r: r.get("start_unix", r.get("t_unix", 0.0))
    )
    open_span.end()


def test_perfetto_export_schema_and_roundtrip(tmp_path):
    trace_dir = str(tmp_path / "trace")
    recorder = FlightRecorder(log_dir=trace_dir)
    tracer = Tracer(recorder=recorder)
    with tracer.span("parent", kindof="serve") as parent:
        parent.event("instant", x=1)
        with tracer.span("child"):
            pass
    tracer.event("standalone")
    dangling = tracer.start_span("dangling")  # never ended: only span_start streams

    path = recorder.dump(reason="test")
    data = json.loads(open(path).read())
    assert set(data) == {"traceEvents", "displayTimeUnit"}
    events = data["traceEvents"]
    phases = {e["ph"] for e in events}
    assert phases <= {"M", "X", "B", "i"}
    for event in events:
        assert isinstance(event["ts"], int) if event["ph"] != "M" else True
        assert "pid" in event and "name" in event
        if event["ph"] == "X":
            assert event["dur"] >= 0
    # monotonic per-pid ordering (what makes the timeline readable)
    ts = [e["ts"] for e in events if e["ph"] != "M"]
    assert ts == sorted(ts)
    by_name = {e["name"] for e in events}
    assert {"parent", "child", "instant", "standalone"} <= by_name
    # the dangling span is not in the RING dump (it never completed)...
    assert "dangling" not in by_name
    # ...but its streamed span_start exports as an unfinished "B" event.
    stitched = to_trace_events(collect_trace_dir(trace_dir))["traceEvents"]
    assert any(e["name"] == "dangling" and e["ph"] == "B" for e in stitched)
    dangling.end()


def test_touch_file_dump_trigger(tmp_path):
    trace_dir = str(tmp_path / "trace")
    recorder = FlightRecorder(log_dir=trace_dir, poll_every=2)
    Tracer(recorder=recorder).start_span("work").end()
    touch = os.path.join(trace_dir, DUMP_TOUCH_FILE)
    open(touch, "w").close()
    assert recorder.poll() is False  # off-cadence call: no probe yet
    assert recorder.poll() is True  # cadence hit: trigger consumed, dump written
    assert not os.path.exists(touch)
    dumps = [n for n in os.listdir(trace_dir) if n.startswith("trace_") and n.endswith(".json")]
    assert len(dumps) == 1


# ------------------------------------------------------------------ hang watchdog
def test_hang_watchdog_fires_on_stalled_fake_step(tmp_path):
    from accelerate_tpu.chaos.injectors import FakeClock

    clock = FakeClock()
    trace_dir = str(tmp_path / "trace")
    recorder = FlightRecorder(log_dir=trace_dir, clock=clock.monotonic)
    tracer = Tracer(recorder=recorder, clock=clock.monotonic)
    watchdog = recorder.start_watchdog(
        deadline_s=30.0, tracer=tracer, clock=clock.monotonic, start_thread=False
    )
    clock.sleep(100)
    assert watchdog.check_once() is False  # unarmed: warmup is not a stall
    tracer.start_span("train.step", step=0).end()
    recorder.heartbeat()
    clock.sleep(10)
    assert watchdog.check_once() is False  # within deadline

    clock.sleep(25)  # 35s since the last heartbeat: the step stalled
    assert watchdog.check_once() is True
    assert watchdog.check_once() is False  # one artifact per stall, not per poll

    # The dump carries the hang marker + the step that preceded the stall...
    data = json.loads(open(watchdog.last_dump).read())
    names = {e["name"] for e in data["traceEvents"]}
    assert "hang.detected" in names and "train.step" in names
    # ...and the stacks file shows what every thread was doing.
    stacks = open(watchdog.last_stacks_path).read()
    assert "thread" in stacks and "test_hang_watchdog_fires_on_stalled_fake_step" in stacks

    recorder.heartbeat()  # the loop came back: the watchdog re-arms
    clock.sleep(31)
    assert watchdog.check_once() is True
    assert watchdog.fired_count == 2


# ------------------------------------------------------------------ cross-process
def test_trace_context_propagates_through_real_supervisor_child(tmp_path):
    from accelerate_tpu.fault_tolerance import Supervisor

    trace_dir = str(tmp_path / "trace")
    tracer = Tracer(recorder=FlightRecorder(log_dir=trace_dir), category="supervisor")
    child_src = (
        "from accelerate_tpu.telemetry.tracing import Tracer\n"
        "tracer = Tracer.from_env()\n"
        "with tracer.span('child.work', category='worker'):\n"
        "    pass\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    supervisor = Supervisor([sys.executable, "-c", child_src], env=env, tracer=tracer)
    assert supervisor.run() == 0

    records = collect_trace_dir(trace_dir)
    attempts = [r for r in records if r["name"] == "supervisor.attempt" and r["kind"] == "span"]
    child_spans = [r for r in records if r["name"] == "child.work" and r["kind"] == "span"]
    assert len(attempts) == 1 and len(child_spans) == 1
    # One trace id across both processes; the child's root span parents under
    # the supervisor attempt that spawned it.
    assert child_spans[0]["trace_id"] == attempts[0]["trace_id"] == tracer.trace_id
    assert child_spans[0]["parent_id"] == attempts[0]["span_id"]
    assert child_spans[0]["pid"] != attempts[0]["pid"]
    exits = [r for r in records if r["name"] == "supervisor.child_exit"]
    assert exits and exits[0]["attrs"]["exit_code"] == 0


def test_tracer_from_env_reads_protocol(tmp_path, monkeypatch):
    monkeypatch.setenv(TRACE_DIR_ENV, str(tmp_path / "t"))
    monkeypatch.setenv(TRACE_ID_ENV, "cafecafecafe")
    monkeypatch.setenv(TRACE_PARENT_ENV, "beefbeefbeef")
    tracer = Tracer.from_env()
    assert tracer.trace_id == "cafecafecafe"
    assert tracer.root_parent_id == "beefbeefbeef"
    assert tracer.recorder.log_dir == str(tmp_path / "t")
    span = tracer.start_span("root")
    assert span.parent_id == "beefbeefbeef"
    span.end()
    # inject_env round-trips the context for the next hop down
    env = tracer.inject_env({})
    assert env[TRACE_ID_ENV] == "cafecafecafe"
    assert env[TRACE_DIR_ENV] == str(tmp_path / "t")


# ------------------------------------------------------------------ serving spans
def _tiny_llama():
    from accelerate_tpu.models.llama import LlamaConfig, create_llama_model

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
        rope_theta=10000.0,
    )
    return create_llama_model(cfg, seq_len=32)


def test_serving_request_lifecycle_spans():
    from accelerate_tpu.serving import ContinuousBatcher, Request

    recorder = FlightRecorder()
    tracer = Tracer(recorder=recorder, category="serve")
    engine = ContinuousBatcher(_tiny_llama(), num_slots=2, max_length=64, chunk_size=4,
                               tracer=tracer)
    rng = np.random.default_rng(0)
    for i in range(4):
        engine.submit(Request(i, rng.integers(1, 128, (6,)).astype(np.int32), max_new_tokens=5))
    engine.run()
    engine.close()

    records = recorder.records()
    requests = {r["attrs"]["request_id"]: r for r in records if r["name"] == "serve.request"}
    assert sorted(requests) == [0, 1, 2, 3]
    for record in requests.values():
        assert record["attrs"]["finish_reason"] == "length"
        assert record["attrs"]["tokens"] == 5
        assert [e["name"] for e in record["events"]] == ["submitted", "admitted", "first_token"]
        admitted = record["events"][1]["attrs"]
        assert admitted["queue_wait_s"] >= 0 and "pages_reserved" in admitted
    inserts = [r for r in records if r["name"] == "serve.insert"]
    assert len(inserts) == 4
    assert all(r["parent_id"] in {q["span_id"] for q in requests.values()} for r in inserts)
    chunks = [r for r in records if r["name"] == "serve.decode_chunk"]
    assert chunks and all("slots" in r["attrs"] for r in chunks)


# ------------------------------------------------------------------ goodput alarm
def test_goodput_unaccounted_warning_and_span_event():
    from accelerate_tpu.chaos.injectors import FakeClock
    from accelerate_tpu.telemetry import StepTimeline

    clock = FakeClock()
    tracer = Tracer(recorder=FlightRecorder(), clock=clock.monotonic)
    timeline = StepTimeline(
        clock=clock.perf_counter, tracer=tracer, unaccounted_warn_s=50.0
    )
    with timeline.phase("dispatch"):
        clock.sleep(1.0)
    timeline.step_done()
    clock.sleep(100.0)  # an opaque stall: nothing productive, nothing charged
    report = timeline.goodput()
    assert report["unaccounted_s"] >= 50.0
    events = [r for r in tracer.recorder.records() if r["name"] == "goodput.unaccounted"]
    assert len(events) == 1
    assert events[0]["attrs"]["unaccounted_s"] == pytest.approx(report["unaccounted_s"], abs=0.1)

    timeline.goodput()  # once per window, not per call
    assert len([r for r in tracer.recorder.records() if r["name"] == "goodput.unaccounted"]) == 1
    timeline.reset()
    clock.sleep(200.0)
    timeline.goodput()  # a fresh window re-arms the alarm
    assert len([r for r in tracer.recorder.records() if r["name"] == "goodput.unaccounted"]) == 2


# ------------------------------------------------------------------ chaos dump
@pytest.mark.chaos
def test_chaos_smoke_serve_dump_is_perfetto_complete(tmp_path):
    """The acceptance path: `chaos run smoke-serve` with a trace dir, then
    `trace dump` — the JSON must hold submit->finish spans for every request
    and every injected fault as an event."""
    from accelerate_tpu.chaos import ChaosRunner, builtin_plans
    from accelerate_tpu.commands.trace import trace_dump_command

    trace_dir = str(tmp_path / "trace")
    runner = ChaosRunner(builtin_plans()["smoke-serve"], trace_dir=trace_dir)
    report = runner.run_serve(num_requests=6)
    assert report.ok, report.render_text()
    trace_check = next(c for c in report.checks if c.name == "trace_complete")
    assert trace_check.passed and trace_check.details["request_spans"] >= 6

    class Args:
        pass

    args = Args()
    args.trace_dir, args.out, args.wait = trace_dir, None, 0.0
    with pytest.raises(SystemExit) as exc:
        trace_dump_command(args)
    assert exc.value.code == 0
    data = json.loads(open(os.path.join(trace_dir, "trace.json")).read())
    names = [e["name"] for e in data["traceEvents"]]
    finished = [
        e for e in data["traceEvents"]
        if e["name"] == "serve.request" and "finish_reason" in e.get("args", {})
    ]
    assert len(finished) == trace_check.details["accepted"]
    for kind in ("serve.dispatch_stall", "serve.queue_burst", "serve.dispatch_error"):
        assert f"chaos.{kind}" in names  # the injected faults, on the timeline
    assert "serve.blast_radius" in names  # the dispatch failure's blast radius


def test_trace_export_cli_stitches_multiple_streams(tmp_path):
    from accelerate_tpu.commands.trace import trace_export_command

    trace_dir = str(tmp_path / "trace")
    recorder = FlightRecorder(log_dir=trace_dir)
    Tracer(recorder=recorder, trace_id="feedfacefeed").start_span("a").end()
    # a second "process": same dir, different stream file
    other = os.path.join(trace_dir, "spans_99999.jsonl")
    with open(other, "w") as f:
        f.write(json.dumps({
            "kind": "span", "name": "b", "cat": "x", "trace_id": "feedfacefeed",
            "span_id": "0b", "parent_id": None, "pid": 99999, "tid": 1,
            "start_unix": 1.0, "end_unix": 2.0, "duration_s": 1.0, "attrs": {},
        }) + "\n")

    class Args:
        pass

    args = Args()
    args.inputs, args.out = [trace_dir], str(tmp_path / "out.json")
    with pytest.raises(SystemExit) as exc:
        trace_export_command(args)
    assert exc.value.code == 0
    data = json.loads(open(args.out).read())
    pids = {e["pid"] for e in data["traceEvents"] if e["ph"] == "X"}
    assert len(pids) == 2

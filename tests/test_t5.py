"""T5 encoder-decoder family: teacher-forced training, cached seq2seq generation
parity, HF interchange, transformers forward parity — the reference's T0pp-11B
benchmark config (benchmarks/README.md:35), and the only encoder-decoder in the
table (cross-attention + relative position biases)."""

import numpy as np
import pytest

import jax.numpy as jnp

from accelerate_tpu.models.t5 import create_t5_model, t5_tiny
from accelerate_tpu.utils.hf_loading import convert_hf_state_dict, export_hf_state_dict


def _batch(rng, bs=4, enc_len=12, dec_len=6, vocab=512):
    return {
        "input_ids": rng.integers(1, vocab, (bs, enc_len)).astype(np.int32),
        "decoder_input_ids": rng.integers(1, vocab, (bs, dec_len)).astype(np.int32),
        "labels": rng.integers(0, vocab, (bs, dec_len)).astype(np.int64),
    }


def test_forward_shapes_and_determinism():
    model = create_t5_model(t5_tiny(), seq_len=16)
    rng = np.random.default_rng(0)
    b = _batch(rng)
    out = model.apply_fn(model.params, jnp.asarray(b["input_ids"]), jnp.asarray(b["decoder_input_ids"]))
    assert out.shape == (4, 6, 512)
    out2 = model.apply_fn(model.params, jnp.asarray(b["input_ids"]), jnp.asarray(b["decoder_input_ids"]))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_training_through_accelerator_decreases_loss():
    import optax

    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    model = create_t5_model(t5_tiny(), seq_len=16)
    pmodel, popt = accelerator.prepare(model, optax.adamw(1e-3))
    step = accelerator.train_step(model=pmodel)
    rng = np.random.default_rng(0)
    batch = _batch(rng, bs=8)
    first = float(step(batch))
    for _ in range(10):
        last = float(step(batch))
    assert last < first


def test_seq2seq_cached_greedy_matches_full_context():
    """The fused encode+decode loop must equal argmax over the full teacher-forced
    forward grown one token at a time (pins cache writes, decoder relative-bias
    positions, and cross-attention under the cache)."""
    from accelerate_tpu.generation import Seq2SeqGenerator

    cfg = t5_tiny()
    model = create_t5_model(cfg, seq_len=16)
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, cfg.vocab_size, (2, 10)).astype(np.int32)

    gen = Seq2SeqGenerator(model, max_new_tokens=6, decoder_start_token_id=0)
    out = np.asarray(gen(prompt, max_new_tokens=6))

    # Reference: grow decoder context through the uncached full forward.
    dec = np.zeros((2, 1), np.int32)  # start token
    for _ in range(6):
        logits = np.asarray(
            model.apply_fn(model.params, jnp.asarray(prompt), jnp.asarray(dec))
        )
        nxt = logits[:, -1, :].argmax(-1).astype(np.int32)
        dec = np.concatenate([dec, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, dec[:, 1:])


def test_seq2seq_generate_with_attention_mask_kwarg():
    """attention_mask rides as a kwarg next to generation settings (the HF calling
    convention); it must not leak into GenerationConfig."""
    from accelerate_tpu.generation import Seq2SeqGenerator

    cfg = t5_tiny()
    model = create_t5_model(cfg, seq_len=16)
    rng = np.random.default_rng(5)
    prompt = rng.integers(1, cfg.vocab_size, (2, 10)).astype(np.int32)
    mask = np.ones((2, 10), np.int32)
    mask[:, 7:] = 0  # padded tail
    gen = Seq2SeqGenerator(model, max_new_tokens=4)
    out = np.asarray(gen(prompt, max_new_tokens=4, attention_mask=mask))
    assert out.shape == (2, 4)
    # Masked positions must actually change the result vs the unmasked prompt.
    out_unmasked = np.asarray(gen(prompt, max_new_tokens=4))
    assert not np.array_equal(out, out_unmasked)


def test_hf_round_trip_preserves_logits():
    cfg = t5_tiny()
    model = create_t5_model(cfg, seq_len=16)
    rng = np.random.default_rng(2)
    b = _batch(rng)
    ids, dec = jnp.asarray(b["input_ids"]), jnp.asarray(b["decoder_input_ids"])
    ref = np.asarray(model.apply_fn(model.params, ids, dec))

    flat = export_hf_state_dict(model.params, "t5", cfg)
    assert "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight" in flat
    assert "decoder.block.1.layer.1.EncDecAttention.q.weight" in flat
    params2 = convert_hf_state_dict(flat, "t5", cfg)
    out = np.asarray(model.apply_fn(params2, ids, dec))
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_real_transformers_t5_matches():
    """Forward parity vs HF T5ForConditionalGeneration in the v1.1 configuration
    (gated-gelu, untied head) — pins relative-bucket math, no-scale attention, and
    the RMSNorm placement."""
    transformers = pytest.importorskip("transformers")
    import torch

    hf_cfg = transformers.T5Config(
        vocab_size=512,
        d_model=64,
        d_kv=16,
        d_ff=128,
        num_layers=2,
        num_decoder_layers=2,
        num_heads=4,
        relative_attention_num_buckets=32,
        relative_attention_max_distance=128,
        dropout_rate=0.0,
        layer_norm_epsilon=1e-6,
        feed_forward_proj="gated-gelu",
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf_model = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    flat = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    cfg = t5_tiny()
    params = convert_hf_state_dict(flat, "t5", cfg)
    model = create_t5_model(cfg, seq_len=16)

    rng = np.random.default_rng(3)
    ids_np = rng.integers(1, 512, (2, 12))
    dec_np = rng.integers(1, 512, (2, 6))
    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.from_numpy(ids_np), decoder_input_ids=torch.from_numpy(dec_np)
        ).logits.numpy()
    out = np.asarray(model.apply_fn(params, jnp.asarray(ids_np, jnp.int32), jnp.asarray(dec_np, jnp.int32)))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_layered_apply_matches_monolithic():
    """Encoder-then-decoder streaming through the LayeredApply protocol (the
    T0pp-11B device_map route) must match the monolithic forward; split/join
    round-trips the params."""
    from accelerate_tpu.models.t5 import T5LayeredApply

    cfg = t5_tiny()
    model = create_t5_model(cfg, seq_len=16)
    layered = T5LayeredApply(cfg)
    rng = np.random.default_rng(6)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 12)), jnp.int32)
    dec = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 6)), jnp.int32)
    ref = np.asarray(model.apply_fn(model.params, ids, dec))

    prelude, layers, tail = layered.split(model.params)
    assert len(layers) == cfg.num_layers + cfg.num_decoder_layers
    carry = layered.apply_prelude(prelude, ids, dec)
    for lp in layers:
        carry = layered.apply_layer(lp, carry)
    out = np.asarray(layered.apply_tail(tail, carry))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    rejoined = layered.join(prelude, layers, tail)
    out2 = np.asarray(model.apply_fn(rejoined, ids, dec))
    np.testing.assert_array_equal(out2, ref)


def test_dispatched_cpu_offload_matches_monolithic():
    """The full big-model path: cpu_offload + streamed execution on a T5 bundle
    equals the monolithic forward (the reference's T0pp CPU-offload benchmark
    configuration, shrunk)."""
    from accelerate_tpu.big_modeling import cpu_offload
    from accelerate_tpu.models.t5 import T5LayeredApply

    cfg = t5_tiny()
    model = create_t5_model(cfg, seq_len=16)
    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 12)), jnp.int32)
    dec = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 6)), jnp.int32)
    ref = np.asarray(model.apply_fn(model.params, ids, dec))

    dispatched = cpu_offload(model, T5LayeredApply(cfg))
    out = np.asarray(dispatched(ids, dec))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_registry_entry():
    from accelerate_tpu.models import get_model_config

    assert get_model_config("t0pp-11b")["hidden_size"] == 4096


def test_pipeline_inference_rejects_heterogeneous_layers():
    """Encoder-decoder stage decompositions can't scan as one pipeline body;
    prepare_pippy must say so clearly and point at the streamed path."""
    from accelerate_tpu.inference import prepare_pippy
    from accelerate_tpu.models.t5 import T5LayeredApply

    cfg = t5_tiny()
    model = create_t5_model(cfg, seq_len=16)
    with pytest.raises(NotImplementedError, match="tier-streamed"):
        prepare_pippy(model, layered=T5LayeredApply(cfg))


def test_seq2seq_overbudget_max_new_tokens_raises():
    """Requesting more tokens than the constructed decoder cache holds must raise
    (not silently clamp — the caller asked for 64 and would get 32 with no signal;
    round-3 advice, mirrors Generator's no-room check)."""
    import pytest

    from accelerate_tpu.generation import GenerationConfig, Seq2SeqGenerator

    cfg = t5_tiny()
    model = create_t5_model(cfg, seq_len=16)
    prompt = np.ones((1, 6), np.int32)
    gen = Seq2SeqGenerator(model, max_new_tokens=4)
    with pytest.raises(ValueError, match="cache was sized for 4"):
        gen(prompt, GenerationConfig(max_new_tokens=8))


def test_seq2seq_bare_call_fills_generator_budget():
    """A bare call (no config, no kwarg) must not trip the over-budget check even
    when the generator's cache is smaller than the GenerationConfig default (32):
    the dataclass default is not a user request."""
    from accelerate_tpu.generation import Seq2SeqGenerator

    cfg = t5_tiny()
    model = create_t5_model(cfg, seq_len=16)
    prompt = np.ones((1, 6), np.int32)
    gen = Seq2SeqGenerator(model, max_new_tokens=4)
    out = np.asarray(gen(prompt))
    assert out.shape == (1, 4)


# ------------------------------------------------------------------ v1.0 layout
@pytest.mark.slow
def test_t5_v1_0_forward_training_and_cached_generation():
    """The v1.0 generation (tied head + relu FFN — t5-small/base/large; the
    reference loads them via load_checkpoint_in_model utils/modeling.py:1565):
    trains and the cached decode loop matches the uncached full forward."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.generation import Seq2SeqGenerator
    from accelerate_tpu.models.t5 import t5_tiny_v1_0

    cfg = t5_tiny_v1_0()
    model = create_t5_model(cfg, seq_len=16)
    # tied head: no lm_head params, single relu wi in the FFN
    inner = model.params["params"]
    assert "lm_head" not in inner
    assert "wi" in inner["enc_blocks_0"]["ff"] and "wi_0" not in inner["enc_blocks_0"]["ff"]

    accelerator = Accelerator()
    pmodel, popt = accelerator.prepare(model, optax.adamw(1e-3))
    step = accelerator.train_step(model=pmodel)
    rng = np.random.default_rng(0)
    batch = _batch(rng, bs=8)
    first = float(step(batch))
    for _ in range(10):
        last = float(step(batch))
    assert last < first

    gen = Seq2SeqGenerator(model, max_new_tokens=5, decoder_start_token_id=0)
    prompt = rng.integers(1, cfg.vocab_size, (2, 10)).astype(np.int32)
    out = np.asarray(gen(prompt, max_new_tokens=5))
    dec = np.zeros((2, 1), np.int32)
    for _ in range(5):
        logits = np.asarray(model.apply_fn(model.params, jnp.asarray(prompt), jnp.asarray(dec)))
        nxt = logits[:, -1, :].argmax(-1).astype(np.int32)
        dec = np.concatenate([dec, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, dec[:, 1:])


def test_t5_v1_0_hf_round_trip():
    from accelerate_tpu.models.t5 import t5_tiny_v1_0

    cfg = t5_tiny_v1_0()
    model = create_t5_model(cfg, seq_len=16)
    flat = export_hf_state_dict(model.params, "t5", cfg)
    # v1.0 signature: tied head absent, single wi present
    assert "lm_head.weight" not in flat
    assert "encoder.block.0.layer.1.DenseReluDense.wi.weight" in flat
    assert "encoder.block.0.layer.1.DenseReluDense.wi_0.weight" not in flat
    back = convert_hf_state_dict(flat, "t5", cfg)
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(model.params), jax.tree_util.tree_leaves(back)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_t5_generation_mismatch_is_one_clear_error():
    """A v1.0 checkpoint against a v1.1 config (and vice versa) must fail with
    the generation-mismatch message, not a missing-key crash. An UN-TIED config
    against a headless checkpoint gets its own actionable error."""
    from accelerate_tpu.models.t5 import t5_tiny_v1_0

    v10_cfg = t5_tiny_v1_0()
    v11_cfg = t5_tiny()
    v10_flat = export_hf_state_dict(create_t5_model(v10_cfg, seq_len=16).params, "t5", v10_cfg)
    v11_flat = export_hf_state_dict(create_t5_model(v11_cfg, seq_len=16).params, "t5", v11_cfg)
    with pytest.raises(ValueError, match="generation mismatch"):
        convert_hf_state_dict(v10_flat, "t5", v11_cfg)
    with pytest.raises(ValueError, match="generation mismatch"):
        convert_hf_state_dict(v11_flat, "t5", v10_cfg)
    # relu FFN + untied config vs a headless (tied) checkpoint: clear error
    import dataclasses

    untied_relu_cfg = dataclasses.replace(v10_cfg, tie_word_embeddings=False)
    with pytest.raises(ValueError, match="tie_word_embeddings=True"):
        convert_hf_state_dict(v10_flat, "t5", untied_relu_cfg)


def test_t5_v1_0_rejects_layered_and_pipeline_apply():
    from accelerate_tpu.models.t5 import T5LayeredApply, T5PipelineApply, t5_tiny_v1_0

    with pytest.raises(NotImplementedError, match="tie_word_embeddings"):
        T5LayeredApply(t5_tiny_v1_0())
    with pytest.raises(NotImplementedError, match="tie_word_embeddings"):
        T5PipelineApply(t5_tiny_v1_0())


def test_real_transformers_t5_v1_0_matches():
    """Forward parity vs HF T5ForConditionalGeneration in the v1.0 configuration
    (relu FFN, tied head) — pins the tied-head d_model**-0.5 logit rescale and
    the single-wi FFN against the original implementation."""
    transformers = pytest.importorskip("transformers")
    import torch

    from accelerate_tpu.models.t5 import t5_tiny_v1_0

    hf_cfg = transformers.T5Config(
        vocab_size=512,
        d_model=64,
        d_kv=16,
        d_ff=128,
        num_layers=2,
        num_decoder_layers=2,
        num_heads=4,
        relative_attention_num_buckets=32,
        relative_attention_max_distance=128,
        dropout_rate=0.0,
        layer_norm_epsilon=1e-6,
        feed_forward_proj="relu",
        tie_word_embeddings=True,
    )
    torch.manual_seed(0)
    hf_model = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    # HF .bin state dicts KEEP the tied lm_head.weight view (safetensors drops
    # it) — the converter must accept both, so this test deliberately leaves it
    # in while test_t5_v1_0_hf_round_trip covers the view-less layout.
    flat = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    cfg = t5_tiny_v1_0()
    params = convert_hf_state_dict(flat, "t5", cfg)
    model = create_t5_model(cfg, seq_len=16)

    rng = np.random.default_rng(3)
    ids_np = rng.integers(1, 512, (2, 12))
    dec_np = rng.integers(1, 512, (2, 6))
    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.from_numpy(ids_np), decoder_input_ids=torch.from_numpy(dec_np)
        ).logits.numpy()
    out = np.asarray(model.apply_fn(params, jnp.asarray(ids_np, jnp.int32), jnp.asarray(dec_np, jnp.int32)))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)

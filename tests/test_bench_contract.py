"""The driver contract on bench.py: stdout carries exactly ONE JSON line with
{"metric", "value", "unit", "vs_baseline"} — the round's official perf artifact
is parsed from it, so a formatting regression silently costs the round its
benchmark. Runs the real script as a subprocess on CPU at smoke sizes."""

import json
import os
import sys

import pytest

from accelerate_tpu.test_utils.testing import cpu_mesh_env, execute_subprocess

BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


def run_bench(*args, supervise=False, extra_env=None):
    env = cpu_mesh_env(num_devices=1)
    env.update(extra_env or {})
    cmd = [sys.executable, BENCH, *([] if supervise else ["--no-supervise"]), *args]
    proc = execute_subprocess(cmd, env=env, timeout=900)
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must carry exactly one line, got {lines!r}"
    return json.loads(lines[0])


@pytest.mark.slow_launch
def test_train_bench_contract():
    row = run_bench("--model", "bert-tiny", "--steps", "4", "--trials", "1", "--warmup", "1")
    assert set(row) >= {"metric", "value", "unit", "vs_baseline", "extra"}
    assert isinstance(row["value"], (int, float)) and row["value"] > 0
    assert row["unit"] == "samples/sec/chip"
    # CPU runs must self-tag and zero the baseline ratio (an untagged smoke
    # number masquerading as chip performance was a round-2 verdict item).
    assert row["metric"].startswith("cpu-smoke")
    assert row["vs_baseline"] == 0.0
    assert row["extra"]["device_kind"] == "cpu"
    assert row["extra"]["attention_impl"] in ("xla", "flash", None)


@pytest.mark.slow_launch
def test_inference_bench_contract():
    row = run_bench("--mode", "inference", "--model", "llama-tiny")
    assert set(row) >= {"metric", "value", "unit", "vs_baseline", "extra"}
    assert isinstance(row["value"], (int, float)) and row["value"] > 0
    assert row["unit"] == "ms/token"
    assert row["metric"].startswith("cpu-smoke")
    assert row["vs_baseline"] == 0.0
    assert row["extra"]["ttft_p50_ms"] > 0


@pytest.mark.slow_launch
def test_supervised_fallback_contract():
    """The path the driver actually invokes: supervise() with the preflight
    disabled and zero real attempts forces the CPU-fallback leg — its re-tagged
    single JSON line is what lands in BENCH_r{N}.json on a dead tunnel."""
    row = run_bench(
        "--model", "bert-tiny", "--steps", "2", "--trials", "1", "--warmup", "1",
        supervise=True,
        extra_env={"BENCH_PREFLIGHT_TIMEOUT": "0", "BENCH_MAX_ATTEMPTS": "0"},
    )
    assert row["metric"].startswith("cpu-fallback"), row["metric"]
    assert row["vs_baseline"] == 0.0
    assert row["extra"]["cpu_fallback"] is True
